"""Dynamic cross-request micro-batching for serving.

Reference: ``org.deeplearning4j.parallelism.inference`` — caller threads
hand ``ParallelInference`` single observations, an ``ObservablesProvider``
coalesces them into ``BatchedInferenceObservable``s, a worker runs one
batched forward, and each caller's observable is signalled with its slice
(SURVEY.md §3.6). The TPU-native version keeps that exact shape — queue,
dispatcher, demux — but the "worker pool" is ONE compiled XLA program:
concurrent requests share a single padded device launch, and the padding
is quantized to power-of-two buckets so every launch shape after
``warmup()`` is an AOT-cache hit (``optimize/aot_cache``), never a
recompile.

Three policies govern the dispatcher (the reference's ``batchLimit`` /
queue semantics, plus production admission control):

- ``max_batch``: rows per launch; the queue drains until the next request
  would overflow it (a single larger request still launches alone).
- ``settle_ms`` / ``max_delay_ms``: continuous batching — once the queue
  goes one settle window without growing, every in-flight caller is
  already waiting on us and the batch launches immediately;
  ``max_delay_ms`` is the hard linger ceiling for the oldest request
  under a steady trickle that never settles.
- ``max_queue`` / per-request deadlines: a full queue rejects at submit
  (HTTP 503 upstream) and a request whose deadline passes while queued is
  expired without ever poisoning a shared launch.

Requests are grouped by (trailing shape, dtype) signature — ragged batch
SIZES share launches (that is the point), heterogeneous shapes/dtypes
each get their own launch, and a malformed request fails at ``submit``
with :class:`BadRequestError` for its sender only.
"""

from __future__ import annotations

import dataclasses
import itertools
import threading
import time
from collections import deque
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu import telemetry
from deeplearning4j_tpu.optimize import aot_cache
from deeplearning4j_tpu.telemetry import tracing
from deeplearning4j_tpu.resilience import faults
from deeplearning4j_tpu.resilience.breaker import (
    CircuitBreaker,
    CircuitOpenError,
)
from deeplearning4j_tpu.resilience.retry import SERVING_RETRY


class BadRequestError(ValueError):
    """Client-side problem (shape/dtype/arity mismatch) — maps to HTTP
    400. Raised at submit, BEFORE the request joins a shared batch."""


class ServerOverloadedError(RuntimeError):
    """Admission control: the pending queue is at ``max_queue`` — maps to
    HTTP 503 (shed load instead of growing an unbounded backlog)."""


class DeadlineExpiredError(RuntimeError):
    """The request's deadline passed while it waited in the queue — maps
    to HTTP 503 (the caller has already given up; don't burn a launch)."""


class LaunchTimeoutError(RuntimeError):
    """The launch watchdog fired: a shared forward exceeded
    ``launch_timeout_ms``. The stuck launch's waiters get this (HTTP 503)
    and a replacement dispatcher keeps draining the queue — a wedged
    device launch must not hang every later caller."""


@dataclasses.dataclass
class BatchingConfig:
    """Dispatcher policy knobs (reference ``ParallelInference.Builder``
    ``batchLimit``/``queueLimit``, plus deadline admission control)."""

    max_batch: int = 64        # rows per shared launch (bucket ceiling)
    max_delay_ms: float = 2.0  # linger for batch fill before ragged launch
    max_queue: int = 256       # pending requests before 503 rejection
    timeout_ms: Optional[float] = None  # default per-request deadline
    # continuous batching: once no new rows arrive within one settle
    # window, nothing else is in flight — launch immediately instead of
    # sitting out the rest of max_delay_ms (which stays the hard ceiling
    # for a steady trickle that never settles). 0 disables early launch.
    settle_ms: float = 0.2
    # launch watchdog: a shared forward running longer than this fails
    # its waiters with LaunchTimeoutError (503) and hands the queue to a
    # replacement dispatcher instead of hanging every later caller.
    # None disables (a healthy compiled forward has no steady-state
    # upper bound the engine can know; opt in per deployment).
    launch_timeout_ms: Optional[float] = None


_ENGINE_SEQ = itertools.count(1)  # default breaker names: serving-1, -2, ...


def next_pow2(n: int) -> int:
    """Smallest power of two >= n (n >= 1)."""
    p = 1
    while p < n:
        p <<= 1
    return p


def bucket_rows(n: int, align: int = 1) -> int:
    """Padding bucket for an ``n``-row launch: the smallest
    ``align * 2**k >= n``. ``align`` is the device-shard multiple (a
    sharded backend needs row counts divisible by its worker count), so
    the bucket ladder is align, 2*align, 4*align, ... — every ragged
    request size quantizes to O(log) distinct compiled shapes."""
    per = -(-int(n) // int(align))
    return next_pow2(per) * int(align)


def bucket_ladder(max_batch: int, align: int = 1) -> List[int]:
    """Every bucket a <=``max_batch``-row request can land in (what
    ``warmup()`` pre-compiles)."""
    out = []
    b = int(align)
    while True:
        out.append(b)
        if b >= max_batch:
            return out
        b *= 2


class _Request:
    __slots__ = ("xs", "n", "group", "event", "result", "error", "deadline",
                 "t0", "trace")

    def __init__(self, xs, n, group, deadline, t0, trace=None):
        self.xs = xs
        self.n = n
        self.group = group
        self.event = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None
        self.deadline = deadline
        self.t0 = t0
        # request trace (telemetry.tracing) or None when tracing is
        # disabled; rides the request across submit/dispatcher/watchdog
        # threads, finished exactly once on the first terminal edge
        self.trace = trace


def _input_types(model):
    """The model conf's per-input InputTypes, or None when unreadable."""
    net = getattr(model, "model", model)  # unwrap ParallelInference
    conf = getattr(net, "conf", None)
    if conf is None:
        return None
    if hasattr(conf, "network_inputs"):
        types = list(getattr(conf, "input_types", ()) or ())
        if len(types) != len(conf.network_inputs):
            return [None] * len(conf.network_inputs)
        return types
    if getattr(conf, "input_type", None) is not None:
        return [conf.input_type]
    return None


def _input_templates(model):
    """Per-input trailing shapes derived from the model's conf (None for
    inputs whose shape the conf cannot pin, e.g. variable timesteps), or
    None when the model has no readable conf at all (then the signature
    of each request's own group is the only validation)."""
    from deeplearning4j_tpu.conf import inputs as it

    types = _input_types(model)
    if types is None:
        return None

    def shape_of(t):
        if isinstance(t, it.FeedForward):
            return (t.size,)
        if isinstance(t, it.Convolutional):
            return (t.height, t.width, t.channels)
        if isinstance(t, it.ConvolutionalFlat):
            return (t.height * t.width * t.channels,)
        if isinstance(t, it.Convolutional3D):
            return (t.depth, t.height, t.width, t.channels)
        if isinstance(t, it.Recurrent) and t.timesteps > 0:
            return (t.timesteps, t.size)
        return None

    return [shape_of(t) for t in types]


class InferenceEngine:
    """Dynamic micro-batching front of one model's compiled forward.

    Usage::

        engine = InferenceEngine(net, BatchingConfig(max_batch=32))
        engine.warmup()                      # pre-compile every bucket
        y = engine.predict(x)                # thread-safe, shares launches
        engine.close()

    ``model`` is anything exposing ``output(*arrays)`` — a
    MultiLayerNetwork, a ComputationGraph, or a ``ParallelInference``
    (whose worker count becomes the bucket alignment so padded launches
    stay shard-divisible). ``graph_opt=True`` (default) runs the
    inference-graph optimization pass at construction
    (``nn.inference_opt.optimize_for_inference``): BN statistics folded
    into conv/dense weights, dropout/identity nodes pruned, params
    copied so a concurrently-training original can donate its buffers
    without corrupting the serving copy. ``bf16=True`` additionally
    serves the forward in bfloat16 with f32 outputs.
    """

    def __init__(self, model, config: Optional[BatchingConfig] = None,
                 graph_opt: bool = True, bf16: bool = False,
                 breaker: Optional[CircuitBreaker] = ...,
                 retry=..., name: Optional[str] = None,
                 admission: Optional[Callable] = None):
        self.config = config or BatchingConfig()
        # multi-tenant identity (parallel.platform): a NAMED engine
        # labels its dl4j_serving_* series with model=<name>, defaults
        # its breaker to "serving:<name>" (so /health aggregates every
        # breaker of one model under one key), and fires the scoped
        # fault site "serving.launch:<name>" so a chaos plan can degrade
        # exactly this tenant. Unnamed engines keep every prior surface.
        self.name = name
        self._fault_site = (f"serving.launch:{name}" if name
                            else "serving.launch")
        # host-level admission hook (platform quota): called at submit
        # with (engine, rows) AFTER this engine's own queue-full check
        # and BEFORE the breaker; it may raise ServerOverloadedError to
        # shed for a reason bigger than this tenant's queue (e.g. total
        # pending across all co-tenants) — counted as "rejected".
        self._admission = admission
        # circuit breaker on the launch path: consecutive failures trip
        # it open and submits shed with CircuitOpenError (503) instead of
        # queueing behind a dead model; half-open probes recover. Pass
        # None to disable, or a configured CircuitBreaker to tune. The
        # default name is unique per engine: multiple engines in one
        # process must not collide on dl4j_circuit_state{breaker=...} or
        # shadow each other in resilience.status() (same multi-engine
        # failure mode as the PR 5 queue-depth gauge).
        self._breaker = (CircuitBreaker(
            name=(f"serving:{name}" if name
                  else f"serving-{next(_ENGINE_SEQ)}"))
            if breaker is ... else breaker)
        # one transient-class retry (OSError/ConnectionError/Timeout/
        # injected faults) before a launch failure reaches the breaker;
        # model bugs (ValueError & co) are never retried. None disables.
        self._retry = SERVING_RETRY if retry is ... else retry
        self._graph_opt = bool(graph_opt)
        self._bf16 = bool(bf16)
        self._adopt_model(model)
        self._queue: deque = deque()
        self._cond = threading.Condition()
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        self._batch_seq = itertools.count(1)  # trace launch ids
        telemetry.register_serving_engine(self)

    # --- submit / wait ------------------------------------------------------
    def _validate(self, inputs: Sequence) -> Tuple[tuple, int, tuple]:
        if not inputs:
            raise BadRequestError("at least one input array required")
        if self._templates is not None and \
                len(inputs) != len(self._templates):
            raise BadRequestError(
                f"model takes {len(self._templates)} input array(s), "
                f"got {len(inputs)}")
        xs = []
        for i, a in enumerate(inputs):
            try:
                a = np.asarray(a)
            except (ValueError, TypeError) as e:
                raise BadRequestError(f"malformed input array: {e}")
            if a.dtype == object:
                raise BadRequestError("malformed input array: ragged")
            # match nn_io.as_device(feature=True): uint8 rides to the
            # device as-is (in-jit dequantization), floats/ints take the
            # network dtype
            if a.dtype != np.uint8 and a.dtype != self._np_dtype:
                a = np.asarray(a, self._np_dtype)
            if a.ndim < 1 or a.shape[0] < 1:
                raise BadRequestError("input needs a non-empty batch dim")
            tmpl = (self._templates[i]
                    if self._templates is not None else None)
            if tmpl is not None and tuple(a.shape[1:]) != tuple(tmpl):
                raise BadRequestError(
                    f"input {i} shape {tuple(a.shape[1:])} does not match "
                    f"model input shape {tuple(tmpl)}")
            xs.append(a)
        n = xs[0].shape[0]
        if any(a.shape[0] != n for a in xs):
            raise BadRequestError("inputs disagree on batch size")
        group = tuple((a.shape[1:], a.dtype.str) for a in xs)
        return tuple(xs), n, group

    def submit(self, inputs: Sequence, timeout_ms=...,
               traceparent: Optional[str] = None) -> _Request:
        """Validate and enqueue one request; returns a handle whose
        ``event`` fires when the result (or error) is in. Raises
        :class:`BadRequestError` / :class:`ServerOverloadedError`
        synchronously — a bad request never enters the shared queue.
        ``traceparent`` (W3C header) is adopted into the request trace
        when tracing is armed; every reject edge below finishes the
        trace before raising."""
        if timeout_ms is ...:
            timeout_ms = self.config.timeout_ms
        trace = tracing.start_trace(
            "predict", traceparent=traceparent,
            attrs={"model": self.name} if self.name else None)
        try:
            xs, n, group = self._validate(inputs)
        except BadRequestError:
            telemetry.record_serving_request("bad_request", model=self.name)
            tracing.finish_trace(trace, "bad_request")
            raise
        t0 = time.monotonic()
        deadline = t0 + timeout_ms / 1000.0 if timeout_ms else None
        req = _Request(xs, n, group, deadline, t0, trace)
        tracing.trace_event(trace, "queued", {"rows": n} if trace else None)
        with self._cond:
            if self._stop:
                tracing.finish_trace(trace, "shutdown")
                raise RuntimeError("engine is closed")
            if len(self._queue) >= self.config.max_queue:
                telemetry.record_serving_request("rejected", model=self.name)
                tracing.finish_trace(trace, "rejected")
                raise ServerOverloadedError(
                    f"model {self.name!r} serving queue full "
                    f"({self.config.max_queue} pending)" if self.name else
                    f"serving queue full ({self.config.max_queue} pending)")
            if self._admission is not None:
                # platform-level quota (e.g. total pending across all
                # co-tenants); still before the breaker so a host-level
                # rejection never burns a half-open probe ticket
                try:
                    self._admission(self, n)
                except ServerOverloadedError:
                    telemetry.record_serving_request("rejected",
                                                     model=self.name)
                    tracing.finish_trace(trace, "rejected")
                    raise
            # breaker check LAST: a request rejected for being malformed
            # or for overload must not consume a half-open probe ticket
            # (a burned ticket with no outcome wedges the breaker
            # half-open for a full recovery window)
            if self._breaker is not None and not self._breaker.allow():
                # fail-fast shedding while the breaker is open: don't
                # queue behind a model currently failing every launch
                telemetry.record_serving_request("shed", model=self.name)
                tracing.finish_trace(trace, "shed")
                raise CircuitOpenError(
                    (f"model {self.name!r}: " if self.name else "")
                    + f"circuit breaker {self._breaker.name!r} is "
                    f"{self._breaker.state}; request shed")
            self._queue.append(req)
            tracing.trace_event(trace, "admitted")
            self._cond.notify_all()
        self._ensure_thread()
        return req

    def result(self, req: _Request):
        """Block until ``req`` completes; returns the model output slice
        for this request (same single-array/list convention as
        ``model.output``) or raises the request's error."""
        req.event.wait()
        if req.error is not None:
            raise req.error
        return req.result

    def predict(self, *inputs, timeout_ms=..., traceparent=None):
        """Synchronous request: enqueue, share a launch, demux
        (reference ``ParallelInference#output`` through the observable)."""
        return self.result(self.submit(inputs, timeout_ms=timeout_ms,
                                       traceparent=traceparent))

    def predict_traced(self, *inputs, timeout_ms=..., traceparent=None):
        """``predict`` that also returns the request's trace (or None
        when tracing is disabled) — the HTTP server uses it to echo the
        ``traceparent`` response header."""
        req = self.submit(inputs, timeout_ms=timeout_ms,
                          traceparent=traceparent)
        return self.result(req), req.trace

    # --- model adoption / hot publish ---------------------------------------
    def _adopt_model(self, model, run_graph_opt: bool = True):
        """Derive the engine's serving surface from ``model`` — the ONE
        place the inference-graph pass, bucket alignment, numpy dtype
        and input templates are computed (construction and ``publish``
        share it, so the derivations can never drift)."""
        if self._graph_opt and run_graph_opt:
            from deeplearning4j_tpu.nn.inference_opt import (
                optimize_for_inference,
            )

            model = optimize_for_inference(model, bf16=self._bf16)
        self.model = model
        # sharded backends need launch rows divisible by the shard count
        self._align = int(getattr(model, "workers", 1) or 1)
        self._np_dtype = np.dtype(getattr(
            getattr(model, "model", model), "_dtype", np.float32))
        self._templates = _input_templates(model)
        return model

    def publish(self, model, params=None, state=None):
        """Swap the serving weights WITHOUT restarting the engine — the
        ``comms.reshard.publish_to_engine`` zero-copy train→serve
        hand-off. ``model`` is the source network (configuration
        authority); ``params``/``state`` override its trees with
        device-resident ones (a live wrapper's resharded state — nothing
        crosses the host). The construction-time inference-graph pass
        re-runs with the same ``graph_opt``/``bf16`` flags, so a
        BN-folding engine keeps folding. The swap is atomic per batch:
        requests drained after it take the new weights; a batch the
        dispatcher already claimed at swap time may run on either
        version (the engine never splits one batch across versions).
        The published model shares the source configuration, so every
        warmed bucket executable stays valid (conf-derived AOT graph
        key + unchanged avals: zero recompiles, pinned by test_comms).
        Returns the model now serving."""
        import copy

        src = model
        if params is not None or state is not None:
            src = copy.copy(model)
            if params is not None:
                src.params = params
            if state is not None:
                src.state = state
        if self._graph_opt:
            from deeplearning4j_tpu.nn.inference_opt import (
                optimize_for_inference,
            )

            src = optimize_for_inference(src, bf16=self._bf16)
        with self._cond:
            self._adopt_model(src, run_graph_opt=False)
        return src

    # --- warmup -------------------------------------------------------------
    def buckets(self) -> List[int]:
        return bucket_ladder(self.config.max_batch, self._align)

    def warmup(self, shapes=None, dtype=None) -> dict:
        """Pre-compile the forward executable for EVERY padding bucket so
        ragged traffic never recompiles (the acceptance invariant:
        ``aot_cache.stats()['misses']`` stays flat across a request-size
        sweep after this returns). ``shapes``: per-input trailing shapes
        (default: derived from the model conf; required if the conf
        cannot pin them). Returns ``{"buckets": [...], "compiled": k,
        "compile_seconds": s}``."""
        if shapes is None:
            shapes = self._templates
        if shapes is None or any(s is None for s in shapes):
            raise ValueError(
                "cannot derive input shapes from the model conf; pass "
                "warmup(shapes=[(...), ...]) explicitly")
        if dtype is not None:
            dtype_sets = [tuple(np.dtype(dtype) for _ in shapes)]
        else:
            dtype_sets = self._warm_dtype_sets(len(shapes))
        before = aot_cache.stats()
        for b in self.buckets():
            for dts in dtype_sets:
                args = [np.zeros((b,) + tuple(s), dt)
                        for s, dt in zip(shapes, dts)]
                self._warm_one(args)
        after = aot_cache.stats()
        return {
            "buckets": self.buckets(),
            "compiled": after["misses"] - before["misses"],
            "compile_seconds": round(
                after["compile_seconds"] - before["compile_seconds"], 3),
        }

    def _warm_dtype_sets(self, k: int) -> List[tuple]:
        """Input-dtype combinations warmup must cover — delegated to
        ``nn_io.warm_dtype_variants``, the ONE derivation of the variant
        set (f32/uint8-image/int8-quantized semantics documented there),
        so engine warmup, the platform's deploy/promote warms, and any
        future caller can never drift apart."""
        from deeplearning4j_tpu.nn import io as nn_io

        types = _input_types(self.model)
        conf = getattr(getattr(self.model, "model", self.model), "conf",
                       None)
        padded = [types[i] if types is not None and i < len(types) else None
                  for i in range(k)]
        return nn_io.warm_dtype_variants(
            padded, self._np_dtype,
            quantization=getattr(conf, "quantization", None))

    def _warm_one(self, args):
        try:
            if self._warm_via_aot(args):
                return
        except aot_cache.WarmupBudgetExceeded:
            # an exhausted per-tenant warmup budget is the CALLER's
            # signal (the platform truncates this tenant's warmup), not
            # a reason to fall back to a real forward — which would
            # compile the very executable the budget just refused
            raise
        except Exception:
            pass
        # fallback: one real zeros-forward (any model with .output); an
        # AOT-cached output fn still charges/honors any active warmup
        # budget inside its own miss path
        import jax

        jax.block_until_ready(self.model.output(*args))

    def _warm_via_aot(self, args) -> bool:
        """Compile-without-dispatch through ``AotStep.warm`` when the
        model is a MultiLayerNetwork whose output fn rides the AOT cache
        (the common serving case) — warmup then costs compile time only,
        no device execution."""
        from deeplearning4j_tpu.nn import io as nn_io
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        m = self.model
        if not isinstance(m, MultiLayerNetwork) or len(args) != 1:
            return False
        if m.params is None:
            m.init()
        if m._output_fn is None:
            m._output_fn = m._build_output_fn()
        if not isinstance(m._output_fn, aot_cache.AotStep):
            return False
        x = nn_io.as_device(args[0], m._dtype, feature=True)
        m._output_fn.warm(m.params, m.state, x, None)
        return True

    # --- dispatcher ---------------------------------------------------------
    def _ensure_thread(self):
        if self._thread is not None and self._thread.is_alive():
            return
        with self._cond:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._loop, name="dl4j-serving-dispatch",
                    daemon=True)
                self._thread.start()

    def _loop(self):
        me = threading.current_thread()
        while True:
            batch = self._take_batch()
            if batch is None:
                return
            if batch:
                self._launch(batch)
            with self._cond:
                if self._thread is not me:
                    # the watchdog declared our launch stuck and started
                    # a replacement dispatcher; it owns the queue now
                    # (checked under the lock: the watchdog's claim +
                    # thread swap are atomic, so we can never take a
                    # batch the replacement is also draining)
                    return

    def _expire_locked(self, now: float):
        if not self._queue:
            return
        live = deque()
        for req in self._queue:
            if req.deadline is not None and now > req.deadline:
                req.error = DeadlineExpiredError(
                    "request deadline expired after "
                    f"{(now - req.t0) * 1000:.1f} ms in queue")
                telemetry.record_serving_request("expired", now - req.t0,
                                                 model=self.name)
                tracing.finish_trace(req.trace, "expired")
                req.event.set()
            else:
                live.append(req)
        if len(live) != len(self._queue):
            self._queue = live

    def _rows_for(self, head: _Request) -> int:
        return sum(r.n for r in self._queue if r.group == head.group)

    def _take_batch(self) -> Optional[List[_Request]]:
        cfg = self.config
        settled = None  # (head, rows) snapshot being timed for settle
        settle_t = 0.0  # monotonic time the snapshot was taken
        with self._cond:
            while True:
                now = time.monotonic()
                self._expire_locked(now)
                if self._stop:
                    return None
                if not self._queue:
                    settled = None
                    self._cond.wait(0.1)
                    continue
                head = self._queue[0]
                rows = self._rows_for(head)
                limit = head.t0 + cfg.max_delay_ms / 1000.0
                # The settle test needs BOTH an unchanged snapshot and a
                # full elapsed window: the condvar also wakes for
                # other-group submits, and those must not cut the head
                # group's settle time short.
                settle_ok = (settled == (head, rows)
                             and now - settle_t >= cfg.settle_ms / 1000.0)
                if rows >= cfg.max_batch or now >= limit or settle_ok:
                    # full bucket, linger ceiling hit, or the queue went a
                    # whole settle window without growing (every caller is
                    # already waiting on us — continuous batching)
                    return self._drain_locked(head)
                if cfg.settle_ms > 0:
                    if settled != (head, rows):
                        settled, settle_t = (head, rows), now
                    tick = settle_t + cfg.settle_ms / 1000.0 - now
                else:
                    settled = None
                    tick = limit - now
                self._cond.wait(min(max(tick, 5e-5), limit - now + 5e-5))

    def _drain_locked(self, head: _Request) -> List[_Request]:
        cfg = self.config
        batch, rows, rest = [], 0, deque()
        for req in self._queue:
            take = (req.group == head.group and rows < cfg.max_batch
                    and (rows + req.n <= cfg.max_batch or not batch))
            if take:
                batch.append(req)
                rows += req.n
                if req.trace is not None:
                    req.trace.event("grouped", {"batch_rows": rows})
            else:
                rest.append(req)
        self._queue = rest
        return batch

    def _finish(self, req: _Request, result=None, error=None,
                status: str = "ok") -> bool:
        """Race-safe request completion: exactly one of {dispatcher,
        watchdog, close} delivers a request's outcome — whoever sets the
        event first wins, later callers are no-ops (False)."""
        with self._cond:
            if req.event.is_set():
                return False
            req.result = result
            req.error = error
            req.event.set()
        telemetry.record_serving_request(status, time.monotonic() - req.t0,
                                         model=self.name)
        tracing.finish_trace(req.trace, status)
        return True

    def _claim_batch(self, claim, owner: str) -> bool:
        """Exactly ONE of {dispatcher, watchdog} owns a launch's outcome:
        the owner delivers every waiter's result/error and reports the
        single breaker outcome. The loser does nothing — so one launch
        can never split its waiters between the two or count on the
        breaker twice (once as a timeout, again as a late success)."""
        with self._cond:
            if claim[0] is not None:
                return False
            claim[0] = owner
            return True

    def _forward(self, cat, batch: List[_Request]):
        """The shared launch, behind the ``serving.launch`` fault site
        and (when configured) one transient-class retry bounded by the
        batch's tightest request deadline."""
        def once():
            faults.fault_point(self._fault_site)
            return self.model.output(*cat)

        if self._retry is None:
            return once()
        deadlines = [r.deadline for r in batch if r.deadline is not None]
        return self._retry.call(
            once, deadline=min(deadlines) if deadlines else None,
            op=self._fault_site)

    def _arm_watchdog(self, batch: List[_Request], claim):
        tmo = self.config.launch_timeout_ms
        if not tmo:
            return None
        t = threading.Timer(tmo / 1000.0, self._watchdog_fire,
                            args=(batch, threading.current_thread(), claim))
        t.daemon = True
        t.start()
        return t

    def _watchdog_fire(self, batch: List[_Request], stuck_thread, claim):
        """Launch-timeout path: claim the batch (atomically with the
        dispatcher swap — ``Timer.cancel`` cannot stop an already-running
        callback, so the claim is what decides the race), fail the stuck
        launch's waiters with 503, and hand the queue to a fresh
        dispatcher. The stuck thread exits when (if ever) its launch
        returns — its claim fails, so its late outcome is a no-op."""
        with self._cond:
            if claim[0] is not None:
                return  # lost the race: the launch completed in time
            claim[0] = "watchdog"
            if not self._stop and self._thread is stuck_thread:
                self._thread = threading.Thread(
                    target=self._loop, name="dl4j-serving-dispatch",
                    daemon=True)
                self._thread.start()
        err = LaunchTimeoutError(
            f"shared launch exceeded {self.config.launch_timeout_ms} ms; "
            "waiters failed by watchdog")
        for r in batch:
            self._finish(r, error=err, status="timeout")
        if self._breaker is not None:
            self._breaker.on_failure()

    def _launch(self, batch: List[_Request]):
        t0 = time.monotonic()
        rows = sum(r.n for r in batch)
        k = len(batch[0].xs)
        claim = [None]  # mutated under self._cond only (_claim_batch)
        watchdog = self._arm_watchdog(batch, claim)
        traced = [r for r in batch if r.trace is not None]
        try:
            cat = [np.concatenate([r.xs[i] for r in batch], axis=0)
                   if len(batch) > 1 else batch[0].xs[i] for i in range(k)]
            target = bucket_rows(rows, self._align)
            if traced:
                attrs = {"batch": next(self._batch_seq), "bucket": target,
                         "rows": rows, "requests": len(batch),
                         "occupancy": round(rows / max(target, 1), 3)}
                for r in traced:
                    r.trace.event("launched", attrs)
            if target != rows:
                cat = [np.concatenate(
                    [a, np.zeros((target - rows,) + a.shape[1:], a.dtype)])
                    for a in cat]
            out = self._forward(cat, batch)
            multi = isinstance(out, (list, tuple))
            host = [np.asarray(o) for o in (out if multi else [out])]
        except Exception as e:
            if watchdog is not None:
                watchdog.cancel()
            # deliver only if we win the batch claim — a launch the
            # watchdog already abandoned (waiters failed, breaker
            # counted) must not report a second, contradictory outcome
            if not self._claim_batch(claim, "dispatcher"):
                return
            for r in batch:
                self._finish(r, error=e, status="error")
            if self._breaker is not None:
                self._breaker.on_failure()
            return
        if watchdog is not None:
            watchdog.cancel()
        if not self._claim_batch(claim, "dispatcher"):
            return  # watchdog fired mid-demux-window: it owns the batch
        now = time.monotonic()
        for r in traced:
            r.trace.event("demuxed")
        off = 0
        try:
            for r in batch:
                sl = [h[off:off + r.n] for h in host]
                off += r.n
                self._finish(r, result=sl if multi else sl[0])
        except Exception as e:
            # demux failure (e.g. a model returning fewer rows than fed):
            # fail the remaining waiters, dispatcher survives
            for r in batch:
                self._finish(r, error=e, status="error")
            if self._breaker is not None:
                self._breaker.on_failure()
            return
        telemetry.record_serving_batch(rows, target, len(batch), now - t0,
                                       model=self.name)
        if self._breaker is not None:
            self._breaker.on_success()

    # --- stats / lifecycle --------------------------------------------------
    def queue_depth(self) -> int:
        """Pending-request count (lock-free read: deque length is
        consistent under the GIL, and the value is a point-in-time gauge
        anyway — the scrape-time collector sums this over live engines)."""
        return len(self._queue)

    def stats(self) -> dict:
        """Queue depth + the AOT executable-cache counters (the
        zero-recompile-after-warmup invariant is read off ``misses``) +
        the circuit breaker's state when one is attached."""
        with self._cond:
            depth = len(self._queue)
        out = {"queue_depth": depth, "buckets": self.buckets(),
               "aot_cache": aot_cache.stats()}
        if self._breaker is not None:
            out["circuit_breaker"] = self._breaker.status()
        return out

    @property
    def breaker(self) -> Optional[CircuitBreaker]:
        return self._breaker

    @property
    def retry(self):
        """The launch retry policy (None = disabled) — public for the
        same rebuild handoff as :attr:`breaker`."""
        return self._retry

    def close(self):
        """Stop the dispatcher; pending requests fail with a shutdown
        error. Idempotent."""
        with self._cond:
            self._stop = True
            for req in self._queue:
                req.error = RuntimeError("serving engine closed")
                tracing.finish_trace(req.trace, "shutdown")
                req.event.set()
            self._queue.clear()
            self._cond.notify_all()
        telemetry.unregister_serving_engine(self)
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=5)
        self._thread = None
        return self

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
