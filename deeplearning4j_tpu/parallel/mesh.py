"""Device mesh / topology abstraction — the distributed backbone.

Reference equivalents (SURVEY.md §2.4, §5.8): the entire Aeron UDP transport
(`nd4j-aeron` ``AeronNDArrayPublisher``/``NDArrayMessage`` chunking), the
``VoidParameterServer`` mesh, and ``AffinityManager`` device pinning. On TPU
all of that collapses into XLA collectives compiled into the program: this
module only names the axes, builds the ``jax.sharding.Mesh``, and hands out
``NamedSharding``s; ``psum``/``all_gather``/``ppermute`` ride ICI within a
slice and DCN across slices, inserted by the compiler.

Axis convention (the full menu; unused axes just have size 1):
``data`` (DP replicas), ``model`` (TP shards), ``pipeline`` (PP stages),
``sequence`` (SP/ring-attention shards), ``expert`` (EP/MoE shards).
"""

from __future__ import annotations

import dataclasses
import typing as tp

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"
PIPELINE_AXIS = "pipeline"
SEQUENCE_AXIS = "sequence"
EXPERT_AXIS = "expert"

ALL_AXES = (DATA_AXIS, MODEL_AXIS, PIPELINE_AXIS, SEQUENCE_AXIS, EXPERT_AXIS)


@dataclasses.dataclass
class MeshConfig:
    """Declarative mesh shape. Unspecified axes default to 1; ``data=-1``
    (the default) absorbs all remaining devices, so the same config scales
    from 1 chip to a pod unchanged."""

    data: int = -1
    model: int = 1
    pipeline: int = 1
    sequence: int = 1
    expert: int = 1
    devices: tp.Optional[tp.Sequence] = None  # default: jax.devices()

    def build(self) -> Mesh:
        devices = list(self.devices if self.devices is not None
                       else jax.devices())
        n = len(devices)
        fixed = self.model * self.pipeline * self.sequence * self.expert
        data = self.data
        if data == -1:
            if n % fixed != 0:
                raise ValueError(
                    f"{n} devices not divisible by model*pipeline*sequence*"
                    f"expert={fixed}")
            data = n // fixed
        total = data * fixed
        if total > n:
            raise ValueError(f"mesh needs {total} devices, have {n}")
        shape = (data, self.model, self.pipeline, self.sequence, self.expert)
        arr = np.array(devices[:total]).reshape(shape)
        return Mesh(arr, ALL_AXES)


def single_host_mesh(n_devices: int | None = None, **axes) -> Mesh:
    """Convenience: mesh over the first n local devices (default: all)."""
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return MeshConfig(devices=devices, **axes).build()


def data_parallel_spec(mesh: Mesh) -> NamedSharding:
    """Batch sharded over 'data', everything else replicated — the
    ParallelWrapper layout."""
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated_spec(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(mesh: Mesh, batch):
    """Place a host batch so its leading dim is split over the 'data' axis
    (the role of ParallelWrapper's splitter + per-worker MagicQueues).

    Multi-process (``jax.distributed``): ``batch`` holds THIS process's
    local partition (the reference's RDD partition per Spark executor); the
    global array is assembled from every process's contribution."""
    sharding = data_parallel_spec(mesh)
    if jax.process_count() > 1:
        return jax.tree_util.tree_map(
            lambda x: jax.make_array_from_process_local_data(
                sharding, np.asarray(x)), batch)
    return jax.tree_util.tree_map(
        lambda x: jax.device_put(x, sharding), batch)


def replicate(mesh: Mesh, tree):
    """Replicate params/opt-state across the mesh (the reference copies
    replica params to each device via AffinityManager)."""
    sharding = replicated_spec(mesh)
    return jax.tree_util.tree_map(
        lambda x: stage_host(x, sharding), tree)


def stage_host(x, sharding) -> jax.Array:
    """Commit one host value under ``sharding``, at ANY process count:
    ``jax.make_array_from_callback`` hands each process only the index
    boxes of its OWN addressable shards, so a pod host stages exactly
    its slice of the global array and never touches (or needs) remote
    devices. At ``process_count == 1`` this is bitwise the old
    ``device_put`` path (pinned by test_sharding's parity suite);
    device-resident single-process values keep the plain ``device_put``
    fast path (no host round-trip)."""
    if jax.process_count() == 1:
        return jax.device_put(x, sharding)
    arr = np.asarray(x)
    return jax.make_array_from_callback(
        arr.shape, sharding, lambda idx: arr[idx])


def host_gather(tree):
    """Device tree -> host numpy tree, at ANY process count: a fully-
    addressable leaf is a plain ``device_get``; a process-SPANNING leaf
    (a pod's ZeRO opt slices, TP shards on remote hosts) first
    replicates through a compiled identity — XLA inserts the cross-host
    all-gather — and reads the local copy. This is the multi-host
    gather that lets checkpoints stay full-host-array and
    mesh-shape-agnostic on a pod (the single-process path is bitwise
    the old ``np.asarray`` route)."""
    def pull(x):
        if not isinstance(x, jax.Array) \
                or getattr(x, "is_fully_addressable", True):
            return np.asarray(jax.device_get(x))
        sh = getattr(x, "sharding", None)
        m = getattr(sh, "mesh", None)
        if m is None:  # exotic sharding: let jax try (clear error > hang)
            return np.asarray(jax.device_get(x))
        # through the AOT-cached compiled identity (comms.reshard):
        # gathers of the same (placement, aval) reuse one executable —
        # a fresh jit per leaf would re-trace the cross-host all-gather
        # on every checkpoint
        from deeplearning4j_tpu.comms.reshard import commit_compiled

        rep = commit_compiled(x, NamedSharding(m, P()))
        return np.asarray(rep.addressable_shards[0].data)

    return jax.tree_util.tree_map(pull, tree)


def pad_leading(tree, target: int):
    """Zero-pad every leaf's leading (batch) dim to ``target`` rows. Padded
    rows carry a zero label-mask so they contribute nothing to loss/grads
    (the role of the reference splitter handling ragged final batches)."""
    import jax.numpy as jnp

    def pad(x):
        x = jnp.asarray(x)
        n = x.shape[0]
        if n == target:
            return x
        return jnp.concatenate(
            [x, jnp.zeros((target - n,) + x.shape[1:], x.dtype)])

    return jax.tree_util.tree_map(pad, tree)


def shard_valid_counts(rows: int, workers: int) -> np.ndarray:
    """Valid (non-padded) row count per shard after ``pad_leading`` to
    ``ceil(rows/workers)*workers`` and an even split: shard i holds rows
    [i*s, (i+1)*s)."""
    s = -(-rows // workers)
    return np.clip(rows - np.arange(workers) * s, 0, s).astype(np.float32)


# vma-era jax (jax.typeof / lax.pcast) tracks varying-manual-axes types
# and transposes collectives replication-correctly inside shard_map
# bodies; older check_rep jax needs explicit anchors and manual scale
# corrections in differentiated regions (pipeline.psum_replicate,
# expert/wrapper grad rescales). ONE feature probe, shared by every
# parallel module so a future jax-version fix lands in one place.
EFFICIENT_PSUM_TRANSPOSE = (hasattr(jax, "typeof")
                            and hasattr(jax.lax, "pcast"))


def ensure_varying(x, axes):
    """Mark ``x`` device-varying over the given mesh axes, version-
    adaptively:

    - vma jax: pcast to varying only on the axes ``x`` does not already
      vary on (pcast errors on varying->varying; shard-mapped inputs
      arrive already varying on their sharded axes).
    - check_rep jax: add a zero anchor derived from ``axis_index`` so the
      replication tracker drops the axes from the value's rep set — a
      free elementwise add under XLA, and a no-op on axes the value
      already varies on."""
    if EFFICIENT_PSUM_TRANSPOSE:
        have = set(getattr(jax.typeof(x), "vma", ()) or ())
        need = tuple(a for a in axes if a not in have)
        return jax.lax.pcast(x, need, to="varying") if need else x
    if not axes:
        return x
    z = sum(jax.lax.axis_index(a) for a in axes) * 0
    return x + z.astype(x.dtype)


try:  # jax >= 0.4.35
    from jax import shard_map as _shard_map

    def shard_map(f, mesh, in_specs, out_specs):
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs)
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map_old

    def shard_map(f, mesh, in_specs, out_specs):
        return _shard_map_old(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs)


def initialize_distributed(coordinator_address: str | None = None,
                           num_processes: int | None = None,
                           process_id: int | None = None) -> None:
    """Multi-host bootstrap (reference: Spark master/worker setup + Aeron
    ``VoidParameterServer`` join — SURVEY.md §3.5). One call per host;
    afterwards ``jax.devices()`` spans the whole pod and the same Mesh code
    scales across hosts, collectives riding ICI intra-slice / DCN inter-
    slice. No-op when every argument is None and env vars configure it."""
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    jax.distributed.initialize(**kwargs)


def device_count(mesh: Mesh, axis: str = DATA_AXIS) -> int:
    return mesh.shape[axis]


