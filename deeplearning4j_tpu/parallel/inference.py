"""ParallelInference — multi-device batched serving.

Reference: ``org.deeplearning4j.parallelism.ParallelInference`` (SURVEY.md
§3.6): caller threads enqueue requests, an ``ObservablesProvider`` batches
them, per-device ``InferenceWorker`` replicas run batched forwards, results
are demuxed.

TPU-native inversion: there are no worker threads or queues — a request
batch is padded to a power-of-two bucket of per-device rows and executed by
the model's (already jitted) forward with inputs sharded ``P('data')``; XLA
splits the batch across devices. ``INPLACE``-style replica semantics are
inherent (params replicated, read-only).

Padding policy (round 9): the exact-worker-multiple pad of earlier rounds
compiled a fresh executable for EVERY distinct request size — ragged
traffic turned into a compile-per-request pathology. Rows per device now
quantize to the power-of-two bucket ladder (``parallel.batcher.
bucket_rows`` with ``align=workers``), so a size sweep touches O(log)
compiled shapes and ``cache_stats()`` (the ``optimize.aot_cache``
counters) shows hits, not misses. ``bucketize=False`` restores the exact
pad for memory-tight models. Cross-request coalescing lives one level up
in ``parallel.batcher.InferenceEngine`` (which accepts a
``ParallelInference`` as its backend and aligns its buckets to the worker
count).
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import numpy as np

from deeplearning4j_tpu.optimize import aot_cache
from deeplearning4j_tpu.parallel import mesh as mesh_mod
from deeplearning4j_tpu.parallel.batcher import bucket_rows


class ParallelInference:
    """Sharded batch inference over all (or ``workers``) local devices.

    Usage::

        pi = ParallelInference(net, workers=8, batch_limit=256)
        y = pi.output(x)          # any leading batch size, incl. ragged
    """

    def __init__(self, model, workers: Optional[int] = None,
                 batch_limit: int = 0, mesh=None, bucketize: bool = True):
        if model.params is None:
            model.init()
        self.model = model
        self.mesh = mesh if mesh is not None else mesh_mod.single_host_mesh(
            n_devices=workers)
        self.workers = self.mesh.shape[mesh_mod.DATA_AXIS]
        # max examples per device program launch (reference batchLimit);
        # 0 = whole request in one launch
        self.batch_limit = int(batch_limit)
        # pad ragged batches to power-of-two per-worker buckets (zero
        # recompiles across a size sweep) instead of the exact multiple
        self.bucketize = bool(bucketize)
        # replicate params once up front (reference: replicas share params
        # via INPLACE model distribution)
        model.params = mesh_mod.replicate(self.mesh, model.params)
        if model.state:
            model.state = mesh_mod.replicate(self.mesh, model.state)

    def _run(self, xs):
        """One sharded program launch over a tuple of input arrays."""
        n = xs[0].shape[0]
        if self.bucketize:
            target = bucket_rows(n, align=self.workers)
        else:
            target = math.ceil(n / self.workers) * self.workers
        spec = mesh_mod.data_parallel_spec(self.mesh)
        placed = [jax.device_put(a, spec)
                  for a in mesh_mod.pad_leading(list(xs), target)]
        ys = self.model.output(*placed)
        if isinstance(ys, (list, tuple)):
            return [np.asarray(y)[:n] for y in ys]
        return np.asarray(ys)[:n]

    def output(self, x, *more_inputs):
        """Forward a request batch (reference ``ParallelInference#output``).
        For multi-input ComputationGraphs pass all inputs positionally."""
        xs = tuple(np.asarray(a) for a in (x,) + more_inputs)
        n = xs[0].shape[0]
        if not self.batch_limit or n <= self.batch_limit:
            result = self._run(xs)
        else:
            # tail chunks ride the same bucket ladder as full chunks, so a
            # batch_limit that sits on a bucket boundary never adds shapes
            chunks = [self._run(tuple(a[i:i + self.batch_limit] for a in xs))
                      for i in range(0, n, self.batch_limit)]
            if isinstance(chunks[0], list):
                result = [np.concatenate([c[j] for c in chunks])
                          for j in range(len(chunks[0]))]
            else:
                result = np.concatenate(chunks)
        return result

    def cache_stats(self) -> dict:
        """The process AOT executable-cache counters
        (``optimize.aot_cache.stats``): after the first call per bucket,
        ragged request sizes must register as hits — a rising miss count
        here is the recompile pathology bucketing exists to kill."""
        return aot_cache.stats()
