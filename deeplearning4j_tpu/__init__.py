"""deeplearning4j_tpu — a TPU-native deep learning framework.

A ground-up rebuild of the capabilities of the Eclipse Deeplearning4j stack
(reference: holgerbrandl/deeplearning4j) designed for TPU hardware:

- whole-graph XLA compilation via ``jax.jit`` instead of per-op JNI dispatch
  (reference: op-by-op ``NativeOpExecutioner#exec`` -> libnd4j ``execCustomOp2``)
- parallelism as sharding (``jax.sharding.Mesh`` + ``shard_map`` + collectives)
  instead of thread-per-device replicas (reference: ``ParallelWrapper``)
- the configuration DSL (builder -> JSON round trip) is the durable API-parity
  surface (reference: ``NeuralNetConfiguration.Builder`` ->
  ``MultiLayerConfiguration``); the execution engine underneath is XLA.

Package map (mirrors the reference's layer map, SURVEY.md section 1):

- ``conf``      — config DSL: layers, vertices, updaters, losses, schedules
                  (reference: ``deeplearning4j-nn/.../nn/conf/``)
- ``nn``        — model runtimes: ``MultiLayerNetwork``, ``ComputationGraph``
                  (reference: ``.../nn/multilayer/``, ``.../nn/graph/``)
- ``ops``       — op library + Pallas kernels (reference: libnd4j declarable ops)
- ``autodiff``  — SameDiff-equivalent symbolic graph API
                  (reference: ``nd4j/.../autodiff/samediff/``)
- ``datasets``  — ``DataSet``/iterators (reference: ``org.nd4j.linalg.dataset``)
- ``datavec``   — ETL: record readers, transforms
                  (reference: ``datavec/``)
- ``eval``      — ``Evaluation``/``ROC``/``RegressionEvaluation``
                  (reference: ``org.nd4j.evaluation``)
- ``optimize``  — solver loop, listeners, early stopping
                  (reference: ``org.deeplearning4j.optimize``)
- ``parallel``  — mesh/topology, ParallelWrapper-equivalent, compressed grads
                  (reference: ``deeplearning4j-scaleout``)
- ``zoo``       — model zoo (reference: ``deeplearning4j-zoo``)
- ``util``      — ModelSerializer, checkpointing
                  (reference: ``.../util/ModelSerializer``)
"""

__version__ = "0.1.0"
