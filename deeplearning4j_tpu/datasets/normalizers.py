"""Data normalizers.

Reference: ``org.nd4j.linalg.dataset.api.preprocessor.*`` —
``NormalizerStandardize`` (fit mean/std over an iterator, transform/revert),
``NormalizerMinMaxScaler``, ``ImagePreProcessingScaler`` (pixel [0,255] →
[min,max]) and the label-normalizing variants. Fitted normalizers are saved
with the model by the serializer, so they carry a JSON state round-trip.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet


class DataNormalization:
    """Fit/transform/revert contract (reference ``DataNormalization``)."""

    def fit(self, iterator) -> "DataNormalization":
        raise NotImplementedError

    def transform(self, ds: DataSet) -> DataSet:
        raise NotImplementedError

    def revert(self, ds: DataSet) -> DataSet:
        raise NotImplementedError

    def transform_features(self, features: np.ndarray) -> np.ndarray:
        ds = DataSet(np.asarray(features), np.zeros((len(features), 0)))
        return self.transform(ds).features

    # --- serialization ------------------------------------------------------
    def state_dict(self) -> dict:
        raise NotImplementedError

    def load_state_dict(self, state: dict) -> "DataNormalization":
        raise NotImplementedError


def _feature_axes(arr: np.ndarray):
    """All axes except the last = per-feature stats over batch (+time/space).
    Matches the reference's per-feature-column statistics."""
    return tuple(range(arr.ndim - 1))


class NormalizerStandardize(DataNormalization):
    """z-score per feature (reference ``NormalizerStandardize``); optionally
    also normalizes labels (regression use)."""

    def __init__(self, fit_labels: bool = False):
        self.fit_labels = fit_labels
        self.mean: Optional[np.ndarray] = None
        self.std: Optional[np.ndarray] = None
        self.label_mean: Optional[np.ndarray] = None
        self.label_std: Optional[np.ndarray] = None

    def fit(self, iterator):
        f_sum = f_sumsq = n = None
        l_sum = l_sumsq = ln = None
        for ds in _iter_of(iterator):
            f = np.asarray(ds.features, np.float64)
            f2 = f.reshape(-1, f.shape[-1])
            f_sum = f2.sum(0) if f_sum is None else f_sum + f2.sum(0)
            f_sumsq = ((f2 ** 2).sum(0) if f_sumsq is None
                       else f_sumsq + (f2 ** 2).sum(0))
            n = f2.shape[0] if n is None else n + f2.shape[0]
            if self.fit_labels:
                l = np.asarray(ds.labels, np.float64).reshape(
                    -1, np.asarray(ds.labels).shape[-1])
                l_sum = l.sum(0) if l_sum is None else l_sum + l.sum(0)
                l_sumsq = ((l ** 2).sum(0) if l_sumsq is None
                           else l_sumsq + (l ** 2).sum(0))
                ln = l.shape[0] if ln is None else ln + l.shape[0]
        _reset(iterator)
        self.mean = (f_sum / n).astype(np.float32)
        var = f_sumsq / n - (f_sum / n) ** 2
        self.std = np.sqrt(np.maximum(var, 1e-12)).astype(np.float32)
        if self.fit_labels:
            self.label_mean = (l_sum / ln).astype(np.float32)
            lvar = l_sumsq / ln - (l_sum / ln) ** 2
            self.label_std = np.sqrt(np.maximum(lvar, 1e-12)).astype(np.float32)
        return self

    def transform(self, ds):
        ds.features = ((np.asarray(ds.features) - self.mean) /
                       self.std).astype(np.float32)
        if self.fit_labels and self.label_mean is not None:
            ds.labels = ((np.asarray(ds.labels) - self.label_mean) /
                         self.label_std).astype(np.float32)
        return ds

    def revert(self, ds):
        ds.features = (np.asarray(ds.features) * self.std + self.mean)
        if self.fit_labels and self.label_mean is not None:
            ds.labels = np.asarray(ds.labels) * self.label_std + self.label_mean
        return ds

    def revert_labels(self, labels: np.ndarray) -> np.ndarray:
        if self.label_mean is None:
            return labels
        return np.asarray(labels) * self.label_std + self.label_mean

    def state_dict(self):
        return {"kind": "standardize", "fit_labels": self.fit_labels,
                "mean": _tolist(self.mean), "std": _tolist(self.std),
                "label_mean": _tolist(self.label_mean),
                "label_std": _tolist(self.label_std)}

    def load_state_dict(self, state):
        self.fit_labels = state["fit_labels"]
        self.mean = _fromlist(state["mean"])
        self.std = _fromlist(state["std"])
        self.label_mean = _fromlist(state["label_mean"])
        self.label_std = _fromlist(state["label_std"])
        return self


class NormalizerMinMaxScaler(DataNormalization):
    """Scale features to [min,max] (reference ``NormalizerMinMaxScaler``)."""

    def __init__(self, min_range: float = 0.0, max_range: float = 1.0):
        self.min_range = float(min_range)
        self.max_range = float(max_range)
        self.data_min: Optional[np.ndarray] = None
        self.data_max: Optional[np.ndarray] = None

    def fit(self, iterator):
        lo = hi = None
        for ds in _iter_of(iterator):
            f = np.asarray(ds.features, np.float64)
            f2 = f.reshape(-1, f.shape[-1])
            cur_lo, cur_hi = f2.min(0), f2.max(0)
            lo = cur_lo if lo is None else np.minimum(lo, cur_lo)
            hi = cur_hi if hi is None else np.maximum(hi, cur_hi)
        _reset(iterator)
        self.data_min = lo.astype(np.float32)
        self.data_max = hi.astype(np.float32)
        return self

    def _scale(self):
        rng = self.data_max - self.data_min
        return np.where(rng == 0, 1.0, rng)

    def transform(self, ds):
        frac = (np.asarray(ds.features) - self.data_min) / self._scale()
        ds.features = (self.min_range +
                       frac * (self.max_range - self.min_range)).astype(np.float32)
        return ds

    def revert(self, ds):
        frac = ((np.asarray(ds.features) - self.min_range) /
                (self.max_range - self.min_range))
        ds.features = frac * self._scale() + self.data_min
        return ds

    def state_dict(self):
        return {"kind": "minmax", "min_range": self.min_range,
                "max_range": self.max_range,
                "data_min": _tolist(self.data_min),
                "data_max": _tolist(self.data_max)}

    def load_state_dict(self, state):
        self.min_range = state["min_range"]
        self.max_range = state["max_range"]
        self.data_min = _fromlist(state["data_min"])
        self.data_max = _fromlist(state["data_max"])
        return self


class ImagePreProcessingScaler(DataNormalization):
    """Pixel [0, 2^bits−1] → [min,max]; no fitting needed (reference
    ``ImagePreProcessingScaler``)."""

    def __init__(self, min_range: float = 0.0, max_range: float = 1.0,
                 max_bits: int = 8):
        self.min_range = float(min_range)
        self.max_range = float(max_range)
        self.max_pixel = float(2 ** max_bits - 1)

    def fit(self, iterator):
        return self

    def transform(self, ds):
        frac = np.asarray(ds.features, np.float32) / self.max_pixel
        ds.features = self.min_range + frac * (self.max_range - self.min_range)
        return ds

    def revert(self, ds):
        frac = ((np.asarray(ds.features) - self.min_range) /
                (self.max_range - self.min_range))
        ds.features = frac * self.max_pixel
        return ds

    def state_dict(self):
        return {"kind": "image_scaler", "min_range": self.min_range,
                "max_range": self.max_range, "max_pixel": self.max_pixel}

    def load_state_dict(self, state):
        self.min_range = state["min_range"]
        self.max_range = state["max_range"]
        self.max_pixel = state["max_pixel"]
        return self


_KINDS = {"standardize": NormalizerStandardize,
          "minmax": NormalizerMinMaxScaler,
          "image_scaler": ImagePreProcessingScaler}


def normalizer_from_state(state: dict) -> DataNormalization:
    """Restore any normalizer from its ``state_dict`` (serializer hook)."""
    return _KINDS[state["kind"]]().load_state_dict(state)


def _iter_of(iterator):
    if isinstance(iterator, DataSet):
        return [iterator]
    return iterator


def _reset(iterator):
    if hasattr(iterator, "reset"):
        iterator.reset()


def _tolist(a):
    return None if a is None else np.asarray(a).tolist()


def _fromlist(v):
    return None if v is None else np.asarray(v, np.float32)
