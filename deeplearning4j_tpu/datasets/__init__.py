from deeplearning4j_tpu.datasets.dataset import DataSet, MultiDataSet
from deeplearning4j_tpu.datasets.iterators import (
    ArrayDataSetIterator,
    DataSetIterator,
    ListDataSetIterator,
)
