"""MNIST dataset iterator.

Reference: ``org.deeplearning4j.datasets.iterator.impl.MnistDataSetIterator``
+ ``MnistDataFetcher`` (auto-download + idx-file cache). This environment has
zero egress, so the fetcher resolves in order:

1. cached idx files under ``~/.deeplearning4j_tpu/mnist/`` (standard
   ``train-images-idx3-ubyte`` etc., gz or raw) — byte-compatible with the
   reference's cache;
2. a deterministic SYNTHETIC digit set: 5x7 bitmap-font glyphs for 0-9
   rendered into 28x28 with random shift/scale jitter + noise. Learnable by
   LeNet to >95%, so the e2e demo and bench exercise the full pipeline.

Images are NHWC [batch, 28, 28, 1] floats in [0,1]; labels one-hot [batch,10].
"""

from __future__ import annotations

import gzip
import os
import struct
from pathlib import Path

import numpy as np

from deeplearning4j_tpu.datasets.iterators import ArrayDataSetIterator

_CACHE = Path(os.path.expanduser("~/.deeplearning4j_tpu/mnist"))

# 5x7 bitmap font for digits 0-9 (rows top->bottom, 5 bits per row)
_FONT = {
    0: ["01110", "10001", "10011", "10101", "11001", "10001", "01110"],
    1: ["00100", "01100", "00100", "00100", "00100", "00100", "01110"],
    2: ["01110", "10001", "00001", "00010", "00100", "01000", "11111"],
    3: ["11111", "00010", "00100", "00010", "00001", "10001", "01110"],
    4: ["00010", "00110", "01010", "10010", "11111", "00010", "00010"],
    5: ["11111", "10000", "11110", "00001", "00001", "10001", "01110"],
    6: ["00110", "01000", "10000", "11110", "10001", "10001", "01110"],
    7: ["11111", "00001", "00010", "00100", "01000", "01000", "01000"],
    8: ["01110", "10001", "10001", "01110", "10001", "10001", "01110"],
    9: ["01110", "10001", "10001", "01111", "00001", "00010", "01100"],
}


def _read_idx(path: Path) -> np.ndarray:
    opener = gzip.open if path.suffix == ".gz" else open
    with opener(path, "rb") as f:
        magic, = struct.unpack(">I", f.read(4))
        ndim = magic & 0xFF
        dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        data = np.frombuffer(f.read(), np.uint8)
    return data.reshape(dims)


def _find(name: str) -> Path | None:
    for cand in (_CACHE / name, _CACHE / (name + ".gz")):
        if cand.exists():
            return cand
    return None


def _load_real(train: bool):
    img = _find(("train" if train else "t10k") + "-images-idx3-ubyte")
    lab = _find(("train" if train else "t10k") + "-labels-idx1-ubyte")
    if img is None or lab is None:
        return None
    from deeplearning4j_tpu import native as _native
    images = _native.u8_to_f32(_read_idx(img))
    labels = _read_idx(lab)
    features = images[..., None]  # NHWC
    onehot = np.eye(10, dtype=np.float32)[labels]
    return features, onehot


def _glyph(digit: int) -> np.ndarray:
    g = np.array([[int(c) for c in row] for row in _FONT[digit]], np.float32)
    return g  # [7, 5]


def synthesize(num: int, seed: int) -> tuple[np.ndarray, np.ndarray]:
    """Deterministic synthetic MNIST-like set."""
    rng = np.random.default_rng(seed)
    digits = rng.integers(0, 10, size=num)
    imgs = np.zeros((num, 28, 28), np.float32)
    for i, d in enumerate(digits):
        scale = rng.integers(2, 4)  # 2x or 3x
        glyph = np.kron(_glyph(int(d)), np.ones((scale, scale), np.float32))
        gh, gw = glyph.shape
        max_y, max_x = 28 - gh, 28 - gw
        y = rng.integers(0, max_y + 1)
        x = rng.integers(0, max_x + 1)
        intensity = 0.7 + 0.3 * rng.random()
        imgs[i, y:y + gh, x:x + gw] = glyph * intensity
    imgs += rng.normal(0, 0.08, imgs.shape).astype(np.float32)
    imgs = np.clip(imgs, 0.0, 1.0)
    labels = np.eye(10, dtype=np.float32)[digits]
    return imgs[..., None], labels


class MnistDataSetIterator(ArrayDataSetIterator):
    """Reference ``MnistDataSetIterator(batch, train, seed)``."""

    def __init__(self, batch: int, train: bool = True, seed: int = 123,
                 num_examples: int | None = None, shuffle: bool = True):
        real = _load_real(train)
        if real is not None:
            features, labels = real
            self.synthetic = False
        else:
            n = num_examples or (8192 if train else 2048)
            features, labels = synthesize(n, seed + (0 if train else 777))
            self.synthetic = True
        if num_examples is not None:
            features, labels = features[:num_examples], labels[:num_examples]
        super().__init__(features, labels, batch, shuffle=shuffle, seed=seed)


class IrisDataSetIterator(ArrayDataSetIterator):
    """Reference ``IrisDataSetIterator`` — the tiny built-in dataset used
    throughout the reference's tests. Fisher's iris is reproduced
    synthetically here (three separable gaussian clusters in 4-D matching
    class means/stds of the real data)."""

    _MEANS = np.array([[5.01, 3.43, 1.46, 0.25],
                       [5.94, 2.77, 4.26, 1.33],
                       [6.59, 2.97, 5.55, 2.03]], np.float32)
    _STDS = np.array([[0.35, 0.38, 0.17, 0.11],
                      [0.52, 0.31, 0.47, 0.20],
                      [0.64, 0.32, 0.55, 0.27]], np.float32)

    def __init__(self, batch: int = 150, num_examples: int = 150,
                 seed: int = 6):
        rng = np.random.default_rng(seed)
        per = num_examples // 3
        feats, labs = [], []
        for c in range(3):
            feats.append(rng.normal(self._MEANS[c], self._STDS[c],
                                    size=(per, 4)).astype(np.float32))
            labs.append(np.full(per, c))
        features = np.concatenate(feats)
        labels = np.eye(3, dtype=np.float32)[np.concatenate(labs)]
        perm = rng.permutation(len(features))
        super().__init__(features[perm], labels[perm], batch, shuffle=False,
                         drop_last=False)
