"""Additional built-in dataset iterators.

Reference: ``org.deeplearning4j.datasets.iterator.impl.{EmnistDataSetIterator,
Cifar10DataSetIterator, SvhnDataSetIterator}`` + fetchers in
``deeplearning4j-datasets`` (auto-download + cache). Zero-egress resolution
order mirrors :mod:`deeplearning4j_tpu.datasets.mnist`: (1) cached files in
the standard formats under ``~/.deeplearning4j_tpu/<name>/``, (2) a
deterministic learnable synthetic set.
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import Optional, Tuple

import numpy as np

from deeplearning4j_tpu.datasets.iterators import ArrayDataSetIterator
from deeplearning4j_tpu.datasets.mnist import _FONT, synthesize

_ROOT = Path(os.path.expanduser("~/.deeplearning4j_tpu"))

# 5x7 glyphs for A-Z (coarse bitmap font; enough signal to be learnable)
_LETTERS = {
    "A": ["01110", "10001", "10001", "11111", "10001", "10001", "10001"],
    "B": ["11110", "10001", "11110", "10001", "10001", "10001", "11110"],
    "C": ["01110", "10001", "10000", "10000", "10000", "10001", "01110"],
    "D": ["11110", "10001", "10001", "10001", "10001", "10001", "11110"],
    "E": ["11111", "10000", "11110", "10000", "10000", "10000", "11111"],
    "F": ["11111", "10000", "11110", "10000", "10000", "10000", "10000"],
    "G": ["01110", "10001", "10000", "10111", "10001", "10001", "01111"],
    "H": ["10001", "10001", "11111", "10001", "10001", "10001", "10001"],
    "I": ["01110", "00100", "00100", "00100", "00100", "00100", "01110"],
    "J": ["00111", "00010", "00010", "00010", "10010", "10010", "01100"],
    "K": ["10001", "10010", "11100", "10010", "10001", "10001", "10001"],
    "L": ["10000", "10000", "10000", "10000", "10000", "10000", "11111"],
    "M": ["10001", "11011", "10101", "10101", "10001", "10001", "10001"],
    "N": ["10001", "11001", "10101", "10011", "10001", "10001", "10001"],
    "O": ["01110", "10001", "10001", "10001", "10001", "10001", "01110"],
    "P": ["11110", "10001", "10001", "11110", "10000", "10000", "10000"],
    "Q": ["01110", "10001", "10001", "10001", "10101", "10010", "01101"],
    "R": ["11110", "10001", "10001", "11110", "10010", "10001", "10001"],
    "S": ["01111", "10000", "01110", "00001", "00001", "10001", "01110"],
    "T": ["11111", "00100", "00100", "00100", "00100", "00100", "00100"],
    "U": ["10001", "10001", "10001", "10001", "10001", "10001", "01110"],
    "V": ["10001", "10001", "10001", "10001", "01010", "01010", "00100"],
    "W": ["10001", "10001", "10001", "10101", "10101", "11011", "10001"],
    "X": ["10001", "01010", "00100", "00100", "01010", "10001", "10001"],
    "Y": ["10001", "01010", "00100", "00100", "00100", "00100", "00100"],
    "Z": ["11111", "00001", "00010", "00100", "01000", "10000", "11111"],
}


def _render_glyphs(glyphs, num, n_classes, seed, size=28):
    rng = np.random.default_rng(seed)
    keys = list(glyphs)
    imgs = np.zeros((num, size, size), np.float32)
    lab = rng.integers(0, n_classes, num)
    for i, cls in enumerate(lab):
        g = glyphs[keys[cls]]
        scale = rng.integers(2, 4)
        gh, gw = 7 * scale, 5 * scale
        oy = rng.integers(1, size - gh - 1)
        ox = rng.integers(1, size - gw - 1)
        for r, row in enumerate(g):
            for c, bit in enumerate(row):
                if bit == "1":
                    imgs[i, oy + r * scale:oy + (r + 1) * scale,
                         ox + c * scale:ox + (c + 1) * scale] = 1.0
    imgs += rng.normal(0, 0.08, imgs.shape).astype(np.float32)
    imgs = np.clip(imgs, 0.0, 1.0)
    return imgs[..., None], np.eye(n_classes, dtype=np.float32)[lab]


class EmnistDataSetIterator(ArrayDataSetIterator):
    """Reference ``EmnistDataSetIterator(dataset_type, batch, train)``;
    sets: LETTERS (26), DIGITS (10), BALANCED (36 here: digits+letters)."""

    LETTERS = "letters"
    DIGITS = "digits"
    BALANCED = "balanced"

    def __init__(self, dataset_type: str = "letters", batch: int = 32,
                 train: bool = True, seed: int = 123,
                 num_examples: Optional[int] = None):
        n = num_examples or (8192 if train else 2048)
        s = seed + (0 if train else 777)
        if dataset_type == self.DIGITS:
            feats, labels = synthesize(n, s)
        elif dataset_type == self.LETTERS:
            feats, labels = _render_glyphs(_LETTERS, n, 26, s)
        elif dataset_type == self.BALANCED:
            both = dict(_LETTERS)
            both.update({str(d): rows for d, rows in _FONT.items()})
            feats, labels = _render_glyphs(both, n, 36, s)
        else:
            raise ValueError(f"unknown EMNIST set '{dataset_type}'")
        self.num_classes = labels.shape[1]
        super().__init__(feats, labels, batch, shuffle=True, seed=seed)


def _load_cifar_binary(train: bool) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Standard CIFAR-10 binary format (data_batch_*.bin / test_batch.bin):
    rows of [label u8, 3072 u8 RGB planar 32x32]."""
    d = _ROOT / "cifar10"
    names = ([f"data_batch_{i}.bin" for i in range(1, 6)] if train
             else ["test_batch.bin"])
    paths = [d / n for n in names]
    if not all(p.exists() for p in paths):
        return None
    feats, labels = [], []
    for p in paths:
        raw = np.frombuffer(p.read_bytes(), np.uint8).reshape(-1, 3073)
        labels.append(raw[:, 0])
        img = raw[:, 1:].reshape(-1, 3, 32, 32)  # planar CHW
        feats.append(np.transpose(img, (0, 2, 3, 1)))  # -> NHWC
    from deeplearning4j_tpu import native

    f = native.u8_to_f32(np.concatenate(feats))
    l = np.concatenate(labels)
    return f, np.eye(10, dtype=np.float32)[l]


def _synthesize_color(num, n_classes, seed, size=32):
    """Learnable color/shape classes: class determines hue + blob layout."""
    rng = np.random.default_rng(seed)
    lab = rng.integers(0, n_classes, num)
    imgs = np.zeros((num, size, size, 3), np.float32)
    hues = np.linspace(0.0, 1.0, n_classes, endpoint=False)
    for i, cls in enumerate(lab):
        h = hues[cls]
        color = np.asarray([abs(np.sin(h * 6.28)), abs(np.sin(h * 6.28 + 2)),
                            abs(np.sin(h * 6.28 + 4))], np.float32)
        cx, cy = rng.integers(8, size - 8, 2)
        r = 4 + (cls % 4) * 2
        yy, xx = np.mgrid[0:size, 0:size]
        mask = ((yy - cy) ** 2 + (xx - cx) ** 2) < r * r
        if cls % 2:  # odd classes: square
            mask = (abs(yy - cy) < r) & (abs(xx - cx) < r)
        imgs[i][mask] = color
    imgs += rng.normal(0, 0.05, imgs.shape).astype(np.float32)
    return np.clip(imgs, 0, 1), np.eye(n_classes, dtype=np.float32)[lab]


class Cifar10DataSetIterator(ArrayDataSetIterator):
    """Reference ``Cifar10DataSetIterator``; NHWC [b,32,32,3] in [0,1]."""

    def __init__(self, batch: int = 32, train: bool = True, seed: int = 123,
                 num_examples: Optional[int] = None):
        real = _load_cifar_binary(train)
        if real is not None:
            feats, labels = real
            self.synthetic = False
        else:
            n = num_examples or (8192 if train else 2048)
            feats, labels = _synthesize_color(
                n, 10, seed + (0 if train else 777))
            self.synthetic = True
        if num_examples is not None:
            feats, labels = feats[:num_examples], labels[:num_examples]
        super().__init__(feats, labels, batch, shuffle=True, seed=seed)


class SvhnDataSetIterator(ArrayDataSetIterator):
    """Reference ``SvhnDataSetIterator``; synthetic = colored digit glyphs
    on clutter (same label space as the real street-view house numbers)."""

    def __init__(self, batch: int = 32, train: bool = True, seed: int = 123,
                 num_examples: Optional[int] = None):
        n = num_examples or (8192 if train else 2048)
        rng = np.random.default_rng(seed + (0 if train else 777))
        gray, labels = synthesize(n, seed + (0 if train else 777))
        # colorize onto noisy background, resize 28->32 by padding
        imgs = rng.uniform(0.0, 0.4, (n, 32, 32, 3)).astype(np.float32)
        tint = rng.uniform(0.5, 1.0, (n, 1, 1, 3)).astype(np.float32)
        imgs[:, 2:30, 2:30, :] += gray * tint
        self.synthetic = True
        super().__init__(np.clip(imgs, 0, 1), labels, batch, shuffle=True,
                         seed=seed)
