"""DataSet containers.

Reference: ``org.nd4j.linalg.dataset.DataSet`` (features + labels +
featuresMask + labelsMask) and ``MultiDataSet`` (lists of each). Arrays here
are host numpy until they cross into the jitted step — device transfer is the
iterator/prefetcher's job, not the container's.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import numpy as np


@dataclasses.dataclass
class DataSet:
    features: np.ndarray
    labels: np.ndarray
    features_mask: Optional[np.ndarray] = None
    labels_mask: Optional[np.ndarray] = None

    def num_examples(self) -> int:
        return int(np.asarray(self.features).shape[0])

    def split_test_and_train(self, n_train: int):
        a = DataSet(self.features[:n_train], self.labels[:n_train],
                    _slice(self.features_mask, None, n_train),
                    _slice(self.labels_mask, None, n_train))
        b = DataSet(self.features[n_train:], self.labels[n_train:],
                    _slice(self.features_mask, n_train, None),
                    _slice(self.labels_mask, n_train, None))
        return a, b

    def shuffle(self, seed: int | None = None):
        rng = np.random.default_rng(seed)
        perm = rng.permutation(self.num_examples())
        self.features = np.asarray(self.features)[perm]
        self.labels = np.asarray(self.labels)[perm]
        if self.features_mask is not None:
            self.features_mask = np.asarray(self.features_mask)[perm]
        if self.labels_mask is not None:
            self.labels_mask = np.asarray(self.labels_mask)[perm]
        return self

    @staticmethod
    def merge(datasets: Sequence["DataSet"]) -> "DataSet":
        return DataSet(
            np.concatenate([np.asarray(d.features) for d in datasets]),
            np.concatenate([np.asarray(d.labels) for d in datasets]),
            _cat([d.features_mask for d in datasets]),
            _cat([d.labels_mask for d in datasets]),
        )

    def migrate(self) -> "DataSet":
        """Move all arrays to device memory in place (reference
        ``DataSet#migrate`` moves into the current workspace). uint8
        features keep their dtype (dequantized inside the jitted step).
        The fit paths also do this write-back automatically, so a DataSet
        reused across epochs transfers once."""
        import jax

        for attr in ("features", "labels", "features_mask", "labels_mask"):
            v = getattr(self, attr)
            if v is not None and not isinstance(v, jax.Array):
                setattr(self, attr, jax.device_put(np.asarray(v)))
        return self

    def detach(self) -> "DataSet":
        """Back to host numpy (reference ``DataSet#detach``)."""
        for attr in ("features", "labels", "features_mask", "labels_mask"):
            v = getattr(self, attr)
            if v is not None:
                setattr(self, attr, np.asarray(v))
        return self


def _slice(arr, a, b):
    return None if arr is None else np.asarray(arr)[a:b]


def _cat(arrs):
    if any(a is None for a in arrs):
        return None
    return np.concatenate([np.asarray(a) for a in arrs])


@dataclasses.dataclass
class MultiDataSet:
    """Reference ``org.nd4j.linalg.dataset.MultiDataSet``: multi-input /
    multi-output sample container for ComputationGraph training."""

    features: Sequence[np.ndarray]
    labels: Sequence[np.ndarray]
    features_masks: Optional[Sequence[Optional[np.ndarray]]] = None
    labels_masks: Optional[Sequence[Optional[np.ndarray]]] = None

    def num_examples(self) -> int:
        return int(np.asarray(self.features[0]).shape[0])
