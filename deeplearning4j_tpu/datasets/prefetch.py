"""Async prefetching iterators.

Reference: ``org.nd4j.linalg.dataset.api.iterator.AsyncDataSetIterator`` —
a background thread pulls from the wrapped iterator into a bounded queue so
ETL overlaps training (the reference wraps every ``fit`` iterator in one,
SURVEY.md §3.1).

TPU additions:

- ``AsyncDataSetIterator(device_put=True)``: the worker thread also
  ``device_put``s batches, so the host->HBM transfer happens off the
  training thread.
- ``DeviceRingIterator`` (round 6): a DEPTH-deep device ring on the
  consumer thread — batch N+1's (async) ``device_put`` is issued before
  batch N is handed to the training loop, so the transfer overlaps the
  running step without any thread handoff, and the buffers of batches the
  consumer has moved past are donated back (deleted) so the ring holds at
  most ``depth + 1`` batches of HBM regardless of epoch length. Compose
  them for ETL + transfer overlap:
  ``DeviceRingIterator(AsyncDataSetIterator(it))``.
"""

from __future__ import annotations

import collections
import queue
import threading
from typing import Optional

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet, MultiDataSet
from deeplearning4j_tpu.datasets.iterators import DataSetIterator

_SENTINEL = object()


# --------------------------------------------------------------------------
# K-batch stacking (round 11: the fused multi-step training driver's feed)
# --------------------------------------------------------------------------

def _uniform(arrs) -> bool:
    """True when every column entry shares shape/dtype (or all are None)."""
    first = arrs[0]
    if first is None:
        return all(a is None for a in arrs)
    if any(a is None for a in arrs[1:]):
        return False
    shape = np.shape(first)
    dtype = getattr(first, "dtype", None)
    return all(np.shape(a) == shape and getattr(a, "dtype", None) == dtype
               for a in arrs[1:])


def _stack_col(arrs):
    """Stack one column to [K, ...]: numpy batches stack on HOST (free —
    the single fused device_put happens at staging time); already-device
    batches stack on device (one tiny dispatch, no host round-trip)."""
    if arrs[0] is None:
        return None
    import jax
    import jax.numpy as jnp

    if any(isinstance(a, jax.Array) for a in arrs):
        return jnp.stack(arrs)
    return np.stack([np.asarray(a) for a in arrs])


def stack_batch_group(group, materialize: bool = True):
    """K uniform batches -> ONE stacked container ([K, B, ...] per array,
    tagged ``fused_stack=K`` so the fit paths route it to the K-step
    fused scan). Returns None when the group cannot stack (mixed types,
    ragged shapes, mismatched mask presence) — the caller then falls back
    to plain per-step batches, so correctness never depends on uniform
    streams.

    ``materialize=False`` runs ONLY the shape/dtype uniformity decision
    and returns a lightweight placeholder (first batch's arrays, tagged
    ``fused_stack=K``) in place of the real stack — for a resuming
    session's fast-forward, which needs the yield positions but discards
    the content, so it must not pay the K-batch copies."""
    k = len(group)
    if k < 2:
        return None
    first = group[0]
    if isinstance(first, DataSet) \
            and all(type(g) is DataSet for g in group):
        cols = [[g.features for g in group], [g.labels for g in group],
                [g.features_mask for g in group],
                [g.labels_mask for g in group]]
        if not all(_uniform(c) for c in cols):
            return None
        if materialize:
            out = DataSet(*(_stack_col(c) for c in cols))
        else:
            out = DataSet(first.features, first.labels,
                          first.features_mask, first.labels_mask)
        out.fused_stack = k
        return out
    if isinstance(first, MultiDataSet) \
            and all(type(g) is MultiDataSet for g in group):
        n_f, n_l = len(first.features), len(first.labels)
        if any(len(g.features) != n_f or len(g.labels) != n_l
               for g in group):
            return None

        def col(attr, i):
            out = []
            for g in group:
                m = getattr(g, attr)
                out.append(None if m is None else m[i])
            return out

        fcols = [[g.features[i] for g in group] for i in range(n_f)]
        lcols = [[g.labels[i] for g in group] for i in range(n_l)]
        fmcols = [col("features_masks", i) for i in range(n_f)]
        lmcols = [col("labels_masks", i) for i in range(n_l)]
        if not all(_uniform(c) for c in fcols + lcols + fmcols + lmcols):
            return None
        if materialize:
            fms = [_stack_col(c) for c in fmcols]
            lms = [_stack_col(c) for c in lmcols]
            out = MultiDataSet(
                features=[_stack_col(c) for c in fcols],
                labels=[_stack_col(c) for c in lcols],
                features_masks=(fms if any(m is not None for m in fms)
                                else None),
                labels_masks=(lms if any(m is not None for m in lms)
                              else None))
        else:
            out = MultiDataSet(features=list(first.features),
                               labels=list(first.labels),
                               features_masks=first.features_masks,
                               labels_masks=first.labels_masks)
        out.fused_stack = k
        return out
    return None


class AsyncDataSetIterator(DataSetIterator):
    """Bounded-queue prefetch wrapper (reference ``AsyncDataSetIterator``,
    default queue size 8 there; same default here)."""

    def __init__(self, wrapped: DataSetIterator, queue_size: int = 8,
                 device_put: bool = False, device=None):
        self.wrapped = wrapped
        self.queue_size = max(1, int(queue_size))
        self.device_put = device_put
        self.device = device
        self._thread: Optional[threading.Thread] = None
        self._queue: Optional[queue.Queue] = None
        self._stop = threading.Event()
        self._error: Optional[BaseException] = None

    def batch_size(self):
        return self.wrapped.batch_size()

    def total_examples(self):
        return self.wrapped.total_examples()

    def _producer(self):
        try:
            for ds in self.wrapped:
                if self._stop.is_set():
                    return
                if self.device_put:
                    ds = self._to_device(ds)
                while not self._stop.is_set():
                    try:
                        self._queue.put(ds, timeout=0.1)
                        break
                    except queue.Full:
                        continue
        except BaseException as e:  # surfaced on the consumer side
            self._error = e
        finally:
            while not self._stop.is_set():
                try:
                    self._queue.put(_SENTINEL, timeout=0.1)
                    return
                except queue.Full:
                    continue

    def _to_device(self, ds: DataSet) -> DataSet:
        import jax

        put = (lambda a: jax.device_put(a, self.device)) if self.device \
            else jax.device_put
        return DataSet(
            put(np.asarray(ds.features)), put(np.asarray(ds.labels)),
            None if ds.features_mask is None else put(np.asarray(ds.features_mask)),
            None if ds.labels_mask is None else put(np.asarray(ds.labels_mask)))

    def __iter__(self):
        self._shutdown()
        self._stop.clear()
        self._error = None
        self._queue = queue.Queue(self.queue_size)
        self._thread = threading.Thread(target=self._producer, daemon=True,
                                        name="AsyncDataSetIterator")
        self._thread.start()
        try:
            while True:
                item = self._queue.get()
                if item is _SENTINEL:
                    break
                yield item
            self._thread.join(timeout=5)
            if self._error is not None:
                raise self._error
        finally:
            # consumer may abandon the generator early (break / exception in
            # the training loop): stop the producer rather than leaking the
            # thread and its queued (possibly device-resident) batches
            self._shutdown()

    def _shutdown(self):
        if self._thread is not None and self._thread.is_alive():
            self._stop.set()
            self._thread.join(timeout=5)
        self._thread = None

    def reset(self):
        self._shutdown()
        self.wrapped.reset()

    def __del__(self):
        try:
            self._shutdown()
        except Exception:
            pass


class StackBatchIterator(DataSetIterator):
    """Host-side K-batch stacking WITHOUT device staging — for consumers
    that own their device placement (ParallelWrapper shards the stacks
    over its mesh itself). Yields ``stack_batch_group`` super-batches;
    ragged tails / non-uniform groups degrade to plain batches."""

    def __init__(self, wrapped: DataSetIterator, stack_batches: int):
        self.wrapped = wrapped
        self.stack_batches = int(stack_batches)
        self._skip_next = 0

    def batch_size(self):
        return self.wrapped.batch_size()

    def total_examples(self):
        return self.wrapped.total_examples()

    def skip_stacking(self, n: int) -> None:
        """One-shot: the next iteration's first ``n`` yields keep their
        positions (the uniformity decision still runs) but skip the
        K-batch copies — placeholder super-batches a fast-forwarding
        consumer discards."""
        self._skip_next = max(0, int(n))

    def __iter__(self):
        from deeplearning4j_tpu import telemetry

        skip = self._skip_next
        self._skip_next = 0
        group = []
        for ds in self.wrapped:
            group.append(ds)
            if len(group) < self.stack_batches:
                continue
            with telemetry.span(telemetry.PHASE_INGEST):
                stacked = stack_batch_group(group, materialize=skip <= 0)
            if stacked is not None:
                skip -= 1
                yield stacked
            else:
                for g in group:
                    skip -= 1
                    yield g
            group = []
        yield from group

    def reset(self):
        self.wrapped.reset()


class DeviceRingIterator(DataSetIterator):
    """Double-buffered device ingest (default ``depth=2``).

    ``jax.device_put`` is asynchronous: issuing batch N+1's transfer
    BEFORE handing batch N to the training loop lets the host->device copy
    ride under the running step instead of serializing after it. The ring
    keeps ``depth`` staged batches in flight; when the consumer comes back
    for the next batch it has necessarily dispatched compute on the
    previous one, so the batch BEFORE that is consumed — its device
    buffers are donated back (``jax.Array.delete``; in-flight executions
    hold their own buffer references, so early deletion only releases the
    Python-side handle's claim on HBM). Donation applies ONLY to arrays
    this iterator staged itself — already-device-resident inputs (e.g. an
    ``AsyncDataSetIterator(device_put=True)`` upstream, or write-back-
    migrated DataSets) pass through untouched, so reuse across epochs
    stays safe.

    ``stack_batches=K`` (round 11, the fused multi-step training feed):
    pull K batches at a time from the wrapped iterator, stack them on
    HOST into one ``[K, B, ...]`` super-batch (``stack_batch_group``,
    tagged ``fused_stack=K``) and stage it with ONE ``device_put`` per
    array — so a K-step fused dispatch costs one transfer, the ring
    overlaps it under the running super-step exactly as it overlaps
    single batches, and a consumed stack's buffers are donated back as
    one unit. Ragged tails (fewer than K left) and non-uniform groups
    (shape/dtype/mask-presence mismatch) fall back to plain per-step
    batches.

    ``MultiDataSet`` items are staged array-by-array the same way
    (round 11; they previously passed through unstaged)."""

    def __init__(self, wrapped: DataSetIterator, depth: int = 2,
                 donate: bool = True, device=None, retry=...,
                 stack_batches: int = 0):
        from deeplearning4j_tpu.resilience import retry as _retry

        self.wrapped = wrapped
        self.depth = max(1, int(depth))
        self.donate = bool(donate)
        self.device = device
        self.stack_batches = int(stack_batches or 0)
        self._skip_next = 0
        # transient device_put failures (driver hiccup, injected fault)
        # are retried with backoff instead of killing the epoch; pass
        # retry=None to stage without a safety net
        self.retry = _retry.INGEST_RETRY if retry is ... else retry
        self.staged_count = 0
        self.retired_count = 0

    def batch_size(self):
        return self.wrapped.batch_size()

    def total_examples(self):
        return self.wrapped.total_examples()

    def _stage(self, ds):
        """-> (device DataSet/MultiDataSet, owned device arrays). Issues
        the async transfers; owned = only the arrays staged here
        (donation-safe). A stacked super-batch keeps its ``fused_stack``
        tag across staging."""
        import jax

        from deeplearning4j_tpu import telemetry

        if not isinstance(ds, (DataSet, MultiDataSet)):
            return ds, []
        owned = []
        put = (lambda a: jax.device_put(a, self.device)) if self.device \
            else jax.device_put

        from deeplearning4j_tpu.resilience import faults

        def put_once(a):
            faults.fault_point("ingest.device_put")
            return put(np.asarray(a))

        def stage(a):
            if a is None or isinstance(a, jax.Array):
                return a
            d = (self.retry.call(put_once, a, op="ingest.device_put")
                 if self.retry is not None else put_once(a))
            owned.append(d)
            return d

        with telemetry.span(telemetry.PHASE_INGEST):
            if isinstance(ds, DataSet):
                staged = DataSet(stage(ds.features), stage(ds.labels),
                                 stage(ds.features_mask),
                                 stage(ds.labels_mask))
            else:
                def stage_list(group):
                    return (None if group is None
                            else [stage(a) for a in group])

                staged = MultiDataSet(
                    features=stage_list(ds.features),
                    labels=stage_list(ds.labels),
                    features_masks=stage_list(ds.features_masks),
                    labels_masks=stage_list(ds.labels_masks))
        k = getattr(ds, "fused_stack", 0)
        if k:
            staged.fused_stack = k
        if telemetry.enabled() and owned:
            telemetry.record_ingest(sum(int(a.nbytes) for a in owned))
        self.staged_count += 1
        return staged, owned

    def _retire(self, owned):
        if not self.donate:
            return
        for a in owned:
            try:
                a.delete()
            except Exception:
                pass  # backend without explicit delete / already freed
        if owned:
            self.retired_count += 1

    def skip_staging(self, n: int) -> None:
        """The next iteration's first ``n`` items bypass device staging
        (yielded as-is, host arrays): a resuming ``TrainingSession``
        fast-forwards past already-trained (super-)steps and must not
        pay their transfers — it counts and discards the SAME yielded
        items either way, so positions stay aligned."""
        self._skip_next = max(0, int(n))

    def __iter__(self):
        ring = collections.deque()
        last_owned = None
        skip = self._skip_next
        self._skip_next = 0
        source = (StackBatchIterator(self.wrapped, self.stack_batches)
                  if self.stack_batches > 1 else self.wrapped)
        if skip and isinstance(source, StackBatchIterator):
            source.skip_stacking(skip)  # skip the host copies too
        for ds in source:
            if skip > 0:
                skip -= 1
                yield ds  # fast-forward: un-staged, consumer discards
                continue
            ring.append(self._stage(ds))
            if len(ring) < self.depth:
                continue
            out, owned = ring.popleft()
            yield out
            # the consumer is back for the next batch: it has dispatched
            # compute on ``out``; the batch it held BEFORE ``out`` is
            # consumed — donate its buffers
            if last_owned is not None:
                self._retire(last_owned)
            last_owned = owned
        while ring:
            out, owned = ring.popleft()
            yield out
            if last_owned is not None:
                self._retire(last_owned)
            last_owned = owned
        # the final batch's buffers stay referenced until the generator
        # is collected: the epoch-end sync may still be reading them

    def reset(self):
        self.wrapped.reset()
