"""Async prefetching iterator.

Reference: ``org.nd4j.linalg.dataset.api.iterator.AsyncDataSetIterator`` —
a background thread pulls from the wrapped iterator into a bounded queue so
ETL overlaps training (the reference wraps every ``fit`` iterator in one,
SURVEY.md §3.1). TPU version: the worker can additionally ``device_put``
batches so the host→HBM transfer also overlaps the running step
(double-buffering); the training loop then consumes device-resident arrays.
"""

from __future__ import annotations

import queue
import threading
from typing import Optional

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import DataSetIterator

_SENTINEL = object()


class AsyncDataSetIterator(DataSetIterator):
    """Bounded-queue prefetch wrapper (reference ``AsyncDataSetIterator``,
    default queue size 8 there; same default here)."""

    def __init__(self, wrapped: DataSetIterator, queue_size: int = 8,
                 device_put: bool = False, device=None):
        self.wrapped = wrapped
        self.queue_size = max(1, int(queue_size))
        self.device_put = device_put
        self.device = device
        self._thread: Optional[threading.Thread] = None
        self._queue: Optional[queue.Queue] = None
        self._stop = threading.Event()
        self._error: Optional[BaseException] = None

    def batch_size(self):
        return self.wrapped.batch_size()

    def total_examples(self):
        return self.wrapped.total_examples()

    def _producer(self):
        try:
            for ds in self.wrapped:
                if self._stop.is_set():
                    return
                if self.device_put:
                    ds = self._to_device(ds)
                while not self._stop.is_set():
                    try:
                        self._queue.put(ds, timeout=0.1)
                        break
                    except queue.Full:
                        continue
        except BaseException as e:  # surfaced on the consumer side
            self._error = e
        finally:
            while not self._stop.is_set():
                try:
                    self._queue.put(_SENTINEL, timeout=0.1)
                    return
                except queue.Full:
                    continue

    def _to_device(self, ds: DataSet) -> DataSet:
        import jax

        put = (lambda a: jax.device_put(a, self.device)) if self.device \
            else jax.device_put
        return DataSet(
            put(np.asarray(ds.features)), put(np.asarray(ds.labels)),
            None if ds.features_mask is None else put(np.asarray(ds.features_mask)),
            None if ds.labels_mask is None else put(np.asarray(ds.labels_mask)))

    def __iter__(self):
        self._shutdown()
        self._stop.clear()
        self._error = None
        self._queue = queue.Queue(self.queue_size)
        self._thread = threading.Thread(target=self._producer, daemon=True,
                                        name="AsyncDataSetIterator")
        self._thread.start()
        try:
            while True:
                item = self._queue.get()
                if item is _SENTINEL:
                    break
                yield item
            self._thread.join(timeout=5)
            if self._error is not None:
                raise self._error
        finally:
            # consumer may abandon the generator early (break / exception in
            # the training loop): stop the producer rather than leaking the
            # thread and its queued (possibly device-resident) batches
            self._shutdown()

    def _shutdown(self):
        if self._thread is not None and self._thread.is_alive():
            self._stop.set()
            self._thread.join(timeout=5)
        self._thread = None

    def reset(self):
        self._shutdown()
        self.wrapped.reset()

    def __del__(self):
        try:
            self._shutdown()
        except Exception:
            pass
