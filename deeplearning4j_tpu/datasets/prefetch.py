"""Async prefetching iterators.

Reference: ``org.nd4j.linalg.dataset.api.iterator.AsyncDataSetIterator`` —
a background thread pulls from the wrapped iterator into a bounded queue so
ETL overlaps training (the reference wraps every ``fit`` iterator in one,
SURVEY.md §3.1).

TPU additions:

- ``AsyncDataSetIterator(device_put=True)``: the worker thread also
  ``device_put``s batches, so the host->HBM transfer happens off the
  training thread.
- ``DeviceRingIterator`` (round 6): a DEPTH-deep device ring on the
  consumer thread — batch N+1's (async) ``device_put`` is issued before
  batch N is handed to the training loop, so the transfer overlaps the
  running step without any thread handoff, and the buffers of batches the
  consumer has moved past are donated back (deleted) so the ring holds at
  most ``depth + 1`` batches of HBM regardless of epoch length. Compose
  them for ETL + transfer overlap:
  ``DeviceRingIterator(AsyncDataSetIterator(it))``.
"""

from __future__ import annotations

import collections
import queue
import threading
from typing import Optional

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import DataSetIterator

_SENTINEL = object()


class AsyncDataSetIterator(DataSetIterator):
    """Bounded-queue prefetch wrapper (reference ``AsyncDataSetIterator``,
    default queue size 8 there; same default here)."""

    def __init__(self, wrapped: DataSetIterator, queue_size: int = 8,
                 device_put: bool = False, device=None):
        self.wrapped = wrapped
        self.queue_size = max(1, int(queue_size))
        self.device_put = device_put
        self.device = device
        self._thread: Optional[threading.Thread] = None
        self._queue: Optional[queue.Queue] = None
        self._stop = threading.Event()
        self._error: Optional[BaseException] = None

    def batch_size(self):
        return self.wrapped.batch_size()

    def total_examples(self):
        return self.wrapped.total_examples()

    def _producer(self):
        try:
            for ds in self.wrapped:
                if self._stop.is_set():
                    return
                if self.device_put:
                    ds = self._to_device(ds)
                while not self._stop.is_set():
                    try:
                        self._queue.put(ds, timeout=0.1)
                        break
                    except queue.Full:
                        continue
        except BaseException as e:  # surfaced on the consumer side
            self._error = e
        finally:
            while not self._stop.is_set():
                try:
                    self._queue.put(_SENTINEL, timeout=0.1)
                    return
                except queue.Full:
                    continue

    def _to_device(self, ds: DataSet) -> DataSet:
        import jax

        put = (lambda a: jax.device_put(a, self.device)) if self.device \
            else jax.device_put
        return DataSet(
            put(np.asarray(ds.features)), put(np.asarray(ds.labels)),
            None if ds.features_mask is None else put(np.asarray(ds.features_mask)),
            None if ds.labels_mask is None else put(np.asarray(ds.labels_mask)))

    def __iter__(self):
        self._shutdown()
        self._stop.clear()
        self._error = None
        self._queue = queue.Queue(self.queue_size)
        self._thread = threading.Thread(target=self._producer, daemon=True,
                                        name="AsyncDataSetIterator")
        self._thread.start()
        try:
            while True:
                item = self._queue.get()
                if item is _SENTINEL:
                    break
                yield item
            self._thread.join(timeout=5)
            if self._error is not None:
                raise self._error
        finally:
            # consumer may abandon the generator early (break / exception in
            # the training loop): stop the producer rather than leaking the
            # thread and its queued (possibly device-resident) batches
            self._shutdown()

    def _shutdown(self):
        if self._thread is not None and self._thread.is_alive():
            self._stop.set()
            self._thread.join(timeout=5)
        self._thread = None

    def reset(self):
        self._shutdown()
        self.wrapped.reset()

    def __del__(self):
        try:
            self._shutdown()
        except Exception:
            pass


class DeviceRingIterator(DataSetIterator):
    """Double-buffered device ingest (default ``depth=2``).

    ``jax.device_put`` is asynchronous: issuing batch N+1's transfer
    BEFORE handing batch N to the training loop lets the host->device copy
    ride under the running step instead of serializing after it. The ring
    keeps ``depth`` staged batches in flight; when the consumer comes back
    for the next batch it has necessarily dispatched compute on the
    previous one, so the batch BEFORE that is consumed — its device
    buffers are donated back (``jax.Array.delete``; in-flight executions
    hold their own buffer references, so early deletion only releases the
    Python-side handle's claim on HBM). Donation applies ONLY to arrays
    this iterator staged itself — already-device-resident inputs (e.g. an
    ``AsyncDataSetIterator(device_put=True)`` upstream, or write-back-
    migrated DataSets) pass through untouched, so reuse across epochs
    stays safe.

    Non-``DataSet`` items (MultiDataSet) pass through unstaged."""

    def __init__(self, wrapped: DataSetIterator, depth: int = 2,
                 donate: bool = True, device=None, retry=...):
        from deeplearning4j_tpu.resilience import retry as _retry

        self.wrapped = wrapped
        self.depth = max(1, int(depth))
        self.donate = bool(donate)
        self.device = device
        # transient device_put failures (driver hiccup, injected fault)
        # are retried with backoff instead of killing the epoch; pass
        # retry=None to stage without a safety net
        self.retry = _retry.INGEST_RETRY if retry is ... else retry
        self.staged_count = 0
        self.retired_count = 0

    def batch_size(self):
        return self.wrapped.batch_size()

    def total_examples(self):
        return self.wrapped.total_examples()

    def _stage(self, ds):
        """-> (device DataSet, owned device arrays). Issues the async
        transfers; owned = only the arrays staged here (donation-safe)."""
        import jax

        from deeplearning4j_tpu import telemetry

        if not isinstance(ds, DataSet):
            return ds, []
        owned = []
        put = (lambda a: jax.device_put(a, self.device)) if self.device \
            else jax.device_put

        from deeplearning4j_tpu.resilience import faults

        def put_once(a):
            faults.fault_point("ingest.device_put")
            return put(np.asarray(a))

        def stage(a):
            if a is None or isinstance(a, jax.Array):
                return a
            d = (self.retry.call(put_once, a, op="ingest.device_put")
                 if self.retry is not None else put_once(a))
            owned.append(d)
            return d

        with telemetry.span(telemetry.PHASE_INGEST):
            staged = DataSet(stage(ds.features), stage(ds.labels),
                             stage(ds.features_mask), stage(ds.labels_mask))
        if telemetry.enabled() and owned:
            telemetry.record_ingest(sum(int(a.nbytes) for a in owned))
        self.staged_count += 1
        return staged, owned

    def _retire(self, owned):
        if not self.donate:
            return
        for a in owned:
            try:
                a.delete()
            except Exception:
                pass  # backend without explicit delete / already freed
        if owned:
            self.retired_count += 1

    def __iter__(self):
        ring = collections.deque()
        last_owned = None
        for ds in self.wrapped:
            ring.append(self._stage(ds))
            if len(ring) < self.depth:
                continue
            out, owned = ring.popleft()
            yield out
            # the consumer is back for the next batch: it has dispatched
            # compute on ``out``; the batch it held BEFORE ``out`` is
            # consumed — donate its buffers
            if last_owned is not None:
                self._retire(last_owned)
            last_owned = owned
        while ring:
            out, owned = ring.popleft()
            yield out
            if last_owned is not None:
                self._retire(last_owned)
            last_owned = owned
        # the final batch's buffers stay referenced until the generator
        # is collected: the epoch-end sync may still be reading them

    def reset(self):
        self.wrapped.reset()
