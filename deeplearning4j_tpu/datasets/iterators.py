"""DataSetIterator contract + basic implementations.

Reference: ``org.nd4j.linalg.dataset.api.iterator.DataSetIterator`` and
impls (``ListDataSetIterator``, ``ExistingDataSetIterator``, …) plus the
``AsyncDataSetIterator`` prefetcher (see
:mod:`deeplearning4j_tpu.datasets.prefetch`).

Iterators yield :class:`DataSet` of host numpy arrays. For TPU efficiency the
training loop keeps batch shapes static — iterators therefore DROP the final
partial batch by default when ``drop_last`` (XLA recompiles per new shape;
the reference has no such constraint). Set ``pad_last=True`` to instead pad
the tail batch with zeroed, mask-excluded examples.
"""

from __future__ import annotations

from typing import Iterator, List, Optional

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet


class DataSetIterator:
    """Iterator protocol (subset of the reference's interface)."""

    def reset(self) -> None:
        raise NotImplementedError

    def batch_size(self) -> int:
        raise NotImplementedError

    def __iter__(self) -> Iterator[DataSet]:
        raise NotImplementedError

    def total_examples(self) -> Optional[int]:
        return None


class ListDataSetIterator(DataSetIterator):
    """Iterate over a pre-built list of DataSets (reference
    ``ListDataSetIterator``)."""

    def __init__(self, datasets: List[DataSet]):
        self._data = list(datasets)

    def reset(self):
        pass

    def batch_size(self):
        return self._data[0].num_examples() if self._data else 0

    def __iter__(self):
        return iter(self._data)

    def total_examples(self):
        return sum(d.num_examples() for d in self._data)


class ArrayDataSetIterator(DataSetIterator):
    """Mini-batch iterator over whole arrays, with optional shuffling per
    epoch and static-shape tail handling."""

    def __init__(self, features, labels, batch: int,
                 features_mask=None, labels_mask=None,
                 shuffle: bool = False, seed: int = 0,
                 drop_last: bool = True, pad_last: bool = False):
        self.features = np.asarray(features)
        self.labels = np.asarray(labels)
        self.features_mask = None if features_mask is None else np.asarray(features_mask)
        self.labels_mask = None if labels_mask is None else np.asarray(labels_mask)
        self.batch = int(batch)
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.pad_last = pad_last
        self._epoch = 0

    def reset(self):
        self._epoch += 1

    def batch_size(self):
        return self.batch

    def total_examples(self):
        return self.features.shape[0]

    def __iter__(self):
        n = self.features.shape[0]
        idx = np.arange(n)
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self._epoch)
            rng.shuffle(idx)
        stop = n - (n % self.batch) if (self.drop_last and not self.pad_last) else n
        from deeplearning4j_tpu import native as _native
        take = _native.gather_rows if self.shuffle else (lambda a, i: a[i])
        for start in range(0, stop, self.batch):
            sel = idx[start:start + self.batch]
            fm = None if self.features_mask is None else take(self.features_mask, sel)
            lm = None if self.labels_mask is None else take(self.labels_mask, sel)
            f, l = take(self.features, sel), take(self.labels, sel)
            if self.pad_last and len(sel) < self.batch:
                pad = self.batch - len(sel)
                f = _pad0(f, pad)
                l = _pad0(l, pad)
                # excluded-from-loss via labels mask
                base_lm = np.ones(len(sel), np.float32) if lm is None else lm
                lm = _pad0(base_lm, pad)
                fm = None if fm is None else _pad0(fm, pad)
            yield DataSet(f, l, fm, lm)


def _pad0(arr, pad):
    width = [(0, pad)] + [(0, 0)] * (arr.ndim - 1)
    return np.pad(arr, width)
