"""Deterministic fault injection.

Production traffic is defined by partial failure — a preempted TPU
worker, a full disk mid-checkpoint, a wedged serving launch — and none
of those can be regression-tested if they only ever happen by accident.
This module makes failure a first-class, *seedable* input: product code
carries permanent one-line ``fault_point(site)`` hooks (a no-op module
check when no plan is armed, the same discipline as telemetry spans),
and a test arms a :class:`FaultPlan` that raises, delays, or
NaN-poisons on exactly the invocations it chose.

Named sites (the permanent hooks in product code)::

    checkpoint.write     util.serializer.write_model, mid-zip-assembly
                         (a raise here IS a partial write: the temp file
                         holds some entries, the publish never happens)
    ingest.device_put    datasets.prefetch.DeviceRingIterator staging
    train.step           nn.multilayer / nn.graph / parallel.wrapper,
                         once per optimization step, before the compiled
                         step launches (corrupt mode poisons the batch)
    serving.launch       parallel.batcher dispatcher, before the shared
                         forward (delay mode simulates a stuck launch —
                         the watchdog's test vector)
    decode.launch        parallel.generation decode loop, before each
                         prefill/decode dispatch (the generation
                         breaker's test vector)
    stats.flush          ui.stats remote-router delivery attempt
    model.load           parallel.platform.ModelRegistry.load, after
                         the version resolves and before the zip is
                         digest-verified + restored (raise = a failed
                         load that must leave the incumbent serving;
                         retried by MODEL_LOAD_RETRY)
    model.swap           parallel.platform.ModelPlatform.swap, after
                         the new version loaded and before it is
                         published into the serving engine (raise =
                         partial swap, incumbent keeps serving; delay =
                         wedged swap, traffic must flow throughout)
    snapshot.shard_write resilience.pod.write_pod_snapshot, inside one
                         host's shard file assembly, before the atomic
                         publish (a raise IS a partial shard on that
                         host: temp bytes exist, no host manifest
                         references them, the coordinator manifest is
                         never committed — the prior complete snapshot
                         stays authoritative)
    pod.heartbeat        resilience.session pod-mode fit loop, once per
                         batch (raise HostDeathError(host=k) here = a
                         FaultPlan-seeded host death; the session
                         treats it as resumable and the whole job
                         resumes from the last distributed snapshot)

Per-model scoping: an engine constructed with ``name=`` fires
``serving.launch:<name>`` / ``decode.launch:<name>`` instead of the
bare site, so a chaos plan can degrade exactly one tenant of a
multi-model host (``ModelPlatform``) while its co-tenants stay clean.

Usage::

    plan = FaultPlan(seed=7)
    plan.inject("checkpoint.write", on_calls=[2],
                exc=lambda: OSError(errno.ENOSPC, "No space left"))
    plan.inject("train.step", probability=0.1, action="corrupt")
    with plan.armed():
        ...   # the run under test

Determinism: ``on_calls`` fires on exact 1-based invocation indices;
``probability`` draws from a per-(seed, site) ``random.Random`` stream,
so two plans with the same seed arm the same invocation sequence. One
plan is armed per process at a time (nesting raises — a chaos run whose
faults silently shadow each other proves nothing). Every fire counts
into ``dl4j_faults_injected_total{site=...}``.
"""

from __future__ import annotations

import contextlib
import random
import threading
import time
from typing import Callable, Iterable, List, Optional

#: The permanent hooks product code carries (documentation + typo guard;
#: ``inject`` warns on unknown sites but does not reject them, so a plan
#: can target sites added by downstream code).
SITES = (
    "checkpoint.write",
    "ingest.device_put",
    "train.step",
    "serving.launch",
    "decode.launch",
    "stats.flush",
    "model.load",
    "model.swap",
    "snapshot.shard_write",
    "pod.heartbeat",
)


class InjectedFault(RuntimeError):
    """Default exception a raise-mode fault throws. Carries the site and
    the 1-based invocation index that fired."""

    def __init__(self, site: str, invocation: int, message: str = None):
        super().__init__(message or
                         f"injected fault at {site!r} "
                         f"(invocation {invocation})")
        self.site = site
        self.invocation = invocation


class _FaultSpec:
    __slots__ = ("site", "on_calls", "probability", "action", "exc",
                 "delay_s", "max_fires", "fired", "_rng")

    def __init__(self, site, on_calls, probability, action, exc, delay_s,
                 max_fires, seed):
        self.site = site
        self.on_calls = frozenset(int(c) for c in on_calls) \
            if on_calls is not None else None
        self.probability = probability
        self.action = action
        self.exc = exc
        self.delay_s = float(delay_s)
        self.max_fires = max_fires
        self.fired = 0
        # per-(seed, site) stream: the k-th invocation's draw is the same
        # number in every run with this seed
        self._rng = random.Random(f"{seed}:{site}:{action}")

    def should_fire(self, invocation: int) -> bool:
        if self.max_fires is not None and self.fired >= self.max_fires:
            return False
        if self.on_calls is not None:
            return invocation in self.on_calls
        if self.probability is not None:
            return self._rng.random() < self.probability
        return True  # no selector: every invocation

    def make_exc(self, invocation: int) -> BaseException:
        if self.exc is None:
            return InjectedFault(self.site, invocation)
        if isinstance(self.exc, BaseException):
            return self.exc
        return self.exc()  # class or factory


def _poison(value):
    """NaN-poison an array-ish value (corrupt mode): float arrays get a
    NaN in element 0, everything else passes through unchanged (uint8
    image batches cannot hold a NaN — poisoning them is a different
    fault class the caller can model with ``action="raise"``)."""
    import numpy as np

    if value is None:
        return None
    try:
        arr = np.array(value, copy=True)
    except Exception:
        return value
    if arr.size == 0 or not np.issubdtype(arr.dtype, np.floating):
        return value
    arr.reshape(-1)[0] = np.nan
    if type(value).__module__.startswith("jax"):
        import jax.numpy as jnp

        return jnp.asarray(arr)
    return arr


class FaultPlan:
    """A seedable set of armed injection sites. Build with chained
    :meth:`inject` calls, activate with :meth:`armed` (context manager)
    or :meth:`arm` / :meth:`disarm`."""

    def __init__(self, seed: int = 0):
        self.seed = int(seed)
        self._specs: List[_FaultSpec] = []
        self._invocations: dict = {}
        self._lock = threading.Lock()

    def inject(self, site: str,
               on_calls: Optional[Iterable[int]] = None,
               probability: Optional[float] = None,
               action: str = "raise",
               exc: Optional[Callable[[], BaseException]] = None,
               delay_s: float = 0.05,
               max_fires: Optional[int] = None) -> "FaultPlan":
        """Arm ``site``. Selector: ``on_calls`` (1-based invocation
        indices) or ``probability`` (seeded per-site stream) or neither
        (every invocation). ``action``: ``"raise"`` (throw ``exc`` —
        class, factory, or instance; default :class:`InjectedFault`),
        ``"delay"`` (sleep ``delay_s`` then proceed), ``"corrupt"``
        (NaN-poison the hook's value). ``max_fires`` caps total fires."""
        if action not in ("raise", "delay", "corrupt"):
            raise ValueError(f"unknown fault action {action!r}")
        if on_calls is not None and probability is not None:
            raise ValueError("choose on_calls OR probability, not both")
        self._specs.append(_FaultSpec(
            site, on_calls, probability, action, exc, delay_s, max_fires,
            self.seed))
        return self

    # --- arming -------------------------------------------------------------
    def arm(self) -> "FaultPlan":
        global _ACTIVE
        with _ARM_LOCK:
            if _ACTIVE is not None:
                raise RuntimeError(
                    "a FaultPlan is already armed in this process")
            _ACTIVE = self
        return self

    def disarm(self) -> "FaultPlan":
        global _ACTIVE
        with _ARM_LOCK:
            if _ACTIVE is self:
                _ACTIVE = None
        return self

    @contextlib.contextmanager
    def armed(self):
        self.arm()
        try:
            yield self
        finally:
            self.disarm()

    # --- introspection ------------------------------------------------------
    def invocations(self, site: str) -> int:
        """How many times ``site``'s hook ran while this plan was armed."""
        return self._invocations.get(site, 0)

    def fired(self, site: str = None) -> int:
        """Total faults fired (optionally for one site)."""
        return sum(s.fired for s in self._specs
                   if site is None or s.site == site)

    # --- the hook's slow path ----------------------------------------------
    def _hit(self, site: str, value):
        with self._lock:
            inv = self._invocations.get(site, 0) + 1
            self._invocations[site] = inv
            to_fire = [s for s in self._specs
                       if s.site == site and s.should_fire(inv)]
            for s in to_fire:
                s.fired += 1
        for s in to_fire:
            _record_injected(site, s.action)
            if s.action == "raise":
                raise s.make_exc(inv)
            if s.action == "delay":
                time.sleep(s.delay_s)
            elif s.action == "corrupt":
                value = _poison(value)
        return value


_ARM_LOCK = threading.Lock()
_ACTIVE: Optional[FaultPlan] = None


def active_plan() -> Optional[FaultPlan]:
    return _ACTIVE


def fault_point(site: str, value=None):
    """The permanent product-code hook: returns ``value`` untouched when
    no plan is armed (one module-global check — the disarmed cost), else
    routes through the armed plan (which may raise, sleep, or return a
    poisoned copy of ``value``)."""
    plan = _ACTIVE
    if plan is None:
        return value
    return plan._hit(site, value)


def _record_injected(site: str, action: str) -> None:
    # lazy import: the disarmed hot path never touches telemetry, and
    # faults.py stays import-cycle-free for the modules that hook it
    from deeplearning4j_tpu import telemetry

    telemetry.record_fault_injected(site, action)
