"""Circuit breaker for the serving path.

A model forward that starts failing (bad weights hot-swapped in, a
wedged device, an OOM loop) must not take the whole serving process
down with it: callers pile onto the queue, every launch burns device
time to fail, and latency for the requests that *would* succeed
explodes. The breaker converts that failure mode into fast, bounded
shedding:

- **closed** — normal operation. Consecutive launch failures (or a
  failure rate over the recent-outcome window) trip it open.
- **open** — ``allow()`` is False: submits shed immediately with
  :class:`CircuitOpenError` (HTTP 503 upstream) instead of queueing
  behind a dead model. After ``recovery_timeout_s`` the breaker goes
  half-open.
- **half_open** — a bounded number of probe requests are admitted;
  ``success_threshold`` consecutive probe successes close the breaker,
  any probe failure re-opens it (and restarts the recovery clock).

State transitions publish ``dl4j_circuit_state{breaker=...}``
(0=closed, 1=half_open, 2=open) and
``dl4j_circuit_transitions_total{breaker=...,to=...}``. Live breakers
are tracked in a WeakSet so ``resilience.status()`` / the ``/health``
surface can report every breaker in the process.
"""

from __future__ import annotations

import collections
import threading
import time
import weakref
from typing import Optional

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"
_STATE_CODE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}

_BREAKERS = weakref.WeakSet()


class CircuitOpenError(RuntimeError):
    """Fail-fast rejection while the breaker is open — maps to HTTP 503
    (the client should back off; the server is shedding on purpose)."""


class CircuitBreaker:
    """Thread-safe three-state breaker.

    Args:
        failure_threshold: consecutive launch failures that trip open.
        failure_rate: optional rate trip — open when
            ``failures/window >= failure_rate`` over the last
            ``window_size`` outcomes (needs at least ``window_size``
            recorded outcomes; catches the steady-trickle failure mode
            consecutive counting misses).
        recovery_timeout_s: open -> half_open delay.
        half_open_probes: requests admitted while half-open before the
            first outcome lands.
        success_threshold: consecutive half-open successes that close.
        clock: injectable monotonic clock (tests).
    """

    def __init__(self, failure_threshold: int = 5,
                 recovery_timeout_s: float = 30.0,
                 half_open_probes: int = 1,
                 success_threshold: int = 1,
                 failure_rate: Optional[float] = None,
                 window_size: int = 20,
                 name: str = "serving",
                 clock=time.monotonic):
        self.failure_threshold = int(failure_threshold)
        self.recovery_timeout_s = float(recovery_timeout_s)
        self.half_open_probes = max(1, int(half_open_probes))
        self.success_threshold = max(1, int(success_threshold))
        self.failure_rate = failure_rate
        self.window_size = int(window_size)
        self.name = name
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive_failures = 0
        self._probe_tickets = 0
        self._probe_successes = 0
        self._opened_at = 0.0
        self._probe_issued_at = 0.0
        self._window = collections.deque(maxlen=self.window_size)
        self.tripped_total = 0
        _BREAKERS.add(self)
        self._publish(CLOSED, transition=False)

    # --- admission ----------------------------------------------------------
    def allow(self) -> bool:
        """Whether a new request may enter. In half-open this consumes a
        probe ticket, so at most ``half_open_probes`` requests are in
        flight before an outcome decides the state."""
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                if self._clock() - self._opened_at \
                        >= self.recovery_timeout_s:
                    self._to_half_open_locked()
                else:
                    return False
            # HALF_OPEN (possibly just entered)
            if self._probe_tickets > 0:
                self._probe_tickets -= 1
                self._probe_issued_at = self._clock()
                return True
            if self._clock() - self._probe_issued_at \
                    >= self.recovery_timeout_s:
                # the outstanding probe never reported an outcome (its
                # waiter expired or was dropped): re-issue instead of
                # wedging half-open shut forever
                self._probe_tickets = self.half_open_probes - 1
                self._probe_issued_at = self._clock()
                return True
            return False

    # --- outcomes -----------------------------------------------------------
    def on_success(self) -> None:
        with self._lock:
            self._window.append(True)
            self._consecutive_failures = 0
            if self._state == HALF_OPEN:
                self._probe_successes += 1
                if self._probe_successes >= self.success_threshold:
                    self._to_closed_locked()
                else:
                    self._probe_tickets += 1  # next probe may proceed

    def on_failure(self) -> None:
        with self._lock:
            self._window.append(False)
            self._consecutive_failures += 1
            if self._state == HALF_OPEN:
                self._to_open_locked()  # a failed probe re-opens immediately
                return
            if self._state != CLOSED:
                return
            if self._consecutive_failures >= self.failure_threshold:
                self._to_open_locked()
                return
            if (self.failure_rate is not None
                    and len(self._window) >= self.window_size
                    and (self._window.count(False) / len(self._window)
                         >= self.failure_rate)):
                self._to_open_locked()

    # --- state (locked callers only) ---------------------------------------
    def _to_open_locked(self):
        self._state = OPEN
        self._opened_at = self._clock()
        self.tripped_total += 1
        self._publish(OPEN)

    def _to_half_open_locked(self):
        self._state = HALF_OPEN
        self._probe_tickets = self.half_open_probes
        self._probe_successes = 0
        self._probe_issued_at = self._clock()
        self._publish(HALF_OPEN)

    def _to_closed_locked(self):
        self._state = CLOSED
        self._consecutive_failures = 0
        self._window.clear()
        self._publish(CLOSED)

    def _publish(self, to_state: str, transition: bool = True):
        from deeplearning4j_tpu import telemetry

        telemetry.record_circuit_state(self.name, _STATE_CODE[to_state],
                                       transition=transition)

    # --- introspection ------------------------------------------------------
    @property
    def state(self) -> str:
        with self._lock:
            # surface the pending open->half_open flip without requiring
            # a probe submit first (scrapes read the truth)
            if self._state == OPEN and (self._clock() - self._opened_at
                                        >= self.recovery_timeout_s):
                self._to_half_open_locked()
            return self._state

    def status(self) -> dict:
        st = self.state
        with self._lock:
            return {
                "name": self.name,
                "state": st,
                "consecutive_failures": self._consecutive_failures,
                "tripped_total": self.tripped_total,
                "window": {
                    "size": len(self._window),
                    "failures": self._window.count(False),
                },
            }


def live_breakers():
    return list(_BREAKERS)
