"""Fault-tolerant execution layer (docs/resilience.md).

Three cooperating pieces:

- :mod:`~deeplearning4j_tpu.resilience.faults` — deterministic,
  seedable fault injection behind permanent one-line ``fault_point``
  hooks in product code (checkpoint writes, device ingest, train steps,
  serving launches, stats flushes).
- :mod:`~deeplearning4j_tpu.resilience.retry` — the
  retry/timeout/backoff engine applied at every transient-failure edge.
- Recovery drivers: :class:`TrainingSession` (periodic snapshots +
  auto-resume to bit-identical results) and :class:`CircuitBreaker`
  (+ launch watchdog) on the serving engine.

Everything is host-side control flow — nothing here enters a compiled
step, so arming/disarming never recompiles and the disarmed overhead is
one module-global check per hook.
"""

from __future__ import annotations

from deeplearning4j_tpu.resilience import breaker as breaker  # noqa: F401
from deeplearning4j_tpu.resilience import faults as faults  # noqa: F401
from deeplearning4j_tpu.resilience import retry as retry  # noqa: F401
from deeplearning4j_tpu.resilience.breaker import (  # noqa: F401
    CircuitBreaker,
    CircuitOpenError,
)
from deeplearning4j_tpu.resilience.faults import (  # noqa: F401
    FaultPlan,
    InjectedFault,
    fault_point,
)
from deeplearning4j_tpu.resilience.retry import RetryPolicy  # noqa: F401
from deeplearning4j_tpu.resilience.session import (  # noqa: F401
    PreemptionError,
    TrainingSession,
)


def status() -> dict:
    """Process-wide resilience snapshot for ``/health`` and debugging:
    every live circuit breaker's state, the retry/resume/fault counters,
    and whether a fault plan is currently armed."""
    from deeplearning4j_tpu.telemetry import REGISTRY

    snap = REGISTRY.snapshot(run_collectors=False)
    counters = {k: v for k, v in snap.items()
                if k.startswith(("dl4j_retries_total",
                                 "dl4j_resumes_total",
                                 "dl4j_faults_injected_total"))}
    return {
        "circuit_breakers": {b.name: b.status()
                             for b in breaker.live_breakers()},
        "counters": counters,
        "fault_plan_armed": faults.active_plan() is not None,
    }
