"""Fault-tolerant execution layer (docs/resilience.md).

Three cooperating pieces:

- :mod:`~deeplearning4j_tpu.resilience.faults` — deterministic,
  seedable fault injection behind permanent one-line ``fault_point``
  hooks in product code (checkpoint writes, device ingest, train steps,
  serving launches, stats flushes).
- :mod:`~deeplearning4j_tpu.resilience.retry` — the
  retry/timeout/backoff engine applied at every transient-failure edge.
- Recovery drivers: :class:`TrainingSession` (periodic snapshots +
  auto-resume to bit-identical results) and :class:`CircuitBreaker`
  (+ launch watchdog) on the serving engine.

Everything is host-side control flow — nothing here enters a compiled
step, so arming/disarming never recompiles and the disarmed overhead is
one module-global check per hook.
"""

from __future__ import annotations

from deeplearning4j_tpu.resilience import breaker as breaker  # noqa: F401
from deeplearning4j_tpu.resilience import faults as faults  # noqa: F401
from deeplearning4j_tpu.resilience import retry as retry  # noqa: F401
from deeplearning4j_tpu.resilience.breaker import (  # noqa: F401
    CircuitBreaker,
    CircuitOpenError,
)
from deeplearning4j_tpu.resilience.faults import (  # noqa: F401
    FaultPlan,
    InjectedFault,
    fault_point,
)
from deeplearning4j_tpu.resilience import pod as pod  # noqa: F401
from deeplearning4j_tpu.resilience.pod import (  # noqa: F401
    HostDeathError,
    PodConfig,
    PodSnapshotIncompleteError,
)
from deeplearning4j_tpu.resilience.retry import RetryPolicy  # noqa: F401
from deeplearning4j_tpu.resilience.session import (  # noqa: F401
    PreemptionError,
    TrainingSession,
)


_STATE_RANK = {"closed": 0, "half_open": 1, "open": 2}


def _aggregate_breakers() -> dict:
    """Every live breaker's status, grouped per MODEL: a multi-tenant
    serving host names a model's breakers ``serving:<model>`` (primary)
    and ``serving:<model>#canary`` — distinct metric series, but ONE
    ``/health`` entry per model, keyed by the pre-``#`` prefix. The
    entry aggregates all of a model's live breakers — worst state wins
    (open > half_open > closed), counters sum — instead of the
    last-registered breaker silently shadowing the rest."""
    groups: dict = {}
    for b in breaker.live_breakers():
        groups.setdefault(b.name.split("#", 1)[0], []).append(b.status())
    out = {}
    for name, sts in sorted(groups.items()):
        agg = dict(max(sts, key=lambda s: _STATE_RANK.get(s["state"], 0)))
        agg["breakers"] = len(sts)
        if len(sts) > 1:
            agg["states"] = sorted(s["state"] for s in sts)
            agg["tripped_total"] = sum(s["tripped_total"] for s in sts)
            agg["consecutive_failures"] = max(
                s["consecutive_failures"] for s in sts)
            agg["window"] = {
                "size": sum(s["window"]["size"] for s in sts),
                "failures": sum(s["window"]["failures"] for s in sts),
            }
        out[name] = agg
    return out


def status() -> dict:
    """Process-wide resilience snapshot for ``/health`` and debugging:
    every live circuit breaker's state (aggregated per breaker name —
    see :func:`_aggregate_breakers`), the retry/resume/fault counters,
    the pod topology + snapshot/restore series (when a pod session has
    recorded any), and whether a fault plan is currently armed."""
    from deeplearning4j_tpu.telemetry import REGISTRY

    snap = REGISTRY.snapshot(run_collectors=False)
    counters = {k: v for k, v in snap.items()
                if k.startswith(("dl4j_retries_total",
                                 "dl4j_resumes_total",
                                 "dl4j_faults_injected_total"))}
    out = {
        "circuit_breakers": _aggregate_breakers(),
        "counters": counters,
        "fault_plan_armed": faults.active_plan() is not None,
    }
    from deeplearning4j_tpu.telemetry import slo

    slo_status = slo.status()
    if slo_status["tenants"]:
        out["slo"] = slo_status
    pod_series = {k: v for k, v in snap.items()
                  if k.startswith("dl4j_pod_")}
    if pod_series:
        out["pod"] = {
            "hosts": int(snap.get("dl4j_pod_hosts", 0)),
            "series": {
                k: (v if not isinstance(v, dict)
                    else {kk: v[kk] for kk in ("count", "mean", "p95")})
                for k, v in pod_series.items()},
        }
    return out
