"""Preemption-safe resumable training.

The reference stack survives worker loss because Spark re-dispatches
work and ``CheckpointListener``/EarlyStopping restart from disk. Here
the whole trainer is one process, so surviving a crash or TPU
preemption needs an explicit driver: :class:`TrainingSession` wraps the
fit loop with periodic durable snapshots (the atomic
``serializer.write_model`` zips, plus RNG key and iterator
epoch/position in a ``session.json`` manifest) and auto-resumes after a
resumable failure to **bit-identical-with-uninterrupted** results.

Why bit-identical is cheap here: a training step is a pure function of
(params, state, opt_state, batch, iteration, epoch, base RNG key) — the
per-step RNG is ``fold_in(base_key, iteration)`` inside the compiled
step. Snapshotting exactly those inputs and replaying the same batch
order therefore reproduces the uninterrupted trajectory exactly; there
is no hidden host-side RNG to drift.

Resume chain (newest first): digest-verified on-disk snapshots (a
corrupt/truncated zip falls back to the previous one — same contract as
``CheckpointListener.load_checkpoint``), then the in-memory last-good
snapshot (``optimize.checkpoint.snapshot_training_state``) for
in-process restarts when the disk copies are gone.

Usage::

    sess = TrainingSession(net, "ckpts/run1",
                           snapshot_every_n_iterations=50)
    sess.fit(iterator, epochs=3)          # auto-resumes on preemption

    # after a process crash: resume and FINISH the original 3-epoch
    # budget (epochs= is relative to the resumed position, so use the
    # absolute to_epoch= form when re-running the same script)
    sess = TrainingSession(None, "ckpts/run1")
    sess.resume()                          # -> restored model
    sess.fit(iterator, to_epoch=3)         # continues where it died
"""

from __future__ import annotations

import json
import logging
import os
from typing import Optional, Tuple, Type

from deeplearning4j_tpu.resilience.faults import InjectedFault
from deeplearning4j_tpu.resilience.retry import CHECKPOINT_RETRY, RetryPolicy

MANIFEST = "session.json"
_MANIFEST_VERSION = 1

logger = logging.getLogger(__name__)


class PreemptionError(RuntimeError):
    """Raise (or map your platform's preemption signal to) this to tell
    a :class:`TrainingSession` the interruption is resumable."""


def _sha256(path: str) -> str:
    from deeplearning4j_tpu.util.serializer import file_digest

    return file_digest(path)


class TrainingSession:
    """Crash/preemption-safe ``fit`` driver for a MultiLayerNetwork or
    ComputationGraph.

    Args:
        model: the network (``None`` to resume a dead process's session
            purely from ``directory``).
        directory: snapshot home (created if missing).
        snapshot_every_n_iterations: periodic durable snapshot cadence
            (0 disables; epoch boundaries always snapshot).
        keep_last: on-disk snapshots retained (older ones pruned — but
            at least two, so digest fallback always has a predecessor).
        retry: policy for snapshot writes (default
            :data:`~deeplearning4j_tpu.resilience.retry.CHECKPOINT_RETRY`).
        resumable: exception classes that trigger auto-resume inside
            :meth:`fit`; anything else propagates.
        max_restarts: auto-resumes per :meth:`fit` call before giving up
            (guards against a deterministic fault that re-fires every
            replay).
        pod: pod-grade distributed snapshots
            (:mod:`~deeplearning4j_tpu.resilience.pod`): an int ``N``
            (→ ``PodConfig(n_hosts=N)``) or a prebuilt
            :class:`~deeplearning4j_tpu.resilience.pod.PodConfig`.
            Snapshots become per-host shard directories (each host
            writes its slice of params/updater state under the ZeroSpec
            flat layout, coordinator manifest committed last), resume
            digest-verifies every shard and falls back newest-first
            past partial snapshots with a logged
            ``PodSnapshotIncompleteError`` reason, the fit loop carries
            the ``pod.heartbeat`` fault site (a seeded
            ``HostDeathError`` there = chaos host death, resumed at
            host scope), and restore re-cuts through ``comms.reshard``
            when the restoring pod shape differs from the saving one.
    """

    def __init__(self, model, directory: str,
                 snapshot_every_n_iterations: int = 50,
                 keep_last: int = 2,
                 retry: Optional[RetryPolicy] = None,
                 resumable: Optional[
                     Tuple[Type[BaseException], ...]] = None,
                 max_restarts: int = 3,
                 pod=None):
        from deeplearning4j_tpu.resilience import pod as pod_mod

        if resumable is None:
            resumable = (PreemptionError, InjectedFault, OSError,
                         pod_mod.HostDeathError)
        self.pod = (pod_mod.PodConfig(n_hosts=pod)
                    if isinstance(pod, int) else pod)
        self.model = model
        self.directory = str(directory)
        os.makedirs(self.directory, exist_ok=True)
        t = self._trainer()
        if t is not None:
            from deeplearning4j_tpu.parallel.wrapper import TrainingMode

            if (t.training_mode is not TrainingMode.SHARED_GRADIENTS
                    or t.threshold_algorithm is not None):
                # model-level snapshots capture params/state/opt only:
                # AVERAGING's per-replica divergence and the threshold
                # accumulator's residual/tau would silently reset on
                # resume, breaking the bit-identical guarantee
                raise ValueError(
                    "TrainingSession drives exact SHARED_GRADIENTS "
                    "wrappers only (AVERAGING replica state and "
                    "threshold-compression residuals are not captured "
                    "by model-level snapshots)")
        self.every_iters = int(snapshot_every_n_iterations)
        self.keep_last = max(2, int(keep_last))
        self.retry = retry or CHECKPOINT_RETRY
        self.resumable = tuple(resumable)
        self.max_restarts = int(max_restarts)
        self.restarts = 0
        self._batch_in_epoch = 0
        self._mem = None        # in-memory last-good (fallback of last resort)
        self._mem_entry = None
        self._manifest = self._read_manifest()

    # --- sharded-trainer adapter -------------------------------------------
    def _trainer(self):
        """The live ``ParallelWrapper`` when this session drives one
        (``TrainingSession(wrapper, dir)``), else None. A wrapper
        session snapshots the WRAPPED model (full host arrays, gathered
        through the ``_live_trainer`` hook — ZeRO opt shards and
        TP-sharded params serialize mesh-agnostically) and resume
        re-shards onto the wrapper's CURRENT mesh, which may be a
        different shape than the one that saved (docs/sharding.md,
        "Resharding restore")."""
        from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper

        return self.model if isinstance(self.model, ParallelWrapper) \
            else None

    @property
    def _net(self):
        """The underlying network (counters/serialization authority) —
        the model itself, or a driven wrapper's wrapped model."""
        t = self._trainer()
        return t.model if t is not None else self.model

    # --- manifest -----------------------------------------------------------
    def _manifest_path(self) -> str:
        return os.path.join(self.directory, MANIFEST)

    def _read_manifest(self) -> dict:
        try:
            with open(self._manifest_path()) as f:
                m = json.load(f)
            if isinstance(m, dict) and isinstance(m.get("snapshots"), list):
                return m
        except (OSError, ValueError):
            pass
        return {"format_version": _MANIFEST_VERSION, "snapshots": []}

    def _write_manifest(self) -> None:
        # same temp+replace discipline as write_model: the manifest is
        # the resume authority and must never be half-written
        tmp = f"{self._manifest_path()}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                json.dump(self._manifest, f, indent=1)
            os.replace(tmp, self._manifest_path())
        finally:
            if os.path.exists(tmp):
                os.remove(tmp)

    def snapshots(self) -> list:
        """Manifest rows for snapshots whose zip still exists."""
        return [s for s in self._manifest["snapshots"]
                if os.path.exists(os.path.join(self.directory, s["file"]))]

    # --- snapshot -----------------------------------------------------------
    def snapshot(self) -> dict:
        """Write one durable snapshot now (atomic zip + manifest row with
        content digest, RNG key, and iterator position). Returns the
        manifest entry."""
        import numpy as np

        from deeplearning4j_tpu.optimize import checkpoint as ckpt
        from deeplearning4j_tpu.util import serializer

        t = self._trainer()
        if t is not None:
            # gather-on-save: ZeRO opt shards / TP-sharded params pull
            # back to full host arrays before the atomic zip (no-op
            # before the wrapper stages anything)
            t.sync_model()
        m = self._net
        if self.pod is not None:
            # distributed snapshot: one directory, per-host shard files
            # + host manifests, coordinator manifest committed last
            # (resilience/pod.py has the protocol)
            import jax

            from deeplearning4j_tpu.resilience import pod as pod_mod

            dname = f"pod_iter{int(m.iteration):08d}"
            args = (pod_mod.write_pod_snapshot, m,
                    os.path.join(self.directory, dname), self.pod)
            kw = dict(batch_in_epoch=int(self._batch_in_epoch),
                      rng_key=getattr(m, "_base_key", None))
            if self.pod.emulated or jax.process_count() == 1:
                self.retry.call(*args, op="checkpoint.write", **kw)
            else:
                # REAL pod: the write contains global barriers, and a
                # PER-PROCESS retry would re-enter them on one host
                # while the others wait at the next tag — desyncing the
                # whole pod. A failed collective snapshot propagates
                # (job-scope resumable) instead of retrying locally.
                args[0](*args[1:], **kw)
            entry = {
                "file": dname,
                "pod": True,
                "n_hosts": self.pod.n_hosts,
                "iteration": int(m.iteration),
                "epoch": int(m.epoch),
                "batch_in_epoch": int(self._batch_in_epoch),
            }
        else:
            fname = f"session_iter{int(m.iteration):08d}.zip"
            path = os.path.join(self.directory, fname)
            self.retry.call(serializer.write_model, m, path,
                            op="checkpoint.write")
            entry = {
                "file": fname,
                "digest": _sha256(path),
                "iteration": int(m.iteration),
                "epoch": int(m.epoch),
                "batch_in_epoch": int(self._batch_in_epoch),
            }
        snaps = [s for s in self._manifest["snapshots"]
                 if s["file"] != entry["file"]] + [entry]
        self._manifest["snapshots"] = snaps[-max(self.keep_last, 2):]
        key = getattr(m, "_base_key", None)
        if key is not None:
            self._manifest["rng_key"] = [
                int(v) for v in np.asarray(key).ravel()]
        self._write_manifest()
        self._prune(snaps)
        self._mem = ckpt.snapshot_training_state(m)
        self._mem_entry = dict(entry)
        return entry

    def _prune(self, all_snaps: list) -> None:
        import shutil

        keep = {s["file"] for s in self._manifest["snapshots"]}
        for s in all_snaps:
            if s["file"] in keep:
                continue
            p = os.path.join(self.directory, s["file"])
            if os.path.exists(p):
                try:
                    # pod snapshots are directories of shard files
                    shutil.rmtree(p) if os.path.isdir(p) else os.remove(p)
                except OSError:
                    pass  # retention is best-effort; resume only needs keep

    # --- resume -------------------------------------------------------------
    def resume(self, scope: str = "job"):
        """Restore the newest loadable snapshot (digest-verified; corrupt
        or truncated zips fall back to the previous one, then to the
        in-memory last-good). Pod snapshots (``pod=``) verify every
        host shard; a partial one — missing shard, digest mismatch,
        uncommitted/stale coordinator manifest — is skipped with its
        :class:`~deeplearning4j_tpu.resilience.pod.
        PodSnapshotIncompleteError` reason logged, falling back
        newest-first. Counts ``dl4j_resumes_total{scope=...}``
        (``scope="host"`` when a pod host died, ``"job"`` otherwise)."""
        import jax.numpy as jnp
        import numpy as np

        from deeplearning4j_tpu import telemetry
        from deeplearning4j_tpu.optimize import checkpoint as ckpt
        from deeplearning4j_tpu.util import serializer

        self._manifest = self._read_manifest()
        listeners = list(getattr(self._net, "listeners", []) or [])
        snaps = self._manifest["snapshots"]
        restored, entry = None, None
        if any(s.get("pod") for s in snaps):
            restored, entry = self._resume_pod_walk(snaps)
        else:
            restored, idx, _ = serializer.restore_newest_verified(
                [(os.path.join(self.directory, s["file"]),
                  s.get("digest", "")) for s in snaps],
                serializer.restore_model)
            entry = snaps[idx] if restored is not None else None
        if restored is None and self._mem is not None \
                and self._net is not None:
            ckpt.restore_training_state(self._net, self._mem)
            restored, entry = self._net, self._mem_entry
        if restored is None:
            raise FileNotFoundError(
                f"no loadable snapshot in {self.directory}")
        if listeners and not getattr(restored, "listeners", None):
            restored.listeners = listeners
        rng = self._manifest.get("rng_key")
        if rng and hasattr(restored, "_base_key"):
            restored._base_key = jnp.asarray(
                np.asarray(rng, dtype=np.uint32))
        trainer = self._trainer()
        if trainer is not None:
            # restore-and-reshard: the snapshot restores to full arrays;
            # the wrapper re-stages (re-scatters ZeRO slices, re-places
            # TP shards) onto its CURRENT mesh on the next run — which
            # may be a different shape than the mesh that saved. The
            # restage routes device-resident trees through
            # comms.reshard's slice-intersection exchange (ZeroSpec.
            # scatter / ShardingPlan.place), so the restore-across-mesh
            # path no longer pays a numpy gather/scatter round-trip.
            # Step closures are dropped (the AOT cache makes the rebuild
            # a compile-cache hit on an unchanged mesh).
            trainer.model = restored
            trainer._params = trainer._state = trainer._opt = None
            trainer._residual = None
            trainer._step = None
            trainer._fused_step = None
        else:
            self.model = restored
        self._batch_in_epoch = int((entry or {}).get("batch_in_epoch", 0))
        telemetry.record_resume(scope=scope)
        return restored

    def _resume_pod_walk(self, snaps):
        """Newest-first walk over pod snapshot rows: a partial snapshot
        is SKIPPED with its specific reason in the log (never a bare
        ``KeyError``/``FileNotFoundError``) and the walk falls back to
        the previous generation. Zip rows interleave transparently (a
        session switched to pod mode mid-history keeps its old
        snapshots restorable)."""
        from deeplearning4j_tpu.resilience import pod as pod_mod
        from deeplearning4j_tpu.util import serializer

        for s in reversed(snaps):
            path = os.path.join(self.directory, s["file"])
            if s.get("pod"):
                try:
                    net, _ = pod_mod.restore_pod_snapshot(path, self.pod)
                    return net, s
                except pod_mod.PodSnapshotIncompleteError as e:
                    logger.warning(
                        "skipping pod snapshot %s: %s", s["file"],
                        e.reason)
                    continue
            restored, _, _ = serializer.restore_newest_verified(
                [(path, s.get("digest", ""))], serializer.restore_model)
            if restored is not None:
                return restored, s
        return None, None

    # --- training -----------------------------------------------------------
    def fit(self, data, labels=None, epochs: int = 1,
            batch_size: Optional[int] = None,
            to_epoch: Optional[int] = None,
            fused_steps: Optional[int] = None):
        """Train to ``model.epoch + epochs`` — i.e. ``epochs`` is
        RELATIVE to the resumed position — snapshotting periodically and
        auto-resuming on resumable failure. A cross-process restart that
        must finish the ORIGINAL run's budget (not add to it) passes the
        absolute ``to_epoch`` instead: ``fit(it, to_epoch=3)`` trains to
        epoch 3 no matter where the snapshot left off, which is what the
        bit-identical-with-uninterrupted guarantee needs after a crash
        mid-run. The data order must be deterministic across replays (it
        is, for the in-repo iterators) for that guarantee to hold.

        ``fused_steps=K``: train through the K-step fused scan (see
        ``MultiLayerNetwork.fit``). Snapshot/resume boundaries align to
        K automatically — one iterator item is one super-step, so
        ``batch_in_epoch`` counts super-steps and the periodic snapshot
        fires whenever the iteration counter CROSSES a cadence multiple
        (at a K-aligned boundary; exact-hit semantics for K=1 are
        unchanged) — and kill-and-resume stays bit-identical because
        the stacking is deterministic and a super-step is atomic: a
        kill mid-super-step replays it whole."""
        from deeplearning4j_tpu.nn.multilayer import _as_iterator, \
            _wrap_fused

        if self.model is None:
            self.resume()
        if self._trainer() is not None and fused_steps:
            raise ValueError(
                "configure fused_steps on the ParallelWrapper, not the "
                "session, when driving a wrapper")
        net = self._net
        if net.params is None:
            net.init()
        if labels is None and hasattr(data, "reset") \
                and hasattr(data, "__iter__"):
            iterator = data
        else:
            iterator = _as_iterator(data, labels, batch_size)
        iterator = _wrap_fused(iterator, fused_steps, net.conf)
        trainer = self._trainer()
        if trainer is not None and getattr(trainer, "fused_steps", 0) > 1 \
                and getattr(iterator, "stack_batches", 0) \
                != trainer.fused_steps:
            # the wrapper's K-step fused dispatch needs [K, B, ...]
            # super-batches; stack host-side exactly as wrapper.fit does
            # (the wrapper owns device placement). One stacked item is
            # one atomic super-step, so the K-aligned snapshot/replay
            # accounting below holds unchanged.
            from deeplearning4j_tpu.datasets.prefetch import (
                StackBatchIterator,
            )

            iterator = StackBatchIterator(iterator, trainer.fused_steps)
        target_epoch = int(to_epoch) if to_epoch is not None \
            else int(net.epoch) + int(epochs)
        from deeplearning4j_tpu.resilience import pod as pod_mod

        restarts_this_fit = 0
        while True:
            try:
                return self._run(iterator, target_epoch)
            except self.resumable as e:
                restarts_this_fit += 1
                if restarts_this_fit > self.max_restarts:
                    raise
                self.restarts += 1  # counts resumes performed, not failures
                # host scope: one pod host died and the whole job is
                # resuming from the last distributed snapshot; job
                # scope: whole-process preemption/fault
                self.resume(scope="host"
                            if isinstance(e, pod_mod.HostDeathError)
                            else "job")

    def _run(self, iterator, target_epoch: int):
        from deeplearning4j_tpu import telemetry

        m = self.model
        # this driver bypasses model.fit, so it re-arms the host-gap
        # clock itself (idle time since a previous fit must not record
        # as a dispatch gap)
        telemetry.host_gap_reset()
        if self.pod is not None:
            telemetry.record_pod_hosts(self.pod.n_hosts)
        trainer = self._trainer()
        if trainer is not None:
            # stage (or RE-stage after resume — possibly onto a
            # different mesh shape) and arm the gather-on-save hook
            # before the pre-first-step snapshot below
            import weakref

            trainer._setup()
            trainer._mp_target = None
            self._net._live_trainer = weakref.ref(trainer)
        if not self.snapshots():
            # a pre-first-step snapshot: a kill before the first periodic
            # snapshot still resumes (from iteration 0) instead of
            # silently training a fresh model
            self.snapshot()
        # same black-box contract as every other fit path: an exception
        # escaping a run attempt dumps one crash bundle (this driver
        # bypasses model.fit, so it carries the wrapper itself)
        try:
            self._run_epochs(iterator, target_epoch)
        finally:
            telemetry.host_gap_stop()
            if trainer is not None:
                # disarm the gather-on-save hook between runs (resume
                # re-arms); outside a run the model's host arrays are
                # authoritative
                self._net._live_trainer = None
        return m

    def _run_epochs(self, iterator, target_epoch: int):
        from deeplearning4j_tpu.nn import io as nn_io
        from deeplearning4j_tpu.telemetry import flightrec

        trainer = self._trainer()
        m = self._net
        with flightrec.flight_recorder(model=m):
            while m.epoch < target_epoch:
                for lst in m.listeners:
                    lst.on_epoch_start(m, m.epoch)
                iterator.reset()
                skip = self._batch_in_epoch
                if skip and hasattr(iterator, "skip_staging"):
                    # replay fast-forward must not pay device transfers
                    # for super-steps it immediately discards
                    iterator.skip_staging(skip)
                elif skip and hasattr(iterator, "skip_stacking"):
                    # host-only stacking iterators (wrapper fused mode):
                    # skip the K-batch copies the same way
                    iterator.skip_stacking(skip)
                pending = []
                for i, ds in enumerate(iterator):
                    if i < skip:
                        continue  # replay fast-forward to the crash pos
                    if self.pod is not None:
                        # the pod liveness edge, once per batch: a
                        # seeded HostDeathError here is the chaos
                        # host-death vector (deterministic kill step —
                        # same seed, same step, every replay)
                        from deeplearning4j_tpu.resilience import faults

                        faults.fault_point("pod.heartbeat")
                    it_before = m.iteration
                    if trainer is not None:
                        # wrapper steps dispatch synchronously (the
                        # collective exchange is inside the compiled
                        # step; there is no async queue to drain)
                        trainer._fit_batch(ds)
                    else:
                        pending.append(m._fit_batch_async(ds))
                        nn_io.drain(pending)
                    self._batch_in_epoch = i + 1
                    # crossing (not exact-hit) check: a fused super-step
                    # advances the counter by K per item, so the cadence
                    # fires at the first K-aligned boundary past each
                    # multiple; identical to the old % check for K=1
                    if self.every_iters \
                            and (m.iteration // self.every_iters
                                 > it_before // self.every_iters):
                        self.snapshot()
                nn_io.drain(pending, force=True)
                for lst in m.listeners:
                    lst.on_epoch_end(m, m.epoch)
                m.epoch += 1
                self._batch_in_epoch = 0
                self.snapshot()  # epoch boundary: position resets to 0
        if trainer is not None:
            trainer._write_back()
        return m
