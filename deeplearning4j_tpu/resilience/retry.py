"""Retry / timeout / backoff engine.

One policy object (max attempts, exponential backoff with deterministic
jitter, retryable-exception classes, deadline awareness) applied at
every I/O edge that can fail transiently: checkpoint save/load, device
ingest staging, remote stats flush, serving dispatch. The reference
stack gets this resilience from Spark's task re-dispatch; here the
edges are explicit, so the policy is too.

Deterministic jitter: the k-th attempt's backoff is a pure function of
``(seed, name, k)`` — a chaos run replays with identical sleep points,
which is what lets the fault-plan suite assert exact recovery
sequences. Deadline awareness: ``call(..., deadline=t)`` never sleeps
past ``t`` (monotonic), so a retried operation composes with the
serving batcher's per-request deadlines instead of silently exceeding
them.

Every retry (not first attempts) counts into
``dl4j_retries_total{op=...}``.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Optional, Tuple, Type

from deeplearning4j_tpu.resilience.faults import InjectedFault

#: Default transient set: filesystem/network hiccups plus injected
#: faults (so a chaos plan's transient errors exercise the same path a
#: real ENOSPC/EINTR would).
DEFAULT_RETRYABLE: Tuple[Type[BaseException], ...] = (
    OSError, ConnectionError, TimeoutError, InjectedFault)


class RetryPolicy:
    """``call(fn)`` with bounded, deterministic retries.

    Args:
        max_attempts: total tries (1 = no retry).
        base_delay_s / multiplier / max_delay_s: exponential backoff —
            attempt k sleeps ``min(base * multiplier**(k-1), max)``
            before jitter.
        jitter: +/- fraction of the backoff (0 disables; 0.5 means the
            sleep lands in [0.5d, 1.5d]), drawn deterministically from
            ``(seed, name, attempt)``.
        retryable: exception classes worth retrying; anything else
            propagates immediately.
        seed: jitter stream seed.
        name: default ``op`` label for the retry counter.
    """

    def __init__(self, max_attempts: int = 3, base_delay_s: float = 0.05,
                 max_delay_s: float = 2.0, multiplier: float = 2.0,
                 jitter: float = 0.5,
                 retryable: Tuple[Type[BaseException], ...] =
                 DEFAULT_RETRYABLE,
                 seed: int = 0, name: str = "default"):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        self.max_attempts = int(max_attempts)
        self.base_delay_s = float(base_delay_s)
        self.max_delay_s = float(max_delay_s)
        self.multiplier = float(multiplier)
        self.jitter = float(jitter)
        self.retryable = tuple(retryable)
        self.seed = int(seed)
        self.name = name

    def backoff_s(self, attempt: int) -> float:
        """Sleep before retry ``attempt+1`` (``attempt`` is the 1-based
        try that just failed). Pure function of (seed, name, attempt)."""
        d = min(self.base_delay_s * self.multiplier ** (attempt - 1),
                self.max_delay_s)
        if self.jitter:
            r = random.Random(f"{self.seed}:{self.name}:{attempt}").random()
            d *= 1.0 + self.jitter * (2.0 * r - 1.0)
        return max(d, 0.0)

    def call(self, fn: Callable, *args,
             deadline: Optional[float] = None,
             op: Optional[str] = None,
             on_retry: Optional[Callable] = None,
             sleep: Callable[[float], None] = time.sleep, **kw):
        """Run ``fn(*args, **kw)``; retry retryable failures up to
        ``max_attempts`` total tries. ``deadline`` is a
        ``time.monotonic()`` instant: when the next backoff would land
        past it, the last error propagates instead (the caller's
        deadline outranks the retry budget). ``on_retry(attempt, exc,
        delay)`` observes each scheduled retry."""
        op = op or self.name
        attempt = 0
        while True:
            attempt += 1
            try:
                return fn(*args, **kw)
            except self.retryable as e:
                if attempt >= self.max_attempts:
                    raise
                delay = self.backoff_s(attempt)
                if deadline is not None \
                        and time.monotonic() + delay > deadline:
                    raise
                from deeplearning4j_tpu import telemetry

                telemetry.record_retry(op)
                if on_retry is not None:
                    on_retry(attempt, e, delay)
                sleep(delay)

    def wrap(self, fn: Callable, op: Optional[str] = None) -> Callable:
        """Decorator form: ``policy.wrap(save)`` returns a callable with
        the same signature riding :meth:`call`."""
        def wrapped(*args, **kw):
            return self.call(fn, *args, op=op, **kw)

        wrapped.__name__ = getattr(fn, "__name__", "wrapped")
        return wrapped


#: Module defaults applied by the wired-in call sites. Short waits: the
#: edges these guard are local-disk and host->HBM operations where a
#: transient failure either clears in milliseconds or is permanent.
CHECKPOINT_RETRY = RetryPolicy(max_attempts=3, base_delay_s=0.05,
                               name="checkpoint.write")
INGEST_RETRY = RetryPolicy(max_attempts=3, base_delay_s=0.01,
                           name="ingest.device_put")
#: One retry only: a serving launch is the latency-critical edge, and a
#: persistent failure should reach the circuit breaker (which sheds)
#: rather than burn the batch's deadline on backoff.
SERVING_RETRY = RetryPolicy(max_attempts=2, base_delay_s=0.02,
                            name="serving.launch")
#: Registry model loads (``parallel.platform.ModelRegistry.load``):
#: SERVING_RETRY-shaped — one retry over the transient class only, so a
#: filesystem hiccup doesn't fail a deploy/swap, while a digest
#: mismatch (``ModelIntegrityError``, not in the retryable set)
#: propagates immediately and the incumbent version keeps serving.
MODEL_LOAD_RETRY = RetryPolicy(max_attempts=2, base_delay_s=0.02,
                               name="model.load")
