"""Pod-grade distributed snapshots + preemption (docs/resilience.md,
"Pod preemption").

A pod checkpoint is not one zip: every host writes ITS OWN shard of the
training state — params and updater state cut flat across hosts by the
same :class:`~deeplearning4j_tpu.sharding.zero.ZeroSpec` layout the
ZeRO exchange uses (component padded to ``n * m``, host ``h`` owns
``[h*m, (h+1)*m)``) — so snapshot bandwidth and disk I/O scale out with
the pod instead of funneling through one coordinator.

Commit protocol (crash anywhere leaves the PRIOR complete snapshot
authoritative)::

    1. each host:  shard_h{h}.npz       temp + os.replace, per-shard
                                        sha256 recorded in...
    2. each host:  host_h{h}.json       ...its host manifest
                                        (temp + os.replace)
    3. barrier     (real pods: multihost sync; emulated pods: the loop)
    4. host 0:     state.npz + manifest.json   the COORDINATOR manifest,
                   written only after every host manifest is durable
                   and digest-recorded — this os.replace IS the commit

A snapshot without a committed coordinator manifest, with a missing or
digest-mismatched shard, or whose coordinator manifest no longer
matches its host manifests (staleness) is never selected:
:func:`verify_pod_snapshot` raises :class:`PodSnapshotIncompleteError`
with the SPECIFIC reason, and ``TrainingSession`` falls back
newest-first logging it — never a bare ``KeyError`` /
``FileNotFoundError``.

Restore aggregates the shards (each digest-verified) and, when the
restoring pod shape differs from the saving one, re-cuts the flat
components through ``comms.reshard`` (:func:`~deeplearning4j_tpu.comms.
reshard.recut_flat` / ``commit_compiled`` — the arXiv:2112.01075
slice-intersection discipline, compiled) — bitwise the snapshot either
way, pinned by test_pod.

Single-process pod-emulation seam: ``PodConfig(n_hosts=N)`` with
``jax.process_count() == 1`` makes THIS process play every host — the
same shard files, manifests, commit ordering, and fault sites
(``snapshot.shard_write``, ``pod.heartbeat``) as a real pod, so the
chaos acceptance (kill any one host mid-fit, resume bit-identically)
runs in a single-process CI container; the N-process loopback harness
(tests/pod_harness.py) runs the real thing where the jaxlib supports
multi-process CPU collectives.
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

import numpy as np

from deeplearning4j_tpu.resilience.faults import fault_point

MANIFEST = "manifest.json"
_FORMAT_VERSION = 1


class PodSnapshotIncompleteError(RuntimeError):
    """A pod snapshot directory that must NOT be restored from, with the
    specific reason (uncommitted coordinator manifest, missing host
    manifest/shard, shard digest mismatch, stale coordinator manifest).
    ``TrainingSession`` resume logs the reason and falls back to the
    previous snapshot — the operator sees WHY a generation was skipped,
    never a bare ``KeyError``/``FileNotFoundError``."""

    def __init__(self, directory: str, reason: str):
        super().__init__(f"pod snapshot {directory!r} unusable: {reason}")
        self.directory = str(directory)
        self.reason = reason


class HostDeathError(RuntimeError):
    """One pod host died (preemption, hardware loss). Resumable by
    default in :class:`~deeplearning4j_tpu.resilience.session.
    TrainingSession` (it joins the session's resumable tuple beside
    ``PreemptionError``): the whole job resumes from the last complete
    distributed snapshot — host scope, counted as
    ``dl4j_resumes_total{scope="host"}``. The ``FaultPlan``-seeded
    host-death action raises this at the ``pod.heartbeat`` site::

        plan = FaultPlan(seed=7)
        plan.inject("pod.heartbeat", probability=0.05,
                    exc=lambda: HostDeathError(host=1), max_fires=1)
    """

    def __init__(self, host: Optional[int] = None, message: str = None):
        super().__init__(message or
                         f"pod host {host if host is not None else '?'} "
                         f"died (preemption)")
        self.host = host


class PodConfig:
    """The pod shape one process sees.

    - **Real pod** (``jax.process_count() > 1``): ``n_hosts`` defaults
      to the process count (and must equal it), ``host_id`` to
      ``jax.process_index()``; each process writes its own shard.
    - **Emulated pod** (single process, ``n_hosts > 1``): this process
      plays every host — same files, same ordering, same fault sites —
      the CPU-container seam for the chaos acceptance tests.
    """

    def __init__(self, n_hosts: Optional[int] = None,
                 host_id: Optional[int] = None):
        import jax

        procs = jax.process_count()
        self.n_hosts = int(n_hosts) if n_hosts else procs
        if self.n_hosts < 1:
            raise ValueError(f"n_hosts must be >= 1, got {self.n_hosts}")
        if procs > 1 and self.n_hosts != procs:
            raise ValueError(
                f"n_hosts={self.n_hosts} must equal the process count "
                f"{procs} on a real pod (each process is one host)")
        self.host_id = (int(host_id) if host_id is not None
                        else jax.process_index())
        if not 0 <= self.host_id < self.n_hosts:
            raise ValueError(
                f"host_id {self.host_id} outside [0, {self.n_hosts})")
        self.emulated = procs == 1 and self.n_hosts > 1
        self._procs = procs

    def hosts_here(self):
        """Host ids THIS process writes shards for: every host when
        emulated (or trivially pod-of-one), else exactly its own."""
        if self.emulated or self._procs == 1:
            return range(self.n_hosts)
        return (self.host_id,)

    @property
    def is_coordinator(self) -> bool:
        return self.emulated or self.host_id == 0

    def __repr__(self):
        mode = "emulated" if self.emulated else "real"
        return (f"PodConfig(n_hosts={self.n_hosts}, "
                f"host_id={self.host_id}, {mode})")


# --------------------------------------------------------------------------
# layout + shard mechanics
# --------------------------------------------------------------------------

def _components(model) -> dict:
    """The flat host vectors a bit-exact resume needs, in the canonical
    serializer order: ``coefficients`` (params) and ``updaterState``
    (updater moments + counters). Layer runtime state (BN running
    stats) is small and rides the coordinator commit as ``state.npz``."""
    from deeplearning4j_tpu.util import params as params_util

    comps = {"coefficients": np.asarray(model.params_flat())}
    if model.opt_state:
        comps["updaterState"] = np.asarray(
            params_util.flatten_state_like(model.opt_state))
    return comps


def _zero_spec(comps: dict, n_hosts: int):
    """The per-host cut of every component — literally a
    :class:`~deeplearning4j_tpu.sharding.zero.ZeroSpec` over the
    component tree, the SAME flatten/pad/scatter layout the ZeRO-1
    exchange shards optimizer state with."""
    from deeplearning4j_tpu.sharding.zero import ZeroSpec

    return ZeroSpec(comps, n_hosts)


def _host_slice(flat: np.ndarray, m: int, h: int) -> np.ndarray:
    """Host ``h``'s ``[h*m, (h+1)*m)`` slice, zero-padded at the tail
    (the ZeroSpec padding contract)."""
    out = np.zeros((m,), flat.dtype)
    lo, hi = h * m, min(flat.size, (h + 1) * m)
    if hi > lo:
        out[:hi - lo] = flat[lo:hi]
    return out


def _write_atomic(path: str, writer) -> None:
    """temp + ``os.replace`` with cleanup — the same atomic-publish
    discipline as ``serializer.write_model``."""
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        writer(tmp)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.remove(tmp)


def _dump_json(obj, tmp: str) -> None:
    with open(tmp, "w") as f:
        json.dump(obj, f, indent=1)


def shard_name(h: int) -> str:
    return f"shard_h{h:03d}.npz"


def host_manifest_name(h: int) -> str:
    return f"host_h{h:03d}.json"


def write_pod_snapshot(model, directory: str, pod: PodConfig,
                       batch_in_epoch: int = 0,
                       rng_key=None) -> dict:
    """Write one distributed snapshot of ``model`` into ``directory``
    following the commit protocol in the module docstring. Returns the
    coordinator manifest (the session's manifest row is derived from
    it). On a real pod every process must call this collectively."""
    from deeplearning4j_tpu import telemetry
    from deeplearning4j_tpu.util.serializer import file_digest

    t_start = time.perf_counter()
    os.makedirs(directory, exist_ok=True)
    comps = _components(model)
    spec = _zero_spec(comps, pod.n_hosts)
    names = sorted(comps)                  # jax dict-flatten order
    iteration = int(model.iteration)
    epoch = int(model.epoch)

    host_rows = {}
    for h in pod.hosts_here():
        t0 = time.perf_counter()
        shards = []
        fname = shard_name(h)
        path = os.path.join(directory, fname)

        def write_shard(tmp, h=h):
            payload = {name: _host_slice(comps[name], m, h)
                       for name, m in zip(names, spec.slice_sizes)}
            with open(tmp, "wb") as f:
                # mid-write injection site: a raise here IS a partial
                # shard — the temp holds some bytes, the publish below
                # never happens, no host manifest references it, and
                # the coordinator manifest is never committed
                fault_point("snapshot.shard_write")
                np.savez(f, **payload)

        _write_atomic(path, write_shard)
        nbytes = os.path.getsize(path)
        shards.append({"file": fname, "sha256": file_digest(path),
                       "bytes": nbytes})
        hman = {
            "format_version": _FORMAT_VERSION,
            "host": h,
            "n_hosts": pod.n_hosts,
            "iteration": iteration,
            "epoch": epoch,
            "shards": shards,
        }
        _write_atomic(
            os.path.join(directory, host_manifest_name(h)),
            lambda tmp, hman=hman: _dump_json(hman, tmp))
        telemetry.record_pod_shard(h, nbytes,
                                   time.perf_counter() - t0)

    _pod_barrier(pod, f"pod_snapshot:{os.path.basename(directory)}:w")
    manifest = None
    if pod.is_coordinator:
        hosts = []
        for h in range(pod.n_hosts):
            hpath = os.path.join(directory, host_manifest_name(h))
            if not os.path.exists(hpath):
                raise PodSnapshotIncompleteError(
                    directory, f"host manifest {host_manifest_name(h)} "
                               f"missing at commit time")
            hosts.append({"file": host_manifest_name(h),
                          "sha256": file_digest(hpath)})
        state_digest = ""
        if model.state:
            spath = os.path.join(directory, "state.npz")

            def write_state(tmp):
                flat = {f"{k}/{name}": np.asarray(v)
                        for k, d in model.state.items()
                        for name, v in d.items()}
                with open(tmp, "wb") as f:
                    np.savez(f, **flat)

            _write_atomic(spath, write_state)
            state_digest = file_digest(spath)
        manifest = {
            "format_version": _FORMAT_VERSION,
            "n_hosts": pod.n_hosts,
            "iteration": iteration,
            "epoch": epoch,
            "batch_in_epoch": int(batch_in_epoch),
            "model_class": type(model).__name__,
            "configuration": model.conf.to_json(),
            "components": {
                name: {"size": int(size), "dtype": str(dt)}
                for name, size, dt in zip(names, spec.sizes,
                                          spec.dtypes)},
            "state_digest": state_digest,
            "hosts": hosts,
        }
        if rng_key is not None:
            manifest["rng_key"] = [int(v) for v in
                                   np.asarray(rng_key).ravel()]
        # THE commit: everything above is invisible to restore until
        # this replace lands
        _write_atomic(
            os.path.join(directory, MANIFEST),
            lambda tmp: _dump_json(manifest, tmp))
    _pod_barrier(pod, f"pod_snapshot:{os.path.basename(directory)}:c")
    telemetry.record_pod_snapshot_seconds(
        time.perf_counter() - t_start)
    telemetry.record_pod_hosts(pod.n_hosts)
    return manifest


def _pod_barrier(pod: PodConfig, tag: str) -> None:
    """Real pods synchronize between the host-manifest and coordinator-
    commit phases (no host may observe a committed manifest whose own
    shard is still in flight); emulated pods are sequential — the loop
    IS the barrier."""
    if pod.emulated or pod._procs == 1:
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(tag)


# --------------------------------------------------------------------------
# verification + restore
# --------------------------------------------------------------------------

def verify_pod_snapshot(directory: str) -> dict:
    """Full integrity walk of one pod snapshot directory — coordinator
    manifest committed, every host manifest present and matching the
    digest the coordinator recorded (staleness check), every shard file
    present with its recorded sha256, host/coordinator counters
    agreeing. Returns the coordinator manifest; raises
    :class:`PodSnapshotIncompleteError` naming the first violation."""
    from deeplearning4j_tpu.util.serializer import file_digest

    if not os.path.isdir(directory):
        raise PodSnapshotIncompleteError(directory,
                                         "snapshot directory missing")
    mpath = os.path.join(directory, MANIFEST)
    if not os.path.exists(mpath):
        raise PodSnapshotIncompleteError(
            directory, "uncommitted coordinator manifest (crash before "
                       "the commit point)")
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as e:
        raise PodSnapshotIncompleteError(
            directory, f"unreadable coordinator manifest ({e})") from e
    if not isinstance(manifest.get("hosts"), list) \
            or "components" not in manifest:
        raise PodSnapshotIncompleteError(
            directory, "malformed coordinator manifest")
    for hrow in manifest["hosts"]:
        hname = hrow["file"]
        hpath = os.path.join(directory, hname)
        if not os.path.exists(hpath):
            raise PodSnapshotIncompleteError(
                directory, f"missing host manifest {hname}")
        if hrow.get("sha256") and file_digest(hpath) != hrow["sha256"]:
            raise PodSnapshotIncompleteError(
                directory, f"stale coordinator manifest: host manifest "
                           f"{hname} does not match the digest recorded "
                           f"at commit")
        try:
            with open(hpath) as f:
                hman = json.load(f)
        except (OSError, ValueError) as e:
            raise PodSnapshotIncompleteError(
                directory, f"unreadable host manifest {hname} "
                           f"({e})") from e
        if int(hman.get("iteration", -1)) != int(manifest["iteration"]):
            raise PodSnapshotIncompleteError(
                directory, f"stale coordinator manifest: host manifest "
                           f"{hname} is from iteration "
                           f"{hman.get('iteration')}, coordinator says "
                           f"{manifest['iteration']}")
        for srow in hman.get("shards", []):
            spath = os.path.join(directory, srow["file"])
            if not os.path.exists(spath):
                raise PodSnapshotIncompleteError(
                    directory, f"missing shard file {srow['file']} "
                               f"(host {hman.get('host')})")
            if file_digest(spath) != srow["sha256"]:
                raise PodSnapshotIncompleteError(
                    directory, f"shard digest mismatch in "
                               f"{srow['file']} (host "
                               f"{hman.get('host')})")
    sd = manifest.get("state_digest", "")
    if sd:
        spath = os.path.join(directory, "state.npz")
        if not os.path.exists(spath):
            raise PodSnapshotIncompleteError(directory,
                                             "missing state.npz")
        if file_digest(spath) != sd:
            raise PodSnapshotIncompleteError(directory,
                                             "state.npz digest mismatch")
    return manifest


def _aggregate_flat(slices, size: int, n_now: int) -> np.ndarray:
    """Host slices (saved layout, ``n_saved = len(slices)``) -> the full
    logical vector. When the restoring pod shape differs and devices
    allow, the re-cut routes through ``comms.reshard`` — each saved
    slice staged on its own device, the compiled exchange
    (:func:`~deeplearning4j_tpu.comms.reshard.recut_flat` /
    ``commit_compiled``) re-laying it out for ``n_now`` hosts — which is
    the restore-across-pod-shapes path of the ISSUE, bitwise the numpy
    concatenation (pinned by test_pod). Same shape (or too few devices
    to emulate the exchange) takes the direct concatenation."""
    import jax

    n_saved = len(slices)
    host_route = np.concatenate(slices)[:size] if n_saved > 1 \
        else slices[0][:size]
    if n_now == n_saved or n_now < 1:
        return host_route
    devs = jax.devices()
    if len(devs) < max(n_saved, n_now):
        return host_route
    try:
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from deeplearning4j_tpu.comms.reshard import recut_flat

        # stage the shards under the SAVED layout (slice h on device h)
        # and re-cut to the restoring pod's padded length through the
        # compiled comms.reshard route. The output replicates over the
        # same device set — jax requires input/output device sets to
        # match, and the restore reads the result to host anyway (a
        # live re-scatter onto the restoring pod's ZeRO layout then
        # happens in wrapper._setup over ITS mesh).
        m_src = slices[0].shape[0]
        mesh = Mesh(np.array(devs[:n_saved]), ("host",))
        src_sh = NamedSharding(mesh, P("host"))
        src = jax.make_array_from_single_device_arrays(
            (n_saved * m_src,), src_sh,
            [jax.device_put(s, d) for s, d in zip(slices, devs)])
        m_dst = -(-size // n_now)
        out = recut_flat(src, size, m_dst * n_now,
                         NamedSharding(mesh, P()))
        return np.asarray(out.addressable_shards[0].data)[:size]
    except Exception:
        # the device route is an optimization with a pinned-identical
        # result; any environment quirk falls back to the host route
        return host_route


def restore_pod_snapshot(directory: str,
                         pod: Optional[PodConfig] = None):
    """Digest-verified restore of one pod snapshot: aggregate every
    host's shards back into the full flat components (re-cutting
    through ``comms.reshard`` when ``pod``'s shape differs from the
    saving pod's — see :func:`_aggregate_flat`), rebuild the network
    from the recorded configuration, and return ``(net, manifest)``.
    Raises :class:`PodSnapshotIncompleteError` (never a bare
    ``KeyError``/``FileNotFoundError``) when the snapshot is partial."""
    import time as _time

    import jax.numpy as jnp

    from deeplearning4j_tpu import serde, telemetry
    from deeplearning4j_tpu.util import params as params_util

    t0 = _time.perf_counter()
    manifest = verify_pod_snapshot(directory)
    n_saved = int(manifest["n_hosts"])
    n_now = pod.n_hosts if pod is not None else n_saved
    comps = {}
    per_host = [np.load(os.path.join(directory, shard_name(h)))
                for h in range(n_saved)]
    try:
        for name, meta in manifest["components"].items():
            slices = [np.asarray(ph[name]) for ph in per_host]
            comps[name] = _aggregate_flat(
                slices, int(meta["size"]), n_now).astype(meta["dtype"])
    finally:
        for ph in per_host:
            ph.close()

    conf = serde.from_json(manifest["configuration"])
    if manifest.get("model_class") == "ComputationGraph" \
            or type(conf).__name__ == "ComputationGraphConfiguration":
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        net = ComputationGraph(conf)
    else:
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        net = MultiLayerNetwork(conf)
    net.init()
    net.set_params_flat(comps["coefficients"])
    if "updaterState" in comps and net.opt_state:
        net.opt_state = params_util.unflatten_state_like(
            comps["updaterState"], net.opt_state)
    if manifest.get("state_digest"):
        data = np.load(os.path.join(directory, "state.npz"))
        for key in data.files:
            layer, name = key.split("/", 1)
            net.state[layer][name] = jnp.asarray(data[key])
    net.iteration = int(manifest["iteration"])
    net.epoch = int(manifest["epoch"])
    telemetry.record_pod_restore_seconds(_time.perf_counter() - t0)
    return net, manifest
