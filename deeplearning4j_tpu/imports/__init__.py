"""Model import from foreign graph formats.

Reference: ``org.nd4j.imports`` — ``TFGraphMapper`` (frozen TensorFlow
GraphDef -> SameDiff) and the partial ``OnnxGraphMapper``.
"""

from deeplearning4j_tpu.imports.onnx import OnnxGraphMapper  # noqa: F401
from deeplearning4j_tpu.imports.tf import TFGraphMapper  # noqa: F401
