"""ONNX model import (partial, like the reference).

Reference: ``org.nd4j.imports.graphmapper.onnx.OnnxGraphMapper`` — the
reference's ONNX mapper is explicitly partial/skeleton compared to its TF
path (SURVEY.md §2.2); this importer covers the common inference op set and
raises ``UnsupportedOnnxOpException`` for the rest.

ONNX graphs are NCHW; they import in their native layout (the samediff
conv/pool ops take ``fmt="NCHW"`` and XLA re-lays-out during compilation),
so weights (OIHW) land untransposed. Protobuf schema is a vendored
wire-compatible subset (``protos/onnx_model.proto``) — no onnx package
needed.

Supported: Constant/initializers, Gemm, MatMul, Conv (incl. groups),
Relu/Sigmoid/Tanh/Elu/Softplus/Exp/Log/Sqrt/Neg/Abs/LeakyRelu, Softmax,
Add/Sub/Mul/Div/Pow, MaxPool/AveragePool/GlobalAveragePool,
BatchNormalization (inference), Reshape, Flatten, Concat, Transpose,
Identity, Squeeze/Unsqueeze, ReduceMean/ReduceSum/ReduceMax/ReduceMin,
Clip, Dropout (inference pass-through), Pad (constant).
"""

from __future__ import annotations

import numpy as np

from deeplearning4j_tpu.imports.protos import onnx_model_pb2 as ox
from deeplearning4j_tpu.samediff import ops as _ops  # noqa: F401
from deeplearning4j_tpu.samediff.core import SameDiff, SDVariable

_DTYPES = {1: np.float32, 2: np.uint8, 3: np.int8, 4: np.uint16,
           5: np.int16, 6: np.int32, 7: np.int64, 9: np.bool_,
           10: np.float16, 11: np.float64}


class UnsupportedOnnxOpException(ValueError):
    pass


def _tensor_to_np(t: "ox.TensorProto") -> np.ndarray:
    shape = tuple(t.dims)
    if t.data_type == 16:  # BFLOAT16: raw bytes or bit patterns
        import ml_dtypes

        if t.raw_data:
            arr = np.frombuffer(t.raw_data, ml_dtypes.bfloat16)
        elif len(t.int32_data):
            arr = np.asarray(list(t.int32_data), np.uint16).view(
                ml_dtypes.bfloat16)
        else:
            raise UnsupportedOnnxOpException(
                f"tensor {t.name!r} (bfloat16) has no inline data")
        return np.asarray(arr, np.float32).reshape(shape).copy()
    dtype = _DTYPES.get(t.data_type)
    if dtype is None:
        raise UnsupportedOnnxOpException(
            f"unsupported ONNX tensor dtype {t.data_type}")
    if t.raw_data:
        return np.frombuffer(t.raw_data, dtype).reshape(shape).copy()
    if t.data_type == 10 and len(t.int32_data):
        # fp16 typed storage is BIT PATTERNS in int32_data
        bits = np.asarray(list(t.int32_data), np.uint16)
        return np.asarray(bits.view(np.float16)).reshape(shape)
    for field, ftype in (("float_data", np.float32),
                         ("int32_data", np.int32),
                         ("int64_data", np.int64),
                         ("double_data", np.float64),
                         ("uint64_data", np.uint64)):
        vals = getattr(t, field)
        if len(vals):
            return np.asarray(list(vals), ftype).astype(dtype).reshape(shape)
    if int(np.prod(shape, dtype=np.int64)) > 0:
        raise UnsupportedOnnxOpException(
            f"tensor {t.name!r} has no inline data — models with EXTERNAL "
            f"data storage are not importable (re-export with "
            f"save_as_external_data=False)")
    return np.zeros(shape, dtype)


def _attrs(node) -> dict:
    out = {}
    for a in node.attribute:
        if a.type == 1:      # FLOAT
            out[a.name] = a.f
        elif a.type == 2:    # INT
            out[a.name] = int(a.i)
        elif a.type == 3:    # STRING
            out[a.name] = a.s.decode()
        elif a.type == 4:    # TENSOR
            out[a.name] = _tensor_to_np(a.t)
        elif a.type == 6:    # FLOATS
            out[a.name] = tuple(a.floats)
        elif a.type == 7:    # INTS
            out[a.name] = tuple(int(v) for v in a.ints)
        else:
            out[a.name] = None
    return out


_UNARY = {"Relu": "nn.relu", "Sigmoid": "nn.sigmoid", "Tanh": "nn.tanh",
          "Elu": "nn.elu", "Softplus": "nn.softplus", "Exp": "math.exp",
          "Log": "math.log", "Sqrt": "math.sqrt", "Neg": "math.neg",
          "Abs": "math.abs", "Erf": "math.erf", "Floor": "math.floor",
          "Ceil": "math.ceil"}
_BINARY = {"Add": "math.add", "Sub": "math.sub", "Mul": "math.mul",
           "Div": "math.div", "Pow": "math.pow"}
_REDUCE = {"ReduceMean": "reduce.mean", "ReduceSum": "reduce.sum",
           "ReduceMax": "reduce.amax", "ReduceMin": "reduce.amin"}


class OnnxGraphMapper:
    """Static import API (reference class of the same name)."""

    @staticmethod
    def import_graph(path_or_bytes) -> SameDiff:
        if isinstance(path_or_bytes, (bytes, bytearray)):
            data = bytes(path_or_bytes)
        else:
            with open(path_or_bytes, "rb") as f:
                data = f.read()
        model = ox.ModelProto()
        model.ParseFromString(data)
        opset = max((o.version for o in model.opset_import
                     if o.domain in ("", "ai.onnx")), default=13)
        return _Mapper(model.graph, opset).run()


class _Mapper:
    def __init__(self, graph: "ox.GraphProto", opset: int = 13):
        self.graph = graph
        self.opset = int(opset)
        self.sd = SameDiff.create()
        self.names: dict[str, str] = {}
        self.const_np: dict[str, np.ndarray] = {}

    def _var(self, name: str) -> SDVariable:
        return SDVariable(self.sd, self.names[name])

    def _static(self, name: str, node) -> np.ndarray:
        if name not in self.const_np:
            raise UnsupportedOnnxOpException(
                f"node {node.name or node.op_type!r} needs a static "
                f"initializer input {name!r}")
        return self.const_np[name]

    def _bind(self, out_name: str, var: SDVariable):
        if out_name not in self.sd.variables:
            self.sd.rename_variable(var.name, out_name)
            self.names[out_name] = out_name
        else:
            self.names[out_name] = var.name

    def run(self) -> SameDiff:
        init_names = set()
        for t in self.graph.initializer:
            arr = _tensor_to_np(t)
            self.const_np[t.name] = arr
            v = self.sd.constant(arr, name=t.name)
            self.names[t.name] = v.name
            init_names.add(t.name)
        for vi in self.graph.input:
            if vi.name in init_names:
                continue
            shape = None
            tt = vi.type.tensor_type
            if tt.shape.dim:
                shape = tuple(
                    d.dim_value if d.WhichOneof("value") == "dim_value"
                    and d.dim_value > 0 else None for d in tt.shape.dim)
            v = self.sd.placeholder(vi.name, shape=shape)
            self.names[vi.name] = v.name
        for node in self.graph.node:
            self._map_node(node)
        # exporters often rename the final output via Identity; make every
        # declared graph output addressable in the returned SameDiff
        for vi in self.graph.output:
            if vi.name not in self.sd.variables and vi.name in self.names:
                self._bind(vi.name, self.sd._op(
                    "identity", [self._var(vi.name)])[0])
        return self.sd

    def _map_node(self, node):
        sd, op = self.sd, node.op_type
        # ONNX encodes omitted optional inputs as empty strings
        ins = [i for i in node.input if i]
        outs = list(node.output)
        at = _attrs(node)

        if op == "Constant":
            arr = at.get("value")
            if arr is None:
                raise UnsupportedOnnxOpException(
                    f"Constant node {node.name!r} without tensor value")
            self.const_np[outs[0]] = np.asarray(arr)
            v = sd.constant(arr, name=outs[0])
            self.names[outs[0]] = v.name
        elif op == "Identity" or op == "Dropout":
            self.names[outs[0]] = self.names[ins[0]]
            if ins[0] in self.const_np:  # keep static operands resolvable
                self.const_np[outs[0]] = self.const_np[ins[0]]
        elif op in _UNARY:
            self._bind(outs[0], sd._op(_UNARY[op], [self._var(ins[0])])[0])
        elif op in _BINARY:
            self._bind(outs[0], sd._op(
                _BINARY[op], [self._var(ins[0]), self._var(ins[1])])[0])
        elif op == "LeakyRelu":
            self._bind(outs[0], sd._op(
                "nn.leakyRelu", [self._var(ins[0])],
                alpha=at.get("alpha", 0.01))[0])
        elif op == "Clip":
            raw = list(node.input)
            lo = (float(self._static(raw[1], node))
                  if len(raw) > 1 and raw[1] else -np.inf)
            hi = (float(self._static(raw[2], node))
                  if len(raw) > 2 and raw[2] else np.inf)
            lo = at.get("min", lo) if "min" in at else lo
            hi = at.get("max", hi) if "max" in at else hi
            self._bind(outs[0], sd._op(
                "math.clip_by_value", [self._var(ins[0])], lo=lo, hi=hi)[0])
        elif op == "Softmax":
            if self.opset < 13:
                # opset<13: default axis 1, flatten-to-2D semantics
                self._bind(outs[0], sd._op(
                    "softmax_flattened", [self._var(ins[0])],
                    axis=at.get("axis", 1))[0])
            else:
                self._bind(outs[0], sd._op(
                    "nn.softmax", [self._var(ins[0])],
                    axis=at.get("axis", -1))[0])
        elif op == "MatMul":
            self._bind(outs[0], sd._op(
                "math.matmul", [self._var(ins[0]), self._var(ins[1])],
                transpose_a=False, transpose_b=False)[0])
        elif op == "Gemm":
            a, b = self._var(ins[0]), self._var(ins[1])
            y = sd._op("math.matmul", [a, b],
                       transpose_a=bool(at.get("transA", 0)),
                       transpose_b=bool(at.get("transB", 0)))[0]
            alpha, beta = at.get("alpha", 1.0), at.get("beta", 1.0)
            if alpha != 1.0:
                y = sd._op("math.mul", [y, sd.constant(
                    np.float32(alpha))])[0]
            if len(ins) > 2:
                c = self._var(ins[2])
                if beta != 1.0:
                    c = sd._op("math.mul", [c, sd.constant(
                        np.float32(beta))])[0]
                y = sd._op("math.add", [y, c])[0]
            self._bind(outs[0], y)
        elif op == "Conv":
            strides = at.get("strides", (1, 1))
            dil = at.get("dilations", (1, 1))
            groups = at.get("group", 1)
            pads = at.get("pads")
            if at.get("auto_pad") == "SAME_LOWER":
                raise UnsupportedOnnxOpException(
                    f"{node.name or op}: auto_pad=SAME_LOWER pads at the "
                    f"START; XLA SAME is SAME_UPPER — re-export with "
                    f"explicit pads")
            if at.get("auto_pad") == "SAME_UPPER":
                padding = "SAME"
            elif pads and any(pads):
                padding = [(pads[0], pads[2]), (pads[1], pads[3])]
            else:
                padding = "VALID"
            x, w = self._var(ins[0]), self._var(ins[1])
            b = (self._var(ins[2]) if len(ins) > 2
                 else sd.constant(np.zeros(1, np.float32)))
            self._bind(outs[0], sd._op(
                "cnn.conv2d", [x, w, b], strides=tuple(strides),
                padding=padding, dilation=tuple(dil), fmt="NCHW",
                groups=int(groups))[0])
        elif op in ("MaxPool", "AveragePool"):
            k = at["kernel_shape"]
            s = at.get("strides", k)
            pads = at.get("pads")
            if at.get("auto_pad") == "SAME_LOWER":
                raise UnsupportedOnnxOpException(
                    f"{node.name or op}: auto_pad=SAME_LOWER unsupported "
                    f"(XLA SAME is SAME_UPPER)")
            if at.get("ceil_mode") or (op == "AveragePool"
                                       and at.get("count_include_pad")):
                raise UnsupportedOnnxOpException(
                    f"{node.name or op}: ceil_mode/count_include_pad "
                    f"unsupported")
            if at.get("auto_pad") == "SAME_UPPER":
                padding = "SAME"
            elif pads and any(pads):
                padding = [(0, 0), (0, 0), (pads[0], pads[2]),
                           (pads[1], pads[3])]
            else:
                padding = "VALID"
            impl = ("cnn.maxPooling2d" if op == "MaxPool"
                    else "cnn.avgPooling2d")
            self._bind(outs[0], sd._op(
                impl, [self._var(ins[0])], k=tuple(k), s=tuple(s),
                padding=padding, fmt="NCHW")[0])
        elif op == "GlobalAveragePool":
            self._bind(outs[0], sd._op(
                "reduce.mean", [self._var(ins[0])], axis=(2, 3),
                keepdims=True)[0])
        elif op == "BatchNormalization":
            eps = at.get("epsilon", 1e-5)
            x = self._var(ins[0])
            gamma, beta, mean, var_ = (self._var(i) for i in ins[1:5])
            self._bind(outs[0], sd._op(
                "nn.batchNorm", [x, mean, var_, gamma, beta], axis=1,
                eps=float(eps))[0])
        elif op == "Reshape":
            shape = tuple(int(v) for v in self._static(ins[1], node))
            self._bind(outs[0], sd._op(
                "reshape_onnx", [self._var(ins[0])], shape=shape)[0])
        elif op == "Flatten":
            axis = at.get("axis", 1)
            if axis != 1:
                raise UnsupportedOnnxOpException(
                    f"Flatten axis={axis} unsupported")
            self._bind(outs[0],
                       sd._op("flatten2d", [self._var(ins[0])])[0])
        elif op == "Concat":
            self._bind(outs[0], sd._op(
                "concat", [self._var(i) for i in ins],
                axis=at.get("axis", 0))[0])
        elif op == "Transpose":
            perm = at.get("perm")
            if perm:
                v = sd._op("permute", [self._var(ins[0])],
                           dims=tuple(perm))[0]
            else:
                v = sd._op("transpose", [self._var(ins[0])])[0]
            self._bind(outs[0], v)
        elif op == "Squeeze":
            axes = (tuple(at["axes"]) if "axes" in at and at["axes"]
                    else (tuple(int(v) for v in self._static(ins[1], node))
                          if len(ins) > 1 else None))
            self._bind(outs[0], sd._op(
                "squeeze", [self._var(ins[0])], axis=axes)[0])
        elif op == "Unsqueeze":
            axes = (tuple(at["axes"]) if "axes" in at and at["axes"]
                    else tuple(int(v) for v in self._static(ins[1], node)))
            self._bind(outs[0], sd._op(
                "unsqueeze_onnx", [self._var(ins[0])], axes=axes)[0])
        elif op in _REDUCE:
            axes = at.get("axes")
            if axes is None and len(ins) > 1:
                axes = tuple(int(v) for v in self._static(ins[1], node))
            keep = bool(at.get("keepdims", 1))
            self._bind(outs[0], sd._op(
                _REDUCE[op], [self._var(ins[0])],
                axis=tuple(axes) if axes else None, keepdims=keep)[0])
        elif op == "Pad":
            mode = at.get("mode", "constant")
            if mode != "constant":
                raise UnsupportedOnnxOpException(f"Pad mode {mode!r}")
            pads = at.get("pads")
            if pads is None:
                pads = tuple(int(v) for v in self._static(ins[1], node))
            value = float(at.get("value", 0.0) or 0.0)
            raw = list(node.input)
            if len(raw) > 2 and raw[2]:  # opset 11+ constant_value input
                value = float(self._static(raw[2], node))
            n = len(pads) // 2
            paddings = [(int(pads[i]), int(pads[i + n])) for i in range(n)]
            self._bind(outs[0], sd._op(
                "nn.pad", [self._var(ins[0])], paddings=paddings,
                mode="constant", value=value)[0])
        else:
            raise UnsupportedOnnxOpException(
                f"unmapped ONNX op {op!r} at node "
                f"{node.name or outs[0]!r} (the reference's OnnxGraphMapper "
                f"is likewise partial)")
