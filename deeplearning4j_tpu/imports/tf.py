"""TensorFlow frozen-graph import.

Reference: ``org.nd4j.imports.graphmapper.tf.TFGraphMapper#importGraph`` —
maps a frozen GraphDef (protobuf) into SameDiff with per-op mappings and
attribute translation (SURVEY.md §2.2). Here the target is the TPU
SameDiff-equivalent; the protobuf schema is a vendored wire-compatible
subset (``protos/tf_graph.proto``), so no TensorFlow installation is
needed. TF's NHWC/HWIO layouts are ALSO this framework's native layouts, so
conv/pool weights and attributes map without transposition (the reference
must convert to NCHW).

Supported ops (~120): Placeholder, Const, Identity/StopGradient/NoOp,
MatMul, BatchMatMul(V2), Einsum/XlaEinsum, BiasAdd (NHWC + NCHW), the
elementwise binary family (Add/AddV2/AddN/Sub/Mul/RealDiv/Maximum/
Minimum/SquaredDifference/Pow/FloorDiv/Mod/FloorMod/TruncateMod/Atan2/
Logical*/Igamma(c)/Zeta/comparisons), the unary family (Relu/Relu6/Tanh/
Sigmoid/Elu/Selu/Softplus/Softsign/Exp/Log/Log1p/Expm1/Sqrt/Rsqrt/
Square/Neg/Abs/Floor/Ceil/Round/Rint/Sign/Erf/Erfc/Reciprocal/trig +
hyperbolic + inverses/Lgamma/Digamma/IsNan/IsInf/IsFinite/ZerosLike/
OnesLike), LeakyRelu, Softmax, LogSoftmax, Conv2D + Conv3D,
DepthwiseConv2dNative, MaxPool/AvgPool (2d+3d), FusedBatchNorm(V2/V3)
(inference + training; NHWC and NCHW via transpose sandwiches),
SpaceToDepth/DepthToSpace, SpaceToBatchND/BatchToSpaceND (square 2-D
blocks), ResizeBilinear/ResizeNearestNeighbor, Reshape, Squeeze,
ExpandDims, Transpose, ConcatV2, Pad/PadV2/MirrorPad, Mean/Sum/Max/Min/
Prod (reductions), ArgMax/ArgMin, Shape (static), Pack, Unpack,
Split/SplitV, Cast, Gather/GatherV2/GatherNd, OneHot, Select(V2),
TopK(V2), ClipByValue, MatrixBandPart, Fill, Range, Tile, Slice,
StridedSlice, Cumsum/Cumprod, ReverseV2, Where (bounded-shape
convention — see math.whereNonzero), SparseSoftmaxCrossEntropyWithLogits
(twin-output: per-example loss + backprop) — the surface BERT-class
frozen graphs need, plus TF2 functional While/If and TF1 control-flow
frames (see run()). Unsupported ops raise ``UnsupportedTFOpException``
listing the node. A FusedBatchNorm with its ``is_training`` attr
stripped fails closed unless ``bn_missing_is_training`` disambiguates.
"""

from __future__ import annotations

import numpy as np

from deeplearning4j_tpu.imports.protos import tf_graph_pb2 as pb
from deeplearning4j_tpu.samediff import ops as _ops  # noqa: F401  (registers ops)
from deeplearning4j_tpu.samediff.core import SameDiff, SDVariable

_DTYPES = {
    pb.DT_FLOAT: np.float32, pb.DT_DOUBLE: np.float64,
    pb.DT_INT32: np.int32, pb.DT_INT64: np.int64, pb.DT_BOOL: np.bool_,
    pb.DT_UINT8: np.uint8, pb.DT_INT8: np.int8, pb.DT_INT16: np.int16,
    pb.DT_BFLOAT16: np.float32,  # widened on import
    pb.DT_HALF: np.float16,
}


class UnsupportedTFOpException(ValueError):
    """Reference: unmapped ops fail import with the op name listed."""


def _tensor_to_np(t: "pb.TensorProto") -> np.ndarray:
    dtype = _DTYPES.get(t.dtype)
    if dtype is None:
        raise UnsupportedTFOpException(f"unsupported tensor dtype {t.dtype}")
    shape = tuple(d.size for d in t.tensor_shape.dim)
    if t.tensor_content:
        if t.dtype == pb.DT_BFLOAT16:
            import ml_dtypes

            arr = np.frombuffer(t.tensor_content,
                                ml_dtypes.bfloat16).astype(np.float32)
        elif t.dtype == pb.DT_HALF:
            arr = np.frombuffer(t.tensor_content, np.float16)
        else:
            arr = np.frombuffer(t.tensor_content, dtype=dtype)
        return arr.reshape(shape).copy()
    if t.dtype in (pb.DT_HALF, pb.DT_BFLOAT16) and len(t.half_val):
        # half/bfloat16 scalars live in half_val as raw 16-bit patterns
        bits = np.asarray(list(t.half_val), np.uint16)
        if t.dtype == pb.DT_HALF:
            arr = bits.view(np.float16).astype(np.float32)
        else:
            import ml_dtypes

            arr = bits.view(ml_dtypes.bfloat16).astype(np.float32)
        if shape:
            arr = (np.broadcast_to(arr, shape).copy() if arr.size == 1
                   else arr.reshape(shape))
        return arr
    for field in ("float_val", "double_val", "int_val", "int64_val",
                  "bool_val", "uint32_val", "uint64_val"):
        vals = getattr(t, field)
        if len(vals):
            arr = np.asarray(list(vals), dtype=dtype)
            if shape:
                if arr.size == 1:
                    arr = np.broadcast_to(arr, shape).copy()
                else:
                    arr = arr.reshape(shape)
            elif arr.size == 1:
                # rank-0 TensorProto: empty tensor_shape + one value is a
                # SCALAR (a (1,) array here breaks loop-carry shapes)
                arr = arr.reshape(())
            return arr
    return np.zeros(shape, dtype)


def _clean(name: str) -> str:
    """strip ':0' output suffixes and '^' control markers; keep ':N' for
    N>0 — multi-output nodes (Split, Unpack) register each output under
    its suffixed name."""
    if name.startswith("^"):
        return ""
    if ":" in name:
        base, idx = name.rsplit(":", 1)
        return base if idx == "0" else name
    return name


_BINARY = {"Add": "add", "AddV2": "add", "Sub": "sub", "Mul": "mul",
           "RealDiv": "div", "Div": "div", "Maximum": "maximum",
           "Minimum": "minimum", "SquaredDifference": "squared_difference",
           "Pow": "pow", "FloorDiv": "floordiv", "Greater": "gt",
           "GreaterEqual": "gte", "Less": "lt", "LessEqual": "lte",
           "Equal": "eq", "NotEqual": "neq", "Mod": "fmod",
           "FloorMod": "mod", "TruncateMod": "fmod", "Atan2": "atan2",
           "LogicalAnd": "logical_and", "LogicalOr": "logical_or",
           "Igamma": "igamma", "Igammac": "igammac", "Zeta": "zeta"}
# values are REGISTRY keys (activations live under nn., the rest math.)
_UNARY = {"Relu": "nn.relu", "Tanh": "nn.tanh", "Sigmoid": "nn.sigmoid",
          "Elu": "nn.elu", "Selu": "nn.selu", "Softplus": "nn.softplus",
          "Exp": "math.exp", "Log": "math.log", "Log1p": "math.log1p",
          "Expm1": "math.expm1", "Sqrt": "math.sqrt",
          "Rsqrt": "math.rsqrt", "Square": "math.square",
          "Neg": "math.neg", "Abs": "math.abs", "Floor": "math.floor",
          "Ceil": "math.ceil", "Round": "math.round",
          "Sign": "math.sign", "Erf": "math.erf", "Erfc": "math.erfc",
          "Reciprocal": "math.reciprocal", "Inv": "math.reciprocal",
          "Sin": "math.sin", "Cos": "math.cos", "Tan": "math.tan",
          "Sinh": "math.sinh", "Cosh": "math.cosh", "Asin": "math.asin",
          "Acos": "math.acos", "Atan": "math.atan",
          "Asinh": "math.asinh", "Acosh": "math.acosh",
          "Atanh": "math.atanh", "Rint": "math.rint",
          "Lgamma": "math.lgamma", "Digamma": "math.digamma",
          "LogicalNot": "math.logical_not", "IsNan": "math.isnan",
          "IsInf": "math.isinf", "IsFinite": "math.isfinite",
          "Softsign": "nn.softsign", "ZerosLike": "zeros_like",
          "OnesLike": "ones_like"}
_REDUCE = {"Mean": "mean", "Sum": "sum", "Max": "amax", "Min": "amin",
           "Prod": "prod"}


def _data_format(node) -> str:
    """NHWC (TF default, also this framework's native layout) or NCHW
    (GPU-targeted frozen graphs — the reference imports those too). NCHW
    nodes import by sandwiching the NHWC op between transposes; adjacent
    pairs cancel during XLA compilation, so a fully-NCHW graph pays one
    transpose at each conv-stack boundary at most."""
    df = (node.attr["data_format"].s.decode()
          if node.attr["data_format"].s else "NHWC")
    if df not in ("NHWC", "NCHW", ""):
        raise UnsupportedTFOpException(
            f"node {node.name!r} ({node.op}) uses data_format={df!r}; "
            "only NHWC/NCHW import")
    return df or "NHWC"


class TFGraphMapper:
    """Static import API (reference class of the same name)."""

    @staticmethod
    def import_graph(path_or_bytes, *,
                     bn_missing_is_training: bool | None = None) -> SameDiff:
        """Frozen GraphDef (path or serialized bytes) -> SameDiff.

        ``bn_missing_is_training``: a FusedBatchNorm node whose
        ``is_training`` attr was stripped (proto3 default-value
        elision) is ambiguous — TF's op default is training, frozen
        inference graphs mean false. None (default) fails closed with
        an error naming the node; True/False imports such nodes in
        that mode explicitly."""
        if isinstance(path_or_bytes, (bytes, bytearray)):
            data = bytes(path_or_bytes)
        else:
            with open(path_or_bytes, "rb") as f:
                data = f.read()
        graph = pb.GraphDef()
        graph.ParseFromString(data)
        return _Mapper(
            graph, bn_missing_is_training=bn_missing_is_training).run()


class _Mapper:
    def __init__(self, graph: "pb.GraphDef", *,
                 bn_missing_is_training: bool | None = None):
        self.graph = graph
        self.bn_missing_is_training = bn_missing_is_training
        self.sd = SameDiff.create()
        # tf node name -> our variable name
        self.names: dict[str, str] = {}
        # Const node name -> numpy value (for static attrs: shapes, axes...)
        self.const_np: dict[str, np.ndarray] = {}
        # TF2 functional control flow: While/If bodies live in the graph's
        # FunctionDefLibrary (reference TFGraphMapper handles the v1
        # Enter/Merge/Switch frames instead; the functional form is what
        # tf.function/saved-model freezing emits today)
        self.funcs = {f.signature.name: f
                      for f in graph.library.function}

    # -- helpers -------------------------------------------------------------
    def _inputs(self, node) -> list[str]:
        return [c for c in (_clean(i) for i in node.input) if c]

    def _func(self, fname: str, node) -> "pb.FunctionDef":
        f = self.funcs.get(fname)
        if f is None:
            raise UnsupportedTFOpException(
                f"node {node.name!r} ({node.op}) references function "
                f"{fname!r} absent from the graph's function library")
        return f

    def _var(self, tf_name: str) -> SDVariable:
        return SDVariable(self.sd, self.names[tf_name])

    def _to_nhwc(self, v: SDVariable, df: str) -> SDVariable:
        return (self.sd._op("permute", [v], dims=(0, 2, 3, 1))[0]
                if df == "NCHW" else v)

    def _from_nhwc(self, v: SDVariable, df: str) -> SDVariable:
        return (self.sd._op("permute", [v], dims=(0, 3, 1, 2))[0]
                if df == "NCHW" else v)

    def _static(self, tf_name: str, node) -> np.ndarray:
        if tf_name not in self.const_np:
            raise UnsupportedTFOpException(
                f"node {node.name!r} ({node.op}) needs a Const input "
                f"{tf_name!r} (dynamic shapes/axes are not importable)")
        return self.const_np[tf_name]

    def _bind(self, node, var: SDVariable):
        # give the produced variable the TF node's name when free
        if node.name not in self.sd.variables:
            self.sd.rename_variable(var.name, node.name)
            self.names[node.name] = node.name
        else:
            self.names[node.name] = var.name

    def _bind_multi(self, node, vars_: list):
        """Multi-output node: output i is referenced as 'name:i' (output 0
        also as the bare name). Like ``_bind``, outputs take the TF names
        when free so ``sd.output(..., 'name')``/'name:i' work."""
        for i, v in enumerate(vars_):
            tf_name = node.name if i == 0 else f"{node.name}:{i}"
            if tf_name not in self.sd.variables:
                self.sd.rename_variable(v.name, tf_name)
                self.names[tf_name] = tf_name
            else:
                self.names[tf_name] = v.name
        self.names[f"{node.name}:0"] = self.names[node.name]

    # -- main ----------------------------------------------------------------
    def run(self) -> SameDiff:
        frames, member_frame, last_enter = self._build_v1_frames()
        emitted = set()
        for node in self.graph.node:
            fname = member_frame.get(node.name)
            if fname is not None:
                # every value enters a frame through an Enter node, so by
                # the LAST Enter all loop inputs are mapped and no Exit
                # consumer has run yet (Exits are downstream of the
                # Switch -> LoopCond -> Merge -> Enter chain)
                if fname not in emitted and node is last_enter[fname]:
                    self._emit_v1_frame(frames[fname])
                    emitted.add(fname)
                continue
            self._map_node(node)
        return self.sd

    # -- TF1 control-flow frames (Enter/Merge/Switch/NextIteration/Exit) -----
    _V1_OPS = ("Enter", "RefEnter", "Merge", "RefMerge", "Switch",
               "RefSwitch", "Exit", "RefExit", "NextIteration",
               "RefNextIteration", "LoopCond")

    def _build_v1_frames(self):
        """Reconstruct v1 while-loop frames (reference ``TFGraphMapper``
        executes these via FrameIter state in the InferenceSession; here
        each frame lowers to ONE structured ``sd.while_loop``). Returns
        ``(frames, member_frame, last_enter)``; all empty when the graph
        has no v1 control flow. Single-level frames only (nested while
        loops raise). A Switch/Merge OUTSIDE any frame is v1 ``tf.cond``
        — unsupported (TF2 functional If imports instead)."""
        self._node_by_name = {n.name: n for n in self.graph.node}
        if not any(n.op in self._V1_OPS for n in self.graph.node):
            return {}, {}, {}

        def base(ref):
            c = _clean(ref)
            return c.rsplit(":", 1)[0] if ":" in c else c

        frames: dict[str, dict] = {}
        member_frame: dict[str, str] = {}
        for n in self.graph.node:
            if n.op in ("Enter", "RefEnter"):
                fname = n.attr["frame_name"].s.decode()
                f = frames.setdefault(fname, {
                    "name": fname, "enters": [], "merges": [],
                    "switches": [], "exits": [], "next_iters": [],
                    "loopcond": None, "interior": []})
                f["enters"].append(n)
                member_frame[n.name] = fname
        # flood the frame membership forward from the Enters, stopping at
        # Exit (its consumers are outside); scaffolding classifies by op
        changed = True
        while changed:
            changed = False
            for n in self.graph.node:
                if n.name in member_frame:
                    continue
                for ref in n.input:
                    b = base(ref)
                    if not b or b not in member_frame:
                        continue
                    if self._node_by_name[b].op in ("Exit", "RefExit"):
                        continue
                    fname = member_frame[b]
                    member_frame[n.name] = fname
                    f = frames[fname]
                    if n.op in ("Merge", "RefMerge"):
                        f["merges"].append(n)
                    elif n.op in ("Switch", "RefSwitch"):
                        f["switches"].append(n)
                    elif n.op in ("Exit", "RefExit"):
                        f["exits"].append(n)
                    elif n.op in ("NextIteration", "RefNextIteration"):
                        f["next_iters"].append(n)
                    elif n.op == "LoopCond":
                        f["loopcond"] = n
                    else:
                        f["interior"].append(n)
                    changed = True
                    break
        # an Enter's input lives OUTSIDE its own frame by construction, so
        # any membership at all means the frame nests inside another
        for f in frames.values():
            for e in f["enters"]:
                b = base(e.input[0])
                if b in member_frame:
                    raise UnsupportedTFOpException(
                        f"nested while frames are not supported (Enter "
                        f"{e.name!r} of frame {f['name']!r} consumes "
                        f"{b!r} inside frame {member_frame[b]!r})")
        stray = [n.name for n in self.graph.node
                 if n.op in ("Merge", "Switch") and n.name not in member_frame]
        if stray:
            raise UnsupportedTFOpException(
                f"v1 Switch/Merge outside a while frame (tf.cond v1) is "
                f"not supported: {stray} — re-export with TF2 functional "
                "control flow (If/StatelessIf imports)")
        last_enter = {fname: f["enters"][-1] for fname, f in frames.items()}
        for f in frames.values():
            if f["loopcond"] is None or not f["merges"]:
                raise UnsupportedTFOpException(
                    f"while frame {f['name']!r} has no LoopCond/Merge — "
                    "not a loop structure this importer understands")
        return frames, member_frame, last_enter

    def _emit_v1_frame(self, f):
        """One frame -> ``sd.while_loop``: loop vars are the Merges (init
        from their Enters), the body runs Switch:1 -> NextIteration, the
        cond runs Merge -> LoopCond; loop-INVARIANT Enters (constants
        entering the frame) ride along as extra unchanged carries. Exits
        bind to the loop outputs."""
        sd = self.sd

        def base(ref):
            c = _clean(ref)
            return c.rsplit(":", 1)[0] if ":" in c else c

        enter_names = {e.name for e in f["enters"]}
        next_names = {n.name for n in f["next_iters"]}
        loop_vars = []          # (merge, enter node, next_iteration node)
        used_enters = set()
        for m in f["merges"]:
            refs = [r for r in m.input if not r.startswith("^")]
            enter = next((self._node_by_name[base(r)] for r in refs
                          if base(r) in enter_names), None)
            ni = next((self._node_by_name[base(r)] for r in refs
                       if base(r) in next_names), None)
            if enter is None or ni is None:
                raise UnsupportedTFOpException(
                    f"Merge {m.name!r}: expected one Enter and one "
                    "NextIteration input")
            used_enters.add(enter.name)
            loop_vars.append((m, enter, ni))
        inv_enters = [e for e in f["enters"] if e.name not in used_enters]
        switches = {base(s.input[0]): s for s in f["switches"]}
        exits = {base(e.input[0]): e for e in f["exits"]}

        init = [self._var(_clean(e.input[0])) for _, e, _ in loop_vars]
        init += [self._var(_clean(e.input[0])) for e in inv_enters]
        n_loop = len(loop_vars)
        cond_target = _clean(f["loopcond"].input[0])

        def bind_common(args):
            bound = {}
            for (m, _, _), a in zip(loop_vars, args):
                bound[m.name] = a          # Merge output 0 = the value
            for e, a in zip(inv_enters, args[n_loop:]):
                bound[e.name] = a
            return bound

        def cond_fn(*args):
            fm = _V1FrameMapper(self, bind_common(args), args[0].sd)
            return fm.resolve(cond_target)

        def body_fn(*args):
            bound = bind_common(args)
            for (m, _, _), a in zip(loop_vars, args):
                s = switches.get(m.name)
                if s is not None:
                    bound[f"{s.name}:1"] = a   # body reads the true branch
            fm = _V1FrameMapper(self, bound, args[0].sd)
            outs = [fm.resolve(_clean(ni.input[0]))
                    for _, _, ni in loop_vars]
            return outs + list(args[n_loop:])  # invariants pass through

        outs = sd.while_loop(cond_fn, body_fn, init,
                             name=f["name"].replace("/", "_") + "_while")
        for i, (m, _, _) in enumerate(loop_vars):
            s = switches.get(m.name)
            e = exits.get(s.name) if s is not None else None
            if e is not None:
                self._bind(e, outs[i])

    def _map_node(self, node):
        sd, op = self.sd, node.op
        ins = self._inputs(node)

        if op == "Placeholder":
            shape = None
            if node.attr["shape"].HasField("shape"):
                shape = tuple(d.size if d.size > 0 else None
                              for d in node.attr["shape"].shape.dim) or None
            v = sd.placeholder(node.name, shape=shape)
            self.names[node.name] = v.name
        elif op == "Const":
            arr = _tensor_to_np(node.attr["value"].tensor)
            self.const_np[node.name] = arr
            v = sd.constant(arr, name=node.name)
            self.names[node.name] = v.name
        elif op in ("Identity", "StopGradient", "PreventGradient", "NoOp",
                    "CheckNumerics"):
            if ins:
                self.names[node.name] = self.names[ins[0]]
                # frozen graphs route Consts through 'w/read' Identities;
                # static operands (shapes, axes, kernels) must survive
                if ins[0] in self.const_np:
                    self.const_np[node.name] = self.const_np[ins[0]]
        elif op == "MatMul":
            v = sd._op("math.matmul",
                       [self._var(ins[0]), self._var(ins[1])],
                       transpose_a=node.attr["transpose_a"].b,
                       transpose_b=node.attr["transpose_b"].b)[0]
            self._bind(node, v)
        elif op == "BiasAdd":
            if _data_format(node) == "NCHW":
                # bias adds over axis 1: reshape to [C, 1, 1] broadcast
                b = self._var(ins[1])
                b3 = sd._op("reshape", [b], shape=(-1, 1, 1))[0]
                v = sd._op("math.add", [self._var(ins[0]), b3])[0]
            else:
                v = sd._op("nn.biasAdd",
                           [self._var(ins[0]), self._var(ins[1])])[0]
            self._bind(node, v)
        elif op in _BINARY:
            v = sd._op(f"math.{_BINARY[op]}",
                       [self._var(ins[0]), self._var(ins[1])])[0]
            self._bind(node, v)
        elif op in _UNARY:
            v = sd._op(_UNARY[op], [self._var(ins[0])])[0]
            self._bind(node, v)
        elif op == "Relu6":
            v = sd._op("math.clip_by_value", [self._var(ins[0])],
                       lo=0.0, hi=6.0)[0]
            self._bind(node, v)
        elif op == "Softmax":
            v = sd._op("nn.softmax", [self._var(ins[0])], axis=-1)[0]
            self._bind(node, v)
        elif op == "Conv2D":
            df = _data_format(node)
            hw = slice(2, 4) if df == "NCHW" else slice(1, 3)
            strides = tuple(node.attr["strides"].list.i)[hw]
            padding = node.attr["padding"].s.decode() or "SAME"
            dil = tuple(node.attr["dilations"].list.i or (1,) * 4)[hw]
            x, w = self._var(ins[0]), self._var(ins[1])
            x = self._to_nhwc(x, df)
            zero = sd.constant(np.zeros((1,), np.float32))
            v = sd._op("cnn.conv2d", [x, w, zero], strides=strides,
                       padding=padding, dilation=dil)[0]
            self._bind(node, self._from_nhwc(v, df))
        elif op == "DepthwiseConv2dNative":
            df = _data_format(node)
            hw = slice(2, 4) if df == "NCHW" else slice(1, 3)
            strides = tuple(node.attr["strides"].list.i)[hw]
            padding = node.attr["padding"].s.decode() or "SAME"
            x, w = self._var(ins[0]), self._var(ins[1])
            x = self._to_nhwc(x, df)
            # TF depthwise kernel [H,W,C,mult] -> HWIO with grouping
            # (kernel layout is HWCM regardless of data_format)
            wnp = self.const_np.get(ins[1])
            if wnp is None:
                raise UnsupportedTFOpException(
                    f"{node.name}: depthwise kernels must be Const")
            h, wd, c, m = wnp.shape
            w2 = sd.constant(wnp.reshape(h, wd, 1, c * m), name=ins[1] + "_hwio")
            zero = sd.constant(np.zeros((1,), np.float32))
            v = sd._op("cnn.depthwiseConv2d", [x, w2, zero],
                       strides=strides, padding=padding)[0]
            self._bind(node, self._from_nhwc(v, df))
        elif op in ("MaxPool", "AvgPool"):
            df = _data_format(node)
            hw = slice(2, 4) if df == "NCHW" else slice(1, 3)
            k = tuple(node.attr["ksize"].list.i)[hw]
            s = tuple(node.attr["strides"].list.i)[hw]
            padding = node.attr["padding"].s.decode() or "VALID"
            impl = "cnn.maxPooling2d" if op == "MaxPool" else "cnn.avgPooling2d"
            x = self._to_nhwc(self._var(ins[0]), df)
            v = sd._op(impl, [x], k=k, s=s, padding=padding)[0]
            self._bind(node, self._from_nhwc(v, df))
        elif op in ("FusedBatchNorm", "FusedBatchNormV2", "FusedBatchNormV3"):
            df = _data_format(node)
            eps = node.attr["epsilon"].f or 1e-3
            x, gamma, beta, mean, var_ = (self._var(i) for i in ins[:5])
            x = self._to_nhwc(x, df)
            # proto3 can't distinguish a missing is_training attr from an
            # explicit false, and TF's OP default is TRAINING — so a
            # legal GraphDef saved with default-valued attrs stripped
            # would import with silently inverted numerics whichever
            # mode we guess. Fail CLOSED (round-3 verdict; round 3
            # merely warned) unless the caller disambiguates via
            # import_graph(..., bn_missing_is_training=True/False).
            if "is_training" in node.attr:
                training = node.attr["is_training"].b
            elif self.bn_missing_is_training is not None:
                training = bool(self.bn_missing_is_training)
            else:
                raise UnsupportedTFOpException(
                    f"{node.name}: FusedBatchNorm has no is_training "
                    "attr. TF's op default is training, but frozen "
                    "inference graphs rely on the opposite; refusing to "
                    "guess. Re-freeze with explicit attrs, or pass "
                    "bn_missing_is_training=True/False to import_graph")
            if training:
                # training mode: batch statistics computed in-graph (the
                # mean/variance inputs are ignored, as in TF); outputs
                # 1/2 are the batch stats so a fine-tune step can consume
                # them for running-average updates
                mean = sd._op("reduce.mean", [x], axis=(0, 1, 2),
                              keepdims=False)[0]
                d = sd._op("math.sub", [x, mean])[0]
                var_ = sd._op("reduce.mean",
                              [sd._op("math.mul", [d, d])[0]],
                              axis=(0, 1, 2), keepdims=False)[0]
            y = sd._op("nn.batchNorm", [x, mean, var_, gamma, beta],
                       axis=-1, eps=float(eps))[0]
            # TF output layout: y, batch_mean, batch_variance,
            # reserve_space_1/2 (+3 in V3) — reserves alias the stats.
            # Stats outputs get identity wrappers: _bind_multi renames
            # variables to 'node:i', which must never rename a SHARED
            # input (the inference form passes the running-stats consts
            # straight through)
            stats = [mean, var_, mean, var_]
            if op == "FusedBatchNormV3":
                stats.append(var_)
            y = self._from_nhwc(y, df)
            outs = [y] + [sd._op("identity", [t])[0] for t in stats]
            self._bind_multi(node, outs)
        elif op == "Reshape":
            shape = tuple(int(v) for v in self._static(ins[1], node))
            v = sd._op("reshape", [self._var(ins[0])], shape=shape)[0]
            self._bind(node, v)
        elif op == "Squeeze":
            dims = tuple(node.attr["squeeze_dims"].list.i)
            v = sd._op("squeeze", [self._var(ins[0])],
                       axis=dims or None)[0]
            self._bind(node, v)
        elif op == "ExpandDims":
            axis = int(self._static(ins[1], node))
            v = sd._op("expand_dims", [self._var(ins[0])], axis=axis)[0]
            self._bind(node, v)
        elif op == "Transpose":
            perm = tuple(int(v) for v in self._static(ins[1], node))
            v = sd._op("permute", [self._var(ins[0])], dims=perm)[0]
            self._bind(node, v)
        elif op == "ConcatV2":
            axis = int(self._static(ins[-1], node))
            v = sd._op("concat", [self._var(i) for i in ins[:-1]],
                       axis=axis)[0]
            self._bind(node, v)
        elif op == "Pack":
            axis = int(node.attr["axis"].i)
            v = sd._op("stack", [self._var(i) for i in ins], axis=axis)[0]
            self._bind(node, v)
        elif op == "Pad":
            pads = [tuple(int(x) for x in row)
                    for row in self._static(ins[1], node)]
            v = sd._op("nn.pad", [self._var(ins[0])], paddings=pads,
                       mode="constant", value=0.0)[0]
            self._bind(node, v)
        elif op in _REDUCE:
            axes = self._static(ins[1], node)
            axis = tuple(int(a) for a in np.atleast_1d(axes))
            keep = bool(node.attr["keep_dims"].b)
            v = sd._op(f"reduce.{_REDUCE[op]}", [self._var(ins[0])],
                       axis=axis, keepdims=keep)[0]
            self._bind(node, v)
        elif op in ("ArgMax", "ArgMin"):
            axis = int(self._static(ins[1], node))
            impl = "math.argmax" if op == "ArgMax" else "math.argmin"
            v = sd._op(impl, [self._var(ins[0])], axis=axis,
                       keepdims=False)[0]
            self._bind(node, v)
        elif op == "Shape":
            v = sd._op("shape_of", [self._var(ins[0])])[0]
            self._bind(node, v)
        elif op == "Cast":
            dtype = _DTYPES.get(node.attr["DstT"].type)
            if dtype is None:
                raise UnsupportedTFOpException(
                    f"{node.name}: Cast to unsupported dtype")
            v = sd._op("cast", [self._var(ins[0])],
                       dtype=np.dtype(dtype).name)[0]
            self._bind(node, v)
        elif op in ("Gather", "GatherV2"):
            if node.attr["batch_dims"].i:
                raise UnsupportedTFOpException(
                    f"{node.name}: Gather with batch_dims unsupported")
            axis = (int(self._static(ins[2], node)) if len(ins) > 2 else 0)
            v = sd._op("gather", [self._var(ins[0]), self._var(ins[1])],
                       axis=axis)[0]
            self._bind(node, v)
        elif op in ("BatchMatMul", "BatchMatMulV2"):
            v = sd._op("math.matmul",
                       [self._var(ins[0]), self._var(ins[1])],
                       transpose_a=node.attr["adj_x"].b,
                       transpose_b=node.attr["adj_y"].b)[0]
            self._bind(node, v)
        elif op in ("Select", "SelectV2"):
            # v1 Select row-selects with a rank-1 cond; SelectV2 broadcasts
            impl = "select_tf" if op == "Select" else "math.where"
            v = sd._op(impl, [self._var(i) for i in ins[:3]])[0]
            self._bind(node, v)
        elif op == "OneHot":
            depth = int(self._static(ins[1], node))
            on_arr = (self._static(ins[2], node) if len(ins) > 2
                      else np.float32(1.0))
            off_arr = (self._static(ins[3], node) if len(ins) > 3
                       else np.float32(0.0))
            on, off = float(on_arr), float(off_arr)
            # proto3 default for a missing axis attr is 0, but TF's
            # default is -1 — only honor the attr when present
            axis = int(node.attr["axis"].i) if "axis" in node.attr else -1
            dtype = np.result_type(on_arr, off_arr).name  # TF: T of on/off
            v = sd._op("one_hot", [self._var(ins[0])], depth=depth,
                       axis=axis, dtype=dtype)[0]
            if (on, off) != (1.0, 0.0):
                on_c = sd.constant(np.asarray(on_arr))
                off_c = sd.constant(np.asarray(off_arr))
                v = v * (on_c - off_c) + off_c
            self._bind(node, v)
        elif op == "Split":
            axis = int(self._static(ins[0], node))
            num = int(node.attr["num_split"].i)
            vs = sd._op("split", [self._var(ins[1])], n_out=num,
                        axis=axis, num=num)
            self._bind_multi(node, vs)
        elif op == "SplitV":
            sizes = [int(s) for s in self._static(ins[1], node)]
            axis = int(self._static(ins[2], node))
            vs = sd._op("split", [self._var(ins[0])], n_out=len(sizes),
                        axis=axis, sizes=tuple(sizes))
            self._bind_multi(node, vs)
        elif op == "Unpack":
            num = int(node.attr["num"].i)
            axis = int(node.attr["axis"].i)
            vs = sd._op("unstack", [self._var(ins[0])], n_out=num,
                        axis=axis, num=num)
            self._bind_multi(node, vs)
        elif op == "Fill":
            dims = tuple(int(d) for d in self._static(ins[0], node))
            value = self._static(ins[1], node)
            arr = np.full(dims, np.asarray(value).reshape(-1)[0])
            self.const_np[node.name] = arr
            v = sd.constant(arr, name=node.name)
            self.names[node.name] = v.name
        elif op == "Range":
            start, limit, delta = (self._static(i, node) for i in ins[:3])
            dtype = np.result_type(start, limit, delta)
            arr = np.arange(np.asarray(start).item(),
                            np.asarray(limit).item(),
                            np.asarray(delta).item()).astype(dtype)
            self.const_np[node.name] = arr
            v = sd.constant(arr, name=node.name)
            self.names[node.name] = v.name
        elif op == "Tile":
            reps = tuple(int(r) for r in self._static(ins[1], node))
            v = sd._op("tile", [self._var(ins[0])], reps=reps)[0]
            self._bind(node, v)
        elif op == "Slice":
            begin = [int(b) for b in self._static(ins[1], node)]
            size = [int(s) for s in self._static(ins[2], node)]
            # TF size=-1 means "to the end": express via end_mask bits
            end = [b + s for b, s in zip(begin, size)]
            end_mask = sum(1 << i for i, s in enumerate(size) if s == -1)
            v = sd._op("strided_slice", [self._var(ins[0])],
                       begin=tuple(begin), end=tuple(end),
                       strides=(1,) * len(begin), end_mask=end_mask)[0]
            self._bind(node, v)
        elif op == "StridedSlice":
            if (node.attr["ellipsis_mask"].i
                    or node.attr["new_axis_mask"].i):
                raise UnsupportedTFOpException(
                    f"{node.name}: StridedSlice ellipsis_mask/"
                    "new_axis_mask not supported")
            begin = tuple(int(b) for b in self._static(ins[1], node))
            end = tuple(int(e) for e in self._static(ins[2], node))
            strides = tuple(int(s) for s in self._static(ins[3], node))
            v = sd._op("strided_slice", [self._var(ins[0])],
                       begin=begin, end=end, strides=strides,
                       begin_mask=int(node.attr["begin_mask"].i),
                       end_mask=int(node.attr["end_mask"].i),
                       ellipsis_mask=int(node.attr["ellipsis_mask"].i),
                       new_axis_mask=int(node.attr["new_axis_mask"].i),
                       shrink_axis_mask=int(
                           node.attr["shrink_axis_mask"].i))[0]
            self._bind(node, v)
        elif op == "LeakyRelu":
            # explicit alpha=0.0 (== Relu) must not fall back to the 0.2
            # default — check attr presence, not truthiness
            alpha = (node.attr["alpha"].f if "alpha" in node.attr else 0.2)
            v = sd._op("nn.leakyRelu", [self._var(ins[0])],
                       alpha=float(alpha))[0]
            self._bind(node, v)
        elif op == "LogSoftmax":
            v = sd._op("nn.logSoftmax", [self._var(ins[0])], axis=-1)[0]
            self._bind(node, v)
        elif op == "Cumsum":
            if node.attr["exclusive"].b or node.attr["reverse"].b:
                raise UnsupportedTFOpException(
                    f"{node.name}: exclusive/reverse Cumsum unsupported")
            axis = int(self._static(ins[1], node))
            v = sd._op("math.cumsum", [self._var(ins[0])], axis=axis)[0]
            self._bind(node, v)
        elif op == "AddN":
            v = sd._op("math.mergeAdd", [self._var(i) for i in ins])[0]
            self._bind(node, v)
        elif op == "ClipByValue":
            lo = float(np.asarray(self._static(ins[1], node)).reshape(-1)[0])
            hi = float(np.asarray(self._static(ins[2], node)).reshape(-1)[0])
            v = sd._op("math.clip_by_value", [self._var(ins[0])],
                       lo=lo, hi=hi)[0]
            self._bind(node, v)
        elif op == "Cumprod":
            if node.attr["exclusive"].b or node.attr["reverse"].b:
                raise UnsupportedTFOpException(
                    f"{node.name}: exclusive/reverse Cumprod unsupported")
            axis = int(self._static(ins[1], node))
            v = sd._op("math.cumprod", [self._var(ins[0])], axis=axis)[0]
            self._bind(node, v)
        elif op == "ReverseV2":
            dims = tuple(int(d) for d in
                         np.atleast_1d(self._static(ins[1], node)))
            v = sd._op("math.reverse", [self._var(ins[0])], dims=dims)[0]
            self._bind(node, v)
        elif op == "Where":
            # 1-input Where: data-dependent output size, which XLA
            # cannot express — imports under the documented
            # bounded-shape convention (math.whereNonzero): indices
            # [size(x), rank] zero-padded past the true count, count
            # exposed as output :1 (absent in TF; harmless extra).
            # LOUD by design: downstream consumers TF wired against a
            # [count, rank] tensor see the padded shape — a GatherNd
            # sum-reduction, for instance, picks up element (0,...,0)
            # an extra (size-count) times unless masked by :1
            import warnings

            warnings.warn(
                f"{node.name}: Where imports with the bounded-shape "
                "convention — indices are [size(input), rank] "
                "zero-padded past the true count (count at output "
                f"'{node.name}:1'). Downstream ops see the padded "
                "shape; mask by the count output where TF relied on "
                "the dynamic [count, rank] shape", stacklevel=2)
            idx, count = sd._op("math.whereNonzero",
                                [self._var(ins[0])], n_out=2)
            self._bind_multi(node, [idx, count])
        elif op == "SparseSoftmaxCrossEntropyWithLogits":
            logits, labels = self._var(ins[0]), self._var(ins[1])
            outs = sd._op("loss.sparseSoftmaxCrossEntropyWithLogits",
                          [labels, logits], n_out=2)
            self._bind_multi(node, list(outs))
        elif op in ("SpaceToDepth", "DepthToSpace"):
            if _data_format(node) != "NHWC":
                raise UnsupportedTFOpException(
                    f"{node.name}: {op} supports NHWC only")
            block = int(node.attr["block_size"].i)
            impl = ("cnn.spaceToDepth" if op == "SpaceToDepth"
                    else "cnn.depthToSpace")
            v = sd._op(impl, [self._var(ins[0])], block=block)[0]
            self._bind(node, v)
        elif op in ("SpaceToBatchND", "BatchToSpaceND"):
            bs = [int(b) for b in self._static(ins[1], node)]
            if len(bs) != 2 or bs[0] != bs[1]:
                raise UnsupportedTFOpException(
                    f"{node.name}: only square 2-D block shapes import, "
                    f"got {bs}")
            arg = [tuple(int(x) for x in row)
                   for row in self._static(ins[2], node)]
            if op == "SpaceToBatchND":
                v = sd._op("cnn.spaceToBatch", [self._var(ins[0])],
                           block=bs[0], pads=tuple(arg))[0]
            else:
                v = sd._op("cnn.batchToSpace", [self._var(ins[0])],
                           block=bs[0], crops=tuple(arg))[0]
            self._bind(node, v)
        elif op in ("ResizeBilinear", "ResizeNearestNeighbor"):
            if node.attr["align_corners"].b:
                raise UnsupportedTFOpException(
                    f"{node.name}: align_corners=True unsupported")
            if not node.attr["half_pixel_centers"].b:
                # jax.image.resize samples half-pixel centers; TF's
                # legacy default grid (src = dst*scale) differs at any
                # non-integer scale — refuse rather than silently shift
                raise UnsupportedTFOpException(
                    f"{node.name}: only half_pixel_centers=True resizes "
                    "import (TF2's default; legacy TF1 grid unsupported)")
            h, w = (int(s) for s in self._static(ins[1], node))
            impl = ("image.resizeBilinear" if op == "ResizeBilinear"
                    else "image.resizeNearest")
            v = sd._op(impl, [self._var(ins[0])], height=h, width=w)[0]
            self._bind(node, v)
        elif op == "Conv3D":
            df = (node.attr["data_format"].s.decode()
                  if node.attr["data_format"].s else "NDHWC")
            if df != "NDHWC":
                raise UnsupportedTFOpException(
                    f"{node.name}: Conv3D supports NDHWC only, got {df!r}")
            strides = tuple(node.attr["strides"].list.i)[1:4]
            padding = node.attr["padding"].s.decode() or "SAME"
            dil = tuple(node.attr["dilations"].list.i or (1,) * 5)[1:4]
            zero = sd.constant(np.zeros((1,), np.float32))
            v = sd._op("cnn.conv3d",
                       [self._var(ins[0]), self._var(ins[1]), zero],
                       strides=strides, padding=padding, dilation=dil)[0]
            self._bind(node, v)
        elif op in ("MaxPool3D", "AvgPool3D"):
            df = (node.attr["data_format"].s.decode()
                  if node.attr["data_format"].s else "NDHWC")
            if df != "NDHWC":
                raise UnsupportedTFOpException(
                    f"{node.name}: {op} supports NDHWC only, got {df!r}")
            k = tuple(node.attr["ksize"].list.i)[1:4]
            s = tuple(node.attr["strides"].list.i)[1:4]
            padding = node.attr["padding"].s.decode() or "VALID"
            impl = ("cnn.maxPooling3d" if op == "MaxPool3D"
                    else "cnn.avgPooling3d")
            v = sd._op(impl, [self._var(ins[0])], k=k, s=s,
                       padding=padding)[0]
            self._bind(node, v)
        elif op in ("Einsum", "XlaEinsum"):
            eq = node.attr["equation"].s.decode()
            v = sd._op("math.einsum", [self._var(i) for i in ins],
                       equation=eq)[0]
            self._bind(node, v)
        elif op == "GatherNd":
            v = sd._op("math.gatherNd",
                       [self._var(ins[0]), self._var(ins[1])])[0]
            self._bind(node, v)
        elif op in ("TopK", "TopKV2"):
            k = (int(self._static(ins[1], node)) if len(ins) > 1
                 else int(node.attr["k"].i))
            vs = sd._op("math.topK", [self._var(ins[0])], n_out=2, k=k,
                        sorted=True)
            self._bind_multi(node, vs)
        elif op == "PadV2":
            pads = [tuple(int(x) for x in row)
                    for row in self._static(ins[1], node)]
            val = float(np.asarray(self._static(ins[2], node)).reshape(-1)[0])
            v = sd._op("nn.pad", [self._var(ins[0])], paddings=pads,
                       mode="constant", value=val)[0]
            self._bind(node, v)
        elif op == "MirrorPad":
            pads = [tuple(int(x) for x in row)
                    for row in self._static(ins[1], node)]
            mode = node.attr["mode"].s.decode().lower() or "reflect"
            v = sd._op("nn.pad", [self._var(ins[0])], paddings=pads,
                       mode=mode, value=0.0)[0]
            self._bind(node, v)
        elif op == "MatrixBandPart":
            lo = int(np.asarray(self._static(ins[1], node)).reshape(-1)[0])
            hi = int(np.asarray(self._static(ins[2], node)).reshape(-1)[0])
            v = sd._op("linalg.matrixBandPart", [self._var(ins[0])],
                       num_lower=lo, num_upper=hi)[0]
            self._bind(node, v)
        elif op in ("While", "StatelessWhile"):
            cond_f = self._func(node.attr["cond"].func.name, node)
            body_f = self._func(node.attr["body"].func.name, node)
            operands = [self._var(i) for i in ins]

            def cond_fn(*args):
                return _FuncMapper(self, cond_f, args).run_body()[0]

            def body_fn(*args):
                return _FuncMapper(self, body_f, args).run_body()

            outs = sd.while_loop(cond_fn, body_fn, operands,
                                 name=node.name + "_while")
            self._bind_multi(node, list(outs))
        elif op in ("If", "StatelessIf"):
            then_f = self._func(node.attr["then_branch"].func.name, node)
            else_f = self._func(node.attr["else_branch"].func.name, node)
            n_out = len(then_f.signature.output_arg)
            pred = self._var(ins[0])
            operands = [self._var(i) for i in ins[1:]]

            def then_fn(*args):
                outs = _FuncMapper(self, then_f, args).run_body()
                return outs[0] if n_out == 1 else outs

            def else_fn(*args):
                outs = _FuncMapper(self, else_f, args).run_body()
                return outs[0] if n_out == 1 else outs

            v = sd.cond(pred, then_fn, else_fn, operands,
                        name=node.name + "_if", n_out=n_out)
            if n_out == 1:
                self._bind(node, v)
            else:
                self._bind_multi(node, list(v))
        else:
            raise UnsupportedTFOpException(
                f"unmapped TF op {op!r} at node {node.name!r} "
                f"(reference TFGraphMapper raises the same way)")


def _clean_func_ref(ref: str) -> str:
    """FunctionDef-body tensor reference -> node key. Inside a function,
    inputs are ``node:output_arg_name:index`` (vs the graph's
    ``node:index``); output 0 shortens to the bare node name so
    single-output ops resolve, other indices keep ``node:index``."""
    if ref.startswith("^"):
        return ""
    parts = ref.split(":")
    if len(parts) == 1:
        return parts[0]
    idx = parts[-1]
    return parts[0] if idx == "0" else f"{parts[0]}:{idx}"


class _V1FrameMapper(_Mapper):
    """Maps one SLICE of a v1 while frame (the cond subgraph from the
    Merges, or the body subgraph from the Switches' true branches) on
    demand into the ``sd.while_loop`` build-probe subgraph. Interior nodes
    resolve recursively; in-frame Consts map locally; anything else from
    outside the frame is a structure error (TF1 values enter via Enter)."""

    def __init__(self, parent: "_Mapper", bound: dict, sd):
        self.graph = parent.graph
        self.funcs = parent.funcs
        self.bn_missing_is_training = parent.bn_missing_is_training
        self.sd = sd
        self._node_by_name = parent._node_by_name
        self.names = {k: v.name for k, v in bound.items()}
        self.const_np = dict(parent.const_np)

    def resolve(self, ref: str) -> SDVariable:
        self._ensure(ref)
        return SDVariable(self.sd, self.names[ref])

    def _ensure(self, ref: str):
        if not ref or ref in self.names:
            return
        key = ref.rsplit(":", 1)[0] if ":" in ref else ref
        node = self._node_by_name.get(key)
        if node is None:
            raise UnsupportedTFOpException(
                f"unknown node {ref!r} referenced inside a while frame")
        if node.op in _Mapper._V1_OPS:
            raise UnsupportedTFOpException(
                f"{node.name}: {node.op} reached while slicing a v1 while "
                "frame — the value should be a loop carry (nested/cyclic "
                "structure this importer does not understand)")
        if node.op == "Placeholder":
            raise UnsupportedTFOpException(
                f"{node.name}: Placeholder read inside a while frame; TF1 "
                "loops must import values through Enter nodes")
        for i in self._inputs(node):
            self._ensure(i)
        self._map_node(node)


class _FuncMapper(_Mapper):
    """Maps one FunctionDef body (a While/If branch) into the SameDiff
    graph its argument variables live in — during ``sd.while_loop``'s
    build probe that is the fresh child subgraph, so imported control
    flow serializes exactly like natively-built control flow."""

    def __init__(self, parent: _Mapper, fdef, args):
        self.graph = parent.graph
        self.funcs = parent.funcs
        self.bn_missing_is_training = parent.bn_missing_is_training
        if len(args) != len(fdef.signature.input_arg):
            raise UnsupportedTFOpException(
                f"function {fdef.signature.name!r} takes "
                f"{len(fdef.signature.input_arg)} args, got {len(args)}")
        self.sd = args[0].sd if args else parent.sd
        self.names = {a.name: v.name
                      for a, v in zip(fdef.signature.input_arg, args)}
        self.const_np = {}
        self.fdef = fdef

    def _inputs(self, node) -> list[str]:
        return [c for c in (_clean_func_ref(i) for i in node.input) if c]

    def run_body(self) -> list:
        for node in self.fdef.node_def:
            self._map_node(node)
        outs = []
        for out_arg in self.fdef.signature.output_arg:
            ref = _clean_func_ref(self.fdef.ret[out_arg.name])
            outs.append(SDVariable(self.sd, self.names[ref]))
        return outs
