"""JSON serialization registry for the config DSL.

The reference serializes its entire configuration tree to JSON/YAML via Jackson
with full round-trip fidelity (reference: ``MultiLayerConfiguration#toJson`` /
``#fromJson``, heavily round-trip tested). Configs-as-data is what enables
ModelSerializer, TransferLearning mutation and hyperparameter search, so the
same property is a parity requirement here.

Every config class is a ``@dataclass`` registered under a type tag; nested
configs, enums, tuples and numpy scalars round-trip losslessly.
"""

from __future__ import annotations

import dataclasses
import enum
import json
from typing import Any, Dict, Type

_REGISTRY: Dict[str, Type] = {}
_TYPE_KEY = "@type"


def register(cls=None, *, name: str | None = None):
    """Class decorator: register a dataclass for polymorphic JSON round-trip."""

    def wrap(c):
        tag = name or c.__name__
        if tag in _REGISTRY and _REGISTRY[tag] is not c:
            raise ValueError(f"serde tag already registered: {tag}")
        _REGISTRY[tag] = c
        c._serde_tag = tag
        return c

    return wrap(cls) if cls is not None else wrap


def registered_class(tag: str) -> Type:
    if tag not in _REGISTRY:
        raise KeyError(f"unknown config type tag: {tag!r}")
    return _REGISTRY[tag]


def to_dict(obj: Any) -> Any:
    """Recursively convert a registered config object to JSON-compatible data."""
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, enum.Enum):
        return {_TYPE_KEY: "@enum", "enum": type(obj).__name__, "value": obj.name}
    if isinstance(obj, (list, tuple)):
        data = [to_dict(v) for v in obj]
        if isinstance(obj, tuple):
            return {_TYPE_KEY: "@tuple", "items": data}
        return data
    if isinstance(obj, dict):
        for k in obj:
            if not isinstance(k, str):
                raise TypeError(
                    f"config dict keys must be strings for JSON round-trip "
                    f"fidelity, got {type(k).__name__} key {k!r}"
                )
        return {k: to_dict(v) for k, v in obj.items()}
    if dataclasses.is_dataclass(obj):
        # Look up the tag on the exact class: an unregistered subclass must
        # not silently serialize under its parent's tag.
        tag = type(obj).__dict__.get("_serde_tag")
        if tag is None:
            raise TypeError(f"dataclass {type(obj).__name__} is not @serde.register-ed")
        out = {_TYPE_KEY: tag}
        for f in dataclasses.fields(obj):
            if not f.metadata.get("serde_skip", False):
                out[f.name] = to_dict(getattr(obj, f.name))
        return out
    # numpy / jax scalars
    if hasattr(obj, "item") and callable(obj.item):
        return obj.item()
    raise TypeError(f"cannot serialize {type(obj).__name__}: {obj!r}")


_ENUMS: Dict[str, Type] = {}


def register_enum(cls):
    """Enum decorator so enums referenced by configs can round-trip by name."""
    _ENUMS[cls.__name__] = cls
    return cls


def from_dict(data: Any) -> Any:
    """Inverse of :func:`to_dict`."""
    if data is None or isinstance(data, (bool, int, float, str)):
        return data
    if isinstance(data, list):
        return [from_dict(v) for v in data]
    if isinstance(data, dict):
        tag = data.get(_TYPE_KEY)
        if tag == "@enum":
            return _ENUMS[data["enum"]][data["value"]]
        if tag == "@tuple":
            return tuple(from_dict(v) for v in data["items"])
        if tag is not None:
            cls = registered_class(tag)
            kwargs = {
                k: from_dict(v) for k, v in data.items() if k != _TYPE_KEY
            }
            field_names = {f.name for f in dataclasses.fields(cls)}
            unknown = set(kwargs) - field_names
            if unknown:
                raise ValueError(f"unknown fields for {tag}: {sorted(unknown)}")
            return cls(**kwargs)
        return {k: from_dict(v) for k, v in data.items()}
    raise TypeError(f"cannot deserialize {type(data).__name__}: {data!r}")


def to_json(obj: Any, indent: int | None = 2) -> str:
    return json.dumps(to_dict(obj), indent=indent)


def from_json(s: str) -> Any:
    return from_dict(json.loads(s))
