"""Mixed-precision compute policy (``conf.compute_dtype``).

Contract (BASELINE.md round-2 MFU work): forward/backward run in the
compute dtype (bf16), while params, optimizer state, BN statistics, the
loss, and all user-visible outputs stay in the storage dtype (f32
masters). The reference has one global DataType
(``NeuralNetConfiguration.Builder#dataType``); the TPU-first design
splits storage from compute because bf16 matmuls are ~2x faster on the
MXU while f32 masters keep updater semantics exact.
"""

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.conf.activations import Activation
from deeplearning4j_tpu.conf.inputs import InputType
from deeplearning4j_tpu.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.conf.layers_cnn import (
    BatchNormalization,
    ConvolutionLayer,
)
from deeplearning4j_tpu.conf.layers_rnn import LSTM, RnnOutputLayer
from deeplearning4j_tpu.conf.losses import LossMCXENT
from deeplearning4j_tpu.conf.multilayer import (
    BackpropType,
    NeuralNetConfiguration,
)
from deeplearning4j_tpu.conf.updaters import Adam
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def _cnn_conf(compute_dtype=None):
    b = (NeuralNetConfiguration.builder().seed(7)
         .updater(Adam(learning_rate=1e-2)))
    if compute_dtype is not None:
        b = b.compute_dtype(compute_dtype)
    return (b.list()
            .layer(ConvolutionLayer(n_out=8, kernel_size=(3, 3),
                                    activation=Activation.RELU))
            .layer(BatchNormalization())
            .layer(DenseLayer(n_out=32, activation=Activation.RELU))
            .layer(OutputLayer(n_out=10, activation=Activation.SOFTMAX,
                               loss_fn=LossMCXENT()))
            .set_input_type(InputType.convolutional(8, 8, 1)).build())


def _batch(n=16):
    rng = np.random.default_rng(0)
    return DataSet(rng.normal(size=(n, 8, 8, 1)).astype(np.float32),
                   np.eye(10, dtype=np.float32)[rng.integers(0, 10, n)])


def test_json_round_trip_preserves_compute_dtype():
    conf = _cnn_conf("bfloat16")
    conf2 = type(conf).from_json(conf.to_json())
    assert conf2.compute_dtype == "bfloat16"
    assert _cnn_conf().compute_dtype is None


def test_bf16_policy_trains_and_keeps_f32_masters():
    net = MultiLayerNetwork(_cnn_conf("bfloat16")).init()
    ds = _batch()
    l0 = net.fit_batch(ds)
    for _ in range(30):
        l = net.fit_batch(ds)
    assert l < l0 * 0.7
    for lp in net.params.values():
        for pv in lp.values():
            assert pv.dtype == jnp.float32
    for s in net.state.values():  # BN running stats stay f32
        for sv in s.values():
            assert sv.dtype == jnp.float32
    out = net.output(ds.features)
    assert out.dtype == jnp.float32


def test_bf16_policy_tracks_f32_training():
    """Same seed/data: the bf16 run should follow the f32 run closely —
    the policy changes precision, not semantics."""
    ds = _batch()
    nets = [MultiLayerNetwork(_cnn_conf(cd)).init()
            for cd in (None, "bfloat16")]
    losses = []
    for net in nets:
        for _ in range(10):
            l = net.fit_batch(ds)
        losses.append(l)
    assert losses[1] == pytest.approx(losses[0], rel=0.25)


def test_bf16_policy_tbptt_and_streaming():
    conf = (NeuralNetConfiguration.builder().seed(7)
            .updater(Adam(learning_rate=1e-2)).compute_dtype("bfloat16")
            .list()
            .layer(LSTM(n_out=16))
            .layer(RnnOutputLayer(n_out=4, activation=Activation.SOFTMAX,
                                  loss_fn=LossMCXENT()))
            .backprop_type(BackpropType.TRUNCATED_BPTT, fwd=5, back=5)
            .set_input_type(InputType.recurrent(3, 20)).build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    ds = DataSet(rng.normal(size=(4, 20, 3)).astype(np.float32),
                 np.eye(4, dtype=np.float32)[rng.integers(0, 4, (4, 20))])
    l0 = net.fit_batch(ds)
    for _ in range(20):
        l = net.fit_batch(ds)
    assert l < l0
    y = net.rnn_time_step(rng.normal(size=(4, 2, 3)).astype(np.float32))
    assert y.dtype == jnp.float32 and y.shape == (4, 2, 4)


def test_bf16_policy_computation_graph():
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.zoo.graphs import ResNet50

    cfg = ResNet50(num_classes=10, height=32, width=32,
                   updater=Adam(learning_rate=1e-3)).conf()
    cfg = dataclasses.replace(cfg, compute_dtype="bfloat16")
    g = ComputationGraph(cfg).init()
    rng = np.random.default_rng(0)
    ds = DataSet(rng.integers(0, 256, (8, 32, 32, 3), dtype=np.uint8),
                 np.eye(10, dtype=np.float32)[rng.integers(0, 10, 8)])
    l0 = g.fit_batch(ds)
    for _ in range(10):
        l = g.fit_batch(ds)
    assert l < l0
    for lp in g.params.values():
        for pv in lp.values():
            assert pv.dtype == jnp.float32
    assert g.output(ds.features).dtype == jnp.float32
