"""Directory-driven import conformance suite.

Reference pattern: ``TFGraphTestAllSameDiff`` over the
``dl4j-test-resources`` artifact — a directory of committed model
binaries + golden input/output pairs; the test is parameterized over
whatever the directory contains, so adding a fixture adds coverage with
no new code. Fixtures here are COMMITTED binaries in the writers' exact
on-disk formats (see ``tests/resources/generate_fixtures.py`` — this
zero-egress env has no TF/Keras to author them, which is the honest
limit of format conformance available; goldens are independent numpy
forward math, never the importer's own output).
"""

import json
import os

import numpy as np
import pytest

RES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                   "resources", "conformance")
CASES = sorted(d for d in (os.listdir(RES) if os.path.isdir(RES) else [])
               if os.path.isdir(os.path.join(RES, d)))


def _load(case):
    d = os.path.join(RES, case)
    with open(os.path.join(d, "META.json")) as f:
        meta = json.load(f)
    x = np.load(os.path.join(d, "input.npy"))
    want = np.load(os.path.join(d, "expected.npy"))
    return d, meta, x, want


@pytest.mark.parametrize("case", CASES)
def test_import_conformance(case):
    d, meta, x, want = _load(case)
    if meta["kind"] == "keras":
        from deeplearning4j_tpu.modelimport.keras import KerasModelImport

        net = KerasModelImport.import_keras_model_and_weights(
            os.path.join(d, "model.h5"))
        got = np.asarray(net.output(x))
    elif meta["kind"] == "tf":
        from deeplearning4j_tpu.imports.tf import TFGraphMapper

        sd = TFGraphMapper.import_graph(os.path.join(d, "graph.pb"))
        out = sd.output({meta["input"]: x}, meta["output"])
        got = np.asarray(out[meta["output"]])
    else:  # pragma: no cover
        pytest.fail(f"unknown fixture kind {meta['kind']!r}")
    np.testing.assert_allclose(got, want, rtol=meta.get("rtol", 1e-4),
                               atol=meta.get("atol", 1e-5),
                               err_msg=f"conformance mismatch for {case}")


def test_conformance_dir_nonempty():
    """The suite must never silently pass because the fixtures vanished."""
    assert len(CASES) >= 4, CASES
