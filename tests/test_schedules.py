import numpy as np

from deeplearning4j_tpu.conf.schedules import (
    ExponentialSchedule,
    FixedSchedule,
    InverseSchedule,
    MapSchedule,
    PolySchedule,
    ScheduleType,
    SigmoidSchedule,
    StepSchedule,
    WarmupSchedule,
)


def v(s, it, ep=0):
    return float(s.value_at(it, ep))


def test_fixed():
    assert v(FixedSchedule(0.01), 0) == np.float32(0.01)
    assert v(FixedSchedule(0.01), 9999) == np.float32(0.01)


def test_step():
    s = StepSchedule(ScheduleType.ITERATION, 0.1, 0.5, 10)
    assert np.isclose(v(s, 0), 0.1)
    assert np.isclose(v(s, 9), 0.1)
    assert np.isclose(v(s, 10), 0.05)
    assert np.isclose(v(s, 25), 0.025)


def test_step_epoch_type():
    s = StepSchedule(ScheduleType.EPOCH, 0.1, 0.1, 1)
    assert np.isclose(v(s, 12345, ep=0), 0.1)
    assert np.isclose(v(s, 12345, ep=2), 0.001)


def test_exponential():
    s = ExponentialSchedule(ScheduleType.ITERATION, 0.2, 0.9)
    assert np.isclose(v(s, 0), 0.2)
    assert np.isclose(v(s, 3), 0.2 * 0.9 ** 3, rtol=1e-5)


def test_inverse():
    s = InverseSchedule(ScheduleType.ITERATION, 0.5, 0.1, 2.0)
    assert np.isclose(v(s, 0), 0.5)
    assert np.isclose(v(s, 10), 0.5 / (1 + 1.0) ** 2)


def test_poly():
    s = PolySchedule(ScheduleType.ITERATION, 0.3, 1.0, 100)
    assert np.isclose(v(s, 0), 0.3)
    assert np.isclose(v(s, 50), 0.15)
    assert np.isclose(v(s, 100), 0.0)
    assert np.isclose(v(s, 150), 0.0)  # clamped past max_iter


def test_sigmoid_monotone_decreasing():
    # Caffe convention: negative gamma = smooth step-down.
    s = SigmoidSchedule(ScheduleType.ITERATION, 0.1, -0.05, 100)
    vals = [v(s, t) for t in range(0, 300, 25)]
    assert all(a >= b for a, b in zip(vals, vals[1:]))
    assert np.isclose(v(s, 100), 0.05, rtol=1e-4)  # half value at step_size


def test_map():
    s = MapSchedule(ScheduleType.ITERATION, {"0": 0.1, "10": 0.01, "20": 0.001})
    assert np.isclose(v(s, 5), 0.1)
    assert np.isclose(v(s, 10), 0.01)
    assert np.isclose(v(s, 19), 0.01)
    assert np.isclose(v(s, 1000), 0.001)


def test_warmup():
    s = WarmupSchedule(warmup_steps=10, inner=FixedSchedule(0.1))
    assert v(s, 0) < v(s, 5) < v(s, 9)
    assert np.isclose(v(s, 10), 0.1)
    assert np.isclose(v(s, 500), 0.1)


def test_map_int_keys_roundtrip():
    from deeplearning4j_tpu import serde

    s = MapSchedule(ScheduleType.ITERATION, {0: 0.1, 100: 0.01})
    assert serde.from_json(serde.to_json(s)) == s
    assert np.isclose(v(s, 100), 0.01)


def test_jit_compatible():
    import jax

    s = StepSchedule(ScheduleType.ITERATION, 0.1, 0.5, 10)
    f = jax.jit(lambda t: s.value_at(t, 0))
    assert np.isclose(float(f(0)), 0.1)
    assert np.isclose(float(f(10)), 0.05)
