"""Quantized serving end to end (ISSUE 20): int8 post-training
calibration, the dequant-free kernel path, quantized versions in the
registry, the accuracy-armed canary gate, and the PRG208 lint rule.

Determinism invariants pinned here:
- same calibration set + seed -> same digest -> same ``q:`` AOT key
  token (recalibration mints a NEW executable, never a silent reuse);
- the quantized artifact is bit-identical across repeated
  ``quantize_for_inference`` calls and across a registry round-trip;
- a seeded accuracy regression rolls the canary back at the SAME
  request index across two fresh replays, with the f32 co-tenant
  byte-identical throughout;
- default-off is bitwise inert: no quant config means no ``:q:`` key
  token, byte-identical serving, zero new compiles.

All AOT assertions read counter DELTAS (the cache is process-global);
nets that must compile cold use hidden widths no other test uses.
"""

import dataclasses
import tempfile
import zipfile
from pathlib import Path

import jax
import numpy as np
import pytest

from deeplearning4j_tpu import kernels
from deeplearning4j_tpu.analysis import program as prog
from deeplearning4j_tpu.conf import Activation, InputType, WeightInit
from deeplearning4j_tpu.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.conf.layers_cnn import ConvolutionLayer
from deeplearning4j_tpu.conf.layers_quant import (
    QuantizationSpec,
    QuantizedDenseLayer,
)
from deeplearning4j_tpu.conf.losses import LossMCXENT
from deeplearning4j_tpu.conf.multilayer import NeuralNetConfiguration
from deeplearning4j_tpu.conf.updaters import Sgd
from deeplearning4j_tpu.kernels.registry import REGISTRY, MatmulEnvelope
from deeplearning4j_tpu.nn import inference_opt as iopt
from deeplearning4j_tpu.nn import io as nn_io
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.optimize import aot_cache
from deeplearning4j_tpu.parallel.batcher import BatchingConfig
from deeplearning4j_tpu.parallel.platform import (
    CanaryGate,
    ModelIntegrityError,
    ModelPlatform,
    ModelRegistry,
    TenantConfig,
)

pytestmark = pytest.mark.quant


@pytest.fixture(autouse=True)
def _fresh_tuning():
    kernels.TUNING.clear()
    yield
    kernels.TUNING.clear()


def _mlp(seed=3, n_in=9, hidden=27, n_out=4, act=Activation.RELU):
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Sgd(0.1))
            .weight_init(WeightInit.XAVIER).list()
            .layer(DenseLayer(n_out=hidden, activation=act))
            .layer(OutputLayer(n_out=n_out, activation=Activation.SOFTMAX,
                               loss_fn=LossMCXENT()))
            .set_input_type(InputType.feed_forward(n_in)).build())
    return MultiLayerNetwork(conf).init()


def _conv_mlp(seed=5, h=4, w=4, c=3, width=11, n_out=3):
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Sgd(0.1))
            .weight_init(WeightInit.XAVIER).list()
            .layer(ConvolutionLayer(n_out=width, kernel_size=(1, 1),
                                    activation=Activation.RELU))
            .layer(OutputLayer(n_out=n_out, activation=Activation.SOFTMAX,
                               loss_fn=LossMCXENT()))
            .set_input_type(InputType.convolutional(h, w, c)).build())
    return MultiLayerNetwork(conf).init()


def _batches(n_in, n=3, rows=16, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.normal(size=(rows, n_in)).astype(np.float32)
            for _ in range(n)]


def _leaves(tree):
    return [np.asarray(x) for x in jax.tree_util.tree_leaves(tree)]


def _tree_equal(a, b):
    la, lb = _leaves(a), _leaves(b)
    return (len(la) == len(lb)
            and all(x.dtype == y.dtype and np.array_equal(x, y)
                    for x, y in zip(la, lb)))


def _quantize(net, batches, **kw):
    rec = iopt.calibrate(net, batches, **kw)
    return iopt.quantize_for_inference(net, rec), rec


# --------------------------------------------------------------------------
# calibration determinism + key discipline
# --------------------------------------------------------------------------

def test_calibration_deterministic_same_digest_same_key():
    """Same calibration set + seed -> same digest -> same AOT key
    token; a different calibration set mints a NEW digest (and with it
    a new executable key)."""
    net = _mlp(hidden=27)
    batches = _batches(9)
    q1, r1 = _quantize(net, batches)
    q2, r2 = _quantize(net, batches)
    assert r1.digest == r2.digest
    assert _tree_equal(q1.params, q2.params)
    assert q1._qtag() == q2._qtag() == f":q:int8:{r1.digest[:8]}"
    # recalibration against different data = different digest/key
    _, r3 = _quantize(net, _batches(9, seed=99))
    assert r3.digest != r1.digest


def test_quantized_output_key_carries_qtag():
    net = _mlp(hidden=29)
    qnet, rec = _quantize(net, _batches(9))
    qnet.output(_batches(9, n=1, rows=4)[0])
    tok = f":q:int8:{rec.digest[:8]}"
    keys = [k[1] for k in aot_cache._EXECUTABLES]
    assert any(k.startswith("output") and tok in k for k in keys)


def test_default_off_bitwise_inert():
    """No quant config: no ``:q:`` token, no manifest quantization
    entry, byte-identical outputs, zero extra compiles on re-serve."""
    net = _mlp(hidden=31)
    assert net.conf.quantization is None
    assert net._qtag() == ""
    x = _batches(9, n=1, rows=4)[0]
    before = set(aot_cache._EXECUTABLES)
    y0 = np.asarray(net.output(x)).tobytes()
    minted = set(aot_cache._EXECUTABLES) - before
    assert minted and all(":q:" not in k[1] for k in minted)
    miss0 = aot_cache.stats()["misses"]
    assert np.asarray(net.output(x)).tobytes() == y0
    assert aot_cache.stats()["misses"] == miss0
    reg = ModelRegistry(tempfile.mkdtemp(prefix="dl4j_q_"))
    reg.publish("plain", net)
    assert "quantization" not in reg._read_manifest("plain")["versions"][0]


def test_quantize_rejects_mismatched_model():
    """The calibration record is pinned to the folded graph: quantizing
    a DIFFERENT architecture with it must refuse, not mis-scale."""
    net = _mlp(hidden=27)
    rec = iopt.calibrate(net, _batches(9))
    other = _mlp(hidden=33)
    with pytest.raises(ValueError, match="recalibrate"):
        iopt.quantize_for_inference(other, rec)


# --------------------------------------------------------------------------
# numerics: stock path + kernel parity
# --------------------------------------------------------------------------

def test_quantized_output_close_to_f32():
    net = _mlp(hidden=27)
    qnet, _ = _quantize(net, _batches(9))
    x = _batches(9, n=1, rows=8)[0]
    yf = np.asarray(net.output(x))
    yq = np.asarray(qnet.output(x))
    assert yq.dtype == yf.dtype
    np.testing.assert_allclose(yq, yf, atol=0.05)


def test_conv1x1_quantizes_and_tracks_f32():
    net = _conv_mlp(width=11)
    rng = np.random.default_rng(1)
    batches = [rng.normal(size=(8, 4, 4, 3)).astype(np.float32)
               for _ in range(3)]
    qnet, rec = _quantize(net, batches)
    names = [type(l).__name__ for l in qnet.conf.layers]
    assert names[0] == "QuantizedConv1x1Layer"
    assert names[-1] == "OutputLayer"      # output layer never quantized
    x = batches[0][:4]
    np.testing.assert_allclose(np.asarray(qnet.output(x)),
                               np.asarray(net.output(x)), atol=0.08)


def test_int8_kernel_parity_vs_lax_reference():
    """The Pallas int8 matmul+epilogue (interpret mode on CPU) against
    the ``jax.lax`` int8->int32 reference, across activations."""
    kern = REGISTRY.get("matmul_bias_act_int8")
    for act in ("identity", "relu"):
        env = MatmulEnvelope(m=16, k=24, n=16, dtype="int8",
                             backend="interpret", act=act)
        cands = kern.candidates(env)
        assert cands
        fn = jax.jit(kern.build(env, cands[0]))
        ref = jax.jit(kern.reference(env))
        args = kern.make_inputs(env, seed=3)
        np.testing.assert_allclose(np.asarray(fn(*args)),
                                   np.asarray(ref(*args)),
                                   rtol=1e-4, atol=1e-4)


def test_routed_quantized_model_matches_stock():
    """use_kernels + tuned int8 envelopes: the routed quantized forward
    must match the stock-XLA quantized forward (same int8 math, fused
    epilogue vs unfused — tight tolerance)."""
    net = _mlp(hidden=32, n_in=8)
    batches = _batches(8)
    qnet, _ = _quantize(net, batches)
    y_stock = np.asarray(qnet.output(batches[0][:8]))

    conf_on = dataclasses.replace(qnet.conf, use_kernels=True)
    planned = kernels.plan_envelopes(conf_on, 8)
    assert any(name == "matmul_bias_act_int8" for name, _ in planned)
    tuned = kernels.autotune_model(conf_on, 8, max_candidates=4)
    assert len(tuned) >= 1
    routed = MultiLayerNetwork(conf_on)
    routed.params, routed.state = qnet.params, qnet.state
    y_routed = np.asarray(routed.output(batches[0][:8]))
    np.testing.assert_allclose(y_routed, y_stock, rtol=1e-4, atol=1e-4)


# --------------------------------------------------------------------------
# registry round-trip + tamper refusal
# --------------------------------------------------------------------------

def test_registry_roundtrip_reverifies_digest():
    net = _mlp(hidden=34)
    qnet, rec = _quantize(net, _batches(9))
    reg = ModelRegistry(tempfile.mkdtemp(prefix="dl4j_q_"))
    reg.publish("m", net)
    v = reg.publish("m", qnet)
    ent = reg._read_manifest("m")["versions"][-1]
    assert ent["quantization"] == {"scheme": "int8",
                                   "calibration_digest": rec.digest}
    # restore after a simulated process restart (calibration registry
    # empty): load() re-registers the digest as live for PRG208
    iopt.clear_calibrations()
    restored, got_v = reg.load("m", v)
    assert got_v == v
    assert _tree_equal(restored.params, qnet.params)
    spec = restored.conf.quantization
    assert isinstance(spec, QuantizationSpec) and spec.digest == rec.digest
    live = iopt.lookup_calibration(rec.digest)
    assert live is not None and live.restored
    x = _batches(9, n=1, rows=4)[0]
    np.testing.assert_array_equal(np.asarray(restored.output(x)),
                                  np.asarray(qnet.output(x)))


def test_registry_tamper_refused():
    net = _mlp(hidden=35)
    qnet, rec = _quantize(net, _batches(9))
    reg = ModelRegistry(tempfile.mkdtemp(prefix="dl4j_q_"))
    v = reg.publish("m", qnet)
    # 1) flip a byte in the zip: sha256 refusal
    path = Path(reg._dir("m")) / f"v{v:04d}.zip"
    blob = bytearray(path.read_bytes())
    blob[len(blob) // 2] ^= 0xFF
    path.write_bytes(bytes(blob))
    with pytest.raises(ModelIntegrityError, match="sha256"):
        reg.load("m", v, retry=None)
    # 2) manifest quantization drift (digest swapped for another run's):
    #    zip is intact but metadata lies -> quantization mismatch refusal
    reg2 = ModelRegistry(tempfile.mkdtemp(prefix="dl4j_q_"))
    v2 = reg2.publish("m", qnet)
    man = reg2._read_manifest("m")
    man["versions"][-1]["quantization"]["calibration_digest"] = "0" * 64
    path2 = Path(reg2._dir("m")) / f"v{v2:04d}.zip"
    man["versions"][-1]["sha256"] = reg2.digest("m", v2)
    with reg2._model_lock("m"):
        reg2._write_manifest_locked("m", man)
    with pytest.raises(ModelIntegrityError, match="quantization metadata"):
        reg2.load("m", v2, retry=None)


# --------------------------------------------------------------------------
# warmup unification
# --------------------------------------------------------------------------

def test_warm_dtype_variants_single_source_of_truth():
    """nn.io.warm_dtype_variants IS the derivation: image inputs get
    (f32, uint8), flat inputs f32 only, and a QuantizationSpec adds NO
    client-visible variant (int8 is in-graph, keyed by the q: token)."""
    img = InputType.convolutional(4, 4, 3)
    ff = InputType.feed_forward(9)
    base = np.dtype(np.float32)
    u8 = np.dtype(np.uint8)
    assert nn_io.warm_dtype_variants([ff], base) == [(base,)]
    assert nn_io.warm_dtype_variants([img], base) == [(base,), (u8,)]
    spec = QuantizationSpec(scheme="int8", digest="ab" * 32, seed=0)
    assert (nn_io.warm_dtype_variants([img], base, quantization=spec)
            == [(base,), (u8,)])
    assert (nn_io.warm_dtype_variants([img, ff], base)
            == [(base, base), (u8, base)])


def test_engine_warm_sets_delegate_to_io():
    """The batcher derives its per-bucket warmup variants from the one
    nn.io source of truth — no parallel derivation to drift."""
    from deeplearning4j_tpu.parallel.batcher import InferenceEngine

    net = _mlp(hidden=36)
    eng = InferenceEngine(net, BatchingConfig(max_batch=4), graph_opt=False)
    try:
        expected = nn_io.warm_dtype_variants([None], eng._np_dtype,
                                             quantization=None)
        assert eng._warm_dtype_sets(1) == expected
    finally:
        eng.close()


def test_quantized_deploy_warm_zero_recompiles_first_traffic():
    """A deployed quantized version serves its FIRST request with zero
    compiles: deploy_canary warms the quantized executables up front."""
    net = _mlp(hidden=37)
    qnet, _ = _quantize(net, _batches(9))
    reg = ModelRegistry(tempfile.mkdtemp(prefix="dl4j_q_"))
    reg.publish("m", net)
    reg.publish("m", qnet)
    plat = ModelPlatform(reg, seed=11)
    cfg = TenantConfig(batching=BatchingConfig(max_batch=8))
    try:
        plat.deploy("m", version=1, config=cfg)
        plat.deploy_canary("m", version=2, fraction=1.0,
                           gate=CanaryGate(min_requests=4,
                                           max_accuracy_delta=0.5))
        miss0 = aot_cache.stats()["misses"]
        for i in range(12):
            plat.predict("m", _batches(9, n=1, rows=2, seed=50 + i)[0])
        assert aot_cache.stats()["misses"] == miss0
        st = plat.stats()["m"]["canary"]
        assert st["accuracy_samples"] > 0
        assert st["accuracy_max_delta"] < 0.5
        r = plat.promote("m")
        assert r["version"] == 2
        miss1 = aot_cache.stats()["misses"]
        for i in range(6):
            plat.predict("m", _batches(9, n=1, rows=2, seed=80 + i)[0])
        assert aot_cache.stats()["misses"] == miss1
    finally:
        plat.close()


# --------------------------------------------------------------------------
# accuracy-armed canary: deterministic regression rollback
# --------------------------------------------------------------------------

def _corrupted_copy(qnet, factor=10.0):
    bad = MultiLayerNetwork(qnet.conf)
    bad.params = {k: dict(v) for k, v in qnet.params.items()}
    bad.state, bad.opt_state = qnet.state, {}
    bad.params["0"]["scale"] = qnet.params["0"]["scale"] * factor
    return bad


def test_accuracy_regression_rolls_back_deterministically():
    """A mis-scaled quantized canary trips the accuracy arm at the SAME
    request index across two fresh replays (same platform seed, same
    traffic), while the f32 co-tenant stays byte-identical with zero
    recompiles after its warmup."""
    net = _mlp(hidden=38)
    co = _mlp(seed=9, hidden=39)
    qnet, _ = _quantize(net, _batches(9))
    bad = _corrupted_copy(qnet)
    reg = ModelRegistry(tempfile.mkdtemp(prefix="dl4j_q_"))
    reg.publish("m", net)
    reg.publish("co", co)
    cfg = TenantConfig(batching=BatchingConfig(max_batch=8))
    probe = _batches(9, n=1, rows=2, seed=7)[0]
    xs = [_batches(9, n=1, rows=2, seed=200 + i)[0] for i in range(40)]

    replays = []
    for _trial in range(2):
        plat = ModelPlatform(reg, seed=42)
        try:
            plat.deploy("m", version=1, config=cfg)
            plat.deploy("co", version=1, config=cfg)
            co_bytes = np.asarray(plat.predict("co", probe)).tobytes()
            plat.deploy_canary(
                "m", version=99, model=bad, fraction=0.5,
                gate=CanaryGate(min_requests=5, max_accuracy_delta=0.05))
            miss0 = aot_cache.stats()["misses"]
            rollback = None
            for i, x in enumerate(xs):
                plat.predict("m", x)
                assert (np.asarray(plat.predict("co", probe)).tobytes()
                        == co_bytes)
                lr = plat.stats()["m"].get("last_rollback")
                if lr:
                    rollback = lr
                    break
            assert rollback is not None, "accuracy arm never tripped"
            assert "accuracy arm" in rollback["reason"]
            assert aot_cache.stats()["misses"] == miss0
            replays.append(rollback["at_request"])
        finally:
            plat.close()
    assert replays[0] == replays[1]


# --------------------------------------------------------------------------
# PRG208 + PRG201 on quantized executables
# --------------------------------------------------------------------------

def _quant_artifact(qnet, rec, x):
    def out(params, state, xx, fmask):
        y, _, _ = qnet._forward(params, state, xx, train=False,
                                rng=None, fmask=fmask)
        return y

    return prog.trace_artifact(
        jax.jit(out), (qnet.params, qnet.state, x, None),
        graph_key="quant_fixture",
        fn_key=f"output:q:{rec.scheme}:{rec.digest[:8]}")


def test_prg208_negative_control_and_prg201_clean():
    """A live calibration record: the quantized serving executable
    lints clean — PRG208 resolves the token and the PRG201 donation
    audit has nothing to say."""
    net = _mlp(hidden=41)
    qnet, rec = _quantize(net, _batches(9))
    art = _quant_artifact(qnet, rec, _batches(9, n=1, rows=4)[0])
    findings = prog.lint_program(art)
    assert not [f for f in findings
                if f.rule in ("PRG208", "PRG201") and f.severity == "ERROR"]


def test_prg208_stale_digest_is_error():
    """Seeded defect: the calibration registry was cleared (a restart /
    recalibration) but an executable still carries the old token —
    stale artifact, ERROR."""
    net = _mlp(hidden=42)
    qnet, rec = _quantize(net, _batches(9))
    art = _quant_artifact(qnet, rec, _batches(9, n=1, rows=4)[0])
    iopt.clear_calibrations()
    try:
        found = [f for f in prog.lint_program(art) if f.rule == "PRG208"]
        assert any(f.severity == "ERROR"
                   and "does not resolve" in f.message for f in found)
    finally:
        iopt.register_calibration(rec)
    # re-registered: clean again
    assert not [f for f in prog.lint_program(art)
                if f.rule == "PRG208" and f.severity == "ERROR"]


def test_prg208_unknown_scheme_is_error():
    net = _mlp(hidden=43)
    qnet, rec = _quantize(net, _batches(9))
    art = _quant_artifact(qnet, rec, _batches(9, n=1, rows=4)[0])
    bad = dataclasses.replace(
        art, fn_key=f"output:q:int3:{rec.digest[:8]}")
    found = [f for f in prog.lint_program(bad) if f.rule == "PRG208"]
    assert any(f.severity == "ERROR" and "scheme" in f.message
               for f in found)
