"""Eval extras (ROCMultiClass, calibration), profiler, dataset fetchers."""

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.fetchers import (
    Cifar10DataSetIterator,
    EmnistDataSetIterator,
    SvhnDataSetIterator,
)
from deeplearning4j_tpu.eval.evaluation import (
    EvaluationCalibration,
    ROC,
    ROCMultiClass,
)
from deeplearning4j_tpu.profiler import (
    OpProfiler,
    ProfilerConfig,
    ProfilerListener,
)


# --------------------------------------------------------------------------
# eval extras
# --------------------------------------------------------------------------

def test_roc_multiclass_perfect_and_random(rng):
    n, c = 400, 3
    labels = np.eye(c, dtype=np.float32)[rng.integers(0, c, n)]
    # perfect predictions
    roc = ROCMultiClass()
    roc.eval(labels, labels * 0.9 + 0.05)
    assert roc.calculate_average_auc() == pytest.approx(1.0)
    # random predictions ~ 0.5
    r2 = ROCMultiClass()
    r2.eval(labels, rng.random((n, c)).astype(np.float32))
    assert 0.35 < r2.calculate_average_auc() < 0.65
    assert 0.0 <= r2.calculate_auprc(0) <= 1.0


def test_calibration_well_calibrated_vs_overconfident(rng):
    n, c = 4000, 2
    # well-calibrated: p = true probability used to draw the label
    p = rng.uniform(0.5, 0.99, n)
    y = (rng.random(n) < p).astype(int)
    labels = np.eye(2, dtype=np.float32)[y]
    preds = np.stack([1 - p, p], axis=1)
    cal = EvaluationCalibration()
    cal.eval(labels, preds)
    ece_good = cal.expected_calibration_error()

    # overconfident: always claims 0.99
    preds_bad = np.stack([np.full(n, 0.01), np.full(n, 0.99)], axis=1)
    cal2 = EvaluationCalibration()
    cal2.eval(labels, preds_bad)
    ece_bad = cal2.expected_calibration_error()
    assert ece_good < 0.05 < ece_bad
    acc = cal.reliability_accuracy()
    conf = cal.reliability_confidence()
    assert acc.shape == (10,) and conf.shape == (10,)


# --------------------------------------------------------------------------
# profiler
# --------------------------------------------------------------------------

def test_profiler_nan_panic_toggle():
    import jax
    import jax.numpy as jnp

    prof = OpProfiler.get_instance()
    prof.set_config(ProfilerConfig(check_for_nan=True))
    with pytest.raises(FloatingPointError):
        jax.jit(lambda x: jnp.log(x))(jnp.asarray(-1.0)).block_until_ready()
    prof.reset()
    # disabled again: silently produces nan
    v = jax.jit(lambda x: jnp.log(x))(jnp.asarray(-1.0))
    assert np.isnan(float(v))


def test_profiler_listener_collects_steps():
    from deeplearning4j_tpu.conf import Activation, InputType
    from deeplearning4j_tpu.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.conf.losses import LossMCXENT
    from deeplearning4j_tpu.conf.multilayer import NeuralNetConfiguration
    from deeplearning4j_tpu.conf.updaters import Sgd
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (NeuralNetConfiguration.builder().seed(1).updater(Sgd(0.1)).list()
            .layer(DenseLayer(n_out=4, activation=Activation.TANH))
            .layer(OutputLayer(n_out=2, activation=Activation.SOFTMAX,
                               loss_fn=LossMCXENT()))
            .set_input_type(InputType.feed_forward(3))
            .build())
    net = MultiLayerNetwork(conf)
    net.init()
    pl = ProfilerListener(warmup_iterations=1)
    net.set_listeners(pl)
    rng = np.random.default_rng(0)
    ds = DataSet(rng.normal(size=(8, 3)).astype(np.float32),
                 np.eye(2, dtype=np.float32)[rng.integers(0, 2, 8)])
    for _ in range(5):
        net.fit_batch(ds)
    assert len(pl.step_times) == 4  # deltas between 5 iters, minus warmup
    assert "mean=" in pl.summary()


# --------------------------------------------------------------------------
# fetchers
# --------------------------------------------------------------------------

def test_emnist_variants():
    for variant, classes in (("digits", 10), ("letters", 26),
                             ("balanced", 36)):
        it = EmnistDataSetIterator(variant, batch=16, num_examples=64)
        ds = next(iter(it))
        assert ds.features.shape == (16, 28, 28, 1)
        assert ds.labels.shape == (16, classes)
    with pytest.raises(ValueError):
        EmnistDataSetIterator("bogus", batch=4)


def test_cifar10_and_svhn_shapes():
    c = Cifar10DataSetIterator(batch=8, num_examples=32)
    ds = next(iter(c))
    assert ds.features.shape == (8, 32, 32, 3)
    assert ds.labels.shape == (8, 10)
    assert 0.0 <= ds.features.min() and ds.features.max() <= 1.0
    s = SvhnDataSetIterator(batch=8, num_examples=32)
    ds2 = next(iter(s))
    assert ds2.features.shape == (8, 32, 32, 3)


def test_synthetic_cifar_is_learnable():
    from deeplearning4j_tpu.conf import Activation, InputType
    from deeplearning4j_tpu.conf.layers import OutputLayer
    from deeplearning4j_tpu.conf.layers_cnn import (
        ConvolutionLayer, ConvolutionMode, PoolingType, SubsamplingLayer)
    from deeplearning4j_tpu.conf.losses import LossMCXENT
    from deeplearning4j_tpu.conf.multilayer import NeuralNetConfiguration
    from deeplearning4j_tpu.conf.updaters import Adam
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (NeuralNetConfiguration.builder().seed(1).updater(Adam(1e-3))
            .list()
            .layer(ConvolutionLayer(n_out=16, kernel_size=(3, 3),
                                    stride=(2, 2),
                                    activation=Activation.RELU,
                                    convolution_mode=ConvolutionMode.SAME))
            .layer(SubsamplingLayer(pooling_type=PoolingType.MAX,
                                    kernel_size=(2, 2), stride=(2, 2)))
            .layer(OutputLayer(n_out=10, activation=Activation.SOFTMAX,
                               loss_fn=LossMCXENT()))
            .set_input_type(InputType.convolutional(32, 32, 3))
            .build())
    net = MultiLayerNetwork(conf)
    net.init()
    train = Cifar10DataSetIterator(batch=64, num_examples=512, seed=5)
    net.fit(train, epochs=6)
    ev = net.evaluate(Cifar10DataSetIterator(batch=64, num_examples=256,
                                             train=False, seed=5))
    assert ev.accuracy() > 0.3  # well above 10% chance


def test_roc_multiclass_skips_absent_classes(rng):
    labels = np.eye(3, dtype=np.float32)[np.array([0, 1, 0, 1] * 20)]
    preds = labels * 0.9 + 0.05  # perfect, class 2 never appears
    roc = ROCMultiClass()
    roc.eval(labels, preds)
    assert roc.calculate_average_auc() == pytest.approx(1.0)


def test_u8_train_and_evaluate_consistent(rng):
    """uint8 batches must see the SAME conversion in fit, score, output,
    and evaluate (feed-forward input: plain cast — the [0,1] scaling is
    keyed to image-shaped InputTypes)."""
    from deeplearning4j_tpu.conf import Activation, InputType
    from deeplearning4j_tpu.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.conf.losses import LossMCXENT
    from deeplearning4j_tpu.conf.multilayer import NeuralNetConfiguration
    from deeplearning4j_tpu.conf.updaters import Adam
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.datasets.iterators import ArrayDataSetIterator
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (NeuralNetConfiguration.builder().seed(1).updater(Adam(1e-2))
            .list()
            .layer(DenseLayer(n_out=8, activation=Activation.TANH))
            .layer(OutputLayer(n_out=2, activation=Activation.SOFTMAX,
                               loss_fn=LossMCXENT()))
            .set_input_type(InputType.feed_forward(4))
            .build())
    net = MultiLayerNetwork(conf)
    net.init()
    x8 = rng.integers(0, 256, (32, 4), dtype=np.uint8)
    y = np.eye(2, dtype=np.float32)[(x8[:, 0] > 127).astype(int)]
    ds = DataSet(x8, y)
    for _ in range(40):
        net.fit_batch(ds)
    # output on uint8 must match output on the plain-cast floats
    out_u8 = np.asarray(net.output(x8))
    out_f = np.asarray(net.output(x8.astype(np.float32)))
    np.testing.assert_allclose(out_u8, out_f, rtol=1e-5, atol=1e-6)
    # and evaluate agrees with training-time performance (raw 0-255
    # inputs saturate tanh, so the bar is modest; consistency is the point)
    ev = net.evaluate(ArrayDataSetIterator(x8, y, batch=32))
    assert ev.accuracy() > 0.7
    # score() path too
    assert np.isfinite(net.score(ds))


def test_u8_token_ids_not_scaled(rng):
    """uint8 inputs to NON-image networks (e.g. embedding token ids) must
    keep their integer values (regression: blanket /255 broke embeddings)."""
    from deeplearning4j_tpu.conf import Activation, InputType
    from deeplearning4j_tpu.conf.layers import (EmbeddingSequenceLayer)
    from deeplearning4j_tpu.conf.layers_rnn import RnnOutputLayer
    from deeplearning4j_tpu.conf.multilayer import NeuralNetConfiguration
    from deeplearning4j_tpu.conf.updaters import Adam
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (NeuralNetConfiguration.builder().seed(1).updater(Adam(1e-2))
            .list()
            .layer(EmbeddingSequenceLayer(n_in=50, n_out=8))
            .layer(RnnOutputLayer(n_out=2))
            .set_input_type(InputType.recurrent(1, timesteps=6))
            .build())
    net = MultiLayerNetwork(conf)
    net.init()
    ids8 = rng.integers(0, 50, (4, 6), dtype=np.uint8)
    out_u8 = np.asarray(net.output(ids8))
    out_int = np.asarray(net.output(ids8.astype(np.int32)))
    np.testing.assert_allclose(out_u8, out_int, rtol=1e-5)


def test_u8_rnn_time_step_matches_output(rng):
    from deeplearning4j_tpu.conf import Activation, InputType
    from deeplearning4j_tpu.conf.layers_rnn import LSTM, RnnOutputLayer
    from deeplearning4j_tpu.conf.multilayer import NeuralNetConfiguration
    from deeplearning4j_tpu.conf.updaters import Adam
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (NeuralNetConfiguration.builder().seed(1).updater(Adam(1e-2))
            .list()
            .layer(LSTM(n_out=6))
            .layer(RnnOutputLayer(n_out=2))
            .set_input_type(InputType.recurrent(3, timesteps=5))
            .build())
    net = MultiLayerNetwork(conf)
    net.init()
    x = rng.normal(size=(2, 5, 3)).astype(np.float32)
    full = np.asarray(net.output(x))
    net.rnn_clear_previous_state()
    stream = np.concatenate(
        [np.asarray(net.rnn_time_step(x[:, t])) for t in range(5)], axis=1)
    np.testing.assert_allclose(stream, full, rtol=1e-4, atol=1e-5)
