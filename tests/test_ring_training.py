"""End-to-end sequence-parallel TRAINING with ring attention: the full
train step (attention + FFN + loss + grads + SGD) runs under shard_map over
a (data x sequence) mesh, with the time axis sharded across devices and
K/V rotating over the ring. The reference has no sequence parallelism at
all (SURVEY.md §5.7) — this locks in the TPU-native strengthening.

Oracle: the identical model trained unsharded on one device produces the
same losses/params (collectives are exact)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.ops.attention import reference_attention
from deeplearning4j_tpu.ops.ring import ring_attention_local
from deeplearning4j_tpu.parallel.mesh import shard_map

B, T, E, H = 4, 16, 16, 4
HD = E // H


def _init_params(key):
    ks = jax.random.split(key, 5)
    s = 0.3
    return {
        "wq": jax.random.normal(ks[0], (E, E)) * s,
        "wk": jax.random.normal(ks[1], (E, E)) * s,
        "wv": jax.random.normal(ks[2], (E, E)) * s,
        "wo": jax.random.normal(ks[3], (E, E)) * s,
        "w_out": jax.random.normal(ks[4], (E, 1)) * s,
    }


def _split_heads(x):
    b, t, e = x.shape
    return jnp.transpose(x.reshape(b, t, H, HD), (0, 2, 1, 3))


def _merge_heads(x):
    b, h, t, d = x.shape
    return jnp.transpose(x, (0, 2, 1, 3)).reshape(b, t, h * d)


def _forward(params, x, attn_fn):
    q = _split_heads(x @ params["wq"])
    k = _split_heads(x @ params["wk"])
    v = _split_heads(x @ params["wv"])
    a = _merge_heads(attn_fn(q, k, v))
    y = x + a @ params["wo"]
    return jnp.mean((y @ params["w_out"])[..., 0], axis=1)  # [b]


def _loss(params, x, targets, attn_fn):
    pred = _forward(params, x, attn_fn)
    return jnp.mean((pred - targets) ** 2)


@pytest.fixture
def mesh2d():
    # 2x2 (was 2x4 over T=32): the ring scan's compile time scales with
    # the sequence-shard count and dominated tier-1 (~133s for this one
    # test); 2 sequence shards still rotate K/V through a genuine
    # cross-device ring and 2 data shards still exercise the combined
    # reduction — same math, half the unrolled collective graph
    devs = np.array(jax.devices()[:4]).reshape(2, 2)
    return Mesh(devs, ("data", "sequence"))


@pytest.mark.slow
def test_ring_sharded_training_matches_unsharded(rng, mesh2d):
    # slow (round 6): ~60s of compile for one test on the 2-core CPU box;
    # the tier-1 870s budget is hard, ring-attention GRADIENT math stays
    # covered in tier-1 by test_attention.py::test_ring_gradients_match,
    # and this end-to-end parity run executes via ``pytest -m slow``.
    seq_n = mesh2d.shape["sequence"]
    params = _init_params(jax.random.PRNGKey(0))
    x = jnp.asarray(rng.standard_normal((B, T, E)), jnp.float32)
    targets = jnp.asarray(rng.standard_normal((B,)), jnp.float32)

    # ---- unsharded oracle: full attention on one device ----
    def ref_attn(q, k, v):
        return reference_attention(q, k, v, causal=True)

    def ref_step(params, x, targets):
        loss, g = jax.value_and_grad(
            lambda p: _loss(p, x, targets, ref_attn))(params)
        return jax.tree_util.tree_map(lambda p, gg: p - 0.1 * gg, params,
                                      g), loss

    # ---- sharded step: batch over 'data', TIME over 'sequence' ----
    def shard_step(params, x, targets):
        def local(params, xl, tl):
            # xl: [B/2, T/4, E] — this shard's batch rows + time slice
            def attn(q, k, v):
                return ring_attention_local(
                    q, k, v, None, axis_name="sequence", axis_size=seq_n,
                    causal=True)

            def loss_fn(p):
                pred_part = _forward_partial(p, xl, attn)
                # time axis is sharded: psum completes the time-mean
                pred = jax.lax.psum(pred_part, "sequence")
                # normalize by the GLOBAL batch: under vma jax, params
                # are replicated so shard_map's AD already psums their
                # cotangents over every mesh axis — per-shard grads come
                # out as the full global gradient with no manual
                # collective (check_rep jax needs the explicit reduction
                # below)
                return jnp.sum((pred - tl) ** 2) / B

            loss, g = jax.value_and_grad(loss_fn)(params)
            if not (hasattr(jax, "typeof") and hasattr(jax.lax, "pcast")):
                # check_rep jax: per-shard AD leaves partial grads (and
                # the old psum transpose scales the sequence path by
                # seq_n) — reduce to the global gradient explicitly
                g = jax.tree_util.tree_map(
                    lambda v: jax.lax.psum(v, ("data", "sequence"))
                    / seq_n, g)
            loss = jax.lax.psum(loss, "data")  # global loss value
            return jax.tree_util.tree_map(
                lambda p, gg: p - 0.1 * gg, params, g), loss

        return shard_map(
            local, mesh2d,
            in_specs=(P(), P("data", "sequence"), P("data")),
            out_specs=(P(), P()))(params, x, targets)

    def _forward_partial(params, xl, attn_fn):
        """Per-shard forward over the LOCAL time slice; emits this shard's
        contribution to the (global) time-mean prediction."""
        q = _split_heads(xl @ params["wq"])
        k = _split_heads(xl @ params["wk"])
        v = _split_heads(xl @ params["wv"])
        a = _merge_heads(attn_fn(q, k, v))
        y = xl + a @ params["wo"]
        return jnp.sum((y @ params["w_out"])[..., 0], axis=1) / T

    p_ref = params
    p_shard = params
    ref_losses, shard_losses = [], []
    for _ in range(5):
        p_ref, lr_ = ref_step(p_ref, x, targets)
        p_shard, ls_ = shard_step(p_shard, x, targets)
        ref_losses.append(float(lr_))
        shard_losses.append(float(ls_))

    np.testing.assert_allclose(shard_losses, ref_losses, rtol=2e-4,
                               atol=1e-6)
    for k in p_ref:
        np.testing.assert_allclose(np.asarray(p_shard[k]),
                                   np.asarray(p_ref[k]), rtol=5e-4,
                                   atol=1e-5)
    assert ref_losses[-1] < ref_losses[0]  # it actually learns
