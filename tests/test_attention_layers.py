"""Attention layer confs: shapes, masking, serde round-trip, gradient checks
(reference: ``AttentionLayerTest`` gradient checks in
``deeplearning4j-core/.../gradientcheck/``)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from deeplearning4j_tpu import serde
from deeplearning4j_tpu.conf import InputType, WeightInit
from deeplearning4j_tpu.conf.graph import AttentionVertex
from deeplearning4j_tpu.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.conf.layers_attention import (
    LearnedSelfAttentionLayer, RecurrentAttentionLayer, SelfAttentionLayer)
from deeplearning4j_tpu.conf.layers_rnn import RnnOutputLayer
from deeplearning4j_tpu.conf.multilayer import NeuralNetConfiguration
from deeplearning4j_tpu.conf.updaters import NoOp
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.util.gradcheck import gradient_check

KEY = jax.random.PRNGKey(0)


def _seq_data(n=4, t=5, f=3, classes=2, masked=True, seed=0, label_t=None):
    rng = np.random.default_rng(seed)
    feats = rng.normal(size=(n, t, f)).astype(np.float32)
    lt = label_t or t
    labels = np.eye(classes, dtype=np.float32)[
        rng.integers(0, classes, (n, lt))]
    if not masked:
        return DataSet(feats, labels)
    mask = np.ones((n, t), np.float32)
    mask[0, 3:] = 0.0
    feats[0, 3:] = 0.0
    lmask = mask if lt == t else np.ones((n, lt), np.float32)
    return DataSet(feats, labels, features_mask=mask, labels_mask=lmask)


def test_self_attention_shapes_and_mask():
    layer = SelfAttentionLayer(n_out=8, n_heads=2)
    t = InputType.recurrent(3, timesteps=5)
    assert layer.output_type(t) == InputType.recurrent(8, timesteps=5)
    params = layer.init(KEY, t)
    assert params["Wq"].shape == (3, 8) and params["Wo"].shape == (8, 8)
    x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 5, 3)),
                    jnp.float32)
    mask = jnp.asarray([[1, 1, 1, 0, 0], [1, 1, 1, 1, 1]], jnp.float32)
    y, _ = layer.forward(params, {}, x, mask=mask)
    assert y.shape == (2, 5, 8)
    # masked-out timesteps emit zeros
    np.testing.assert_allclose(np.asarray(y[0, 3:]), 0.0)
    # masked keys don't affect valid outputs: change masked input, same out
    x2 = x.at[0, 3:].set(99.0)
    y2, _ = layer.forward(params, {}, x2, mask=mask)
    np.testing.assert_allclose(np.asarray(y[0, :3]), np.asarray(y2[0, :3]),
                               atol=1e-6)


def test_learned_self_attention_fixed_output_length():
    layer = LearnedSelfAttentionLayer(n_out=8, n_heads=2, n_queries=4)
    t = InputType.recurrent(3, timesteps=7)
    assert layer.output_type(t) == InputType.recurrent(8, timesteps=4)
    params = layer.init(KEY, t)
    assert params["Q"].shape == (4, 8)
    x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 7, 3)),
                    jnp.float32)
    y, _ = layer.forward(params, {}, x)
    assert y.shape == (2, 4, 8)


def test_recurrent_attention_shapes():
    layer = RecurrentAttentionLayer(n_out=6, n_heads=2)
    t = InputType.recurrent(3, timesteps=5)
    assert layer.output_type(t) == InputType.recurrent(6, timesteps=5)
    params = layer.init(KEY, t)
    x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 5, 3)),
                    jnp.float32)
    y, _ = layer.forward(params, {}, x)
    assert y.shape == (2, 5, 6)


@pytest.mark.parametrize("layer_fn", [
    lambda: SelfAttentionLayer(n_out=4, n_heads=2,
                               attention_impl="reference"),
    lambda: SelfAttentionLayer(n_out=4, n_heads=1, project_input=True,
                               causal=True, attention_impl="reference"),
    lambda: RecurrentAttentionLayer(n_out=4, n_heads=2),
])
def test_attention_gradients(layer_fn):
    conf = (NeuralNetConfiguration.builder()
            .seed(12345)
            .updater(NoOp())
            .weight_init(WeightInit.XAVIER)
            .list()
            .layer(layer_fn())
            .layer(RnnOutputLayer(n_out=2))
            .set_input_type(InputType.recurrent(3, timesteps=5))
            .build())
    res = gradient_check(conf, _seq_data(), n_samples=60)
    assert res.passed, res.summary()


def test_learned_attention_gradients():
    conf = (NeuralNetConfiguration.builder()
            .seed(12345)
            .updater(NoOp())
            .list()
            .layer(LearnedSelfAttentionLayer(n_out=4, n_heads=2, n_queries=3,
                                             attention_impl="reference"))
            .layer(RnnOutputLayer(n_out=2))
            .set_input_type(InputType.recurrent(3, timesteps=5))
            .build())
    res = gradient_check(conf, _seq_data(label_t=3), n_samples=60)
    assert res.passed, res.summary()


def test_serde_round_trip():
    for layer in (SelfAttentionLayer(n_out=8, n_heads=2, head_size=4),
                  LearnedSelfAttentionLayer(n_out=8, n_queries=5),
                  RecurrentAttentionLayer(n_out=6, n_heads=3)):
        js = serde.to_json(layer)
        back = serde.from_json(js)
        assert back == layer


def test_attention_vertex_forward_and_mask():
    v = AttentionVertex(n_out=8, n_heads=2)
    tq = InputType.recurrent(3, timesteps=4)
    tk = InputType.recurrent(5, timesteps=6)
    assert v.output_type([tq, tk, tk]) == InputType.recurrent(8, timesteps=4)
    params = v.init(KEY, [tq, tk, tk])
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(2, 4, 3)), jnp.float32)
    kv = jnp.asarray(rng.normal(size=(2, 6, 5)), jnp.float32)
    mask = jnp.ones((2, 6), jnp.float32).at[0, 4:].set(0.0)
    y, _ = v.forward(params, {}, [q, kv, kv, mask])
    assert y.shape == (2, 4, 8)
    kv2 = kv.at[0, 4:].set(7.0)
    y2, _ = v.forward(params, {}, [q, kv2, kv2, mask])
    np.testing.assert_allclose(np.asarray(y[0]), np.asarray(y2[0]), atol=1e-6)
