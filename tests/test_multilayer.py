"""MultiLayerNetwork end-to-end: builder DSL, fit/output/evaluate,
serialization round-trip (reference oracle: deeplearning4j-core tests +
MultiLayerTest, SURVEY.md §4)."""

import numpy as np
import pytest

from deeplearning4j_tpu.conf import Activation, InputType, WeightInit
from deeplearning4j_tpu.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.conf.layers_cnn import (
    BatchNormalization,
    ConvolutionLayer,
    ConvolutionMode,
    PoolingType,
    SubsamplingLayer,
)
from deeplearning4j_tpu.conf.losses import LossMCXENT, LossMSE
from deeplearning4j_tpu.conf.multilayer import (
    MultiLayerConfiguration,
    NeuralNetConfiguration,
)
from deeplearning4j_tpu.conf.updaters import Adam, Sgd
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import ArrayDataSetIterator
from deeplearning4j_tpu.datasets.mnist import IrisDataSetIterator
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.optimize.listeners import (
    CollectScoresListener,
    ScoreIterationListener,
)
from deeplearning4j_tpu.util import serializer


def iris_conf(seed=12345):
    return (NeuralNetConfiguration.builder()
            .seed(seed)
            .updater(Adam(learning_rate=0.02))
            .weight_init(WeightInit.XAVIER)
            .list()
            .layer(DenseLayer(n_out=16, activation=Activation.RELU))
            .layer(OutputLayer(n_out=3, activation=Activation.SOFTMAX,
                               loss_fn=LossMCXENT()))
            .set_input_type(InputType.feed_forward(4))
            .build())


def test_config_json_roundtrip():
    conf = iris_conf()
    js = conf.to_json()
    conf2 = MultiLayerConfiguration.from_json(js)
    assert conf2 == conf


def test_network_init_and_summary():
    net = MultiLayerNetwork(iris_conf()).init()
    assert net.num_params() == (4 * 16 + 16) + (16 * 3 + 3)
    s = net.summary()
    assert "DenseLayer" in s and "Total params" in s


def test_fit_iris_converges_and_evaluates():
    it = IrisDataSetIterator(batch=150)
    net = MultiLayerNetwork(iris_conf()).init()
    scores = CollectScoresListener()
    net.set_listeners(scores)
    net.fit(it, epochs=150)
    assert scores.scores[-1] < scores.scores[0] * 0.5
    ev = net.evaluate(it)
    assert ev.accuracy() > 0.9, ev.stats()


def test_fit_arrays_api_and_score():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 4)).astype(np.float32)
    w_true = rng.normal(size=(4, 2)).astype(np.float32)
    y = x @ w_true
    conf = (NeuralNetConfiguration.builder()
            .seed(1)
            .updater(Sgd(learning_rate=0.1))
            .list()
            .layer(OutputLayer(n_out=2, activation=Activation.IDENTITY,
                               loss_fn=LossMSE()))
            .set_input_type(InputType.feed_forward(4))
            .build())
    net = MultiLayerNetwork(conf).init()
    ds = DataSet(x, y)
    s0 = net.score(ds)
    net.fit(x, y, epochs=200)
    assert net.score(ds) < s0 * 0.01


def test_flat_params_roundtrip():
    net = MultiLayerNetwork(iris_conf()).init()
    flat = net.params_flat()
    out_before = np.asarray(net.output(np.ones((1, 4), np.float32)))
    flat2 = flat * 0.0
    net.set_params_flat(flat2)
    assert np.allclose(net.params_flat(), 0.0)
    net.set_params_flat(flat)
    out_after = np.asarray(net.output(np.ones((1, 4), np.float32)))
    np.testing.assert_allclose(out_before, out_after, rtol=1e-6)


def test_model_serializer_roundtrip(tmp_path):
    it = IrisDataSetIterator(batch=150)
    net = MultiLayerNetwork(iris_conf()).init()
    net.fit(it, epochs=5)
    path = tmp_path / "model.zip"
    serializer.write_model(net, path)
    net2 = serializer.restore_multi_layer_network(path)
    assert net2.conf == net.conf
    assert net2.iteration == net.iteration
    x = np.ones((2, 4), np.float32)
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               np.asarray(net2.output(x)), rtol=1e-6)
    # exact resume: continue training both, scores match
    ds = next(iter(it))
    s1 = net.fit_batch(ds)
    s2 = net2.fit_batch(ds)
    assert np.isclose(s1, s2, rtol=1e-5)


def test_cnn_pipeline_with_preprocessor_and_bn():
    conf = (NeuralNetConfiguration.builder()
            .seed(7)
            .updater(Adam(learning_rate=0.01))
            .list()
            .layer(ConvolutionLayer(n_out=4, kernel_size=(3, 3),
                                    convolution_mode=ConvolutionMode.SAME,
                                    activation=Activation.RELU))
            .layer(BatchNormalization())
            .layer(SubsamplingLayer(pooling_type=PoolingType.MAX))
            .layer(DenseLayer(n_out=8, activation=Activation.RELU))
            .layer(OutputLayer(n_out=2))
            .set_input_type(InputType.convolutional(8, 8, 1))
            .build())
    # preprocessor auto-inserted between pool and dense
    names = [type(l).__name__ for l in conf.layers]
    assert "CnnToFeedForwardPreProcessor" in names
    net = MultiLayerNetwork(conf).init()
    x = np.random.default_rng(0).normal(size=(4, 8, 8, 1)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[[0, 1, 0, 1]]
    net.fit(x, y, epochs=3)
    out = np.asarray(net.output(x))
    assert out.shape == (4, 2)
    np.testing.assert_allclose(out.sum(-1), 1.0, rtol=1e-4)  # softmax


def test_listeners_fire():
    import io

    buf = io.StringIO()
    it = IrisDataSetIterator(batch=150)
    net = MultiLayerNetwork(iris_conf()).init()
    net.set_listeners(ScoreIterationListener(1, stream=buf))
    net.fit(it, epochs=2)
    assert "Score at iteration" in buf.getvalue()


def test_per_layer_updater_override():
    conf = (NeuralNetConfiguration.builder()
            .seed(3)
            .updater(Sgd(learning_rate=0.0))  # global: frozen
            .list()
            .layer(DenseLayer(n_out=4, updater=Sgd(learning_rate=0.5)))
            .layer(OutputLayer(n_out=2))
            .set_input_type(InputType.feed_forward(3))
            .build())
    net = MultiLayerNetwork(conf).init()
    # locate dense and output layer indices in built conf
    w0_before = np.asarray(net.params["0"]["W"]).copy()
    w_out_key = str(len(conf.layers) - 1)
    w1_before = np.asarray(net.params[w_out_key]["W"]).copy()
    x = np.random.default_rng(0).normal(size=(8, 3)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[[0, 1] * 4]
    net.fit(x, y, epochs=1)
    assert not np.allclose(np.asarray(net.params["0"]["W"]), w0_before)
    np.testing.assert_allclose(np.asarray(net.params[w_out_key]["W"]),
                               w1_before)  # global lr=0 -> unchanged


def test_builder_does_not_mutate_caller_layers():
    shared = SubsamplingLayer()
    dense = DenseLayer(n_out=4)
    for _ in range(2):
        (NeuralNetConfiguration.builder().list()
         .layer(ConvolutionLayer(n_out=2, kernel_size=(3, 3),
                                 convolution_mode=ConvolutionMode.SAME))
         .layer(shared)
         .layer(dense)
         .layer(OutputLayer(n_out=2))
         .set_input_type(InputType.convolutional(8, 8, 1))
         .build())
    assert shared.name is None and dense.name is None


def test_score_uses_eval_mode_batchnorm():
    conf = (NeuralNetConfiguration.builder()
            .seed(5)
            .list()
            .layer(BatchNormalization())
            .layer(OutputLayer(n_out=2, loss_fn=LossMCXENT()))
            .set_input_type(InputType.feed_forward(3))
            .build())
    net = MultiLayerNetwork(conf).init()
    x = np.random.default_rng(1).normal(7.0, 0.1, (4, 3)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[[0, 1, 0, 1]]
    # untrained running stats are mean=0/var=1; eval-mode score must differ
    # hugely from a train-mode (batch-normalized) score on shifted data
    s_eval = net.score(DataSet(x, y))
    grads, s_train_mode = net.compute_gradient_and_score(DataSet(x, y))
    assert abs(s_eval - s_train_mode) > 0.1


def test_bfloat16_dtype_trains():
    """conf.dtype('bfloat16'): params live in bf16 and training converges
    (the reference's Nd4j.setDefaultDataTypes HALF/BFLOAT16 analog)."""
    import jax.numpy as jnp
    import numpy as np

    from deeplearning4j_tpu.conf import Activation, InputType
    from deeplearning4j_tpu.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.conf.losses import LossMCXENT
    from deeplearning4j_tpu.conf.multilayer import NeuralNetConfiguration
    from deeplearning4j_tpu.conf.updaters import Adam
    from deeplearning4j_tpu.datasets.dataset import DataSet

    conf = (NeuralNetConfiguration.builder()
            .seed(1).updater(Adam(1e-2)).dtype("bfloat16").list()
            .layer(DenseLayer(n_out=8, activation=Activation.TANH))
            .layer(OutputLayer(n_out=2, activation=Activation.SOFTMAX,
                               loss_fn=LossMCXENT()))
            .set_input_type(InputType.feed_forward(4))
            .build())
    net = MultiLayerNetwork(conf)
    net.init()
    assert net.params["0"]["W"].dtype == jnp.bfloat16
    rng = np.random.default_rng(0)
    x = rng.normal(size=(16, 4)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 16)]
    ds = DataSet(x, y)
    s0 = net.fit_batch(ds)
    for _ in range(20):
        s1 = net.fit_batch(ds)
    assert s1 < s0


def test_gradient_checkpointing_matches_standard():
    """.gradient_checkpointing(True): same loss/grads (remat changes
    memory, not math)."""
    import numpy as np

    from deeplearning4j_tpu.conf import Activation, InputType
    from deeplearning4j_tpu.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.conf.losses import LossMCXENT
    from deeplearning4j_tpu.conf.multilayer import NeuralNetConfiguration
    from deeplearning4j_tpu.conf.updaters import Sgd
    from deeplearning4j_tpu.datasets.dataset import DataSet

    def build(remat):
        b = (NeuralNetConfiguration.builder()
             .seed(7).updater(Sgd(0.1)).list()
             .layer(DenseLayer(n_out=8, activation=Activation.TANH))
             .layer(DenseLayer(n_out=8, activation=Activation.RELU))
             .layer(OutputLayer(n_out=3, activation=Activation.SOFTMAX,
                                loss_fn=LossMCXENT())))
        if remat:
            b.gradient_checkpointing(True)
        b.set_input_type(InputType.feed_forward(4))
        net = MultiLayerNetwork(b.build())
        net.init()
        return net

    a, b = build(False), build(True)
    assert b.conf.gradient_checkpointing
    rng = np.random.default_rng(0)
    x = rng.normal(size=(16, 4)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 16)]
    ds = DataSet(x, y)
    for _ in range(5):
        la = a.fit_batch(ds)
        lb = b.fit_batch(ds)
    np.testing.assert_allclose(la, lb, rtol=1e-5)
    np.testing.assert_allclose(a.params_flat(), b.params_flat(), rtol=1e-5)


def test_dataset_device_writeback_and_migrate():
    """Fitting writes staged device arrays back into the DataSet (reference
    DataSet#migrate semantics): a reused batch transfers once."""
    import jax

    from deeplearning4j_tpu.conf import Activation, InputType
    from deeplearning4j_tpu.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.conf.losses import LossMCXENT
    from deeplearning4j_tpu.conf.multilayer import NeuralNetConfiguration
    from deeplearning4j_tpu.conf.updaters import Sgd
    from deeplearning4j_tpu.datasets.dataset import DataSet

    conf = (NeuralNetConfiguration.builder().seed(1).updater(Sgd(0.1)).list()
            .layer(DenseLayer(n_out=8, activation=Activation.RELU))
            .layer(OutputLayer(n_out=2, activation=Activation.SOFTMAX,
                               loss_fn=LossMCXENT()))
            .set_input_type(InputType.feed_forward(4)).build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    ds = DataSet(rng.normal(size=(16, 4)).astype(np.float32),
                 np.eye(2, dtype=np.float32)[rng.integers(0, 2, 16)])
    assert isinstance(ds.features, np.ndarray)
    l1 = net.fit_batch(ds)
    assert isinstance(ds.features, jax.Array)  # written back
    assert isinstance(ds.labels, jax.Array)
    l2 = net.fit_batch(ds)  # second pass: no host->device transfer
    assert np.isfinite(l1) and np.isfinite(l2)

    ds.detach()
    assert isinstance(ds.features, np.ndarray)
    ds.migrate()
    assert isinstance(ds.features, jax.Array)


def test_score_does_not_mutate_dataset():
    """Only the fit path writes device arrays back; score()/evaluate()
    leave the caller's host arrays untouched."""
    from deeplearning4j_tpu.conf import Activation, InputType
    from deeplearning4j_tpu.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.conf.losses import LossMCXENT
    from deeplearning4j_tpu.conf.multilayer import NeuralNetConfiguration
    from deeplearning4j_tpu.conf.updaters import Sgd
    from deeplearning4j_tpu.datasets.dataset import DataSet

    conf = (NeuralNetConfiguration.builder().seed(1).updater(Sgd(0.1)).list()
            .layer(DenseLayer(n_out=4, activation=Activation.RELU))
            .layer(OutputLayer(n_out=2, activation=Activation.SOFTMAX,
                               loss_fn=LossMCXENT()))
            .set_input_type(InputType.feed_forward(3)).build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    ds = DataSet(rng.normal(size=(8, 3)).astype(np.float32),
                 np.eye(2, dtype=np.float32)[rng.integers(0, 2, 8)])
    net.score(ds)
    assert isinstance(ds.features, np.ndarray)
    assert isinstance(ds.labels, np.ndarray)
