"""Pallas kernel subsystem tests (kernels/): registry parity against the
XLA references, per-shape autotuner + persistent digest-verified tuning
cache, cache-keyed selection through the model fit paths, PRG207, and
the capability probe-and-skip discipline.

Every kernel here executes through the Pallas INTERPRETER (no TPU in
CI) — the same kernel bodies a TPU run lowers through Mosaic, so the
numerics and the selection/fallback/re-key machinery are validated end
to end; only the real-lowering leg probes and skips.
"""

import json
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu import kernels
from deeplearning4j_tpu.conf import inputs as it
from deeplearning4j_tpu.conf.activations import Activation
from deeplearning4j_tpu.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.conf.layers_cnn import (
    ConvolutionLayer,
    ConvolutionMode,
    FusedConvBN1x1,
)
from deeplearning4j_tpu.conf.multilayer import NeuralNetConfiguration
from deeplearning4j_tpu.conf.updaters import Adam
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.kernels.registry import (
    AttentionEnvelope,
    MatmulEnvelope,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.optimize import aot_cache

pytestmark = pytest.mark.kernels


@pytest.fixture(autouse=True)
def _fresh_tuning():
    kernels.TUNING.clear()
    yield
    kernels.TUNING.clear()


def _env(m, k, n, dtype="float32", act="identity"):
    return MatmulEnvelope(m=m, k=k, n=n, dtype=dtype,
                          backend="interpret", act=act)


def _leaves(tree):
    return [np.asarray(x) for x in jax.tree_util.tree_leaves(tree)]


def _max_delta(a, b):
    return max(float(np.max(np.abs(x - y))) if x.size else 0.0
               for x, y in zip(_leaves(a), _leaves(b)))


def _conv_dense_conf(use_kernels, width=16, seed=7, compute_dtype=None,
                     act=Activation.RELU):
    b = NeuralNetConfiguration.builder().seed(seed).updater(
        Adam(learning_rate=1e-3))
    if compute_dtype:
        b = b.compute_dtype(compute_dtype)
    if use_kernels:
        b = b.use_kernels()
    return (b.list()
            .layer(FusedConvBN1x1(n_out=8, activation=act))
            .layer(DenseLayer(n_out=width, activation=act))
            .layer(OutputLayer(n_out=4))
            .set_input_type(it.Convolutional(4, 4, 3))
            .build())


def _batch(batch=8, seed=0, classes=4, img=4, chans=3):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(batch, img, img, chans)).astype(np.float32)
    Y = np.eye(classes, dtype=np.float32)[rng.integers(0, classes, batch)]
    return X, Y


def _fit(net, X, Y, steps=3):
    for _ in range(steps):
        net.fit_batch(DataSet(X.copy(), Y.copy()))
    return net


# --------------------------------------------------------------------------
# capability probe + skip discipline
# --------------------------------------------------------------------------

def test_capability_probe():
    cap = kernels.capability()
    assert cap in ("tpu", "interpret", "none")
    # this container has pallas importable -> at least interpret mode
    assert cap != "none"
    assert kernels.backend() in ("tpu", "interpret")


@pytest.mark.skipif(kernels.capability() != "tpu",
                    reason="no real Pallas TPU lowering in this container "
                           "(interpret mode covers the kernel bodies)")
def test_real_tpu_lowering_compiles():
    env = _env(128, 128, 128)
    env = MatmulEnvelope(m=env.m, k=env.k, n=env.n, dtype=env.dtype,
                         backend="tpu", act="relu")
    k = kernels.REGISTRY.get("matmul_bias_act")
    fn = jax.jit(k.build(env, (128, 128, 128)))
    jax.block_until_ready(fn(*k.make_inputs(env)))


# --------------------------------------------------------------------------
# numerical parity: every registry kernel vs its XLA reference
# --------------------------------------------------------------------------

@pytest.mark.parametrize("act", ["identity", "relu", "tanh"])
def test_matmul_bias_act_parity_f32(act):
    env = _env(32, 24, 16, act=act)
    k = kernels.REGISTRY.get("matmul_bias_act")
    assert k.supports(env)
    args = k.make_inputs(env, seed=3)
    ref = np.asarray(k.reference(env)(*args))
    for tiling in k.candidates(env, limit=4):
        got = np.asarray(k.build(env, tiling)(*args))
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


def test_matmul_bias_act_parity_bf16():
    env = _env(16, 32, 8, dtype="bfloat16", act="relu")
    k = kernels.REGISTRY.get("matmul_bias_act")
    args = k.make_inputs(env, seed=4)
    ref = np.asarray(k.reference(env)(*args), np.float32)
    got = np.asarray(k.build(env, k.candidates(env, limit=1)[0])(*args),
                     np.float32)
    # bf16 storage: the kernel accumulates f32 and rounds once, the
    # reference rounds per-op — agreement to bf16 resolution
    np.testing.assert_allclose(got, ref, rtol=0.05, atol=0.1)


def test_matmul_stats_parity():
    env = _env(64, 16, 8)
    k = kernels.REGISTRY.get("conv_bn_act")
    args = k.make_inputs(env, seed=5)
    ry, rs, rq = (np.asarray(a) for a in k.reference(env)(*args))
    for tiling in k.candidates(env, limit=4):
        y, s, q = (np.asarray(a)
                   for a in k.build(env, tiling)(*args))
        np.testing.assert_allclose(y, ry, rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(s, rs, rtol=1e-4, atol=1e-3)
        np.testing.assert_allclose(q, rq, rtol=1e-4, atol=1e-3)


def test_kernel_gradients_match_reference():
    env = _env(16, 8, 8, act="tanh")
    k = kernels.REGISTRY.get("matmul_bias_act")
    tiling = k.candidates(env, limit=1)[0]
    x, w, b = k.make_inputs(env, seed=6)

    def loss_k(x, w, b):
        return jnp.sum(k.build(env, tiling)(x, w, b) ** 2)

    def loss_r(x, w, b):
        return jnp.sum(k.reference(env)(x, w, b) ** 2)

    gk = jax.grad(loss_k, argnums=(0, 1, 2))(x, w, b)
    gr = jax.grad(loss_r, argnums=(0, 1, 2))(x, w, b)
    for a, b_ in zip(gk, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-4, atol=1e-4)


# --------------------------------------------------------------------------
# autotuner + tuning cache
# --------------------------------------------------------------------------

def test_autotune_records_winner_and_counters():
    from deeplearning4j_tpu import telemetry

    telemetry.reset()
    env = _env(32, 16, 8, act="relu")
    k = kernels.REGISTRY.get("matmul_bias_act")
    res = kernels.autotune(k, env, max_candidates=4)
    assert res.tiling in [tuple(t) for t in k.candidates(env, limit=4)]
    win = kernels.TUNING.winner("matmul_bias_act", env.key)
    assert tuple(win["tiling"]) == res.tiling
    snap = telemetry.REGISTRY.snapshot(run_collectors=False)
    trials = snap.get(
        'dl4j_kernel_autotune_trials_total{kernel="matmul_bias_act"}', 0)
    assert trials >= len([r for r in res.trials])
    assert snap.get(
        'dl4j_kernel_autotune_winners_total{kernel="matmul_bias_act"}',
        0) >= 1
    assert snap.get("dl4j_kernel_tuning_cache_entries", 0) >= 1


def test_tuning_digest_tracks_winner_set():
    d0 = kernels.tuning_digest("matmul_bias_act")
    env = _env(32, 16, 8)
    kernels.TUNING.record("matmul_bias_act", env.key, (8, 8, 8), 1.0)
    d1 = kernels.tuning_digest("matmul_bias_act")
    assert d0 != d1
    # a DIFFERENT winner for the same envelope re-digests again
    kernels.TUNING.record("matmul_bias_act", env.key, (16, 8, 8), 0.9)
    assert kernels.tuning_digest("matmul_bias_act") not in (d0, d1)


def test_winner_persists_on_disk_and_reloads(tmp_path):
    path = str(tmp_path / "tuning.json")
    kernels.set_tuning_cache(path)
    env = _env(32, 16, 8, act="relu")
    k = kernels.REGISTRY.get("matmul_bias_act")
    res = kernels.autotune(k, env, max_candidates=4)
    # a FRESH cache object (a new process's view) loads the same winner
    fresh = kernels.TuningCache().bind(path)
    assert tuple(fresh.winner("matmul_bias_act",
                              env.key)["tiling"]) == res.tiling
    # and a fresh registry over it derives the same digest -> the same
    # kern:<id>:<digest> key tokens -> warmed executables stay valid
    from deeplearning4j_tpu.kernels.registry import KernelRegistry

    r2 = KernelRegistry(cache=fresh)
    for kern in (kernels.registry.MatmulBiasActKernel(),
                 kernels.registry.ConvBnActKernel()):
        r2.register(kern)
    assert r2.tuning_digest("matmul_bias_act") == \
        kernels.tuning_digest("matmul_bias_act")


@pytest.mark.slow
def test_winner_persists_across_real_processes(tmp_path):
    path = str(tmp_path / "tuning.json")
    kernels.set_tuning_cache(path)
    env = _env(32, 16, 8, act="relu")
    kernels.autotune(kernels.REGISTRY.get("matmul_bias_act"), env,
                     max_candidates=4)
    digest = kernels.tuning_digest("matmul_bias_act")
    code = (
        "import json\n"
        "from deeplearning4j_tpu import kernels\n"
        f"kernels.set_tuning_cache({path!r})\n"
        f"w = kernels.TUNING.winner('matmul_bias_act', {env.key!r})\n"
        "print(json.dumps({'tiling': w['tiling'], "
        "'digest': kernels.tuning_digest('matmul_bias_act')}))\n")
    out = subprocess.run([sys.executable, "-c", code],
                         capture_output=True, text=True, check=True,
                         env={"JAX_PLATFORMS": "cpu", "PATH": "/usr/bin:"
                              "/bin:/usr/local/bin"}, cwd="/root/repo")
    blob = json.loads(out.stdout.strip().splitlines()[-1])
    assert tuple(blob["tiling"]) == tuple(
        kernels.TUNING.winner("matmul_bias_act", env.key)["tiling"])
    assert blob["digest"] == digest


def test_tuning_cache_corruption_named_error_and_fallback(tmp_path):
    path = str(tmp_path / "tuning.json")
    kernels.set_tuning_cache(path)
    env = _env(32, 16, 8, act="relu")
    kernels.autotune(kernels.REGISTRY.get("matmul_bias_act"), env,
                     max_candidates=2)
    # tamper with the published winners: the recorded digest no longer
    # matches the content
    blob = json.loads(open(path).read())
    blob["winners"]["matmul_bias_act"][env.key]["tiling"] = [99, 99, 99]
    open(path, "w").write(json.dumps(blob))
    kernels.TUNING.clear()
    with pytest.raises(kernels.TuningCacheCorruptError) as ei:
        kernels.set_tuning_cache(path)
    assert "digest mismatch" in str(ei.value)
    # fallback: the cache refused the file entirely -> selection is
    # stock XLA (None), and a use_kernels net still trains
    assert kernels.REGISTRY.select("matmul_bias_act", env) is None
    net = MultiLayerNetwork(_conv_dense_conf(True, width=17)).init()
    X, Y = _batch()
    _fit(net, X, Y, steps=1)
    # unreadable garbage is refused with the same named error
    open(path, "w").write("{not json")
    with pytest.raises(kernels.TuningCacheCorruptError):
        kernels.set_tuning_cache(path)


def test_select_refuses_illegal_hand_edited_winner():
    env = _env(32, 16, 8)
    # a "winner" that does not divide the problem (hand-edited cache)
    kernels.TUNING.record("matmul_bias_act", env.key, (24, 7, 5), 1.0)
    assert kernels.REGISTRY.select("matmul_bias_act", env) is None


# --------------------------------------------------------------------------
# model wiring: off-by-default, parity, fallback, re-keying
# --------------------------------------------------------------------------

def test_use_kernels_off_by_default_bitwise():
    conf_default = _conv_dense_conf(False, width=18)
    assert conf_default.use_kernels is False
    net_a = MultiLayerNetwork(conf_default).init()
    net_b = MultiLayerNetwork(_conv_dense_conf(False, width=18)).init()
    assert net_a._ktag() == ""
    X, Y = _batch(seed=1)
    _fit(net_a, X, Y)
    _fit(net_b, X, Y)
    for a, b in zip(_leaves(net_a.params), _leaves(net_b.params)):
        assert np.array_equal(a, b)


def test_use_kernels_untuned_is_bitwise_stock_xla():
    """use_kernels=True with an EMPTY tuning cache routes nothing: the
    trace is the stock trace, pinned bitwise against the off net."""
    net_off = MultiLayerNetwork(_conv_dense_conf(False, width=19)).init()
    net_on = MultiLayerNetwork(_conv_dense_conf(True, width=19)).init()
    X, Y = _batch(seed=2)
    _fit(net_off, X, Y)
    _fit(net_on, X, Y)
    for a, b in zip(_leaves(net_off.params), _leaves(net_on.params)):
        assert np.array_equal(a, b)
    for a, b in zip(_leaves(net_off.opt_state),
                    _leaves(net_on.opt_state)):
        assert np.array_equal(a, b)


def test_kernel_path_training_parity_f32():
    """The acceptance pin: kernel-path training on a conv net tracks
    the stock-XLA path numerically (interpret mode on CPU)."""
    batch = 8
    conf_on = _conv_dense_conf(True, width=20)
    kernels.autotune_model(conf_on, batch, max_candidates=4)
    net_on = MultiLayerNetwork(conf_on).init()
    net_off = MultiLayerNetwork(_conv_dense_conf(False, width=20)).init()
    X, Y = _batch(batch, seed=3)
    _fit(net_on, X, Y, steps=4)
    _fit(net_off, X, Y, steps=4)
    assert _max_delta(net_on.params, net_off.params) < 1e-4
    assert _max_delta(net_on.state, net_off.state) < 1e-4
    # output parity (the routed dense rides eval too)
    yo = np.asarray(net_on.output(X))
    yr = np.asarray(net_off.output(X))
    np.testing.assert_allclose(yo, yr, rtol=1e-4, atol=1e-4)


def test_kernel_path_training_parity_bf16_storage():
    batch = 8
    conf_on = _conv_dense_conf(True, width=21, compute_dtype="bfloat16")
    kernels.autotune_model(conf_on, batch, max_candidates=2)
    net_on = MultiLayerNetwork(conf_on).init()
    net_off = MultiLayerNetwork(
        _conv_dense_conf(False, width=21, compute_dtype="bfloat16")).init()
    X, Y = _batch(batch, seed=4)
    _fit(net_on, X, Y, steps=3)
    _fit(net_off, X, Y, steps=3)
    # bf16 compute: per-op rounding differs between the fused epilogue
    # and the stock pass; f32 masters keep the drift at bf16 resolution
    assert _max_delta(net_on.params, net_off.params) < 0.05


def test_fallback_on_untuned_shape_zero_recompile_churn():
    batch = 8
    conf = _conv_dense_conf(True, width=22)
    kernels.autotune_model(conf, batch, max_candidates=2)
    net = MultiLayerNetwork(conf).init()
    X, Y = _batch(batch, seed=5)
    _fit(net, X, Y, steps=1)
    # an UNTUNED batch size: every routed layer falls back to stock XLA
    X6, Y6 = _batch(6, seed=6)
    _fit(net, X6, Y6, steps=1)
    m0 = aot_cache.stats()["misses"]
    _fit(net, X6, Y6, steps=2)
    _fit(net, X, Y, steps=2)
    assert aot_cache.stats()["misses"] == m0, \
        "fallback shapes must not churn recompiles"


def test_retune_mints_new_executable():
    batch = 8
    conf = _conv_dense_conf(True, width=23)
    kernels.autotune_model(conf, batch, max_candidates=2)
    net = MultiLayerNetwork(conf).init()
    X, Y = _batch(batch, seed=7)
    _fit(net, X, Y, steps=2)
    tag0 = net._ktag()
    assert "kern:matmul_bias_act:" in tag0
    assert "kern:conv_bn_act:" in tag0
    m0 = aot_cache.stats()["misses"]
    _fit(net, X, Y, steps=1)
    assert aot_cache.stats()["misses"] == m0  # warmed
    # retune: force a different winner for the dense envelope
    envs = dict(kernels.plan_envelopes(conf, batch))
    env = envs["matmul_bias_act"]
    cur = tuple(kernels.TUNING.winner("matmul_bias_act",
                                      env.key)["tiling"])
    alt = next(t for t in kernels.REGISTRY.get(
        "matmul_bias_act").candidates(env) if t != cur)
    kernels.TUNING.record("matmul_bias_act", env.key, alt, 0.0)
    assert net._ktag() != tag0
    _fit(net, X, Y, steps=1)
    assert aot_cache.stats()["misses"] > m0, \
        "a retuned kernel must be a NEW executable"


def test_conv1x1_layer_routes():
    b = NeuralNetConfiguration.builder().seed(11).updater(
        Adam(learning_rate=1e-3)).use_kernels()
    conf = (b.list()
            .layer(ConvolutionLayer(
                n_out=8, kernel_size=(1, 1), stride=(1, 1),
                convolution_mode=ConvolutionMode.SAME,
                activation=Activation.RELU))
            .layer(OutputLayer(n_out=4))
            .set_input_type(it.Convolutional(4, 4, 3))
            .build())
    batch = 8
    planned = kernels.plan_envelopes(conf, batch)
    assert any(kid == "matmul_bias_act" and e.m == batch * 16
               for kid, e in planned)
    kernels.autotune_model(conf, batch, max_candidates=2)
    from deeplearning4j_tpu import telemetry

    telemetry.reset()
    net = MultiLayerNetwork(conf).init()
    X, Y = _batch(batch, seed=8)
    _fit(net, X, Y, steps=2)
    snap = telemetry.REGISTRY.snapshot(run_collectors=False)
    assert any(k.startswith('dl4j_kernel_selected_total{'
                            'kernel="matmul_bias_act"') for k in snap), snap
    # parity vs the stock conv
    off = (NeuralNetConfiguration.builder().seed(11).updater(
        Adam(learning_rate=1e-3)).list()
        .layer(ConvolutionLayer(
            n_out=8, kernel_size=(1, 1), stride=(1, 1),
            convolution_mode=ConvolutionMode.SAME,
            activation=Activation.RELU))
        .layer(OutputLayer(n_out=4))
        .set_input_type(it.Convolutional(4, 4, 3))
        .build())
    net_off = MultiLayerNetwork(off).init()
    _fit(net_off, X, Y, steps=2)
    assert _max_delta(net.params, net_off.params) < 1e-4


def test_conv1x1_strided_dropout_parity():
    """Regression (review finding): the routed 1x1 conv must draw its
    dropout mask over the FULL input before the stride subsample, like
    the stock forward — a post-slice draw is a different stream for the
    same rng and the on/off paths diverge by far more than kernel
    rounding."""
    def conf(use_k):
        b = NeuralNetConfiguration.builder().seed(17).updater(
            Adam(learning_rate=1e-3))
        if use_k:
            b = b.use_kernels()
        return (b.list()
                .layer(ConvolutionLayer(
                    n_out=8, kernel_size=(1, 1), stride=(2, 2),
                    convolution_mode=ConvolutionMode.SAME,
                    activation=Activation.RELU, dropout=0.5))
                .layer(OutputLayer(n_out=4))
                .set_input_type(it.Convolutional(6, 6, 3))
                .build())

    batch = 8
    kernels.autotune_model(conf(True), batch, max_candidates=2)
    net_on = MultiLayerNetwork(conf(True)).init()
    net_off = MultiLayerNetwork(conf(False)).init()
    X, Y = _batch(batch, seed=12, img=6)
    _fit(net_on, X, Y, steps=2)
    _fit(net_off, X, Y, steps=2)
    # same seed -> same full-shape bernoulli stream on both paths
    assert _max_delta(net_on.params, net_off.params) < 1e-4


def test_graph_vertex_routes_and_parity():
    from deeplearning4j_tpu.nn.graph import ComputationGraph

    def build(use_k):
        b = NeuralNetConfiguration.builder().seed(13).updater(
            Adam(learning_rate=1e-3))
        if use_k:
            b = b.use_kernels()
        gb = (b.graph_builder()
              .add_inputs("in")
              .set_input_types(it.FeedForward(12))
              .add_layer("d1", DenseLayer(n_out=24,
                                          activation=Activation.RELU),
                         "in")
              .add_layer("out", OutputLayer(n_out=3), "d1")
              .set_outputs("out"))
        return gb.build()

    conf_on = build(True)
    env = _env(8, 12, 24, act="relu")
    kernels.autotune(kernels.REGISTRY.get("matmul_bias_act"), env,
                     max_candidates=2)
    g_on = ComputationGraph(conf_on).init()
    g_off = ComputationGraph(build(False)).init()
    rng = np.random.default_rng(9)
    X = rng.normal(size=(8, 12)).astype(np.float32)
    Y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 8)]
    for _ in range(3):
        g_on.fit_batch(DataSet(X.copy(), Y.copy()))
        g_off.fit_batch(DataSet(X.copy(), Y.copy()))
    assert _max_delta(g_on.params, g_off.params) < 1e-4
    assert "kern:" in g_on._ktag()


# --------------------------------------------------------------------------
# attention kernels: flash prefill + paged decode
# --------------------------------------------------------------------------

def _attn_env(b=2, h=2, tq=16, tk=16, d=8, dtype="float32", causal=True,
              masked=False):
    return AttentionEnvelope(b=b, h=h, tq=tq, tk=tk, d=d, dtype=dtype,
                             backend="interpret", causal=causal,
                             masked=masked)


@pytest.mark.parametrize("causal,masked", [(True, False), (False, False),
                                           (True, True)])
def test_flash_attention_parity_f32(causal, masked):
    env = _attn_env(causal=causal, masked=masked)
    k = kernels.REGISTRY.get("flash_attention")
    assert k.supports(env)
    args = k.make_inputs(env, seed=3)
    ref = np.asarray(k.reference(env)(*args))
    for tiling in k.candidates(env, limit=4):
        got = np.asarray(k.build(env, tiling)(*args))
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


def test_flash_attention_parity_bf16():
    env = _attn_env(dtype="bfloat16")
    k = kernels.REGISTRY.get("flash_attention")
    args = k.make_inputs(env, seed=4)
    ref = np.asarray(k.reference(env)(*args), np.float32)
    got = np.asarray(k.build(env, k.candidates(env, limit=1)[0])(*args),
                     np.float32)
    np.testing.assert_allclose(got, ref, rtol=0.05, atol=0.1)


def test_flash_attention_tall_query_parity():
    """Tq != Tk (the prefill_suffix join shape): the kernel's single
    off = Tk - Tq causal rule must match the reference exactly."""
    env = _attn_env(tq=8, tk=24, masked=True)
    k = kernels.REGISTRY.get("flash_attention")
    args = k.make_inputs(env, seed=5)
    ref = np.asarray(k.reference(env)(*args))
    got = np.asarray(k.build(env, k.candidates(env, limit=1)[0])(*args))
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


def test_flash_gradient_parity():
    """The custom-VJP backward (blockwise recompute from the saved
    row-max/row-sum stats) tracks the reference gradients — the pin the
    train-path routing rests on."""
    env = _attn_env(tq=16, tk=16)
    k = kernels.REGISTRY.get("flash_attention")
    tiling = k.candidates(env, limit=1)[0]
    q, kk, v = k.make_inputs(env, seed=6)

    def loss(fn):
        return lambda q, kk, v: jnp.sum(fn(q, kk, v) ** 2)

    gk = jax.grad(loss(k.build(env, tiling)), argnums=(0, 1, 2))(q, kk, v)
    gr = jax.grad(loss(k.reference(env)), argnums=(0, 1, 2))(q, kk, v)
    for a, b in zip(gk, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_paged_decode_parity_f32_per_candidate():
    env = _attn_env(b=4, tq=1, tk=32)
    k = kernels.REGISTRY.get("paged_decode_attention")
    assert k.supports(env)
    args = k.make_inputs(env, seed=7)
    ref = np.asarray(k.reference(env)(*args))
    cands = [tuple(t) for t in k.candidates(env)]
    assert len(cands) >= 2  # 32 admits at least pages 32, 16, 8
    for tiling in cands:
        got = np.asarray(k.build(env, tiling)(*args))
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


def test_paged_decode_ragged_occupancy_parity():
    """Per-row positions at every occupancy extreme — empty-but-one,
    page-boundary, mid-page, full cache — match the masked full-cache
    read for every legal page size."""
    env = _attn_env(b=5, tq=1, tk=32)
    k = kernels.REGISTRY.get("paged_decode_attention")
    q, kc, vc, _ = k.make_inputs(env, seed=8)
    pos = jnp.asarray([0, 7, 8, 21, 31], jnp.int32)
    ref = np.asarray(k.reference(env)(q, kc, vc, pos))
    for tiling in k.candidates(env):
        got = np.asarray(k.build(env, tiling)(q, kc, vc, pos))
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


def test_paged_decode_parity_bf16():
    env = _attn_env(b=2, tq=1, tk=16, dtype="bfloat16")
    k = kernels.REGISTRY.get("paged_decode_attention")
    args = k.make_inputs(env, seed=9)
    ref = np.asarray(k.reference(env)(*args), np.float32)
    got = np.asarray(k.build(env, k.candidates(env, limit=1)[0])(*args),
                     np.float32)
    np.testing.assert_allclose(got, ref, rtol=0.05, atol=0.1)


def test_attention_routing_untuned_is_stock():
    """Empty tuning cache: both attention entry points decline (None)
    — the caller runs stock XLA, zero behavior change."""
    env = _attn_env()
    k = kernels.REGISTRY.get("flash_attention")
    q, kk, v = k.make_inputs(env, seed=10)
    assert kernels.maybe_flash_attention(q, kk, v, causal=True) is None
    penv = _attn_env(b=2, tq=1, tk=16)
    pk = kernels.REGISTRY.get("paged_decode_attention")
    q1, kc, vc, pos = pk.make_inputs(penv, seed=10)
    assert kernels.maybe_decode_attention(q1, kc, vc, pos) is None


def test_attention_routing_tuned_selects_and_records():
    from deeplearning4j_tpu import telemetry

    telemetry.reset()
    env = _attn_env()
    k = kernels.REGISTRY.get("flash_attention")
    kernels.autotune(k, env, max_candidates=2, trials=1)
    q, kk, v = k.make_inputs(env, seed=11)
    out = kernels.maybe_flash_attention(q, kk, v, causal=True)
    assert out is not None
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(k.reference(env)(q, kk, v)),
                               rtol=1e-5, atol=1e-5)
    penv = _attn_env(b=2, tq=1, tk=16)
    pk = kernels.REGISTRY.get("paged_decode_attention")
    kernels.autotune(pk, penv, max_candidates=2, trials=1)
    q1, kc, vc, pos = pk.make_inputs(penv, seed=11)
    pout = kernels.maybe_decode_attention(q1, kc, vc, pos)
    assert pout is not None
    np.testing.assert_allclose(
        np.asarray(pout), np.asarray(pk.reference(penv)(q1, kc, vc, pos)),
        rtol=1e-5, atol=1e-5)
    snap = telemetry.REGISTRY.snapshot(run_collectors=False)
    assert any(k_.startswith('dl4j_kernel_selected_total{'
                             'kernel="flash_attention"') for k_ in snap)
    assert any(k_.startswith('dl4j_kernel_selected_total{'
                             'kernel="paged_decode_attention"')
               for k_ in snap)


def _attn_net(use_k, seed=11):
    from deeplearning4j_tpu.zoo.graphs import TransformerEncoder

    return TransformerEncoder(num_classes=3, embed_dim=16, n_heads=2,
                              n_layers=1, max_len=8, seed=seed,
                              use_kernels=use_k).init()


def test_self_attention_layer_train_parity():
    """The train-fit acceptance pin: a transformer classifier with the
    routed flash kernel tracks the stock path through eval AND through
    optimizer steps (forward + custom-VJP backward in the real loss)."""
    stock = _attn_net(False)
    kern = _attn_net(True)
    for kid, env in kernels.plan_envelopes(kern.conf, 4):
        k = kernels.REGISTRY.get(kid)
        if k and k.supports(env):
            kernels.autotune(k, env, max_candidates=1, trials=1)
    assert "kern:flash_attention:" in kern._ktag()
    rng = np.random.default_rng(0)
    x = np.asarray(rng.normal(size=(4, 8, 16)), np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 4)]
    np.testing.assert_allclose(np.asarray(kern.output(x)),
                               np.asarray(stock.output(x)),
                               rtol=1e-5, atol=1e-5)
    stock.fit(x, y, epochs=3)
    kern.fit(x, y, epochs=3)
    assert _max_delta(stock.params, kern.params) < 1e-3


def test_self_attention_untuned_is_bitwise_stock():
    stock = _attn_net(False, seed=12)
    kern = _attn_net(True, seed=12)
    rng = np.random.default_rng(1)
    x = np.asarray(rng.normal(size=(4, 8, 16)), np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 4)]
    stock.fit(x, y, epochs=2)
    kern.fit(x, y, epochs=2)
    for a, b in zip(_leaves(stock.params), _leaves(kern.params)):
        assert np.array_equal(a, b)


@pytest.mark.slow
def test_flash_autotune_full_sweep():
    """The heaviest tuning leg: the full (block_q, block_k) candidate
    space at a shape big enough to split blocks, through the interpreter.
    Slow-marked; tier-1 covers the limited sweeps above."""
    env = _attn_env(b=1, h=1, tq=256, tk=256, d=8)
    k = kernels.REGISTRY.get("flash_attention")
    cands = [tuple(t) for t in k.candidates(env)]
    assert len(cands) >= 2
    res = kernels.autotune(k, env, trials=1)
    assert tuple(res.tiling) in cands
    sel = kernels.REGISTRY.select("flash_attention", env)
    assert sel is not None and tuple(sel.tiling) == tuple(res.tiling)


def test_cache_tag_memoized_against_epoch():
    """cache_tag() is a per-dispatch hot path: repeated calls must hit
    the (epoch, ids) memo — same object, no re-digest — and a tuning
    mutation must bump the epoch and re-mint."""
    t0 = kernels.REGISTRY.cache_tag()
    assert kernels.REGISTRY.cache_tag() is t0
    env = _attn_env()
    kernels.TUNING.record("flash_attention", env.key, (128, 128), 1.0)
    t1 = kernels.REGISTRY.cache_tag()
    assert t1 != t0
    assert kernels.REGISTRY.cache_tag() is t1


# --------------------------------------------------------------------------
# program-linter integration: PRG207 + the donation audit
# --------------------------------------------------------------------------

def test_prg207_seeded_defects_and_negative_control():
    from deeplearning4j_tpu.analysis import program

    fn = jax.jit(lambda x: x * 2.0)
    x = jnp.ones((4,))
    # unknown kernel id -> ERROR
    art = program.trace_artifact(fn, (x,),
                                 fn_key="output:kern:nope:deadbeef")
    rules = [(f.rule, f.severity) for f in program.lint_program(art)]
    assert ("PRG207", "ERROR") in rules
    # stale digest -> ERROR naming the mismatch
    art = program.trace_artifact(
        fn, (x,), fn_key="output:kern:matmul_bias_act:00000000")
    finds = [f for f in program.lint_program(art) if f.rule == "PRG207"]
    assert finds and finds[0].severity == "ERROR"
    assert "mismatches" in finds[0].message
    # negative control: the CURRENT digest audits clean
    d = kernels.tuning_digest("matmul_bias_act")
    art = program.trace_artifact(
        fn, (x,), fn_key=f"output:kern:matmul_bias_act:{d}")
    assert not [f for f in program.lint_program(art)
                if f.rule == "PRG207"]
    # no tokens: the rule stays silent
    art = program.trace_artifact(fn, (x,), fn_key="output")
    assert not [f for f in program.lint_program(art)
                if f.rule == "PRG207"]


def test_prg207_attention_step_kinds_seeded_and_clean():
    """PRG207 over the serving step kinds the attention kernels key:
    a decode_step key with a stale flash digest is an ERROR, an unknown
    paged id is an ERROR, and keys carrying the CURRENT digests audit
    clean. PRG201 classification: every kernel-bearing decode/prefill
    kind stays a train kind (the token is a suffix)."""
    from deeplearning4j_tpu.analysis import program

    fn = jax.jit(lambda x: x * 2.0)
    x = jnp.ones((4,))
    art = program.trace_artifact(
        fn, (x,), fn_key="decode_step:s16:k1:kern:flash_attention:00000000")
    finds = [f for f in program.lint_program(art) if f.rule == "PRG207"]
    assert finds and finds[0].severity == "ERROR"
    assert "mismatches" in finds[0].message
    art = program.trace_artifact(
        fn, (x,), fn_key="prefill_join:s16:t8:b2:kern:paged_decode:bad00bad")
    rules = [(f.rule, f.severity) for f in program.lint_program(art)]
    assert ("PRG207", "ERROR") in rules
    # negative control: current digests on an attention-bearing key
    df = kernels.tuning_digest("flash_attention")
    dp = kernels.tuning_digest("paged_decode_attention")
    key = (f"decode_step:s16:k2:kern:flash_attention:{df}"
           f":kern:paged_decode_attention:{dp}")
    art = program.trace_artifact(fn, (x,), fn_key=key)
    assert not [f for f in program.lint_program(art)
                if f.rule == "PRG207"]
    for kind in ("decode_step", "prefill", "spec_verify", "prefix_join"):
        assert (f"{kind}:s16:kern:flash_attention:{df}").startswith(
            program.TRAIN_KIND_PREFIXES)


def test_kernel_bearing_step_donates_and_audits_clean():
    from deeplearning4j_tpu.analysis import program

    batch = 8
    conf = _conv_dense_conf(True, width=24)
    kernels.autotune_model(conf, batch, max_candidates=2)
    net = MultiLayerNetwork(conf).init()
    X, Y = _batch(batch, seed=10)
    _fit(net, X, Y, steps=1)
    audit = program.donation_audit()
    mine = {k: v for k, v in audit.items()
            if k[0] == net._graph_key() and "kern:" in k[1]}
    assert mine, f"no kernel-bearing train compile audited: {audit.keys()}"
    for key, rec in mine.items():
        assert rec["aliases"] is None or rec["aliases"] > 0, \
            f"kernel-bearing step {key} lost donation"
        assert rec["findings"] == 0, \
            f"kernel-bearing step {key} has lint findings"


# --------------------------------------------------------------------------
# telemetry + UI surface
# --------------------------------------------------------------------------

def test_kernel_telemetry_and_ui_panel():
    from deeplearning4j_tpu import telemetry
    from deeplearning4j_tpu.ui.server import UIServer

    telemetry.reset()
    batch = 8
    conf = _conv_dense_conf(True, width=25)
    kernels.autotune_model(conf, batch, max_candidates=2)
    net = MultiLayerNetwork(conf).init()
    X, Y = _batch(batch, seed=11)
    _fit(net, X, Y, steps=1)
    snap = telemetry.REGISTRY.snapshot(run_collectors=False)
    selected = [k for k in snap
                if k.startswith("dl4j_kernel_selected_total")]
    assert selected, snap
    assert any('shape_bucket="' in k for k in selected)
    assert snap.get("dl4j_kernel_tuning_cache_entries", 0) >= 2
    ui = UIServer()
    html = ui.render_html()
    assert "Kernels (autotuner)" in html
    assert "dl4j_kernel_selected_total" in html
