"""Keras HDF5 import (reference: modelimport golden tests, SURVEY.md §4).

No TensorFlow in this env, so fixtures are handcrafted in the exact Keras
2.x HDF5 layout (model_config JSON attr + model_weights groups) and the
oracle is manual numpy forward math."""

import json

import h5py
import numpy as np
import pytest

from deeplearning4j_tpu.modelimport.keras import (
    InvalidKerasConfigurationException,
    KerasModelImport,
)


def _write_keras_h5(path, model_cfg, weights):
    """weights: {layer_name: {weight_name: array}} in Keras layout."""
    with h5py.File(path, "w") as f:
        f.attrs["model_config"] = json.dumps(model_cfg)
        mw = f.create_group("model_weights")
        for lname, ws in weights.items():
            g = mw.create_group(lname).create_group(lname)
            names = []
            for wname, arr in ws.items():
                g.create_dataset(wname, data=arr)
                names.append(f"{lname}/{lname}/{wname}:0".encode())
            mw[lname].attrs["weight_names"] = names


def _dense_cfg(name, units, activation, input_shape=None):
    cfg = {"name": name, "units": units, "activation": activation,
           "use_bias": True}
    if input_shape is not None:
        cfg["batch_input_shape"] = [None] + list(input_shape)
    return {"class_name": "Dense", "config": cfg}


def test_import_dense_mlp(tmp_path, rng):
    w1 = rng.normal(size=(4, 8)).astype(np.float32)
    b1 = rng.normal(size=(8,)).astype(np.float32)
    w2 = rng.normal(size=(8, 3)).astype(np.float32)
    b2 = rng.normal(size=(3,)).astype(np.float32)
    cfg = {"class_name": "Sequential", "config": {"name": "seq", "layers": [
        _dense_cfg("dense", 8, "tanh", input_shape=[4]),
        _dense_cfg("dense_1", 3, "softmax"),
    ]}}
    path = str(tmp_path / "mlp.h5")
    _write_keras_h5(path, cfg, {
        "dense": {"kernel": w1, "bias": b1},
        "dense_1": {"kernel": w2, "bias": b2},
    })
    net = KerasModelImport.import_keras_sequential_model_and_weights(path)
    x = rng.normal(size=(5, 4)).astype(np.float32)
    got = np.asarray(net.output(x))
    h = np.tanh(x @ w1 + b1)
    logits = h @ w2 + b2
    want = np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_import_cnn(tmp_path, rng):
    k = rng.normal(size=(3, 3, 1, 4), scale=0.5).astype(np.float32)
    kb = rng.normal(size=(4,)).astype(np.float32)
    w = rng.normal(size=(4 * 4 * 4, 2)).astype(np.float32)  # after pool
    b = rng.normal(size=(2,)).astype(np.float32)
    cfg = {"class_name": "Sequential", "config": {"name": "cnn", "layers": [
        {"class_name": "Conv2D", "config": {
            "name": "conv2d", "filters": 4, "kernel_size": [3, 3],
            "strides": [1, 1], "padding": "same", "activation": "relu",
            "use_bias": True, "batch_input_shape": [None, 8, 8, 1]}},
        {"class_name": "MaxPooling2D", "config": {
            "name": "pool", "pool_size": [2, 2], "strides": [2, 2],
            "padding": "valid"}},
        {"class_name": "Flatten", "config": {"name": "flatten"}},
        _dense_cfg("dense", 2, "softmax"),
    ]}}
    path = str(tmp_path / "cnn.h5")
    _write_keras_h5(path, cfg, {
        "conv2d": {"kernel": k, "bias": kb},
        "dense": {"kernel": w, "bias": b},
    })
    net = KerasModelImport.import_keras_sequential_model_and_weights(path)
    x = rng.normal(size=(2, 8, 8, 1)).astype(np.float32)
    got = np.asarray(net.output(x))
    assert got.shape == (2, 2)
    np.testing.assert_allclose(got.sum(-1), 1.0, rtol=1e-5)
    # conv weights landed untransposed (HWIO == HWIO)
    np.testing.assert_array_equal(np.asarray(net.params["0"]["W"]), k)


def test_import_lstm_gate_reorder(tmp_path, rng):
    u, fdim = 5, 3
    kernel = rng.normal(size=(fdim, 4 * u)).astype(np.float32)
    rec = rng.normal(size=(u, 4 * u)).astype(np.float32)
    bias = rng.normal(size=(4 * u,)).astype(np.float32)
    w2 = rng.normal(size=(u, 2)).astype(np.float32)
    b2 = np.zeros(2, np.float32)
    cfg = {"class_name": "Sequential", "config": {"name": "rnn", "layers": [
        {"class_name": "LSTM", "config": {
            "name": "lstm", "units": u, "activation": "tanh",
            "recurrent_activation": "sigmoid", "return_sequences": True,
            "batch_input_shape": [None, 7, fdim]}},
        _dense_cfg("dense", 2, "softmax"),
    ]}}
    path = str(tmp_path / "rnn.h5")
    _write_keras_h5(path, cfg, {
        "lstm": {"kernel": kernel, "recurrent_kernel": rec, "bias": bias},
        "dense": {"kernel": w2, "bias": b2},
    })
    net = KerasModelImport.import_keras_sequential_model_and_weights(path)
    x = rng.normal(size=(2, 7, fdim)).astype(np.float32)
    got = np.asarray(net.output(x))
    assert got.shape == (2, 7, 2)

    # manual Keras-order LSTM forward as the oracle
    def sigmoid(z):
        return 1.0 / (1.0 + np.exp(-z))

    ki, kf, kc, ko = np.split(kernel, 4, axis=1)
    ri, rf, rc, ro = np.split(rec, 4, axis=1)
    bi, bf, bc, bo = np.split(bias, 4)
    h = np.zeros((2, u), np.float32)
    c = np.zeros((2, u), np.float32)
    outs = []
    for t in range(7):
        xt = x[:, t]
        i = sigmoid(xt @ ki + h @ ri + bi)
        f_ = sigmoid(xt @ kf + h @ rf + bf)
        g = np.tanh(xt @ kc + h @ rc + bc)
        o = sigmoid(xt @ ko + h @ ro + bo)
        c = f_ * c + i * g
        h = o * np.tanh(c)
        outs.append(h.copy())
    hs = np.stack(outs, 1)
    logits = hs @ w2 + b2
    want = np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-4)


def test_import_rejects_functional_and_bad_layers(tmp_path):
    path = str(tmp_path / "bad.h5")
    _write_keras_h5(path, {"class_name": "Functional", "config": {}}, {})
    with pytest.raises(InvalidKerasConfigurationException):
        KerasModelImport.import_keras_sequential_model_and_weights(path)

    cfg = {"class_name": "Sequential", "config": {"layers": [
        {"class_name": "ConvLSTM2D", "config": {
            "name": "cl", "batch_input_shape": [None, 4, 4, 4, 1]}}]}}
    path2 = str(tmp_path / "bad2.h5")
    _write_keras_h5(path2, cfg, {})
    with pytest.raises(InvalidKerasConfigurationException):
        KerasModelImport.import_keras_sequential_model_and_weights(path2)


def test_import_shape_mismatch_raises(tmp_path, rng):
    cfg = {"class_name": "Sequential", "config": {"layers": [
        _dense_cfg("dense", 8, "tanh", input_shape=[4]),
    ]}}
    path = str(tmp_path / "mismatch.h5")
    _write_keras_h5(path, cfg, {
        "dense": {"kernel": np.zeros((5, 8), np.float32),
                  "bias": np.zeros(8, np.float32)},
    })
    with pytest.raises(InvalidKerasConfigurationException):
        KerasModelImport.import_keras_sequential_model_and_weights(path)


def test_trailing_activation_folds_into_output(tmp_path, rng):
    w1 = rng.normal(size=(4, 3)).astype(np.float32)
    b1 = rng.normal(size=(3,)).astype(np.float32)
    cfg = {"class_name": "Sequential", "config": {"layers": [
        _dense_cfg("dense", 3, "linear", input_shape=[4]),
        {"class_name": "Activation", "config": {"name": "act",
                                                "activation": "softmax"}},
    ]}}
    path = str(tmp_path / "trail.h5")
    _write_keras_h5(path, cfg, {"dense": {"kernel": w1, "bias": b1}})
    net = KerasModelImport.import_keras_sequential_model_and_weights(path)
    x = rng.normal(size=(5, 4)).astype(np.float32)
    got = np.asarray(net.output(x))
    logits = x @ w1 + b1
    want = np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
    # and it trains (the last layer IS the output layer)
    from deeplearning4j_tpu.datasets.dataset import DataSet
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 5)]
    net.fit_batch(DataSet(x, y))


def test_bn_scale_false_imports(tmp_path, rng):
    cfg = {"class_name": "Sequential", "config": {"layers": [
        {"class_name": "BatchNormalization", "config": {
            "name": "bn", "epsilon": 1e-3, "momentum": 0.99,
            "batch_input_shape": [None, 4]}},
        _dense_cfg("dense", 2, "softmax"),
    ]}}
    path = str(tmp_path / "bn.h5")
    # scale=False: no gamma saved
    _write_keras_h5(path, cfg, {
        "bn": {"beta": np.zeros(4, np.float32),
               "moving_mean": np.zeros(4, np.float32),
               "moving_variance": np.ones(4, np.float32)},
        "dense": {"kernel": rng.normal(size=(4, 2)).astype(np.float32),
                  "bias": np.zeros(2, np.float32)},
    })
    net = KerasModelImport.import_keras_sequential_model_and_weights(path)
    np.testing.assert_array_equal(np.asarray(net.params["0"]["gamma"]),
                                  np.ones(4, np.float32))


# --------------------------------------------------------------------------
# functional (Model) import -> ComputationGraph
# --------------------------------------------------------------------------

def _functional_cfg(layers, inputs, outputs):
    return {"class_name": "Model", "config": {
        "name": "model", "layers": layers,
        "input_layers": [[n, 0, 0] for n in inputs],
        "output_layers": [[n, 0, 0] for n in outputs]}}


def _node(names):
    return [[[n, 0, 0, {}] for n in names]]


def test_import_functional_residual_mlp(tmp_path, rng):
    w1 = rng.normal(size=(4, 4)).astype(np.float32)
    b1 = rng.normal(size=(4,)).astype(np.float32)
    w2 = rng.normal(size=(4, 3)).astype(np.float32)
    b2 = rng.normal(size=(3,)).astype(np.float32)
    layers = [
        {"class_name": "InputLayer", "config": {
            "name": "in", "batch_input_shape": [None, 4]}},
        {"class_name": "Dense", "config": {
            "name": "d1", "units": 4, "activation": "relu",
            "use_bias": True}, "inbound_nodes": _node(["in"])},
        {"class_name": "Add", "config": {"name": "res"},
         "inbound_nodes": _node(["d1", "in"])},
        {"class_name": "Dense", "config": {
            "name": "out", "units": 3, "activation": "softmax",
            "use_bias": True}, "inbound_nodes": _node(["res"])},
    ]
    cfg = _functional_cfg(layers, ["in"], ["out"])
    path = str(tmp_path / "func.h5")
    _write_keras_h5(path, cfg, {
        "d1": {"kernel": w1, "bias": b1},
        "out": {"kernel": w2, "bias": b2},
    })
    net = KerasModelImport.import_keras_model_and_weights(path)
    x = rng.normal(size=(6, 4)).astype(np.float32)
    got = np.asarray(net.output(x))
    h = np.maximum(x @ w1 + b1, 0.0) + x
    logits = h @ w2 + b2
    want = np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_import_functional_two_branch_concat(tmp_path, rng):
    wa = rng.normal(size=(5, 3)).astype(np.float32)
    ba = rng.normal(size=(3,)).astype(np.float32)
    wb = rng.normal(size=(5, 2)).astype(np.float32)
    bb = rng.normal(size=(2,)).astype(np.float32)
    wo = rng.normal(size=(5, 2)).astype(np.float32)
    bo = rng.normal(size=(2,)).astype(np.float32)
    layers = [
        {"class_name": "InputLayer", "config": {
            "name": "in", "batch_input_shape": [None, 5]}},
        {"class_name": "Dense", "config": {
            "name": "a", "units": 3, "activation": "tanh",
            "use_bias": True}, "inbound_nodes": _node(["in"])},
        {"class_name": "Dense", "config": {
            "name": "b", "units": 2, "activation": "sigmoid",
            "use_bias": True}, "inbound_nodes": _node(["in"])},
        {"class_name": "Concatenate", "config": {"name": "cat", "axis": -1},
         "inbound_nodes": _node(["a", "b"])},
        {"class_name": "Dense", "config": {
            "name": "out", "units": 2, "activation": "linear",
            "use_bias": True}, "inbound_nodes": _node(["cat"])},
    ]
    cfg = _functional_cfg(layers, ["in"], ["out"])
    path = str(tmp_path / "func2.h5")
    _write_keras_h5(path, cfg, {
        "a": {"kernel": wa, "bias": ba},
        "b": {"kernel": wb, "bias": bb},
        "out": {"kernel": wo, "bias": bo},
    })
    net = KerasModelImport.import_keras_model_and_weights(path)
    x = rng.normal(size=(4, 5)).astype(np.float32)
    got = np.asarray(net.output(x))
    ha = np.tanh(x @ wa + ba)
    hb = 1.0 / (1.0 + np.exp(-(x @ wb + bb)))
    want = np.concatenate([ha, hb], -1) @ wo + bo
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_import_functional_flatten_cnn(tmp_path, rng):
    k = rng.normal(size=(3, 3, 1, 2), scale=0.5).astype(np.float32)
    kb = rng.normal(size=(2,)).astype(np.float32)
    w = rng.normal(size=(8 * 8 * 2, 3)).astype(np.float32)
    b = rng.normal(size=(3,)).astype(np.float32)
    layers = [
        {"class_name": "InputLayer", "config": {
            "name": "img", "batch_input_shape": [None, 8, 8, 1]}},
        {"class_name": "Conv2D", "config": {
            "name": "conv", "filters": 2, "kernel_size": [3, 3],
            "strides": [1, 1], "padding": "same", "activation": "relu",
            "use_bias": True}, "inbound_nodes": _node(["img"])},
        {"class_name": "Flatten", "config": {"name": "flat"},
         "inbound_nodes": _node(["conv"])},
        {"class_name": "Dense", "config": {
            "name": "out", "units": 3, "activation": "softmax",
            "use_bias": True}, "inbound_nodes": _node(["flat"])},
    ]
    cfg = _functional_cfg(layers, ["img"], ["out"])
    path = str(tmp_path / "func3.h5")
    _write_keras_h5(path, cfg, {
        "conv": {"kernel": k, "bias": kb},
        "out": {"kernel": w, "bias": b},
    })
    net = KerasModelImport.import_keras_model_and_weights(path)
    x = rng.normal(size=(2, 8, 8, 1)).astype(np.float32)
    got = np.asarray(net.output(x))
    assert got.shape == (2, 3)
    np.testing.assert_allclose(got.sum(-1), 1.0, rtol=1e-5)


def test_import_functional_dispatches_sequential(tmp_path, rng):
    w = rng.normal(size=(4, 3)).astype(np.float32)
    b = rng.normal(size=(3,)).astype(np.float32)
    cfg = {"class_name": "Sequential", "config": {"name": "seq", "layers": [
        _dense_cfg("dense", 3, "softmax", input_shape=[4]),
    ]}}
    path = str(tmp_path / "seq.h5")
    _write_keras_h5(path, cfg, {"dense": {"kernel": w, "bias": b}})
    net = KerasModelImport.import_keras_model_and_weights(path)
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    assert isinstance(net, MultiLayerNetwork)


def test_import_functional_trailing_activation_folds(tmp_path, rng):
    w = rng.normal(size=(4, 3)).astype(np.float32)
    b = rng.normal(size=(3,)).astype(np.float32)
    layers = [
        {"class_name": "InputLayer", "config": {
            "name": "in", "batch_input_shape": [None, 4]}},
        {"class_name": "Dense", "config": {
            "name": "logits", "units": 3, "activation": "linear",
            "use_bias": True}, "inbound_nodes": _node(["in"])},
        {"class_name": "Activation", "config": {
            "name": "sm", "activation": "softmax"},
         "inbound_nodes": _node(["logits"])},
    ]
    cfg = _functional_cfg(layers, ["in"], ["sm"])
    path = str(tmp_path / "fold.h5")
    _write_keras_h5(path, cfg, {"logits": {"kernel": w, "bias": b}})
    net = KerasModelImport.import_keras_model_and_weights(path)
    x = rng.normal(size=(5, 4)).astype(np.float32)
    got = np.asarray(net.output(x))
    z = x @ w + b
    want = np.exp(z) / np.exp(z).sum(-1, keepdims=True)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
    # the folded graph must be trainable (scoring vertex is an OutputLayer)
    from deeplearning4j_tpu.datasets.dataset import DataSet
    labels = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 5)]
    net.fit_batch(DataSet(x, labels))


def test_import_functional_shared_layer_rejected(tmp_path, rng):
    layers = [
        {"class_name": "InputLayer", "config": {
            "name": "in", "batch_input_shape": [None, 4]}},
        {"class_name": "Dense", "config": {
            "name": "shared", "units": 4, "activation": "relu",
            "use_bias": True},
         "inbound_nodes": [[["in", 0, 0, {}]], [["in", 0, 0, {}]]]},
    ]
    cfg = _functional_cfg(layers, ["in"], ["shared"])
    path = str(tmp_path / "shared.h5")
    _write_keras_h5(path, cfg, {})
    with pytest.raises(InvalidKerasConfigurationException,
                       match="shared layer"):
        KerasModelImport.import_keras_model_and_weights(path)


def test_import_functional_multi_input_order(tmp_path, rng):
    # input_layers order (b then a) deliberately differs from the
    # layers-list definition order (a then b)
    wa = rng.normal(size=(3, 2)).astype(np.float32)
    wb = rng.normal(size=(5, 2)).astype(np.float32)
    wo = rng.normal(size=(4, 2)).astype(np.float32)
    bo = rng.normal(size=(2,)).astype(np.float32)
    layers = [
        {"class_name": "InputLayer", "config": {
            "name": "a", "batch_input_shape": [None, 3]}},
        {"class_name": "InputLayer", "config": {
            "name": "b", "batch_input_shape": [None, 5]}},
        {"class_name": "Dense", "config": {
            "name": "da", "units": 2, "activation": "linear",
            "use_bias": False}, "inbound_nodes": _node(["a"])},
        {"class_name": "Dense", "config": {
            "name": "db", "units": 2, "activation": "linear",
            "use_bias": False}, "inbound_nodes": _node(["b"])},
        {"class_name": "Concatenate", "config": {"name": "cat", "axis": -1},
         "inbound_nodes": _node(["da", "db"])},
        {"class_name": "Dense", "config": {
            "name": "out", "units": 2, "activation": "linear",
            "use_bias": True}, "inbound_nodes": _node(["cat"])},
    ]
    cfg = _functional_cfg(layers, ["b", "a"], ["out"])
    path = str(tmp_path / "multi.h5")
    _write_keras_h5(path, cfg, {
        "da": {"kernel": wa}, "db": {"kernel": wb},
        "out": {"kernel": wo, "bias": bo},
    })
    net = KerasModelImport.import_keras_model_and_weights(path)
    assert net.conf.network_inputs == ("b", "a")
    xb = rng.normal(size=(4, 5)).astype(np.float32)
    xa = rng.normal(size=(4, 3)).astype(np.float32)
    got = np.asarray(net.output(xb, xa))  # keras Model(inputs=[b, a]) order
    want = np.concatenate([xa @ wa, xb @ wb], -1) @ wo + bo
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_import_functional_fanout_dense_not_folded(tmp_path, rng):
    # logits feeds BOTH an output Activation and a second head: the fold
    # must not fire (it would corrupt the second branch)
    w1 = rng.normal(size=(4, 3)).astype(np.float32)
    w2 = rng.normal(size=(3, 2)).astype(np.float32)
    b2 = rng.normal(size=(2,)).astype(np.float32)
    layers = [
        {"class_name": "InputLayer", "config": {
            "name": "in", "batch_input_shape": [None, 4]}},
        {"class_name": "Dense", "config": {
            "name": "logits", "units": 3, "activation": "linear",
            "use_bias": False}, "inbound_nodes": _node(["in"])},
        {"class_name": "Activation", "config": {
            "name": "sm", "activation": "softmax"},
         "inbound_nodes": _node(["logits"])},
        {"class_name": "Dense", "config": {
            "name": "aux", "units": 2, "activation": "linear",
            "use_bias": True}, "inbound_nodes": _node(["logits"])},
    ]
    cfg = _functional_cfg(layers, ["in"], ["sm", "aux"])
    path = str(tmp_path / "fanout.h5")
    _write_keras_h5(path, cfg, {
        "logits": {"kernel": w1}, "aux": {"kernel": w2, "bias": b2}})
    net = KerasModelImport.import_keras_model_and_weights(path)
    x = rng.normal(size=(5, 4)).astype(np.float32)
    got_sm, got_aux = [np.asarray(o) for o in net.output(x)]
    z = x @ w1
    want_sm = np.exp(z) / np.exp(z).sum(-1, keepdims=True)
    np.testing.assert_allclose(got_sm, want_sm, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(got_aux, z @ w2 + b2, rtol=1e-4, atol=1e-5)


def test_import_extended_cnn_layers(tmp_path, rng):
    """SeparableConv2D / DepthwiseConv2D / UpSampling2D / ZeroPadding2D /
    GlobalMaxPooling2D mappings with weight repacking."""
    C, M, F = 2, 2, 3
    dk = rng.normal(size=(3, 3, C, M), scale=0.4).astype(np.float32)
    pk = rng.normal(size=(1, 1, C * M, F), scale=0.4).astype(np.float32)
    sb = rng.normal(size=(F,)).astype(np.float32)
    dk2 = rng.normal(size=(3, 3, F, 1), scale=0.4).astype(np.float32)
    w = rng.normal(size=(F, 2)).astype(np.float32)
    b = rng.normal(size=(2,)).astype(np.float32)
    cfg = {"class_name": "Sequential", "config": {"name": "seq", "layers": [
        {"class_name": "SeparableConv2D", "config": {
            "name": "sep", "filters": F, "kernel_size": [3, 3],
            "strides": [1, 1], "padding": "same", "depth_multiplier": M,
            "activation": "relu", "use_bias": True,
            "batch_input_shape": [None, 8, 8, C]}},
        {"class_name": "ZeroPadding2D", "config": {
            "name": "zp", "padding": [[1, 1], [1, 1]]}},
        {"class_name": "DepthwiseConv2D", "config": {
            "name": "dw", "kernel_size": [3, 3], "strides": [1, 1],
            "padding": "valid", "depth_multiplier": 1,
            "activation": "linear", "use_bias": False}},
        {"class_name": "UpSampling2D", "config": {
            "name": "up", "size": [2, 2]}},
        {"class_name": "GlobalMaxPooling2D", "config": {"name": "gmp"}},
        _dense_cfg("dense", 2, "softmax"),
    ]}}
    path = str(tmp_path / "ext.h5")
    _write_keras_h5(path, cfg, {
        "sep": {"depthwise_kernel": dk, "pointwise_kernel": pk, "bias": sb},
        "dw": {"depthwise_kernel": dk2},
        "dense": {"kernel": w, "bias": b},
    })
    net = KerasModelImport.import_keras_sequential_model_and_weights(path)
    x = rng.normal(size=(2, 8, 8, C)).astype(np.float32)
    out = np.asarray(net.output(x))
    assert out.shape == (2, 2)
    np.testing.assert_allclose(out.sum(-1), 1.0, rtol=1e-5)
    # depthwise weights landed repacked
    assert net.params["0"]["dW"].shape == (3, 3, 1, C * M)
    assert net.params["2"]["W"].shape == (3, 3, 1, F)


def test_import_simple_rnn(tmp_path, rng):
    k = rng.normal(size=(3, 4), scale=0.4).astype(np.float32)
    rk = rng.normal(size=(4, 4), scale=0.4).astype(np.float32)
    rb = rng.normal(size=(4,)).astype(np.float32)
    cfg = {"class_name": "Sequential", "config": {"name": "seq", "layers": [
        {"class_name": "SimpleRNN", "config": {
            "name": "rnn", "units": 4, "activation": "tanh",
            "return_sequences": True,
            "batch_input_shape": [None, 6, 3]}},
    ]}}
    path = str(tmp_path / "rnn.h5")
    _write_keras_h5(path, cfg, {
        "rnn": {"kernel": k, "recurrent_kernel": rk, "bias": rb}})
    net = KerasModelImport.import_keras_sequential_model_and_weights(path)
    np.testing.assert_allclose(np.asarray(net.params["0"]["W"]), k)
    np.testing.assert_allclose(np.asarray(net.params["0"]["RW"]), rk)
    x = rng.normal(size=(2, 6, 3)).astype(np.float32)
    y = np.asarray(net.output(x))
    assert y.shape == (2, 6, 4)
    # oracle: plain tanh RNN
    h = np.zeros((2, 4), np.float32)
    want = []
    for t in range(6):
        h = np.tanh(x[:, t] @ k + h @ rk + rb)
        want.append(h)
    np.testing.assert_allclose(y, np.stack(want, 1), rtol=1e-4, atol=1e-5)


def test_import_depthwise_numeric_oracle(tmp_path, rng):
    """depth_multiplier > 1 repack checked against an explicit loop (a
    transposed reshape would silently pass shape-only checks)."""
    C, M = 2, 2
    dk = rng.normal(size=(3, 3, C, M), scale=0.5).astype(np.float32)
    cfg = {"class_name": "Sequential", "config": {"name": "s", "layers": [
        {"class_name": "DepthwiseConv2D", "config": {
            "name": "dw", "kernel_size": [3, 3], "strides": [1, 1],
            "padding": "valid", "depth_multiplier": M,
            "activation": "linear", "use_bias": False,
            "batch_input_shape": [None, 5, 5, C]}},
    ]}}
    path = str(tmp_path / "dw.h5")
    _write_keras_h5(path, cfg, {"dw": {"depthwise_kernel": dk}})
    net = KerasModelImport.import_keras_sequential_model_and_weights(path)
    x = rng.normal(size=(1, 5, 5, C)).astype(np.float32)
    got = np.asarray(net.output(x))
    # TF depthwise semantics: out[..., c*M + m] = conv(x[..., c], dk[..., c, m])
    want = np.zeros((1, 3, 3, C * M), np.float32)
    for i in range(3):
        for j in range(3):
            patch = x[0, i:i + 3, j:j + 3, :]                 # [3, 3, C]
            for c in range(C):
                for m in range(M):
                    want[0, i, j, c * M + m] = np.sum(
                        patch[:, :, c] * dk[:, :, c, m])
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-4)


def test_import_rejects_unsupported_rnn_and_dilation(tmp_path, rng):
    for layers, match in [
        ([{"class_name": "SimpleRNN", "config": {
            "name": "r", "units": 4, "return_sequences": False,
            "batch_input_shape": [None, 6, 3]}}],
         "return_sequences"),
        ([{"class_name": "DepthwiseConv2D", "config": {
            "name": "d", "kernel_size": [3, 3], "dilation_rate": [2, 2],
            "padding": "valid", "batch_input_shape": [None, 8, 8, 2]}}],
         "dilated"),
    ]:
        cfg = {"class_name": "Sequential",
               "config": {"name": "s", "layers": layers}}
        path = str(tmp_path / f"bad_{match}.h5")
        _write_keras_h5(path, cfg, {})
        with pytest.raises(InvalidKerasConfigurationException, match=match):
            KerasModelImport.import_keras_sequential_model_and_weights(path)


# --------------------------------------------------------------------------
# round 2: GRU / Bidirectional / go_backwards / Conv1D / Conv3D /
# RepeatVector
# --------------------------------------------------------------------------

def _sigmoid(z):
    return 1.0 / (1.0 + np.exp(-z))


def _np_gru(x, kernel, rec, b_in, b_rec, reset_after):
    """Keras-order GRU forward (z|r|h packing)."""
    u = rec.shape[0]
    kz, kr, kh = np.split(kernel, 3, axis=1)
    rz, rr, rh = np.split(rec, 3, axis=1)
    bz, br, bh = np.split(b_in, 3)
    h = np.zeros((x.shape[0], u), np.float32)
    outs = []
    for t in range(x.shape[1]):
        xt = x[:, t]
        if reset_after:
            rbz, rbr, rbh = np.split(b_rec, 3)
            z = _sigmoid(xt @ kz + bz + h @ rz + rbz)
            r = _sigmoid(xt @ kr + br + h @ rr + rbr)
            hh = np.tanh(xt @ kh + bh + r * (h @ rh + rbh))
        else:
            z = _sigmoid(xt @ kz + bz + h @ rz)
            r = _sigmoid(xt @ kr + br + h @ rr)
            hh = np.tanh(xt @ kh + bh + (r * h) @ rh)
        h = z * h + (1 - z) * hh
        outs.append(h.copy())
    return np.stack(outs, 1)


@pytest.mark.parametrize("reset_after", [True, False])
def test_import_gru(tmp_path, rng, reset_after):
    u, fdim, t = 4, 3, 6
    kernel = rng.normal(size=(fdim, 3 * u)).astype(np.float32)
    rec = rng.normal(size=(u, 3 * u)).astype(np.float32)
    if reset_after:
        bias = rng.normal(size=(2, 3 * u)).astype(np.float32)
        b_in, b_rec = bias[0], bias[1]
    else:
        bias = rng.normal(size=(3 * u,)).astype(np.float32)
        b_in, b_rec = bias, None
    w2 = rng.normal(size=(u, 2)).astype(np.float32)
    cfg = {"class_name": "Sequential", "config": {"name": "g", "layers": [
        {"class_name": "GRU", "config": {
            "name": "gru", "units": u, "activation": "tanh",
            "recurrent_activation": "sigmoid", "return_sequences": True,
            "reset_after": reset_after,
            "batch_input_shape": [None, t, fdim]}},
        _dense_cfg("dense", 2, "softmax"),
    ]}}
    path = str(tmp_path / "gru.h5")
    _write_keras_h5(path, cfg, {
        "gru": {"kernel": kernel, "recurrent_kernel": rec, "bias": bias},
        "dense": {"kernel": w2, "bias": np.zeros(2, np.float32)},
    })
    net = KerasModelImport.import_keras_sequential_model_and_weights(path)
    x = rng.normal(size=(2, t, fdim)).astype(np.float32)
    got = np.asarray(net.output(x))
    hs = _np_gru(x, kernel, rec, b_in, b_rec, reset_after)
    logits = hs @ w2
    want = np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-4)


def test_import_bidirectional_lstm(tmp_path, rng):
    u, fdim, t = 3, 2, 5
    mk = lambda *s: rng.normal(size=s).astype(np.float32)
    fk, fr, fb = mk(fdim, 4 * u), mk(u, 4 * u), mk(4 * u)
    bk, br, bb = mk(fdim, 4 * u), mk(u, 4 * u), mk(4 * u)
    w2 = mk(2 * u, 2)
    cfg = {"class_name": "Sequential", "config": {"name": "b", "layers": [
        {"class_name": "Bidirectional", "config": {
            "name": "bidi", "merge_mode": "concat",
            "batch_input_shape": [None, t, fdim],
            "layer": {"class_name": "LSTM", "config": {
                "name": "lstm", "units": u, "activation": "tanh",
                "recurrent_activation": "sigmoid",
                "return_sequences": True}}}},
        _dense_cfg("dense", 2, "softmax"),
    ]}}
    path = str(tmp_path / "bidi.h5")
    # keras nests forward_lstm/backward_lstm groups under the wrapper
    with h5py.File(path, "w") as f:
        f.attrs["model_config"] = json.dumps(cfg)
        mw = f.create_group("model_weights")
        g = mw.create_group("bidi").create_group("bidi")
        gf = g.create_group("forward_lstm")
        gf.create_dataset("kernel", data=fk)
        gf.create_dataset("recurrent_kernel", data=fr)
        gf.create_dataset("bias", data=fb)
        gb = g.create_group("backward_lstm")
        gb.create_dataset("kernel", data=bk)
        gb.create_dataset("recurrent_kernel", data=br)
        gb.create_dataset("bias", data=bb)
        gd = mw.create_group("dense").create_group("dense")
        gd.create_dataset("kernel", data=w2)
        gd.create_dataset("bias", data=np.zeros(2, np.float32))

    net = KerasModelImport.import_keras_sequential_model_and_weights(path)
    x = rng.normal(size=(2, t, fdim)).astype(np.float32)
    got = np.asarray(net.output(x))

    def np_lstm(x, kernel, rec, bias):
        ki, kf_, kc, ko = np.split(kernel, 4, axis=1)
        ri, rf_, rc, ro = np.split(rec, 4, axis=1)
        bi, bf_, bc, bo = np.split(bias, 4)
        h = np.zeros((x.shape[0], u), np.float32)
        c = np.zeros((x.shape[0], u), np.float32)
        outs = []
        for ti in range(x.shape[1]):
            xt = x[:, ti]
            i = _sigmoid(xt @ ki + h @ ri + bi)
            f_ = _sigmoid(xt @ kf_ + h @ rf_ + bf_)
            g_ = np.tanh(xt @ kc + h @ rc + bc)
            o = _sigmoid(xt @ ko + h @ ro + bo)
            c = f_ * c + i * g_
            h = o * np.tanh(c)
            outs.append(h.copy())
        return np.stack(outs, 1)

    yf = np_lstm(x, fk, fr, fb)
    yb = np_lstm(x[:, ::-1], bk, br, bb)[:, ::-1]
    hs = np.concatenate([yf, yb], axis=-1)
    logits = hs @ w2
    want = np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-4)
    # the flagship follow-up: the imported model fine-tunes
    from deeplearning4j_tpu.datasets.dataset import DataSet

    y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, (2, t))]
    l0 = net.fit_batch(DataSet(x, y))
    for _ in range(5):
        l = net.fit_batch(DataSet(x, y))
    assert l < l0


def test_import_go_backwards_simple_rnn(tmp_path, rng):
    u, fdim, t = 3, 2, 4
    k = rng.normal(size=(fdim, u)).astype(np.float32)
    r = rng.normal(size=(u, u)).astype(np.float32)
    b = rng.normal(size=(u,)).astype(np.float32)
    cfg = {"class_name": "Sequential", "config": {"name": "s", "layers": [
        {"class_name": "SimpleRNN", "config": {
            "name": "rnn", "units": u, "activation": "tanh",
            "return_sequences": True, "go_backwards": True,
            "batch_input_shape": [None, t, fdim]}},
        _dense_cfg("dense", 2, "softmax"),
    ]}}
    path = str(tmp_path / "gb.h5")
    _write_keras_h5(path, cfg, {
        "rnn": {"kernel": k, "recurrent_kernel": r, "bias": b},
        "dense": {"kernel": rng.normal(size=(u, 2)).astype(np.float32),
                  "bias": np.zeros(2, np.float32)},
    })
    net = KerasModelImport.import_keras_sequential_model_and_weights(path)
    x = rng.normal(size=(2, t, fdim)).astype(np.float32)
    got = np.asarray(net.output(x))
    assert net.conf.layers[0].go_backwards is True
    # keras go_backwards: process reversed input, outputs in processing
    # order — layer output equals rnn(x[:, ::-1])
    wd = np.asarray(net.params["1"]["W"])
    h = np.zeros((2, u), np.float32)
    outs = []
    for ti in range(t - 1, -1, -1):
        h = np.tanh(x[:, ti] @ k + h @ r + b)
        outs.append(h.copy())
    hs = np.stack(outs, 1)
    logits = hs @ wd
    want = np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-4)


def test_import_conv1d_conv3d_repeatvector(tmp_path, rng):
    # Conv1D over [b, t, f]
    k1 = rng.normal(size=(3, 2, 4), scale=0.5).astype(np.float32)
    b1 = rng.normal(size=(4,)).astype(np.float32)
    cfg = {"class_name": "Sequential", "config": {"name": "c", "layers": [
        {"class_name": "Conv1D", "config": {
            "name": "conv1d", "filters": 4, "kernel_size": [3],
            "strides": [1], "padding": "valid", "activation": "relu",
            "use_bias": True, "batch_input_shape": [None, 8, 2]}},
    ]}}
    path = str(tmp_path / "c1.h5")
    _write_keras_h5(path, cfg, {"conv1d": {"kernel": k1, "bias": b1}})
    net = KerasModelImport.import_keras_sequential_model_and_weights(path)
    x = rng.normal(size=(2, 8, 2)).astype(np.float32)
    got = np.asarray(net.output(x))
    want = np.zeros((2, 6, 4), np.float32)
    for i in range(6):
        want[:, i] = np.maximum(
            np.einsum("bwc,wco->bo", x[:, i:i + 3], k1) + b1, 0.0)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    # Conv3D over [b, d, h, w, c]
    k3 = rng.normal(size=(2, 2, 2, 1, 3), scale=0.5).astype(np.float32)
    b3 = np.zeros(3, np.float32)
    cfg3 = {"class_name": "Sequential", "config": {"name": "c3", "layers": [
        {"class_name": "Conv3D", "config": {
            "name": "conv3d", "filters": 3, "kernel_size": [2, 2, 2],
            "strides": [1, 1, 1], "padding": "valid",
            "activation": "linear", "use_bias": True,
            "batch_input_shape": [None, 4, 4, 4, 1]}},
    ]}}
    p3 = str(tmp_path / "c3.h5")
    _write_keras_h5(p3, cfg3, {"conv3d": {"kernel": k3, "bias": b3}})
    net3 = KerasModelImport.import_keras_sequential_model_and_weights(p3)
    x3 = rng.normal(size=(1, 4, 4, 4, 1)).astype(np.float32)
    got3 = np.asarray(net3.output(x3))
    assert got3.shape == (1, 3, 3, 3, 3)
    want000 = np.einsum("dhwc,dhwco->o", x3[0, :2, :2, :2], k3)
    np.testing.assert_allclose(got3[0, 0, 0, 0], want000, rtol=1e-4,
                               atol=1e-5)

    # RepeatVector: [b, f] -> [b, n, f]
    cfgr = {"class_name": "Sequential", "config": {"name": "r", "layers": [
        _dense_cfg("dense", 3, "tanh", input_shape=[2]),
        {"class_name": "RepeatVector", "config": {"name": "rep", "n": 4}},
    ]}}
    pr = str(tmp_path / "rep.h5")
    wd = rng.normal(size=(2, 3)).astype(np.float32)
    _write_keras_h5(pr, cfgr, {
        "dense": {"kernel": wd, "bias": np.zeros(3, np.float32)}})
    netr = KerasModelImport.import_keras_sequential_model_and_weights(pr)
    xr = rng.normal(size=(2, 2)).astype(np.float32)
    gotr = np.asarray(netr.output(xr))
    wantr = np.repeat(np.tanh(xr @ wd)[:, None, :], 4, axis=1)
    np.testing.assert_allclose(gotr, wantr, rtol=1e-4, atol=1e-5)


def test_import_permute(tmp_path, rng):
    cfg = {"class_name": "Sequential", "config": {"name": "p", "layers": [
        {"class_name": "Permute", "config": {
            "name": "perm", "dims": [2, 1],
            "batch_input_shape": [None, 4, 3]}},
    ]}}
    path = str(tmp_path / "perm.h5")
    _write_keras_h5(path, cfg, {})
    net = KerasModelImport.import_keras_sequential_model_and_weights(path)
    x = rng.normal(size=(2, 4, 3)).astype(np.float32)
    got = np.asarray(net.output(x))
    np.testing.assert_allclose(got, x.transpose(0, 2, 1), rtol=1e-6)


def test_permute_validates_dims():
    from deeplearning4j_tpu.conf.layers_extra import Permute
    from deeplearning4j_tpu.conf.inputs import InputType

    with pytest.raises(ValueError, match="permutation"):
        Permute(dims=(1, 3)).output_type(InputType.recurrent(3, timesteps=4))


def test_import_bidirectional_over_go_backwards(tmp_path, rng):
    """Round 3 (round-2 residual): Bidirectional over a go_backwards
    inner layer imports with Keras' exact composition — the forward copy
    processes the sequence REVERSED and emits in processing order, the
    backward copy is the flipped clone (plain order) whose output the
    wrapper reverses."""
    u, fdim, t = 3, 2, 5
    mk = lambda *s: rng.normal(size=s).astype(np.float32)  # noqa: E731
    fk, fr, fb = mk(fdim, 4 * u), mk(u, 4 * u), mk(4 * u)
    bk, br, bb = mk(fdim, 4 * u), mk(u, 4 * u), mk(4 * u)
    w2 = mk(2 * u, 2)
    cfg = {"class_name": "Sequential", "config": {"name": "b", "layers": [
        {"class_name": "Bidirectional", "config": {
            "name": "bidi", "merge_mode": "concat",
            "batch_input_shape": [None, t, fdim],
            "layer": {"class_name": "LSTM", "config": {
                "name": "lstm", "units": u, "activation": "tanh",
                "recurrent_activation": "sigmoid",
                "go_backwards": True,
                "return_sequences": True}}}},
        _dense_cfg("dense", 2, "softmax"),
    ]}}
    path = str(tmp_path / "bidi_gb.h5")
    with h5py.File(path, "w") as f:
        f.attrs["model_config"] = json.dumps(cfg)
        mw = f.create_group("model_weights")
        g = mw.create_group("bidi").create_group("bidi")
        gf = g.create_group("forward_lstm")
        gf.create_dataset("kernel", data=fk)
        gf.create_dataset("recurrent_kernel", data=fr)
        gf.create_dataset("bias", data=fb)
        gb = g.create_group("backward_lstm")
        gb.create_dataset("kernel", data=bk)
        gb.create_dataset("recurrent_kernel", data=br)
        gb.create_dataset("bias", data=bb)
        gd = mw.create_group("dense").create_group("dense")
        gd.create_dataset("kernel", data=w2)
        gd.create_dataset("bias", data=np.zeros(2, np.float32))

    net = KerasModelImport.import_keras_sequential_model_and_weights(path)
    x = rng.normal(size=(2, t, fdim)).astype(np.float32)
    got = np.asarray(net.output(x))

    def np_lstm(x, kernel, rec, bias):
        ki, kf_, kc, ko = np.split(kernel, 4, axis=1)
        ri, rf_, rc, ro = np.split(rec, 4, axis=1)
        bi, bf_, bc, bo = np.split(bias, 4)
        h = np.zeros((x.shape[0], u), np.float32)
        c = np.zeros((x.shape[0], u), np.float32)
        outs = []
        for ti in range(x.shape[1]):
            xt = x[:, ti]
            i = _sigmoid(xt @ ki + h @ ri + bi)
            f_ = _sigmoid(xt @ kf_ + h @ rf_ + bf_)
            g_ = np.tanh(xt @ kc + h @ rc + bc)
            o = _sigmoid(xt @ ko + h @ ro + bo)
            c = f_ * c + i * g_
            h = o * np.tanh(c)
            outs.append(h.copy())
        return np.stack(outs, 1)

    yf = np_lstm(x[:, ::-1], fk, fr, fb)          # NOT re-reversed (Keras)
    yb = np_lstm(x, bk, br, bb)[:, ::-1]          # flipped clone, reversed
    hs = np.concatenate([yf, yb], axis=-1)
    logits = hs @ w2
    want = np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-4)


# --------------------------------------------------------------------------
# round 3: HDF5 layout robustness (round-2 advisor: the fixtures are
# self-authored, so exercise the reader over the on-disk variants a real
# Keras writer produces — chunked/compressed datasets, attribute encodings,
# wider dtypes)
# --------------------------------------------------------------------------

def _mlp_cfg(fdim=4):
    return {"class_name": "Sequential", "config": {"name": "m", "layers": [
        {"class_name": "Dense", "config": {
            "name": "d1", "units": 6, "activation": "tanh",
            "batch_input_shape": [None, fdim]}},
        _dense_cfg("d2", 3, "softmax"),
    ]}}


@pytest.mark.parametrize("variant", ["chunked_gzip", "bytes_attr",
                                     "vlen_str_attr", "float64"])
def test_h5_layout_variants_import_identically(tmp_path, rng, variant):
    fdim = 4
    w1 = rng.normal(size=(fdim, 6)).astype(np.float32)
    b1 = rng.normal(size=(6,)).astype(np.float32)
    w2 = rng.normal(size=(6, 3)).astype(np.float32)
    b2 = rng.normal(size=(3,)).astype(np.float32)
    cfg_json = json.dumps(_mlp_cfg(fdim))

    # reference file via the plain writer
    ref = str(tmp_path / "ref.h5")
    _write_keras_h5(ref, _mlp_cfg(fdim), {
        "d1": {"kernel": w1, "bias": b1},
        "d2": {"kernel": w2, "bias": b2}})
    x = rng.normal(size=(5, fdim)).astype(np.float32)
    want = np.asarray(
        KerasModelImport.import_keras_sequential_model_and_weights(ref)
        .output(x))

    path = str(tmp_path / f"{variant}.h5")
    with h5py.File(path, "w") as f:
        if variant == "bytes_attr":
            f.attrs["model_config"] = np.bytes_(cfg_json)
        elif variant == "vlen_str_attr":
            f.attrs.create("model_config", cfg_json,
                           dtype=h5py.string_dtype("utf-8"))
        else:
            f.attrs["model_config"] = cfg_json
        mw = f.create_group("model_weights")
        for name, (k, b) in (("d1", (w1, b1)), ("d2", (w2, b2))):
            g = mw.create_group(name).create_group(name)
            if variant == "chunked_gzip":
                g.create_dataset("kernel", data=k, chunks=(2, 3),
                                 compression="gzip", shuffle=True)
                g.create_dataset("bias", data=b, chunks=(2,),
                                 compression="gzip")
            elif variant == "float64":
                g.create_dataset("kernel", data=k.astype(np.float64))
                g.create_dataset("bias", data=b.astype(np.float64))
            else:
                g.create_dataset("kernel", data=k)
                g.create_dataset("bias", data=b)

    net = KerasModelImport.import_keras_sequential_model_and_weights(path)
    got = np.asarray(net.output(x))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
