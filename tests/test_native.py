"""Native host-runtime library: codec/CSV/gather parity between the C++
OpenMP path and the numpy fallbacks (reference: libnd4j encodeThreshold /
encodeBitmap kernels + DataVec native ETL, SURVEY.md §2.1)."""

import os
import shutil
import subprocess
import tempfile

import numpy as np
import pytest

from deeplearning4j_tpu import native


def _toolchain_supports_native() -> bool:
    """Capability probe for the environments that can build the native
    library at all: a ``g++`` on PATH whose libstdc++ has FLOATING-POINT
    ``std::from_chars`` (C++17 <charconv>; GCC's standard library only
    grew it in GCC 11, and the CSV parser depends on it). Containers
    without that capability run the numpy fallbacks — covered by the
    rest of this file — so the build test skips instead of failing
    identically every round."""
    if shutil.which("g++") is None:
        return False
    probe = ("#include <charconv>\n"
             "int main(){float v; const char b[]=\"1.5\";"
             " std::from_chars(b, b+3, v); return 0;}\n")
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "probe.cpp")
        with open(path, "w") as f:
            f.write(probe)
        try:
            return subprocess.run(
                ["g++", "-std=c++17", "-fsyntax-only", path],
                capture_output=True, timeout=60).returncode == 0
        except Exception:
            return False


@pytest.fixture
def grads(rng):
    return rng.normal(size=50_000).astype(np.float32)


def _expected_flips(g, tau):
    return np.where(g >= tau, tau,
                    np.where(g <= -tau, -tau, 0)).astype(np.float32)


def test_native_builds():
    if not _toolchain_supports_native():
        pytest.skip("container toolchain cannot build the native library "
                    "(no g++, or libstdc++ lacks floating-point "
                    "std::from_chars) — numpy fallbacks cover this "
                    "environment")
    assert native.available(), "native library failed to build/load"
    assert native.get_lib().dl4j_native_version() == 2


def test_threshold_roundtrip(grads):
    tau = 1.0
    enc = native.encode_threshold(grads, tau)
    dec = native.decode_threshold(enc, tau, grads.size)
    np.testing.assert_allclose(dec, _expected_flips(grads, tau))
    # decode accumulates
    dec2 = native.decode_threshold(enc, tau, grads.size, out=dec)
    np.testing.assert_allclose(dec2, 2 * _expected_flips(grads, tau))


def test_bitmap_roundtrip(grads):
    tau = 0.5
    words, nnz = native.encode_bitmap(grads, tau)
    assert nnz == int(np.sum(np.abs(grads) >= tau))
    dec = native.decode_bitmap(words, tau, grads.size)
    np.testing.assert_allclose(dec, _expected_flips(grads, tau))


def test_fallback_matches_native(monkeypatch, grads):
    tau = 1.0
    enc_n = native.encode_threshold(grads, tau)
    words_n, nnz_n = native.encode_bitmap(grads[:2000], tau)
    monkeypatch.setattr(native, "get_lib", lambda: None)
    enc_p = native.encode_threshold(grads, tau)
    np.testing.assert_array_equal(np.sort(enc_n), np.sort(enc_p))
    words_p, nnz_p = native.encode_bitmap(grads[:2000], tau)
    assert nnz_p == nnz_n
    np.testing.assert_array_equal(words_n, words_p)
    dec_p = native.decode_threshold(enc_p, tau, grads.size)
    np.testing.assert_allclose(dec_p, _expected_flips(grads, tau))


def test_parse_numeric_csv():
    m = native.parse_numeric_csv("# header\n1.5,2,3\n4,5.25,-6e2\n",
                                 skip_lines=1)
    np.testing.assert_allclose(
        m, np.asarray([[1.5, 2, 3], [4, 5.25, -600]], np.float32))
    with pytest.raises(ValueError):
        native.parse_numeric_csv(b"1,abc,3\n")


def test_parse_csv_matches_python_fallback(monkeypatch, rng):
    data = rng.normal(size=(200, 7)).astype(np.float32)
    text = "\n".join(",".join(f"{v:.6g}" for v in row) for row in data)
    m_native = native.parse_numeric_csv(text)
    monkeypatch.setattr(native, "get_lib", lambda: None)
    m_py = native.parse_numeric_csv(text)
    np.testing.assert_allclose(m_native, m_py, rtol=1e-6)
    np.testing.assert_allclose(m_native, data, rtol=1e-4)


def test_read_numeric_csv_from_file(tmp_path, rng):
    from deeplearning4j_tpu.datavec.records import read_numeric_csv
    from deeplearning4j_tpu.datavec.split import FileSplit

    data = rng.normal(size=(50, 4)).astype(np.float32)
    f = tmp_path / "data.csv"
    f.write_text("\n".join(",".join(f"{v:.6g}" for v in r) for r in data))
    m = read_numeric_csv(str(f))
    np.testing.assert_allclose(m, data, rtol=1e-4)
    m2 = read_numeric_csv(FileSplit(str(tmp_path), allowed_extensions=[".csv"]))
    np.testing.assert_allclose(m2, data, rtol=1e-4)


def test_u8_and_gather(rng):
    u = rng.integers(0, 256, size=(3, 28, 28), dtype=np.uint8)
    f = native.u8_to_f32(u)
    np.testing.assert_allclose(f, u.astype(np.float32) / 255.0)
    src = rng.normal(size=(100, 5, 2)).astype(np.float32)
    idx = rng.permutation(100)[:32]
    np.testing.assert_array_equal(native.gather_rows(src, idx), src[idx])


def test_iterator_uses_native_gather(rng):
    from deeplearning4j_tpu.datasets.iterators import ArrayDataSetIterator

    feats = rng.normal(size=(64, 3)).astype(np.float32)
    labels = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 64)]
    it = ArrayDataSetIterator(feats, labels, batch=16, shuffle=True, seed=7)
    seen = np.concatenate([ds.features for ds in it])
    np.testing.assert_allclose(np.sort(seen.ravel()),
                               np.sort(feats.ravel()))


def test_csv_whitespace_cell_is_error_not_row_steal():
    # a whitespace-only cell must error, not steal the next row's value
    with pytest.raises(ValueError):
        native.parse_numeric_csv("1, \n2,3\n")
    # but padded numeric cells parse fine
    m = native.parse_numeric_csv("1 , 2\n 3,4\n")
    np.testing.assert_allclose(m, [[1, 2], [3, 4]])


def test_csv_ragged_rows_are_errors():
    with pytest.raises(ValueError):
        native.parse_numeric_csv("1,2\n3,4,5\n")
    with pytest.raises(ValueError):
        native.parse_numeric_csv("1,2,3\n4,5\n")


def test_decode_threshold_duplicate_indices():
    # concatenated multi-worker messages contain repeats; every flip counts
    enc = np.asarray([1, 1, 1, -2, -2, 3] * 30000, np.int32)
    out = native.decode_threshold(enc, 0.5, 4)
    np.testing.assert_allclose(
        out, [0.5 * 3 * 30000, -0.5 * 2 * 30000, 0.5 * 30000, 0.0])


def test_decode_bounds_validation():
    with pytest.raises(ValueError):
        native.decode_threshold(np.asarray([10_000_000], np.int32), 0.5, 4)
    with pytest.raises(ValueError):
        native.decode_threshold(np.asarray([0], np.int32), 0.5, 4)
    with pytest.raises(ValueError):
        native.decode_bitmap(np.zeros(1, np.uint64), 0.5, 1000)


def test_gather_numpy_semantics(rng):
    src = rng.normal(size=(4, 3)).astype(np.float32)
    np.testing.assert_array_equal(
        native.gather_rows(src, np.asarray([-1, 0])), src[[-1, 0]])
    with pytest.raises(IndexError):
        native.gather_rows(src, np.asarray([4]))
    with pytest.raises(IndexError):
        native.gather_rows(src, np.asarray([-5]))


def test_csv_whitespace_line_parity(monkeypatch):
    m_native = native.parse_numeric_csv("1,2\n \n3,4\n")
    monkeypatch.setattr(native, "get_lib", lambda: None)
    m_py = native.parse_numeric_csv("1,2\n \n3,4\n")
    np.testing.assert_array_equal(m_native, m_py)
    assert native.parse_numeric_csv("").shape == (0, 0)


def test_w2v_pairs_native_vs_fallback(monkeypatch, rng):
    sents = [rng.integers(0, 50, rng.integers(2, 12)).astype(np.int32)
             for _ in range(30)]
    pn = native.w2v_pairs(sents, window=3, seed=9)
    assert pn.shape[1] == 2 and len(pn) > 0
    monkeypatch.setattr(native, "get_lib", lambda: None)
    pf = native.w2v_pairs(sents, window=3, seed=9)
    # both paths replay the identical xorshift64 stream: BIT-EQUAL pairs
    np.testing.assert_array_equal(pn, pf)


def test_w2v_pairs_contents(rng):
    # positional identity: with window w every emitted pair's context must
    # lie within w positions of its center in the generating sentence
    sent = np.arange(100, 110, dtype=np.int32)  # unique tokens
    w = 2
    pairs = native.w2v_pairs([sent], window=w, seed=5)
    pos = {int(t): i for i, t in enumerate(sent)}
    for c, ctx in pairs.tolist():
        d = abs(pos[c] - pos[ctx])
        assert 1 <= d <= w
    # every center token appears (each token emits >= 1 pair)
    assert {int(t) for t in sent} == {int(c) for c, _ in pairs.tolist()}


def test_w2v_pairs_rejects_bad_window(rng):
    sents = [rng.integers(0, 9, 5).astype(np.int32)]
    with pytest.raises(ValueError):
        native.w2v_pairs(sents, window=0)
    with pytest.raises(ValueError):
        native.w2v_pairs(sents, window=-1)


def test_w2v_pairs_chunked_matches_unchunked(monkeypatch, rng):
    sents = [rng.integers(0, 50, rng.integers(2, 12)).astype(np.int32)
             for _ in range(40)]
    whole = native.w2v_pairs(sents, window=3, seed=11)
    monkeypatch.setattr(native, "_W2V_CHUNK_TOKENS", 32)  # force many chunks
    chunked = native.w2v_pairs(sents, window=3, seed=11)
    np.testing.assert_array_equal(whole, chunked)
