"""Conf-DSL MoE layer (round-4 productization of expert parallelism):
builder -> ComputationGraph/MultiLayerNetwork lowering, aux-loss wiring,
serde round-trip, and data+expert-parallel training through
ParallelWrapper(expert_parallel=True) with NO hand-written shard_map —
pinned against the single-device run."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.conf import Activation, InputType, WeightInit
from deeplearning4j_tpu.conf.graph import ElementWiseOp, ElementWiseVertex
from deeplearning4j_tpu.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.conf.layers_moe import AUX_LOSS_KEY, MoELayer
from deeplearning4j_tpu.conf.losses import LossMCXENT
from deeplearning4j_tpu.conf.multilayer import NeuralNetConfiguration
from deeplearning4j_tpu.conf.updaters import Sgd
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.parallel import mesh as mesh_mod
from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper

D, CLASSES = 16, 4


def _moe_graph(n_experts=4, top_k=2, aux_weight=1e-2, seed=7,
               capacity_factor=8.0):
    """input -> dense -> MoE (residual FFN) -> output; recurrent-free so
    the EP token count is just the batch."""
    g = (NeuralNetConfiguration.builder()
         .seed(seed).updater(Sgd(learning_rate=0.1))
         .weight_init(WeightInit.XAVIER)
         .graph_builder()
         .add_inputs("input")
         .set_input_types(InputType.feed_forward(D)))
    g.add_layer("embed", DenseLayer(n_out=D, activation=Activation.TANH),
                "input")
    g.add_layer("moe", MoELayer(
        n_experts=n_experts, d_hidden=2 * D, top_k=top_k,
        aux_weight=aux_weight, capacity_factor=capacity_factor), "embed")
    g.add_layer("out", OutputLayer(n_out=CLASSES,
                                   activation=Activation.SOFTMAX,
                                   loss_fn=LossMCXENT()), "moe")
    g.set_outputs("out")
    return g.build()


def _batch(n=32, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, D)).astype(np.float32)
    y = np.eye(CLASSES, dtype=np.float32)[rng.integers(0, CLASSES, n)]
    return DataSet(x, y)


def test_moe_layer_trains_locally():
    net = ComputationGraph(_moe_graph()).init()
    ds = _batch()
    first = net.fit_batch(ds)
    for _ in range(30):
        loss = net.fit_batch(ds)
    assert loss < first * 0.7
    out = net.output(ds.features)
    assert out.shape == (32, CLASSES)
    np.testing.assert_allclose(np.asarray(out).sum(-1), 1.0, rtol=1e-4)


def test_moe_aux_loss_reaches_objective():
    """aux_weight > 0 changes the reported training loss by exactly the
    (weighted) load-balance term stashed under AUX_LOSS_KEY."""
    n0 = ComputationGraph(_moe_graph(aux_weight=0.0)).init()
    n1 = ComputationGraph(_moe_graph(aux_weight=0.5)).init()
    n1.params = jax.tree_util.tree_map(
        lambda a: jnp.array(a, copy=True), dict(n0.params))
    n1.state = jax.tree_util.tree_map(
        lambda a: jnp.array(a, copy=True), dict(n0.state))
    ds = _batch()
    l0 = n0.fit_batch(ds)
    l1 = n1.fit_batch(ds)
    aux = float(n1.state["moe"][AUX_LOSS_KEY])
    assert aux > 0.0
    np.testing.assert_allclose(l1 - l0, aux, rtol=1e-3, atol=1e-5)


def test_moe_layer_serde_round_trip(tmp_path):
    from deeplearning4j_tpu.util import serializer

    net = ComputationGraph(_moe_graph(top_k=1)).init()
    ds = _batch()
    net.fit_batch(ds)
    path = str(tmp_path / "moe.zip")
    serializer.write_model(net, path)
    loaded = serializer.restore_computation_graph(path)
    lay = loaded.conf.vertex_map()["moe"].vertex.layer
    assert isinstance(lay, MoELayer)
    assert (lay.n_experts, lay.top_k) == (4, 1)
    np.testing.assert_allclose(
        np.asarray(loaded.output(ds.features)),
        np.asarray(net.output(ds.features)), rtol=1e-5, atol=1e-6)


def test_moe_expert_parallel_matches_single_device():
    """One train step under ParallelWrapper(expert_parallel=True) on the
    8-device mesh == the plain single-device fit_batch, elementwise on
    every parameter (aux_weight=0: the aux statistics are per-shard by
    design; capacity ample so no drops)."""
    mesh = mesh_mod.single_host_mesh()
    if mesh.shape[mesh_mod.DATA_AXIS] != 8:
        pytest.skip("needs the 8-device CPU mesh")
    ds = _batch(n=32, seed=3)

    ref = ComputationGraph(_moe_graph(n_experts=8, aux_weight=0.0)).init()
    p0 = jax.tree_util.tree_map(lambda a: np.asarray(a).copy(), dict(ref.params))
    s0 = jax.tree_util.tree_map(lambda a: np.asarray(a).copy(), dict(ref.state))
    ref_loss = ref.fit_batch(ds)

    ep = ComputationGraph(_moe_graph(n_experts=8, aux_weight=0.0)).init()
    ep.params = jax.tree_util.tree_map(jnp.asarray, p0)
    ep.state = jax.tree_util.tree_map(jnp.asarray, s0)
    pw = ParallelWrapper(ep, mesh=mesh, expert_parallel=True)
    pw.fit(ds)
    np.testing.assert_allclose(pw.score_value, ref_loss, rtol=1e-4)
    for k in ref.params:
        for pk in ref.params[k]:
            np.testing.assert_allclose(
                np.asarray(ep.params[k][pk]),
                np.asarray(ref.params[k][pk]), rtol=1e-3, atol=1e-5,
                err_msg=f"{k}/{pk}")


def test_moe_expert_parallel_multi_step_training():
    """The EP wrapper actually trains (loss decreases over steps) with
    top-2 routing and a real aux weight."""
    mesh = mesh_mod.single_host_mesh()
    if mesh.shape[mesh_mod.DATA_AXIS] != 8:
        pytest.skip("needs the 8-device CPU mesh")
    net = ComputationGraph(_moe_graph(n_experts=8, aux_weight=1e-2)).init()
    pw = ParallelWrapper(net, mesh=mesh, expert_parallel=True)
    ds = _batch(n=64, seed=4)
    losses = []
    for _ in range(12):
        pw.fit(ds)
        losses.append(pw.score_value)
    assert losses[-1] < losses[0] * 0.8


def test_moe_expert_count_must_divide_axis():
    mesh = mesh_mod.single_host_mesh()
    if mesh.shape[mesh_mod.DATA_AXIS] != 8:
        pytest.skip("needs the 8-device CPU mesh")
    net = ComputationGraph(_moe_graph(n_experts=6)).init()
    with pytest.raises(ValueError, match="multiple of the data-axis"):
        ParallelWrapper(net, mesh=mesh, expert_parallel=True)


def test_zoo_transformer_moe_trains_expert_parallel():
    """The round-4 'done' criterion: a transformer config with an MoE
    layer trains data+expert-parallel straight from the builder DSL (zoo
    TransformerEncoder(moe_experts=...) -> ParallelWrapper(
    expert_parallel=True)) — no hand-written shard_map anywhere."""
    from deeplearning4j_tpu.conf.updaters import Adam
    from deeplearning4j_tpu.zoo.graphs import TransformerEncoder

    mesh = mesh_mod.single_host_mesh()
    if mesh.shape[mesh_mod.DATA_AXIS] != 8:
        pytest.skip("needs the 8-device CPU mesh")
    model = TransformerEncoder(
        num_classes=3, embed_dim=16, n_heads=2, n_layers=2, max_len=8,
        moe_experts=8, moe_top_k=2, moe_capacity_factor=4.0,
        updater=Adam(learning_rate=3e-3))
    net = model.init()
    assert any("moe" in k for k in net.params)
    pw = ParallelWrapper(net, mesh=mesh, expert_parallel=True)
    rng = np.random.default_rng(11)
    x = rng.normal(size=(16, 8, 16)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 16)]
    ds = DataSet(x, y)
    losses = []
    for _ in range(15):
        pw.fit(ds)
        losses.append(pw.score_value)
    assert losses[-1] < losses[0] * 0.8
    out = net.output(x)
    assert out.shape == (16, 3)


def test_moe_aux_not_in_eval_score():
    """Round-4 review regression: the stale training-step aux must NOT
    inflate eval scores (score() after fit_batch)."""
    n0 = ComputationGraph(_moe_graph(aux_weight=0.0)).init()
    n1 = ComputationGraph(_moe_graph(aux_weight=0.5)).init()
    n1.params = jax.tree_util.tree_map(
        lambda a: jnp.array(a, copy=True), dict(n0.params))
    n1.state = jax.tree_util.tree_map(
        lambda a: jnp.array(a, copy=True), dict(n0.state))
    ds = _batch()
    n0.fit_batch(ds)
    n1.fit_batch(ds)
    assert float(n1.state["moe"][AUX_LOSS_KEY]) > 0.0
    # eval scores on FRESH data must not include the stashed aux: the two
    # nets took the same data-loss trajectory modulo the aux gradient,
    # so the scores differ by training dynamics only, not by +0.5*aux
    ds2 = _batch(seed=99)
    s0, s1 = n0.score(ds2), n1.score(ds2)
    aux = float(n1.state["moe"][AUX_LOSS_KEY])
    assert abs(s1 - s0) < 0.5 * aux


def test_moe_expert_parallel_with_l2_matches_single_device():
    """Round-4 review regression: l2 over the expert-sharded w1/w2 must
    contribute its FULL (all-experts) penalty under EP, matching the
    single-device step."""
    mesh = mesh_mod.single_host_mesh()
    if mesh.shape[mesh_mod.DATA_AXIS] != 8:
        pytest.skip("needs the 8-device CPU mesh")

    def build():
        g = (NeuralNetConfiguration.builder()
             .seed(7).updater(Sgd(learning_rate=0.1))
             .weight_init(WeightInit.XAVIER)
             .l2(1e-2)
             .graph_builder()
             .add_inputs("input")
             .set_input_types(InputType.feed_forward(D)))
        g.add_layer("embed", DenseLayer(n_out=D, activation=Activation.TANH),
                    "input")
        g.add_layer("moe", MoELayer(n_experts=8, d_hidden=2 * D, top_k=2,
                                    aux_weight=0.0, capacity_factor=8.0),
                    "embed")
        g.add_layer("out", OutputLayer(n_out=CLASSES,
                                       activation=Activation.SOFTMAX,
                                       loss_fn=LossMCXENT()), "moe")
        g.set_outputs("out")
        return ComputationGraph(g.build()).init()

    ds = _batch(n=32, seed=5)
    ref = build()
    p0 = jax.tree_util.tree_map(lambda a: np.asarray(a).copy(),
                                dict(ref.params))
    ref_loss = ref.fit_batch(ds)

    ep = build()
    ep.params = jax.tree_util.tree_map(jnp.asarray, p0)
    pw = ParallelWrapper(ep, mesh=mesh, expert_parallel=True)
    pw.fit(ds)
    np.testing.assert_allclose(pw.score_value, ref_loss, rtol=1e-4)
    for k in ref.params:
        for pk in ref.params[k]:
            np.testing.assert_allclose(
                np.asarray(ep.params[k][pk]),
                np.asarray(ref.params[k][pk]), rtol=1e-3, atol=1e-5,
                err_msg=f"{k}/{pk}")
