"""Declarative sharding subsystem (sharding/): rule tables, ShardingPlan,
ZeRO optimizer-state sharding, sharding-aware checkpoints.

Runs on the conftest's 8 virtual CPU devices — the same simulated mesh
the ParallelWrapper suites use. The load-bearing invariants:

- ZeRO mode trains BIT-identical (params AND updater state) to the
  all-reduce DP path on the same stream;
- a snapshot saved from a sharded run restores digest-verified onto a
  DIFFERENT mesh shape;
- the reduce-scatter/all-gather ops feed the same collective counter
  series bucketed_psum populates;
- sharded executables get their own AOT-cache keys (zero recompiles
  across refits, no aliasing between placements).
"""

import json
import os
import shutil
import tempfile
import urllib.request

import numpy as np
import pytest

import jax
from jax.sharding import PartitionSpec as P

from deeplearning4j_tpu.conf import Activation, InputType, WeightInit
from deeplearning4j_tpu.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.conf.losses import LossMCXENT
from deeplearning4j_tpu.conf.multilayer import NeuralNetConfiguration
from deeplearning4j_tpu.conf.updaters import Adam, Nesterovs, Sgd
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import (
    ArrayDataSetIterator,
    ListDataSetIterator,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel import mesh as mesh_mod
from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper
from deeplearning4j_tpu.sharding import (
    ShardingPlan,
    ZeroSpec,
    create_opt_spec,
    match_partition_rules,
)

pytestmark = pytest.mark.sharding


def _conf(updater=None, seed=12345):
    return (NeuralNetConfiguration.builder()
            .seed(seed)
            .updater(updater or Adam(learning_rate=0.05))
            .weight_init(WeightInit.XAVIER)
            .list()
            .layer(DenseLayer(n_out=16, activation=Activation.TANH))
            .layer(OutputLayer(n_out=3, activation=Activation.SOFTMAX,
                               loss_fn=LossMCXENT()))
            .set_input_type(InputType.feed_forward(4))
            .build())


def _data(n=64, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 4)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, n)]
    return x, y


def _train(updater=None, n=64, batch=16, epochs=2, **kw):
    net = MultiLayerNetwork(_conf(updater)).init()
    pw = ParallelWrapper(net, workers=kw.pop("workers", 8), **kw)
    x, y = _data(n)
    pw.fit(ArrayDataSetIterator(x, y, batch=batch), epochs=epochs)
    return net, pw


def _bit_identical(a, b):
    la = jax.tree_util.tree_leaves((a.params, a.opt_state))
    lb = jax.tree_util.tree_leaves((b.params, b.opt_state))
    assert jax.tree_util.tree_structure(a.opt_state) == \
        jax.tree_util.tree_structure(b.opt_state)
    for u, v in zip(la, lb):
        assert np.asarray(u).shape == np.asarray(v).shape
        np.testing.assert_array_equal(np.asarray(u), np.asarray(v))


# ---------------------------------------------------------------------------
# rule tables
# ---------------------------------------------------------------------------

def _toy_params():
    return {"0": {"W": np.zeros((4, 16), np.float32),
                  "b": np.zeros((16,), np.float32)},
            "1": {"W": np.zeros((16, 3), np.float32),
                  "s": np.zeros((), np.float32)}}


def test_match_partition_rules_first_match_wins_and_scalars_skip():
    specs = match_partition_rules(
        [(r"0/W$", P("model")), (r"W$", P(None, "model")), (r".*", P())],
        _toy_params())
    assert specs["0"]["W"] == P("model")          # first match wins
    assert specs["1"]["W"] == P(None, "model")
    assert specs["0"]["b"] == P()
    # the scalar never consults the table (no rule matches "1/s" besides
    # the catch-all, but even without one it would replicate)
    assert specs["1"]["s"] == P()


def test_match_partition_rules_scalar_skips_without_catchall():
    specs = match_partition_rules(
        [(r"W$", P(None, "model")), (r"b$", P())], _toy_params())
    assert specs["1"]["s"] == P()


def test_unmatched_param_raises_with_nearest_rule():
    with pytest.raises(ValueError) as exc:
        match_partition_rules([(r"0/Wq$", P("model"))],
                              {"0": {"W": np.zeros((4, 4), np.float32)}})
    msg = str(exc.value)
    assert "no partition rule matches param '0/W'" in msg
    assert "0/Wq" in msg                          # nearest-rule suggestion


def test_rule_wider_than_rank_raises():
    with pytest.raises(ValueError, match="rank"):
        match_partition_rules(
            [(r"b$", P(None, None, "model")), (r".*", P())], _toy_params())


def test_create_opt_spec_clones_moments_replicates_scalars():
    params = _toy_params()
    specs = match_partition_rules([(r"W$", P(None, "model")), (r".*", P())],
                                  params)
    opt = {k: {pk: {"m": np.zeros_like(v), "v": np.zeros_like(v),
                    "t": np.zeros((), np.float32)}
               for pk, v in d.items()} for k, d in params.items()}
    ospecs = create_opt_spec(specs, opt)
    assert ospecs["0"]["W"]["m"] == P(None, "model")   # cloned
    assert ospecs["0"]["W"]["v"] == P(None, "model")
    assert ospecs["0"]["W"]["t"] == P()                # scalar state
    assert ospecs["0"]["b"]["m"] == P()
    # stateless updaters (empty dicts) survive the walk
    ospecs2 = create_opt_spec(specs, {k: {pk: {} for pk in d}
                                      for k, d in params.items()})
    assert ospecs2["0"]["W"] == {}


def test_plan_strict_raises_on_indivisible_and_demote_replicates():
    mesh = mesh_mod.single_host_mesh(data=4, model=2)
    params = _toy_params()
    strict = ShardingPlan([(r"W$", P(None, "model")), (r".*", P())],
                          mesh=mesh)
    with pytest.raises(ValueError, match="not divisible"):
        strict.param_specs(params)                 # 1/W is 16x3
    lax_plan = ShardingPlan([(r"W$", P(None, "model")), (r".*", P())],
                            mesh=mesh, demote_indivisible=True)
    specs = lax_plan.param_specs(params)
    assert specs["0"]["W"] == P(None, "model")
    assert specs["1"]["W"] == P(None, None)        # demoted dim
    rows = {r["path"]: r for r in lax_plan.explain(fmt="json")["params"]}
    assert rows["1/W"].get("demoted") is True


def test_plan_explain_and_cache_tag():
    mesh = mesh_mod.single_host_mesh(data=4, model=2)
    params = _toy_params()
    plan = ShardingPlan([(r"W$", P(None, "model")), (r".*", P())],
                        mesh=mesh, demote_indivisible=True)
    with pytest.raises(ValueError):
        plan.cache_tag()                           # unresolved
    plan.param_specs(params)
    tag = plan.cache_tag()
    text = plan.explain()
    assert "0/W" in text and "model" in text
    data = plan.explain(fmt="json")
    assert data["mesh"] == {"data": 4, "model": 2}
    assert len(data["params"]) == 4
    # same rules + same mesh -> same tag; different mesh -> different
    plan2 = ShardingPlan([(r"W$", P(None, "model")), (r".*", P())],
                         mesh=mesh, demote_indivisible=True)
    plan2.param_specs(params)
    assert plan2.cache_tag() == tag
    plan3 = ShardingPlan([(r"W$", P(None, "model")), (r".*", P())],
                         mesh=mesh_mod.single_host_mesh(data=8),
                         demote_indivisible=True)
    plan3.param_specs(params)
    assert plan3.cache_tag() != tag


def test_zoo_rule_tables_resolve_on_real_nets():
    from deeplearning4j_tpu.zoo import rules as zoo_rules
    from deeplearning4j_tpu.zoo.graphs import TransformerEncoder
    from deeplearning4j_tpu.zoo.models import LeNet

    mesh = mesh_mod.single_host_mesh(data=4, model=2)
    tr = TransformerEncoder(num_classes=2, embed_dim=8, n_heads=2,
                            n_layers=1, max_len=8).init()
    plan = zoo_rules.plan_for(zoo_rules.transformer_rules(), mesh=mesh)
    specs = plan.param_specs(tr.params)
    assert specs["b0_attn"]["Wq"] == P(None, "model")
    assert specs["b0_attn"]["Wo"] == P("model", None)
    assert specs["b0_ff1"]["W"] == P(None, "model")
    assert specs["b0_ff2"]["W"] == P("model", None)
    assert specs["b0_ln1"]["gain"] == P() if "gain" in specs["b0_ln1"] \
        else True                                  # norms replicated
    ln = LeNet(num_classes=10).init()
    plan2 = zoo_rules.plan_for(zoo_rules.lenet_rules(), mesh=mesh)
    specs2 = plan2.param_specs(ln.params)
    assert specs2["0"]["W"] == P(None, None, None, "model")
    assert specs2["5"]["W"] == P(None, "model")
    assert specs2["0"]["b"] == P()


# ---------------------------------------------------------------------------
# ZeRO numerics: bit-identity with the all-reduce DP path
# ---------------------------------------------------------------------------

def test_zero_bit_identical_to_allreduce_dp():
    ref, _ = _train()
    zero, pw = _train(zero_optimizer=True)
    _bit_identical(ref, zero)
    # and the optimizer state REALLY lives scattered on device: each
    # leaf of the live tree is a flat padded vector sharded over 'data'
    leaf = jax.tree_util.tree_leaves(pw._opt)[0]
    assert leaf.ndim == 1
    shard = leaf.addressable_shards[0].data
    assert shard.shape[0] * 8 == leaf.shape[0]


def test_zero_bit_identical_with_ragged_tail_and_buckets():
    ref, _ = _train(n=61)                          # ragged final batch
    zero, _ = _train(n=61, zero_optimizer=True)
    _bit_identical(ref, zero)
    bucketed, _ = _train(n=61, zero_optimizer=True,
                         gradient_bucket_mb=0.0001)
    _bit_identical(ref, bucketed)


def test_zero_bit_identical_momentum_and_stateless_updaters():
    for upd in (Nesterovs(learning_rate=0.02, momentum=0.9),
                Sgd(learning_rate=0.05)):
        ref, _ = _train(updater=upd)
        zero, _ = _train(updater=upd, zero_optimizer=True)
        _bit_identical(ref, zero)


def test_zero_bit_identical_computation_graph():
    from deeplearning4j_tpu.nn.graph import ComputationGraph

    def _gconf():
        g = (NeuralNetConfiguration.builder().seed(9)
             .updater(Adam(learning_rate=0.05))
             .weight_init(WeightInit.XAVIER).graph_builder()
             .add_inputs("in")
             .set_input_types(InputType.feed_forward(4)))
        g.add_layer("d", DenseLayer(n_out=16, activation=Activation.TANH),
                    "in")
        g.add_layer("out", OutputLayer(n_out=3,
                                       activation=Activation.SOFTMAX,
                                       loss_fn=LossMCXENT()), "d")
        g.set_outputs("out")
        return g.build()

    x, y = _data()

    def train(**kw):
        net = ComputationGraph(_gconf()).init()
        ParallelWrapper(net, workers=8, **kw).fit(
            ArrayDataSetIterator(x, y, batch=16), epochs=2)
        return net

    _bit_identical(train(), train(zero_optimizer=True))


def test_zero_mode_refusals():
    net = MultiLayerNetwork(_conf()).init()
    from deeplearning4j_tpu.parallel import ThresholdAlgorithm, TrainingMode

    with pytest.raises(ValueError, match="zero_optimizer"):
        ParallelWrapper(net, training_mode=TrainingMode.AVERAGING,
                        zero_optimizer=True)
    with pytest.raises(ValueError, match="zero_optimizer"):
        ParallelWrapper(net, threshold_algorithm=ThresholdAlgorithm(1e-3),
                        zero_optimizer=True)
    with pytest.raises(ValueError, match="fused_steps"):
        ParallelWrapper(net, zero_optimizer=True, fused_steps=4)


def test_zero_health_skip_matches_dp_skip():
    from deeplearning4j_tpu.telemetry import health

    def batches(poison):
        rng = np.random.default_rng(3)
        out = []
        for i in range(4):
            x = rng.normal(size=(16, 4)).astype(np.float32)
            if i == poison:
                x = x + np.nan
            out.append(DataSet(x, np.eye(3, dtype=np.float32)[
                np.arange(16) % 3]))
        return out

    try:
        health.configure(policy=health.AnomalyPolicy.SKIP_STEP,
                         record_flights=False)
        ref = MultiLayerNetwork(_conf()).init()
        ParallelWrapper(ref, workers=8).fit(
            ListDataSetIterator(batches(2)), epochs=1)
        r_ref = dict(health.report())
        health.configure(policy=health.AnomalyPolicy.SKIP_STEP,
                         record_flights=False)
        zero = MultiLayerNetwork(_conf()).init()
        ParallelWrapper(zero, workers=8, zero_optimizer=True).fit(
            ListDataSetIterator(batches(2)), epochs=1)
        r_zero = dict(health.report())
    finally:
        health.disable()
    _bit_identical(ref, zero)
    assert r_zero["nonfinite_steps"] == r_ref["nonfinite_steps"] == 1
    assert r_zero["skipped_steps"] == r_ref["skipped_steps"] == 1


# ---------------------------------------------------------------------------
# DP x TP partition-rule training
# ---------------------------------------------------------------------------

def test_partition_rules_dp_tp_matches_dp():
    ref, _ = _train()
    mesh = mesh_mod.single_host_mesh(data=4, model=2)
    plan = ShardingPlan([(r"W$", P(None, "model")), (r".*", P())],
                        mesh=mesh, demote_indivisible=True)
    tp, pw = _train(workers=4, mesh=mesh, partition_rules=plan)
    la = jax.tree_util.tree_leaves((ref.params, ref.opt_state))
    lb = jax.tree_util.tree_leaves((tp.params, tp.opt_state))
    for u, v in zip(la, lb):
        # GSPMD-partitioned matmuls: same math, compiler-chosen
        # reduction order -> allclose, not bitwise
        np.testing.assert_allclose(np.asarray(u), np.asarray(v),
                                   rtol=5e-5, atol=5e-6)
    # the TP split is real: the first dense kernel is sharded 2-way on
    # its output features during training
    w0 = pw._params["0"]["W"]
    assert w0.addressable_shards[0].data.shape == (4, 8)


# ---------------------------------------------------------------------------
# AOT cache: sharding-keyed executables
# ---------------------------------------------------------------------------

def test_zero_refit_zero_recompiles_and_no_dp_aliasing():
    from deeplearning4j_tpu.optimize import aot_cache

    net, pw = _train(zero_optimizer=True, epochs=1)
    misses = aot_cache.stats()["misses"]
    # refit on a FRESH wrapper over the same (retrained) model: the
    # sharding-keyed executable is a cache hit, zero recompiles
    pw2 = ParallelWrapper(net, workers=8, zero_optimizer=True)
    x, y = _data()
    pw2.fit(ArrayDataSetIterator(x, y, batch=16), epochs=1)
    assert aot_cache.stats()["misses"] == misses


def test_signature_keys_shardings():
    from deeplearning4j_tpu.optimize.aot_cache import signature_of

    mesh = mesh_mod.single_host_mesh()
    x = np.zeros((8, 4), np.float32)
    rep = jax.device_put(x, mesh_mod.replicated_spec(mesh))
    sharded = jax.device_put(x, mesh_mod.data_parallel_spec(mesh))
    assert signature_of((rep,)) != signature_of((sharded,))
    # two identically-sharded arrays share a signature
    sharded2 = jax.device_put(x, mesh_mod.data_parallel_spec(mesh))
    assert signature_of((sharded,)) == signature_of((sharded2,))


# ---------------------------------------------------------------------------
# collective-counter parity (regression-pins the series names)
# ---------------------------------------------------------------------------

def test_zero_feeds_same_collective_counters_as_bucketed_psum():
    from deeplearning4j_tpu import telemetry

    telemetry.enable()
    try:
        telemetry.reset()
        _train(zero_optimizer=True, gradient_bucket_mb=0.0001, epochs=1)
        snap = telemetry.REGISTRY.snapshot(run_collectors=False)
    finally:
        telemetry.disable()
        telemetry.reset()
    # the SAME series every other exchange feeds, new op labels
    for op in ("grad_reduce_scatter", "param_all_gather"):
        assert snap[f'dl4j_collective_bytes_total{{op="{op}"}}'] > 0
        assert snap[f'dl4j_collective_ops_total{{op="{op}"}}'] > 0
        assert snap[f'dl4j_collective_buckets{{op="{op}"}}'] > 1
        hist = snap[f'dl4j_collective_bucket_bytes{{op="{op}"}}']
        assert hist["count"] > 1
    # both halves move the same payload on the same bucket layout
    assert snap['dl4j_collective_bytes_total{op="grad_reduce_scatter"}'] \
        == snap['dl4j_collective_bytes_total{op="param_all_gather"}']


def test_shard_bytes_gauges_show_one_eighth_opt_state():
    from deeplearning4j_tpu import telemetry

    telemetry.reset()
    net, pw = _train(zero_optimizer=True, epochs=1)
    snap = telemetry.REGISTRY.snapshot(run_collectors=False)
    opt_total = sum(np.asarray(v).nbytes
                    for v in jax.tree_util.tree_leaves(net.opt_state))
    per_dev = [v for k, v in snap.items()
               if k.startswith("dl4j_shard_opt_bytes")]
    assert per_dev, "gauge missing"
    # <= ~1/8 of the unsharded footprint (+ padding slack)
    assert max(per_dev) <= opt_total / 8 * 1.25
    telemetry.reset()


# ---------------------------------------------------------------------------
# sharding-aware checkpoints: save on mesh A, restore on mesh B
# ---------------------------------------------------------------------------

def test_session_snapshot_gathers_and_restores_onto_different_mesh():
    from deeplearning4j_tpu.resilience import TrainingSession
    from deeplearning4j_tpu.util.serializer import file_digest

    d = tempfile.mkdtemp()
    try:
        net = MultiLayerNetwork(_conf()).init()
        pw = ParallelWrapper(net, workers=8, zero_optimizer=True)
        sess = TrainingSession(pw, d, snapshot_every_n_iterations=100)
        x, y = _data()
        sess.fit(ArrayDataSetIterator(x, y, batch=16), epochs=1)
        snap_params = jax.tree_util.tree_map(
            lambda a: np.asarray(a).copy(), net.params)
        snap_opt = jax.tree_util.tree_map(
            lambda a: np.asarray(a).copy(), net.opt_state)
        # the manifest digest matches the bytes on disk (gather-on-save
        # went through the same atomic temp+replace as every snapshot)
        entry = sess.snapshots()[-1]
        assert file_digest(os.path.join(d, entry["file"])) \
            == entry["digest"]

        # "new process", DIFFERENT mesh shape: 4-way ZeRO wrapper
        net_b = MultiLayerNetwork(_conf()).init()
        pw_b = ParallelWrapper(net_b, workers=4, zero_optimizer=True)
        sess_b = TrainingSession(pw_b, d)
        restored = sess_b.resume()
        for k in snap_params:
            for pk in snap_params[k]:
                np.testing.assert_array_equal(
                    np.asarray(restored.params[k][pk]),
                    snap_params[k][pk])
        r_opt = jax.tree_util.tree_leaves(restored.opt_state)
        for u, v in zip(r_opt, jax.tree_util.tree_leaves(snap_opt)):
            np.testing.assert_array_equal(np.asarray(u), v)
        # and the restored state TRAINS on the new mesh (re-scattered
        # onto 4 shards)
        sess_b.fit(ArrayDataSetIterator(x, y, batch=16), to_epoch=2)
        assert pw_b.model.epoch == 2
        leaf = jax.tree_util.tree_leaves(pw_b._opt)[0]
        assert leaf.addressable_shards[0].data.shape[0] * 4 \
            == leaf.shape[0]
    finally:
        shutil.rmtree(d, ignore_errors=True)


def test_session_refuses_non_exact_wrapper_modes():
    """Model-level snapshots can't capture AVERAGING replica divergence
    or threshold residuals — those wrapper modes must be refused at
    session construction, not silently resumed wrong."""
    from deeplearning4j_tpu.parallel import ThresholdAlgorithm, TrainingMode
    from deeplearning4j_tpu.resilience import TrainingSession

    d = tempfile.mkdtemp()
    try:
        net = MultiLayerNetwork(_conf()).init()
        with pytest.raises(ValueError, match="SHARED_GRADIENTS"):
            TrainingSession(ParallelWrapper(
                net, training_mode=TrainingMode.AVERAGING), d)
        with pytest.raises(ValueError, match="SHARED_GRADIENTS"):
            TrainingSession(ParallelWrapper(
                net, threshold_algorithm=ThresholdAlgorithm(1e-3)), d)
    finally:
        shutil.rmtree(d, ignore_errors=True)


def test_session_kill_and_resume_bit_identical_same_mesh():
    from deeplearning4j_tpu.resilience import TrainingSession, faults

    d1, d2 = tempfile.mkdtemp(), tempfile.mkdtemp()
    try:
        ref = MultiLayerNetwork(_conf()).init()
        TrainingSession(ParallelWrapper(ref, workers=8,
                                        zero_optimizer=True),
                        d1, snapshot_every_n_iterations=2).fit(
            ArrayDataSetIterator(*_data(), batch=16), epochs=2)

        net = MultiLayerNetwork(_conf()).init()
        sess = TrainingSession(
            ParallelWrapper(net, workers=8, zero_optimizer=True),
            d2, snapshot_every_n_iterations=2, max_restarts=0)
        plan = faults.FaultPlan(seed=1)
        plan.inject("train.step", on_calls=[5], action="raise")
        with pytest.raises(faults.InjectedFault):
            with plan.armed():
                sess.fit(ArrayDataSetIterator(*_data(), batch=16),
                         epochs=2)
        # fresh wrapper, same mesh, resume from directory alone
        net_b = MultiLayerNetwork(_conf()).init()
        sess_b = TrainingSession(
            ParallelWrapper(net_b, workers=8, zero_optimizer=True), d2)
        sess_b.resume()
        sess_b.fit(ArrayDataSetIterator(*_data(), batch=16), to_epoch=2)
        _bit_identical(ref, sess_b._net)
    finally:
        shutil.rmtree(d1, ignore_errors=True)
        shutil.rmtree(d2, ignore_errors=True)


def test_checkpoint_listener_gathers_live_wrapper_state():
    """write_model DURING a wrapper fit (a CheckpointListener firing
    mid-run) serializes the CURRENT trained state — gathered from the
    live (ZeRO-scattered) device trees through the _live_trainer hook —
    not the stale pre-fit host copy. After fit the hook is DISARMED:
    the model's host arrays are authoritative again, so later solo
    training can never be clobbered by old device trees."""
    from deeplearning4j_tpu.optimize.checkpoint import CheckpointListener
    from deeplearning4j_tpu.util import serializer

    d = tempfile.mkdtemp()
    try:
        net = MultiLayerNetwork(_conf()).init()
        pre = jax.tree_util.tree_map(
            lambda a: np.asarray(a).copy(), net.params)
        net.set_listeners(CheckpointListener(
            d, save_every_n_iterations=2, keep_last=2))
        pw = ParallelWrapper(net, workers=8, zero_optimizer=True)
        x, y = _data()
        pw.fit(ArrayDataSetIterator(x, y, batch=16), epochs=1)
        # the mid-fit checkpoint holds TRAINED params (the stale pre-fit
        # host copy would equal `pre`), gathered behind the atomic save
        lst = net.listeners[0]
        cp = lst.list_checkpoints()[0]
        restored = lst.load_checkpoint(cp.number)
        moved = any(
            not np.array_equal(np.asarray(restored.params[k][pk]),
                               pre[k][pk])
            for k in pre for pk in pre[k])
        assert moved, "mid-fit checkpoint captured the stale host copy"
        assert lst.verify(cp)
        # and the hook disarmed at fit end
        assert net._live_trainer is None
    finally:
        shutil.rmtree(d, ignore_errors=True)


# ---------------------------------------------------------------------------
# debugging surfaces
# ---------------------------------------------------------------------------

def test_sharding_endpoint_and_system_panel():
    from deeplearning4j_tpu.ui.server import UIServer
    from deeplearning4j_tpu.ui.stats import collect_system_metrics

    mesh = mesh_mod.single_host_mesh(data=4, model=2)
    plan = ShardingPlan([(r"W$", P(None, "model")), (r".*", P())],
                        mesh=mesh, demote_indivisible=True)
    plan.param_specs(_toy_params())
    sysm = collect_system_metrics()
    assert any(p["mesh"] == {"data": 4, "model": 2}
               for p in sysm.get("sharding_plans", []))
    ui = UIServer()
    port = ui.start(port=0)
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/sharding") as r:
            plans = json.loads(r.read())
        assert any(p["mesh"] == {"data": 4, "model": 2} for p in plans)
        assert any(r_["path"] == "0/W" for p in plans
                   for r_ in p["params"])
        html_page = ui.render_html()
        assert "Sharding plans" in html_page
    finally:
        ui.stop()


def test_zero_spec_roundtrip():
    tree = {"a": np.arange(13, dtype=np.float32).reshape(13),
            "b": np.arange(6, dtype=np.float32).reshape(2, 3)}
    z = ZeroSpec(tree, 8)
    mesh = mesh_mod.single_host_mesh()
    scattered = z.scatter_host(tree, mesh, "data")
    leaves = jax.tree_util.tree_leaves(scattered)
    assert all(l.shape[0] % 8 == 0 for l in leaves)
    back = z.gather_host(scattered)
    for k in tree:
        np.testing.assert_array_equal(back[k], tree[k])
    assert z.bytes_per_device() == (2 + 1) * 4     # ceil(13/8)+ceil(6/8)


# ---------------------------------------------------------------------------
# pod-refactor parity: the make_array-based scatter/gather pinned BITWISE
# against the legacy numpy round-trip at process_count == 1 (the pod
# scale-out rebuilt these paths on jax.make_array_from_callback /
# host_gather; every existing green path must be unperturbed)
# ---------------------------------------------------------------------------

def test_make_array_scatter_matches_legacy_device_put_bitwise():
    """ZeroSpec.scatter_host now stages through mesh.stage_host
    (make_array_from_callback on pods); at process_count == 1 the
    arrays must be BITWISE the legacy jax.device_put staging, and
    gather_host must be bitwise np.asarray."""
    from jax.sharding import NamedSharding

    tree = {"a": np.arange(13, dtype=np.float32),
            "b": np.arange(6, dtype=np.float32).reshape(2, 3)}
    z = ZeroSpec(tree, 8)
    mesh = mesh_mod.single_host_mesh()
    new = z.scatter_host(tree, mesh, "data")
    # the legacy route, re-implemented inline
    sh = NamedSharding(mesh, P("data"))
    leaves = jax.tree_util.tree_leaves(tree)
    legacy = []
    for leaf, padded, dt in zip(leaves, z.padded_sizes, z.dtypes):
        flat = np.zeros((padded,), dt)
        flat[:leaf.size] = np.asarray(leaf).reshape(-1)
        legacy.append(jax.device_put(flat, sh))
    for n, l in zip(jax.tree_util.tree_leaves(new), legacy):
        assert n.sharding == l.sharding
        np.testing.assert_array_equal(np.asarray(n), np.asarray(l))
    # and the explicit make_array_from_callback staging agrees too
    cb = jax.make_array_from_callback(
        legacy[0].shape, sh,
        lambda idx: np.asarray(legacy[0])[idx])
    np.testing.assert_array_equal(np.asarray(cb), np.asarray(legacy[0]))
    back = z.gather_host(new)
    for k in tree:
        np.testing.assert_array_equal(back[k], tree[k])


def test_plan_place_parity_with_device_put():
    """ShardingPlan.place (the comms.reshard host route) pinned bitwise
    against direct device_put placement under the same shardings —
    plan placement is one of the paths the pod refactor re-staged."""
    mesh = mesh_mod.single_host_mesh(data=4, model=2)
    plan = ShardingPlan([(r"W$", P(None, "model")), (r".*", P())],
                        mesh=mesh, demote_indivisible=True)
    params = _toy_params()
    specs = plan.param_specs(params)
    placed = plan.place(params, specs)
    shardings = plan.shardings(specs)
    legacy = jax.tree_util.tree_map(
        lambda x, s: jax.device_put(np.asarray(x), s), params, shardings)
    for a, b in zip(jax.tree_util.tree_leaves(placed),
                    jax.tree_util.tree_leaves(legacy)):
        assert a.sharding == b.sharding
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_zero_fit_checkpoint_roundtrip_parity(tmp_path):
    """ZeRO fit -> mid-training write_model (gather-on-save through the
    new host_gather) -> restore: bitwise the wrapper's live state. Pins
    that the pod refactor's gather cannot silently perturb the
    checkpoint path at process_count == 1."""
    from deeplearning4j_tpu.util import params as params_util
    from deeplearning4j_tpu.util.serializer import (
        restore_multi_layer_network,
        write_model,
    )

    net = MultiLayerNetwork(_conf()).init()
    pw = ParallelWrapper(net, workers=8, zero_optimizer=True)
    x, y = _data(32)
    pw.fit(ArrayDataSetIterator(x, y, batch=16), epochs=1)
    path = os.path.join(str(tmp_path), "zero.zip")
    write_model(net, path)
    restored = restore_multi_layer_network(path)
    np.testing.assert_array_equal(np.asarray(restored.params_flat()),
                                  np.asarray(net.params_flat()))
    np.testing.assert_array_equal(
        np.asarray(params_util.flatten_state_like(restored.opt_state)),
        np.asarray(params_util.flatten_state_like(net.opt_state)))
