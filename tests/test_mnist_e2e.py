"""BASELINE config #1 e2e: LeNet on MNIST (synthetic fallback), CPU oracle.
Reference: MultiLayerNetwork LeNet on MNIST (dl4j-examples)."""

import numpy as np

from deeplearning4j_tpu.conf.updaters import Adam
from deeplearning4j_tpu.datasets.mnist import MnistDataSetIterator
from deeplearning4j_tpu.zoo.models import LeNet


def test_lenet_mnist_learns():
    train = MnistDataSetIterator(batch=64, train=True, num_examples=2048)
    test = MnistDataSetIterator(batch=256, train=False, num_examples=512,
                                shuffle=False)
    net = LeNet(updater=Adam(learning_rate=2e-3)).init()
    net.fit(train, epochs=4)
    ev = net.evaluate(test)
    assert ev.accuracy() > 0.85, ev.stats()


def test_mnist_iterator_shapes():
    it = MnistDataSetIterator(batch=32, train=True, num_examples=64)
    ds = next(iter(it))
    assert ds.features.shape == (32, 28, 28, 1)
    assert ds.labels.shape == (32, 10)
    assert 0.0 <= ds.features.min() and ds.features.max() <= 1.0
    # deterministic synthesis
    it2 = MnistDataSetIterator(batch=32, train=True, num_examples=64)
    ds2 = next(iter(it2))
    np.testing.assert_array_equal(ds.features, ds2.features)
