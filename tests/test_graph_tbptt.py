"""ComputationGraph truncated-BPTT training (reference
``ComputationGraph#doTruncatedBPTT`` + ``BackpropType.TruncatedBPTT``,
SURVEY.md §2.2/§5.7).

Oracle strategy: a linear-chain ComputationGraph and an equivalent
MultiLayerNetwork share the same per-layer init streams (both fold the seed
by layer position), so tBPTT training on identical data must produce
IDENTICAL parameters — the strongest available parity check. Plus DAG-only
cases (multi-input), wrapper integration, streaming rnn_time_step, and the
validation/refusal surface.
"""

import numpy as np
import pytest

from deeplearning4j_tpu.conf import Activation, InputType, WeightInit
from deeplearning4j_tpu.conf.graph import MergeVertex
from deeplearning4j_tpu.conf.layers_rnn import (
    LSTM,
    RnnOutputLayer,
    SimpleRnn,
)
from deeplearning4j_tpu.conf.losses import LossMCXENT
from deeplearning4j_tpu.conf.multilayer import (
    BackpropType,
    NeuralNetConfiguration,
)
from deeplearning4j_tpu.conf.updaters import Adam
from deeplearning4j_tpu.datasets.dataset import DataSet, MultiDataSet
from deeplearning4j_tpu.datasets.iterators import (
    ArrayDataSetIterator,
    ListDataSetIterator,
)
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def _base(seed=12345):
    return (NeuralNetConfiguration.builder()
            .seed(seed)
            .updater(Adam(learning_rate=0.02))
            .weight_init(WeightInit.XAVIER))


def _mln_conf(fwd=5, back=5, t=20, seed=12345):
    return (_base(seed)
            .list()
            .layer(LSTM(n_out=12))
            .layer(RnnOutputLayer(n_out=3, activation=Activation.SOFTMAX,
                                  loss_fn=LossMCXENT()))
            .backprop_type(BackpropType.TRUNCATED_BPTT, fwd=fwd, back=back)
            .set_input_type(InputType.recurrent(4, t))
            .build())


def _cg_conf(fwd=5, back=5, t=20, seed=12345, cell=LSTM):
    return (_base(seed)
            .graph_builder()
            .add_inputs("in")
            .set_input_types(InputType.recurrent(4, t))
            .add_layer("rnn", cell(n_out=12), "in")
            .add_layer("out", RnnOutputLayer(n_out=3,
                                             activation=Activation.SOFTMAX,
                                             loss_fn=LossMCXENT()), "rnn")
            .set_outputs("out")
            .backprop_type(BackpropType.TRUNCATED_BPTT, fwd=fwd, back=back)
            .build())


def _seq_data(n=8, t=20, f=4, classes=3, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, t, f)).astype(np.float32)
    y = np.eye(classes, dtype=np.float32)[rng.integers(0, classes, (n, t))]
    return x, y


def _assert_chain_params_equal(mln, cg, names=("rnn", "out"), atol=0.0):
    """Chain CG params (by vertex name) == MLN params (by layer index)."""
    for i, name in enumerate(names):
        for pk in mln.params[str(i)]:
            a = np.asarray(mln.params[str(i)][pk])
            b = np.asarray(cg.params[name][pk])
            if atol:
                np.testing.assert_allclose(a, b, atol=atol,
                                           err_msg=f"{name}.{pk}")
            else:
                np.testing.assert_array_equal(a, b, err_msg=f"{name}.{pk}")


# --------------------------------------------------------------------------
# exact-match vs MultiLayerNetwork (the judge-specified oracle)
# --------------------------------------------------------------------------
def test_cg_tbptt_exact_matches_multilayer():
    """Linear-chain CG trains tBPTT bit-for-bit like the equivalent MLN:
    same init streams, same segment scan, same updates."""
    x, y = _seq_data()
    mln = MultiLayerNetwork(_mln_conf()).init()
    cg = ComputationGraph(_cg_conf()).init()
    _assert_chain_params_equal(mln, cg)  # identical init

    for _ in range(2):
        mln.fit_batch(DataSet(x, y))
        cg.fit_batch(DataSet(x, y))
    assert mln.iteration == cg.iteration == 8  # 2 batches x 4 segments
    _assert_chain_params_equal(mln, cg, atol=1e-6)
    assert np.isclose(mln.score(), cg.score(), atol=1e-5)


def test_cg_tbptt_back_lt_fwd_matches_multilayer():
    """back < fwd: the no-grad state-advance head runs through the DAG the
    same way MLN's does."""
    x, y = _seq_data(seed=3)
    mln = MultiLayerNetwork(_mln_conf(fwd=5, back=2)).init()
    cg = ComputationGraph(_cg_conf(fwd=5, back=2)).init()
    mln.fit_batch(DataSet(x, y))
    cg.fit_batch(DataSet(x, y))
    _assert_chain_params_equal(mln, cg, atol=1e-6)


def test_cg_tbptt_masked_prepad_matches_multilayer():
    """T=7 with fwd=5 forces the numpy prepad (tail zero-padded, masked);
    per-timestep masks flow identically through both runtimes."""
    x, y = _seq_data(n=6, t=7, seed=4)
    mask = np.ones((6, 7), np.float32)
    mask[0, 4:] = 0.0
    x[0, 4:] = 0.0
    mln = MultiLayerNetwork(_mln_conf(t=7)).init()
    cg = ComputationGraph(_cg_conf(t=7)).init()
    mln.fit_batch(DataSet(x, y, features_mask=mask, labels_mask=mask))
    cg.fit_batch(DataSet(x, y, features_mask=mask, labels_mask=mask))
    assert mln.iteration == cg.iteration == 2  # ceil(7/5) segments
    _assert_chain_params_equal(mln, cg, atol=1e-6)


def test_cg_tbptt_fit_epochs_and_learns():
    """fit() over an iterator: loss decreases; prepad wrapper cache keeps
    the device write-back across epochs."""
    x, y = _seq_data(n=8, t=10, seed=5)
    cg = ComputationGraph(_cg_conf(t=10)).init()
    ds = DataSet(x, y)
    cg.fit(ListDataSetIterator([ds]), epochs=1)
    first = cg.score()
    cg.fit(ListDataSetIterator([ds]), epochs=6)
    assert np.isfinite(cg.score_value)
    assert cg.score() < first


# --------------------------------------------------------------------------
# DAG-only coverage (what MultiLayerNetwork cannot express)
# --------------------------------------------------------------------------
def _two_input_conf(fwd=4, t=12, seed=7):
    return (_base(seed)
            .graph_builder()
            .add_inputs("a", "b")
            .set_input_types(InputType.recurrent(3, t),
                             InputType.recurrent(2, t))
            .add_vertex("merge", MergeVertex(), "a", "b")
            .add_layer("rnn", LSTM(n_out=10), "merge")
            .add_layer("rnn2", SimpleRnn(n_out=8), "rnn")
            .add_layer("out", RnnOutputLayer(n_out=2,
                                             activation=Activation.SOFTMAX,
                                             loss_fn=LossMCXENT()), "rnn2")
            .set_outputs("out")
            .backprop_type(BackpropType.TRUNCATED_BPTT, fwd=fwd, back=fwd)
            .build())


def test_cg_tbptt_multi_input_stacked_rnn_trains():
    """Two sequence inputs merged into a 2-deep RNN stack: per-vertex
    carries thread across segments; loss decreases."""
    rng = np.random.default_rng(0)
    a = rng.normal(size=(8, 12, 3)).astype(np.float32)
    b = rng.normal(size=(8, 12, 2)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, (8, 12))]
    mds = MultiDataSet(features=[a, b], labels=[y])
    cg = ComputationGraph(_two_input_conf()).init()
    cg.fit_batch(mds)
    assert cg.iteration == 3  # 12/4 segments
    first = cg.score()
    for _ in range(8):
        cg.fit_batch(mds)
    assert cg.score() < first
    assert np.all(np.isfinite(cg.params_flat()))


def test_cg_tbptt_carries_actually_thread():
    """The second segment must SEE the first segment's final RNN state:
    training with tBPTT(seg=6 over T=12) differs from training on the two
    6-step halves independently (which zero-resets state)."""
    rng = np.random.default_rng(1)
    x = rng.normal(size=(4, 12, 4)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, (4, 12))]

    threaded = ComputationGraph(_cg_conf(fwd=6, back=6, t=12)).init()
    threaded.fit_batch(DataSet(x, y))

    reset = ComputationGraph(_cg_conf(fwd=6, back=6, t=6)).init()
    reset.fit_batch(DataSet(x[:, :6], y[:, :6]))
    reset.fit_batch(DataSet(x[:, 6:], y[:, 6:]))

    diff = np.abs(threaded.params_flat() - reset.params_flat()).max()
    assert diff > 1e-6  # identical would mean carries never crossed


# --------------------------------------------------------------------------
# ParallelWrapper integration
# --------------------------------------------------------------------------
def test_cg_tbptt_wrapper_exact_matches_single_device():
    from deeplearning4j_tpu.parallel.wrapper import (
        ParallelWrapper,
        TrainingMode,
    )

    x, y = _seq_data(n=16, seed=9)
    serial = ComputationGraph(_cg_conf()).init()
    par = ComputationGraph(_cg_conf()).init()
    pw = ParallelWrapper(par, training_mode=TrainingMode.SHARED_GRADIENTS)
    for _ in range(2):
        serial.fit_batch(DataSet(x, y))
    pw.fit(ArrayDataSetIterator(x, y, batch=16), epochs=2)
    assert par.iteration == serial.iteration == 8
    for name in serial.params:
        for pk in serial.params[name]:
            np.testing.assert_allclose(
                np.asarray(serial.params[name][pk]),
                np.asarray(par.params[name][pk]), atol=3e-5,
                err_msg=f"{name}.{pk}")


def test_cg_tbptt_wrapper_averaging_converges():
    from deeplearning4j_tpu.parallel.wrapper import (
        ParallelWrapper,
        TrainingMode,
    )

    x, y = _seq_data(n=16, seed=11)
    par = ComputationGraph(_cg_conf(seed=7)).init()
    pw = ParallelWrapper(par, training_mode=TrainingMode.AVERAGING,
                         averaging_frequency=4)
    it = ArrayDataSetIterator(x, y, batch=16)
    pw.fit(it, epochs=1)
    first = pw.score_value
    pw.fit(it, epochs=4)
    assert np.isfinite(pw.score_value)
    assert pw.score_value < first
    assert np.all(np.isfinite(par.params_flat()))


def test_cg_tbptt_wrapper_threshold_converges():
    from deeplearning4j_tpu.parallel.compression import (
        AdaptiveThresholdAlgorithm,
    )
    from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper

    x, y = _seq_data(n=16, seed=13)
    par = ComputationGraph(_cg_conf(seed=3)).init()
    pw = ParallelWrapper(
        par, threshold_algorithm=AdaptiveThresholdAlgorithm(1e-4))
    it = ArrayDataSetIterator(x, y, batch=16)
    pw.fit(it, epochs=1)
    first = pw.score_value
    pw.fit(it, epochs=5)
    assert np.isfinite(pw.score_value)
    assert pw.score_value < first


# --------------------------------------------------------------------------
# streaming inference (reference ComputationGraph#rnnTimeStep)
# --------------------------------------------------------------------------
def test_cg_rnn_time_step_matches_full_forward():
    x, _ = _seq_data(n=3, t=12, seed=15)
    cg = ComputationGraph(_cg_conf(t=12)).init()
    full = np.asarray(cg.output(x))
    cg.rnn_clear_previous_state()
    parts = [np.asarray(cg.rnn_time_step(x[:, :5])),
             np.asarray(cg.rnn_time_step(x[:, 5:9])),
             np.asarray(cg.rnn_time_step(x[:, 9:]))]
    np.testing.assert_allclose(np.concatenate(parts, axis=1), full,
                               atol=1e-5)
    # state get/set round-trip
    st = cg.rnn_get_previous_state("rnn")
    assert st is not None and all(np.all(np.isfinite(np.asarray(v)))
                                  for v in st.values())
    cg.rnn_clear_previous_state()
    cg.rnn_set_previous_state("rnn", {k: np.asarray(v)
                                      for k, v in st.items()})
    y2 = np.asarray(cg.rnn_time_step(x[:, :2]))
    assert np.all(np.isfinite(y2))


# --------------------------------------------------------------------------
# validation / refusal surface
# --------------------------------------------------------------------------
def _gb_conf(t, fwd, bidirectional=False, seed=12345):
    g = (_base(seed)
         .graph_builder()
         .add_inputs("in")
         .set_input_types(InputType.recurrent(4, t)))
    if bidirectional:
        from deeplearning4j_tpu.conf.layers_rnn import Bidirectional

        g.add_layer("rnn", Bidirectional(
            layer=LSTM(n_out=6, go_backwards=True)), "in")
    else:
        g.add_layer("rnn", LSTM(n_out=6, go_backwards=True), "in")
    g.add_layer("out", RnnOutputLayer(n_out=2,
                                      activation=Activation.SOFTMAX,
                                      loss_fn=LossMCXENT()), "rnn")
    g.set_outputs("out")
    if fwd:
        g.backprop_type(BackpropType.TRUNCATED_BPTT, fwd=fwd, back=fwd)
    return g.build()


@pytest.mark.parametrize("bidirectional", [False, True])
def test_cg_tbptt_go_backwards_single_segment_is_standard(bidirectional):
    """Round-3 refusal CLOSED: go_backwards (and Bidirectional over it)
    trains under truncated BPTT. Single segment (T == fwd) is exactly
    standard BPTT — losses and every parameter match elementwise."""
    x, y = _seq_data(n=4, t=5, classes=2)
    std = ComputationGraph(_gb_conf(5, fwd=0, bidirectional=bidirectional)
                           ).init()
    tb = ComputationGraph(_gb_conf(5, fwd=5, bidirectional=bidirectional)
                          ).init()
    for pk in std.params["rnn"]:
        np.testing.assert_array_equal(np.asarray(std.params["rnn"][pk]),
                                      np.asarray(tb.params["rnn"][pk]))
    l_std = std.fit_batch(DataSet(x, y))
    l_tb = tb.fit_batch(DataSet(x, y))
    np.testing.assert_allclose(l_tb, l_std, rtol=1e-6)
    for name in ("rnn", "out"):
        for pk in std.params[name]:
            np.testing.assert_allclose(
                np.asarray(tb.params[name][pk]),
                np.asarray(std.params[name][pk]), rtol=1e-5, atol=1e-7,
                err_msg=f"{name}/{pk}")


def test_cg_tbptt_go_backwards_multi_segment_per_segment_reset():
    """Multi-segment semantics: the reversed direction RESETS each
    segment (its carry would come from the future), so for a pure
    go_backwards net tBPTT over [T] == sequential STANDARD fits on the
    [fwd]-slices — the strongest oracle available, exact elementwise."""
    x, y = _seq_data(n=4, t=10, classes=2)
    tb = ComputationGraph(_gb_conf(10, fwd=5)).init()
    std = ComputationGraph(_gb_conf(5, fwd=0)).init()
    std.params = {k: {pk: np.asarray(v).copy()
                      for pk, v in d.items()}
                  for k, d in tb.params.items()}
    l_tb = tb.fit_batch(DataSet(x, y))
    l1 = std.fit_batch(DataSet(x[:, :5], y[:, :5]))
    l2 = std.fit_batch(DataSet(x[:, 5:], y[:, 5:]))
    np.testing.assert_allclose(l_tb, (l1 + l2) / 2.0, rtol=1e-5)
    for name in ("rnn", "out"):
        for pk in std.params[name]:
            np.testing.assert_allclose(
                np.asarray(tb.params[name][pk]),
                np.asarray(std.params[name][pk]), rtol=1e-4, atol=1e-6,
                err_msg=f"{name}/{pk}")


def test_cg_rnn_time_step_still_refuses_go_backwards():
    cg = ComputationGraph(_gb_conf(6, fwd=0)).init()
    x, _ = _seq_data(n=2, t=6, classes=2)
    with pytest.raises(RuntimeError, match="go_backwards|whole sequence"):
        cg.rnn_time_step(x[:, :2])


def test_cg_tbptt_rejects_sequence_level_labels():
    cg = ComputationGraph(_cg_conf(t=10)).init()
    x, _ = _seq_data(n=4, t=10)
    y2d = np.eye(3, dtype=np.float32)[np.zeros(4, int)]
    with pytest.raises(ValueError, match="per-timestep labels"):
        cg.fit_batch(DataSet(x, y2d))


def test_cg_tbptt_rejects_mismatched_time_lengths():
    conf = (_base()
            .graph_builder()
            .add_inputs("a", "b")
            .set_input_types(InputType.recurrent(3, 8),
                             InputType.recurrent(2, 8))
            .add_vertex("merge", MergeVertex(), "a", "b")
            .add_layer("rnn", SimpleRnn(n_out=6), "merge")
            .add_layer("out", RnnOutputLayer(n_out=2), "rnn")
            .set_outputs("out")
            .backprop_type(BackpropType.TRUNCATED_BPTT, fwd=4, back=4)
            .build())
    cg = ComputationGraph(conf).init()
    rng = np.random.default_rng(0)
    a = rng.normal(size=(2, 8, 3)).astype(np.float32)
    b = rng.normal(size=(2, 6, 2)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, (2, 8))]
    with pytest.raises(ValueError, match="time length"):
        cg.fit_batch(MultiDataSet(features=[a, b], labels=[y]))


def test_cg_tbptt_mixed_seq_static_inputs_rejected():
    """A tBPTT conf with one sequence and one static input must RAISE from
    fit (not silently train STANDARD) — matching ParallelWrapper's check
    (round-3 review finding)."""
    from deeplearning4j_tpu.conf.layers import DenseLayer

    conf = (_base()
            .graph_builder()
            .add_inputs("s", "v")
            .set_input_types(InputType.recurrent(3, 8),
                             InputType.feed_forward(4))
            .add_layer("rnn", SimpleRnn(n_out=6), "s")
            .add_layer("d", DenseLayer(n_out=6), "v")
            .add_layer("out", RnnOutputLayer(n_out=2), "rnn")
            .set_outputs("out")
            .backprop_type(BackpropType.TRUNCATED_BPTT, fwd=4, back=4)
            .build())
    cg = ComputationGraph(conf).init()
    rng = np.random.default_rng(0)
    s = rng.normal(size=(2, 8, 3)).astype(np.float32)
    v = rng.normal(size=(2, 4)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, (2, 8))]
    with pytest.raises(ValueError, match="every network input"):
        cg.fit_batch(MultiDataSet(features=[s, v], labels=[y]))


def test_padded_pointwise_conv_streaming_rejected():
    """kernel=1 conv WITH explicit time padding injects synthetic steps
    per call — rnn_time_step must refuse it (round-3 review finding)."""
    from deeplearning4j_tpu.conf.layers_cnn import (
        Convolution1DLayer,
        ConvolutionMode,
    )

    conf = (_base()
            .graph_builder()
            .add_inputs("in")
            .set_input_types(InputType.recurrent(4, 8))
            .add_layer("conv", Convolution1DLayer(
                n_out=6, kernel=1, stride1d=1, padding1d=1,
                convolution_mode=ConvolutionMode.TRUNCATE), "in")
            .add_layer("out", RnnOutputLayer(n_out=2), "conv")
            .set_outputs("out")
            .build())
    cg = ComputationGraph(conf).init()
    x = np.random.default_rng(0).normal(size=(2, 8, 4)).astype(np.float32)
    with pytest.raises(RuntimeError, match="rnn_time_step is unsupported"):
        cg.rnn_time_step(x)


def test_cg_tbptt_rejects_wrong_length_masks():
    """A mask at the wrong time rate (e.g. reused from a downsampled-rate
    head) must raise up front, not desynchronize the segment scan
    (found by examples/round3_features.py)."""
    cg = ComputationGraph(_cg_conf(t=20)).init()
    x, y = _seq_data(n=4, t=20)
    bad = np.ones((4, 10), np.float32)
    with pytest.raises(ValueError, match="INPUT rate"):
        cg.fit_batch(DataSet(x, y, labels_mask=bad))
