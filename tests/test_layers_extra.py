"""Extra layer confs: shapes, gradients, serde (reference layer-surface
completion — Convolution3D, locally-connected, PReLU, etc.)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from deeplearning4j_tpu import serde
from deeplearning4j_tpu.conf import Activation, InputType
from deeplearning4j_tpu.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.conf.layers_cnn import PoolingType
from deeplearning4j_tpu.conf.layers_extra import (
    Convolution3D,
    Cropping1D,
    Cropping3D,
    DepthwiseConvolution2D,
    ElementWiseMultiplicationLayer,
    GravesBidirectionalLSTM,
    LocallyConnected1D,
    LocallyConnected2D,
    MaskLayer,
    PReLULayer,
    RepeatVector,
    Subsampling1DLayer,
    Subsampling3DLayer,
    Upsampling1D,
    Upsampling3D,
    ZeroPadding1DLayer,
    ZeroPadding3DLayer,
)
from deeplearning4j_tpu.conf.losses import LossMCXENT
from deeplearning4j_tpu.conf.multilayer import NeuralNetConfiguration
from deeplearning4j_tpu.conf.updaters import NoOp
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.util.gradcheck import gradient_check

KEY = jax.random.PRNGKey(0)


def test_conv3d_stack_shapes(rng):
    t = InputType.convolutional_3d(8, 8, 8, 2)
    c = Convolution3D(n_out=4, kernel_size=(3, 3, 3), stride=(2, 2, 2))
    out = c.output_type(t)
    assert (out.depth, out.height, out.width, out.channels) == (4, 4, 4, 4)
    params = c.init(KEY, t)
    x = jnp.asarray(rng.normal(size=(2, 8, 8, 8, 2)), jnp.float32)
    y, _ = c.forward(params, {}, x)
    assert y.shape == (2, 4, 4, 4, 4)

    p = Subsampling3DLayer(kernel_size=(2, 2, 2), stride=(2, 2, 2))
    y2, _ = p.forward({}, {}, y)
    assert y2.shape == (2, 2, 2, 2, 4)

    u = Upsampling3D(size=(2, 2, 2))
    y3, _ = u.forward({}, {}, y2)
    assert y3.shape == (2, 4, 4, 4, 4)

    z = ZeroPadding3DLayer(padding=(1, 1, 0, 0, 2, 2))
    y4, _ = z.forward({}, {}, y3)
    assert y4.shape == (2, 6, 4, 8, 4)
    cr = Cropping3D(cropping=(1, 1, 0, 0, 2, 2))
    y5, _ = cr.forward({}, {}, y4)
    np.testing.assert_array_equal(np.asarray(y5), np.asarray(y3))


def test_1d_layers(rng):
    x = jnp.asarray(rng.normal(size=(2, 8, 3)), jnp.float32)
    s = Subsampling1DLayer(pooling_type=PoolingType.AVG, kernel_size=2,
                           stride=2)
    y, _ = s.forward({}, {}, x)
    assert y.shape == (2, 4, 3)
    np.testing.assert_allclose(np.asarray(y[:, 0]),
                               np.asarray((x[:, 0] + x[:, 1]) / 2),
                               rtol=1e-6)
    u = Upsampling1D(size=3)
    assert u.forward({}, {}, y)[0].shape == (2, 12, 3)
    zp = ZeroPadding1DLayer(padding=(1, 2))
    assert zp.forward({}, {}, x)[0].shape == (2, 11, 3)
    cr = Cropping1D(cropping=(1, 2))
    assert cr.forward({}, {}, x)[0].shape == (2, 5, 3)


def test_depthwise_matches_manual(rng):
    t = InputType.convolutional(6, 6, 3)
    d = DepthwiseConvolution2D(kernel_size=(3, 3), depth_multiplier=2,
                               activation=Activation.IDENTITY)
    params = d.init(KEY, t)
    x = jnp.asarray(rng.normal(size=(1, 6, 6, 3)), jnp.float32)
    y, _ = d.forward(params, {}, x)
    assert y.shape == (1, 6, 6, 6)
    assert d.output_type(t).channels == 6


def test_locally_connected_2d_unshared(rng):
    t = InputType.convolutional(5, 5, 2)
    lc = LocallyConnected2D(n_out=3, kernel_size=(3, 3), stride=(1, 1),
                            activation=Activation.IDENTITY)
    params = lc.init(KEY, t)
    assert params["W"].shape == (3, 3, 18, 3)
    x = jnp.asarray(rng.normal(size=(2, 5, 5, 2)), jnp.float32)
    y, _ = lc.forward(params, {}, x)
    assert y.shape == (2, 3, 3, 3)
    # unshared: zeroing ONE position's weights only changes that position
    w2 = params["W"].at[1, 1].set(0.0)
    y2, _ = lc.forward({**params, "W": w2}, {}, x)
    diff = np.abs(np.asarray(y - y2)).sum(axis=(0, 3))
    assert diff[1, 1] > 0
    diff[1, 1] = 0
    assert diff.sum() == 0


def test_locally_connected_1d(rng):
    t = InputType.recurrent(3, timesteps=7)
    lc = LocallyConnected1D(n_out=4, kernel_size=3, stride=2,
                            activation=Activation.TANH)
    params = lc.init(KEY, t)
    x = jnp.asarray(rng.normal(size=(2, 7, 3)), jnp.float32)
    y, _ = lc.forward(params, {}, x)
    assert y.shape == (2, 3, 4)


def test_prelu_and_elementwise_mult(rng):
    t = InputType.feed_forward(4)
    pr = PReLULayer()
    params = pr.init(KEY, t)
    x = jnp.asarray([[-2.0, -1.0, 1.0, 2.0]], jnp.float32)
    y, _ = pr.forward(params, {}, x)
    np.testing.assert_allclose(np.asarray(y),
                               [[-0.5, -0.25, 1.0, 2.0]], rtol=1e-6)
    ew = ElementWiseMultiplicationLayer()
    p2 = ew.init(KEY, t)
    y2, _ = ew.forward(p2, {}, x)
    np.testing.assert_allclose(np.asarray(y2), np.asarray(x), rtol=1e-6)


def test_repeat_vector_and_mask_layer(rng):
    rv = RepeatVector(repetition_factor=3)
    x = jnp.asarray(rng.normal(size=(2, 4)), jnp.float32)
    y, _ = rv.forward({}, {}, x)
    assert y.shape == (2, 3, 4)
    ml = MaskLayer()
    seq = jnp.asarray(rng.normal(size=(2, 3, 4)), jnp.float32)
    mask = jnp.asarray([[1, 1, 0], [1, 0, 0]], jnp.float32)
    y2, _ = ml.forward({}, {}, seq, mask=mask)
    np.testing.assert_allclose(np.asarray(y2[0, 2]), 0.0)
    np.testing.assert_allclose(np.asarray(y2[1, 1:]), 0.0)


def test_graves_bidirectional_lstm(rng):
    from deeplearning4j_tpu.conf.layers_rnn import RnnOutputLayer

    conf = (NeuralNetConfiguration.builder()
            .seed(12345).updater(NoOp()).list()
            .layer(GravesBidirectionalLSTM(n_out=4))
            .layer(RnnOutputLayer(n_out=2))
            .set_input_type(InputType.recurrent(3, timesteps=5))
            .build())
    feats = rng.normal(size=(4, 5, 3)).astype(np.float64)
    labels = np.eye(2)[rng.integers(0, 2, (4, 5))].astype(np.float64)
    res = gradient_check(conf, DataSet(feats, labels), n_samples=50)
    assert res.passed, res.summary()


@pytest.mark.parametrize("layer_fn", [
    lambda: PReLULayer(),
    lambda: ElementWiseMultiplicationLayer(),
])
def test_extra_ff_gradients(layer_fn, rng):
    conf = (NeuralNetConfiguration.builder()
            .seed(12345).updater(NoOp()).list()
            .layer(DenseLayer(n_out=5, activation=Activation.TANH))
            .layer(layer_fn())
            .layer(OutputLayer(n_out=3, activation=Activation.SOFTMAX,
                               loss_fn=LossMCXENT()))
            .set_input_type(InputType.feed_forward(4))
            .build())
    feats = rng.normal(size=(6, 4)).astype(np.float64)
    labels = np.eye(3)[rng.integers(0, 3, 6)].astype(np.float64)
    res = gradient_check(conf, DataSet(feats, labels), n_samples=50)
    assert res.passed, res.summary()


def test_serde_roundtrip():
    for layer in (Convolution3D(n_out=4), Subsampling3DLayer(),
                  Subsampling1DLayer(), Upsampling1D(size=3), Upsampling3D(),
                  Cropping1D(cropping=(1, 2)), Cropping3D(),
                  ZeroPadding1DLayer(padding=(1, 2)), ZeroPadding3DLayer(),
                  DepthwiseConvolution2D(depth_multiplier=2),
                  LocallyConnected2D(n_out=3), LocallyConnected1D(n_out=4),
                  PReLULayer(), ElementWiseMultiplicationLayer(),
                  RepeatVector(repetition_factor=3), MaskLayer(),
                  GravesBidirectionalLSTM(n_out=4)):
        back = serde.from_json(serde.to_json(layer))
        assert back == layer, type(layer).__name__


def test_pooling_sum_pnorm_and_prelu_shapes(rng):
    x = jnp.asarray(rng.normal(size=(1, 4, 3)), jnp.float32)
    s = Subsampling1DLayer(pooling_type=PoolingType.SUM, kernel_size=2,
                           stride=2)
    y, _ = s.forward({}, {}, x)
    np.testing.assert_allclose(np.asarray(y[:, 0]),
                               np.asarray(x[:, 0] + x[:, 1]), rtol=1e-6)
    x3 = jnp.asarray(rng.normal(size=(1, 4, 4, 4, 2)), jnp.float32)
    s3 = Subsampling3DLayer(pooling_type=PoolingType.SUM)
    assert s3.forward({}, {}, x3)[0].shape == (1, 2, 2, 2, 2)
    # PReLU handles 3D/flat input types
    assert PReLULayer().init(KEY, InputType.convolutional_3d(4, 4, 4, 2))[
        "alpha"].shape == (2,)
