"""Pod scale-out + pod-grade preemption suite (docs/resilience.md,
"Pod preemption"; `make pod-smoke`).

The acceptance bars of the multi-host PR:

- **Chaos**: a seeded ``FaultPlan`` kills one simulated host mid-fit
  (``HostDeathError`` at the ``pod.heartbeat`` site); the session
  resumes from the last DISTRIBUTED snapshot and final params+updater
  state are bit-identical to an uninterrupted run — and the same seed
  kills/resumes at the same step across two full replays.
- **Partial snapshots are never selected**: a snapshot interrupted
  mid-shard-write (fault at ``snapshot.shard_write``, any host), a
  missing/corrupt shard, or an uncommitted/stale coordinator manifest
  is skipped with a specific ``PodSnapshotIncompleteError`` reason in
  the log — never a bare ``KeyError``/``FileNotFoundError`` — and the
  prior complete snapshot restores digest-verified.
- **Cross-pod-shape restore**: save on one pod shape, restore on
  another (2 hosts → 1, 2 → 4) through ``comms.reshard``'s compiled
  re-cut, bitwise the snapshot.
- **Single-process parity**: the make_array-based scatter/gather the
  pod refactor introduced is pinned bitwise against the legacy numpy
  round-trip at ``process_count == 1`` (see also test_sharding's
  parity additions).

Real multi-host legs run through tests/pod_harness.py's N-process
loopback harness and SKIP cleanly where the jaxlib lacks CPU
multi-process collectives (this container does — the emulation seam
covers the logic; the harness leg proves the wiring where supported).
"""

import glob
import json
import logging
import os
import textwrap

import numpy as np
import pytest

from deeplearning4j_tpu.conf import Activation, InputType
from deeplearning4j_tpu.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.conf.losses import LossMCXENT
from deeplearning4j_tpu.conf.multilayer import NeuralNetConfiguration
from deeplearning4j_tpu.conf.updaters import Adam
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.resilience import (
    FaultPlan,
    HostDeathError,
    InjectedFault,
    PodConfig,
    PodSnapshotIncompleteError,
    TrainingSession,
    status,
)
from deeplearning4j_tpu.resilience import faults, pod as pod_mod
from deeplearning4j_tpu.telemetry import REGISTRY
from deeplearning4j_tpu.util import params as params_util
from tests import pod_harness

pytestmark = pytest.mark.pod


@pytest.fixture(autouse=True)
def _clean():
    faults._ACTIVE = None
    REGISTRY.reset()
    yield
    faults._ACTIVE = None
    REGISTRY.reset()


def _net(seed=7):
    conf = (NeuralNetConfiguration.builder().seed(seed)
            .updater(Adam(0.01)).list()
            .layer(DenseLayer(n_out=8, activation=Activation.TANH))
            .layer(OutputLayer(n_out=3, activation=Activation.SOFTMAX,
                               loss_fn=LossMCXENT()))
            .set_input_type(InputType.feed_forward(4)).build())
    return MultiLayerNetwork(conf).init()


def _iterator(seed=0, n=6):
    rng = np.random.default_rng(seed)
    return ListDataSetIterator([
        DataSet(rng.normal(size=(8, 4)).astype(np.float32),
                np.eye(3, dtype=np.float32)[rng.integers(0, 3, 8)])
        for _ in range(n)])


def _flat(net):
    return np.asarray(net.params_flat())


def _opt_flat(net):
    return np.asarray(params_util.flatten_state_like(net.opt_state))


def _baseline(epochs=2):
    net = _net()
    net.fit(_iterator(), epochs=epochs)
    return _flat(net), _opt_flat(net)


# ---------------------------------------------------------------------------
# snapshot layout + commit protocol
# ---------------------------------------------------------------------------

def test_pod_snapshot_layout_and_digests(tmp_path):
    """Every host writes its shard under the ZeroSpec flat cut, per-shard
    sha256 in its host manifest, coordinator manifest recording the host
    manifests' digests — and the shards reassemble the exact params."""
    net = _net()
    net.fit(_iterator(), epochs=1)
    d = str(tmp_path / "pod_a")
    pod = PodConfig(n_hosts=2)
    pod_mod.write_pod_snapshot(net, d, pod, rng_key=net._base_key)
    files = sorted(os.listdir(d))
    assert files == ["host_h000.json", "host_h001.json", "manifest.json",
                     "shard_h000.npz", "shard_h001.npz"]
    man = json.load(open(os.path.join(d, "manifest.json")))
    assert man["n_hosts"] == 2
    from deeplearning4j_tpu.util.serializer import file_digest

    for h in range(2):
        hman = json.load(open(os.path.join(d, f"host_h{h:03d}.json")))
        assert hman["iteration"] == man["iteration"]
        for row in hman["shards"]:
            assert file_digest(os.path.join(d, row["file"])) \
                == row["sha256"]
        assert file_digest(os.path.join(d, f"host_h{h:03d}.json")) \
            == man["hosts"][h]["sha256"]
    # ZeroSpec cut: the two shard halves concatenate back to the flats
    ref = _flat(net)
    m = -(-ref.size // 2)
    s0 = np.load(os.path.join(d, "shard_h000.npz"))["coefficients"]
    s1 = np.load(os.path.join(d, "shard_h001.npz"))["coefficients"]
    assert s0.size == m and s1.size == m
    np.testing.assert_array_equal(np.concatenate([s0, s1])[:ref.size],
                                  ref)


def test_restore_same_shape_bitwise(tmp_path):
    net = _net()
    net.fit(_iterator(), epochs=1)
    d = str(tmp_path / "pod_a")
    pod = PodConfig(n_hosts=2)
    pod_mod.write_pod_snapshot(net, d, pod, rng_key=net._base_key)
    restored, man = pod_mod.restore_pod_snapshot(d, pod)
    np.testing.assert_array_equal(_flat(restored), _flat(net))
    np.testing.assert_array_equal(_opt_flat(restored), _opt_flat(net))
    assert restored.iteration == net.iteration
    assert man["n_hosts"] == 2


@pytest.mark.parametrize("n_save,n_restore", [(2, 1), (2, 4), (4, 2)])
def test_restore_across_pod_shapes_through_reshard(tmp_path, n_save,
                                                   n_restore):
    """Save on one pod shape, restore on another — the flat components
    re-cut through comms.reshard's compiled ``pod_recut`` route (key
    pinned in the AOT cache), bitwise the snapshot."""
    from deeplearning4j_tpu.optimize import aot_cache

    net = _net()
    net.fit(_iterator(), epochs=1)
    d = str(tmp_path / "pod_a")
    pod_mod.write_pod_snapshot(net, d, PodConfig(n_hosts=n_save))
    restored, _ = pod_mod.restore_pod_snapshot(
        d, PodConfig(n_hosts=n_restore))
    np.testing.assert_array_equal(_flat(restored), _flat(net))
    np.testing.assert_array_equal(_opt_flat(restored), _opt_flat(net))
    # the re-cut went through the comms.reshard compiled route
    assert any(k[1].startswith("pod_recut:")
               for k in aot_cache._EXECUTABLES), \
        "cross-shape restore did not route through comms.reshard"


# ---------------------------------------------------------------------------
# named errors: partial snapshots are never selected
# ---------------------------------------------------------------------------

def _committed_snapshot(tmp_path, n_hosts=2):
    net = _net()
    net.fit(_iterator(), epochs=1)
    d = str(tmp_path / "pod_a")
    pod_mod.write_pod_snapshot(net, d, PodConfig(n_hosts=n_hosts))
    return net, d


def test_missing_shard_raises_named_error(tmp_path):
    net, d = _committed_snapshot(tmp_path)
    os.remove(os.path.join(d, "shard_h001.npz"))
    with pytest.raises(PodSnapshotIncompleteError) as ei:
        pod_mod.restore_pod_snapshot(d)
    assert "missing shard file shard_h001.npz" in ei.value.reason


def test_corrupt_shard_raises_named_error(tmp_path):
    net, d = _committed_snapshot(tmp_path)
    p = os.path.join(d, "shard_h000.npz")
    with open(p, "r+b") as f:
        f.seek(os.path.getsize(p) // 2)
        f.write(b"\x00corrupt\x00")
    with pytest.raises(PodSnapshotIncompleteError) as ei:
        pod_mod.restore_pod_snapshot(d)
    assert "shard digest mismatch" in ei.value.reason


def test_uncommitted_coordinator_manifest_raises_named_error(tmp_path):
    net, d = _committed_snapshot(tmp_path)
    os.remove(os.path.join(d, "manifest.json"))
    with pytest.raises(PodSnapshotIncompleteError) as ei:
        pod_mod.restore_pod_snapshot(d)
    assert "uncommitted coordinator manifest" in ei.value.reason


def test_stale_coordinator_manifest_raises_named_error(tmp_path):
    """A host manifest rewritten after the coordinator commit (a crashed
    re-snapshot into the same directory) must read as STALE, not load
    mismatched generations."""
    net, d = _committed_snapshot(tmp_path)
    hpath = os.path.join(d, "host_h001.json")
    hman = json.load(open(hpath))
    hman["iteration"] += 1
    json.dump(hman, open(hpath, "w"))
    with pytest.raises(PodSnapshotIncompleteError) as ei:
        pod_mod.restore_pod_snapshot(d)
    assert "stale coordinator manifest" in ei.value.reason


def test_session_resume_skips_partial_newest_with_reason(tmp_path,
                                                         caplog):
    """Resume falls back past a corrupted newest pod snapshot to the
    previous complete one, logging the SPECIFIC reason — never a bare
    KeyError/FileNotFoundError."""
    sess = TrainingSession(_net(), str(tmp_path),
                           snapshot_every_n_iterations=2, pod=2)
    sess.fit(_iterator(), epochs=1)
    snaps = sess.snapshots()
    assert len(snaps) >= 2 and all(s.get("pod") for s in snaps)
    newest = os.path.join(str(tmp_path), snaps[-1]["file"])
    os.remove(os.path.join(newest, "shard_h000.npz"))
    revived = TrainingSession(None, str(tmp_path), pod=2)
    with caplog.at_level(logging.WARNING,
                         logger="deeplearning4j_tpu.resilience.session"):
        model = revived.resume()
    assert model.iteration == snaps[-2]["iteration"]
    assert any("missing shard file shard_h000.npz" in r.getMessage()
               for r in caplog.records)


def test_snapshot_interrupted_mid_shard_write_never_selected(tmp_path):
    """THE commit-protocol acceptance: a fault at ``snapshot.shard_write``
    (here: host 1's shard of the second snapshot, re-fired on every
    retry attempt) leaves that snapshot UNCOMMITTED — no coordinator
    manifest, no temp files — and resume restores the prior complete
    snapshot digest-verified, finishing bit-identical."""
    ref_params, ref_opt = _baseline()
    sess = TrainingSession(_net(), str(tmp_path),
                           snapshot_every_n_iterations=2, pod=2,
                           max_restarts=0)
    # invocations count one per shard write: snapshot1 = 1,2 (pre-first
    # -step), snapshot2 = 3,4 — kill host 1's write (4) on all three
    # CHECKPOINT_RETRY attempts (6, 8 are its replays)
    plan = FaultPlan(seed=3).inject("snapshot.shard_write",
                                    on_calls=[4, 6, 8])
    with plan.armed():
        with pytest.raises(InjectedFault):
            sess.fit(_iterator(), epochs=2)
    assert plan.fired("snapshot.shard_write") == 3
    # the interrupted snapshot directory is uncommitted and temp-free
    dirs = sorted(glob.glob(os.path.join(str(tmp_path), "pod_iter*")))
    partial = [p for p in dirs
               if not os.path.exists(os.path.join(p, "manifest.json"))]
    assert len(partial) == 1
    assert not glob.glob(os.path.join(partial[0], "*.tmp.*"))
    with pytest.raises(PodSnapshotIncompleteError):
        pod_mod.verify_pod_snapshot(partial[0])
    # a revived session restores the PRIOR complete snapshot and the
    # finished run is bit-identical to uninterrupted
    revived = TrainingSession(None, str(tmp_path),
                              snapshot_every_n_iterations=2, pod=2)
    model = revived.resume()
    assert model.iteration == 0      # the pre-first-step snapshot
    revived.fit(_iterator(), to_epoch=2)
    np.testing.assert_array_equal(_flat(revived.model), ref_params)
    np.testing.assert_array_equal(_opt_flat(revived.model), ref_opt)


# ---------------------------------------------------------------------------
# chaos acceptance: host death mid-fit
# ---------------------------------------------------------------------------

def test_host_death_resumes_bit_identical_and_replays_deterministically(
        tmp_path):
    """Kill one simulated host mid-fit via the seeded FaultPlan
    host-death action; the session resumes the WHOLE job from the last
    distributed snapshot bit-identically — and the same seed kills (and
    resumes) at the same heartbeat across two full replays."""
    ref_params, ref_opt = _baseline()
    kill_points = []
    for rep in range(2):
        d = str(tmp_path / f"run{rep}")
        sess = TrainingSession(_net(), d,
                               snapshot_every_n_iterations=2, pod=2)
        plan = FaultPlan(seed=11).inject(
            "pod.heartbeat", probability=0.12,
            exc=lambda: HostDeathError(host=1), max_fires=1)
        before = REGISTRY.counter("dl4j_resumes_total",
                                  scope="host").snapshot_value()
        with plan.armed():
            sess.fit(_iterator(), epochs=2)
        assert plan.fired("pod.heartbeat") == 1     # the kill was real
        kill_points.append(plan.invocations("pod.heartbeat"))
        assert REGISTRY.counter(
            "dl4j_resumes_total", scope="host").snapshot_value() \
            - before == 1
        assert sess.model.epoch == 2
        np.testing.assert_array_equal(_flat(sess.model), ref_params)
        np.testing.assert_array_equal(_opt_flat(sess.model), ref_opt)
    assert kill_points[0] == kill_points[1], \
        "same seed must kill/resume at the same step across replays"


def test_host_death_on_zero_wrapper_session(tmp_path):
    """The pod snapshot layer composes with a ZeRO wrapper session: the
    per-host shard files hold the GATHERED state (mesh-agnostic), a
    host death resumes bit-identically, and the ZeRO step's donation +
    collective audit stay clean on the pod path."""
    from deeplearning4j_tpu.analysis import program
    from deeplearning4j_tpu.analysis.findings import LOG
    from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper

    def wrapper():
        return ParallelWrapper(_net(), workers=8, zero_optimizer=True)

    ref = wrapper()
    ref.fit(_iterator(), epochs=2)
    ref_p, ref_o = _flat(ref.model), _opt_flat(ref.model)

    sess = TrainingSession(wrapper(), str(tmp_path),
                           snapshot_every_n_iterations=2, pod=2)
    plan = FaultPlan(seed=5).inject(
        "pod.heartbeat", on_calls=[4],
        exc=lambda: HostDeathError(host=0))
    with plan.armed():
        sess.fit(_iterator(), epochs=2)
    assert plan.fired("pod.heartbeat") == 1
    np.testing.assert_array_equal(_flat(sess._net), ref_p)
    np.testing.assert_array_equal(_opt_flat(sess._net), ref_o)
    # pod-path executables pass the donation + collective audit
    audit = {k: v for k, v in program.donation_audit().items()
             if k[1].startswith("pw_zero")}
    assert audit and all(v["aliases"] for v in audit.values()), audit
    bad = [f for f in LOG.items()
           if f.rule in ("PRG201", "PRG205") and not f.waived
           and "pw_zero" in f.location]
    assert bad == [], "\n".join(f.render() for f in bad)


# ---------------------------------------------------------------------------
# parity: the make_array scatter/gather vs the legacy numpy round-trip
# ---------------------------------------------------------------------------

def test_reshard_kinds_are_lint_clean_and_donation_exempt():
    """The compiled reshard kinds (pod_recut / reshard_commit) are NOT
    train kinds (cross-placement buffers cannot alias — exempt by
    construction in PRG201), and their compiles produce zero findings."""
    from deeplearning4j_tpu.analysis import program
    from deeplearning4j_tpu.analysis.findings import LOG

    # ensure at least one pod_recut executable exists in this process
    flat = np.arange(11, dtype=np.float32)
    m = -(-flat.size // 2)
    slices = [np.zeros((m,), np.float32) for _ in range(2)]
    for h in range(2):
        lo, hi = h * m, min(flat.size, (h + 1) * m)
        slices[h][:hi - lo] = flat[lo:hi]
    np.testing.assert_array_equal(
        pod_mod._aggregate_flat(slices, flat.size, 1), flat)
    assert not any(k[1].startswith(program.RESHARD_KIND_PREFIXES)
                   for k in program.donation_audit())
    bad = [f for f in LOG.items()
           if not f.waived and f.rule.startswith("PRG")
           and ("pod_recut" in f.location
                or "reshard_commit" in f.location)]
    assert bad == [], "\n".join(f.render() for f in bad)


def test_commit_compiled_matches_device_put_bitwise():
    """``comms.reshard.commit_compiled`` — the compiled identity that IS
    the multi-process reshard route — reproduces ``device_put``
    recommits bitwise at process_count == 1 (the only leg a single-
    process container can execute; the N-process harness leg proves the
    cross-host wiring where supported)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from deeplearning4j_tpu.comms.reshard import commit_compiled
    from deeplearning4j_tpu.parallel import mesh as mesh_mod

    mesh = mesh_mod.single_host_mesh()
    sharded = NamedSharding(mesh, P("data"))
    rep = NamedSharding(mesh, P())
    x = jax.device_put(np.arange(64, dtype=np.float32), sharded)
    out = commit_compiled(x, rep)
    assert out.sharding == rep
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x))
    back = commit_compiled(out, sharded)
    assert back.sharding == sharded
    np.testing.assert_array_equal(np.asarray(back), np.asarray(x))


def test_stage_host_matches_device_put_bitwise():
    """``mesh.stage_host`` (the make_array route's single-process fast
    path) and an explicit ``make_array_from_callback`` staging both
    reproduce the legacy ``device_put`` arrays bitwise — the parity pin
    for the refactor's staging layer."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from deeplearning4j_tpu.parallel import mesh as mesh_mod

    mesh = mesh_mod.single_host_mesh()
    sh = NamedSharding(mesh, P("data"))
    flat = np.arange(64, dtype=np.float32)
    legacy = jax.device_put(flat, sh)
    staged = mesh_mod.stage_host(flat, sh)
    via_callback = jax.make_array_from_callback(
        flat.shape, sh, lambda idx: flat[idx])
    np.testing.assert_array_equal(np.asarray(staged), np.asarray(legacy))
    np.testing.assert_array_equal(np.asarray(via_callback),
                                  np.asarray(legacy))
    assert staged.sharding == legacy.sharding \
        and via_callback.sharding == legacy.sharding
    # and host_gather is bitwise np.asarray for addressable arrays
    np.testing.assert_array_equal(mesh_mod.host_gather(staged), flat)


# ---------------------------------------------------------------------------
# telemetry + surfaces
# ---------------------------------------------------------------------------

def test_pod_telemetry_and_status_and_ui(tmp_path):
    sess = TrainingSession(_net(), str(tmp_path),
                           snapshot_every_n_iterations=2, pod=2)
    sess.fit(_iterator(), epochs=1)
    snap = REGISTRY.snapshot(run_collectors=False)
    assert snap.get("dl4j_pod_hosts") == 2
    for h in ("0", "1"):
        assert snap.get(
            f'dl4j_pod_snapshot_shard_bytes{{host="{h}"}}', 0) > 0
    assert snap["dl4j_pod_snapshot_seconds"]["count"] >= 1
    pod_mod.restore_pod_snapshot(
        os.path.join(str(tmp_path), sess.snapshots()[-1]["file"]))
    snap = REGISTRY.snapshot(run_collectors=False)
    assert snap["dl4j_pod_restore_seconds"]["count"] >= 1
    st = status()
    assert st["pod"]["hosts"] == 2
    assert any(k.startswith("dl4j_pod_snapshot_shard_bytes")
               for k in st["pod"]["series"])
    from deeplearning4j_tpu.ui.server import UIServer

    html = UIServer().render_html()
    assert "Pod (distributed snapshots)" in html
    assert "dl4j_pod_hosts" in html


def test_resume_counter_scopes(tmp_path):
    """``dl4j_resumes_total`` carries scope=host|job: a host death
    counts host scope, a whole-process fault counts job scope."""
    sess = TrainingSession(_net(), str(tmp_path),
                           snapshot_every_n_iterations=2, pod=2)
    plan = (FaultPlan(seed=2)
            .inject("pod.heartbeat", on_calls=[3],
                    exc=lambda: HostDeathError(host=0))
            .inject("train.step", on_calls=[9]))
    with plan.armed():
        sess.fit(_iterator(), epochs=2)
    snap = REGISTRY.snapshot(run_collectors=False)
    assert snap.get('dl4j_resumes_total{scope="host"}') == 1
    assert snap.get('dl4j_resumes_total{scope="job"}') == 1


# ---------------------------------------------------------------------------
# real multi-process leg (skips where the jaxlib lacks CPU collectives)
# ---------------------------------------------------------------------------

_MP_BODY = textwrap.dedent("""
    import os
    import numpy as np
    from tests.test_pod import _net, _iterator, _flat, _opt_flat
    from deeplearning4j_tpu.resilience import PodConfig, pod as pod_mod

    net = _net()
    net.fit(_iterator(), epochs=1)
    pod = PodConfig()           # real: n_hosts == process_count
    assert pod.n_hosts == 2 and not pod.emulated
    d = os.path.join(outdir, "pod_mp")
    pod_mod.write_pod_snapshot(net, d, pod, rng_key=net._base_key)
    if pod.is_coordinator:
        restored, man = pod_mod.restore_pod_snapshot(d, pod)
        np.save(os.path.join(outdir, "restored.npy"), _flat(restored))
        np.save(os.path.join(outdir, "ref.npy"), _flat(net))
    print("POD_MP_DONE", pid)
""")


def test_two_process_pod_snapshot_roundtrip(tmp_path):
    """REAL 2-process pod: each host writes only its own shard, the
    coordinator commits, restore round-trips bitwise. Skips cleanly
    where the jaxlib cannot run multi-process CPU collectives."""
    pod_harness.require_multiprocess(2)
    results = pod_harness.run_pod(_MP_BODY, n=2, local_devices=4,
                                  outdir=str(tmp_path))
    assert all("POD_MP_DONE" in o for _, o in results)
    d = os.path.join(str(tmp_path), "pod_mp")
    assert os.path.exists(os.path.join(d, "manifest.json"))
    restored = np.load(os.path.join(str(tmp_path), "restored.npy"))
    ref = np.load(os.path.join(str(tmp_path), "ref.npy"))
    np.testing.assert_array_equal(restored, ref)
