"""Model zoo: topology construction, parameter-count parity, forward shapes
(reference oracle: dl4j-zoo model smoke tests, SURVEY.md §4 integration
tier). Small spatial sizes keep the CPU oracle fast; channel structure is
the full reference topology."""

import numpy as np
import pytest

from deeplearning4j_tpu.zoo.graphs import (
    VGG16,
    VGG19,
    Darknet19,
    ResNet50,
    SqueezeNet,
    UNet,
)
from deeplearning4j_tpu.zoo.models import LeNet, SimpleCNN


def _forward(model, h, w, c, batch=2):
    net = model.init()
    x = np.random.default_rng(0).normal(size=(batch, h, w, c)).astype(
        np.float32)
    return net, np.asarray(net.output(x))


class TestSequentialZoo:
    def test_lenet_shapes(self):
        net, out = _forward(LeNet(num_classes=10), 28, 28, 1)
        assert out.shape == (2, 10)
        np.testing.assert_allclose(out.sum(axis=1), 1.0, rtol=1e-5)

    def test_simplecnn_shapes(self):
        net, out = _forward(SimpleCNN(num_classes=5, height=32, width=32,
                                      channels=3), 32, 32, 3)
        assert out.shape == (2, 5)


class TestGraphZoo:
    def test_vgg16_small(self):
        net, out = _forward(VGG16(num_classes=10, height=64, width=64), 64,
                            64, 3)
        assert out.shape == (2, 10)
        # 13 conv layers + 3 dense
        convs = [n for n in net.conf.topo_order() if n.startswith("conv")]
        assert len(convs) == 13

    def test_vgg19_has_16_convs(self):
        conf = VGG19(num_classes=10, height=64, width=64).conf()
        convs = [n for n in conf.topo_order() if n.startswith("conv")]
        assert len(convs) == 16

    def test_resnet50_param_count_parity(self):
        # Reference ResNet50 (ImageNet, 1000 classes): 25,557,032 trainable
        # params (conv weights w/o bias, BN gamma/beta, final FC w/ bias).
        net = ResNet50(num_classes=1000).init()
        assert net.num_params() == 25_557_032

    def test_resnet50_small_forward_and_train(self):
        from deeplearning4j_tpu.datasets.dataset import DataSet

        model = ResNet50(num_classes=7, height=64, width=64)
        net, out = _forward(model, 64, 64, 3)
        assert out.shape == (2, 7)
        np.testing.assert_allclose(out.sum(axis=1), 1.0, rtol=1e-4)
        # one train step runs and produces a finite loss
        y = np.eye(7, dtype=np.float32)[[0, 3]]
        x = np.random.default_rng(1).normal(size=(2, 64, 64, 3)).astype(
            np.float32)
        loss = net.fit_batch(DataSet(x, y))
        assert np.isfinite(loss)

    def test_squeezenet_small(self):
        net, out = _forward(SqueezeNet(num_classes=10, height=96, width=96),
                            96, 96, 3)
        assert out.shape == (2, 10)
        fires = {n.rsplit("_", 1)[0] for n in net.conf.topo_order()
                 if n.startswith("fire")}
        assert len(fires) == 8

    def test_darknet19_has_19_convs(self):
        net, out = _forward(Darknet19(num_classes=10, height=64, width=64),
                            64, 64, 3)
        assert out.shape == (2, 10)
        # 18 bn convs + the 1x1 classification head = 19 convolutions
        convs = [n for n in net.conf.topo_order()
                 if (n.startswith("conv") and not n.endswith("_bn"))
                 or n == "head"]
        assert len(convs) == 19

    def test_unet_output_is_input_resolution_mask(self):
        net, out = _forward(UNet(height=32, width=32, channels=1, base=8),
                            32, 32, 1)
        assert out.shape == (2, 32, 32, 1)
        assert (out >= 0).all() and (out <= 1).all()  # sigmoid head


class TestNewZooModels:
    def test_alexnet(self):
        from deeplearning4j_tpu.zoo.models import AlexNet

        net, out = _forward(AlexNet(num_classes=7, height=64, width=64),
                            64, 64, 3)
        assert out.shape == (2, 7)

    def test_text_generation_lstm(self):
        from deeplearning4j_tpu.zoo.models import TextGenerationLSTM

        net = TextGenerationLSTM(total_unique_characters=30,
                                 max_length=12).init()
        x = np.random.default_rng(0).normal(size=(2, 12, 30)).astype(
            np.float32)
        out = np.asarray(net.output(x))
        assert out.shape == (2, 12, 30)
        np.testing.assert_allclose(out.sum(-1), 1.0, rtol=1e-5)

    def test_xception(self):
        from deeplearning4j_tpu.zoo.graphs import Xception

        net, out = _forward(Xception(num_classes=5, height=71, width=71,
                                     middle_flow_repeats=1), 71, 71, 3)
        assert out.shape == (2, 5)

    def test_inception_resnet_v1(self):
        from deeplearning4j_tpu.zoo.graphs import InceptionResNetV1

        net, out = _forward(InceptionResNetV1(
            num_classes=5, height=96, width=96, blocks_a=1, blocks_b=1,
            blocks_c=1), 96, 96, 3)
        assert out.shape == (2, 5)

    def test_tiny_yolo(self):
        from deeplearning4j_tpu.zoo.graphs import TinyYOLO

        net, out = _forward(TinyYOLO(num_classes=3, height=64, width=64),
                            64, 64, 3)
        # 64/32 = 2x2 grid, 5 anchors * (5+3) = 40 channels
        assert out.shape == (2, 2, 2, 40)

    def test_yolo2_passthrough(self):
        from deeplearning4j_tpu.zoo.graphs import YOLO2

        net, out = _forward(YOLO2(num_classes=3, height=64, width=64),
                            64, 64, 3)
        assert out.shape == (2, 2, 2, 40)
        assert "route_s2d" in net.conf.topo_order()

    def test_nasnet(self):
        from deeplearning4j_tpu.zoo.graphs import NASNet

        net, out = _forward(NASNet(num_classes=5, height=32, width=32,
                                   num_cells=1, penultimate_filters=96),
                            32, 32, 3)
        assert out.shape == (2, 5)


class TestTransformerEncoder:
    def test_forward_and_learn(self):
        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.zoo.graphs import TransformerEncoder

        rng = np.random.default_rng(0)
        net = TransformerEncoder(num_classes=2, embed_dim=16, n_heads=2,
                                 n_layers=2, max_len=8,
                                 attention_impl="reference").init()
        # task: class = sign of mean of first feature over time
        x = rng.normal(size=(32, 8, 16)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[(x[:, :, 0].mean(1) > 0).astype(int)]
        ds = DataSet(x, y)
        s0 = net.fit_batch(ds)
        for _ in range(40):
            s1 = net.fit_batch(ds)
        assert s1 < s0 * 0.7

    def test_order_dependence_via_positions(self):
        # without positional information this task is unlearnable: class =
        # (first half mean of feature 0) > (second half mean of feature 0)
        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.zoo.graphs import TransformerEncoder

        rng = np.random.default_rng(3)
        net = TransformerEncoder(num_classes=2, embed_dim=16, n_heads=2,
                                 n_layers=2, max_len=8,
                                 attention_impl="reference").init()
        x = rng.normal(size=(64, 8, 16)).astype(np.float32)
        cls = (x[:, :4, 0].mean(1) > x[:, 4:, 0].mean(1)).astype(int)
        y = np.eye(2, dtype=np.float32)[cls]
        ds = DataSet(x, y)
        for _ in range(120):
            net.fit_batch(ds)
        preds = np.asarray(net.output(x)).argmax(-1)
        acc = (preds == cls).mean()
        assert acc > 0.85  # permutation-invariant models sit at ~0.5

    def test_token_input_variant(self):
        from deeplearning4j_tpu.zoo.graphs import TransformerEncoder

        rng = np.random.default_rng(0)
        net = TransformerEncoder(num_classes=3, vocab_size=50, embed_dim=16,
                                 n_heads=2, n_layers=1, max_len=10).init()
        ids = rng.integers(0, 50, (4, 10)).astype(np.int32)
        out = np.asarray(net.output(ids))
        assert out.shape == (4, 3)
        np.testing.assert_allclose(out.sum(-1), 1.0, rtol=1e-5)


def test_layer_normalization_math():
    import jax.numpy as jnp

    from deeplearning4j_tpu.conf import InputType
    from deeplearning4j_tpu.conf.layers_extra import LayerNormalization

    rng = np.random.default_rng(0)
    ln = LayerNormalization()
    t = InputType.recurrent(8, timesteps=4)
    params = ln.init(None, t)
    x = jnp.asarray(rng.normal(size=(2, 4, 8), scale=3.0), jnp.float32)
    y, _ = ln.forward(params, {}, x)
    np.testing.assert_allclose(np.asarray(y).mean(-1), 0.0, atol=1e-5)
    np.testing.assert_allclose(np.asarray(y).std(-1), 1.0, atol=1e-3)


def test_resnet_space_to_depth_stem_is_exact():
    """stem_space_to_depth is an EXACT rewrite (round 3, MLPerf trick):
    with stem weights remapped through stem_weights_to_s2d, the rewritten
    network computes the SAME function as the reference topology."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.zoo.graphs import ResNet50

    rng = np.random.default_rng(0)

    # unit check of the conv identity itself
    x = jnp.asarray(rng.normal(size=(2, 32, 32, 3)).astype(np.float32))
    w7 = jnp.asarray(rng.normal(size=(7, 7, 3, 8)).astype(np.float32))
    ref = jax.lax.conv_general_dilated(
        x, w7, (2, 2), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"))
    xs = x.reshape(2, 16, 2, 16, 2, 3).transpose(0, 1, 3, 2, 4, 5) \
        .reshape(2, 16, 16, 12)
    xp = jnp.pad(xs, ((0, 0), (1, 2), (1, 2), (0, 0)))
    w4 = jnp.asarray(ResNet50.stem_weights_to_s2d(np.asarray(w7)))
    got = jax.lax.conv_general_dilated(
        xp, w4, (1, 1), "VALID", dimension_numbers=("NHWC", "HWIO", "NHWC"))
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)

    # end-to-end through the zoo wiring on a small ResNet50
    base = ResNet50(num_classes=4, height=32, width=32, seed=9)
    na = ComputationGraph(base.conf()).init()
    s2d = ResNet50(num_classes=4, height=32, width=32, seed=9)
    s2d.stem_space_to_depth = True
    nb = ComputationGraph(s2d.conf()).init()

    import jax as _jax

    nb.params = _jax.tree_util.tree_map(jnp.asarray, dict(na.params))
    nb.params["stem_conv"] = {"W": jnp.asarray(
        ResNet50.stem_weights_to_s2d(np.asarray(na.params["stem_conv"]["W"])))}
    nb.state = _jax.tree_util.tree_map(jnp.asarray, dict(na.state))

    xin = rng.normal(size=(2, 32, 32, 3)).astype(np.float32)
    ya = np.asarray(na.output(xin))
    yb = np.asarray(nb.output(xin))
    np.testing.assert_allclose(yb, ya, rtol=2e-3, atol=2e-4)


def test_restore_partial_remaps_s2d_stem(tmp_path):
    """Pretrained weights saved from the REFERENCE topology load into an
    s2d-stem network: the [7,7,3,64] stem kernel remaps to [4,4,12,64]
    through stem_weights_to_s2d instead of being silently skipped
    (round-3 review finding)."""
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.util import serializer
    from deeplearning4j_tpu.zoo.graphs import ResNet50
    from deeplearning4j_tpu.zoo.pretrained import restore_partial

    base = ResNet50(num_classes=4, height=32, width=32, seed=9)
    na = ComputationGraph(base.conf()).init()
    path = str(tmp_path / "ref.zip")
    serializer.write_model(na, path)

    s2d = ResNet50(num_classes=4, height=32, width=32, seed=1)
    s2d.stem_space_to_depth = True
    nb = ComputationGraph(s2d.conf()).init()
    loaded, skipped = restore_partial(path, nb)
    assert "stem_conv/W" in loaded
    assert not any(k.startswith("stem_conv") for k in skipped)
    # the loaded network computes the same function as the donor
    x = np.random.default_rng(0).normal(size=(2, 32, 32, 3)) \
        .astype(np.float32)
    np.testing.assert_allclose(np.asarray(nb.output(x)),
                               np.asarray(na.output(x)),
                               rtol=2e-3, atol=2e-4)


def test_fused_conv_bn_layer_matches_pair():
    """FusedConvBN1x1 == ConvolutionLayer(1x1, no bias, identity) +
    BatchNormalization(relu): forward, statistics, running-state update,
    AND gradients — with the Pallas kernel force-enabled (interpret mode
    on CPU) so the fused single-pass path itself is what's validated."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.conf import Activation, InputType
    from deeplearning4j_tpu.conf.layers_cnn import (
        BatchNormalization,
        ConvolutionLayer,
        ConvolutionMode,
        FusedConvBN1x1,
    )

    rng = np.random.default_rng(0)
    t = InputType.convolutional(8, 8, 64)
    fused = FusedConvBN1x1(n_out=128, activation=Activation.RELU,
                           force_kernel=True)
    conv = ConvolutionLayer(n_out=128, kernel_size=(1, 1), has_bias=False,
                            activation=Activation.IDENTITY,
                            convolution_mode=ConvolutionMode.SAME)
    bn = BatchNormalization(activation=Activation.RELU)

    key = jax.random.PRNGKey(3)
    pf = fused.init(key, t)
    sf = fused.init_state(t)
    pc = {"W": pf["W"]}
    pb = {"gamma": pf["gamma"], "beta": pf["beta"]}
    sb = bn.init_state(t._replace(channels=128) if hasattr(t, "_replace")
                       else InputType.convolutional(8, 8, 128))

    x = jnp.asarray(rng.normal(size=(2, 8, 8, 64)).astype(np.float32))

    def pair_fwd(pc, pb, x, train):
        y, _ = conv.forward(pc, {}, x, train=train)
        out, ns = bn.forward(pb, sb, y, train=train)
        return out, ns

    # train mode: kernel path vs pair
    yf, nsf = fused.forward(pf, sf, x, train=True)
    yr, nsr = pair_fwd(pc, pb, x, train=True)
    np.testing.assert_allclose(np.asarray(yf), np.asarray(yr),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(nsf["mean"]),
                               np.asarray(nsr["mean"]), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(nsf["var"]),
                               np.asarray(nsr["var"]), rtol=1e-3, atol=1e-5)

    # eval mode (XLA fallback path — running stats)
    ye, _ = fused.forward(pf, sf, x, train=False)
    yre, _ = pair_fwd(pc, pb, x, train=False)
    np.testing.assert_allclose(np.asarray(ye), np.asarray(yre),
                               rtol=1e-5, atol=1e-5)

    # gradient parity through the custom VJP (nonlinear probe so the
    # BN-normalization null-space doesn't hide errors)
    def loss_fused(pf, x):
        y, _ = fused.forward(pf, sf, x, train=True)
        return jnp.sum(y * y * jnp.linspace(0.5, 1.5, 128))

    def loss_pair(pf, x):
        y, _ = pair_fwd({"W": pf["W"]},
                        {"gamma": pf["gamma"], "beta": pf["beta"]}, x, True)
        return jnp.sum(y * y * jnp.linspace(0.5, 1.5, 128))

    gf, gxf = jax.grad(loss_fused, argnums=(0, 1))(pf, x)
    gr, gxr = jax.grad(loss_pair, argnums=(0, 1))(pf, x)
    for k in ("W", "gamma", "beta"):
        np.testing.assert_allclose(np.asarray(gf[k]), np.asarray(gr[k]),
                                   rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(np.asarray(gxf), np.asarray(gxr),
                               rtol=1e-3, atol=1e-3)

    # strided variant == strided 1x1 conv + BN
    fused_s = FusedConvBN1x1(n_out=128, stride=(2, 2),
                             activation=Activation.RELU, force_kernel=True)
    conv_s = ConvolutionLayer(n_out=128, kernel_size=(1, 1), stride=(2, 2),
                              has_bias=False, activation=Activation.IDENTITY,
                              convolution_mode=ConvolutionMode.SAME)
    x2 = jnp.asarray(rng.normal(size=(8, 8, 8, 64)).astype(np.float32))
    ys, _ = fused_s.forward(pf, sf, x2, train=True)
    yc, _ = conv_s.forward(pc, {}, x2, train=True)
    yb, _ = bn.forward(pb, sb, yc, train=True)
    np.testing.assert_allclose(np.asarray(ys), np.asarray(yb),
                               rtol=1e-4, atol=1e-4)


def test_resnet_fused_conv_bn_is_exact():
    """fused_conv_bn=True computes the same function as the reference
    topology with weights mapped through fused_param_remap — eval output
    parity end-to-end, and train-mode fit-step parity (same loss, params
    stay close after one update) with the kernel force-enabled."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.conf.layers_cnn import FusedConvBN1x1
    from deeplearning4j_tpu.nn.graph import ComputationGraph

    base = ResNet50(num_classes=4, height=32, width=32, seed=9)
    na = ComputationGraph(base.conf()).init()
    fz = ResNet50(num_classes=4, height=32, width=32, seed=9)
    fz.fused_conv_bn = True
    conf = fz.conf()
    n_fused = 0
    for vs in conf.vertices:
        layer = getattr(vs.vertex, "layer", None)
        if isinstance(layer, FusedConvBN1x1):
            layer.force_kernel = True
            n_fused += 1
    # 16 bottlenecks x (a + c) + 4 stage projections; the 3x3 b-convs
    # and the 7x7 stem stay unfused
    assert n_fused == 36
    nb = ComputationGraph(conf).init()

    p, s = ResNet50.fused_param_remap(dict(na.params), dict(na.state))
    assert set(p.keys()) == set(nb.params.keys())
    # copies, not references: the fit step donates its input buffers
    nb.params = jax.tree_util.tree_map(lambda a: jnp.array(a, copy=True), p)
    nb.state = jax.tree_util.tree_map(lambda a: jnp.array(a, copy=True), s)

    rng = np.random.default_rng(0)
    x = rng.normal(size=(2, 32, 32, 3)).astype(np.float32)
    ya = np.asarray(na.output(x))
    yb = np.asarray(nb.output(x))
    np.testing.assert_allclose(yb, ya, rtol=2e-3, atol=2e-4)

    # one train step, kernel ON vs kernel OFF (both one-pass statistics,
    # so the only delta is the Pallas matmul+sums vs XLA conv+reduces):
    # same loss, parameters agree after the update. The unfused PAIR
    # uses two-pass jnp.var whose f32 cancellation difference amplifies
    # through 53 BN layers — layer-level parity vs the pair is pinned in
    # test_fused_conv_bn_layer_matches_pair instead.
    from deeplearning4j_tpu.datasets.dataset import DataSet

    off = ResNet50(num_classes=4, height=32, width=32, seed=9)
    off.fused_conv_bn = True
    conf_off = off.conf()
    for vs in conf_off.vertices:
        layer = getattr(vs.vertex, "layer", None)
        if isinstance(layer, FusedConvBN1x1):
            layer.kernel_mode = "off"
    nc = ComputationGraph(conf_off).init()
    nc.params = jax.tree_util.tree_map(lambda a: jnp.array(a, copy=True), p)
    nc.state = jax.tree_util.tree_map(lambda a: jnp.array(a, copy=True), s)

    labels = np.eye(4, dtype=np.float32)[rng.integers(0, 4, size=2)]
    lb = nb.fit_batch(DataSet(x, labels))
    lc = nc.fit_batch(DataSet(x, labels))
    # the loss sits behind ~50 BN/ReLU layers at batch 2: f32
    # reduce-order noise (~1e-6 at the first site, verified tight below)
    # amplifies chaotically with depth — one-pass BN statistics make the
    # amplification stronger still — so the deep loss is only a sanity
    # band (catches NaN / wrong wiring); the tight numeric pinning is
    # the layer-level test above plus the first fused site here, whose
    # inputs are still bit-identical between the two nets
    assert np.isfinite(lb) and np.isfinite(lc)
    assert 0.5 < lb / lc < 2.0, (lb, lc)
    np.testing.assert_allclose(
        np.asarray(nb.state["res2a_a_cb"]["mean"]),
        np.asarray(nc.state["res2a_a_cb"]["mean"]), rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(
        np.asarray(nb.state["res2a_a_cb"]["var"]),
        np.asarray(nc.state["res2a_a_cb"]["var"]), rtol=1e-4, atol=1e-6)
    # (no param comparison after the update: Adam's first step is
    # ~±lr elementwise, so deep chaotic grad noise flips signs — the
    # custom-VJP gradient itself is pinned in the layer-level test)
