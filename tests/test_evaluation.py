import numpy as np

from deeplearning4j_tpu.eval.evaluation import (
    Evaluation,
    EvaluationBinary,
    RegressionEvaluation,
    ROC,
)


def test_evaluation_confusion_and_metrics():
    ev = Evaluation()
    labels = np.eye(3)[[0, 0, 1, 1, 2, 2]]
    preds = np.eye(3)[[0, 1, 1, 1, 2, 0]]
    ev.eval(labels, preds)
    assert ev.confusion.tolist() == [[1, 1, 0], [0, 2, 0], [1, 0, 1]]
    assert np.isclose(ev.accuracy(), 4 / 6)
    # class 1: precision 2/3, recall 1.0
    assert np.isclose(ev.precision(1), 2 / 3)
    assert np.isclose(ev.recall(1), 1.0)
    f1 = ev.f1(1)
    assert np.isclose(f1, 2 * (2 / 3) / (2 / 3 + 1))
    assert "Accuracy" in ev.stats()


def test_evaluation_batched_equals_single():
    rng = np.random.default_rng(0)
    labels = np.eye(4)[rng.integers(0, 4, 100)]
    preds = rng.random((100, 4))
    ev1 = Evaluation().eval(labels, preds)
    ev2 = Evaluation()
    ev2.eval(labels[:50], preds[:50])
    ev2.eval(labels[50:], preds[50:])
    assert (ev1.confusion == ev2.confusion).all()


def test_evaluation_mask():
    ev = Evaluation(num_classes=2)
    labels = np.eye(2)[[0, 1, 1]]
    preds = np.eye(2)[[0, 0, 0]]
    ev.eval(labels, preds, mask=np.array([1.0, 1.0, 0.0]))
    assert ev.confusion.sum() == 2
    assert np.isclose(ev.accuracy(), 0.5)


def test_roc_auc_perfect_and_random():
    roc = ROC()
    labels = np.array([0, 0, 1, 1])
    scores = np.array([0.1, 0.2, 0.8, 0.9])
    roc.eval(labels, scores)
    assert np.isclose(roc.calculate_auc(), 1.0)
    roc2 = ROC()
    roc2.eval(np.array([0, 1, 0, 1]), np.array([0.5, 0.5, 0.5, 0.5]))
    assert np.isclose(roc2.calculate_auc(), 0.5)
    assert 0.0 < roc.calculate_auprc() <= 1.0


def test_regression_evaluation():
    re = RegressionEvaluation()
    labels = np.array([[1.0], [2.0], [3.0]])
    preds = np.array([[1.5], [2.0], [2.5]])
    re.eval(labels, preds)
    assert np.isclose(re.mean_squared_error(0), (0.25 + 0 + 0.25) / 3)
    assert np.isclose(re.mean_absolute_error(0), 1 / 3)
    assert re.r_squared(0) > 0.5
    assert re.pearson_correlation(0) > 0.9


def test_evaluation_binary():
    eb = EvaluationBinary()
    labels = np.array([[1, 0], [1, 1], [0, 0], [0, 1]], np.float32)
    preds = np.array([[0.9, 0.1], [0.8, 0.4], [0.3, 0.2], [0.1, 0.9]], np.float32)
    eb.eval(labels, preds)
    assert np.isclose(eb.accuracy(0), 1.0)
    assert np.isclose(eb.recall(1), 0.5)


def test_roc_binary_multilabel(rng):
    from deeplearning4j_tpu.eval.evaluation import ROCBinary

    n = 200
    labels = (rng.random((n, 3)) > 0.5).astype(np.float32)
    preds = np.clip(labels * 0.8 + rng.random((n, 3)) * 0.2, 0, 1)
    roc = ROCBinary()
    roc.eval(labels, preds.astype(np.float32))
    assert roc.calculate_average_auc() > 0.9
