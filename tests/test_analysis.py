"""Static-analysis subsystem: program (jaxpr/HLO) + source (AST) linters.

Every rule gets a SEEDED-DEFECT fixture (a minimal program/source sample
carrying exactly the bug the rule exists to catch) plus a negative
control, and the repo itself must come out clean: the source pass over
``deeplearning4j_tpu/`` reports zero unwaived findings, and the
donation audit over real train-step executables reports full aliasing.
"""

import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.analysis import findings as fmod
from deeplearning4j_tpu.analysis import program, source
from deeplearning4j_tpu.analysis.findings import LOG, Finding, parse_waivers

pytestmark = pytest.mark.analysis


def rules_of(findings, waived=None):
    out = []
    for f in findings:
        if waived is None or f.waived == waived:
            out.append(f.rule)
    return out


# ==========================================================================
# program rules (seeded defects via trace_artifact: no cache-global state)
# ==========================================================================

def test_prg201_undonated_train_step_detected():
    def step(params, opt, x):
        g = x * 2.0
        return params - g, opt + 1.0

    args = (jnp.ones((16,)), jnp.ones((16,)), jnp.ones((16,)))
    art = program.trace_artifact(jax.jit(step), args,
                                 fn_key="train_step:seeded")
    assert "PRG201" in rules_of(program.lint_program(art))


def test_prg201_donated_train_step_clean():
    def step(params, opt, x):
        return params - x, opt + 1.0

    args = (jnp.ones((16,)), jnp.ones((16,)), jnp.ones((16,)))
    art = program.trace_artifact(
        jax.jit(step, donate_argnums=(0, 1)), args,
        fn_key="train_step:seeded")
    assert "PRG201" not in rules_of(program.lint_program(art))


def test_prg201_not_applied_to_inference_kinds():
    art = program.trace_artifact(
        jax.jit(lambda x: x * 2.0), (jnp.ones((4,)),), fn_key="output")
    assert rules_of(program.lint_program(art)) == []


def test_prg201_covers_spec_and_prefix_kinds():
    """The speculative-decoding window/sync and prefix-cache attach/join
    executables consume the donated decode state: an undonated fixture
    under any of those kinds trips PRG201, a donated one is clean, and
    the suffix PREFILL (reads shared refcounted pages — must NOT
    donate) stays exempt by construction."""
    def step(state, upd):
        return state + upd

    args = (jnp.ones((16,)), jnp.ones((16,)))
    for kind in ("spec_verify:s32:k2", "spec_sync:s32",
                 "prefix_attach:s32:t8:b2", "prefix_join:s32:t8:b2"):
        art = program.trace_artifact(jax.jit(step), args, fn_key=kind)
        assert "PRG201" in rules_of(program.lint_program(art)), kind
        art = program.trace_artifact(
            jax.jit(step, donate_argnums=(0,)), args, fn_key=kind)
        assert "PRG201" not in rules_of(program.lint_program(art)), kind
    art = program.trace_artifact(jax.jit(step), args,
                                 fn_key="gen_prompt_sfx:t8:p16:b2")
    assert "PRG201" not in rules_of(program.lint_program(art))


def test_prg202_baked_constant():
    big = np.ones((512, 1024), np.float32)  # 2 MiB closure capture

    def step(x):
        return (jnp.asarray(big) @ x).sum()

    art = program.trace_artifact(jax.jit(step), (jnp.ones((1024,)),),
                                 fn_key="output", compile=False)
    hits = [f for f in program.lint_program(art) if f.rule == "PRG202"]
    assert hits and hits[0].severity == fmod.WARN
    assert "2.0 MiB" in hits[0].message


def test_prg203_f64_promotion_leak():
    with jax.experimental.enable_x64(True):
        def step(x):
            return x.astype("float64").sum() * 2.0

        art = program.trace_artifact(
            jax.jit(step), (jnp.ones((8,), "float32"),),
            fn_key="score", compile=False)
        assert "PRG203" in rules_of(program.lint_program(art))


def test_prg203_silent_when_caller_passes_f64():
    with jax.experimental.enable_x64(True):
        art = program.trace_artifact(
            jax.jit(lambda x: x.sum()), (jnp.ones((8,), "float64"),),
            fn_key="score", compile=False)
        assert "PRG203" not in rules_of(program.lint_program(art))


def test_prg204_host_callback():
    def step(x):
        y = jax.pure_callback(
            np.sin, jax.ShapeDtypeStruct(x.shape, x.dtype), x)
        return y.sum()

    art = program.trace_artifact(jax.jit(step), (jnp.ones((4,)),),
                                 fn_key="train_step:cb", compile=False)
    hits = [f for f in program.lint_program(art) if f.rule == "PRG204"]
    assert hits and hits[0].severity == fmod.ERROR
    assert "pure_callback" in hits[0].message


def _one_device_mesh():
    from jax.sharding import Mesh

    return Mesh(np.array(jax.devices()[:1]), ("data",))


def _shard_mapped(body, n_out):
    from jax.sharding import PartitionSpec as P

    try:
        from jax.experimental.shard_map import shard_map
    except ImportError:  # newer jax
        from jax.sharding import shard_map

    return shard_map(body, _one_device_mesh(), in_specs=P("data"),
                     out_specs=(P("data"),) * n_out)


def test_prg205_zero_step_that_all_reduces():
    def body(g):
        return (jax.lax.psum(g, "data"),)  # dense all-reduce: the defect

    art = program.trace_artifact(
        jax.jit(_shard_mapped(body, 1)), (jnp.ones((4,)),),
        fn_key="pw_zero:n1:b0", compile=False)
    hits = [f for f in program.lint_program(art) if f.rule == "PRG205"]
    assert hits and hits[0].severity == fmod.ERROR
    assert "reduce-scatter" in hits[0].message


def test_prg205_unordered_bucket_chain():
    def body(g):
        a = jax.lax.psum_scatter(g, "data", scatter_dimension=0,
                                 tiled=True)
        b = jax.lax.psum_scatter(g * 2.0, "data", scatter_dimension=0,
                                 tiled=True)
        return a, b  # two buckets, no optimization_barrier pin

    art = program.trace_artifact(
        jax.jit(_shard_mapped(body, 2)), (jnp.ones((4,)),),
        fn_key="pw_zero:n1:b4096", compile=False)
    hits = [f for f in program.lint_program(art) if f.rule == "PRG205"]
    assert hits and hits[0].severity == fmod.WARN
    assert "optimization_barrier" in hits[0].message


def test_prg205_clean_on_real_zero_exchange():
    """The repo's own bucketed exchange (scatter + barrier chain) must
    pass its own audit."""
    from deeplearning4j_tpu.parallel.compression import (
        bucketed_psum_scatter,
    )

    def body(g):
        tree = {"a": g, "b": g * 2.0, "c": g * 3.0}
        out = bucketed_psum_scatter(tree, "data", bucket_bytes=8)
        return out["a"], out["b"], out["c"]

    art = program.trace_artifact(
        jax.jit(_shard_mapped(body, 3)), (jnp.ones((4,)),),
        fn_key="pw_zero:n1:b8", compile=False)
    assert "PRG205" not in rules_of(program.lint_program(art))


def test_prg206_python_scalar_churn():
    from deeplearning4j_tpu.optimize.aot_cache import signature_of

    x = jnp.ones((4,))
    sig_int = signature_of((x, 1))        # python scalar leaf
    args = (x, np.int32(1))

    art = program.trace_artifact(jax.jit(lambda a, b: a + b), args,
                                 fn_key="adhoc", compile=False,
                                 sibling_sigs=(sig_int,))
    hits = [f for f in program.lint_program(art) if f.rule == "PRG206"]
    assert hits and "python scalar" in hits[0].message


def test_prg206_shape_change_is_a_legitimate_miss():
    from deeplearning4j_tpu.optimize.aot_cache import signature_of

    sig_other = signature_of((jnp.ones((8,)), np.int32(1)))
    art = program.trace_artifact(
        jax.jit(lambda a, b: a + b), (jnp.ones((4,)), np.int32(1)),
        fn_key="adhoc", compile=False, sibling_sigs=(sig_other,))
    assert "PRG206" not in rules_of(program.lint_program(art))


def test_prg206_fires_through_the_live_cache():
    """Integration: the aot_cache miss hook reports scalar churn for
    real — two calls differing only in a python-vs-np scalar leaf."""
    from deeplearning4j_tpu.optimize import aot_cache

    LOG.clear()
    step = aot_cache.wrap(jax.jit(lambda a, b: a + b),
                          "prg206-integration", "adhoc")
    x = jnp.ones((3,))
    step(x, np.float32(2.0))
    step(x, 2.0)  # python float: same shapes, churned signature
    # locations carry the first 12 chars of the graph key
    assert any(f.rule == "PRG206" and "prg206-integ" in f.location
               for f in LOG.items())


def test_program_waiver_by_key():
    def step(params, x):
        return params - x

    art = program.trace_artifact(jax.jit(step),
                                 (jnp.ones((4,)), jnp.ones((4,))),
                                 fn_key="train_step:waived-fixture")
    try:
        program.waive_program("PRG201", "waived-fixture",
                              "fixture: donation intentionally absent")
        fs = program.lint_program(art)
    finally:
        program._WAIVERS.clear()
    hits = [f for f in fs if f.rule == "PRG201"]
    assert hits and hits[0].waived
    assert "intentionally absent" in hits[0].waiver_reason


# ==========================================================================
# source rules (seeded-defect fixtures as inline modules)
# ==========================================================================

def lint(src: str, today=None):
    return source.lint_source(textwrap.dedent(src), "fix.py", today=today)


def test_src101_host_sync_fixture():
    fs = lint('''
        import jax
        import numpy as np

        def build():
            def step(params, x):
                a = params["w"].item()
                b = float(x.sum())
                c = np.asarray(x)
                x.block_until_ready()
                return a + b + c.sum()
            return jax.jit(step)
    ''')
    assert rules_of(fs).count("SRC101") == 4
    assert all(f.severity == fmod.ERROR for f in fs)


def test_src101_host_code_not_flagged():
    fs = lint('''
        import numpy as np

        def host_metrics(loss):
            return float(np.asarray(loss))  # never traced: fine
    ''')
    assert "SRC101" not in rules_of(fs)


def test_src101_reaches_through_builder_and_nested_calls():
    """The fixpoint follows the repo idiom: jit(step) where step calls
    raw = self.train_step_fn(...) whose returned inner fn syncs."""
    fs = lint('''
        import jax

        class Net:
            def train_step_fn(self):
                def fn(params, x):
                    return float(x.sum())
                return fn

            def build(self):
                raw = self.train_step_fn()

                def step(params, x):
                    return raw(params, x)

                return jax.jit(step, donate_argnums=(0,))
    ''')
    assert "SRC101" in rules_of(fs)


def test_src102_unlocked_mutation_fixture():
    fs = lint('''
        import threading

        _REG = {}
        _LOCK = threading.Lock()

        def put(k, v):
            with _LOCK:
                _REG[k] = v

        def put_fast(k, v):
            _REG[k] = v  # the defect: same registry, no lock
    ''')
    hits = [f for f in fs if f.rule == "SRC102"]
    assert len(hits) == 1 and "put_fast" in hits[0].message


def test_src102_locked_suffix_and_init_exempt():
    fs = lint('''
        import threading

        class Reg:
            def __init__(self):
                self._m = {}
                self._lock = threading.Lock()
                self._m["boot"] = 1

            def put(self, k, v):
                with self._lock:
                    self._m[k] = v

            def _put_locked(self, k, v):
                self._m[k] = v  # caller holds the lock: exempt
    ''')
    assert "SRC102" not in rules_of(fs)


def test_src103_wallclock_and_rng_fixture():
    fs = lint('''
        import time
        import numpy as np
        import jax

        def build():
            def step(x):
                t = time.time()
                r = np.random.rand(4)
                return x.sum() + t + r.sum()
            return jax.jit(step)
    ''')
    assert rules_of(fs).count("SRC103") == 2


def test_src105_bracketing_fixture():
    fs = lint('''
        from deeplearning4j_tpu import telemetry

        def dispatch(step, batch):
            telemetry.host_gap_close()
            return step(batch)     # no host_gap_open, no fault_point

        def fit(it):
            telemetry.host_gap_reset()
            for b in it:
                dispatch(None, b)  # no host_gap_stop
    ''')
    msgs = " | ".join(f.message for f in fs if f.rule == "SRC105")
    assert rules_of(fs).count("SRC105") == 3
    assert "host_gap_open" in msgs
    assert "host_gap_stop" in msgs
    assert "fault_point" in msgs


def test_src105_clean_when_bracketed():
    fs = lint('''
        from deeplearning4j_tpu import telemetry
        from deeplearning4j_tpu.resilience import faults

        def dispatch(step, batch):
            batch = faults.fault_point("train.step", batch)
            telemetry.host_gap_close()
            out = step(batch)
            telemetry.host_gap_open()
            return out

        def fit(it):
            telemetry.host_gap_reset()
            try:
                for b in it:
                    dispatch(None, b)
            finally:
                telemetry.host_gap_stop()
    ''')
    assert "SRC105" not in rules_of(fs)


def test_src106_unused_import_fixture():
    fs = lint('''
        import os
        import json as j
        from typing import List, Optional

        def f(x: Optional[int]):
            return os.sep + str(x)
    ''')
    hits = sorted(f.message for f in fs if f.rule == "SRC106")
    assert len(hits) == 2  # j, List; Optional and os are used
    assert "'List'" in hits[0] and "'j'" in hits[1]


@pytest.mark.obs
def test_src107_unfinished_request_span_fixture():
    """Seeded defect: a request span opened and never finished anywhere
    in the module — the trace leaks and the tail sampler never sees it."""
    fs = lint('''
        from deeplearning4j_tpu.telemetry import tracing

        def submit(x):
            t = tracing.start_trace("predict")
            return x, t
    ''')
    hits = [f for f in fs if f.rule == "SRC107"]
    assert hits and hits[0].severity == fmod.ERROR


@pytest.mark.obs
def test_src107_leaky_raise_warns():
    """The module does finish traces, but a function that both opens a
    span and raises without a finish on its own error edges leaks the
    span on exactly the abnormal path the sampler always keeps."""
    fs = lint('''
        from deeplearning4j_tpu.telemetry import tracing

        def submit(x):
            t = tracing.start_trace("predict")
            if x is None:
                raise ValueError("x required")
            return t

        def retire(t):
            tracing.finish_trace(t, "done")
    ''')
    hits = [f for f in fs if f.rule == "SRC107"]
    assert hits and hits[0].severity == fmod.WARN


@pytest.mark.obs
def test_src107_negative_control_and_xprof_exempt():
    # finish on every edge (the batcher/generation idiom): clean
    fs = lint('''
        from deeplearning4j_tpu.telemetry import tracing

        def submit(x):
            t = tracing.start_trace("predict")
            if x is None:
                tracing.finish_trace(t, "bad_request")
                raise ValueError("x required")
            return t

        def retire(t):
            tracing.finish_trace(t, "done")
    ''')
    assert "SRC107" not in rules_of(fs)
    # jax.profiler.start_trace is the XProf capture API, a different
    # protocol (stop_trace), not a request span: exempt
    fs = lint('''
        import jax

        def capture(path):
            jax.profiler.start_trace(path)
    ''')
    assert "SRC107" not in rules_of(fs)


def test_src106_exemptions():
    fs = lint('''
        from deeplearning4j_tpu.analysis import findings as findings  # re-export
        import fancyplugin  # noqa: F401

        try:
            import axon_tpu
        except ImportError:
            axon_tpu = None

        __all__ = ["exported"]
        from somewhere import exported
    ''')
    assert "SRC106" not in rules_of(fs)


# ==========================================================================
# waivers
# ==========================================================================

WAIVED_SRC = '''
    import jax

    def build():
        def step(x):
            return float(x.sum())  # dl4j: waive SRC101 %s— fixture accepts
        return jax.jit(step)
'''


def test_waiver_honored():
    fs = lint(WAIVED_SRC % "")
    hits = [f for f in fs if f.rule == "SRC101"]
    assert hits and hits[0].waived
    assert hits[0].waiver_reason == "fixture accepts"
    assert fmod.summarize(fs)["actionable"] == 0


def test_waiver_unexpired_dates_honored():
    fs = lint(WAIVED_SRC % "until=2999-01-01 ", today="2026-08-04")
    assert [f for f in fs if f.rule == "SRC101"][0].waived


def test_waiver_expired_stops_suppressing():
    fs = lint(WAIVED_SRC % "until=2020-01-01 ", today="2026-08-04")
    hits = [f for f in fs if f.rule == "SRC101"]
    assert hits and not hits[0].waived
    assert "waiver expired 2020-01-01" in hits[0].message
    assert fmod.summarize(fs)["actionable"] >= 1


def test_stale_waiver_flagged():
    fs = lint('''
        import os

        def f():
            return os.sep  # dl4j: waive SRC101 — nothing to suppress
    ''')
    hits = [f for f in fs if f.rule == "SRC100"]
    assert len(hits) == 1 and "suppresses nothing" in hits[0].message


def test_waiver_parser():
    ws = parse_waivers("x = 1  # dl4j: waive SRC101,SRC103 "
                       "until=2026-12-31 — two rules at once\n")
    assert ws[0].rules == ("SRC101", "SRC103")
    assert ws[0].until == "2026-12-31"
    assert ws[0].reason == "two rules at once"


# ==========================================================================
# findings log + metric + surfaces
# ==========================================================================

def test_findings_log_feeds_metric_and_snapshot():
    from deeplearning4j_tpu.telemetry import REGISTRY

    LOG.clear()
    LOG.record(Finding(rule="PRG204", severity="ERROR",
                       message="fixture", location="graph=x kind=y"))
    LOG.record(Finding(rule="SRC101", severity="ERROR", message="w",
                       location="a.py:1", waived=True,
                       waiver_reason="ok"))
    snap = LOG.snapshot()
    assert snap["counts"]["PRG204/ERROR"] == 1
    assert snap["counts"]["SRC101/ERROR"] == 1  # waived still listed...
    reg = REGISTRY.snapshot(run_collectors=False)
    key = 'dl4j_analysis_findings_total{rule="PRG204",severity="ERROR"}'
    assert reg[key] >= 1  # ...but only unwaived findings hit the metric
    assert ('dl4j_analysis_findings_total{rule="SRC101"'
            not in " ".join(reg))
    LOG.clear()


def test_analysis_endpoint_on_ui_server():
    import json
    import urllib.request

    from deeplearning4j_tpu.ui.server import UIServer

    LOG.clear()
    LOG.record(Finding(rule="PRG202", severity="WARN",
                       message="fixture const", location="graph=z kind=k"))
    ui = UIServer()
    port = ui.start(port=0)
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/analysis", timeout=10) as r:
            body = json.loads(r.read())
    finally:
        ui.stop()
        LOG.clear()
    assert body["counts"]["PRG202/WARN"] == 1
    assert body["findings"][0]["rule"] == "PRG202"


# ==========================================================================
# the repo itself is clean
# ==========================================================================

def test_repo_source_tree_is_clean():
    import os

    root = os.path.join(os.path.dirname(__file__), "..",
                        "deeplearning4j_tpu")
    fs = source.lint_paths(os.path.abspath(root))
    actionable = [f for f in fs if not f.waived
                  and fmod.severity_at_least(f.severity, fmod.WARN)]
    assert actionable == [], "\n" + "\n".join(
        f.render() for f in actionable)


def test_repo_train_steps_pass_program_lint_and_donation_audit():
    """Compile-and-fit one MLN and one graph step with the lint hook
    live; their executables must produce zero findings and full
    donation aliasing in the audit."""
    from deeplearning4j_tpu.conf import Activation, InputType
    from deeplearning4j_tpu.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.conf.losses import LossMCXENT
    from deeplearning4j_tpu.conf.multilayer import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    rng = np.random.RandomState(3)
    x = rng.randn(8, 5).astype("float32")
    y = np.eye(3, dtype="float32")[rng.randint(0, 3, 8)]
    conf = (NeuralNetConfiguration.builder().seed(3).list()
            .layer(DenseLayer(n_out=17, activation=Activation.TANH))
            .layer(OutputLayer(n_out=3, activation=Activation.SOFTMAX,
                               loss_fn=LossMCXENT()))
            .set_input_type(InputType.feed_forward(5)).build())
    net = MultiLayerNetwork(conf).init()
    LOG.clear()
    net.fit(x, y, epochs=1)
    gkey = net._graph_key()

    mine = [f for f in LOG.items() if gkey[:12] in f.location]
    assert mine == [], "\n".join(f.render() for f in mine)
    audit = {k: v for k, v in program.donation_audit().items()
             if k[0] == gkey}
    assert audit, "train step never reached the lint hook"
    assert all(v["aliases"] for v in audit.values()), audit


def test_every_cached_train_kind_is_donated_process_wide():
    """The global invariant the satellite demands: by this point in the
    suite every train-kind executable the process compiled (whatever
    test built it) aliases its buffers."""
    bad = {k: v for k, v in program.donation_audit().items()
           if v["aliases"] == 0}
    assert bad == {}, bad
