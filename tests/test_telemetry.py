"""Telemetry layer: spans (nesting/aggregation/Chrome trace), metrics
registry (determinism, prometheus format), /metrics endpoint, disabled-mode
fast path, training-path instrumentation, and the bench phase-name drift
check (ISSUE 3 acceptance criteria)."""

import json
import time
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu import telemetry as tel
from deeplearning4j_tpu.conf import Activation, InputType
from deeplearning4j_tpu.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.conf.losses import LossMCXENT
from deeplearning4j_tpu.conf.multilayer import NeuralNetConfiguration
from deeplearning4j_tpu.conf.updaters import Sgd
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.telemetry.registry import MetricsRegistry

pytestmark = pytest.mark.telemetry


@pytest.fixture(autouse=True)
def _clean_telemetry():
    tel.disable()
    tel.reset()
    yield
    tel.disable()
    tel.reset()


def _net(seed=1):
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Sgd(0.1))
            .list()
            .layer(DenseLayer(n_out=4, activation=Activation.TANH))
            .layer(OutputLayer(n_out=2, activation=Activation.SOFTMAX,
                               loss_fn=LossMCXENT()))
            .set_input_type(InputType.feed_forward(3))
            .build())
    return MultiLayerNetwork(conf).init()


def _ds(n=8, seed=0):
    rng = np.random.default_rng(seed)
    return DataSet(rng.normal(size=(n, 3)).astype(np.float32),
                   np.eye(2, dtype=np.float32)[rng.integers(0, 2, n)])


# --------------------------------------------------------------------------
# spans
# --------------------------------------------------------------------------

def test_disabled_mode_zero_allocation_fast_path():
    assert not tel.enabled()
    # one shared no-op singleton, nothing recorded
    assert tel.span("a") is tel.span("b")
    with tel.span("ingest"):
        pass
    assert tel.events() == []
    assert tel.phase_stats() == {}


def test_span_nesting_records_depth_and_parent():
    tel.enable()
    with tel.span("outer"):
        with tel.span("inner"):
            time.sleep(0.001)
    evts = tel.events()
    by_name = {e["name"]: e for e in evts}
    assert by_name["outer"]["depth"] == 0
    assert by_name["outer"]["parent"] is None
    assert by_name["inner"]["depth"] == 1
    assert by_name["inner"]["parent"] == "outer"
    # inner closes first and is contained in outer
    assert by_name["inner"]["duration_ns"] <= by_name["outer"]["duration_ns"]


def test_span_aggregation_math():
    tel.enable()
    # synthesize spans with known durations by direct ring writes
    for ms in (1, 2, 3, 4, 100):
        s = tel.spans.Span("phase")
        s.t0 = 0
        s.t1 = ms * 1_000_000
        tel.spans._ring.append((s.name, s.t0, s.t1, 0, None, 0, None))
    st = tel.phase_stats()["phase"]
    assert st["count"] == 5
    assert st["total_ms"] == pytest.approx(110.0)
    assert st["mean_ms"] == pytest.approx(22.0)
    # nearest-rank percentiles: p50 = ceil(0.5*5)=3rd -> 3ms,
    # p95/p99 = ceil(4.75)/ceil(4.95) = 5th -> 100ms
    assert st["p50_ms"] == pytest.approx(3.0)
    assert st["p95_ms"] == pytest.approx(100.0)
    assert st["p99_ms"] == pytest.approx(100.0)
    assert st["max_ms"] == pytest.approx(100.0)


def test_span_ring_is_bounded():
    tel.enable(ring_size=16)
    for i in range(50):
        with tel.span("s"):
            pass
    assert len(tel.events()) == 16
    tel.enable(ring_size=4096)  # restore default for other tests


def test_chrome_trace_export(tmp_path):
    tel.enable()
    with tel.span("compute") as sp:
        sp.annotate(step=3)
    path = tel.export_chrome_trace(str(tmp_path / "sub" / "trace.json"))
    data = json.load(open(path))
    evts = data["traceEvents"]
    assert evts and evts[0]["ph"] == "X"
    assert evts[0]["name"] == "compute"
    assert evts[0]["args"]["step"] == 3
    assert evts[0]["dur"] >= 0


# --------------------------------------------------------------------------
# registry
# --------------------------------------------------------------------------

def test_registry_snapshot_deterministic():
    def build():
        r = MetricsRegistry()
        r.counter("steps", path="mln").inc(3)
        r.gauge("mem", device="cpu:0").set(1.5)
        h = r.histogram("lat")
        for v in (0.1, 0.2, 0.3):
            h.observe(v)
        return r

    s1 = build().snapshot()
    s2 = build().snapshot()
    assert s1 == s2
    # stable key order (sorted) -> identical serialization
    assert json.dumps(s1) == json.dumps(s2)
    assert s1['steps{path="mln"}'] == 3.0
    assert s1["lat"]["count"] == 3
    assert s1["lat"]["p50"] == pytest.approx(0.2)


def test_registry_type_conflict_raises():
    r = MetricsRegistry()
    r.counter("x")
    with pytest.raises(TypeError):
        r.gauge("x")


def test_registry_collector_best_effort():
    r = MetricsRegistry()

    @r.register_collector
    def bad(reg):
        raise RuntimeError("probe down")

    @r.register_collector
    def good(reg):
        reg.gauge("up").set(1)

    snap = r.snapshot()
    assert snap["up"] == 1.0


def test_prometheus_text_format():
    tel.enable()
    tel.record_step("multilayer", 32)
    tel.record_collective("grad_psum", 4096, 2)
    with tel.span("compute"):
        pass
    text = tel.prometheus_text()
    assert "# TYPE dl4j_training_steps_total counter" in text
    assert 'dl4j_training_steps_total{path="multilayer"} 1' in text
    assert 'dl4j_collective_bytes_total{op="grad_psum"} 4096' in text
    # scrape-time collectors contribute the AOT-cache ratio
    assert "dl4j_aot_cache_hit_ratio" in text
    # span phases render as a summary
    assert 'dl4j_phase_ms{phase="compute",quantile="0.50"}' in text
    assert 'dl4j_phase_ms_count{phase="compute"} 1' in text


# --------------------------------------------------------------------------
# instrumented training paths
# --------------------------------------------------------------------------

def test_training_run_produces_trace_and_metrics(tmp_path):
    """Acceptance (a)+(b): one training run with telemetry enabled yields
    a Chrome trace with ingest/compute/grad_sync spans and a /metrics
    scrape with step histograms, AOT-cache ratio, collective bytes."""
    from deeplearning4j_tpu.parallel.wrapper import (
        ParallelWrapper,
        TrainingMode,
    )

    tel.enable(sync=True)
    net = _net()
    from deeplearning4j_tpu.profiler import ProfilerListener

    net.set_listeners(ProfilerListener(warmup_iterations=1))
    pw = ParallelWrapper(net, workers=2,
                         training_mode=TrainingMode.SHARED_GRADIENTS,
                         gradient_bucket_mb=0.001, prefetch_buffer=0)
    ds = _ds(n=8)
    pw.fit(ds, epochs=4)

    # (a) Chrome trace with all three phases
    path = tel.export_chrome_trace(str(tmp_path / "trace.json"))
    names = {e["name"] for e in json.load(open(path))["traceEvents"]}
    assert set(tel.PHASES) <= names

    # (b) scrape content
    text = tel.prometheus_text()
    assert "dl4j_aot_cache_hit_ratio" in text
    assert 'dl4j_collective_bytes_total{op="grad_psum"}' in text
    assert "dl4j_step_seconds" in text  # ProfilerListener -> registry
    st = tel.phase_stats()
    for phase in tel.PHASES:
        assert st[phase]["count"] >= 4


def test_multilayer_and_graph_record_steps():
    tel.enable()
    net = _net()
    ds = _ds()
    for _ in range(3):
        net.fit_batch(ds)
    snap = tel.REGISTRY.snapshot(run_collectors=False)
    assert snap['dl4j_training_steps_total{path="multilayer"}'] == 3.0
    assert snap['dl4j_training_examples_total{path="multilayer"}'] == 24.0
    st = tel.phase_stats()
    assert st["ingest"]["count"] == 3
    assert st["compute"]["count"] == 3


def test_device_ring_iterator_records_ingest():
    from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
    from deeplearning4j_tpu.datasets.prefetch import DeviceRingIterator

    tel.enable()
    batches = [_ds(n=4, seed=i) for i in range(4)]
    it = DeviceRingIterator(ListDataSetIterator(batches), depth=2)
    assert len(list(it)) == 4
    snap = tel.REGISTRY.snapshot(run_collectors=False)
    assert snap["dl4j_ingest_batches_total"] == 4.0
    assert snap["dl4j_ingest_bytes_total"] > 0


def test_metrics_endpoint_on_ui_server():
    from deeplearning4j_tpu.ui.server import UIServer

    tel.enable()
    tel.record_step("multilayer", 16)
    ui = UIServer()
    port = ui.start(port=0)
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
            assert r.headers["Content-Type"].startswith("text/plain")
            body = r.read().decode()
        assert "# TYPE dl4j_training_steps_total counter" in body
        assert "dl4j_aot_cache_hit_ratio" in body
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics.json", timeout=10) as r:
            rec = json.loads(r.read())
        assert "telemetry" in rec and "phases" in rec
        assert ('dl4j_training_steps_total{path="multilayer"}'
                in rec["telemetry"])
    finally:
        ui.stop()


def test_telemetry_listener_bridges_into_storage():
    from deeplearning4j_tpu.ui.stats import InMemoryStatsStorage

    tel.enable()
    storage = InMemoryStatsStorage()
    net = _net()
    net.set_listeners(tel.TelemetryListener(storage, frequency=1,
                                            session_id="t"))
    net.fit(_ds(), epochs=2)
    recs = storage.records()
    assert recs and recs[0]["session"] == "t"
    assert "telemetry" in recs[0] and "phases" in recs[0]


def test_dump_jsonl_round_trip(tmp_path):
    tel.enable()
    tel.record_step("graph", 8)
    p = str(tmp_path / "round.jsonl")
    tel.dump_jsonl(p, extra={"round": "r07"})
    tel.dump_jsonl(p)
    lines = [json.loads(ln) for ln in open(p)]
    assert len(lines) == 2
    assert lines[0]["round"] == "r07"
    assert 'dl4j_training_steps_total{path="graph"}' in lines[0]["telemetry"]


def test_telemetry_overhead_bound():
    """Acceptance (c): per-step telemetry cost (3 spans + step counters,
    async mode — the instrumentation every training path adds) is <2% of
    step time on idle hardware (~9µs vs ~300µs even for a toy CPU net;
    ~0.4% of the 2.4ms ResNet-50 TPU step). Asserted with a GENEROUS
    bound (<25%) so a loaded 2-core CI box cannot flake: the per-step
    cost is measured over 2000 reps (stable), the step time as a min of
    several timed runs, instead of differencing two noisy full-loop
    timings whose variance exceeds the effect."""
    net = _net()
    ds = _ds(n=16)
    net.fit_batch(ds)  # compile outside the timed region

    def steps_per_sec(n=40):
        t0 = time.perf_counter()
        for _ in range(n):
            net.fit_batch(ds)
        return (time.perf_counter() - t0) / n

    step_s = min(steps_per_sec() for _ in range(3))

    tel.enable()  # async mode: no host sync added

    def one_step_instrumentation():
        with tel.span(tel.PHASE_INGEST):
            pass
        with tel.span(tel.PHASE_COMPUTE) as sp:
            sp.set_result(None)
        with tel.span(tel.PHASE_GRAD_SYNC) as sp:
            sp.set_result(None)
        tel.record_step("multilayer", 16)

    reps = 2000
    costs = []
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(reps):
            one_step_instrumentation()
        costs.append((time.perf_counter() - t0) / reps)
    tel.disable()
    overhead = min(costs) / step_s
    assert overhead < 0.25, (min(costs), step_s, overhead)


# --------------------------------------------------------------------------
# bench <-> framework phase-name drift check
# --------------------------------------------------------------------------

def test_bench_phase_keys_match_telemetry_phases():
    import bench_resnet_profile as brp

    # the bench imports telemetry.PHASES and derives its --phases row
    # keys from them, so both sides report the same phase vocabulary
    assert set(brp.PHASE_ROWS) == set(tel.PHASES)
    assert (brp.PHASE_INGEST, brp.PHASE_COMPUTE, brp.PHASE_GRAD_SYNC,
            brp.PHASE_HOST_GAP) == tel.PHASES
    for phase, keys in brp.PHASE_ROWS.items():
        assert keys, f"phase {phase} has no bench rows"
        if phase != tel.PHASE_COMPUTE:  # compute rows are the step probes
            for k in keys:
                assert k == phase or k.startswith(phase + "_"), (phase, k)


# --------------------------------------------------------------------------
# satellites: profiler round-trip, FileStatsStorage, PerformanceListener
# --------------------------------------------------------------------------

def test_profiler_trace_round_trip(tmp_path):
    import glob

    import jax.numpy as jnp

    from deeplearning4j_tpu.profiler import OpProfiler

    prof = OpProfiler.get_instance()
    d = str(tmp_path / "xprof" / "run1")
    with prof.trace(d):
        jnp.sum(jnp.ones((8, 8))).block_until_ready()
    assert glob.glob(d + "/**/*", recursive=True), "trace dir empty"
    # double stop is a no-op
    assert prof.stop_trace() is None
    assert prof.stop_trace() is None
    # plain start/stop returns the dir; a third stop is again a no-op
    d2 = str(tmp_path / "xprof" / "run2")
    prof.start_trace(d2)
    assert prof.stop_trace() == d2
    assert prof.stop_trace() is None


def test_profiler_listener_routes_into_registry():
    from deeplearning4j_tpu.profiler import ProfilerListener

    tel.enable()
    pl = ProfilerListener(warmup_iterations=0)
    for i in range(4):
        pl.iteration_done(None, i, 0, 0.0)
        time.sleep(0.001)
    snap = tel.REGISTRY.snapshot(run_collectors=False)
    h = snap['dl4j_step_seconds{path="profiler"}']
    assert h["count"] == 3  # deltas between 4 iterations
    assert h["sum"] > 0


def test_file_stats_storage_skips_corrupt_lines(tmp_path):
    from deeplearning4j_tpu.ui.stats import FileStatsStorage

    p = str(tmp_path / "stats.jsonl")
    with open(p, "w") as f:
        f.write(json.dumps({"iteration": 0, "score": 1.0}) + "\n")
        f.write('{"iteration": 1, "score"')  # truncated mid-write
        f.write("\n\n")
        f.write("[1, 2, 3]\n")  # valid JSON, not a record
        f.write(json.dumps({"iteration": 2, "score": 0.5}) + "\n")
    st = FileStatsStorage(p)
    recs = st.records()
    assert [r["iteration"] for r in recs] == [0, 2]
    assert st.corrupt_lines == 2
    # storage stays appendable after a damaged load
    st.put({"iteration": 3})
    assert FileStatsStorage(p).records()[-1] == {"iteration": 3}


def test_performance_listener_refit_and_batches_per_sec():
    import io

    from deeplearning4j_tpu.optimize.listeners import PerformanceListener

    class _M:
        last_batch_size = 10

    out = io.StringIO()
    pl = PerformanceListener(frequency=1, stream=out)
    m = _M()
    # first fit: two quick iterations -> high rate
    pl.iteration_done(m, 0, 0, 0.0)
    time.sleep(0.01)
    pl.iteration_done(m, 1, 0, 0.0)
    first_rate = pl.last_examples_per_sec
    assert first_rate is not None
    # refit after an idle gap: on_epoch_start re-primes the window, so
    # the stale timestamp must NOT depress the first post-refit rate
    time.sleep(0.25)
    pl.on_epoch_start(m, 1)
    pl.iteration_done(m, 2, 1, 0.0)  # primes only — no rate over the gap
    rate_after_prime = pl.last_examples_per_sec
    assert rate_after_prime == first_rate  # unchanged: no stale report
    time.sleep(0.01)
    pl.iteration_done(m, 3, 1, 0.0)
    assert pl.last_examples_per_sec == pytest.approx(
        pl.last_batches_per_sec * 10)
    # the post-refit window excludes the 0.25s gap -> rate stays high
    assert pl.last_batches_per_sec > 1.0 / 0.2
    assert "batches/sec" in out.getvalue()
