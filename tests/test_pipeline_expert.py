"""Pipeline parallelism (GPipe over ppermute) and expert parallelism
(MoE over all_to_all) — round 3. Both are beyond the reference's
parity surface (SURVEY.md §2.3 lists PP and EP absent upstream); the
oracle for each is the same math with the parallel dimension collapsed:
serial stage application for the pipeline, a one-device expert mesh for
the MoE layer."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from deeplearning4j_tpu.parallel.expert import (
    EXPERT_AXIS,
    moe_init,
    moe_spmd_fn,
    moe_train_step,
    shard_moe_params,
)
from deeplearning4j_tpu.parallel.pipeline import (
    STAGE_AXIS,
    pipeline_spmd_fn,
    pipeline_train_step,
    serial_reference,
    stack_stage_params,
)

D = 16


def _stage_fn(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def _stage_params(key, n_stages):
    ks = jax.random.split(key, n_stages)
    return [{"w": 0.5 * jax.random.normal(k, (D, D)),
             "b": jnp.zeros((D,))} for k in ks]


def _stage_mesh(n):
    return Mesh(np.array(jax.devices()[:n]), (STAGE_AXIS,))


def _expert_mesh(n):
    return Mesh(np.array(jax.devices()[:n]), (EXPERT_AXIS,))


# --------------------------------------------------------------------------
# pipeline
# --------------------------------------------------------------------------
@pytest.mark.parametrize("n_stages,n_micro", [(4, 8), (2, 3), (8, 4)])
def test_pipeline_forward_matches_serial(n_stages, n_micro):
    mesh = _stage_mesh(n_stages)
    per_stage = _stage_params(jax.random.PRNGKey(0), n_stages)
    stacked = stack_stage_params(per_stage, mesh)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(n_micro, 4, D)).astype(np.float32))

    fn = pipeline_spmd_fn(_stage_fn, n_stages, n_micro, mesh)
    got = np.asarray(fn(stacked, x))
    want = np.stack([np.asarray(serial_reference(_stage_fn, per_stage,
                                                 x[m]))
                     for m in range(n_micro)])
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_pipeline_gradients_match_serial():
    """jax.grad of the pipelined forward == grads of the serial stack
    (the reverse pipeline schedule is derived, not hand-written)."""
    n_stages, n_micro = 4, 6
    mesh = _stage_mesh(n_stages)
    per_stage = _stage_params(jax.random.PRNGKey(1), n_stages)
    stacked = stack_stage_params(per_stage, mesh)
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(n_micro, 4, D)).astype(np.float32))
    y = jnp.asarray(rng.normal(size=(n_micro, 4, D)).astype(np.float32))

    def loss_fn(outs, tgt):
        return jnp.mean((outs - tgt) ** 2)

    step = pipeline_train_step(_stage_fn, loss_fn, n_stages, n_micro,
                               mesh, lr=0.1)
    new_params, loss = step(stacked, x, y)

    # serial oracle: one SGD step on the equivalent unrolled network
    def serial_loss(flat):
        outs = jnp.stack([serial_reference(_stage_fn, flat, x[m])
                          for m in range(n_micro)])
        return loss_fn(outs, y)

    sgrads = jax.grad(serial_loss)(per_stage)
    sloss = float(serial_loss(per_stage))
    assert np.isclose(float(loss), sloss, rtol=1e-5, atol=1e-6)
    for s in range(n_stages):
        for k in ("w", "b"):
            want = np.asarray(per_stage[s][k] - 0.1 * sgrads[s][k])
            got = np.asarray(new_params[k][s])
            np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5,
                                       err_msg=f"stage {s} {k}")


def test_pipeline_trains():
    n_stages, n_micro = 4, 8
    mesh = _stage_mesh(n_stages)
    stacked = stack_stage_params(_stage_params(jax.random.PRNGKey(2),
                                               n_stages), mesh)
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(n_micro, 4, D)).astype(np.float32))
    y = jnp.asarray(np.tanh(rng.normal(size=(n_micro, 4, D)))
                    .astype(np.float32))
    step = pipeline_train_step(_stage_fn, lambda o, t: jnp.mean((o - t) ** 2),
                               n_stages, n_micro, mesh, lr=0.2)
    stacked, first = step(stacked, x, y)
    for _ in range(15):
        stacked, loss = step(stacked, x, y)
    assert float(loss) < float(first)


# --------------------------------------------------------------------------
# expert parallel (MoE)
# --------------------------------------------------------------------------
def test_moe_sharded_matches_single_device():
    """4-way expert-parallel layer == the same layer on a 1-device
    expert mesh (capacity big enough that nothing drops, so per-shard
    capacity queues cannot diverge)."""
    E, DH, T, CAP = 4, 32, 32, 32
    params = moe_init(jax.random.PRNGKey(0), D, DH, E)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(T, D)).astype(np.float32))

    mesh1 = _expert_mesh(1)
    f1 = moe_spmd_fn(E, CAP, mesh1)
    y1, aux1 = f1(shard_moe_params(params, mesh1), x)

    mesh4 = _expert_mesh(4)
    f4 = moe_spmd_fn(E, CAP, mesh4)
    y4, aux4 = f4(shard_moe_params(params, mesh4), x)

    np.testing.assert_allclose(np.asarray(y4), np.asarray(y1),
                               rtol=1e-4, atol=1e-5)
    # the aux load-balance loss uses PER-SHARD token statistics (as
    # GShard does) — pmean of per-shard products is a documented
    # approximation of the global product, not an identity; require the
    # same ballpark, exact only for the outputs above
    assert np.isfinite(float(aux4))
    assert abs(float(aux4) - float(aux1)) < 0.3 * max(float(aux1), 1.0)


def test_moe_capacity_drops_pass_residual():
    """Tokens beyond an expert's capacity bypass the expert: output ==
    input (the residual) for dropped tokens."""
    E, DH, T = 2, 8, 6
    params = moe_init(jax.random.PRNGKey(1), D, DH, E)
    # force every token to expert 0
    params["router"] = params["router"].at[:, 0].set(5.0).at[:, 1].set(-5.0)
    mesh = _expert_mesh(1)
    f = moe_spmd_fn(E, capacity=2, mesh=mesh)
    # all-positive tokens: with no router bias, logits = x @ router, so
    # positive token sums guarantee every token routes to expert 0
    x = jnp.asarray(np.abs(np.random.default_rng(1).normal(size=(T, D)))
                    .astype(np.float32))
    y, _ = f(shard_moe_params(params, mesh), x)
    # first 2 tokens routed (output != input), remaining 4 dropped
    changed = np.abs(np.asarray(y) - np.asarray(x)).max(axis=1)
    assert (changed[:2] > 1e-4).all()
    np.testing.assert_allclose(np.asarray(y)[2:], np.asarray(x)[2:],
                               atol=1e-6)


def test_moe_train_step_gradients_match_single_device():
    """One moe_train_step on the 4-way expert mesh == the identical step
    on a 1-device expert mesh, elementwise. Pins the router-gradient
    reduction (round-3 advisor follow-up): differentiating the pmean'd
    loss inside the shard_map body already cross-shard-accumulates the
    router cotangent, so g["router"] arrives as the full logical
    gradient replicated on every shard — the correct reduction is the
    identity-on-replicas pmean moe_train_step uses (a psum would
    over-scale by n_shards when vma tracking is off). aux_weight=0
    because the load-balance aux uses per-shard token statistics that
    legitimately differ between mesh sizes; capacity covers every token
    so the queues cannot diverge either."""
    E, DH, T, CAP = 4, 32, 32, 32
    params = moe_init(jax.random.PRNGKey(3), D, DH, E)
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(T, D)).astype(np.float32))
    tgt = jnp.asarray(np.tanh(rng.normal(size=(T, D))).astype(np.float32))

    results = {}
    for n in (1, 4):
        mesh = _expert_mesh(n)
        step = moe_train_step(E, CAP, mesh, lr=0.1, aux_weight=0.0)
        # fresh copy per mesh: the step donates its params, and on a
        # 1-device mesh device_put aliases rather than copies
        fresh = jax.tree.map(jnp.array, params)
        new, loss = step(shard_moe_params(fresh, mesh), x, tgt)
        results[n] = (jax.device_get(new), float(loss))

    assert np.isclose(results[4][1], results[1][1], rtol=1e-5, atol=1e-6)
    for k in ("router", "w1", "w2"):
        np.testing.assert_allclose(results[4][0][k], results[1][0][k],
                                   rtol=1e-4, atol=1e-5, err_msg=k)


def test_moe_trains_and_balances():
    E, DH, T, CAP = 4, 32, 64, 32
    params = moe_init(jax.random.PRNGKey(2), D, DH, E)
    mesh = _expert_mesh(4)
    sp = shard_moe_params(params, mesh)
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(T, D)).astype(np.float32))
    tgt = jnp.asarray(np.tanh(rng.normal(size=(T, D))).astype(np.float32))
    step = moe_train_step(E, CAP, mesh, lr=0.1)
    sp, first = step(sp, x, tgt)
    for _ in range(20):
        sp, loss = step(sp, x, tgt)
    assert np.isfinite(float(loss))
    assert float(loss) < float(first)
    # expert weights stayed sharded, router replicated
    assert EXPERT_AXIS in str(sp["w1"].sharding.spec)
