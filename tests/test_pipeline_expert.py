"""Pipeline parallelism (GPipe over ppermute) and expert parallelism
(MoE over all_to_all) — round 3. Both are beyond the reference's
parity surface (SURVEY.md §2.3 lists PP and EP absent upstream); the
oracle for each is the same math with the parallel dimension collapsed:
serial stage application for the pipeline, a one-device expert mesh for
the MoE layer."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from deeplearning4j_tpu.parallel.expert import (
    EXPERT_AXIS,
    moe_init,
    moe_spmd_fn,
    moe_train_step,
    shard_moe_params,
)
from deeplearning4j_tpu.parallel.pipeline import (
    STAGE_AXIS,
    pipeline_spmd_fn,
    pipeline_train_step,
    serial_reference,
    stack_stage_params,
)

D = 16


def _stage_fn(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def _stage_params(key, n_stages):
    ks = jax.random.split(key, n_stages)
    return [{"w": 0.5 * jax.random.normal(k, (D, D)),
             "b": jnp.zeros((D,))} for k in ks]


def _stage_mesh(n):
    return Mesh(np.array(jax.devices()[:n]), (STAGE_AXIS,))


def _expert_mesh(n):
    return Mesh(np.array(jax.devices()[:n]), (EXPERT_AXIS,))


# --------------------------------------------------------------------------
# pipeline
# --------------------------------------------------------------------------
@pytest.mark.parametrize("n_stages,n_micro", [(4, 8), (2, 3), (8, 4)])
def test_pipeline_forward_matches_serial(n_stages, n_micro):
    mesh = _stage_mesh(n_stages)
    per_stage = _stage_params(jax.random.PRNGKey(0), n_stages)
    stacked = stack_stage_params(per_stage, mesh)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(n_micro, 4, D)).astype(np.float32))

    fn = pipeline_spmd_fn(_stage_fn, n_stages, n_micro, mesh)
    got = np.asarray(fn(stacked, x))
    want = np.stack([np.asarray(serial_reference(_stage_fn, per_stage,
                                                 x[m]))
                     for m in range(n_micro)])
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_pipeline_gradients_match_serial():
    """jax.grad of the pipelined forward == grads of the serial stack
    (the reverse pipeline schedule is derived, not hand-written)."""
    n_stages, n_micro = 4, 6
    mesh = _stage_mesh(n_stages)
    per_stage = _stage_params(jax.random.PRNGKey(1), n_stages)
    stacked = stack_stage_params(per_stage, mesh)
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(n_micro, 4, D)).astype(np.float32))
    y = jnp.asarray(rng.normal(size=(n_micro, 4, D)).astype(np.float32))

    def loss_fn(outs, tgt):
        return jnp.mean((outs - tgt) ** 2)

    step = pipeline_train_step(_stage_fn, loss_fn, n_stages, n_micro,
                               mesh, lr=0.1)
    new_params, loss = step(stacked, x, y)

    # serial oracle: one SGD step on the equivalent unrolled network
    def serial_loss(flat):
        outs = jnp.stack([serial_reference(_stage_fn, flat, x[m])
                          for m in range(n_micro)])
        return loss_fn(outs, y)

    sgrads = jax.grad(serial_loss)(per_stage)
    sloss = float(serial_loss(per_stage))
    assert np.isclose(float(loss), sloss, rtol=1e-5, atol=1e-6)
    for s in range(n_stages):
        for k in ("w", "b"):
            want = np.asarray(per_stage[s][k] - 0.1 * sgrads[s][k])
            got = np.asarray(new_params[k][s])
            np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5,
                                       err_msg=f"stage {s} {k}")


def test_pipeline_trains():
    n_stages, n_micro = 4, 8
    mesh = _stage_mesh(n_stages)
    stacked = stack_stage_params(_stage_params(jax.random.PRNGKey(2),
                                               n_stages), mesh)
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(n_micro, 4, D)).astype(np.float32))
    y = jnp.asarray(np.tanh(rng.normal(size=(n_micro, 4, D)))
                    .astype(np.float32))
    step = pipeline_train_step(_stage_fn, lambda o, t: jnp.mean((o - t) ** 2),
                               n_stages, n_micro, mesh, lr=0.2)
    stacked, first = step(stacked, x, y)
    for _ in range(15):
        stacked, loss = step(stacked, x, y)
    assert float(loss) < float(first)


# --------------------------------------------------------------------------
# expert parallel (MoE)
# --------------------------------------------------------------------------
def test_moe_sharded_matches_single_device():
    """4-way expert-parallel layer == the same layer on a 1-device
    expert mesh (capacity big enough that nothing drops, so per-shard
    capacity queues cannot diverge)."""
    E, DH, T, CAP = 4, 32, 32, 32
    params = moe_init(jax.random.PRNGKey(0), D, DH, E)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(T, D)).astype(np.float32))

    mesh1 = _expert_mesh(1)
    f1 = moe_spmd_fn(E, CAP, mesh1)
    y1, aux1 = f1(shard_moe_params(params, mesh1), x)

    mesh4 = _expert_mesh(4)
    f4 = moe_spmd_fn(E, CAP, mesh4)
    y4, aux4 = f4(shard_moe_params(params, mesh4), x)

    np.testing.assert_allclose(np.asarray(y4), np.asarray(y1),
                               rtol=1e-4, atol=1e-5)
    # the aux load-balance loss uses PER-SHARD token statistics (as
    # GShard does) — pmean of per-shard products is a documented
    # approximation of the global product, not an identity; require the
    # same ballpark, exact only for the outputs above
    assert np.isfinite(float(aux4))
    assert abs(float(aux4) - float(aux1)) < 0.3 * max(float(aux1), 1.0)


def test_moe_capacity_drops_pass_residual():
    """Tokens beyond an expert's capacity bypass the expert: output ==
    input (the residual) for dropped tokens."""
    E, DH, T = 2, 8, 6
    params = moe_init(jax.random.PRNGKey(1), D, DH, E)
    # force every token to expert 0
    params["router"] = params["router"].at[:, 0].set(5.0).at[:, 1].set(-5.0)
    mesh = _expert_mesh(1)
    f = moe_spmd_fn(E, capacity=2, mesh=mesh)
    # all-positive tokens: with no router bias, logits = x @ router, so
    # positive token sums guarantee every token routes to expert 0
    x = jnp.asarray(np.abs(np.random.default_rng(1).normal(size=(T, D)))
                    .astype(np.float32))
    y, _ = f(shard_moe_params(params, mesh), x)
    # first 2 tokens routed (output != input), remaining 4 dropped
    changed = np.abs(np.asarray(y) - np.asarray(x)).max(axis=1)
    assert (changed[:2] > 1e-4).all()
    np.testing.assert_allclose(np.asarray(y)[2:], np.asarray(x)[2:],
                               atol=1e-6)


def test_moe_train_step_gradients_match_single_device():
    """One moe_train_step on the 4-way expert mesh == the identical step
    on a 1-device expert mesh, elementwise. Pins the router-gradient
    reduction (round-3 advisor follow-up): differentiating the pmean'd
    loss inside the shard_map body already cross-shard-accumulates the
    router cotangent, so g["router"] arrives as the full logical
    gradient replicated on every shard — the correct reduction is the
    identity-on-replicas pmean moe_train_step uses (a psum would
    over-scale by n_shards when vma tracking is off). aux_weight=0
    because the load-balance aux uses per-shard token statistics that
    legitimately differ between mesh sizes; capacity covers every token
    so the queues cannot diverge either."""
    E, DH, T, CAP = 4, 32, 32, 32
    params = moe_init(jax.random.PRNGKey(3), D, DH, E)
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(T, D)).astype(np.float32))
    tgt = jnp.asarray(np.tanh(rng.normal(size=(T, D))).astype(np.float32))

    results = {}
    for n in (1, 4):
        mesh = _expert_mesh(n)
        step = moe_train_step(E, CAP, mesh, lr=0.1, aux_weight=0.0)
        # fresh copy per mesh: the step donates its params, and on a
        # 1-device mesh device_put aliases rather than copies
        fresh = jax.tree.map(jnp.array, params)
        new, loss = step(shard_moe_params(fresh, mesh), x, tgt)
        results[n] = (jax.device_get(new), float(loss))

    assert np.isclose(results[4][1], results[1][1], rtol=1e-5, atol=1e-6)
    for k in ("router", "w1", "w2"):
        np.testing.assert_allclose(results[4][0][k], results[1][0][k],
                                   rtol=1e-4, atol=1e-5, err_msg=k)


def test_moe_top1_router_gets_task_gradient():
    """Switch-style top-1 keeps the RAW router probability as the
    combine gate (round-4 advisor, medium): with aux_weight=0 the router
    must still receive a nonzero gradient through the task loss. A
    pair-style renormalization would pin the gate at 1.0 and zero this
    gradient exactly."""
    from deeplearning4j_tpu.parallel.expert import moe_apply

    E, DH, T, CAP = 4, 16, 32, 32
    params = moe_init(jax.random.PRNGKey(11), D, DH, E)
    rng = np.random.default_rng(11)
    x = jnp.asarray(rng.normal(size=(T, D)).astype(np.float32))
    tgt = jnp.asarray(rng.normal(size=(T, D)).astype(np.float32))

    def task_loss(p):
        y, _aux = moe_apply(p["router"], p["w1"], p["w2"], x, E, CAP,
                            top_k=1, axis_name=None)
        return jnp.mean((y - tgt) ** 2)  # NO aux term

    g = jax.grad(task_loss)(params)
    assert float(jnp.abs(g["router"]).max()) > 1e-6

    # top-1 combine gate is the raw softmax prob: with identical experts
    # the MoE output must equal x + p_top1 * ffn(x), not x + ffn(x)
    w1 = jnp.broadcast_to(params["w1"][:1], params["w1"].shape)
    w2 = jnp.broadcast_to(params["w2"][:1], params["w2"].shape)
    y, _ = moe_apply(params["router"], w1, w2, x, E, CAP, top_k=1,
                     axis_name=None)
    probs = jax.nn.softmax(x @ params["router"], axis=-1)
    p1 = jnp.max(probs, axis=-1, keepdims=True)
    ffn = jnp.maximum(x @ params["w1"][0], 0.0) @ params["w2"][0]
    np.testing.assert_allclose(np.asarray(y), np.asarray(x + p1 * ffn),
                               rtol=1e-4, atol=1e-5)


def test_moe_trains_and_balances():
    E, DH, T, CAP = 4, 32, 64, 32
    params = moe_init(jax.random.PRNGKey(2), D, DH, E)
    mesh = _expert_mesh(4)
    sp = shard_moe_params(params, mesh)
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(T, D)).astype(np.float32))
    tgt = jnp.asarray(np.tanh(rng.normal(size=(T, D))).astype(np.float32))
    step = moe_train_step(E, CAP, mesh, lr=0.1)
    sp, first = step(sp, x, tgt)
    for _ in range(20):
        sp, loss = step(sp, x, tgt)
    assert np.isfinite(float(loss))
    assert float(loss) < float(first)
    # expert weights stayed sharded, router replicated
    assert EXPERT_AXIS in str(sp["w1"].sharding.spec)


def test_moe_top2_sharded_matches_single_device():
    """Round-4 top-2 routing: 4-way expert-parallel == 1-device mesh
    (ample capacity), and top-2 differs from top-1 (the second expert
    actually contributes)."""
    E, DH, T, CAP = 4, 32, 32, 64
    params = moe_init(jax.random.PRNGKey(3), D, DH, E)
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(T, D)).astype(np.float32))

    mesh1 = _expert_mesh(1)
    y1, _ = moe_spmd_fn(E, CAP, mesh1, top_k=2)(
        shard_moe_params(params, mesh1), x)
    mesh4 = _expert_mesh(4)
    y4, _ = moe_spmd_fn(E, CAP, mesh4, top_k=2)(
        shard_moe_params(params, mesh4), x)
    np.testing.assert_allclose(np.asarray(y4), np.asarray(y1),
                               rtol=1e-4, atol=1e-5)

    ytop1, _ = moe_spmd_fn(E, CAP, mesh1, top_k=1)(
        shard_moe_params(params, mesh1), x)
    assert float(np.abs(np.asarray(ytop1) - np.asarray(y1)).max()) > 1e-4


def test_moe_top2_gates_renormalize():
    """With capacity ample and both experts identical-weighted, the
    top-2 combine must apply renormalized gates summing to 1: forcing
    w1/w2 of all experts equal makes the MoE output independent of the
    routing — a direct check of the combine-weight normalization."""
    E, DH, T = 4, 16, 8
    params = moe_init(jax.random.PRNGKey(5), D, DH, E)
    params["w1"] = jnp.broadcast_to(params["w1"][:1], params["w1"].shape)
    params["w2"] = jnp.broadcast_to(params["w2"][:1], params["w2"].shape)
    rng = np.random.default_rng(5)
    x = jnp.asarray(rng.normal(size=(T, D)).astype(np.float32))
    mesh = _expert_mesh(1)
    y2, _ = moe_spmd_fn(E, capacity=T, mesh=mesh, top_k=2)(
        shard_moe_params(params, mesh), x)
    # identical experts + gates summing to 1 -> same as a plain FFN pass
    h = np.maximum(np.asarray(x) @ np.asarray(params["w1"][0]), 0.0)
    want = np.asarray(x) + h @ np.asarray(params["w2"][0])
    np.testing.assert_allclose(np.asarray(y2), want, rtol=1e-4, atol=1e-5)


def test_moe_top2_train_step_gradients_match_single_device():
    """One top-2 train step on the 4-shard mesh == the 1-shard mesh,
    elementwise (router AND expert weights) — the top-2 sibling of the
    round-3 router-gradient pin."""
    E, DH, T, CAP = 4, 16, 32, 64
    params = moe_init(jax.random.PRNGKey(7), D, DH, E)
    rng = np.random.default_rng(7)
    x = jnp.asarray(rng.normal(size=(T, D)).astype(np.float32))
    tgt = jnp.asarray(np.tanh(rng.normal(size=(T, D))).astype(np.float32))

    outs = {}
    for n in (1, 4):
        mesh = _expert_mesh(n)
        # aux_weight=0: the aux loss uses PER-SHARD statistics by
        # design (GShard), so exact cross-mesh equality holds only for
        # the data path
        step = moe_train_step(E, CAP, mesh, lr=0.1, top_k=2,
                              aux_weight=0.0)
        p, loss = step(shard_moe_params(
            jax.tree_util.tree_map(jnp.copy, params), mesh), x, tgt)
        outs[n] = (jax.tree_util.tree_map(np.asarray, dict(p)),
                   float(loss))
    np.testing.assert_allclose(outs[4][1], outs[1][1], rtol=1e-5)
    for k in ("router", "w1", "w2"):
        np.testing.assert_allclose(outs[4][0][k], outs[1][0][k],
                                   rtol=1e-4, atol=1e-6)


# --------------------------------------------------------------------------
# round 4: heterogeneous stages + PipelineParallelWrapper
# --------------------------------------------------------------------------
def _hetero_setup(rng, dims):
    fns, ps = [], []
    for s in range(len(dims) - 1):
        w = jnp.asarray(rng.normal(size=(dims[s], dims[s + 1]))
                        .astype(np.float32) * 0.5)
        b = jnp.asarray(rng.normal(size=(dims[s + 1],))
                        .astype(np.float32) * 0.1)
        ps.append({"w": w, "b": b})
        fns.append(lambda p, x: jnp.tanh(x @ p["w"] + p["b"]))
    return fns, ps


def test_hetero_pipeline_matches_serial():
    """Per-stage heterogeneous widths (the round-3 'equal signature'
    restriction, lifted): forward AND one SGD step match the serial
    oracle elementwise."""
    from deeplearning4j_tpu.parallel.pipeline import (
        HeteroPipeline,
        hetero_serial_reference,
    )

    mesh = _stage_mesh(4)
    rng = np.random.default_rng(0)
    dims = [8, 12, 6, 10, 7]
    fns, ps = _hetero_setup(rng, dims)
    M, mb = 3, 5
    x_micro = jnp.asarray(rng.normal(size=(M, mb, 8)).astype(np.float32))
    pipe = HeteroPipeline(fns, ps,
                          jax.ShapeDtypeStruct((mb, 8), jnp.float32),
                          mesh, M)
    stacked = pipe.stack_params(ps)
    out = pipe.spmd_fn()(stacked, x_micro)
    ref = np.stack([np.asarray(hetero_serial_reference(fns, ps, x_micro[m]))
                    for m in range(M)])
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5, atol=1e-6)

    tgt = jnp.asarray(np.tanh(rng.normal(size=(M, mb, 7)))
                      .astype(np.float32))
    step = pipe.train_step(lambda o, t: jnp.mean((o - t) ** 2), lr=0.1)

    def serial_loss(ps_list):
        outs = jnp.stack([hetero_serial_reference(fns, ps_list, x_micro[m])
                          for m in range(M)])
        return jnp.mean((outs - tgt) ** 2)

    g_ref = jax.grad(serial_loss)(ps)
    st1, _ = step(stacked, x_micro, tgt)
    ps1 = pipe.unstack_params(np.asarray(st1))
    for s in range(4):
        for k in ("w", "b"):
            want = np.asarray(ps[s][k]) - 0.1 * np.asarray(g_ref[s][k])
            np.testing.assert_allclose(np.asarray(ps1[s][k]), want,
                                       rtol=1e-4, atol=1e-6,
                                       err_msg=f"stage {s} {k}")


def _mlp_net(seed=5, lr=0.1, updater=None):
    from deeplearning4j_tpu.conf import Activation, InputType, WeightInit
    from deeplearning4j_tpu.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.conf.losses import LossMCXENT
    from deeplearning4j_tpu.conf.multilayer import NeuralNetConfiguration
    from deeplearning4j_tpu.conf.updaters import Sgd
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (NeuralNetConfiguration.builder()
            .seed(seed).updater(updater or Sgd(learning_rate=lr))
            .weight_init(WeightInit.XAVIER)
            .list()
            .layer(DenseLayer(n_out=24, activation=Activation.TANH))
            .layer(DenseLayer(n_out=10, activation=Activation.TANH))
            .layer(DenseLayer(n_out=18, activation=Activation.TANH))
            .layer(DenseLayer(n_out=12, activation=Activation.TANH))
            .layer(OutputLayer(n_out=3, activation=Activation.SOFTMAX,
                               loss_fn=LossMCXENT()))
            .set_input_type(InputType.feed_forward(16))
            .build())
    return MultiLayerNetwork(conf).init()


def test_pipeline_wrapper_matches_plain_fit():
    """PipelineParallelWrapper (4 stages, conf Sgd) one step == plain
    net.fit_batch elementwise — heterogeneous Dense widths, output head
    replicated, all from the conf DSL."""
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.parallel.pipeline import PipelineParallelWrapper

    rng = np.random.default_rng(2)
    x = rng.normal(size=(8, 16)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 8)]

    ref = _mlp_net()
    p0 = jax.tree_util.tree_map(lambda a: np.asarray(a).copy(),
                                dict(ref.params))
    ref_loss = ref.fit_batch(DataSet(x, y))

    net = _mlp_net()
    net.params = jax.tree_util.tree_map(jnp.asarray, p0)
    pw = PipelineParallelWrapper(net, n_micro=2, mesh=_stage_mesh(4))
    loss = pw.fit_batch(DataSet(x, y))
    np.testing.assert_allclose(loss, ref_loss, rtol=1e-5)
    pw.write_back()
    for k in ref.params:
        for pk in ref.params[k]:
            np.testing.assert_allclose(
                np.asarray(net.params[k][pk]),
                np.asarray(ref.params[k][pk]), rtol=1e-4, atol=1e-6,
                err_msg=f"{k}/{pk}")


def test_pipeline_wrapper_stage_times_data():
    """Stage axis composing with the data axis on ONE mesh (2 stages x 4
    data shards over the 8 CPU devices): still matches the plain single-
    device step elementwise, with Adam."""
    from deeplearning4j_tpu.conf.updaters import Adam
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.parallel import mesh as mesh_mod
    from deeplearning4j_tpu.parallel.pipeline import PipelineParallelWrapper
    from jax.sharding import Mesh

    devs = np.array(jax.devices()[:8]).reshape(2, 4)
    mesh = Mesh(devs, (STAGE_AXIS, mesh_mod.DATA_AXIS))

    rng = np.random.default_rng(4)
    x = rng.normal(size=(16, 16)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 16)]

    ref = _mlp_net(updater=__import__(
        "deeplearning4j_tpu.conf.updaters", fromlist=["Adam"]).Adam(
        learning_rate=0.01))
    p0 = jax.tree_util.tree_map(lambda a: np.asarray(a).copy(),
                                dict(ref.params))
    ref_loss = ref.fit_batch(DataSet(x, y))

    net = _mlp_net(updater=Adam(learning_rate=0.01))
    net.params = jax.tree_util.tree_map(jnp.asarray, p0)
    pw = PipelineParallelWrapper(net, n_micro=2, mesh=mesh)
    loss = pw.fit_batch(DataSet(x, y))
    np.testing.assert_allclose(loss, ref_loss, rtol=1e-5)
    pw.write_back()
    for k in ref.params:
        for pk in ref.params[k]:
            np.testing.assert_allclose(
                np.asarray(net.params[k][pk]),
                np.asarray(ref.params[k][pk]), rtol=1e-3, atol=1e-5,
                err_msg=f"{k}/{pk}")


def test_pipeline_wrapper_refusals():
    """v2's REMAINING refusals (the v1 BN-state refusal is lifted —
    tests/test_pipeline_v2.py trains BN+dropout nets): tBPTT, masked
    DataSets, MoE aux losses, compute_dtype policies, multi-output
    graphs, and non-divisible batches all refuse loudly."""
    import dataclasses

    from deeplearning4j_tpu.conf import Activation, InputType, WeightInit
    from deeplearning4j_tpu.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.conf.layers_moe import MoELayer
    from deeplearning4j_tpu.conf.layers_rnn import LSTM, RnnOutputLayer
    from deeplearning4j_tpu.conf.losses import LossMCXENT
    from deeplearning4j_tpu.conf.multilayer import (
        BackpropType,
        NeuralNetConfiguration,
    )
    from deeplearning4j_tpu.conf.updaters import Sgd
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.parallel.pipeline import PipelineParallelWrapper

    rng = np.random.default_rng(0)

    # tBPTT composes with ParallelWrapper, not the pipeline yet
    rnn_conf = (NeuralNetConfiguration.builder()
                .seed(1).updater(Sgd(learning_rate=0.1))
                .list()
                .layer(LSTM(n_out=8))
                .layer(RnnOutputLayer(n_out=2,
                                      activation=Activation.SOFTMAX,
                                      loss_fn=LossMCXENT()))
                .backprop_type(BackpropType.TRUNCATED_BPTT, fwd=4, back=4)
                .set_input_type(InputType.recurrent(4, timesteps=8))
                .build())
    rnn = MultiLayerNetwork(rnn_conf).init()
    with pytest.raises(ValueError, match="tBPTT"):
        PipelineParallelWrapper(rnn, n_micro=2, mesh=_stage_mesh(2))

    # masked DataSets: the head's score runs unmasked
    net = _mlp_net()
    pw = PipelineParallelWrapper(net, n_micro=2, mesh=_stage_mesh(4))
    x = rng.normal(size=(8, 16)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 8)]
    with pytest.raises(ValueError, match="masked DataSets"):
        pw.fit_batch(DataSet(x, y,
                             labels_mask=np.ones((8,), np.float32)))

    # MoE aux-loss layers (per-micro aux has no serial equivalent yet)
    moe_conf = (NeuralNetConfiguration.builder()
                .seed(1).updater(Sgd(learning_rate=0.1))
                .weight_init(WeightInit.XAVIER)
                .list()
                .layer(MoELayer(n_experts=2, d_hidden=8))
                .layer(RnnOutputLayer(n_out=2,
                                      activation=Activation.SOFTMAX,
                                      loss_fn=LossMCXENT()))
                .set_input_type(InputType.recurrent(8, timesteps=4))
                .build())
    moe_net = MultiLayerNetwork(moe_conf).init()
    with pytest.raises(ValueError, match="auxiliary losses"):
        PipelineParallelWrapper(moe_net, n_micro=2, mesh=_stage_mesh(2))

    # compute_dtype policies (flat stage packing keeps f32 masters)
    mp_net = _mlp_net()
    mp_net = MultiLayerNetwork(
        dataclasses.replace(mp_net.conf, compute_dtype="bfloat16")).init()
    with pytest.raises(ValueError, match="compute_dtype"):
        PipelineParallelWrapper(mp_net, n_micro=2, mesh=_stage_mesh(2))

    # multi-output graphs
    g = (NeuralNetConfiguration.builder()
         .seed(1).updater(Sgd(learning_rate=0.1))
         .weight_init(WeightInit.XAVIER)
         .graph_builder()
         .add_inputs("in")
         .set_input_types(InputType.feed_forward(8)))
    g.add_layer("h", DenseLayer(n_out=8, activation=Activation.TANH), "in")
    g.add_layer("o1", OutputLayer(n_out=2, activation=Activation.SOFTMAX,
                                  loss_fn=LossMCXENT()), "h")
    g.add_layer("o2", OutputLayer(n_out=2, activation=Activation.SOFTMAX,
                                  loss_fn=LossMCXENT()), "h")
    g.set_outputs("o1", "o2")
    multi = ComputationGraph(g.build()).init()
    with pytest.raises(ValueError, match="single-output"):
        PipelineParallelWrapper(multi, n_micro=2, mesh=_stage_mesh(2))

    # non-divisible batches refuse (unchanged from v1)
    pw2 = PipelineParallelWrapper(_mlp_net(), n_micro=3,
                                  mesh=_stage_mesh(4))
    with pytest.raises(ValueError, match="must divide"):
        pw2.fit_batch(DataSet(
            rng.normal(size=(8, 16)).astype(np.float32),
            np.eye(3, dtype=np.float32)[rng.integers(0, 3, 8)]))


def test_pipeline_wrapper_partition_never_empty():
    """Round-4 review regression: heavily-skewed param counts must not
    produce empty trailing stages (devices doing identity work)."""
    from deeplearning4j_tpu.conf import Activation, InputType, WeightInit
    from deeplearning4j_tpu.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.conf.losses import LossMCXENT
    from deeplearning4j_tpu.conf.multilayer import NeuralNetConfiguration
    from deeplearning4j_tpu.conf.updaters import Sgd
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.parallel.pipeline import PipelineParallelWrapper

    conf = (NeuralNetConfiguration.builder()
            .seed(1).updater(Sgd(learning_rate=0.1))
            .weight_init(WeightInit.XAVIER)
            .list()
            .layer(DenseLayer(n_out=4, activation=Activation.TANH))
            .layer(DenseLayer(n_out=4, activation=Activation.TANH))
            .layer(DenseLayer(n_out=256, activation=Activation.TANH))
            .layer(OutputLayer(n_out=2, activation=Activation.SOFTMAX,
                               loss_fn=LossMCXENT()))
            .set_input_type(InputType.feed_forward(4))
            .build())
    net = MultiLayerNetwork(conf).init()
    pw = PipelineParallelWrapper(net, n_micro=2, mesh=_stage_mesh(3),
                                 n_stages=3)
    assert all(pw.stage_layers), pw.stage_layers
    assert [i for idxs in pw.stage_layers for i in idxs] == [0, 1, 2]


def test_pipeline_wrapper_rejects_shrunk_batch():
    """Round-4 review regression: a later batch with a different
    microbatch shape must refuse, not train on phantom zero rows."""
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.parallel.pipeline import PipelineParallelWrapper

    rng = np.random.default_rng(0)
    net = _mlp_net()
    pw = PipelineParallelWrapper(net, n_micro=2, mesh=_stage_mesh(4))
    mk = lambda n: DataSet(
        rng.normal(size=(n, 16)).astype(np.float32),
        np.eye(3, dtype=np.float32)[rng.integers(0, 3, n)])
    pw.fit_batch(mk(8))
    with pytest.raises(ValueError, match="compiled for microbatch"):
        pw.fit_batch(mk(2))
