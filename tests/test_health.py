"""Training-health layer (telemetry.health + telemetry.flightrec):
in-graph guard-vector math (plain jit and shard_map), the four anomaly
policies end-to-end on real networks, flight-recorder bundle schema,
NaN-safe early stopping, atomic checkpoint saves, and the /health
endpoint."""

import json
import os
import zipfile

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from deeplearning4j_tpu.conf import Activation, InputType
from deeplearning4j_tpu.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.conf.losses import LossMCXENT
from deeplearning4j_tpu.conf.multilayer import NeuralNetConfiguration
from deeplearning4j_tpu.conf.updaters import Sgd
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.telemetry import REGISTRY, flightrec, health
from deeplearning4j_tpu.telemetry.health import (
    GUARD_GRAD_NONFINITE,
    GUARD_GRAD_NORM,
    GUARD_HEAD,
    GUARD_LOSS,
    GUARD_LOSS_NONFINITE,
    GUARD_RATIO,
    DivergenceError,
)

pytestmark = pytest.mark.health


@pytest.fixture(autouse=True)
def _clean_health():
    """Every test starts and ends with the health layer off and the
    recorder/metrics empty (the module switches are process-global)."""
    health.disable()
    health.MONITOR.reset()
    flightrec.RECORDER.disable().reset()
    flightrec.RECORDER._conf_digest = None
    REGISTRY.reset()
    yield
    health.disable()
    health.MONITOR.reset()
    flightrec.RECORDER.disable().reset()
    flightrec.RECORDER._conf_digest = None
    REGISTRY.reset()


def tiny_net(seed=7, lr=0.05):
    conf = (NeuralNetConfiguration.builder().seed(seed)
            .updater(Sgd(lr))
            .list()
            .layer(DenseLayer(n_out=8, activation=Activation.RELU))
            .layer(OutputLayer(n_out=3, activation=Activation.SOFTMAX,
                               loss_fn=LossMCXENT()))
            .set_input_type(InputType.feed_forward(4))
            .build())
    return MultiLayerNetwork(conf).init()


def data(rng, n=16, bad=False):
    x = rng.normal(size=(n, 4)).astype(np.float32)
    if bad:
        x[0, 0] = np.nan
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, n)]
    return DataSet(x, y)


def host_params(tree):
    return jax.tree_util.tree_map(np.asarray, tree)


def trees_equal(a, b):
    return all(np.array_equal(x, y) for x, y in zip(
        jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)))


# ---------------------------------------------------------------------------
# guard-vector math
# ---------------------------------------------------------------------------

def test_guard_vector_math_under_jit():
    grads = {"0": {"w": jnp.asarray([[3.0, 4.0]])},
             "1": {"w": jnp.asarray([0.0, 0.0])}}
    params = {"0": {"w": jnp.asarray([[1.0, 0.0]])},
              "1": {"w": jnp.asarray([2.0, 0.0])}}
    new = {"0": {"w": jnp.asarray([[1.0, 1.0]])},
           "1": {"w": jnp.asarray([2.0, 0.0])}}

    vec = jax.jit(health.guard_vector)(jnp.float32(1.5), grads,
                                       params=params, new_params=new)
    v = np.asarray(vec)
    assert v[GUARD_LOSS] == pytest.approx(1.5)
    assert v[GUARD_LOSS_NONFINITE] == 0.0
    assert v[GUARD_GRAD_NONFINITE] == 0.0
    assert v[GUARD_GRAD_NORM] == pytest.approx(5.0)           # 3-4-5
    assert v[GUARD_RATIO] == pytest.approx(1.0 / np.sqrt(5.0), rel=1e-5)
    # per-bucket tail in sorted key order
    keys = health.bucket_keys(grads)
    assert keys == ("0", "1")
    assert v[GUARD_HEAD] == pytest.approx(5.0)
    assert v[GUARD_HEAD + 1] == pytest.approx(0.0)


def test_guard_vector_flags_nonfinite():
    grads = {"a": jnp.asarray([1.0, np.nan, 2.0]),
             "b": jnp.asarray([0.5, np.inf])}
    vec = jax.jit(health.guard_vector)(jnp.float32(np.nan), grads)
    v = np.asarray(vec)
    assert v[GUARD_LOSS_NONFINITE] == 1.0
    assert v[GUARD_GRAD_NONFINITE] == 1.0  # NaN/Inf poison the sq-sums
    # a finite loss with poisoned grads still trips only the grad flag
    v2 = np.asarray(jax.jit(health.guard_vector)(jnp.float32(1.0), grads))
    assert v2[GUARD_LOSS_NONFINITE] == 0.0
    assert v2[GUARD_GRAD_NONFINITE] == 1.0


def test_guard_combine_is_elementwise_max():
    vecs = jnp.asarray([[1.0, 0.0, 0.0], [0.5, 1.0, 3.0]])
    np.testing.assert_allclose(np.asarray(health.combine(vecs)),
                               [1.0, 1.0, 3.0])


def test_apply_skip_selects_old_on_anomaly():
    old = {"w": jnp.zeros(3)}
    new = {"w": jnp.ones(3)}
    bad = jnp.zeros((GUARD_HEAD + 1,)).at[GUARD_GRAD_NONFINITE].set(1.0)
    ok = jnp.zeros((GUARD_HEAD + 1,))
    (kept,) = health.apply_skip(bad, (new,), (old,))
    np.testing.assert_array_equal(np.asarray(kept["w"]), 0.0)
    (taken,) = health.apply_skip(ok, (new,), (old,))
    np.testing.assert_array_equal(np.asarray(taken["w"]), 1.0)


def test_guard_vector_inside_shard_map():
    """The packed guard math composes with shard_map: grads psum'd
    across the mesh axis, the vector computed on the shared tree (the
    ParallelWrapper bucketed/threshold wiring) and returned replicated."""
    from deeplearning4j_tpu.parallel import mesh as mesh_mod

    devs = jax.devices()[:4]
    mesh = Mesh(np.array(devs), ("data",))

    def step(local_grads):
        shared = jax.tree_util.tree_map(
            lambda g: jax.lax.psum(g, "data"), local_grads)
        return health.guard_vector(jnp.float32(0.5), shared)

    sharded = mesh_mod.shard_map(
        step, mesh, in_specs=(P("data"),), out_specs=P())
    local = {"l": jnp.ones((4, 2))}  # each shard holds [1, 2] of ones
    v = np.asarray(jax.jit(sharded)(local))
    # psum over 4 shards -> each element 4.0; norm = sqrt(2 * 16)
    assert v[GUARD_GRAD_NORM] == pytest.approx(np.sqrt(32.0), rel=1e-6)
    assert v[GUARD_GRAD_NONFINITE] == 0.0


# ---------------------------------------------------------------------------
# policies end-to-end
# ---------------------------------------------------------------------------

def test_warn_counts_lazily_without_halting(rng):
    health.configure(policy="warn")
    net = tiny_net()
    net.fit(data(rng), epochs=1)
    net.fit(data(rng, bad=True), epochs=1)  # does not raise
    # lazy: nothing materialized yet at default flush_every
    rep = health.report()  # report() flushes
    assert rep["nonfinite_steps"] == 1
    assert rep["status"] == "anomalous"
    snap = REGISTRY.snapshot()
    assert snap['dl4j_nonfinite_steps_total{path="multilayer"}'] == 1


def test_skip_step_leaves_params_bit_identical(rng):
    health.configure(policy="skip_step")
    net = tiny_net()
    net.fit(data(rng), epochs=1)
    before = host_params(net.params)
    net.fit(data(rng, bad=True), epochs=1)
    assert trees_equal(before, host_params(net.params))
    assert health.report()["skipped_steps"] == 1
    # and a healthy step afterwards still trains (params move again)
    net.fit(data(rng), epochs=1)
    assert not trees_equal(before, host_params(net.params))
    assert np.isfinite(net.score_value)


def test_rollback_restores_exact_last_good(rng):
    health.configure(policy="rollback", snapshot_every=1)
    net = tiny_net()
    net.fit(data(rng), epochs=1)
    good = host_params(net.params)
    good_iter = net.iteration
    net.fit(data(rng, bad=True), epochs=1)
    assert trees_equal(good, host_params(net.params))
    assert net.iteration == good_iter
    assert health.MONITOR.rollbacks == 1
    # training continues cleanly from the restored state
    net.fit(data(rng), epochs=1)
    assert np.isfinite(net.score_value)


def test_halt_raises_divergence_error(rng):
    health.configure(policy="halt")
    net = tiny_net()
    net.fit(data(rng), epochs=1)
    with pytest.raises(DivergenceError) as ei:
        net.fit(data(rng, bad=True), epochs=1)
    assert ei.value.path == "multilayer"
    assert health.MONITOR.halted
    assert health.report()["status"] == "halted"


def test_detection_on_the_step_it_occurs(rng):
    """HALT fires on the FIRST anomalous step, not at epoch end: a
    multi-batch epoch stops at the poisoned batch."""
    health.configure(policy="halt")
    net = tiny_net()
    batches = [data(rng), data(rng, bad=True), data(rng)]
    from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator

    with pytest.raises(DivergenceError) as ei:
        net.fit(ListDataSetIterator(batches), epochs=1)
    assert ei.value.step == 2  # monitor saw exactly two steps


def test_parallel_wrapper_skip_inside_shard_map(rng):
    from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper

    health.configure(policy="skip_step")
    pw = ParallelWrapper(tiny_net(), workers=8, gradient_bucket_mb=0.001)
    pw.fit(data(rng))
    before = host_params(pw._params)
    pw.fit(data(rng, bad=True))
    assert trees_equal(before, host_params(pw._params))
    assert health.report()["skipped_steps"] == 1


def test_parallel_wrapper_exact_mode_detects(rng):
    from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper

    health.configure(policy="halt")
    pw = ParallelWrapper(tiny_net(), workers=8)
    pw.fit(data(rng))
    with pytest.raises(DivergenceError):
        pw.fit(data(rng, bad=True))


def test_guard_mode_change_rebuilds_step_and_cache_key(rng):
    """An unguarded compiled step must never serve a guarded fit (the
    AOT cache keys diverge via cache_tag)."""
    net = tiny_net()
    net.fit(data(rng), epochs=1)  # compiles the unguarded step
    health.configure(policy="warn")
    net.fit(data(rng), epochs=1)  # must rebuild, not unpack 5-tuple as 6
    assert health.report()["steps"] == 1
    health.disable()
    net.fit(data(rng), epochs=1)  # and back again
    assert np.isfinite(net.score_value)


def test_skipped_flag_false_never_counts_discards():
    """Paths without the in-graph select (pipeline, expert-parallel)
    report anomalies but must never claim the update was discarded."""
    health.configure(policy="skip_step")
    bad = jnp.zeros((GUARD_HEAD + 1,)).at[GUARD_LOSS_NONFINITE].set(1.0)
    health.MONITOR.on_step(bad, keys=("all",), path="pipeline",
                           skipped=False)
    rep = health.report()
    assert rep["nonfinite_steps"] == 1
    assert rep["skipped_steps"] == 0


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

def test_bundle_schema_roundtrip(tmp_path):
    rec = flightrec.FlightRecorder(capacity=8)
    rec.enable()
    for i in range(12):  # overflows the ring: only the last 8 survive
        rec.record_step("multilayer", i, 0, score=jnp.float32(i),
                        guard=jnp.zeros((GUARD_HEAD + 2,)),
                        guard_keys=("0", "1"), lr=0.05, rng_seed=7,
                        batch_fp=[[[16, 4], "float32"]])
    out = rec.dump_bundle(str(tmp_path / "bundle"), reason="test")
    names = sorted(os.listdir(out))
    assert names == ["manifest.json", "metrics.json", "records.jsonl",
                     "trace.json", "traces.json"]
    manifest = json.loads((tmp_path / "bundle" / "manifest.json")
                          .read_text())
    assert manifest["reason"] == "test"
    assert manifest["n_records"] == 8
    assert manifest["format_version"] == 1
    recs = [json.loads(l) for l in
            (tmp_path / "bundle" / "records.jsonl").read_text()
            .splitlines()]
    assert len(recs) == 8
    assert recs[0]["step"] == 4 and recs[-1]["step"] == 11
    assert recs[0]["score"] == pytest.approx(4.0)
    assert len(recs[0]["guard"]) == GUARD_HEAD + 2
    assert recs[0]["guard_keys"] == ["0", "1"]
    assert recs[0]["batch"] == [[[16, 4], "float32"]]
    json.loads((tmp_path / "bundle" / "metrics.json").read_text())
    json.loads((tmp_path / "bundle" / "trace.json").read_text())


def test_induced_nan_e2e_halts_and_dumps_bundle(rng, tmp_path,
                                                monkeypatch):
    monkeypatch.setenv("DL4J_FLIGHTREC_DIR", str(tmp_path))
    health.configure(policy="halt")  # enables the recorder too
    net = tiny_net()
    net.fit(data(rng), epochs=1)
    with pytest.raises(DivergenceError):
        net.fit(data(rng, bad=True), epochs=1)
    bundle = flightrec.RECORDER.last_bundle
    assert bundle and bundle.startswith(str(tmp_path))
    manifest = json.loads(
        open(os.path.join(bundle, "manifest.json")).read())
    assert "DivergenceError" in manifest["reason"]
    assert manifest["health"]["status"] == "halted"
    assert manifest["config_digest"]  # model conf was registered
    recs = [json.loads(l) for l in
            open(os.path.join(bundle, "records.jsonl"))]
    assert recs, "step records must be present"
    last = recs[-1]
    assert last["path"] == "multilayer"
    # the poisoned step's guard survived into the bundle
    assert last["guard"][GUARD_LOSS_NONFINITE] == 1.0


def test_bundle_dumped_on_generic_uncaught_exception(rng, tmp_path,
                                                     monkeypatch):
    monkeypatch.setenv("DL4J_FLIGHTREC_DIR", str(tmp_path))
    health.configure(policy="warn")

    from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator

    class Boom(RuntimeError):
        pass

    class ExplodingIterator(ListDataSetIterator):
        """Yields one good batch, then dies mid-epoch."""

        def __iter__(self):
            yield from super().__iter__()
            raise Boom("data pipeline died")

    net = tiny_net()
    with pytest.raises(Boom):
        net.fit(ExplodingIterator([data(rng)]), epochs=1)
    bundle = flightrec.RECORDER.last_bundle
    assert bundle is not None
    manifest = json.loads(
        open(os.path.join(bundle, "manifest.json")).read())
    assert "Boom" in manifest["reason"]


def test_bundle_json_is_strictly_parseable_with_nan(rng, tmp_path,
                                                    monkeypatch):
    """The bundle carries non-finite values as strings, never as bare
    NaN literals (which strict JSON parsers reject)."""
    monkeypatch.setenv("DL4J_FLIGHTREC_DIR", str(tmp_path))
    health.configure(policy="halt")
    net = tiny_net()
    with pytest.raises(DivergenceError):
        net.fit(data(rng, bad=True), epochs=1)
    bundle = flightrec.RECORDER.last_bundle
    for name in ("manifest.json", "records.jsonl", "metrics.json"):
        text = open(os.path.join(bundle, name)).read()
        docs = (filter(None, text.splitlines())
                if name.endswith(".jsonl") else [text])
        for doc in docs:
            json.loads(doc, parse_constant=lambda c: pytest.fail(
                f"bare {c} literal in {name}"))
    recs = [json.loads(l) for l in
            open(os.path.join(bundle, "records.jsonl"))]
    assert recs[-1]["score"] == "NaN"  # explicit, not a spec violation


def test_health_endpoint_json_strict_under_nan(rng):
    import urllib.request

    from deeplearning4j_tpu.ui.server import UIServer

    health.configure(policy="warn")
    net = tiny_net()
    net.fit(data(rng, bad=True), epochs=1)
    health.MONITOR.flush()
    ui = UIServer()
    port = ui.start(port=0)
    try:
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/health", timeout=10).read()
    finally:
        ui.stop()
    rep = json.loads(body.decode(),
                     parse_constant=lambda c: pytest.fail(
                         f"bare {c} literal in /health"))
    assert rep["last"]["grad_norm"] == "NaN"


def test_config_digest_tracks_current_model(rng, tmp_path, monkeypatch):
    monkeypatch.setenv("DL4J_FLIGHTREC_DIR", str(tmp_path))
    health.configure(policy="warn")
    net_a = tiny_net(seed=1)
    net_a.fit(data(rng), epochs=1)
    digest_a = flightrec.RECORDER._conf_digest
    net_b = tiny_net(seed=2)
    net_b.fit(data(rng), epochs=1)
    assert flightrec.RECORDER._conf_digest != digest_a


def test_recorder_disabled_is_noop(rng, tmp_path, monkeypatch):
    monkeypatch.setenv("DL4J_FLIGHTREC_DIR", str(tmp_path))
    net = tiny_net()
    net.fit(data(rng), epochs=1)
    assert flightrec.RECORDER.last_bundle is None
    assert not os.listdir(str(tmp_path))


# ---------------------------------------------------------------------------
# surfaces: /health endpoint, listener, termination condition
# ---------------------------------------------------------------------------

def test_health_endpoint_serves_monitor_report(rng):
    import urllib.request

    from deeplearning4j_tpu.ui.server import UIServer

    health.configure(policy="warn")
    net = tiny_net()
    net.fit(data(rng), epochs=1)
    net.fit(data(rng, bad=True), epochs=1)
    ui = UIServer()
    port = ui.start(port=0)
    try:
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/health", timeout=10).read()
    finally:
        ui.stop()
    rep = json.loads(body)
    assert rep["status"] == "anomalous"
    assert rep["nonfinite_steps"] == 1
    assert rep["policy"] == "warn"
    assert "grad_norm" in rep["last"]


def test_health_listener_reports_and_registry_gauges(rng):
    import io

    from deeplearning4j_tpu.optimize.listeners import HealthListener

    health.configure(policy="warn")
    stream = io.StringIO()
    net = tiny_net()
    net.set_listeners(HealthListener(frequency=1, stream=stream))
    net.fit(data(rng), epochs=1)
    net.fit(data(rng, bad=True), epochs=1)
    out = stream.getvalue()
    assert "[health]" in out and "non-finite" in out
    assert net.listeners[0].history[-1]["nonfinite_steps"] == 1
    snap = REGISTRY.snapshot()
    assert "dl4j_grad_global_norm" in snap
    assert "dl4j_update_param_ratio" in snap


def test_divergence_termination_condition(rng):
    from deeplearning4j_tpu.earlystopping import (
        DivergenceTerminationCondition,
        EarlyStoppingConfiguration,
        EarlyStoppingTrainer,
        MaxEpochsTerminationCondition,
        TerminationReason,
    )
    from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator

    health.configure(policy="skip_step")  # score stays finite; guard trips
    cfg = EarlyStoppingConfiguration(
        epoch_termination_conditions=[MaxEpochsTerminationCondition(50)],
        iteration_termination_conditions=[DivergenceTerminationCondition()])
    net = tiny_net()
    it = ListDataSetIterator([data(rng), data(rng, bad=True), data(rng)])
    result = EarlyStoppingTrainer(cfg, net, it).fit()
    assert result.termination_reason is TerminationReason.ITERATION
    assert "DivergenceTerminationCondition" in result.termination_details
    # under SKIP_STEP the poisoned batch still reports a NaN loss, so
    # either the score check or the monitor check may fire first — both
    # carry an explicit non-finite reason
    assert "non-finite" in result.termination_details


# ---------------------------------------------------------------------------
# NaN-safe early stopping (satellite)
# ---------------------------------------------------------------------------

def test_score_improvement_condition_nan_terminates_with_reason():
    from deeplearning4j_tpu.earlystopping import (
        ScoreImprovementEpochTerminationCondition,
    )

    cond = ScoreImprovementEpochTerminationCondition(5)
    cond.initialize()
    assert not cond.terminate(0, 1.0)
    assert cond.terminate(1, float("nan"))
    assert "non-finite" in cond.last_reason
    # NOT silently counted as one bad epoch of the patience window
    cond.initialize()
    for e in range(4):
        assert not cond.terminate(e, 1.0 - 0.1 * e)


def test_best_score_condition_nan_terminates_with_reason():
    from deeplearning4j_tpu.earlystopping import (
        BestScoreEpochTerminationCondition,
    )

    cond = BestScoreEpochTerminationCondition(0.1)
    cond.initialize()
    assert not cond.terminate(0, 0.5)
    assert cond.terminate(1, float("inf"))
    assert "non-finite" in cond.last_reason


# ---------------------------------------------------------------------------
# atomic checkpointing (satellite)
# ---------------------------------------------------------------------------

def test_write_model_is_atomic_on_crash(rng, tmp_path, monkeypatch):
    """A crash mid-save leaves the previous checkpoint intact and no
    temp debris; a corrupt zip fails loudly on load."""
    from deeplearning4j_tpu.util import serializer

    net = tiny_net()
    path = tmp_path / "model.zip"
    serializer.write_model(net, path)
    original = path.read_bytes()

    net.fit(data(rng), epochs=1)
    real_replace = os.replace

    def exploding_replace(src, dst):
        raise OSError("disk died mid-publish")

    monkeypatch.setattr(os, "replace", exploding_replace)
    with pytest.raises(OSError):
        serializer.write_model(net, path)
    monkeypatch.setattr(os, "replace", real_replace)

    assert path.read_bytes() == original  # old checkpoint untouched
    assert [p for p in os.listdir(tmp_path) if ".tmp" in p] == []
    restored = serializer.restore_multi_layer_network(path)
    assert restored.num_params() == net.num_params()


def test_corrupt_checkpoint_load_fails_loudly(tmp_path):
    from deeplearning4j_tpu.util import serializer

    path = tmp_path / "corrupt.zip"
    path.write_bytes(b"PK\x03\x04 this is not a finished zip archive")
    with pytest.raises((zipfile.BadZipFile, OSError, KeyError)):
        serializer.restore_multi_layer_network(path)


def test_snapshot_restore_training_state_roundtrip(rng):
    from deeplearning4j_tpu.optimize import checkpoint

    net = tiny_net()
    net.fit(data(rng), epochs=1)
    snap = checkpoint.snapshot_training_state(net)
    good = host_params(net.params)
    net.fit(data(rng), epochs=2)  # moves params + counters
    assert not trees_equal(good, host_params(net.params))
    checkpoint.restore_training_state(net, snap)
    assert trees_equal(good, host_params(net.params))
    assert net.iteration == snap["iteration"]
    # restored state trains onward
    net.fit(data(rng), epochs=1)
    assert np.isfinite(net.score_value)
