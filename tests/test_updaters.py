"""Updater numerics vs hand-computed reference steps (reference oracle:
``org.nd4j.linalg.learning`` updater tests compute expected arrays in-test)."""

import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.conf.updaters import (
    AMSGrad,
    AdaDelta,
    AdaGrad,
    AdaMax,
    Adam,
    Nadam,
    Nesterovs,
    NoOp,
    RmsProp,
    Sgd,
)


def run_steps(updater, grads, lr=0.1, steps=3):
    p = jnp.zeros_like(jnp.asarray(grads[0]))
    state = updater.init_state(p)
    outs = []
    for t in range(steps):
        g = jnp.asarray(grads[t % len(grads)])
        upd, state = updater.update_leaf(g, state, lr, float(t))
        p = p - upd
        outs.append(np.asarray(p))
    return outs


def test_sgd():
    g = np.array([1.0, -2.0, 0.5], np.float32)
    outs = run_steps(Sgd(), [g], lr=0.1, steps=2)
    np.testing.assert_allclose(outs[0], -0.1 * g, rtol=1e-6)
    np.testing.assert_allclose(outs[1], -0.2 * g, rtol=1e-6)


def test_noop_passthrough():
    g = np.array([1.0, 2.0], np.float32)
    upd, _ = NoOp().update_leaf(jnp.asarray(g), {}, 0.5, 0.0)
    np.testing.assert_allclose(np.asarray(upd), g)


def test_adam_first_step_is_lr_sized():
    # After one step from zero state, Adam's update ≈ lr * sign(g).
    g = np.array([0.3, -0.7], np.float32)
    adam = Adam(epsilon=1e-12)
    upd, _ = adam.update_leaf(jnp.asarray(g), adam.init_state(jnp.zeros(2)), 0.01, 0.0)
    np.testing.assert_allclose(np.asarray(upd), 0.01 * np.sign(g), rtol=1e-4)


def test_adam_matches_manual_two_steps():
    g1 = np.array([0.5], np.float64)
    g2 = np.array([-0.25], np.float64)
    b1, b2, eps, lr = 0.9, 0.999, 1e-8, 0.05
    m = (1 - b1) * g1
    v = (1 - b2) * g1 * g1
    a1 = lr * np.sqrt(1 - b2) / (1 - b1)
    exp1 = a1 * m / (np.sqrt(v) + eps)
    m2 = b1 * m + (1 - b1) * g2
    v2 = b2 * v + (1 - b2) * g2 * g2
    a2 = lr * np.sqrt(1 - b2 ** 2) / (1 - b1 ** 2)
    exp2 = a2 * m2 / (np.sqrt(v2) + eps)

    adam = Adam()
    st = adam.init_state(jnp.zeros(1))
    u1, st = adam.update_leaf(jnp.asarray(g1, jnp.float32), st, lr, 0.0)
    u2, st = adam.update_leaf(jnp.asarray(g2, jnp.float32), st, lr, 1.0)
    np.testing.assert_allclose(np.asarray(u1), exp1, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(u2), exp2, rtol=1e-5)


def test_adagrad_accumulates():
    g = np.array([2.0], np.float32)
    ada = AdaGrad(epsilon=1e-12)
    st = ada.init_state(jnp.zeros(1))
    u1, st = ada.update_leaf(jnp.asarray(g), st, 0.1, 0.0)
    u2, st = ada.update_leaf(jnp.asarray(g), st, 0.1, 1.0)
    np.testing.assert_allclose(np.asarray(u1), [0.1], rtol=1e-5)
    np.testing.assert_allclose(np.asarray(u2), [0.1 / np.sqrt(2.0)], rtol=1e-5)


def test_nesterovs_momentum_accelerates():
    g = np.array([1.0], np.float32)
    nes = Nesterovs(momentum=0.9)
    outs = run_steps(nes, [g], lr=0.1, steps=3)
    # displacement must exceed plain SGD's due to momentum
    sgd_outs = run_steps(Sgd(), [g], lr=0.1, steps=3)
    assert outs[2][0] < sgd_outs[2][0] < 0


def test_nesterovs_momentum_schedule_is_used():
    from deeplearning4j_tpu.conf.schedules import MapSchedule, ScheduleType

    g = jnp.asarray(np.array([1.0], np.float32))
    # schedule drops momentum to 0 => update must equal plain SGD's lr*g
    nes = Nesterovs(momentum=0.9,
                    momentum_schedule=MapSchedule(ScheduleType.ITERATION, {0: 0.0}))
    upd, _ = nes.update_leaf(g, nes.init_state(jnp.zeros(1)), 0.1, 0.0)
    np.testing.assert_allclose(np.asarray(upd), [0.1], rtol=1e-6)


def test_rmsprop_scale_invariance():
    big = np.array([100.0], np.float32)
    small = np.array([0.01], np.float32)
    rms = RmsProp(epsilon=1e-12)
    ub, _ = rms.update_leaf(jnp.asarray(big), rms.init_state(jnp.zeros(1)), 0.01, 0.0)
    us, _ = rms.update_leaf(jnp.asarray(small), rms.init_state(jnp.zeros(1)), 0.01, 0.0)
    np.testing.assert_allclose(np.asarray(ub), np.asarray(us), rtol=1e-4)


@pytest.mark.parametrize(
    "updater", [Adam(), AMSGrad(), AdaMax(), Nadam(), AdaDelta(), RmsProp(), AdaGrad()]
)
def test_updates_finite_and_descend(updater):
    # quadratic bowl: f(p) = 0.5*||p - target||^2
    target = jnp.asarray(np.array([1.0, -2.0, 3.0], np.float32))
    p = jnp.zeros(3)
    state = updater.init_state(p)
    loss0 = float(jnp.sum((p - target) ** 2))
    for t in range(200):
        g = p - target
        upd, state = updater.update_leaf(g, state, 0.05, float(t))
        p = p - upd
        assert np.all(np.isfinite(np.asarray(p)))
    assert float(jnp.sum((p - target) ** 2)) < loss0 * 0.5


def test_adamw_decays_weights():
    from deeplearning4j_tpu.conf.updaters import AdamW

    w = jnp.asarray([10.0])
    g = jnp.asarray([0.0])  # zero gradient: only decay acts
    u = AdamW(weight_decay=0.1)
    upd, _ = u.update_leaf(g, u.init_state(w), 0.5, 0.0, param=w)
    np.testing.assert_allclose(np.asarray(upd), [0.5], rtol=1e-6)  # wd*lr*w


def test_nesterovs_epoch_momentum_schedule():
    from deeplearning4j_tpu.conf.schedules import MapSchedule, ScheduleType

    nes = Nesterovs(momentum=0.9, momentum_schedule=MapSchedule(
        ScheduleType.EPOCH, {0: 0.0, 5: 0.9}))
    g = jnp.asarray([1.0])
    # epoch 0: mu=0 -> plain sgd
    upd, _ = nes.update_leaf(g, nes.init_state(jnp.zeros(1)), 0.1, 0.0, epoch=0.0)
    np.testing.assert_allclose(np.asarray(upd), [0.1], rtol=1e-6)
    # epoch 5: mu=0.9 -> first-step update (1+mu)*lr*g
    upd2, _ = nes.update_leaf(g, nes.init_state(jnp.zeros(1)), 0.1, 0.0, epoch=5.0)
    np.testing.assert_allclose(np.asarray(upd2), [0.19], rtol=1e-5)
