"""Recurrent layer tests: shapes, masking, gradients vs central differences,
tBPTT segmentation and streaming inference (reference test model:
``LSTMGradientCheckTests``, ``GravesLSTMTest``, ``MultiLayerTest`` tBPTT and
``rnnTimeStep`` tests)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.conf.activations import Activation
from deeplearning4j_tpu.conf.inputs import InputType
from deeplearning4j_tpu.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.conf.layers_rnn import (
    Bidirectional, BidirectionalMode, GravesLSTM, LSTM, LastTimeStep,
    MaskZeroLayer, RnnLossLayer, RnnOutputLayer, SimpleRnn, reverse_sequence,
)
from deeplearning4j_tpu.conf.multilayer import (
    BackpropType, NeuralNetConfiguration,
)
from deeplearning4j_tpu.conf.updaters import Adam, Sgd
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.util.gradcheck import gradient_check

KEY = jax.random.PRNGKey(0)


def _seq_conf(cell, n_in=3, n_out=4, classes=2, tbptt=None, bid=None):
    b = (NeuralNetConfiguration.builder()
         .seed(12345)
         .updater(Adam(5e-3))
         .list())
    layer = cell(n_out=n_out)
    if bid is not None:
        layer = Bidirectional(layer=layer, mode=bid)
    b.layer(layer)
    b.layer(RnnOutputLayer(n_out=classes))
    b.set_input_type(InputType.recurrent(n_in, timesteps=5))
    if tbptt:
        b.backprop_type(BackpropType.TRUNCATED_BPTT, tbptt, tbptt)
    return b.build()


def _seq_data(n=4, t=5, f=3, classes=2, masked=True, seed=0):
    rng = np.random.default_rng(seed)
    feats = rng.normal(size=(n, t, f)).astype(np.float32)
    labels = np.eye(classes, dtype=np.float32)[rng.integers(0, classes, (n, t))]
    if not masked:
        return DataSet(feats, labels)
    mask = np.ones((n, t), np.float32)
    mask[0, 3:] = 0.0  # first sample has length 3
    feats[0, 3:] = 0.0
    return DataSet(feats, labels, features_mask=mask, labels_mask=mask)


# --------------------------------------------------------------------------
# forward shapes + masking semantics
# --------------------------------------------------------------------------
@pytest.mark.parametrize("cell", [SimpleRnn, LSTM, GravesLSTM])
def test_rnn_forward_shapes(cell):
    layer = cell(n_out=6)
    t = InputType.recurrent(3, timesteps=5)
    assert layer.output_type(t) == InputType.recurrent(6, timesteps=5)
    params = layer.init(KEY, t)
    x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 5, 3)),
                    jnp.float32)
    y, _ = layer.forward(params, {}, x)
    assert y.shape == (2, 5, 6)


@pytest.mark.parametrize("cell", [SimpleRnn, LSTM, GravesLSTM])
def test_rnn_mask_freezes_state_and_zeroes_output(cell):
    layer = cell(n_out=4)
    t = InputType.recurrent(2, timesteps=6)
    params = layer.init(KEY, t)
    rng = np.random.default_rng(1)
    x = rng.normal(size=(1, 6, 2)).astype(np.float32)
    mask = np.array([[1, 1, 1, 0, 0, 0]], np.float32)
    y_masked, _ = layer.forward(params, {}, jnp.asarray(x),
                                mask=jnp.asarray(mask))
    # outputs at masked steps are exactly zero
    np.testing.assert_allclose(np.asarray(y_masked[0, 3:]), 0.0)
    # valid prefix identical to running the 3-step sequence alone
    y_short, _ = layer.forward(params, {}, jnp.asarray(x[:, :3]))
    np.testing.assert_allclose(np.asarray(y_masked[0, :3]),
                               np.asarray(y_short[0]), rtol=1e-5, atol=1e-6)


def test_reverse_sequence_mask_aware():
    x = jnp.asarray(np.arange(8, dtype=np.float32).reshape(1, 4, 2))
    mask = jnp.asarray([[1, 1, 1, 0]], jnp.float32)
    r = np.asarray(reverse_sequence(x, mask))
    # valid steps 0,1,2 reversed; padding step 3 untouched
    np.testing.assert_allclose(r[0, 0], [4, 5])
    np.testing.assert_allclose(r[0, 2], [0, 1])
    np.testing.assert_allclose(r[0, 3], [6, 7])


@pytest.mark.parametrize("mode,expected_size", [
    (BidirectionalMode.CONCAT, 8), (BidirectionalMode.ADD, 4),
    (BidirectionalMode.AVERAGE, 4), (BidirectionalMode.MUL, 4)])
def test_bidirectional_modes(mode, expected_size):
    layer = Bidirectional(layer=LSTM(n_out=4), mode=mode)
    t = InputType.recurrent(3, timesteps=5)
    assert layer.output_type(t).size == expected_size
    params = layer.init(KEY, t)
    assert set(params) == {f"{d}{k}" for d in "fb" for k in ("W", "RW", "b")}
    x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 5, 3)),
                    jnp.float32)
    y, _ = layer.forward(params, {}, x)
    assert y.shape == (2, 5, expected_size)


def test_last_time_step_mask_aware():
    inner = SimpleRnn(n_out=4)
    layer = LastTimeStep(layer=inner)
    t = InputType.recurrent(2, timesteps=5)
    params = layer.init(KEY, t)
    assert layer.output_type(t) == InputType.feed_forward(4)
    rng = np.random.default_rng(2)
    x = rng.normal(size=(2, 5, 2)).astype(np.float32)
    mask = np.array([[1, 1, 1, 0, 0], [1, 1, 1, 1, 1]], np.float32)
    y, _ = layer.forward(params, {}, jnp.asarray(x), mask=jnp.asarray(mask))
    full, _ = inner.forward(params, {}, jnp.asarray(x),
                            mask=jnp.asarray(mask))
    np.testing.assert_allclose(np.asarray(y[0]), np.asarray(full[0, 2]))
    np.testing.assert_allclose(np.asarray(y[1]), np.asarray(full[1, 4]))


def test_mask_zero_layer_derives_mask_from_sentinel():
    layer = MaskZeroLayer(layer=SimpleRnn(n_out=3), mask_value=0.0)
    t = InputType.recurrent(2, timesteps=4)
    params = layer.init(KEY, t)
    x = np.ones((1, 4, 2), np.float32)
    x[0, 2:] = 0.0  # all-zero steps => masked
    y, _ = layer.forward(params, {}, jnp.asarray(x))
    assert not np.allclose(np.asarray(y[0, :2]), 0.0)
    np.testing.assert_allclose(np.asarray(y[0, 2:]), 0.0)


# --------------------------------------------------------------------------
# gradient checks (the reference's core oracle)
# --------------------------------------------------------------------------
@pytest.mark.parametrize("cell", [SimpleRnn, LSTM, GravesLSTM])
def test_rnn_gradients(cell):
    conf = _seq_conf(cell)
    res = gradient_check(conf, _seq_data(), n_samples=60)
    assert res.n_failed == 0, res.failures


def test_bidirectional_gradients():
    conf = _seq_conf(LSTM, bid=BidirectionalMode.CONCAT)
    res = gradient_check(conf, _seq_data(), n_samples=60)
    assert res.n_failed == 0, res.failures


def test_last_time_step_gradients():
    b = (NeuralNetConfiguration.builder().seed(1).updater(Sgd(0.1)).list()
         .layer(LastTimeStep(layer=LSTM(n_out=3)))
         .layer(OutputLayer(n_out=2))
         .set_input_type(InputType.recurrent(2, timesteps=4)))
    conf = b.build()
    rng = np.random.default_rng(3)
    feats = rng.normal(size=(3, 4, 2)).astype(np.float32)
    labels = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 3)]
    mask = np.ones((3, 4), np.float32)
    mask[1, 2:] = 0.0
    ds = DataSet(feats, labels, features_mask=mask)
    res = gradient_check(conf, ds, n_samples=40)
    assert res.n_failed == 0, res.failures


# --------------------------------------------------------------------------
# training: standard BPTT, tBPTT, streaming
# --------------------------------------------------------------------------
def test_lstm_learns_sequence_task():
    # predict whether the cumulative sum of inputs so far is positive
    rng = np.random.default_rng(0)
    feats = rng.normal(size=(64, 8, 1)).astype(np.float32)
    cum = np.cumsum(feats[:, :, 0], axis=1)
    labels = np.stack([(cum <= 0), (cum > 0)], axis=-1).astype(np.float32)
    conf = (NeuralNetConfiguration.builder().seed(7).updater(Adam(2e-2))
            .list()
            .layer(LSTM(n_out=8))
            .layer(RnnOutputLayer(n_out=2))
            .set_input_type(InputType.recurrent(1, timesteps=8))
            .build())
    net = MultiLayerNetwork(conf).init()
    ds = DataSet(feats, labels)
    first = net.fit_batch(ds)
    for _ in range(150):
        last = net.fit_batch(ds)
    assert last < first * 0.5, (first, last)
    out = np.asarray(net.output(feats))
    acc = np.mean(out.argmax(-1) == labels.argmax(-1))
    assert acc > 0.9, acc


def test_tbptt_segments_and_learns():
    rng = np.random.default_rng(1)
    feats = rng.normal(size=(8, 10, 2)).astype(np.float32)
    labels = np.eye(2, dtype=np.float32)[
        (feats.sum(-1) > 0).astype(int)]
    conf = (NeuralNetConfiguration.builder().seed(3).updater(Adam(1e-2))
            .list()
            .layer(LSTM(n_out=4))
            .layer(RnnOutputLayer(n_out=2))
            .set_input_type(InputType.recurrent(2, timesteps=10))
            .backprop_type(BackpropType.TRUNCATED_BPTT, 4, 4)
            .build())
    net = MultiLayerNetwork(conf).init()
    ds = DataSet(feats, labels)
    net.fit_batch(ds)
    # 10 steps in segments of 4 -> 3 parameter updates per batch
    assert net.iteration == 3
    first = net.score_value
    for _ in range(60):
        net.fit_batch(ds)
    assert net.score_value < first


def test_rnn_time_step_streaming_matches_full_forward():
    conf = (NeuralNetConfiguration.builder().seed(5).updater(Adam(1e-3))
            .list()
            .layer(LSTM(n_out=4))
            .layer(SimpleRnn(n_out=3))
            .layer(RnnOutputLayer(n_out=2))
            .set_input_type(InputType.recurrent(2, timesteps=6))
            .build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(4)
    x = rng.normal(size=(2, 6, 2)).astype(np.float32)
    full = np.asarray(net.output(x))
    # stream in chunks of 2 timesteps
    net.rnn_clear_previous_state()
    parts = [np.asarray(net.rnn_time_step(x[:, i:i + 2])) for i in (0, 2, 4)]
    streamed = np.concatenate(parts, axis=1)
    np.testing.assert_allclose(streamed, full, rtol=1e-5, atol=1e-6)
    # state inspection / reset round-trip
    st = net.rnn_get_previous_state(0)
    assert set(st) == {"h", "c"}
    net.rnn_clear_previous_state()
    assert net.rnn_get_previous_state(0) is None
    # single-step [batch, f] input works
    y1 = net.rnn_time_step(x[:, 0])
    assert np.asarray(y1).shape == (2, 1, 2)


def test_rnn_conf_json_roundtrip():
    conf = _seq_conf(GravesLSTM, bid=BidirectionalMode.ADD)
    from deeplearning4j_tpu.conf.multilayer import MultiLayerConfiguration

    conf2 = MultiLayerConfiguration.from_json(conf.to_json())
    assert conf2 == conf
    net = MultiLayerNetwork(conf2).init()
    y = net.output(np.zeros((1, 5, 3), np.float32))
    assert np.asarray(y).shape == (1, 5, 2)


def test_last_time_step_align_end_mask():
    inner = SimpleRnn(n_out=3)
    layer = LastTimeStep(layer=inner)
    t = InputType.recurrent(2, timesteps=4)
    params = layer.init(KEY, t)
    x = np.random.default_rng(5).normal(size=(1, 4, 2)).astype(np.float32)
    mask = np.array([[0, 0, 1, 1]], np.float32)  # ALIGN_END, length 2
    y, _ = layer.forward(params, {}, jnp.asarray(x), mask=jnp.asarray(mask))
    full, _ = inner.forward(params, {}, jnp.asarray(x),
                            mask=jnp.asarray(mask))
    np.testing.assert_allclose(np.asarray(y[0]), np.asarray(full[0, 3]))
    assert not np.allclose(np.asarray(y[0]), 0.0)


def test_mask_zero_layer_carries_state_in_streaming():
    conf = (NeuralNetConfiguration.builder().seed(5).updater(Adam(1e-3))
            .list()
            .layer(MaskZeroLayer(layer=LSTM(n_out=4)))
            .layer(RnnOutputLayer(n_out=2))
            .set_input_type(InputType.recurrent(2, timesteps=6))
            .build())
    net = MultiLayerNetwork(conf).init()
    x = np.random.default_rng(6).normal(size=(2, 6, 2)).astype(np.float32)
    full = np.asarray(net.output(x))
    net.rnn_clear_previous_state()
    parts = [np.asarray(net.rnn_time_step(x[:, i:i + 3])) for i in (0, 3)]
    np.testing.assert_allclose(np.concatenate(parts, axis=1), full,
                               rtol=1e-5, atol=1e-6)


def test_rnn_recurrent_weights_are_regularized():
    from deeplearning4j_tpu.conf.regularization import L2Regularization as L2
    from deeplearning4j_tpu.optimize.solver import regularization_score

    layer = LSTM(n_out=3, regularization=(L2(0.1),))
    t = InputType.recurrent(2, timesteps=4)
    params = {"0": layer.init(KEY, t)}
    score = regularization_score([layer], params)
    w_rw = 0.5 * 0.1 * float(jnp.sum(params["0"]["W"] ** 2)
                             + jnp.sum(params["0"]["RW"] ** 2))
    assert float(score) == pytest.approx(w_rw, rel=1e-5)


def test_tbptt_rejects_sequence_level_labels():
    conf = (NeuralNetConfiguration.builder().seed(1).updater(Adam(1e-3))
            .list()
            .layer(LastTimeStep(layer=LSTM(n_out=3)))
            .layer(OutputLayer(n_out=2))
            .set_input_type(InputType.recurrent(2, timesteps=6))
            .backprop_type(BackpropType.TRUNCATED_BPTT, 3, 3)
            .build())
    net = MultiLayerNetwork(conf).init()
    feats = np.zeros((2, 6, 2), np.float32)
    labels = np.eye(2, dtype=np.float32)[[0, 1]]
    with pytest.raises(ValueError, match="per-timestep labels"):
        net.fit_batch(DataSet(feats, labels))


# --------------------------------------------------------------------------
# regression tests for review findings (wrapper delegation, ALIGN_END,
# builder defaults through wrappers, tbptt back length)
# --------------------------------------------------------------------------
def test_wrapper_layers_delegate_training_hyperparams():
    """Regularization/updater/gradient-norm set on a wrapped layer must be
    visible through the wrapper (the solver reads them off the top conf)."""
    from deeplearning4j_tpu.conf.layers import GradientNormalization
    from deeplearning4j_tpu.conf.regularization import L2Regularization
    from deeplearning4j_tpu.optimize import solver

    inner = LSTM(n_out=3, regularization=(L2Regularization(0.1),),
                 updater=Sgd(0.5),
                 gradient_normalization=GradientNormalization.CLIP_L2_PER_LAYER,
                 gradient_normalization_threshold=0.5)
    for wrapper in (LastTimeStep(layer=inner),
                    Bidirectional(layer=inner)):
        assert wrapper.regularization == inner.regularization
        assert wrapper.updater is inner.updater
        assert (wrapper.gradient_normalization
                is GradientNormalization.CLIP_L2_PER_LAYER)
        g = {"W": jnp.ones((4, 12))}
        clipped = solver.normalize_layer_gradients(wrapper, g)
        norm = float(jnp.sqrt(jnp.sum(clipped["W"] ** 2)))
        assert norm <= 0.5 + 1e-5


def test_reverse_sequence_align_end():
    """ALIGN_END masks: valid segment reversed in place, padding intact."""
    from deeplearning4j_tpu.conf.layers_rnn import reverse_sequence

    x = np.arange(8, dtype=np.float32).reshape(1, 4, 2)
    mask = np.array([[0.0, 0.0, 1.0, 1.0]])  # valid steps at t=2,3
    out = np.asarray(reverse_sequence(jnp.asarray(x), jnp.asarray(mask)))
    np.testing.assert_allclose(out[0, 2], x[0, 3])
    np.testing.assert_allclose(out[0, 3], x[0, 2])
    np.testing.assert_allclose(out[0, :2], x[0, :2])  # padding untouched


def test_builder_defaults_reach_wrapped_layer():
    from deeplearning4j_tpu.conf.regularization import L2Regularization
    from deeplearning4j_tpu.conf.weights import WeightInit

    conf = (NeuralNetConfiguration.builder()
            .seed(1)
            .weight_init(WeightInit.UNIFORM)
            .l2(0.01)
            .list()
            .layer(Bidirectional(layer=LSTM(n_out=4)))
            .layer(RnnOutputLayer(n_out=2))
            .set_input_type(InputType.recurrent(3, timesteps=5))
            .build())
    inner = conf.layers[0].layer
    assert inner.weight_init == WeightInit.UNIFORM
    assert any(isinstance(r, L2Regularization) for r in inner.regularization)


def test_rnn_time_step_rejects_wrapped_bidirectional():
    conf = (NeuralNetConfiguration.builder()
            .seed(1)
            .list()
            .layer(LastTimeStep(layer=Bidirectional(layer=LSTM(n_out=4))))
            .layer(OutputLayer(n_out=2))
            .set_input_type(InputType.recurrent(3, timesteps=5))
            .build())
    net = MultiLayerNetwork(conf).init()
    with pytest.raises(RuntimeError, match="Bidirectional"):
        net.rnn_time_step(np.zeros((2, 5, 3), np.float32))


def test_tbptt_back_length_shorter_than_fwd():
    """fwd=4, back=2: runs, learns, and the prefix steps carry state."""
    b = (NeuralNetConfiguration.builder()
         .seed(12345)
         .updater(Adam(5e-3))
         .list()
         .layer(LSTM(n_out=4))
         .layer(RnnOutputLayer(n_out=2)))
    b.set_input_type(InputType.recurrent(3, timesteps=8))
    b.backprop_type(BackpropType.TRUNCATED_BPTT, 4, 2)
    conf = b.build()
    assert conf.tbptt_back_length == 2
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    feats = rng.normal(size=(4, 8, 3)).astype(np.float32)
    labels = np.eye(2, dtype=np.float32)[rng.integers(0, 2, (4, 8))]
    loss = net.fit_batch(DataSet(feats, labels))
    assert np.isfinite(loss)


def test_graves_lstm_peepholes_change_output():
    """GravesLSTM inherits LSTM's scan; nonzero peepholes must alter it."""
    layer = GravesLSTM(n_out=3)
    itype = InputType.recurrent(2, timesteps=4)
    p = layer.init(KEY, itype)
    x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 4, 2)),
                    jnp.float32)
    carry = layer.zero_carry(2)
    y0, _ = layer.forward_with_carry(p, carry, x)
    p2 = dict(p)
    p2["pO"] = jnp.ones_like(p["pO"])
    y1, _ = layer.forward_with_carry(p2, carry, x)
    assert float(jnp.abs(y1 - y0).max()) > 1e-6


def test_tbptt_seg_change_and_prepad(rng):
    """Changing tbptt_fwd_length between fits must not reuse a stale
    compiled closure; variable-length numpy batches pre-pad so the scan
    cache quantizes to the segment count."""
    from deeplearning4j_tpu.conf.multilayer import BackpropType

    conf = (NeuralNetConfiguration.builder().seed(3).updater(Adam(1e-3))
            .list()
            .layer(LSTM(n_out=8, activation=Activation.TANH))
            .layer(RnnOutputLayer(n_out=2, activation=Activation.SOFTMAX))
            .set_input_type(InputType.recurrent(3, timesteps=20))
            .backprop_type(BackpropType.TRUNCATED_BPTT, 5, 5)
            .build())
    net = MultiLayerNetwork(conf).init()
    x = rng.normal(size=(4, 20, 3)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, (4, 20))]
    from deeplearning4j_tpu.datasets.dataset import DataSet
    net.fit_batch(DataSet(x, y))
    assert net.iteration == 4  # 20/5 segments

    # variable length, NOT a multiple of seg: prepad -> 4 segments of 5
    x2 = rng.normal(size=(4, 17, 3)).astype(np.float32)
    y2 = np.eye(2, dtype=np.float32)[rng.integers(0, 2, (4, 17))]
    ds2 = DataSet(x2, y2)
    net.fit_batch(ds2)
    assert net.iteration == 8
    assert ds2.features.shape[1] == 17  # caller's DataSet untouched

    # seg change between fits: fresh compile, segment count follows
    # (back length too — back < seg would take the loop path instead of
    # the seg-keyed scan cache this test guards)
    net.conf.tbptt_fwd_length = 10
    net.conf.tbptt_back_length = 10
    net.fit_batch(DataSet(x, y))
    assert net.iteration == 10  # +2 segments of 10


def test_tbptt_prepad_caches_across_epochs(rng):
    """The padded copy is reused so a reused DataSet transfers once."""
    import jax

    from deeplearning4j_tpu.conf.multilayer import BackpropType

    conf = (NeuralNetConfiguration.builder().seed(3).updater(Adam(1e-3))
            .list()
            .layer(LSTM(n_out=8, activation=Activation.TANH))
            .layer(RnnOutputLayer(n_out=2, activation=Activation.SOFTMAX))
            .set_input_type(InputType.recurrent(3, timesteps=17))
            .backprop_type(BackpropType.TRUNCATED_BPTT, 5, 5)
            .build())
    net = MultiLayerNetwork(conf).init()
    x = rng.normal(size=(4, 17, 3)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, (4, 17))]
    from deeplearning4j_tpu.datasets.dataset import DataSet
    ds = DataSet(x, y)
    net.fit_batch(ds)
    padded1 = ds._tbptt_padded[1]
    assert isinstance(padded1.features, jax.Array)  # write_back migrated
    net.fit_batch(ds)
    assert ds._tbptt_padded[1] is padded1  # same copy, no re-pad
    assert ds.features is x                # caller arrays untouched


def test_tbptt_prepad_cache_invalidates_on_label_change(rng):
    from deeplearning4j_tpu.conf.multilayer import BackpropType

    conf = (NeuralNetConfiguration.builder().seed(3).updater(Adam(1e-2))
            .list()
            .layer(SimpleRnn(n_out=4))
            .layer(RnnOutputLayer(n_out=2, activation=Activation.SOFTMAX))
            .set_input_type(InputType.recurrent(2, timesteps=7))
            .backprop_type(BackpropType.TRUNCATED_BPTT, 3, 3)
            .build())
    net = MultiLayerNetwork(conf).init()
    x = rng.normal(size=(4, 7, 2)).astype(np.float32)
    y1 = np.eye(2, dtype=np.float32)[np.zeros((4, 7), int)]
    y2 = np.eye(2, dtype=np.float32)[np.ones((4, 7), int)]
    ds = DataSet(x, y1)
    net.fit_batch(ds)
    first = ds._tbptt_padded[1]
    ds.labels = y2              # swapping labels must invalidate the cache
    net.fit_batch(ds)
    assert ds._tbptt_padded[1] is not first
    np.testing.assert_allclose(
        np.asarray(ds._tbptt_padded[1].labels[:, :7]), y2)


def test_tbptt_back_lt_fwd_tail_segment_trains(rng):
    """fwd=5, back=3, T=11: the tail segment's single real step must land
    in the GRADIENT window, not the no-grad state-advance head (round-2
    fix: tail padding is inserted before the real steps). Oracle: two
    identical nets fit on data differing ONLY in the t=10 labels must end
    with different params."""
    from deeplearning4j_tpu.conf.layers_rnn import LSTM, RnnOutputLayer
    from deeplearning4j_tpu.conf.multilayer import (
        BackpropType, NeuralNetConfiguration,
    )
    from deeplearning4j_tpu.conf.losses import LossMCXENT
    from deeplearning4j_tpu.conf.updaters import Sgd
    from deeplearning4j_tpu.conf import Activation, InputType, WeightInit

    def conf():
        return (NeuralNetConfiguration.builder()
                .seed(3).updater(Sgd(learning_rate=0.1))
                .weight_init(WeightInit.XAVIER).list()
                .layer(LSTM(n_out=8))
                .layer(RnnOutputLayer(n_out=3, activation=Activation.SOFTMAX,
                                      loss_fn=LossMCXENT()))
                .backprop_type(BackpropType.TRUNCATED_BPTT, fwd=5, back=3)
                .set_input_type(InputType.recurrent(2, 11)).build())

    x = rng.normal(size=(4, 11, 2)).astype(np.float32)
    y1 = np.eye(3, dtype=np.float32)[rng.integers(0, 3, (4, 11))]
    y2 = y1.copy()
    y2[:, 10] = np.roll(y1[:, 10], 1, axis=-1)  # only t=10 differs
    a = MultiLayerNetwork(conf()).init()
    b = MultiLayerNetwork(conf()).init()
    la = a.fit_batch(DataSet(x, y1))
    lb = b.fit_batch(DataSet(x, y2))
    diff = np.max(np.abs(a.params_flat() - b.params_flat()))
    assert diff > 0, "tail-segment labels had no gradient effect"
    # and the mean loss is not diluted by a hard-zero tail segment
    assert la > 0 and lb > 0


def test_mln_tbptt_go_backwards_matches_standard_and_slices():
    """Round-4: go_backwards under MLN truncated BPTT (per-segment
    reset, same contract as ComputationGraph): single segment == exact
    standard BPTT; multi-segment == sequential standard fits on the
    fwd-length slices."""
    from deeplearning4j_tpu.conf import Activation, InputType, WeightInit
    from deeplearning4j_tpu.conf.layers_rnn import LSTM, RnnOutputLayer
    from deeplearning4j_tpu.conf.losses import LossMCXENT
    from deeplearning4j_tpu.conf.multilayer import (
        BackpropType,
        NeuralNetConfiguration,
    )
    from deeplearning4j_tpu.conf.updaters import Adam
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    def build(t, fwd):
        b = (NeuralNetConfiguration.builder()
             .seed(11).updater(Adam(learning_rate=0.02))
             .weight_init(WeightInit.XAVIER)
             .list()
             .layer(LSTM(n_out=6, go_backwards=True))
             .layer(RnnOutputLayer(n_out=2, activation=Activation.SOFTMAX,
                                   loss_fn=LossMCXENT())))
        if fwd:
            b.backprop_type(BackpropType.TRUNCATED_BPTT, fwd=fwd, back=fwd)
        return MultiLayerNetwork(
            b.set_input_type(InputType.recurrent(4, t)).build()).init()

    rng = np.random.default_rng(3)
    x = rng.normal(size=(4, 10, 4)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, (4, 10))]

    # single segment == standard
    std = build(5, fwd=0)
    tb = build(5, fwd=5)
    l_std = std.fit_batch(DataSet(x[:, :5], y[:, :5]))
    l_tb = tb.fit_batch(DataSet(x[:, :5], y[:, :5]))
    np.testing.assert_allclose(l_tb, l_std, rtol=1e-6)
    for k in std.params:
        for pk in std.params[k]:
            np.testing.assert_allclose(
                np.asarray(tb.params[k][pk]),
                np.asarray(std.params[k][pk]), rtol=1e-5, atol=1e-7)

    # multi-segment == sequential slice fits
    tb2 = build(10, fwd=5)
    std2 = build(5, fwd=0)
    std2.params = {k: {pk: np.asarray(v).copy() for pk, v in d.items()}
                   for k, d in tb2.params.items()}
    l_tb2 = tb2.fit_batch(DataSet(x, y))
    l1 = std2.fit_batch(DataSet(x[:, :5], y[:, :5]))
    l2 = std2.fit_batch(DataSet(x[:, 5:], y[:, 5:]))
    np.testing.assert_allclose(l_tb2, (l1 + l2) / 2.0, rtol=1e-5)
    for k in std2.params:
        for pk in std2.params[k]:
            np.testing.assert_allclose(
                np.asarray(tb2.params[k][pk]),
                np.asarray(std2.params[k][pk]), rtol=1e-4, atol=1e-6)
