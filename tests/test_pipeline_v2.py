"""Pipeline-parallel training v2 (round 5): real networks under
``PipelineParallelWrapper`` — BatchNormalization running statistics,
dropout, L1/L2/weight-decay, per-layer updaters, ComputationGraph
partitioning, and the 1F1B schedule.

The oracle everywhere is the SAME math with the pipeline dimension
collapsed: a serial MICROBATCHED train step (forward per microbatch with
state threaded in micro order, mean of per-micro head scores + the
regularization score, the per-layer solver chain) — this is what the
pipeline computes by construction; plain full-batch ``fit_batch`` is NOT
the oracle once BN statistics or dropout masks depend on the microbatch
split. The rng fold chain is pinned:
``fold_in(fold_in(fold_in(PRNGKey(seed), it), m), layer_index)``.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from deeplearning4j_tpu.conf import Activation, InputType, WeightInit
from deeplearning4j_tpu.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.conf.layers_cnn import (
    BatchNormalization,
    ConvolutionLayer,
    ConvolutionMode,
)
from deeplearning4j_tpu.conf.losses import LossMCXENT
from deeplearning4j_tpu.conf.multilayer import NeuralNetConfiguration
from deeplearning4j_tpu.conf.regularization import (
    L1Regularization,
    L2Regularization,
    WeightDecay,
)
from deeplearning4j_tpu.conf.updaters import Adam, Nesterovs, Sgd
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel.pipeline import (
    STAGE_AXIS,
    PipelineParallelWrapper,
)


def _stage_mesh(n):
    return Mesh(np.array(jax.devices()[:n]), (STAGE_AXIS,))


def _copy_params(net):
    return jax.tree_util.tree_map(lambda a: np.asarray(a).copy(),
                                  dict(net.params))


def _mln_oracle_step(net, x, y, n_micro, it=0, ep=0):
    """Serial microbatched oracle: threads state per micro, folds rng
    exactly as the pipeline does, differentiates loss + reg score, then
    runs the per-layer solver chain. Returns (new_params, new_state,
    loss)."""
    from deeplearning4j_tpu.optimize import solver

    layers = net.conf.layers
    last = len(layers) - 1
    params = jax.tree_util.tree_map(jnp.asarray, dict(net.params))
    state0 = jax.tree_util.tree_map(jnp.asarray, dict(net.state))
    base = jax.random.PRNGKey(net.conf.seed)
    step_key = jax.random.fold_in(base, it)
    M = n_micro
    x_micro = x.reshape((M, -1) + x.shape[1:])
    y_micro = y.reshape((M, -1) + y.shape[1:])

    def loss_fn(p):
        cur = {k: dict(v) for k, v in state0.items()}
        total = 0.0
        for m in range(M):
            rng_m = jax.random.fold_in(step_key, m)
            xa = jnp.asarray(x_micro[m])
            for i in range(last):
                lrng = jax.random.fold_in(rng_m, i)
                xa, s2 = layers[i].forward(
                    p.get(str(i), {}), cur.get(str(i), {}), xa,
                    train=True, rng=lrng)
                if str(i) in cur:
                    cur[str(i)] = s2
            total = total + layers[last].score(
                p.get(str(last), {}), xa, jnp.asarray(y_micro[m]), None)
        loss = total / M
        loss = loss + solver.regularization_score(layers, p)
        return loss, cur

    (loss, new_state), grads = jax.value_and_grad(
        loss_fn, has_aux=True)(params)
    new_params = {}
    for k in params:
        layer = layers[int(k)]
        upd = getattr(layer, "updater", None) or net.conf.updater
        lr = upd.current_lr(np.float32(it), np.float32(ep))
        opt = {pk: upd.init_state(pv) for pk, pv in params[k].items()}
        g = solver.normalize_layer_gradients(layer, grads[k])
        new_params[k], _ = solver.apply_updater_to_layer(
            layer, upd, params[k], g, opt, lr, np.float32(it),
            np.float32(ep))
    return new_params, new_state, float(loss)


def _assert_tree_close(actual, expected, rtol=1e-4, atol=1e-5, msg=""):
    for k in expected:
        for pk in expected[k]:
            np.testing.assert_allclose(
                np.asarray(actual[k][pk]), np.asarray(expected[k][pk]),
                rtol=rtol, atol=atol, err_msg=f"{msg}{k}/{pk}")


def _bn_dropout_conv_net(seed=7, updater=None):
    """The verdict's target: a conv net with BN running stats AND
    dropout — v1 refused both."""
    conf = (NeuralNetConfiguration.builder()
            .seed(seed).updater(updater or Sgd(learning_rate=0.05))
            .weight_init(WeightInit.XAVIER)
            .list()
            .layer(ConvolutionLayer(n_out=8, kernel_size=(3, 3),
                                    stride=(1, 1),
                                    convolution_mode=ConvolutionMode
                                    .SAME,
                                    activation=Activation.IDENTITY))
            .layer(BatchNormalization())
            .layer(DenseLayer(n_out=16, activation=Activation.TANH,
                              dropout=0.5))
            .layer(OutputLayer(n_out=3, activation=Activation.SOFTMAX,
                               loss_fn=LossMCXENT()))
            .set_input_type(InputType.convolutional(8, 8, 3))
            .build())
    return MultiLayerNetwork(conf).init()


def _batch(rng, n=12, h=8, w=8, c=3, classes=3):
    x = rng.normal(size=(n, h, w, c)).astype(np.float32)
    y = np.eye(classes, dtype=np.float32)[rng.integers(0, classes, n)]
    return x, y


def test_bn_dropout_net_matches_microbatched_oracle():
    """Round-4 verdict item #2's done criterion: a BN+dropout conv net
    trains under PipelineParallelWrapper matching the serial oracle
    elementwise — params AND running statistics."""
    rng = np.random.default_rng(0)
    x, y = _batch(rng)

    ref = _bn_dropout_conv_net()
    exp_params, exp_state, exp_loss = _mln_oracle_step(ref, x, y,
                                                       n_micro=3)

    net = _bn_dropout_conv_net()
    pw = PipelineParallelWrapper(net, n_micro=3, mesh=_stage_mesh(3))
    loss = pw.fit_batch(DataSet(x, y))
    pw.write_back()
    np.testing.assert_allclose(loss, exp_loss, rtol=1e-5)
    _assert_tree_close(net.params, exp_params)
    _assert_tree_close(net.state, exp_state, msg="state:")


def test_bn_state_updates_in_micro_order_across_steps():
    """Running statistics must advance per microbatch per step (decay
    applied M times per batch), matching the oracle over several
    steps."""
    rng = np.random.default_rng(3)
    x, y = _batch(rng)

    ref = _bn_dropout_conv_net(seed=11)
    net = _bn_dropout_conv_net(seed=11)
    pw = PipelineParallelWrapper(net, n_micro=2, mesh=_stage_mesh(2),
                                 n_stages=2)
    for it in range(3):
        exp_params, exp_state, _ = _mln_oracle_step(ref, x, y,
                                                    n_micro=2, it=it)
        ref.params = jax.tree_util.tree_map(jnp.asarray, exp_params)
        ref.state = jax.tree_util.tree_map(jnp.asarray, exp_state)
        pw.fit_batch(DataSet(x, y))
    pw.write_back()
    _assert_tree_close(net.state, ref.state, msg="state:")
    # NOTE: multi-step parameter equality needs opt-state threading in
    # the oracle; Sgd is stateless so params must match too
    _assert_tree_close(net.params, ref.params)


def _reg_mixed_updater_net(seed=13):
    """L1+L2 on one layer, WeightDecay on another, a per-layer updater
    override, and CLIP gradient normalization — the whole solver
    path."""
    from deeplearning4j_tpu.conf.layers import GradientNormalization

    conf = (NeuralNetConfiguration.builder()
            .seed(seed).updater(Adam(learning_rate=0.01))
            .weight_init(WeightInit.XAVIER)
            .list()
            .layer(DenseLayer(n_out=14, activation=Activation.TANH,
                              regularization=(
                                  L2Regularization(l2=1e-2),
                                  L1Regularization(l1=1e-3))))
            .layer(DenseLayer(n_out=10, activation=Activation.TANH,
                              regularization=(WeightDecay(coeff=1e-2),),
                              updater=Nesterovs(learning_rate=0.05,
                                                momentum=0.9)))
            .layer(DenseLayer(
                n_out=12, activation=Activation.TANH,
                gradient_normalization=GradientNormalization
                .CLIP_L2_PER_LAYER,
                gradient_normalization_threshold=0.5))
            .layer(OutputLayer(n_out=3, activation=Activation.SOFTMAX,
                               loss_fn=LossMCXENT(),
                               regularization=(
                                   L2Regularization(l2=1e-2),)))
            .set_input_type(InputType.feed_forward(16))
            .build())
    return MultiLayerNetwork(conf).init()


def test_regularization_and_per_layer_updaters_match_oracle():
    """v1 refused l1/l2/weight-decay, per-layer updaters and gradient
    normalization; v2 routes the flat stage packing through the real
    solver path — pinned against the oracle elementwise (and against
    plain fit_batch, which is equivalent here: no BN/dropout)."""
    rng = np.random.default_rng(5)
    x = rng.normal(size=(8, 16)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 8)]

    ref = _reg_mixed_updater_net()
    exp_params, _, exp_loss = _mln_oracle_step(ref, x, y, n_micro=2)
    plain = _reg_mixed_updater_net()
    plain_loss = plain.fit_batch(DataSet(x, y))

    net = _reg_mixed_updater_net()
    pw = PipelineParallelWrapper(net, n_micro=2, mesh=_stage_mesh(3))
    loss = pw.fit_batch(DataSet(x, y))
    pw.write_back()
    np.testing.assert_allclose(loss, exp_loss, rtol=1e-5)
    np.testing.assert_allclose(loss, plain_loss, rtol=1e-5)
    _assert_tree_close(net.params, exp_params)
    _assert_tree_close(net.params, dict(plain.params), rtol=1e-4,
                       atol=1e-5)


# --------------------------------------------------------------------------
# ComputationGraph under the wrapper
# --------------------------------------------------------------------------


def _transformer(seed=21, n_layers=2):
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.zoo.graphs import TransformerEncoder

    model = TransformerEncoder(
        num_classes=3, embed_dim=16, n_heads=2, n_layers=n_layers,
        max_len=12, seed=seed, updater=Sgd(learning_rate=0.05))
    return ComputationGraph(model.conf()).init()


def _cg_oracle_step(net, feats, labels, n_micro, it=0, ep=0):
    """Microbatched serial oracle for a single-output CG, mirroring the
    wrapper's vertex-topo rng fold."""
    from deeplearning4j_tpu.optimize import solver

    conf = net.conf
    vmap = net._vmap
    topo = net._topo
    out_name = conf.network_outputs[0]
    out_spec = vmap[out_name]
    params = jax.tree_util.tree_map(jnp.asarray, dict(net.params))
    state0 = jax.tree_util.tree_map(jnp.asarray, dict(net.state))
    base = jax.random.PRNGKey(conf.seed)
    step_key = jax.random.fold_in(base, it)
    M = n_micro
    f_micro = [f.reshape((M, -1) + f.shape[1:]) for f in feats]
    y_micro = labels.reshape((M, -1) + labels.shape[1:])
    topo_index = {n: i for i, n in enumerate(topo)}

    def loss_fn(p):
        cur = {k: dict(v) for k, v in state0.items()}
        total = 0.0
        for m in range(M):
            rng_m = jax.random.fold_in(step_key, m)
            acts = {n: jnp.asarray(f[m])
                    for n, f in zip(conf.network_inputs, f_micro)}
            for n in topo:
                if n == out_name:
                    continue
                spec = vmap[n]
                xs = [acts[src] for src in spec.inputs]
                vrng = jax.random.fold_in(rng_m, topo_index[n])
                yv, s2 = spec.vertex.forward(
                    p.get(n, {}), cur.get(n, {}), xs, train=True,
                    rng=vrng)
                acts[n] = yv
                if n in cur:
                    cur[n] = s2
            total = total + out_spec.vertex.score(
                p.get(out_name, {}), acts[out_spec.inputs[0]],
                jnp.asarray(y_micro[m]), None)
        loss = total / M + net._regularization_score(p)
        return loss, cur

    (loss, new_state), grads = jax.value_and_grad(
        loss_fn, has_aux=True)(params)
    new_params = {}
    for k in params:
        v = vmap[k].vertex
        layer_conf = getattr(v, "layer", None) or v
        upd = net._updater_for(k)
        lr = upd.current_lr(np.float32(it), np.float32(ep))
        opt = {pk: upd.init_state(pv) for pk, pv in params[k].items()}
        g = solver.normalize_layer_gradients(layer_conf, grads[k])
        new_params[k], _ = solver.apply_updater_to_layer(
            layer_conf, upd, params[k], g, opt, lr, np.float32(it),
            np.float32(ep))
    return new_params, new_state, float(loss)


def test_transformer_graph_matches_microbatched_oracle():
    """The verdict's second done criterion: the zoo TransformerEncoder
    (a ComputationGraph — LN/MHA/FFN blocks with residual skips, i.e.
    real crossing sets) trains under PipelineParallelWrapper matching
    the serial oracle elementwise."""
    rng = np.random.default_rng(8)
    x = rng.normal(size=(8, 12, 16)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 8)]

    ref = _transformer()
    exp_params, _, exp_loss = _cg_oracle_step(ref, [x], y, n_micro=2)

    net = _transformer()
    pw = PipelineParallelWrapper(net, n_micro=2, mesh=_stage_mesh(4))
    loss = pw.fit_batch(DataSet(x, y))
    pw.write_back()
    np.testing.assert_allclose(loss, exp_loss, rtol=1e-5)
    _assert_tree_close(net.params, exp_params, rtol=2e-4, atol=2e-5)


def test_transformer_graph_trains_multi_step():
    net = _transformer(seed=31)
    pw = PipelineParallelWrapper(net, n_micro=2, mesh=_stage_mesh(4))
    rng = np.random.default_rng(31)
    x = rng.normal(size=(8, 12, 16)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 8)]
    first = pw.fit_batch(DataSet(x, y))
    for _ in range(15):
        loss = pw.fit_batch(DataSet(x, y))
    assert np.isfinite(loss) and loss < first


def test_graph_refusals():
    """CG-specific v2 refusals: multi-output graphs, MoE aux layers."""
    from deeplearning4j_tpu.conf.layers_moe import MoELayer
    from deeplearning4j_tpu.nn.graph import ComputationGraph

    g = (NeuralNetConfiguration.builder()
         .seed(1).updater(Sgd(learning_rate=0.1))
         .weight_init(WeightInit.XAVIER)
         .graph_builder()
         .add_inputs("in")
         .set_input_types(InputType.recurrent(8, timesteps=6)))
    g.add_layer("moe", MoELayer(n_experts=2, d_hidden=16), "in")
    from deeplearning4j_tpu.conf.layers_rnn import RnnOutputLayer

    g.add_layer("out", RnnOutputLayer(n_out=3,
                                      activation=Activation.SOFTMAX,
                                      loss_fn=LossMCXENT()), "moe")
    g.set_outputs("out")
    net = ComputationGraph(g.build()).init()
    with pytest.raises(ValueError, match="auxiliary losses"):
        PipelineParallelWrapper(net, n_micro=2, mesh=_stage_mesh(2))


# --------------------------------------------------------------------------
# 1F1B schedule
# --------------------------------------------------------------------------


def test_1f1b_tables_invariants():
    from deeplearning4j_tpu.parallel.pipeline import _one_f1b_tables

    for S, M in ((2, 4), (3, 5), (4, 8), (4, 3), (1, 4), (5, 16)):
        fwd, bwd, total = _one_f1b_tables(S, M)
        # every micro forwarded and backwarded exactly once per stage
        for s in range(S):
            assert sorted(m for m in fwd[s] if m >= 0) == list(range(M))
            assert sorted(m for m in bwd[s] if m >= 0) == list(range(M))
        # dependencies: fwd consumes upstream fwd from an EARLIER slot,
        # bwd consumes downstream bwd from an earlier slot (head: own
        # fwd same slot allowed), bwd after own fwd
        slot_f = {(s, m): t for s in range(S)
                  for t, m in enumerate(fwd[s]) if m >= 0}
        slot_b = {(s, m): t for s in range(S)
                  for t, m in enumerate(bwd[s]) if m >= 0}
        for s in range(S):
            for m in range(M):
                if s > 0:
                    assert slot_f[(s - 1, m)] < slot_f[(s, m)]
                if s < S - 1:
                    assert slot_b[(s + 1, m)] < slot_b[(s, m)]
                assert slot_f[(s, m)] <= slot_b[(s, m)]
        # the MEMORY claim: in-flight (forwarded, not yet backwarded)
        # micros at stage s never exceed S - s
        for s in range(S):
            for t in range(total):
                inflight = sum(
                    1 for m in range(M)
                    if slot_f[(s, m)] <= t < slot_b[(s, m)])
                assert inflight <= S - s, (S, M, s, t, inflight)
        # and the schedule is never longer than GPipe's fwd+bwd sweep
        assert total <= 2 * (S + M - 1), (S, M, total)


@pytest.mark.parametrize("build,mkbatch,micros", [
    (_bn_dropout_conv_net,
     lambda rng: _batch(rng), 3),
    (_reg_mixed_updater_net,
     lambda rng: (rng.normal(size=(8, 16)).astype(np.float32),
                  np.eye(3, dtype=np.float32)[rng.integers(0, 3, 8)]),
     4),
])
def test_1f1b_matches_gpipe(build, mkbatch, micros):
    """Gradient equality between schedules: one step under
    schedule='1f1b' == the same step under 'gpipe', elementwise (both
    run the identical per-micro math; only accumulation order and
    activation liveness differ)."""
    rng = np.random.default_rng(17)
    x, y = mkbatch(rng)

    nets = {}
    for sched in ("gpipe", "1f1b"):
        net = build()
        pw = PipelineParallelWrapper(net, n_micro=micros,
                                     mesh=_stage_mesh(3), n_stages=3,
                                     schedule=sched)
        loss = pw.fit_batch(DataSet(x, y))
        pw.write_back()
        nets[sched] = (net, loss)
    np.testing.assert_allclose(nets["1f1b"][1], nets["gpipe"][1],
                               rtol=1e-5)
    _assert_tree_close(dict(nets["1f1b"][0].params),
                       dict(nets["gpipe"][0].params))
    _assert_tree_close(dict(nets["1f1b"][0].state),
                       dict(nets["gpipe"][0].state), msg="state:")


def test_1f1b_transformer_graph_matches_gpipe():
    rng = np.random.default_rng(23)
    x = rng.normal(size=(12, 12, 16)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 12)]
    nets = {}
    for sched in ("gpipe", "1f1b"):
        net = _transformer(seed=41)
        pw = PipelineParallelWrapper(net, n_micro=3,
                                     mesh=_stage_mesh(4),
                                     schedule=sched)
        loss = pw.fit_batch(DataSet(x, y))
        pw.write_back()
        nets[sched] = (net, loss)
    np.testing.assert_allclose(nets["1f1b"][1], nets["gpipe"][1],
                               rtol=1e-5)
    _assert_tree_close(dict(nets["1f1b"][0].params),
                       dict(nets["gpipe"][0].params), rtol=2e-4,
                       atol=2e-5)


def _deep_mlp_net(seed=19):
    """>= 4 stage-able layers so a 4-stage mesh partitions one layer per
    stage — the O(S) liveness claim is only exercised when every stage
    actually holds work (a 3-layer net under 4 stages refuses)."""
    conf = (NeuralNetConfiguration.builder()
            .seed(seed).updater(Sgd(learning_rate=0.05))
            .weight_init(WeightInit.XAVIER)
            .list()
            .layer(DenseLayer(n_out=24, activation=Activation.TANH))
            .layer(DenseLayer(n_out=20, activation=Activation.TANH))
            .layer(DenseLayer(n_out=18, activation=Activation.TANH))
            .layer(DenseLayer(n_out=12, activation=Activation.TANH))
            .layer(OutputLayer(n_out=3, activation=Activation.SOFTMAX,
                               loss_fn=LossMCXENT()))
            .set_input_type(InputType.feed_forward(16))
            .build())
    return MultiLayerNetwork(conf).init()


def test_1f1b_activation_liveness_bounded():
    """The schedule's point: 1F1B's live activation memory is O(S)
    stage-inputs (stash + rings), while GPipe's AD saves residuals for
    every scan step — so growing M must grow GPipe's temp memory
    linearly while 1F1B's stays ~flat (rings/stash are [S, a_max]
    regardless of M)."""
    def temp_bytes(schedule, micros):
        rng = np.random.default_rng(2)
        x = rng.normal(size=(4 * micros, 16)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 4 * micros)]
        net = _deep_mlp_net()
        pw = PipelineParallelWrapper(net, n_micro=micros,
                                     mesh=_stage_mesh(4),
                                     schedule=schedule)
        pw.fit_batch(DataSet(x, y))
        lowered = pw._step.lower(
            pw._stacked, pw._stacked_state, pw._stacked_opt,
            pw._out_params, pw._out_opt,
            jnp.asarray(x.reshape((micros, 4, 16))),
            jnp.asarray(y.reshape((micros, 4, 3))),
            np.float32(1), np.float32(0))
        mem = lowered.compile().memory_analysis()
        if mem is None:
            pytest.skip("memory_analysis unavailable on this backend")
        return mem.temp_size_in_bytes

    g_small, g_big = temp_bytes("gpipe", 4), temp_bytes("gpipe", 16)
    f_small, f_big = temp_bytes("1f1b", 4), temp_bytes("1f1b", 16)
    # gpipe residuals grow with M; 1f1b bounded by the S-slot rings
    assert g_big > 1.5 * g_small, (g_small, g_big)
    assert f_big < 1.25 * f_small + 4096 * 16 * 4, (f_small, f_big)
    assert f_big < g_big, (f_big, g_big)
