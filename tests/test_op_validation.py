"""Per-op validation via the OpValidation harness (reference
``org.nd4j.autodiff.validation.OpValidation`` — forward + gradient per op,
with coverage accounting)."""

import numpy as np
import pytest

from deeplearning4j_tpu.samediff.core import SameDiff
from deeplearning4j_tpu.samediff.validation import (
    TestCase,
    coverage_report,
    validate,
)


def _case(build, inputs, expected, **kw):
    sd = SameDiff.create()
    build(sd)
    return TestCase(sd, inputs, expected, **kw)


def test_matmul_and_bias():
    sd = SameDiff.create()
    a = sd.placeholder("a", shape=(2, 3), dtype="float64")
    b = sd.placeholder("b", shape=(3, 2), dtype="float64")
    y = sd.math.mmul(a, b, name="y")
    av = np.arange(6, dtype=np.float64).reshape(2, 3)
    bv = np.arange(6, dtype=np.float64).reshape(3, 2) * 0.5
    validate(TestCase(sd, {"a": av, "b": bv}, {"y": av @ bv}))


def test_elementwise_family():
    sd = SameDiff.create()
    x = sd.placeholder("x", shape=(4,), dtype="float64")
    y = sd.placeholder("y", shape=(4,), dtype="float64")
    s = (x * y + x - y / 2.0).rename("s")
    xv = np.asarray([0.5, -1.0, 2.0, 3.0])
    yv = np.asarray([1.0, 2.0, -0.5, 0.25])
    validate(TestCase(sd, {"x": xv, "y": yv},
                      {"s": xv * yv + xv - yv / 2.0}))


def test_activations_and_reductions():
    sd = SameDiff.create()
    x = sd.placeholder("x", shape=(3, 4), dtype="float64")
    h = sd.nn.tanh(x)
    m = sd.math.mean(h, dims=(1,), name="m")
    xv = np.linspace(-2, 2, 12).reshape(3, 4)
    validate(TestCase(sd, {"x": xv}, {"m": np.tanh(xv).mean(1)}))


def test_softmax_gradient():
    sd = SameDiff.create()
    x = sd.placeholder("x", shape=(2, 5), dtype="float64")
    p = sd.nn.softmax(x, name="p")
    xv = np.random.default_rng(0).normal(size=(2, 5))
    e = np.exp(xv - xv.max(1, keepdims=True))
    validate(TestCase(sd, {"x": xv}, {"p": e / e.sum(1, keepdims=True)}))


def test_conv2d_forward_and_grad():
    sd = SameDiff.create()
    x = sd.placeholder("x", shape=(1, 4, 4, 2), dtype="float64")
    w = sd.placeholder("w", shape=(2, 2, 2, 3), dtype="float64")
    b = sd.constant(np.zeros(3))
    y = sd.cnn.conv2d(x, w, b, strides=(1, 1), padding="VALID", name="y")
    rng = np.random.default_rng(1)
    xv = rng.normal(size=(1, 4, 4, 2))
    wv = rng.normal(size=(2, 2, 2, 3)) * 0.5
    import jax

    want = np.asarray(jax.lax.conv_general_dilated(
        xv, wv, (1, 1), "VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC")))
    validate(TestCase(sd, {"x": xv, "w": wv}, {"y": want},
                      max_rel_error=1e-3))


def test_layer_norm_grad():
    sd = SameDiff.create()
    x = sd.placeholder("x", shape=(2, 6), dtype="float64")
    g = sd.constant(np.ones(6))
    b = sd.constant(np.zeros(6))
    y = sd.nn.layerNorm(x, g, b, name="y")
    xv = np.random.default_rng(2).normal(size=(2, 6)) * 3
    mu = xv.mean(-1, keepdims=True)
    var = xv.var(-1, keepdims=True)
    validate(TestCase(sd, {"x": xv}, {"y": (xv - mu) / np.sqrt(var + 1e-5)},
                      max_rel_error=1e-3))


def test_coverage_accounting_floor():
    """Reference parity: op validation keeps a coverage ledger. Runs its
    own case so the ledger check is self-contained (independent of test
    order / xdist sharding)."""
    sd = SameDiff()
    x = sd.placeholder("x", (2, 3))
    sd.math.mul(x, x, name="y")
    xv = np.random.default_rng(3).normal(size=(2, 3))
    validate(TestCase(sd, {"x": xv}, {"y": xv * xv}))
    rep = coverage_report()
    assert rep["registered"] > 150  # the registry is substantial
    assert rep["validated"] >= 1    # the case above recorded its ops
    assert isinstance(rep["missing"], list)


# --------------------------------------------------------------------------
# broad registry sweep (reference: OpValidation coverage accounting fails CI
# for untested ops; this sweep pushes per-op forward+gradient coverage)
# --------------------------------------------------------------------------

def _seed(op: str) -> int:
    import zlib

    return zlib.crc32(op.encode())  # stable across runs (hash() is not)


# (registry op, numpy oracle, (lo, hi) input range, grad_checked)
_UNARY_SWEEP = [
    ("math.exp", np.exp, (-1, 1), True),
    ("math.expm1", np.expm1, (-1, 1), True),
    ("math.exp2", np.exp2, (-1, 1), True),
    ("math.log", np.log, (0.5, 2.0), True),
    ("math.log1p", np.log1p, (-0.4, 1.0), True),
    ("math.log2", np.log2, (0.5, 2.0), True),
    ("math.log10", np.log10, (0.5, 2.0), True),
    ("math.sqrt", np.sqrt, (0.5, 2.0), True),
    ("math.rsqrt", lambda x: 1.0 / np.sqrt(x), (0.5, 2.0), True),
    ("math.square", np.square, (-2, 2), True),
    ("math.reciprocal", np.reciprocal, (0.5, 2.0), True),
    ("math.abs", np.abs, (0.3, 2.0), True),
    ("math.neg", np.negative, (-2, 2), True),
    ("math.sin", np.sin, (-1, 1), True),
    ("math.cos", np.cos, (-1, 1), True),
    ("math.tan", np.tan, (-1, 1), True),
    ("math.asin", np.arcsin, (-0.8, 0.8), True),
    ("math.acos", np.arccos, (-0.8, 0.8), True),
    ("math.atan", np.arctan, (-2, 2), True),
    ("math.sinh", np.sinh, (-1, 1), True),
    ("math.cosh", np.cosh, (-1, 1), True),
    ("math.asinh", np.arcsinh, (-2, 2), True),
    ("math.acosh", np.arccosh, (1.5, 3.0), True),
    ("math.atanh", np.arctanh, (-0.8, 0.8), True),
    ("math.erf", None, (-1.5, 1.5), True),     # oracle via math.erf below
    ("math.erfc", None, (-1.5, 1.5), True),
    ("math.floor", np.floor, (0.1, 0.9), False),
    ("math.ceil", np.ceil, (0.1, 0.9), False),
    ("math.round", np.round, (0.1, 0.4), False),
    ("math.sign", np.sign, (0.3, 2.0), False),
    ("math.isnan", np.isnan, (-1, 1), False),
    ("math.isinf", np.isinf, (-1, 1), False),
    ("math.isfinite", np.isfinite, (-1, 1), False),
]


def _run_unary(op, oracle, rng_range, check_grad):
    import math as _m

    if oracle is None:
        base = {"math.erf": _m.erf, "math.erfc": _m.erfc}[op]
        oracle = np.vectorize(base)
    rng = np.random.default_rng(_seed(op))
    xv = rng.uniform(*rng_range, size=(2, 3))
    sd = SameDiff()
    x = sd.placeholder("x", (2, 3))
    sd._op(op, [x], name="y")
    validate(TestCase(sd, {"x": xv}, {"y": oracle(xv)},
                      grad_wrt=["x"] if check_grad else []))


@pytest.mark.parametrize("op,oracle,rng_range,check_grad", _UNARY_SWEEP,
                         ids=[c[0] for c in _UNARY_SWEEP])
def test_unary_sweep(op, oracle, rng_range, check_grad):
    _run_unary(op, oracle, rng_range, check_grad)


_BINARY_SWEEP = [
    ("math.add", np.add, True),
    ("math.sub", np.subtract, True),
    ("math.mul", np.multiply, True),
    ("math.div", np.divide, True),
    ("math.pow", np.power, True),
    ("math.maximum", np.maximum, True),
    ("math.minimum", np.minimum, True),
    ("math.atan2", np.arctan2, True),
    ("math.squared_difference", lambda a, b: (a - b) ** 2, True),
    ("math.rsub", lambda a, b: b - a, True),
    ("math.rdiv", lambda a, b: b / a, True),
    ("math.mod", np.mod, False),
    ("math.floordiv", np.floor_divide, False),
    ("math.gt", np.greater, False),
    ("math.gte", np.greater_equal, False),
    ("math.lt", np.less, False),
    ("math.lte", np.less_equal, False),
    ("math.eq", np.equal, False),
    ("math.neq", np.not_equal, False),
]


def _run_binary(op, oracle, check_grad):
    rng = np.random.default_rng(_seed(op))
    av = rng.uniform(0.5, 2.0, size=(2, 3))
    bv = rng.uniform(0.6, 1.9, size=(2, 3))
    sd = SameDiff()
    a = sd.placeholder("a", (2, 3))
    b = sd.placeholder("b", (2, 3))
    sd._op(op, [a, b], name="y")
    validate(TestCase(sd, {"a": av, "b": bv}, {"y": oracle(av, bv)},
                      grad_wrt=["a", "b"] if check_grad else []))


@pytest.mark.parametrize("op,oracle,check_grad", _BINARY_SWEEP,
                         ids=[c[0] for c in _BINARY_SWEEP])
def test_binary_sweep(op, oracle, check_grad):
    _run_binary(op, oracle, check_grad)


_REDUCE_SWEEP = [
    ("reduce.sum", lambda x, ax, kd: x.sum(axis=ax, keepdims=kd), True),
    ("reduce.mean", lambda x, ax, kd: x.mean(axis=ax, keepdims=kd), True),
    ("reduce.prod", lambda x, ax, kd: x.prod(axis=ax, keepdims=kd), True),
    ("reduce.amax", lambda x, ax, kd: x.max(axis=ax, keepdims=kd), False),
    ("reduce.amin", lambda x, ax, kd: x.min(axis=ax, keepdims=kd), False),
    ("reduce.std", lambda x, ax, kd: x.std(axis=ax, keepdims=kd), True),
    ("reduce.var", lambda x, ax, kd: x.var(axis=ax, keepdims=kd), True),
    ("reduce.norm1", lambda x, ax, kd: np.abs(x).sum(axis=ax, keepdims=kd),
     True),
    ("reduce.norm2",
     lambda x, ax, kd: np.sqrt((x * x).sum(axis=ax, keepdims=kd)), True),
    ("reduce.normmax",
     lambda x, ax, kd: np.abs(x).max(axis=ax, keepdims=kd), False),
    ("reduce.countNonZero",
     lambda x, ax, kd: (x != 0).sum(axis=ax, keepdims=kd), False),
]


def _run_reduce(op, oracle, check_grad, axis, keepdims):
    rng = np.random.default_rng(_seed(op))
    xv = rng.uniform(0.5, 2.0, size=(3, 4))
    sd = SameDiff()
    x = sd.placeholder("x", (3, 4))
    sd._op(op, [x], name="y", axis=axis, keepdims=keepdims)
    validate(TestCase(sd, {"x": xv},
                      {"y": oracle(xv, axis, keepdims)},
                      grad_wrt=["x"] if check_grad else []))


@pytest.mark.parametrize("op,oracle,check_grad", _REDUCE_SWEEP,
                         ids=[c[0] for c in _REDUCE_SWEEP])
@pytest.mark.parametrize("axis,keepdims", [((1,), False), ((0, 1), True)])
def test_reduce_sweep(op, oracle, check_grad, axis, keepdims):
    _run_reduce(op, oracle, check_grad, axis, keepdims)


def test_shape_op_sweep(rng):
    """Forward-only validation of the structural ops (reference shape
    function tests)."""
    xv = rng.normal(size=(2, 3, 4)).astype(np.float64)
    sd = SameDiff()
    x = sd.placeholder("x", (2, 3, 4))
    sd._op("reshape", [x], name="r", shape=(6, 4))
    sd._op("permute", [x], name="p", dims=(2, 0, 1))
    sd._op("expand_dims", [x], name="e", axis=1)
    sd._op("tile", [x], name="t", reps=(1, 2, 1))
    sd._op("squeeze", [sd._op("expand_dims", [x], name="e2", axis=0)[0]],
           name="sq", axis=(0,))
    sd._op("strided_slice", [x], name="ss", begin=(0, 1, 0),
           end=(2, 3, 4), strides=(1, 1, 2))
    sd._op("split", [x], name="sp", n_out=2, axis=2, num=2)
    sd._op("stack", [x, x], name="st", axis=0)
    sd._op("unstack", [x], name="us", n_out=2, axis=0, num=2)
    sd._op("cast", [x], name="c", dtype="float32")
    validate(TestCase(sd, {"x": xv}, {
        "r": xv.reshape(6, 4),
        "p": xv.transpose(2, 0, 1),
        "e": xv[:, None],
        "t": np.tile(xv, (1, 2, 1)),
        "sq": xv,
        "ss": xv[0:2, 1:3, ::2],
        "sp:0": xv[:, :, :2], "sp:1": xv[:, :, 2:],
        "st": np.stack([xv, xv]),
        "us:0": xv[0], "us:1": xv[1],
        "c": xv.astype(np.float32),
    }, grad_wrt=[]))


def test_coverage_after_sweep():
    """Self-contained (isolation/xdist-safe): runs the whole sweep
    forward-only in-process, then asserts the ledger floor."""
    for op, oracle, rng_range, _ in _UNARY_SWEEP:
        _run_unary(op, oracle, rng_range, check_grad=False)
    for op, oracle, _ in _BINARY_SWEEP:
        _run_binary(op, oracle, check_grad=False)
    for op, oracle, _ in _REDUCE_SWEEP:
        _run_reduce(op, oracle, False, (1,), False)
    rep = coverage_report()
    assert rep["validated"] >= 60, rep["validated"]


# --------------------------------------------------------------------------
# nn / cnn / structural sweep (activation oracles in numpy; conv/pool
# against explicit loops)
# --------------------------------------------------------------------------

def _np_sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


_NN_SWEEP = [
    ("nn.relu", lambda x: np.maximum(x, 0.0), False),  # kink at 0
    ("nn.relu6", lambda x: np.clip(x, 0.0, 6.0), False),
    ("nn.elu", lambda x: np.where(x > 0, x, np.exp(x) - 1.0), True),
    ("nn.sigmoid", _np_sigmoid, True),
    ("nn.tanh", np.tanh, True),
    ("nn.softplus", lambda x: np.log1p(np.exp(x)), True),
    ("nn.softsign", lambda x: x / (1.0 + np.abs(x)), True),
    ("nn.swish", lambda x: x * _np_sigmoid(x), True),
    ("nn.silu", lambda x: x * _np_sigmoid(x), True),
    ("nn.gelu", None, True),   # jax default gelu is the tanh approximation
    ("nn.mish", lambda x: x * np.tanh(np.log1p(np.exp(x))), True),
    ("nn.selu", lambda x: 1.0507009873554805 * np.where(
        x > 0, x, 1.6732632423543772 * (np.exp(x) - 1.0)), True),
]


def _run_nn_unary(op, oracle, check_grad):
    if oracle is None:  # tanh-approx gelu
        def oracle(x):
            return 0.5 * x * (1.0 + np.tanh(
                np.sqrt(2.0 / np.pi) * (x + 0.044715 * x ** 3)))
    rng = np.random.default_rng(_seed(op))
    xv = rng.uniform(0.3, 2.0, size=(2, 3)) * np.where(
        rng.random((2, 3)) < 0.5, -1.0, 1.0)  # both signs, away from 0
    sd = SameDiff()
    x = sd.placeholder("x", (2, 3))
    sd._op(op, [x], name="y")
    validate(TestCase(sd, {"x": xv}, {"y": oracle(xv)},
                      grad_wrt=["x"] if check_grad else [],
                      max_rel_error=1e-3))


@pytest.mark.parametrize("op,oracle,check_grad", _NN_SWEEP,
                         ids=[c[0] for c in _NN_SWEEP])
def test_nn_unary_sweep(op, oracle, check_grad):
    _run_nn_unary(op, oracle, check_grad)


def test_nn_composite_sweep(rng):
    xv = rng.normal(size=(4, 6))
    wv = rng.normal(size=(6, 3))
    bv = rng.normal(size=(3,))
    gv = rng.normal(size=(6,)) + 1.0
    sd = SameDiff()
    x = sd.placeholder("x", (4, 6))
    w = sd.constant(wv, name="w")
    b3 = sd.constant(bv, name="b3")
    g = sd.constant(gv, name="g")
    b6 = sd.constant(np.zeros(6), name="b6")
    sd._op("nn.linear", [x, w, b3], name="lin")
    sd._op("nn.biasAdd", [sd._op("math.mul", [x, x])[0], b6], name="ba")
    sd._op("nn.softmax", [x], name="sm", axis=-1)
    sd._op("nn.logSoftmax", [x], name="lsm", axis=-1)
    sd._op("nn.leakyRelu", [x], name="lr", alpha=0.1)
    sd._op("nn.layerNorm", [x, g, b6], name="ln", axis=-1, eps=1e-5)
    sd._op("nn.pad", [x], name="pd", paddings=((0, 0), (1, 2)),
           mode="constant", value=0.0)

    e = np.exp(xv - xv.max(-1, keepdims=True))
    sm = e / e.sum(-1, keepdims=True)
    mu = xv.mean(-1, keepdims=True)
    var = xv.var(-1, keepdims=True)
    validate(TestCase(sd, {"x": xv}, {
        "lin": xv @ wv + bv,
        "ba": xv * xv,
        "sm": sm,
        "lsm": np.log(sm),
        "lr": np.where(xv > 0, xv, 0.1 * xv),
        "ln": gv * (xv - mu) / np.sqrt(var + 1e-5),
        "pd": np.pad(xv, ((0, 0), (1, 2))),
    }, max_rel_error=1e-3))


def test_cnn_ops_sweep(rng):
    """conv2d / pooling / depthwise against explicit numpy loops."""
    x = rng.normal(size=(2, 6, 6, 3))
    k = rng.normal(size=(3, 3, 3, 4), scale=0.5)
    sd = SameDiff()
    xin = sd.placeholder("x", (2, 6, 6, 3))
    kc = sd.placeholder("k", (3, 3, 3, 4))     # placeholders stay f64 in
    zero = sd.placeholder("b0", (4,))          # the x64 validate context
    sd._op("cnn.conv2d", [xin, kc, zero], name="cv", strides=(1, 1),
           padding="VALID", dilation=(1, 1))
    sd._op("cnn.maxPooling2d", [xin], name="mp", k=(2, 2), s=(2, 2),
           padding="VALID")
    sd._op("cnn.avgPooling2d", [xin], name="ap", k=(2, 2), s=(2, 2),
           padding="VALID")

    conv = np.zeros((2, 4, 4, 4))
    for i in range(4):
        for j in range(4):
            patch = x[:, i:i + 3, j:j + 3, :]
            conv[:, i, j, :] = np.einsum("bhwc,hwco->bo", patch, k)
    mp = x.reshape(2, 3, 2, 3, 2, 3).max(axis=(2, 4))
    ap = x.reshape(2, 3, 2, 3, 2, 3).mean(axis=(2, 4))
    validate(TestCase(sd, {"x": x, "k": k, "b0": np.zeros(4)},
                      {"cv": conv, "mp": mp, "ap": ap},
                      grad_wrt=[], max_rel_error=1e-3))


def test_coverage_final_floor():
    """With the nn/cnn sweeps the harness-validated slice of the registry
    crosses 90 ops (self-contained like test_coverage_after_sweep)."""
    test_coverage_after_sweep()
    for case in _NN_SWEEP:
        _run_nn_unary(*case)
    r = np.random.default_rng(0)
    test_nn_composite_sweep(r)
    test_cnn_ops_sweep(r)
    rep = coverage_report()
    assert rep["validated"] >= 90, rep["validated"]


# --------------------------------------------------------------------------
# round 2: scatter / gather-nd / segment / linalg / image / bitwise / loss
# sweeps + the STRICT coverage gate (reference: OpValidation fails CI for
# any op without a TestCase)
# --------------------------------------------------------------------------

_SCATTER_SWEEP = [
    ("scatter.update", lambda r, i, u: _np_scatter(r, i, u, "update"), True),
    ("scatter.add", lambda r, i, u: _np_scatter(r, i, u, "add"), True),
    ("scatter.sub", lambda r, i, u: _np_scatter(r, i, u, "sub"), True),
    ("scatter.mul", lambda r, i, u: _np_scatter(r, i, u, "mul"), False),
    ("scatter.div", lambda r, i, u: _np_scatter(r, i, u, "div"), False),
    ("scatter.max", lambda r, i, u: _np_scatter(r, i, u, "max"), False),
    ("scatter.min", lambda r, i, u: _np_scatter(r, i, u, "min"), False),
]


def _np_scatter(ref, idx, upd, kind):
    out = ref.copy()
    for n, i in enumerate(idx):
        if kind == "update":
            out[i] = upd[n]
        elif kind == "add":
            out[i] += upd[n]
        elif kind == "sub":
            out[i] -= upd[n]
        elif kind == "mul":
            out[i] *= upd[n]
        elif kind == "div":
            out[i] /= upd[n]
        elif kind == "max":
            out[i] = np.maximum(out[i], upd[n])
        elif kind == "min":
            out[i] = np.minimum(out[i], upd[n])
    return out


def _run_scatter(op, oracle, check_grad):
    rng = np.random.default_rng(_seed(op))
    ref = rng.uniform(0.5, 2.0, size=(5, 3))
    # unique indices: duplicate-accumulation order matches jnp only for
    # add/sub; uniqueness makes the numpy loop an exact oracle for all
    idx = np.asarray([0, 2, 4], np.int32)
    upd = rng.uniform(0.5, 2.0, size=(3, 3))
    sd = SameDiff()
    r = sd.placeholder("r", (5, 3))
    i = sd.placeholder("i", (3,), dtype="int32")
    u = sd.placeholder("u", (3, 3))
    sd._op(op, [r, i, u], name="y")
    validate(TestCase(sd, {"r": ref, "i": idx, "u": upd},
                      {"y": oracle(ref, idx, upd)},
                      grad_wrt=["r", "u"] if check_grad else []))


@pytest.mark.parametrize("op,oracle,check_grad", _SCATTER_SWEEP,
                         ids=[c[0] for c in _SCATTER_SWEEP])
def test_scatter_sweep(op, oracle, check_grad):
    _run_scatter(op, oracle, check_grad)


def test_scatter_add_duplicate_indices_accumulate():
    sd = SameDiff()
    r = sd.placeholder("r", (4, 2))
    i = sd.placeholder("i", (3,), dtype="int32")
    u = sd.placeholder("u", (3, 2))
    sd.scatter_add(r, i, u).rename("y")
    ref = np.zeros((4, 2))
    upd = np.asarray([[1., 2.], [10., 20.], [100., 200.]])
    out = sd.output({"r": ref, "i": np.asarray([1, 1, 3]), "u": upd}, "y")
    np.testing.assert_allclose(np.asarray(out["y"]),
                               [[0, 0], [11, 22], [0, 0], [100, 200]])


def _run_gather_segment():
    rng = np.random.default_rng(11)
    xv = rng.uniform(0.5, 2.0, size=(3, 4, 5))
    nd_idx = np.asarray([[0, 1], [2, 3]], np.int32)
    data = rng.uniform(0.5, 2.0, size=(6, 3))
    ids = np.asarray([0, 0, 1, 2, 2, 2], np.int32)
    lens = np.asarray([1, 3, 0], np.int32)

    sd = SameDiff()
    x = sd.placeholder("x", (3, 4, 5))
    gi = sd.placeholder("gi", (2, 2), dtype="int32")
    d = sd.placeholder("d", (6, 3))
    sids = sd.placeholder("sids", (6,), dtype="int32")
    ln = sd.placeholder("ln", (3,), dtype="int32")
    sd.gather_nd(x, gi, name="gnd")
    sd.segment_sum(d, sids, 4, name="ssum")
    sd.segment_mean(d, sids, 4, name="smean")
    sd.segment_max(d, sids, 4, name="smax")
    sd.segment_min(d, sids, 4, name="smin")
    sd.segment_prod(d, sids, 4, name="sprod")
    sd.sequence_mask(ln, 4, name="smask")

    seg = {"sum": np.zeros((4, 3)), "prod": np.ones((4, 3)),
           "max": np.full((4, 3), -np.inf),   # jax identities: empty
           "min": np.full((4, 3), np.inf)}    # segments stay +-inf
    cnt = np.zeros(4)
    for n, i in enumerate(ids):
        seg["sum"][i] += data[n]
        seg["prod"][i] *= data[n]
        seg["max"][i] = np.maximum(seg["max"][i], data[n])
        seg["min"][i] = np.minimum(seg["min"][i], data[n])
        cnt[i] += 1
    mean = seg["sum"] / np.maximum(cnt, 1)[:, None]
    validate(TestCase(
        sd, {"x": xv, "gi": nd_idx, "d": data, "sids": ids, "ln": lens},
        {"gnd": xv[[0, 2], [1, 3]],
         "ssum": seg["sum"], "smean": mean, "smax": seg["max"],
         "smin": seg["min"], "sprod": seg["prod"],
         "smask": (np.arange(4) < lens[:, None]).astype(np.float64)},
        grad_wrt=[]))


def test_gather_segment_mask_sweep():
    _run_gather_segment()


def test_segment_sum_gradient():
    sd = SameDiff()
    d = sd.placeholder("d", (4, 2))
    sids = sd.placeholder("sids", (4,), dtype="int32")
    sd.segment_sum(d, sids, 3, name="y")
    rng = np.random.default_rng(12)
    data = rng.uniform(0.5, 2.0, size=(4, 2))
    ids = np.asarray([0, 2, 2, 1], np.int32)
    want = np.zeros((3, 2))
    for n, i in enumerate(ids):
        want[i] += data[n]
    validate(TestCase(sd, {"d": data, "sids": ids}, {"y": want},
                      grad_wrt=["d"]))


def _run_linalg():
    rng = np.random.default_rng(21)
    a = rng.normal(size=(3, 3))
    spd = a @ a.T + 3 * np.eye(3)          # SPD, well-conditioned
    b = rng.normal(size=(3, 2))
    low = np.tril(a) + 3 * np.eye(3)

    sd = SameDiff()
    s = sd.placeholder("s", (3, 3))
    bb = sd.placeholder("b", (3, 2))
    lo = sd.placeholder("lo", (3, 3))
    sd.linalg.cholesky(s, name="chol")
    sd.linalg.det(s, name="det")
    sd.linalg.inv(s, name="inv")
    sd._op("linalg.matrixInverse", [s], name="minv")
    sgn, logabs = sd._op("linalg.slogdet", [s], n_out=2, name="sld")
    sd.linalg.logdet(s, name="logdet")
    sd.linalg.solve(s, bb, name="solve")
    sd.linalg.lstsq(s, bb, name="lstsq")
    sd.linalg.triangularSolve(lo, bb, lower=True, name="tsolve")
    sd.linalg.matrixBandPart(s, 1, 0, name="band")
    sd.linalg.triu(s, name="triu")
    sd.linalg.tril(s, name="tril")
    sd.linalg.diagPart(s, name="dpart")
    sd.linalg.tri(3, 3, 0, dtype="float64", name="tri")
    sd.linalg.eye(3, dtype="float64", name="eye")
    # qr / svd: orthogonal-factor signs are implementation-defined, so
    # validate via reconstruction (q@r == x; u*s@vt == x)
    q, r = sd.linalg.qr(s)
    sd.math.mmul(q, r, name="qr_recon")
    u, sv, vt = sd.linalg.svd(s)
    sd.math.mmul(u * sv.reshape(1, 3), vt, name="svd_recon")

    sgn_v, logabs_v = np.linalg.slogdet(spd)
    validate(TestCase(
        sd, {"s": spd, "b": b, "lo": low},
        {"chol": np.linalg.cholesky(spd),
         "det": np.linalg.det(spd),
         "inv": np.linalg.inv(spd),
         "minv": np.linalg.inv(spd),
         "sld:0": sgn_v, "sld:1": logabs_v,
         "logdet": logabs_v,
         "solve": np.linalg.solve(spd, b),
         "lstsq": np.linalg.lstsq(spd, b, rcond=None)[0],
         "tsolve": np.linalg.solve(low, b),
         "band": np.where(
             (np.arange(3)[:, None] - np.arange(3)[None, :] <= 1)
             & (np.arange(3)[None, :] - np.arange(3)[:, None] <= 0),
             spd, 0.0),
         "triu": np.triu(spd),
         "tril": np.tril(spd),
         "dpart": np.diag(spd),
         "tri": np.tri(3),
         "eye": np.eye(3),
         "qr_recon": spd,
         "svd_recon": spd},
        grad_wrt=[], max_rel_error=1e-3))


def test_linalg_sweep():
    _run_linalg()


def test_linalg_gradients():
    """Gradient checks for the differentiable linalg core (solve /
    cholesky / det on an SPD input)."""
    rng = np.random.default_rng(22)
    a = rng.normal(size=(3, 3))
    spd = a @ a.T + 3 * np.eye(3)
    b = rng.normal(size=(3, 1))
    sd = SameDiff()
    s = sd.placeholder("s", (3, 3))
    bb = sd.placeholder("b", (3, 1))
    sd.linalg.solve(s, bb, name="solve")
    validate(TestCase(sd, {"s": spd, "b": b},
                      {"solve": np.linalg.solve(spd, b)},
                      grad_wrt=["s", "b"], max_rel_error=1e-3))


def _run_image():
    import colorsys

    rng = np.random.default_rng(31)
    img = rng.uniform(0.05, 0.95, size=(1, 4, 4, 3))
    hsv = np.zeros_like(img)
    for i in range(4):
        for j in range(4):
            hsv[0, i, j] = colorsys.rgb_to_hsv(*img[0, i, j])

    sd = SameDiff()
    x = sd.placeholder("x", (1, 4, 4, 3))
    sd.image.rgbToHsv(x, name="hsv")
    sd.image.hsvToRgb(sd.image.rgbToHsv(x), name="rgb_rt")
    sd.image.rgbToGrayscale(x, name="gray")
    sd.image.adjustSaturation(x, 0.5, name="sat")
    sd.image.adjustHue(x, 0.1, name="hue")
    sd.image.flipLeftRight(x, name="flr")
    sd.image.flipUpDown(x, name="fud")
    sd.image.adjustContrast(x, 2.0, name="ctr")
    sd.image.resizeNearest(x, 8, 8, name="rn")
    sd.image.resizeBilinear(x, 4, 4, name="rb")  # identity size
    sd.image.cropAndResize(x, 1, 1, 2, 2, 2, 2, name="car")
    sd.image.extractImagePatches(x, 2, 2, 2, 2, "VALID", name="pat")

    sat = np.zeros_like(img)
    hue = np.zeros_like(img)
    for i in range(4):
        for j in range(4):
            h, s, v = colorsys.rgb_to_hsv(*img[0, i, j])
            sat[0, i, j] = colorsys.hsv_to_rgb(h, s * 0.5, v)
            hue[0, i, j] = colorsys.hsv_to_rgb((h + 0.1) % 1.0, s, v)
    mean = img.mean(axis=(1, 2), keepdims=True)
    patches = np.zeros((1, 2, 2, 12))
    for i in range(2):
        for j in range(2):
            patches[0, i, j] = img[0, 2 * i:2 * i + 2,
                                   2 * j:2 * j + 2, :].reshape(-1)
    validate(TestCase(
        sd, {"x": img},
        {"hsv": hsv, "rgb_rt": img,
         "gray": (img * [0.2989, 0.5870, 0.1140]).sum(-1, keepdims=True),
         "sat": sat, "hue": hue,
         "flr": img[:, :, ::-1], "fud": img[:, ::-1],
         "ctr": (img - mean) * 2.0 + mean,
         "rn": img.repeat(2, axis=1).repeat(2, axis=2),
         "rb": img,
         "car": img[:, 1:3, 1:3, :],
         "pat": patches},
        grad_wrt=[], max_rel_error=1e-3))


def test_image_sweep():
    _run_image()


def _run_nms():
    boxes = np.asarray([[0, 0, 2, 2], [0.1, 0.1, 2, 2], [3, 3, 4, 4],
                        [0, 0, 0.5, 0.5]], np.float64)
    scores = np.asarray([0.9, 0.8, 0.7, 0.6], np.float64)
    sd = SameDiff()
    b = sd.placeholder("b", (4, 4))
    s = sd.placeholder("s", (4,))
    sd.image.nonMaxSuppression(b, s, 3, iou_threshold=0.5, name="keep")
    # box1 overlaps box0 (iou>0.5) -> suppressed; box2, box3 kept
    validate(TestCase(sd, {"b": boxes, "s": scores},
                      {"keep": np.asarray([0, 2, 3], np.int32)},
                      grad_wrt=[]))


def test_nms_sweep():
    _run_nms()


def _run_bitwise():
    a = np.asarray([0b1100, 0b1010, 7, -8], np.int32)
    b = np.asarray([0b1010, 0b0110, 2, 3], np.int32)
    sh = np.asarray([1, 2, 3, 4], np.int32)
    sd = SameDiff()
    av = sd.placeholder("a", (4,), dtype="int32")
    bv = sd.placeholder("b", (4,), dtype="int32")
    sv = sd.placeholder("s", (4,), dtype="int32")
    sd.bitwise.and_(av, bv, name="and")
    sd.bitwise.or_(av, bv, name="or")
    sd.bitwise.xor(av, bv, name="xor")
    sd.bitwise.leftShift(av, sv, name="shl")
    sd.bitwise.rightShift(av, sv, name="shr")
    sd.bitwise.cyclicShiftLeft(av, sv, name="rotl")
    sd.bitwise.cyclicShiftRight(av, sv, name="rotr")
    sd.bitwise.toggleBits(av, name="tog")
    sd.bitwise.bitsHammingDistance(av, bv, name="ham")

    def rotl(x, s):
        x = np.uint32(x)
        return np.int32((x << s) | (x >> (32 - s)))

    def rotr(x, s):
        x = np.uint32(x)
        return np.int32((x >> s) | (x << (32 - s)))

    ham = sum(bin(int(np.uint32(x) ^ np.uint32(y))).count("1")
              for x, y in zip(a, b))
    validate(TestCase(
        sd, {"a": a, "b": b, "s": sh},
        {"and": a & b, "or": a | b, "xor": a ^ b,
         "shl": a << sh, "shr": a >> sh,
         "rotl": np.asarray([rotl(x, s) for x, s in zip(a, sh)]),
         "rotr": np.asarray([rotr(x, s) for x, s in zip(a, sh)]),
         "tog": ~a, "ham": ham},
        grad_wrt=[]))


def test_bitwise_sweep():
    _run_bitwise()


def _run_loss_sweep():
    rng = np.random.default_rng(41)
    labels = np.eye(4)[rng.integers(0, 4, 3)]
    logits = rng.normal(size=(3, 4))
    preds = _np_sigmoid(logits)
    sparse = rng.integers(0, 4, 3).astype(np.int32)

    sd = SameDiff()
    lb = sd.placeholder("lb", (3, 4))
    lg = sd.placeholder("lg", (3, 4))
    pr = sd.placeholder("pr", (3, 4))
    sp = sd.placeholder("sp", (3,), dtype="int32")
    sd.loss.meanSquaredError(lb, pr, name="mse")
    sd.loss.absoluteDifference(lb, pr, name="mae")
    sd.loss.softmaxCrossEntropy(lb, lg, name="sce")
    sd.loss.sparseSoftmaxCrossEntropy(sp, lg, name="ssce")
    sd.loss.sigmoidCrossEntropy(lb, lg, name="bce")
    sd.loss.logLoss(lb, pr, name="ll")
    sd.loss.huberLoss(lb, pr, name="hub")
    sd.loss.hingeLoss(lb, pr, name="hinge")
    sd.loss.cosineDistance(lb, pr, name="cos")
    sd.loss.logPoisson(lb, lg, name="lp")

    lsm = logits - np.log(np.exp(logits).sum(-1, keepdims=True))
    err = preds - labels
    absd = np.abs(err)
    quad = np.minimum(absd, 1.0)
    eps = 1e-7
    validate(TestCase(
        sd, {"lb": labels, "lg": logits, "pr": preds, "sp": sparse},
        {"mse": (err ** 2).mean(),
         "mae": absd.mean(),
         "sce": (-(labels * lsm).sum(-1)).mean(),
         "ssce": (-lsm[np.arange(3), sparse]).mean(),
         "bce": (np.maximum(logits, 0) - logits * labels
                 + np.log1p(np.exp(-np.abs(logits)))).mean(),
         "ll": (-(labels * np.log(preds + eps)
                  + (1 - labels) * np.log(1 - preds + eps))).mean(),
         "hub": (0.5 * quad ** 2 + (absd - quad)).mean(),
         "hinge": np.maximum(0.0, 1.0 - (2 * labels - 1) * preds)
         .mean(),
         "cos": (1.0 - (labels * preds).sum(-1)).mean(),
         "lp": (np.exp(logits) - labels * logits).mean()},
        grad_wrt=["lg"], max_rel_error=1e-3))


def test_loss_sweep():
    _run_loss_sweep()


def _run_math_misc():
    rng = np.random.default_rng(51)
    xv = rng.uniform(0.5, 2.0, size=(3, 4))
    sq = rng.normal(size=(4, 4))
    vec = rng.normal(size=(4,))
    a3 = rng.uniform(0.5, 2.0, size=(2, 3, 4))
    bools = xv > 1.0

    sd = SameDiff()
    x = sd.placeholder("x", (3, 4))
    s = sd.placeholder("s", (4, 4))
    v = sd.placeholder("v", (4,))
    t3 = sd.placeholder("t3", (2, 3, 4))
    sd._op("math.argmax", [x], name="amax", axis=1, keepdims=False)
    sd._op("math.argmin", [x], name="amin", axis=1, keepdims=False)
    sd._op("math.clip_by_value", [x], name="clip", lo=0.8, hi=1.5)
    sd._op("math.cumsum", [x], name="cs", axis=1)
    sd._op("math.cumprod", [x], name="cp", axis=1)
    sd._op("math.diag", [v], name="dg")
    sd._op("math.trace", [s], name="tr")
    sd._op("math.reverse", [x], name="rev", dims=(1,))
    sd._op("math.where", [sd._op("math.gt", [x, sd.constant(
        np.float64(1.0))], name="gt1")[0], x, sd.constant(
        np.zeros((3, 4)))], name="wh")
    sd._op("math.tensordot", [t3, s], name="td", axes_a=(2,), axes_b=(0,))
    sd._op("math.matmul", [x, s], name="mm", transpose_a=False,
           transpose_b=False)
    sd._op("math.tanh", [x], name="th")
    gt = sd._op("math.gt", [x, sd.constant(np.float64(1.0))], name="g")[0]
    lt = sd._op("math.lt", [x, sd.constant(np.float64(1.5))], name="l")[0]
    sd._op("math.logical_and", [gt, lt], name="land")
    sd._op("math.logical_or", [gt, lt], name="lor")
    sd._op("math.logical_xor", [gt, lt], name="lxor")
    sd._op("math.logical_not", [gt], name="lnot")

    g = xv > 1.0
    lt_ = xv < 1.5
    validate(TestCase(
        sd, {"x": xv, "s": sq, "v": vec, "t3": a3},
        {"amax": xv.argmax(1), "amin": xv.argmin(1),
         "clip": np.clip(xv, 0.8, 1.5),
         "cs": xv.cumsum(1), "cp": xv.cumprod(1),
         "dg": np.diag(vec), "tr": np.trace(sq),
         "rev": xv[:, ::-1],
         "wh": np.where(xv > 1.0, xv, 0.0),
         "td": np.tensordot(a3, sq, axes=([2], [0])),
         "mm": xv @ sq, "th": np.tanh(xv),
         "land": g & lt_, "lor": g | lt_, "lxor": g ^ lt_, "lnot": ~g},
        grad_wrt=[]))


def test_math_misc_sweep():
    _run_math_misc()


def _run_structural_misc():
    rng = np.random.default_rng(61)
    xv = rng.normal(size=(3, 4))
    idx = np.asarray([2, 0], np.int32)

    sd = SameDiff()
    x = sd.placeholder("x", (3, 4))
    iv = sd.placeholder("iv", (2,), dtype="int32")
    sd._op("identity", [x], name="id")
    sd._op("transpose", [x], name="tp")
    sd._op("concat", [x, x], name="cc", axis=0)
    sd._op("slice_op", [x], name="sl", begin=(1, 0), size=(2, 3))
    sd._op("gather", [x, iv], name="ga", axis=0)
    sd._op("one_hot", [iv], name="oh", depth=4)
    sd._op("shape_of", [x], name="sh")
    sd._op("zeros_like", [x], name="zl")
    sd._op("ones_like", [x], name="ol")
    sd._op("flatten2d", [sd._op("identity", [x], name="id2")[0]], name="fl")
    sd._op("softmax_flattened", [x], name="sf", axis=1)
    sd._op("reshape_onnx", [x], name="ro", shape=(0, -1))
    sd._op("unsqueeze_onnx", [x], name="uo", axes=(0,))
    sel = sd.placeholder("sel", (3,), dtype="bool")
    sd._op("select_tf", [sel, x, x * 0.0], name="st")
    xvar = sd.placeholder("xi", (3, 4))
    sd._op("getitem", [xvar], name="gi",
           index={"tuple": [{"slice": [0, 2, None]}, 1]})

    e = np.exp(xv - xv.max(1, keepdims=True))
    selv = np.asarray([True, False, True])
    validate(TestCase(
        sd, {"x": xv, "iv": idx, "sel": selv, "xi": xv},
        {"id": xv, "tp": xv.T, "cc": np.concatenate([xv, xv]),
         "sl": xv[1:3, 0:3], "ga": xv[idx],
         "oh": np.eye(4, dtype=np.float32)[idx],
         "sh": np.asarray([3, 4], np.int32),
         "zl": np.zeros_like(xv), "ol": np.ones_like(xv),
         "fl": xv.reshape(3, 4),
         "sf": e / e.sum(1, keepdims=True),
         "ro": xv, "uo": xv[None],
         "st": np.where(selv[:, None], xv, 0.0),
         "gi": xv[0:2, 1]},
        grad_wrt=[]))


def test_structural_misc_sweep():
    _run_structural_misc()


def _run_cnn_nn_extra():
    rng = np.random.default_rng(71)
    x1 = rng.normal(size=(2, 8, 3))            # NWC
    k1 = rng.normal(size=(3, 3, 5), scale=0.5)  # WIO
    x2 = rng.normal(size=(1, 4, 4, 2))
    kd = rng.normal(size=(2, 2, 1, 2), scale=0.5)  # HWIO, I=1 per group
    xf = rng.normal(size=(2, 6))

    sd = SameDiff()
    a = sd.placeholder("a", (2, 8, 3))
    w1 = sd.placeholder("w1", (3, 3, 5))
    b5 = sd.placeholder("b5", (5,))
    b2 = sd.placeholder("b2", (2,))
    c = sd.placeholder("c", (1, 4, 4, 2))
    wd = sd.placeholder("wd", (2, 2, 1, 2))
    f = sd.placeholder("f", (2, 6))
    mean = sd.placeholder("mean", (6,))
    var = sd.placeholder("var", (6,))
    gamma = sd.placeholder("gamma", (6,))
    beta = sd.placeholder("beta", (6,))
    sd._op("cnn.conv1d", [a, w1, b5], name="c1", stride=1, padding="VALID")
    sd._op("cnn.depthwiseConv2d", [c, wd, b2], name="dw", strides=(1, 1),
           padding="VALID")
    sd._op("cnn.upsampling2d", [c], name="up", scale=2)
    sd._op("nn.hardSigmoid", [f], name="hs")
    sd._op("nn.hardTanh", [f], name="ht")
    sd._op("nn.batchNorm", [f, mean, var, gamma, beta], name="bn",
           axis=-1, eps=1e-5)

    conv1 = np.zeros((2, 6, 5))
    for i in range(6):
        conv1[:, i, :] = np.einsum("bwc,wco->bo", x1[:, i:i + 3, :], k1)
    dw = np.zeros((1, 3, 3, 2))
    for i in range(3):
        for j in range(3):
            patch = x2[:, i:i + 2, j:j + 2, :]
            dw[:, i, j, :] = np.einsum("bhwc,hwc->bc", patch, kd[:, :, 0, :])
    mv = rng.normal(size=(6,))
    vv = rng.uniform(0.5, 1.5, size=(6,))
    gv = rng.normal(size=(6,))
    bv = rng.normal(size=(6,))
    validate(TestCase(
        sd, {"a": x1, "w1": k1, "b5": np.zeros(5), "c": x2, "wd": kd,
             "b2": np.zeros(2), "f": xf, "mean": mv, "var": vv,
             "gamma": gv, "beta": bv},
        {"c1": conv1, "dw": dw,
         "up": x2.repeat(2, axis=1).repeat(2, axis=2),
         "hs": np.clip(xf / 6.0 + 0.5, 0.0, 1.0),  # jax hard_sigmoid slope
         "ht": np.clip(xf, -1.0, 1.0),
         "bn": gv * (xf - mv) / np.sqrt(vv + 1e-5) + bv},
        grad_wrt=[], max_rel_error=1e-3))


def test_cnn_nn_extra_sweep():
    _run_cnn_nn_extra()


# Ops whose validation lives OUTSIDE this harness, each with the test that
# covers it (reference OpValidation keeps an equivalent exclusion list for
# ops covered by dedicated suites). Adding a NEW op to the registry
# without either a sweep entry here or an exemption fails the gate below.
_EXEMPT = {
    "cond": "tests/test_samediff.py control-flow exec/serde",
    "while_loop": "tests/test_samediff.py control-flow exec/serde",
    "scan_op": "tests/test_samediff.py control-flow exec/serde",
    "rnn.lstmLayer": "tests/test_samediff.py LSTM training",
    "rnn.gru": "tests/test_samediff.py GRU exec",
    "rnn.simpleRnn": "tests/test_samediff.py simpleRnn exec",
    "nn.dropout": "stochastic; tests/test_samediff.py dropout statistics",
    "random.normal": "stochastic; tests/test_samediff.py rng determinism",
    "random.uniform": "stochastic; tests/test_samediff.py rng determinism",
    "random.bernoulli": "stochastic; tests/test_samediff.py rng determinism",
    "nn.dotProductAttention": "tests/test_attention_layers.py",
    "nn.multiHeadDotProductAttention": "tests/test_attention_layers.py",
}


def test_coverage_registry_complete():
    """THE coverage gate (reference: OpValidation coverage accounting
    fails CI for registered-but-untested ops). Runs every sweep in this
    module in-process, then requires the missing set to be exactly the
    documented exemptions."""
    test_coverage_after_sweep()
    for case in _NN_SWEEP:
        _run_nn_unary(*case)
    r = np.random.default_rng(0)
    test_nn_composite_sweep(r)
    test_cnn_ops_sweep(r)
    test_shape_op_sweep(r)
    for op, oracle, check_grad in _SCATTER_SWEEP:
        _run_scatter(op, oracle, check_grad=False)
    _run_gather_segment()
    _run_linalg()
    _run_image()
    _run_nms()
    _run_bitwise()
    _run_loss_sweep()
    _run_math_misc()
    _run_structural_misc()
    _run_cnn_nn_extra()
    _run_reduce3()
    _run_stats_misc()
    rep = coverage_report()
    unexpected = sorted(set(rep["missing"]) - set(_EXEMPT))
    assert not unexpected, (
        f"registered ops without validation coverage: {unexpected} — add a "
        "sweep entry in test_op_validation.py or an explicit exemption "
        "with a pointer to the covering test")
    assert rep["validated"] >= 190, rep["validated"]


# --- round 2b: reduce3 distances / statistics / misc math -------------------

def _run_reduce3():
    rng = np.random.default_rng(81)
    xv = rng.uniform(0.2, 2.0, size=(3, 4))
    yv = rng.uniform(0.2, 2.0, size=(3, 4))
    sd = SameDiff()
    x = sd.placeholder("x", (3, 4))
    y = sd.placeholder("y", (3, 4))
    sd.math.euclideanDistance(x, y, dims=(1,), name="eu")
    sd.math.manhattanDistance(x, y, dims=(1,), name="mh")
    sd.math.cosineSimilarity(x, y, dims=(1,), name="cs")
    sd.math.cosineDistance(x, y, dims=(1,), name="cd")
    sd.math.dot(x, y, dims=(1,), name="dt")
    sd.math.hammingDistance(x, y, dims=(1,), name="hm")
    sd.math.jaccardDistance(x, y, dims=(1,), name="jc")
    cs = (xv * yv).sum(1) / (np.linalg.norm(xv, axis=1)
                             * np.linalg.norm(yv, axis=1) + 1e-12)
    validate(TestCase(sd, {"x": xv, "y": yv}, {
        "eu": np.sqrt(((xv - yv) ** 2).sum(1)),
        "mh": np.abs(xv - yv).sum(1),
        "cs": cs, "cd": 1.0 - cs,
        "dt": (xv * yv).sum(1),
        "hm": (xv != yv).sum(1).astype(np.float64),
        "jc": 1.0 - np.minimum(xv, yv).sum(1)
        / (np.maximum(xv, yv).sum(1) + 1e-12),
    }, grad_wrt=["x", "y"], max_rel_error=1e-3))


def test_reduce3_sweep():
    _run_reduce3()


def _run_stats_misc():
    rng = np.random.default_rng(82)
    p = rng.uniform(0.05, 1.0, size=(2, 5))
    p = p / p.sum(1, keepdims=True)          # distributions per row
    xv = rng.uniform(0.5, 2.0, size=(2, 5))
    xz = xv.copy()
    xz[0, 1] = 0.0                            # a zero for countZero
    v3a = rng.normal(size=(4, 3))
    v3b = rng.normal(size=(4, 3))

    sd = SameDiff()
    pp = sd.placeholder("p", (2, 5))
    x = sd.placeholder("x", (2, 5))
    xzv = sd.placeholder("xz", (2, 5))
    a3 = sd.placeholder("a3", (4, 3))
    b3 = sd.placeholder("b3", (4, 3))
    sd.math.entropy(pp, dims=(1,), name="ent")
    sd.math.logEntropy(pp, dims=(1,), name="lent")
    sd.math.shannonEntropy(pp, dims=(1,), name="sent")
    sd.math.amean(x, dims=(1,), name="am")
    sd.math.asum(x, dims=(1,), name="as")
    sd.math.countZero(xzv, dims=(1,), name="cz")
    sd.math.zeroFraction(xzv, dims=(1,), name="zf")
    sd.math.standardize(x, dims=(1,), name="std")
    sd.math.isMax(x, dims=(1,), name="im")
    sd.math.cross(a3, b3, name="cr")
    sd.math.lgamma(x, name="lg")
    sd.math.digamma(x, name="dg")
    sd.math.rint(x, name="ri")

    import scipy.special as sps

    ent = -(p * np.log(p + 1e-12)).sum(1)
    mu = xv.mean(1, keepdims=True)
    sdv = xv.std(1, keepdims=True)
    validate(TestCase(
        sd, {"p": p, "x": xv, "xz": xz, "a3": v3a, "b3": v3b},
        {"ent": ent, "lent": np.log(ent + 1e-12),
         "sent": -(p * np.log2(p + 1e-12)).sum(1),
         "am": np.abs(xv).mean(1), "as": np.abs(xv).sum(1),
         "cz": (xz == 0).sum(1), "zf": (xz == 0).mean(1),
         "std": (xv - mu) / (sdv + 1e-12),
         "im": np.eye(5)[xv.argmax(1)],
         "cr": np.cross(v3a, v3b),
         "lg": sps.gammaln(xv), "dg": sps.digamma(xv),
         "ri": np.rint(xv)},
        grad_wrt=[], max_rel_error=1e-3))


def test_stats_misc_sweep():
    _run_stats_misc()


def test_is_max_tie_breaks_to_single_one():
    """Reference IsMax semantics: exactly one 1 on tied maxima."""
    sd = SameDiff()
    x = sd.placeholder("x", (2, 3))
    sd.math.isMax(x, dims=(1,), name="im")
    out = sd.output({"x": np.asarray([[1.0, 3.0, 3.0],
                                      [2.0, 2.0, 1.0]])}, "im")
    got = np.asarray(out["im"])
    np.testing.assert_allclose(got.sum(1), [1.0, 1.0])
    np.testing.assert_allclose(got, [[0, 1, 0], [1, 0, 0]])
