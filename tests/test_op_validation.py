"""Per-op validation via the OpValidation harness (reference
``org.nd4j.autodiff.validation.OpValidation`` — forward + gradient per op,
with coverage accounting)."""

import numpy as np
import pytest

from deeplearning4j_tpu.samediff.core import SameDiff
from deeplearning4j_tpu.samediff.validation import (
    TestCase,
    coverage_report,
    validate,
)


def _case(build, inputs, expected, **kw):
    sd = SameDiff.create()
    build(sd)
    return TestCase(sd, inputs, expected, **kw)


def test_matmul_and_bias():
    sd = SameDiff.create()
    a = sd.placeholder("a", shape=(2, 3), dtype="float64")
    b = sd.placeholder("b", shape=(3, 2), dtype="float64")
    y = sd.math.mmul(a, b, name="y")
    av = np.arange(6, dtype=np.float64).reshape(2, 3)
    bv = np.arange(6, dtype=np.float64).reshape(3, 2) * 0.5
    validate(TestCase(sd, {"a": av, "b": bv}, {"y": av @ bv}))


def test_elementwise_family():
    sd = SameDiff.create()
    x = sd.placeholder("x", shape=(4,), dtype="float64")
    y = sd.placeholder("y", shape=(4,), dtype="float64")
    s = (x * y + x - y / 2.0).rename("s")
    xv = np.asarray([0.5, -1.0, 2.0, 3.0])
    yv = np.asarray([1.0, 2.0, -0.5, 0.25])
    validate(TestCase(sd, {"x": xv, "y": yv},
                      {"s": xv * yv + xv - yv / 2.0}))


def test_activations_and_reductions():
    sd = SameDiff.create()
    x = sd.placeholder("x", shape=(3, 4), dtype="float64")
    h = sd.nn.tanh(x)
    m = sd.math.mean(h, dims=(1,), name="m")
    xv = np.linspace(-2, 2, 12).reshape(3, 4)
    validate(TestCase(sd, {"x": xv}, {"m": np.tanh(xv).mean(1)}))


def test_softmax_gradient():
    sd = SameDiff.create()
    x = sd.placeholder("x", shape=(2, 5), dtype="float64")
    p = sd.nn.softmax(x, name="p")
    xv = np.random.default_rng(0).normal(size=(2, 5))
    e = np.exp(xv - xv.max(1, keepdims=True))
    validate(TestCase(sd, {"x": xv}, {"p": e / e.sum(1, keepdims=True)}))


def test_conv2d_forward_and_grad():
    sd = SameDiff.create()
    x = sd.placeholder("x", shape=(1, 4, 4, 2), dtype="float64")
    w = sd.placeholder("w", shape=(2, 2, 2, 3), dtype="float64")
    b = sd.constant(np.zeros(3))
    y = sd.cnn.conv2d(x, w, b, strides=(1, 1), padding="VALID", name="y")
    rng = np.random.default_rng(1)
    xv = rng.normal(size=(1, 4, 4, 2))
    wv = rng.normal(size=(2, 2, 2, 3)) * 0.5
    import jax

    want = np.asarray(jax.lax.conv_general_dilated(
        xv, wv, (1, 1), "VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC")))
    validate(TestCase(sd, {"x": xv, "w": wv}, {"y": want},
                      max_rel_error=1e-3))


def test_layer_norm_grad():
    sd = SameDiff.create()
    x = sd.placeholder("x", shape=(2, 6), dtype="float64")
    g = sd.constant(np.ones(6))
    b = sd.constant(np.zeros(6))
    y = sd.nn.layerNorm(x, g, b, name="y")
    xv = np.random.default_rng(2).normal(size=(2, 6)) * 3
    mu = xv.mean(-1, keepdims=True)
    var = xv.var(-1, keepdims=True)
    validate(TestCase(sd, {"x": xv}, {"y": (xv - mu) / np.sqrt(var + 1e-5)},
                      max_rel_error=1e-3))


def test_coverage_accounting_floor():
    """Reference parity: op validation keeps a coverage ledger. Runs its
    own case so the ledger check is self-contained (independent of test
    order / xdist sharding)."""
    sd = SameDiff()
    x = sd.placeholder("x", (2, 3))
    sd.math.mul(x, x, name="y")
    xv = np.random.default_rng(3).normal(size=(2, 3))
    validate(TestCase(sd, {"x": xv}, {"y": xv * xv}))
    rep = coverage_report()
    assert rep["registered"] > 150  # the registry is substantial
    assert rep["validated"] >= 1    # the case above recorded its ops
    assert isinstance(rep["missing"], list)


# --------------------------------------------------------------------------
# broad registry sweep (reference: OpValidation coverage accounting fails CI
# for untested ops; this sweep pushes per-op forward+gradient coverage)
# --------------------------------------------------------------------------

def _seed(op: str) -> int:
    import zlib

    return zlib.crc32(op.encode())  # stable across runs (hash() is not)


# (registry op, numpy oracle, (lo, hi) input range, grad_checked)
_UNARY_SWEEP = [
    ("math.exp", np.exp, (-1, 1), True),
    ("math.expm1", np.expm1, (-1, 1), True),
    ("math.exp2", np.exp2, (-1, 1), True),
    ("math.log", np.log, (0.5, 2.0), True),
    ("math.log1p", np.log1p, (-0.4, 1.0), True),
    ("math.log2", np.log2, (0.5, 2.0), True),
    ("math.log10", np.log10, (0.5, 2.0), True),
    ("math.sqrt", np.sqrt, (0.5, 2.0), True),
    ("math.rsqrt", lambda x: 1.0 / np.sqrt(x), (0.5, 2.0), True),
    ("math.square", np.square, (-2, 2), True),
    ("math.reciprocal", np.reciprocal, (0.5, 2.0), True),
    ("math.abs", np.abs, (0.3, 2.0), True),
    ("math.neg", np.negative, (-2, 2), True),
    ("math.sin", np.sin, (-1, 1), True),
    ("math.cos", np.cos, (-1, 1), True),
    ("math.tan", np.tan, (-1, 1), True),
    ("math.asin", np.arcsin, (-0.8, 0.8), True),
    ("math.acos", np.arccos, (-0.8, 0.8), True),
    ("math.atan", np.arctan, (-2, 2), True),
    ("math.sinh", np.sinh, (-1, 1), True),
    ("math.cosh", np.cosh, (-1, 1), True),
    ("math.asinh", np.arcsinh, (-2, 2), True),
    ("math.acosh", np.arccosh, (1.5, 3.0), True),
    ("math.atanh", np.arctanh, (-0.8, 0.8), True),
    ("math.erf", None, (-1.5, 1.5), True),     # oracle via math.erf below
    ("math.erfc", None, (-1.5, 1.5), True),
    ("math.floor", np.floor, (0.1, 0.9), False),
    ("math.ceil", np.ceil, (0.1, 0.9), False),
    ("math.round", np.round, (0.1, 0.4), False),
    ("math.sign", np.sign, (0.3, 2.0), False),
    ("math.isnan", np.isnan, (-1, 1), False),
    ("math.isinf", np.isinf, (-1, 1), False),
    ("math.isfinite", np.isfinite, (-1, 1), False),
]


def _run_unary(op, oracle, rng_range, check_grad):
    import math as _m

    if oracle is None:
        base = {"math.erf": _m.erf, "math.erfc": _m.erfc}[op]
        oracle = np.vectorize(base)
    rng = np.random.default_rng(_seed(op))
    xv = rng.uniform(*rng_range, size=(2, 3))
    sd = SameDiff()
    x = sd.placeholder("x", (2, 3))
    sd._op(op, [x], name="y")
    validate(TestCase(sd, {"x": xv}, {"y": oracle(xv)},
                      grad_wrt=["x"] if check_grad else []))


@pytest.mark.parametrize("op,oracle,rng_range,check_grad", _UNARY_SWEEP,
                         ids=[c[0] for c in _UNARY_SWEEP])
def test_unary_sweep(op, oracle, rng_range, check_grad):
    _run_unary(op, oracle, rng_range, check_grad)


_BINARY_SWEEP = [
    ("math.add", np.add, True),
    ("math.sub", np.subtract, True),
    ("math.mul", np.multiply, True),
    ("math.div", np.divide, True),
    ("math.pow", np.power, True),
    ("math.maximum", np.maximum, True),
    ("math.minimum", np.minimum, True),
    ("math.atan2", np.arctan2, True),
    ("math.squared_difference", lambda a, b: (a - b) ** 2, True),
    ("math.rsub", lambda a, b: b - a, True),
    ("math.rdiv", lambda a, b: b / a, True),
    ("math.mod", np.mod, False),
    ("math.floordiv", np.floor_divide, False),
    ("math.gt", np.greater, False),
    ("math.gte", np.greater_equal, False),
    ("math.lt", np.less, False),
    ("math.lte", np.less_equal, False),
    ("math.eq", np.equal, False),
    ("math.neq", np.not_equal, False),
]


def _run_binary(op, oracle, check_grad):
    rng = np.random.default_rng(_seed(op))
    av = rng.uniform(0.5, 2.0, size=(2, 3))
    bv = rng.uniform(0.6, 1.9, size=(2, 3))
    sd = SameDiff()
    a = sd.placeholder("a", (2, 3))
    b = sd.placeholder("b", (2, 3))
    sd._op(op, [a, b], name="y")
    validate(TestCase(sd, {"a": av, "b": bv}, {"y": oracle(av, bv)},
                      grad_wrt=["a", "b"] if check_grad else []))


@pytest.mark.parametrize("op,oracle,check_grad", _BINARY_SWEEP,
                         ids=[c[0] for c in _BINARY_SWEEP])
def test_binary_sweep(op, oracle, check_grad):
    _run_binary(op, oracle, check_grad)


_REDUCE_SWEEP = [
    ("reduce.sum", lambda x, ax, kd: x.sum(axis=ax, keepdims=kd), True),
    ("reduce.mean", lambda x, ax, kd: x.mean(axis=ax, keepdims=kd), True),
    ("reduce.prod", lambda x, ax, kd: x.prod(axis=ax, keepdims=kd), True),
    ("reduce.amax", lambda x, ax, kd: x.max(axis=ax, keepdims=kd), False),
    ("reduce.amin", lambda x, ax, kd: x.min(axis=ax, keepdims=kd), False),
    ("reduce.std", lambda x, ax, kd: x.std(axis=ax, keepdims=kd), True),
    ("reduce.var", lambda x, ax, kd: x.var(axis=ax, keepdims=kd), True),
    ("reduce.norm1", lambda x, ax, kd: np.abs(x).sum(axis=ax, keepdims=kd),
     True),
    ("reduce.norm2",
     lambda x, ax, kd: np.sqrt((x * x).sum(axis=ax, keepdims=kd)), True),
    ("reduce.normmax",
     lambda x, ax, kd: np.abs(x).max(axis=ax, keepdims=kd), False),
    ("reduce.countNonZero",
     lambda x, ax, kd: (x != 0).sum(axis=ax, keepdims=kd), False),
]


def _run_reduce(op, oracle, check_grad, axis, keepdims):
    rng = np.random.default_rng(_seed(op))
    xv = rng.uniform(0.5, 2.0, size=(3, 4))
    sd = SameDiff()
    x = sd.placeholder("x", (3, 4))
    sd._op(op, [x], name="y", axis=axis, keepdims=keepdims)
    validate(TestCase(sd, {"x": xv},
                      {"y": oracle(xv, axis, keepdims)},
                      grad_wrt=["x"] if check_grad else []))


@pytest.mark.parametrize("op,oracle,check_grad", _REDUCE_SWEEP,
                         ids=[c[0] for c in _REDUCE_SWEEP])
@pytest.mark.parametrize("axis,keepdims", [((1,), False), ((0, 1), True)])
def test_reduce_sweep(op, oracle, check_grad, axis, keepdims):
    _run_reduce(op, oracle, check_grad, axis, keepdims)


def test_shape_op_sweep(rng):
    """Forward-only validation of the structural ops (reference shape
    function tests)."""
    xv = rng.normal(size=(2, 3, 4)).astype(np.float64)
    sd = SameDiff()
    x = sd.placeholder("x", (2, 3, 4))
    sd._op("reshape", [x], name="r", shape=(6, 4))
    sd._op("permute", [x], name="p", dims=(2, 0, 1))
    sd._op("expand_dims", [x], name="e", axis=1)
    sd._op("tile", [x], name="t", reps=(1, 2, 1))
    sd._op("squeeze", [sd._op("expand_dims", [x], name="e2", axis=0)[0]],
           name="sq", axis=(0,))
    sd._op("strided_slice", [x], name="ss", begin=(0, 1, 0),
           end=(2, 3, 4), strides=(1, 1, 2))
    sd._op("split", [x], name="sp", n_out=2, axis=2, num=2)
    sd._op("stack", [x, x], name="st", axis=0)
    sd._op("unstack", [x], name="us", n_out=2, axis=0, num=2)
    sd._op("cast", [x], name="c", dtype="float32")
    validate(TestCase(sd, {"x": xv}, {
        "r": xv.reshape(6, 4),
        "p": xv.transpose(2, 0, 1),
        "e": xv[:, None],
        "t": np.tile(xv, (1, 2, 1)),
        "sq": xv,
        "ss": xv[0:2, 1:3, ::2],
        "sp:0": xv[:, :, :2], "sp:1": xv[:, :, 2:],
        "st": np.stack([xv, xv]),
        "us:0": xv[0], "us:1": xv[1],
        "c": xv.astype(np.float32),
    }, grad_wrt=[]))


def test_coverage_after_sweep():
    """Self-contained (isolation/xdist-safe): runs the whole sweep
    forward-only in-process, then asserts the ledger floor."""
    for op, oracle, rng_range, _ in _UNARY_SWEEP:
        _run_unary(op, oracle, rng_range, check_grad=False)
    for op, oracle, _ in _BINARY_SWEEP:
        _run_binary(op, oracle, check_grad=False)
    for op, oracle, _ in _REDUCE_SWEEP:
        _run_reduce(op, oracle, False, (1,), False)
    rep = coverage_report()
    assert rep["validated"] >= 60, rep["validated"]


# --------------------------------------------------------------------------
# nn / cnn / structural sweep (activation oracles in numpy; conv/pool
# against explicit loops)
# --------------------------------------------------------------------------

def _np_sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


_NN_SWEEP = [
    ("nn.relu", lambda x: np.maximum(x, 0.0), False),  # kink at 0
    ("nn.relu6", lambda x: np.clip(x, 0.0, 6.0), False),
    ("nn.elu", lambda x: np.where(x > 0, x, np.exp(x) - 1.0), True),
    ("nn.sigmoid", _np_sigmoid, True),
    ("nn.tanh", np.tanh, True),
    ("nn.softplus", lambda x: np.log1p(np.exp(x)), True),
    ("nn.softsign", lambda x: x / (1.0 + np.abs(x)), True),
    ("nn.swish", lambda x: x * _np_sigmoid(x), True),
    ("nn.silu", lambda x: x * _np_sigmoid(x), True),
    ("nn.gelu", None, True),   # jax default gelu is the tanh approximation
    ("nn.mish", lambda x: x * np.tanh(np.log1p(np.exp(x))), True),
    ("nn.selu", lambda x: 1.0507009873554805 * np.where(
        x > 0, x, 1.6732632423543772 * (np.exp(x) - 1.0)), True),
]


def _run_nn_unary(op, oracle, check_grad):
    if oracle is None:  # tanh-approx gelu
        def oracle(x):
            return 0.5 * x * (1.0 + np.tanh(
                np.sqrt(2.0 / np.pi) * (x + 0.044715 * x ** 3)))
    rng = np.random.default_rng(_seed(op))
    xv = rng.uniform(0.3, 2.0, size=(2, 3)) * np.where(
        rng.random((2, 3)) < 0.5, -1.0, 1.0)  # both signs, away from 0
    sd = SameDiff()
    x = sd.placeholder("x", (2, 3))
    sd._op(op, [x], name="y")
    validate(TestCase(sd, {"x": xv}, {"y": oracle(xv)},
                      grad_wrt=["x"] if check_grad else [],
                      max_rel_error=1e-3))


@pytest.mark.parametrize("op,oracle,check_grad", _NN_SWEEP,
                         ids=[c[0] for c in _NN_SWEEP])
def test_nn_unary_sweep(op, oracle, check_grad):
    _run_nn_unary(op, oracle, check_grad)


def test_nn_composite_sweep(rng):
    xv = rng.normal(size=(4, 6))
    wv = rng.normal(size=(6, 3))
    bv = rng.normal(size=(3,))
    gv = rng.normal(size=(6,)) + 1.0
    sd = SameDiff()
    x = sd.placeholder("x", (4, 6))
    w = sd.constant(wv, name="w")
    b3 = sd.constant(bv, name="b3")
    g = sd.constant(gv, name="g")
    b6 = sd.constant(np.zeros(6), name="b6")
    sd._op("nn.linear", [x, w, b3], name="lin")
    sd._op("nn.biasAdd", [sd._op("math.mul", [x, x])[0], b6], name="ba")
    sd._op("nn.softmax", [x], name="sm", axis=-1)
    sd._op("nn.logSoftmax", [x], name="lsm", axis=-1)
    sd._op("nn.leakyRelu", [x], name="lr", alpha=0.1)
    sd._op("nn.layerNorm", [x, g, b6], name="ln", axis=-1, eps=1e-5)
    sd._op("nn.pad", [x], name="pd", paddings=((0, 0), (1, 2)),
           mode="constant", value=0.0)

    e = np.exp(xv - xv.max(-1, keepdims=True))
    sm = e / e.sum(-1, keepdims=True)
    mu = xv.mean(-1, keepdims=True)
    var = xv.var(-1, keepdims=True)
    validate(TestCase(sd, {"x": xv}, {
        "lin": xv @ wv + bv,
        "ba": xv * xv,
        "sm": sm,
        "lsm": np.log(sm),
        "lr": np.where(xv > 0, xv, 0.1 * xv),
        "ln": gv * (xv - mu) / np.sqrt(var + 1e-5),
        "pd": np.pad(xv, ((0, 0), (1, 2))),
    }, max_rel_error=1e-3))


def test_cnn_ops_sweep(rng):
    """conv2d / pooling / depthwise against explicit numpy loops."""
    x = rng.normal(size=(2, 6, 6, 3))
    k = rng.normal(size=(3, 3, 3, 4), scale=0.5)
    sd = SameDiff()
    xin = sd.placeholder("x", (2, 6, 6, 3))
    kc = sd.placeholder("k", (3, 3, 3, 4))     # placeholders stay f64 in
    zero = sd.placeholder("b0", (4,))          # the x64 validate context
    sd._op("cnn.conv2d", [xin, kc, zero], name="cv", strides=(1, 1),
           padding="VALID", dilation=(1, 1))
    sd._op("cnn.maxPooling2d", [xin], name="mp", k=(2, 2), s=(2, 2),
           padding="VALID")
    sd._op("cnn.avgPooling2d", [xin], name="ap", k=(2, 2), s=(2, 2),
           padding="VALID")

    conv = np.zeros((2, 4, 4, 4))
    for i in range(4):
        for j in range(4):
            patch = x[:, i:i + 3, j:j + 3, :]
            conv[:, i, j, :] = np.einsum("bhwc,hwco->bo", patch, k)
    mp = x.reshape(2, 3, 2, 3, 2, 3).max(axis=(2, 4))
    ap = x.reshape(2, 3, 2, 3, 2, 3).mean(axis=(2, 4))
    validate(TestCase(sd, {"x": x, "k": k, "b0": np.zeros(4)},
                      {"cv": conv, "mp": mp, "ap": ap},
                      grad_wrt=[], max_rel_error=1e-3))


def test_coverage_final_floor():
    """With the nn/cnn sweeps the harness-validated slice of the registry
    crosses 90 ops (self-contained like test_coverage_after_sweep)."""
    test_coverage_after_sweep()
    for case in _NN_SWEEP:
        _run_nn_unary(*case)
    r = np.random.default_rng(0)
    test_nn_composite_sweep(r)
    test_cnn_ops_sweep(r)
    rep = coverage_report()
    assert rep["validated"] >= 90, rep["validated"]


# --------------------------------------------------------------------------
# round 2: scatter / gather-nd / segment / linalg / image / bitwise / loss
# sweeps + the STRICT coverage gate (reference: OpValidation fails CI for
# any op without a TestCase)
# --------------------------------------------------------------------------

_SCATTER_SWEEP = [
    ("scatter.update", lambda r, i, u: _np_scatter(r, i, u, "update"), True),
    ("scatter.add", lambda r, i, u: _np_scatter(r, i, u, "add"), True),
    ("scatter.sub", lambda r, i, u: _np_scatter(r, i, u, "sub"), True),
    ("scatter.mul", lambda r, i, u: _np_scatter(r, i, u, "mul"), False),
    ("scatter.div", lambda r, i, u: _np_scatter(r, i, u, "div"), False),
    ("scatter.max", lambda r, i, u: _np_scatter(r, i, u, "max"), False),
    ("scatter.min", lambda r, i, u: _np_scatter(r, i, u, "min"), False),
]


def _np_scatter(ref, idx, upd, kind):
    out = ref.copy()
    for n, i in enumerate(idx):
        if kind == "update":
            out[i] = upd[n]
        elif kind == "add":
            out[i] += upd[n]
        elif kind == "sub":
            out[i] -= upd[n]
        elif kind == "mul":
            out[i] *= upd[n]
        elif kind == "div":
            out[i] /= upd[n]
        elif kind == "max":
            out[i] = np.maximum(out[i], upd[n])
        elif kind == "min":
            out[i] = np.minimum(out[i], upd[n])
    return out


def _run_scatter(op, oracle, check_grad):
    rng = np.random.default_rng(_seed(op))
    ref = rng.uniform(0.5, 2.0, size=(5, 3))
    # unique indices: duplicate-accumulation order matches jnp only for
    # add/sub; uniqueness makes the numpy loop an exact oracle for all
    idx = np.asarray([0, 2, 4], np.int32)
    upd = rng.uniform(0.5, 2.0, size=(3, 3))
    sd = SameDiff()
    r = sd.placeholder("r", (5, 3))
    i = sd.placeholder("i", (3,), dtype="int32")
    u = sd.placeholder("u", (3, 3))
    sd._op(op, [r, i, u], name="y")
    validate(TestCase(sd, {"r": ref, "i": idx, "u": upd},
                      {"y": oracle(ref, idx, upd)},
                      grad_wrt=["r", "u"] if check_grad else []))


@pytest.mark.parametrize("op,oracle,check_grad", _SCATTER_SWEEP,
                         ids=[c[0] for c in _SCATTER_SWEEP])
def test_scatter_sweep(op, oracle, check_grad):
    _run_scatter(op, oracle, check_grad)


def test_scatter_add_duplicate_indices_accumulate():
    sd = SameDiff()
    r = sd.placeholder("r", (4, 2))
    i = sd.placeholder("i", (3,), dtype="int32")
    u = sd.placeholder("u", (3, 2))
    sd.scatter_add(r, i, u).rename("y")
    ref = np.zeros((4, 2))
    upd = np.asarray([[1., 2.], [10., 20.], [100., 200.]])
    out = sd.output({"r": ref, "i": np.asarray([1, 1, 3]), "u": upd}, "y")
    np.testing.assert_allclose(np.asarray(out["y"]),
                               [[0, 0], [11, 22], [0, 0], [100, 200]])


def _run_gather_segment():
    rng = np.random.default_rng(11)
    xv = rng.uniform(0.5, 2.0, size=(3, 4, 5))
    nd_idx = np.asarray([[0, 1], [2, 3]], np.int32)
    data = rng.uniform(0.5, 2.0, size=(6, 3))
    ids = np.asarray([0, 0, 1, 2, 2, 2], np.int32)
    lens = np.asarray([1, 3, 0], np.int32)

    sd = SameDiff()
    x = sd.placeholder("x", (3, 4, 5))
    gi = sd.placeholder("gi", (2, 2), dtype="int32")
    d = sd.placeholder("d", (6, 3))
    sids = sd.placeholder("sids", (6,), dtype="int32")
    ln = sd.placeholder("ln", (3,), dtype="int32")
    sd.gather_nd(x, gi, name="gnd")
    sd.segment_sum(d, sids, 4, name="ssum")
    sd.segment_mean(d, sids, 4, name="smean")
    sd.segment_max(d, sids, 4, name="smax")
    sd.segment_min(d, sids, 4, name="smin")
    sd.segment_prod(d, sids, 4, name="sprod")
    sd.sequence_mask(ln, 4, name="smask")

    seg = {"sum": np.zeros((4, 3)), "prod": np.ones((4, 3)),
           "max": np.full((4, 3), -np.inf),   # jax identities: empty
           "min": np.full((4, 3), np.inf)}    # segments stay +-inf
    cnt = np.zeros(4)
    for n, i in enumerate(ids):
        seg["sum"][i] += data[n]
        seg["prod"][i] *= data[n]
        seg["max"][i] = np.maximum(seg["max"][i], data[n])
        seg["min"][i] = np.minimum(seg["min"][i], data[n])
        cnt[i] += 1
    mean = seg["sum"] / np.maximum(cnt, 1)[:, None]
    validate(TestCase(
        sd, {"x": xv, "gi": nd_idx, "d": data, "sids": ids, "ln": lens},
        {"gnd": xv[[0, 2], [1, 3]],
         "ssum": seg["sum"], "smean": mean, "smax": seg["max"],
         "smin": seg["min"], "sprod": seg["prod"],
         "smask": (np.arange(4) < lens[:, None]).astype(np.float64)},
        grad_wrt=[]))


def test_gather_segment_mask_sweep():
    _run_gather_segment()


def test_segment_sum_gradient():
    sd = SameDiff()
    d = sd.placeholder("d", (4, 2))
    sids = sd.placeholder("sids", (4,), dtype="int32")
    sd.segment_sum(d, sids, 3, name="y")
    rng = np.random.default_rng(12)
    data = rng.uniform(0.5, 2.0, size=(4, 2))
    ids = np.asarray([0, 2, 2, 1], np.int32)
    want = np.zeros((3, 2))
    for n, i in enumerate(ids):
        want[i] += data[n]
    validate(TestCase(sd, {"d": data, "sids": ids}, {"y": want},
                      grad_wrt=["d"]))


def _run_linalg():
    rng = np.random.default_rng(21)
    a = rng.normal(size=(3, 3))
    spd = a @ a.T + 3 * np.eye(3)          # SPD, well-conditioned
    b = rng.normal(size=(3, 2))
    low = np.tril(a) + 3 * np.eye(3)

    sd = SameDiff()
    s = sd.placeholder("s", (3, 3))
    bb = sd.placeholder("b", (3, 2))
    lo = sd.placeholder("lo", (3, 3))
    sd.linalg.cholesky(s, name="chol")
    sd.linalg.det(s, name="det")
    sd.linalg.inv(s, name="inv")
    sd._op("linalg.matrixInverse", [s], name="minv")
    sgn, logabs = sd._op("linalg.slogdet", [s], n_out=2, name="sld")
    sd.linalg.logdet(s, name="logdet")
    sd.linalg.solve(s, bb, name="solve")
    sd.linalg.lstsq(s, bb, name="lstsq")
    sd.linalg.triangularSolve(lo, bb, lower=True, name="tsolve")
    sd.linalg.matrixBandPart(s, 1, 0, name="band")
    sd.linalg.triu(s, name="triu")
    sd.linalg.tril(s, name="tril")
    sd.linalg.diagPart(s, name="dpart")
    sd.linalg.tri(3, 3, 0, dtype="float64", name="tri")
    sd.linalg.eye(3, dtype="float64", name="eye")
    # qr / svd: orthogonal-factor signs are implementation-defined, so
    # validate via reconstruction (q@r == x; u*s@vt == x)
    q, r = sd.linalg.qr(s)
    sd.math.mmul(q, r, name="qr_recon")
    u, sv, vt = sd.linalg.svd(s)
    sd.math.mmul(u * sv.reshape(1, 3), vt, name="svd_recon")

    sgn_v, logabs_v = np.linalg.slogdet(spd)
    validate(TestCase(
        sd, {"s": spd, "b": b, "lo": low},
        {"chol": np.linalg.cholesky(spd),
         "det": np.linalg.det(spd),
         "inv": np.linalg.inv(spd),
         "minv": np.linalg.inv(spd),
         "sld:0": sgn_v, "sld:1": logabs_v,
         "logdet": logabs_v,
         "solve": np.linalg.solve(spd, b),
         "lstsq": np.linalg.lstsq(spd, b, rcond=None)[0],
         "tsolve": np.linalg.solve(low, b),
         "band": np.where(
             (np.arange(3)[:, None] - np.arange(3)[None, :] <= 1)
             & (np.arange(3)[None, :] - np.arange(3)[:, None] <= 0),
             spd, 0.0),
         "triu": np.triu(spd),
         "tril": np.tril(spd),
         "dpart": np.diag(spd),
         "tri": np.tri(3),
         "eye": np.eye(3),
         "qr_recon": spd,
         "svd_recon": spd},
        grad_wrt=[], max_rel_error=1e-3))


def test_linalg_sweep():
    _run_linalg()


def test_linalg_gradients():
    """Gradient checks for the differentiable linalg core (solve /
    cholesky / det on an SPD input)."""
    rng = np.random.default_rng(22)
    a = rng.normal(size=(3, 3))
    spd = a @ a.T + 3 * np.eye(3)
    b = rng.normal(size=(3, 1))
    sd = SameDiff()
    s = sd.placeholder("s", (3, 3))
    bb = sd.placeholder("b", (3, 1))
    sd.linalg.solve(s, bb, name="solve")
    validate(TestCase(sd, {"s": spd, "b": b},
                      {"solve": np.linalg.solve(spd, b)},
                      grad_wrt=["s", "b"], max_rel_error=1e-3))


def _run_image():
    import colorsys

    rng = np.random.default_rng(31)
    img = rng.uniform(0.05, 0.95, size=(1, 4, 4, 3))
    hsv = np.zeros_like(img)
    for i in range(4):
        for j in range(4):
            hsv[0, i, j] = colorsys.rgb_to_hsv(*img[0, i, j])

    sd = SameDiff()
    x = sd.placeholder("x", (1, 4, 4, 3))
    sd.image.rgbToHsv(x, name="hsv")
    sd.image.hsvToRgb(sd.image.rgbToHsv(x), name="rgb_rt")
    sd.image.rgbToGrayscale(x, name="gray")
    sd.image.adjustSaturation(x, 0.5, name="sat")
    sd.image.adjustHue(x, 0.1, name="hue")
    sd.image.flipLeftRight(x, name="flr")
    sd.image.flipUpDown(x, name="fud")
    sd.image.adjustContrast(x, 2.0, name="ctr")
    sd.image.resizeNearest(x, 8, 8, name="rn")
    sd.image.resizeBilinear(x, 4, 4, name="rb")  # identity size
    sd.image.cropAndResize(x, 1, 1, 2, 2, 2, 2, name="car")
    sd.image.extractImagePatches(x, 2, 2, 2, 2, "VALID", name="pat")

    sat = np.zeros_like(img)
    hue = np.zeros_like(img)
    for i in range(4):
        for j in range(4):
            h, s, v = colorsys.rgb_to_hsv(*img[0, i, j])
            sat[0, i, j] = colorsys.hsv_to_rgb(h, s * 0.5, v)
            hue[0, i, j] = colorsys.hsv_to_rgb((h + 0.1) % 1.0, s, v)
    mean = img.mean(axis=(1, 2), keepdims=True)
    patches = np.zeros((1, 2, 2, 12))
    for i in range(2):
        for j in range(2):
            patches[0, i, j] = img[0, 2 * i:2 * i + 2,
                                   2 * j:2 * j + 2, :].reshape(-1)
    validate(TestCase(
        sd, {"x": img},
        {"hsv": hsv, "rgb_rt": img,
         "gray": (img * [0.2989, 0.5870, 0.1140]).sum(-1, keepdims=True),
         "sat": sat, "hue": hue,
         "flr": img[:, :, ::-1], "fud": img[:, ::-1],
         "ctr": (img - mean) * 2.0 + mean,
         "rn": img.repeat(2, axis=1).repeat(2, axis=2),
         "rb": img,
         "car": img[:, 1:3, 1:3, :],
         "pat": patches},
        grad_wrt=[], max_rel_error=1e-3))


def test_image_sweep():
    _run_image()


def _run_nms():
    boxes = np.asarray([[0, 0, 2, 2], [0.1, 0.1, 2, 2], [3, 3, 4, 4],
                        [0, 0, 0.5, 0.5]], np.float64)
    scores = np.asarray([0.9, 0.8, 0.7, 0.6], np.float64)
    sd = SameDiff()
    b = sd.placeholder("b", (4, 4))
    s = sd.placeholder("s", (4,))
    sd.image.nonMaxSuppression(b, s, 3, iou_threshold=0.5, name="keep")
    # box1 overlaps box0 (iou>0.5) -> suppressed; box2, box3 kept
    validate(TestCase(sd, {"b": boxes, "s": scores},
                      {"keep": np.asarray([0, 2, 3], np.int32)},
                      grad_wrt=[]))


def test_nms_sweep():
    _run_nms()


def _run_bitwise():
    a = np.asarray([0b1100, 0b1010, 7, -8], np.int32)
    b = np.asarray([0b1010, 0b0110, 2, 3], np.int32)
    sh = np.asarray([1, 2, 3, 4], np.int32)
    sd = SameDiff()
    av = sd.placeholder("a", (4,), dtype="int32")
    bv = sd.placeholder("b", (4,), dtype="int32")
    sv = sd.placeholder("s", (4,), dtype="int32")
    sd.bitwise.and_(av, bv, name="and")
    sd.bitwise.or_(av, bv, name="or")
    sd.bitwise.xor(av, bv, name="xor")
    sd.bitwise.leftShift(av, sv, name="shl")
    sd.bitwise.rightShift(av, sv, name="shr")
    sd.bitwise.cyclicShiftLeft(av, sv, name="rotl")
    sd.bitwise.cyclicShiftRight(av, sv, name="rotr")
    sd.bitwise.toggleBits(av, name="tog")
    sd.bitwise.bitsHammingDistance(av, bv, name="ham")

    def rotl(x, s):
        x = np.uint32(x)
        return np.int32((x << s) | (x >> (32 - s)))

    def rotr(x, s):
        x = np.uint32(x)
        return np.int32((x >> s) | (x << (32 - s)))

    ham = sum(bin(int(np.uint32(x) ^ np.uint32(y))).count("1")
              for x, y in zip(a, b))
    validate(TestCase(
        sd, {"a": a, "b": b, "s": sh},
        {"and": a & b, "or": a | b, "xor": a ^ b,
         "shl": a << sh, "shr": a >> sh,
         "rotl": np.asarray([rotl(x, s) for x, s in zip(a, sh)]),
         "rotr": np.asarray([rotr(x, s) for x, s in zip(a, sh)]),
         "tog": ~a, "ham": ham},
        grad_wrt=[]))


def test_bitwise_sweep():
    _run_bitwise()


def _run_loss_sweep():
    rng = np.random.default_rng(41)
    labels = np.eye(4)[rng.integers(0, 4, 3)]
    logits = rng.normal(size=(3, 4))
    preds = _np_sigmoid(logits)
    sparse = rng.integers(0, 4, 3).astype(np.int32)

    sd = SameDiff()
    lb = sd.placeholder("lb", (3, 4))
    lg = sd.placeholder("lg", (3, 4))
    pr = sd.placeholder("pr", (3, 4))
    sp = sd.placeholder("sp", (3,), dtype="int32")
    sd.loss.meanSquaredError(lb, pr, name="mse")
    sd.loss.absoluteDifference(lb, pr, name="mae")
    sd.loss.softmaxCrossEntropy(lb, lg, name="sce")
    sd.loss.sparseSoftmaxCrossEntropy(sp, lg, name="ssce")
    sd.loss.sigmoidCrossEntropy(lb, lg, name="bce")
    sd.loss.logLoss(lb, pr, name="ll")
    sd.loss.huberLoss(lb, pr, name="hub")
    sd.loss.hingeLoss(lb, pr, name="hinge")
    sd.loss.cosineDistance(lb, pr, name="cos")
    sd.loss.logPoisson(lb, lg, name="lp")

    lsm = logits - np.log(np.exp(logits).sum(-1, keepdims=True))
    err = preds - labels
    absd = np.abs(err)
    quad = np.minimum(absd, 1.0)
    eps = 1e-7
    validate(TestCase(
        sd, {"lb": labels, "lg": logits, "pr": preds, "sp": sparse},
        {"mse": (err ** 2).mean(),
         "mae": absd.mean(),
         "sce": (-(labels * lsm).sum(-1)).mean(),
         "ssce": (-lsm[np.arange(3), sparse]).mean(),
         "bce": (np.maximum(logits, 0) - logits * labels
                 + np.log1p(np.exp(-np.abs(logits)))).mean(),
         "ll": (-(labels * np.log(preds + eps)
                  + (1 - labels) * np.log(1 - preds + eps))).mean(),
         "hub": (0.5 * quad ** 2 + (absd - quad)).mean(),
         "hinge": np.maximum(0.0, 1.0 - (2 * labels - 1) * preds)
         .mean(),
         "cos": (1.0 - (labels * preds).sum(-1)).mean(),
         "lp": (np.exp(logits) - labels * logits).mean()},
        grad_wrt=["lg"], max_rel_error=1e-3))


def test_loss_sweep():
    _run_loss_sweep()


def _run_math_misc():
    rng = np.random.default_rng(51)
    xv = rng.uniform(0.5, 2.0, size=(3, 4))
    sq = rng.normal(size=(4, 4))
    vec = rng.normal(size=(4,))
    a3 = rng.uniform(0.5, 2.0, size=(2, 3, 4))
    bools = xv > 1.0

    sd = SameDiff()
    x = sd.placeholder("x", (3, 4))
    s = sd.placeholder("s", (4, 4))
    v = sd.placeholder("v", (4,))
    t3 = sd.placeholder("t3", (2, 3, 4))
    sd._op("math.argmax", [x], name="amax", axis=1, keepdims=False)
    sd._op("math.argmin", [x], name="amin", axis=1, keepdims=False)
    sd._op("math.clip_by_value", [x], name="clip", lo=0.8, hi=1.5)
    sd._op("math.cumsum", [x], name="cs", axis=1)
    sd._op("math.cumprod", [x], name="cp", axis=1)
    sd._op("math.diag", [v], name="dg")
    sd._op("math.trace", [s], name="tr")
    sd._op("math.reverse", [x], name="rev", dims=(1,))
    sd._op("math.where", [sd._op("math.gt", [x, sd.constant(
        np.float64(1.0))], name="gt1")[0], x, sd.constant(
        np.zeros((3, 4)))], name="wh")
    sd._op("math.tensordot", [t3, s], name="td", axes_a=(2,), axes_b=(0,))
    sd._op("math.matmul", [x, s], name="mm", transpose_a=False,
           transpose_b=False)
    sd._op("math.tanh", [x], name="th")
    gt = sd._op("math.gt", [x, sd.constant(np.float64(1.0))], name="g")[0]
    lt = sd._op("math.lt", [x, sd.constant(np.float64(1.5))], name="l")[0]
    sd._op("math.logical_and", [gt, lt], name="land")
    sd._op("math.logical_or", [gt, lt], name="lor")
    sd._op("math.logical_xor", [gt, lt], name="lxor")
    sd._op("math.logical_not", [gt], name="lnot")

    g = xv > 1.0
    lt_ = xv < 1.5
    validate(TestCase(
        sd, {"x": xv, "s": sq, "v": vec, "t3": a3},
        {"amax": xv.argmax(1), "amin": xv.argmin(1),
         "clip": np.clip(xv, 0.8, 1.5),
         "cs": xv.cumsum(1), "cp": xv.cumprod(1),
         "dg": np.diag(vec), "tr": np.trace(sq),
         "rev": xv[:, ::-1],
         "wh": np.where(xv > 1.0, xv, 0.0),
         "td": np.tensordot(a3, sq, axes=([2], [0])),
         "mm": xv @ sq, "th": np.tanh(xv),
         "land": g & lt_, "lor": g | lt_, "lxor": g ^ lt_, "lnot": ~g},
        grad_wrt=[]))


def test_math_misc_sweep():
    _run_math_misc()


def _run_structural_misc():
    rng = np.random.default_rng(61)
    xv = rng.normal(size=(3, 4))
    idx = np.asarray([2, 0], np.int32)

    sd = SameDiff()
    x = sd.placeholder("x", (3, 4))
    iv = sd.placeholder("iv", (2,), dtype="int32")
    sd._op("identity", [x], name="id")
    sd._op("transpose", [x], name="tp")
    sd._op("concat", [x, x], name="cc", axis=0)
    sd._op("slice_op", [x], name="sl", begin=(1, 0), size=(2, 3))
    sd._op("gather", [x, iv], name="ga", axis=0)
    sd._op("one_hot", [iv], name="oh", depth=4)
    sd._op("shape_of", [x], name="sh")
    sd._op("zeros_like", [x], name="zl")
    sd._op("ones_like", [x], name="ol")
    sd._op("flatten2d", [sd._op("identity", [x], name="id2")[0]], name="fl")
    sd._op("softmax_flattened", [x], name="sf", axis=1)
    sd._op("reshape_onnx", [x], name="ro", shape=(0, -1))
    sd._op("unsqueeze_onnx", [x], name="uo", axes=(0,))
    sel = sd.placeholder("sel", (3,), dtype="bool")
    sd._op("select_tf", [sel, x, x * 0.0], name="st")
    xvar = sd.placeholder("xi", (3, 4))
    sd._op("getitem", [xvar], name="gi",
           index={"tuple": [{"slice": [0, 2, None]}, 1]})

    e = np.exp(xv - xv.max(1, keepdims=True))
    selv = np.asarray([True, False, True])
    validate(TestCase(
        sd, {"x": xv, "iv": idx, "sel": selv, "xi": xv},
        {"id": xv, "tp": xv.T, "cc": np.concatenate([xv, xv]),
         "sl": xv[1:3, 0:3], "ga": xv[idx],
         "oh": np.eye(4, dtype=np.float32)[idx],
         "sh": np.asarray([3, 4], np.int32),
         "zl": np.zeros_like(xv), "ol": np.ones_like(xv),
         "fl": xv.reshape(3, 4),
         "sf": e / e.sum(1, keepdims=True),
         "ro": xv, "uo": xv[None],
         "st": np.where(selv[:, None], xv, 0.0),
         "gi": xv[0:2, 1]},
        grad_wrt=[]))


def test_structural_misc_sweep():
    _run_structural_misc()


def _run_cnn_nn_extra():
    rng = np.random.default_rng(71)
    x1 = rng.normal(size=(2, 8, 3))            # NWC
    k1 = rng.normal(size=(3, 3, 5), scale=0.5)  # WIO
    x2 = rng.normal(size=(1, 4, 4, 2))
    kd = rng.normal(size=(2, 2, 1, 2), scale=0.5)  # HWIO, I=1 per group
    xf = rng.normal(size=(2, 6))

    sd = SameDiff()
    a = sd.placeholder("a", (2, 8, 3))
    w1 = sd.placeholder("w1", (3, 3, 5))
    b5 = sd.placeholder("b5", (5,))
    b2 = sd.placeholder("b2", (2,))
    c = sd.placeholder("c", (1, 4, 4, 2))
    wd = sd.placeholder("wd", (2, 2, 1, 2))
    f = sd.placeholder("f", (2, 6))
    mean = sd.placeholder("mean", (6,))
    var = sd.placeholder("var", (6,))
    gamma = sd.placeholder("gamma", (6,))
    beta = sd.placeholder("beta", (6,))
    sd._op("cnn.conv1d", [a, w1, b5], name="c1", stride=1, padding="VALID")
    sd._op("cnn.depthwiseConv2d", [c, wd, b2], name="dw", strides=(1, 1),
           padding="VALID")
    sd._op("cnn.upsampling2d", [c], name="up", scale=2)
    sd._op("nn.hardSigmoid", [f], name="hs")
    sd._op("nn.hardTanh", [f], name="ht")
    sd._op("nn.batchNorm", [f, mean, var, gamma, beta], name="bn",
           axis=-1, eps=1e-5)

    conv1 = np.zeros((2, 6, 5))
    for i in range(6):
        conv1[:, i, :] = np.einsum("bwc,wco->bo", x1[:, i:i + 3, :], k1)
    dw = np.zeros((1, 3, 3, 2))
    for i in range(3):
        for j in range(3):
            patch = x2[:, i:i + 2, j:j + 2, :]
            dw[:, i, j, :] = np.einsum("bhwc,hwc->bc", patch, kd[:, :, 0, :])
    mv = rng.normal(size=(6,))
    vv = rng.uniform(0.5, 1.5, size=(6,))
    gv = rng.normal(size=(6,))
    bv = rng.normal(size=(6,))
    validate(TestCase(
        sd, {"a": x1, "w1": k1, "b5": np.zeros(5), "c": x2, "wd": kd,
             "b2": np.zeros(2), "f": xf, "mean": mv, "var": vv,
             "gamma": gv, "beta": bv},
        {"c1": conv1, "dw": dw,
         "up": x2.repeat(2, axis=1).repeat(2, axis=2),
         "hs": np.clip(xf / 6.0 + 0.5, 0.0, 1.0),  # jax hard_sigmoid slope
         "ht": np.clip(xf, -1.0, 1.0),
         "bn": gv * (xf - mv) / np.sqrt(vv + 1e-5) + bv},
        grad_wrt=[], max_rel_error=1e-3))


def test_cnn_nn_extra_sweep():
    _run_cnn_nn_extra()


# Ops whose validation lives OUTSIDE this harness, each with the test that
# covers it (reference OpValidation keeps an equivalent exclusion list for
# ops covered by dedicated suites). Adding a NEW op to the registry
# without either a sweep entry here or an exemption fails the gate below.
_EXEMPT = {
    "cond": "tests/test_samediff.py control-flow exec/serde",
    "while_loop": "tests/test_samediff.py control-flow exec/serde",
    "scan_op": "tests/test_samediff.py control-flow exec/serde",
    "rnn.lstmLayer": "tests/test_samediff.py LSTM training",
    "rnn.gru": "tests/test_samediff.py GRU exec",
    "rnn.simpleRnn": "tests/test_samediff.py simpleRnn exec",
    "nn.dropout": "stochastic; tests/test_samediff.py dropout statistics",
    "random.normal": "stochastic; tests/test_samediff.py rng determinism",
    "random.uniform": "stochastic; tests/test_samediff.py rng determinism",
    "random.bernoulli": "stochastic; tests/test_samediff.py rng determinism",
    "nn.dotProductAttention": "tests/test_attention_layers.py",
    "nn.multiHeadDotProductAttention": "tests/test_attention_layers.py",
    "random.exponential": "stochastic; test_random_round3_statistics",
    "random.gamma": "stochastic; test_random_round3_statistics",
    "random.poisson": "stochastic; test_random_round3_statistics",
    "random.logNormal": "stochastic; test_random_round3_statistics",
    "random.truncatedNormal": "stochastic; test_random_round3_statistics",
    "random.shuffle": "stochastic; test_random_round3_statistics",
    "random.multinomial": "stochastic; test_round4_stochastic_ops_statistics",
    "image.randomCrop": "stochastic; test_round4_stochastic_ops_statistics",
}


@pytest.mark.slow
def test_coverage_registry_complete():
    """THE coverage gate (reference: OpValidation coverage accounting
    fails CI for registered-but-untested ops). Runs every sweep in this
    module in-process, then requires the missing set to be exactly the
    documented exemptions.

    Marked slow (round 6): it re-executes every sweep this module ALREADY
    runs as individual tier-1 tests (~95s of duplicate f64 work purely to
    populate one process-local coverage set); the tier-1 budget is hard
    (ROADMAP 870s) and the per-op validation itself still runs there.
    Run explicitly (``pytest -m slow tests/test_op_validation.py``) for
    the registry-completeness assertion."""
    test_coverage_after_sweep()
    for case in _NN_SWEEP:
        _run_nn_unary(*case)
    r = np.random.default_rng(0)
    test_nn_composite_sweep(r)
    test_cnn_ops_sweep(r)
    test_shape_op_sweep(r)
    for op, oracle, check_grad in _SCATTER_SWEEP:
        _run_scatter(op, oracle, check_grad=False)
    _run_gather_segment()
    _run_linalg()
    _run_image()
    _run_nms()
    _run_bitwise()
    _run_loss_sweep()
    _run_math_misc()
    _run_structural_misc()
    _run_cnn_nn_extra()
    _run_reduce3()
    _run_stats_misc()
    _run_cnn_round3()
    _run_cnn_pool_space_round3()
    _run_cnn_lrn_im2col_round3()
    _run_rnn_cells_round3()
    _run_math_round3()
    _run_math_structural_round3()
    _run_nn_image_round3()
    _run_linalg_segment_loss_round3()
    _run_einsum_gathernd_topk_round3()
    _run_where_sparse_ce_round4()
    _run_round4_ctc_fft_embed()
    _run_round4_tail_math()
    _run_round4_tail_misc()
    rep = coverage_report()
    unexpected = sorted(set(rep["missing"]) - set(_EXEMPT))
    assert not unexpected, (
        f"registered ops without validation coverage: {unexpected} — add a "
        "sweep entry in test_op_validation.py or an explicit exemption "
        "with a pointer to the covering test")
    assert rep["validated"] >= 350, rep["validated"]


# --- round 4: bounded Where + TF twin-output sparse CE ----------------------

def _run_where_sparse_ce_round4():
    rng = np.random.default_rng(96)
    xv = rng.normal(size=(3, 4))
    xv[xv < 0.4] = 0.0
    lv = rng.normal(size=(3, 5))
    labels = np.asarray([1, 4, 0], np.int32)

    sd = SameDiff()
    px = sd.placeholder("x", (3, 4))
    wi, wc = sd.math.whereNonzero(px, name="wn")
    wi.rename("wi"); wc.rename("wc")
    # forward-only: integer outputs
    want = np.argwhere(xv)
    wi_want = np.zeros((12, 2), np.int32)
    wi_want[:len(want)] = want
    validate(TestCase(sd, {"x": xv},
                      {"wi": wi_want, "wc": np.int32(len(want))},
                      grad_wrt=[]))

    sd2 = SameDiff()
    pl = sd2.placeholder("lg", (3, 5))
    pt = sd2.placeholder("lb", (3,))
    per, bp = sd2.loss.sparseSoftmaxCrossEntropyWithLogits(pt, pl,
                                                           name="ce")
    per.rename("ce_l"); bp.rename("ce_b")
    e = np.exp(lv - lv.max(axis=-1, keepdims=True))
    sm = e / e.sum(axis=-1, keepdims=True)
    onehot = np.eye(5)[labels]
    validate(TestCase(
        sd2, {"lg": lv, "lb": labels},
        {"ce_l": -np.log(sm[np.arange(3), labels]), "ce_b": sm - onehot},
        grad_wrt=["lg"], max_rel_error=1e-3))


def test_where_sparse_ce_round4_sweep():
    _run_where_sparse_ce_round4()


# --- round 2b: reduce3 distances / statistics / misc math -------------------

def _run_reduce3():
    rng = np.random.default_rng(81)
    xv = rng.uniform(0.2, 2.0, size=(3, 4))
    yv = rng.uniform(0.2, 2.0, size=(3, 4))
    sd = SameDiff()
    x = sd.placeholder("x", (3, 4))
    y = sd.placeholder("y", (3, 4))
    sd.math.euclideanDistance(x, y, dims=(1,), name="eu")
    sd.math.manhattanDistance(x, y, dims=(1,), name="mh")
    sd.math.cosineSimilarity(x, y, dims=(1,), name="cs")
    sd.math.cosineDistance(x, y, dims=(1,), name="cd")
    sd.math.dot(x, y, dims=(1,), name="dt")
    sd.math.hammingDistance(x, y, dims=(1,), name="hm")
    sd.math.jaccardDistance(x, y, dims=(1,), name="jc")
    cs = (xv * yv).sum(1) / (np.linalg.norm(xv, axis=1)
                             * np.linalg.norm(yv, axis=1) + 1e-12)
    validate(TestCase(sd, {"x": xv, "y": yv}, {
        "eu": np.sqrt(((xv - yv) ** 2).sum(1)),
        "mh": np.abs(xv - yv).sum(1),
        "cs": cs, "cd": 1.0 - cs,
        "dt": (xv * yv).sum(1),
        "hm": (xv != yv).sum(1).astype(np.float64),
        "jc": 1.0 - np.minimum(xv, yv).sum(1)
        / (np.maximum(xv, yv).sum(1) + 1e-12),
    }, grad_wrt=["x", "y"], max_rel_error=1e-3))


def test_reduce3_sweep():
    _run_reduce3()


def _run_stats_misc():
    rng = np.random.default_rng(82)
    p = rng.uniform(0.05, 1.0, size=(2, 5))
    p = p / p.sum(1, keepdims=True)          # distributions per row
    xv = rng.uniform(0.5, 2.0, size=(2, 5))
    xz = xv.copy()
    xz[0, 1] = 0.0                            # a zero for countZero
    v3a = rng.normal(size=(4, 3))
    v3b = rng.normal(size=(4, 3))

    sd = SameDiff()
    pp = sd.placeholder("p", (2, 5))
    x = sd.placeholder("x", (2, 5))
    xzv = sd.placeholder("xz", (2, 5))
    a3 = sd.placeholder("a3", (4, 3))
    b3 = sd.placeholder("b3", (4, 3))
    sd.math.entropy(pp, dims=(1,), name="ent")
    sd.math.logEntropy(pp, dims=(1,), name="lent")
    sd.math.shannonEntropy(pp, dims=(1,), name="sent")
    sd.math.amean(x, dims=(1,), name="am")
    sd.math.asum(x, dims=(1,), name="as")
    sd.math.countZero(xzv, dims=(1,), name="cz")
    sd.math.zeroFraction(xzv, dims=(1,), name="zf")
    sd.math.standardize(x, dims=(1,), name="std")
    sd.math.isMax(x, dims=(1,), name="im")
    sd.math.cross(a3, b3, name="cr")
    sd.math.lgamma(x, name="lg")
    sd.math.digamma(x, name="dg")
    sd.math.rint(x, name="ri")

    import scipy.special as sps

    ent = -(p * np.log(p + 1e-12)).sum(1)
    mu = xv.mean(1, keepdims=True)
    sdv = xv.std(1, keepdims=True)
    validate(TestCase(
        sd, {"p": p, "x": xv, "xz": xz, "a3": v3a, "b3": v3b},
        {"ent": ent, "lent": np.log(ent + 1e-12),
         "sent": -(p * np.log2(p + 1e-12)).sum(1),
         "am": np.abs(xv).mean(1), "as": np.abs(xv).sum(1),
         "cz": (xz == 0).sum(1), "zf": (xz == 0).mean(1),
         "std": (xv - mu) / (sdv + 1e-12),
         "im": np.eye(5)[xv.argmax(1)],
         "cr": np.cross(v3a, v3b),
         "lg": sps.gammaln(xv), "dg": sps.digamma(xv),
         "ri": np.rint(xv)},
        grad_wrt=[], max_rel_error=1e-3))


def test_stats_misc_sweep():
    _run_stats_misc()


def test_is_max_tie_breaks_to_single_one():
    """Reference IsMax semantics: exactly one 1 on tied maxima."""
    sd = SameDiff()
    x = sd.placeholder("x", (2, 3))
    sd.math.isMax(x, dims=(1,), name="im")
    out = sd.output({"x": np.asarray([[1.0, 3.0, 3.0],
                                      [2.0, 2.0, 1.0]])}, "im")
    got = np.asarray(out["im"])
    np.testing.assert_allclose(got.sum(1), [1.0, 1.0])
    np.testing.assert_allclose(got, [[0, 1, 0], [1, 0, 0]])


# --- round 3: cnn 3d / transposed / space-batch family ----------------------

def _deconv_scatter_oracle(x, w, strides):
    """Transposed conv, VALID padding, by direct scatter-add (pure
    numpy loops — deliberately independent of lax.conv_transpose)."""
    n = x.shape[0]
    spatial_in = x.shape[1:-1]
    c_out = w.shape[-1]
    k = w.shape[:-2]
    out_spatial = tuple((i - 1) * s + kk
                        for i, s, kk in zip(spatial_in, strides, k))
    out = np.zeros((n,) + out_spatial + (c_out,), dtype=np.float64)
    for idx in np.ndindex(*spatial_in):
        for kidx in np.ndindex(*k):
            pos = tuple(i * s + p for i, s, p in zip(idx, strides, kidx))
            out[(slice(None),) + pos] += x[(slice(None),) + idx] @ w[kidx]
    return out


def _run_cnn_round3():
    import jax as _jax

    rng = np.random.default_rng(91)
    x3 = rng.normal(size=(1, 3, 4, 4, 2))
    w3 = rng.normal(size=(2, 2, 2, 2, 3), scale=0.5)
    x2 = rng.normal(size=(1, 4, 4, 2))
    wdc = rng.normal(size=(2, 2, 2, 3), scale=0.5)
    wd = rng.normal(size=(2, 2, 1, 2), scale=0.5)
    wp = rng.normal(size=(1, 1, 2, 4), scale=0.5)

    sd = SameDiff()
    a3 = sd.placeholder("a3", (1, 3, 4, 4, 2))
    k3 = sd.placeholder("k3", (2, 2, 2, 2, 3))
    a2 = sd.placeholder("a2", (1, 4, 4, 2))
    kdc = sd.placeholder("kdc", (2, 2, 2, 3))
    kd = sd.placeholder("kd", (2, 2, 1, 2))
    kp = sd.placeholder("kp", (1, 1, 2, 4))
    sd.cnn.conv3d(a3, k3, strides=(1, 1, 1), padding="VALID", name="c3")
    sd.cnn.deconv2d(a2, kdc, strides=(2, 2), padding="VALID", name="d2")
    sd.cnn.deconv3d(a3, k3, strides=(1, 1, 1), padding="VALID", name="d3")
    sd.cnn.sconv2d(a2, kd, kp, strides=(1, 1), padding="VALID", name="sc")

    dn3 = ("NDHWC", "DHWIO", "NDHWC")
    dn2 = ("NHWC", "HWIO", "NHWC")
    want_c3 = np.asarray(_jax.lax.conv_general_dilated(
        x3, w3, (1, 1, 1), "VALID", dimension_numbers=dn3))
    # independent scatter-add oracle for transposed conv (the round-3
    # oracle restated the implementation's conv_transpose call, which
    # could not catch the missing spatial kernel flip):
    # out[n, i*s+p, ..., o] += x[n, i, ..., c] * w[p, ..., c, o]
    want_d2 = _deconv_scatter_oracle(x2, wdc, (2, 2))
    want_d3 = _deconv_scatter_oracle(x3, w3, (1, 1, 1))
    dwo = _jax.lax.conv_general_dilated(
        x2, wd, (1, 1), "VALID", feature_group_count=2,
        dimension_numbers=dn2)
    want_sc = np.asarray(_jax.lax.conv_general_dilated(
        dwo, wp, (1, 1), "VALID", dimension_numbers=dn2))
    validate(TestCase(
        sd, {"a3": x3, "k3": w3, "a2": x2, "kdc": wdc, "kd": wd, "kp": wp},
        {"c3": want_c3, "d2": want_d2, "d3": want_d3, "sc": want_sc},
        max_rel_error=1e-3))


def test_cnn_round3_sweep():
    _run_cnn_round3()


def test_deconv2d_same_matches_layer():
    """SAME-padded sd.cnn.deconv2d == the Deconvolution2D layer on the
    same weights (the sd default is SAME; lax.conv_transpose's SAME pads
    the dilated input one pixel differently, so the op computes its
    padding explicitly — this pins the two code paths to one
    convention, out = i*s with an asymmetric kernel)."""
    import jax.numpy as jnp

    from deeplearning4j_tpu.conf.layers_cnn import (ConvolutionMode,
                                                    Deconvolution2D)

    rng = np.random.default_rng(95)
    x = rng.normal(size=(1, 5, 5, 2)).astype(np.float32)
    w = rng.normal(size=(3, 2, 2, 4), scale=0.5).astype(np.float32)

    sd = SameDiff()
    px = sd.placeholder("x", x.shape)
    pw = sd.placeholder("w", w.shape)
    sd.cnn.deconv2d(px, pw, strides=(2, 2), padding="SAME", name="d2")
    got = np.asarray(sd.output({"x": x, "w": w}, "d2")["d2"])
    assert got.shape == (1, 10, 10, 4)

    layer = Deconvolution2D(n_out=4, kernel_size=(3, 2),
                            stride=(2, 2), has_bias=False,
                            convolution_mode=ConvolutionMode.SAME)
    want, _ = layer.forward({"W": jnp.asarray(w)}, None, jnp.asarray(x))
    np.testing.assert_allclose(got, np.asarray(want), rtol=1e-4,
                               atol=1e-5)


def _run_cnn_pool_space_round3():
    rng = np.random.default_rng(92)
    x1 = rng.normal(size=(2, 6, 3))
    x3 = rng.normal(size=(1, 4, 4, 4, 2))
    x2 = rng.normal(size=(1, 4, 4, 8))

    sd = SameDiff()
    a1 = sd.placeholder("a1", (2, 6, 3))
    a3 = sd.placeholder("a3", (1, 4, 4, 4, 2))
    a2 = sd.placeholder("a2", (1, 4, 4, 8))
    sd.cnn.maxPooling1d(a1, k=2, s=2, name="mp1")
    sd.cnn.avgPooling1d(a1, k=2, s=2, name="ap1")
    sd.cnn.maxPooling3d(a3, k=(2, 2, 2), s=(2, 2, 2), name="mp3")
    sd.cnn.avgPooling3d(a3, k=(2, 2, 2), s=(2, 2, 2), name="ap3")
    sd.cnn.upsampling1d(a1, scale=2, name="up1")
    sd.cnn.upsampling3d(a3, scale=2, name="up3")
    sd.cnn.spaceToDepth(a2, block=2, name="s2d")
    sd.cnn.depthToSpace(a2, block=2, name="d2s")
    sd.cnn.spaceToBatch(a2, block=2, name="s2b")
    sd.cnn.batchToSpace(sd.cnn.spaceToBatch(a2, block=2), block=2,
                        name="b2s_rt")

    mp1 = x1.reshape(2, 3, 2, 3).max(axis=2)
    ap1 = x1.reshape(2, 3, 2, 3).mean(axis=2)
    x3b = x3.reshape(1, 2, 2, 2, 2, 2, 2, 2)
    mp3 = x3b.max(axis=(2, 4, 6))
    ap3 = x3b.mean(axis=(2, 4, 6))
    # independent numpy oracle for space<->depth (TF semantics)
    n, h, w, c = x2.shape
    s2d = x2.reshape(n, h // 2, 2, w // 2, 2, c).transpose(
        0, 1, 3, 2, 4, 5).reshape(n, h // 2, w // 2, 4 * c)
    d2s = x2.reshape(n, h, w, 2, 2, c // 4).transpose(
        0, 1, 3, 2, 4, 5).reshape(n, h * 2, w * 2, c // 4)
    s2b = x2.reshape(n, h // 2, 2, w // 2, 2, c).transpose(
        2, 4, 0, 1, 3, 5).reshape(4 * n, h // 2, w // 2, c)
    validate(TestCase(
        sd, {"a1": x1, "a3": x3, "a2": x2},
        {"mp1": mp1, "ap1": ap1, "mp3": mp3, "ap3": ap3,
         "up1": x1.repeat(2, axis=1),
         "up3": x3.repeat(2, axis=1).repeat(2, axis=2).repeat(2, axis=3),
         "s2d": s2d, "d2s": d2s, "s2b": s2b, "b2s_rt": x2},
        max_rel_error=1e-3))


def test_cnn_pool_space_round3_sweep():
    _run_cnn_pool_space_round3()


def _run_cnn_lrn_im2col_round3():
    rng = np.random.default_rng(93)
    x = rng.normal(size=(1, 3, 3, 4))
    xw = rng.normal(size=(1, 4, 4, 2))
    wdil = rng.normal(size=(2, 2, 2), scale=0.5)

    sd = SameDiff()
    a = sd.placeholder("a", (1, 3, 3, 4))
    aw = sd.placeholder("aw", (1, 4, 4, 2))
    kdil = sd.placeholder("kdil", (2, 2, 2))
    sd.cnn.localResponseNormalization(a, depth=1, bias=1.0, alpha=0.5,
                                      beta=0.75, name="lrn")
    cols = sd.cnn.im2col(aw, k=(2, 2), s=(1, 1), padding="VALID",
                         name="cols")
    sd.cnn.col2im(cols, shape=(1, 4, 4, 2), k=(2, 2), s=(1, 1),
                  padding="VALID", name="img")
    sd.cnn.dilation2d(aw, kdil, strides=(1, 1), rates=(1, 1), name="dil")

    # LRN numpy oracle (across-channel window +-1)
    lrn = np.zeros_like(x)
    for c in range(4):
        lo, hi = max(0, c - 1), min(4, c + 2)
        ssum = (x[..., lo:hi] ** 2).sum(-1)
        lrn[..., c] = x[..., c] / (1.0 + 0.5 * ssum) ** 0.75
    # im2col: channel-major (c, kh, kw) feature ordering per patch
    cols_np = np.zeros((1, 3, 3, 8))
    for i in range(3):
        for j in range(3):
            patch = xw[0, i:i + 2, j:j + 2, :]          # [2, 2, C]
            cols_np[0, i, j] = patch.transpose(2, 0, 1).reshape(-1)
    # col2im: scatter-add the SAME patches back
    img_np = np.zeros((1, 4, 4, 2))
    for i in range(3):
        for j in range(3):
            img_np[0, i:i + 2, j:j + 2, :] += cols_np[0, i, j].reshape(
                2, 2, 2).transpose(1, 2, 0)
    dil = np.zeros((1, 3, 3, 2))
    for i in range(3):
        for j in range(3):
            dil[0, i, j] = (xw[0, i:i + 2, j:j + 2, :] + wdil).max((0, 1))
    validate(TestCase(
        sd, {"a": x, "aw": xw, "kdil": wdil},
        {"lrn": lrn, "cols": cols_np, "img": img_np, "dil": dil},
        max_rel_error=1e-3))


def test_cnn_lrn_im2col_round3_sweep():
    _run_cnn_lrn_im2col_round3()


# --- round 3: rnn cells -----------------------------------------------------

def _sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


def _run_rnn_cells_round3():
    rng = np.random.default_rng(94)
    B, I, H = 2, 3, 4
    x = rng.normal(size=(B, I))
    h0 = rng.normal(size=(B, H)) * 0.3
    c0 = rng.normal(size=(B, H)) * 0.3
    wl = rng.normal(size=(I, 4 * H), scale=0.4)
    rl = rng.normal(size=(H, 4 * H), scale=0.4)
    bl = rng.normal(size=(4 * H,), scale=0.1)
    wg = rng.normal(size=(I, 3 * H), scale=0.4)
    rg = rng.normal(size=(H, 3 * H), scale=0.4)
    bg = rng.normal(size=(3 * H,), scale=0.1)
    xs = rng.normal(size=(B, H))           # sru needs I == H
    cs = rng.normal(size=(B, H)) * 0.3
    ws = rng.normal(size=(H, 3 * H), scale=0.4)
    bs = rng.normal(size=(2 * H,), scale=0.1)
    xseq = rng.normal(size=(3, B, H))

    sd = SameDiff()
    px = sd.placeholder("x", (B, I))
    ph = sd.placeholder("h", (B, H))
    pc = sd.placeholder("c", (B, H))
    pwl = sd.placeholder("wl", (I, 4 * H))
    prl = sd.placeholder("rl", (H, 4 * H))
    pbl = sd.placeholder("bl", (4 * H,))
    pwg = sd.placeholder("wg", (I, 3 * H))
    prg = sd.placeholder("rg", (H, 3 * H))
    pbg = sd.placeholder("bg", (3 * H,))
    pxs = sd.placeholder("xs", (B, H))
    pcs = sd.placeholder("cs", (B, H))
    pws = sd.placeholder("ws", (H, 3 * H))
    pbs = sd.placeholder("bs", (2 * H,))
    pxq = sd.placeholder("xq", (3, B, H))
    hh, cc = sd.rnn.lstmCell(px, ph, pc, pwl, prl, pbl, name="lc")
    hh.rename("lc_h"); cc.rename("lc_c")
    sd.rnn.gruCell(px, ph, pwg, prg, pbg, name="gc")
    sh, scc = sd.rnn.sruCell(pxs, pcs, pws, pbs, name="sc")
    sh.rename("sc_h"); scc.rename("sc_c")
    ys, cf = sd.rnn.sru(pxq, pws, pbs, pcs, name="sr")
    ys.rename("sr_y"); cf.rename("sr_c")

    # numpy oracles of the same gate formulas
    z = x @ wl + h0 @ rl + bl
    i, f, g, o = (z[:, :H], z[:, H:2 * H], z[:, 2 * H:3 * H], z[:, 3 * H:])
    lc_c = _sigmoid(f) * c0 + _sigmoid(i) * np.tanh(g)
    lc_h = _sigmoid(o) * np.tanh(lc_c)
    zx = x @ wg + bg
    zh = h0 @ rg
    rgt = _sigmoid(zx[:, :H] + zh[:, :H])
    zgt = _sigmoid(zx[:, H:2 * H] + zh[:, H:2 * H])
    # original Cho et al. candidate — reset applied to the STATE before
    # the recurrent matmul (reference gruCell semantics, round-3 advisor;
    # the reset_after variant rgt * zh would differ numerically here)
    ngt = np.tanh(zx[:, 2 * H:] + (rgt * h0) @ rg[:, 2 * H:])
    gc = (1 - zgt) * ngt + zgt * h0

    def sru_step_np(xt, c):
        wx = xt @ ws
        xt_t = wx[:, :H]
        fg = _sigmoid(wx[:, H:2 * H] + bs[:H])
        rg_ = _sigmoid(wx[:, 2 * H:] + bs[H:])
        c_new = fg * c + (1 - fg) * xt_t
        h_new = rg_ * np.tanh(c_new) + (1 - rg_) * xt
        return h_new, c_new

    sc_h, sc_c = sru_step_np(xs, cs)
    c = cs
    sr_y = np.zeros((3, B, H))
    for t in range(3):
        sr_y[t], c = sru_step_np(xseq[t], c)
    validate(TestCase(
        sd, {"x": x, "h": h0, "c": c0, "wl": wl, "rl": rl, "bl": bl,
             "wg": wg, "rg": rg, "bg": bg, "xs": xs, "cs": cs, "ws": ws,
             "bs": bs, "xq": xseq},
        {"lc_h": lc_h, "lc_c": lc_c, "gc": gc, "sc_h": sc_h, "sc_c": sc_c,
         "sr_y": sr_y, "sr_c": c},
        max_rel_error=1e-3))


def test_rnn_cells_round3_sweep():
    _run_rnn_cells_round3()


# --- round 3: math transforms / merges / special functions ------------------

def _run_math_round3():
    import scipy.special as sps

    rng = np.random.default_rng(95)
    xv = rng.normal(size=(2, 5))
    yv = rng.normal(size=(2, 5))
    zv = rng.normal(size=(2, 5))
    pos = rng.uniform(0.5, 3.0, size=(2, 4))
    q = rng.uniform(0.5, 2.0, size=(2, 4))
    ab = rng.uniform(1.0, 3.0, size=(2, 4))
    xb = rng.uniform(0.05, 0.95, size=(2, 4))
    bm_a = rng.normal(size=(3, 2, 4))
    bm_b = rng.normal(size=(3, 4, 2))

    sd = SameDiff()
    x = sd.placeholder("x", (2, 5))
    y = sd.placeholder("y", (2, 5))
    z = sd.placeholder("z", (2, 5))
    p = sd.placeholder("p", (2, 4))
    pq = sd.placeholder("q", (2, 4))
    pa = sd.placeholder("pa", (2, 4))
    pxb = sd.placeholder("pxb", (2, 4))
    ba = sd.placeholder("ba", (3, 2, 4))
    bb = sd.placeholder("bb", (3, 4, 2))
    sd.math.cube(x, name="cu")
    sd.math.oneMinus(x, name="om")
    sd.math.step(x, cutoff=0.1, name="st")
    sd.math.rationalTanh(x, name="rt")
    sd.math.rectifiedTanh(x, name="rh")
    sd.math.fmod(x, sd.math.oneMinus(sd.math.step(y, cutoff=100.0)) * 2.0
                 + 0.5, name="fm")
    sd.math.lerp(x, y, 0.3, name="lp")
    sd.math.mergeAdd(x, y, z, name="ma")
    sd.math.mergeAvg(x, y, z, name="mv")
    sd.math.mergeMax(x, y, z, name="mm")
    sd.math.logSumExp(x, dims=(1,), name="lse")
    sd.math.zeta(p + 1.5, pq, name="zt")
    sd.math.polygamma(p, n=1, name="pg")
    sd.math.igamma(pa, pxb, name="ig")
    sd.math.igammac(pa, pxb, name="ic")
    sd.math.betainc(pa, pa, pxb, name="bi")
    sd.math.clipByNorm(x, 1.5, dims=(1,), name="cn")
    sd.math.clipByAvgNorm(x, 0.1, dims=(1,), name="ca")
    sd.math.batchMmul(ba, bb, name="bm")

    ry = 0.5 + 2.0 * (1.0 - (yv > 100.0))   # == 2.5 everywhere
    yy = 2.0 * xv / 3.0
    rt = 1.7159 * np.sign(yy) * (
        1.0 - 1.0 / (1.0 + np.abs(yy) + yy ** 2 + 1.41645 * yy ** 4))
    nrm = np.sqrt((xv ** 2).sum(1, keepdims=True))
    cn = np.where(nrm > 1.5, xv * 1.5 / nrm, xv)
    avg = nrm / 5.0
    ca = np.where(avg > 0.1, xv * 0.1 / np.maximum(avg, 1e-30), xv)
    validate(TestCase(
        sd, {"x": xv, "y": yv, "z": zv, "p": pos, "q": q, "pa": ab,
             "pxb": xb, "ba": bm_a, "bb": bm_b},
        {"cu": xv ** 3, "om": 1.0 - xv,
         "st": (xv > 0.1).astype(np.float64),
         "rt": rt, "rh": np.maximum(0.0, np.tanh(xv)),
         "fm": np.fmod(xv, ry), "lp": xv + 0.3 * (yv - xv),
         "ma": xv + yv + zv, "mv": (xv + yv + zv) / 3.0,
         "mm": np.maximum(np.maximum(xv, yv), zv),
         "lse": sps.logsumexp(xv, axis=1),
         "zt": sps.zeta(pos + 1.5, q),
         "pg": sps.polygamma(1, pos),
         "ig": sps.gammainc(ab, xb), "ic": sps.gammaincc(ab, xb),
         "bi": sps.betainc(ab, ab, xb),
         "cn": cn, "ca": ca,
         "bm": bm_a @ bm_b},
        grad_wrt=["x", "y", "z", "ba", "bb"], max_rel_error=1e-3))


def test_math_round3_sweep():
    _run_math_round3()


def _run_math_structural_round3():
    rng = np.random.default_rng(96)
    xv = np.asarray([1.0, 2.0, 5.0, 7.0])
    seq = rng.normal(size=(3, 5, 2))
    lens = np.asarray([2, 5, 0])
    labels = np.asarray([0, 1, 2, 1])
    preds = np.asarray([0, 2, 2, 1])
    ints = np.asarray([0, 1, 1, 3, 1])
    i1 = np.asarray([0, 2])
    i2 = np.asarray([1, 3])
    d1 = rng.normal(size=(2, 3))
    d2 = rng.normal(size=(2, 3))
    mg_x = np.asarray([1.0, 2.0, 3.0])
    mg_y = np.asarray([4.0, 5.0])

    sd = SameDiff()
    x = sd.placeholder("x", (4,))
    s = sd.placeholder("s", (3, 5, 2))
    ln = sd.placeholder("ln", (3,))
    lb = sd.placeholder("lb", (4,))
    pr = sd.placeholder("pr", (4,))
    iv = sd.placeholder("iv", (5,))
    pi1 = sd.placeholder("i1", (2,))
    pi2 = sd.placeholder("i2", (2,))
    pd1 = sd.placeholder("d1", (2, 3))
    pd2 = sd.placeholder("d2", (2, 3))
    mx = sd.placeholder("mx", (3,))
    my = sd.placeholder("my", (2,))
    sd.math.isStrictlyIncreasing(x, name="isi")
    sd.math.isNonDecreasing(x, name="ind")
    sd.math.sequenceMask(ln, maxlen=5, name="sm")
    sd.math.reverseSequence(s, ln, seq_axis=1, batch_axis=0, name="rs")
    sd.math.confusionMatrix(lb, pr, 3, name="cm")
    sd.math.bincount(iv, length=4, name="bc")
    sd.math.dynamicStitch([pi1, pi2], [pd1, pd2], name="ds")
    g1, g2 = sd.math.moments(s, dims=(1, 2), name="mo")
    g1.rename("mo_mean"); g2.rename("mo_var")
    m1, m2 = sd.math.meshgrid(mx, my, name="mg")
    m1.rename("mg_x"); m2.rename("mg_y")

    rs = seq.copy()
    for b in range(3):
        L = lens[b]
        rs[b, :L] = seq[b, :L][::-1]
    cm = np.zeros((3, 3), np.int32)
    for l, pp in zip(labels, preds):
        cm[l, pp] += 1
    ds = np.zeros((4, 3))
    ds[i1] = d1
    ds[i2] = d2
    mgx, mgy = np.meshgrid(mg_x, mg_y)
    validate(TestCase(
        sd, {"x": xv, "s": seq, "ln": lens, "lb": labels, "pr": preds,
             "iv": ints, "i1": i1, "i2": i2, "d1": d1, "d2": d2,
             "mx": mg_x, "my": mg_y},
        {"isi": 1.0, "ind": 1.0,
         "sm": (np.arange(5)[None] < lens[:, None]).astype(np.float64),
         "rs": rs, "cm": cm, "bc": np.bincount(ints, minlength=4),
         "ds": ds, "mo_mean": seq.mean((1, 2)), "mo_var": seq.var((1, 2)),
         "mg_x": mgx, "mg_y": mgy},
        grad_wrt=[], max_rel_error=1e-3))


def test_math_structural_round3_sweep():
    _run_math_structural_round3()


# --- round 3: nn activations / image color / linalg / segment / loss --------

def _run_nn_image_round3():
    import scipy.special as sps

    rng = np.random.default_rng(97)
    xv = rng.normal(size=(2, 6))
    alpha = rng.uniform(0.1, 0.4, size=(6,))
    img = rng.uniform(0.0, 1.0, size=(1, 3, 3, 3))

    sd = SameDiff()
    x = sd.placeholder("x", (2, 6))
    al = sd.placeholder("al", (6,))
    im = sd.placeholder("im", (1, 3, 3, 3))
    sd.nn.prelu(x, al, name="pr")
    sd.nn.crelu(x, name="cr")
    sd.nn.logSigmoid(x, name="ls")
    sd.nn.thresholdRelu(x, cutoff=0.2, name="tr")
    sd.nn.preciseGelu(x, name="pg")
    sd.image.rgbToYuv(im, name="yuv")
    sd.image.yuvToRgb(sd.image.rgbToYuv(im), name="yuv_rt")
    sd.image.rgbToYiq(im, name="yiq")
    sd.image.yiqToRgb(sd.image.rgbToYiq(im), name="yiq_rt")
    sd.image.resizeBicubic(im, 3, 3, name="bc")       # identity size
    sd.image.imageResize(im, 6, 6, method="nearest", name="rn")

    yuv_m = np.array([[0.299, 0.587, 0.114],
                      [-0.14714119, -0.28886916, 0.43601035],
                      [0.61497538, -0.51496512, -0.10001026]])
    yiq_m = np.array([[0.299, 0.587, 0.114],
                      [0.59590059, -0.27455667, -0.32134392],
                      [0.21153661, -0.52273617, 0.31119955]])
    validate(TestCase(
        sd, {"x": xv, "al": alpha, "im": img},
        {"pr": np.where(xv >= 0, xv, alpha * xv),
         "cr": np.concatenate([np.maximum(xv, 0), np.maximum(-xv, 0)], -1),
         "ls": np.log(1.0 / (1.0 + np.exp(-xv))),
         "tr": np.where(xv > 0.2, xv, 0.0),
         "pg": 0.5 * xv * (1.0 + sps.erf(xv / np.sqrt(2.0))),
         "yuv": img @ yuv_m.T, "yuv_rt": img,
         "yiq": img @ yiq_m.T, "yiq_rt": img,
         "bc": img, "rn": img.repeat(2, axis=1).repeat(2, axis=2)},
        grad_wrt=["x", "al"], max_rel_error=1e-3))


def test_nn_image_round3_sweep():
    _run_nn_image_round3()


def _run_linalg_segment_loss_round3():
    import scipy.linalg as spl

    rng = np.random.default_rng(98)
    m = rng.normal(size=(3, 3)) * 0.4
    rect = rng.normal(size=(4, 3))
    dg = rng.normal(size=(3,))
    data = rng.normal(size=(6, 2))
    ids = np.asarray([0, 2, 0, 1, 2, 2])
    lx = rng.normal(size=(2, 4))
    llab = rng.integers(0, 2, size=(2, 4)).astype(np.float64)
    llog = rng.normal(size=(2, 4))

    sd = SameDiff()
    pm = sd.placeholder("m", (3, 3))
    pr = sd.placeholder("r", (4, 3))
    pdg = sd.placeholder("dg", (3,))
    pdata = sd.placeholder("data", (6, 2))
    pids = sd.placeholder("ids", (6,))
    px = sd.placeholder("lx", (2, 4))
    plab = sd.placeholder("llab", (2, 4))
    plog = sd.placeholder("llog", (2, 4))
    sd.linalg.expm(pm, name="em")
    sd.linalg.pinv(pr, name="pv")
    sd.linalg.matrixSetDiag(pm, pdg, name="msd")
    sd._op("segment.unsortedSegmentSqrtN", [pdata, pids], name="sq",
           num_segments=3)
    sd.loss.l2Loss(px, name="l2")
    sd.loss.weightedCrossEntropyWithLogits(plab, plog, weight=2.0,
                                           name="wce")

    msd = m.copy()
    np.fill_diagonal(msd, dg)
    ssum = np.zeros((3, 2))
    cnt = np.zeros(3)
    for d, i in zip(data, ids):
        ssum[i] += d
        cnt[i] += 1
    q = 2.0
    per = ((1 - llab) * llog
           + (1 + (q - 1) * llab)
           * (np.log1p(np.exp(-np.abs(llog))) + np.maximum(-llog, 0.0)))
    validate(TestCase(
        sd, {"m": m, "r": rect, "dg": dg, "data": data, "ids": ids,
             "lx": lx, "llab": llab, "llog": llog},
        {"em": spl.expm(m), "pv": np.linalg.pinv(rect), "msd": msd,
         "sq": ssum / np.sqrt(np.maximum(cnt, 1.0))[:, None],
         "l2": (lx ** 2).sum() / 2.0, "wce": per.mean()},
        grad_wrt=["data", "lx", "llog"], max_rel_error=1e-3))


def test_linalg_segment_loss_round3_sweep():
    _run_linalg_segment_loss_round3()


def test_random_round3_statistics():
    """Determinism + distribution sanity for the round-3 stochastic ops
    (the _EXEMPT pointers for random.* land here)."""
    sd = SameDiff()
    e = sd.random.exponential(2.0, (4000,), seed=7, name="e")
    g = sd.random.gamma(3.0, 2.0, (4000,), seed=8, name="g")
    p = sd.random.poisson(4.0, (4000,), seed=9, name="p")
    ln = sd.random.logNormal(0.0, 0.25, (4000,), seed=10, name="ln")
    tn = sd.random.truncatedNormal(1.0, 0.5, (4000,), seed=11, name="tn")
    x = sd.placeholder("x", (100,))
    sd.random.shuffle(x, seed=12, name="sh")

    xv = np.arange(100, dtype=np.float64)
    o1 = sd.output({"x": xv}, "e", "g", "p", "ln", "tn", "sh")
    o2 = sd.output({"x": xv}, "e", "g", "p", "ln", "tn", "sh")
    for k in o1:
        np.testing.assert_array_equal(np.asarray(o1[k]), np.asarray(o2[k]))
    assert abs(np.mean(o1["e"]) - 0.5) < 0.05          # Exp(lam=2): 1/2
    assert abs(np.mean(o1["g"]) - 1.5) < 0.1           # Gamma(3, beta=2)
    assert abs(np.mean(o1["p"]) - 4.0) < 0.2           # Poisson(4)
    assert abs(np.mean(o1["ln"]) - np.exp(0.03125)) < 0.05
    tnv = np.asarray(o1["tn"])
    assert tnv.min() >= 0.0 and tnv.max() <= 2.0       # +-2 sigma bounds
    assert abs(np.mean(tnv) - 1.0) < 0.05
    sh = np.asarray(o1["sh"])
    assert sorted(sh.tolist()) == xv.tolist() and not np.all(sh == xv)


def _run_einsum_gathernd_topk_round3():
    rng = np.random.default_rng(99)
    a = rng.normal(size=(3, 4))
    b = rng.normal(size=(4, 5))
    x = rng.normal(size=(3, 4))
    idx = np.asarray([[0, 1], [2, 3], [1, 0]])

    sd = SameDiff()
    pa = sd.placeholder("a", (3, 4))
    pb = sd.placeholder("b", (4, 5))
    px = sd.placeholder("x", (3, 4))
    pi = sd.placeholder("i", (3, 2))
    sd.math.einsum("ij,jk->ik", pa, pb, name="es")
    sd.math.gatherNd(px, pi, name="gn")
    v, ind = sd.math.topK(px, 2, name="tk")
    v.rename("tk_v"); ind.rename("tk_i")
    srt = np.sort(x, axis=-1)[:, ::-1]
    validate(TestCase(
        sd, {"a": a, "b": b, "x": x, "i": idx},
        {"es": a @ b, "gn": x[idx[:, 0], idx[:, 1]],
         "tk_v": srt[:, :2],
         "tk_i": np.argsort(-x, axis=-1)[:, :2]},
        grad_wrt=["a", "b"], max_rel_error=1e-3))


def test_einsum_gathernd_topk_round3_sweep():
    _run_einsum_gathernd_topk_round3()


# --- round 4b: ctc loss / fft family / embedding / space-batch nd -----------

def _ctc_loss_numpy(labels, logp, lab_len, inp_len, blank):
    """Independent float64 forward-algorithm oracle (textbook alpha DP,
    per-example python loops — deliberately NOT the op's vectorized
    masked-scan formulation)."""
    B = logp.shape[0]
    out = np.zeros(B)
    for b in range(B):
        lab = labels[b][:lab_len[b]]
        T = inp_len[b]
        ext = [blank]
        for c in lab:
            ext += [int(c), blank]
        S = len(ext)
        alpha = np.full(S, -np.inf)
        alpha[0] = logp[b, 0, blank]
        if S > 1:
            alpha[1] = logp[b, 0, ext[1]]
        for t in range(1, T):
            new = np.full(S, -np.inf)
            for s in range(S):
                acc = alpha[s]
                if s >= 1:
                    acc = np.logaddexp(acc, alpha[s - 1])
                if s >= 2 and ext[s] != blank and ext[s] != ext[s - 2]:
                    acc = np.logaddexp(acc, alpha[s - 2])
                new[s] = acc + logp[b, t, ext[s]]
            alpha = new
        tot = alpha[S - 1]
        if S > 1:
            tot = np.logaddexp(tot, alpha[S - 2])
        out[b] = -tot
    return out


def _run_round4_ctc_fft_embed():
    rng = np.random.default_rng(101)

    # --- ctcLoss vs the loop oracle + f64 central-difference gradient ---
    B, T, C, L = 3, 6, 5, 2
    logits = rng.normal(size=(B, T, C))
    labels = np.asarray([[1, 2], [3, 3], [2, 0]], np.int32)
    lab_len = np.asarray([2, 2, 1], np.int32)
    inp_len = np.asarray([6, 5, 4], np.int32)
    logp = logits - np.log(
        np.exp(logits).sum(-1, keepdims=True))
    want = _ctc_loss_numpy(labels, logp, lab_len, inp_len, blank=0)
    sd = SameDiff()
    pl = sd.placeholder("lg", (B, T, C))
    pt = sd.constant(labels, "lb")
    pll = sd.constant(lab_len, "ll")
    pil = sd.constant(inp_len, "il")
    sd.loss.ctcLoss(pt, pl, pll, pil, blank_index=0, name="ctc")
    validate(TestCase(sd, {"lg": logits}, {"ctc": want},
                      grad_wrt=["lg"], max_rel_error=1e-3))

    # blank at C-1 (the TF convention) exercises the skip-mask path
    blank = C - 1
    labels2 = np.asarray([[0, 1], [2, 2], [1, 3]], np.int32)
    want2 = _ctc_loss_numpy(labels2, logp, lab_len, inp_len, blank=blank)
    sd = SameDiff()
    pl = sd.placeholder("lg", (B, T, C))
    sd.loss.ctcLoss(sd.constant(labels2, "lb"), pl,
                    sd.constant(lab_len, "ll"), sd.constant(inp_len, "il"),
                    blank_index=blank, name="ctc")
    validate(TestCase(sd, {"lg": logits}, {"ctc": want2}, grad_wrt=[]))

    # --- fft family (complex outputs validated through |.|; irfft real) ---
    xv = rng.normal(size=(2, 8))
    sd = SameDiff()
    x = sd.placeholder("x", (2, 8))
    sd.math.abs(sd.math.fft(x), name="f")
    sd.math.abs(sd.math.ifft(x), name="fi")
    sd.math.abs(sd.math.rfft(x), name="fr")
    validate(TestCase(sd, {"x": xv}, {
        "f": np.abs(np.fft.fft(xv)),
        "fi": np.abs(np.fft.ifft(xv)),
        "fr": np.abs(np.fft.rfft(xv)),
    }, grad_wrt=["x"], max_rel_error=1e-3))

    cv = rng.normal(size=(2, 5))  # irfft: real output, direct compare
    sd = SameDiff()
    x = sd.placeholder("x", (2, 5))
    sd.math.irfft(sd.math.rfft(x), n=5, name="rt")  # round-trip = identity
    validate(TestCase(sd, {"x": cv}, {"rt": cv}, grad_wrt=["x"],
                      max_rel_error=1e-3))

    x2 = rng.normal(size=(2, 4, 4))
    sd = SameDiff()
    x = sd.placeholder("x", (2, 4, 4))
    sd.math.abs(sd.math.fft2(x), name="f2")
    sd.math.abs(sd.math.ifft2(x), name="fi2")
    validate(TestCase(sd, {"x": x2}, {
        "f2": np.abs(np.fft.fft2(x2)),
        "fi2": np.abs(np.fft.ifft2(x2))}, grad_wrt=[]))
    x3 = rng.normal(size=(2, 2, 4, 4))
    sd = SameDiff()
    x = sd.placeholder("x", (2, 2, 4, 4))
    sd.math.abs(sd.math.fft3(x), name="f3")
    sd.math.abs(sd.math.ifft3(x), name="fi3")
    validate(TestCase(sd, {"x": x3}, {
        "f3": np.abs(np.fft.fftn(x3, axes=(-3, -2, -1))),
        "fi3": np.abs(np.fft.ifftn(x3, axes=(-3, -2, -1)))}, grad_wrt=[]))

    # --- embeddingLookup: values + gradient scatters into the table ---
    wv = rng.normal(size=(6, 4))
    ids = np.asarray([[0, 3], [5, 3]], np.int32)
    sd = SameDiff()
    w = sd.placeholder("w", (6, 4))
    sd.nn.embeddingLookup(w, sd.constant(ids, "ids"), name="e")
    validate(TestCase(sd, {"w": wv}, {"e": wv[ids]}, grad_wrt=["w"]))

    # --- spaceToBatchNd / batchToSpaceNd vs an index-loop oracle ---
    xv = rng.normal(size=(2, 4, 6, 3))
    block = (2, 3)
    pads = ((0, 0), (0, 0))

    def s2b_oracle(x, block, pads):
        x = np.pad(x, [(0, 0)] + [tuple(p) for p in pads] + [(0, 0)])
        B, H, W, C = x.shape
        bh, bw = block
        out = np.zeros((B * bh * bw, H // bh, W // bw, C), x.dtype)
        for b in range(B):
            for i in range(H):
                for j in range(W):
                    ob = (i % bh * bw + j % bw) * B + b
                    out[ob, i // bh, j // bw] = x[b, i, j]
        return out

    want = s2b_oracle(xv, block, pads)
    sd = SameDiff()
    x = sd.placeholder("x", (2, 4, 6, 3))
    sd.cnn.spaceToBatchNd(x, block, pads, name="s")
    validate(TestCase(sd, {"x": xv}, {"s": want}))

    yv = rng.normal(size=(12, 2, 2, 3))
    sd = SameDiff()
    y = sd.placeholder("y", (12, 2, 2, 3))
    sd.cnn.batchToSpaceNd(y, block, ((1, 0), (0, 1)), name="b")
    inv = np.zeros((2, 4, 6, 3))
    for ob in range(12):
        for oi in range(2):
            for oj in range(2):
                blk, b = divmod(ob, 2)
                bi, bj = divmod(blk, 3)
                inv[b, oi * 2 + bi, oj * 3 + bj] = yv[ob, oi, oj]
    validate(TestCase(sd, {"y": yv},
                      {"b": inv[:, 1:, :-1]}, grad_wrt=["y"]))

    # round-trip pins the two as exact inverses (with pad/crop)
    sd = SameDiff()
    x = sd.placeholder("x", (2, 4, 6, 3))
    s = sd.cnn.spaceToBatchNd(x, block, ((2, 0), (1, 2)))
    sd.cnn.batchToSpaceNd(s, block, ((2, 0), (1, 2)), name="rt")
    validate(TestCase(sd, {"x": xv}, {"rt": xv}))


def test_round4_ctc_fft_embed_sweep():
    _run_round4_ctc_fft_embed()


def test_ctc_loss_infeasible_is_inf():
    """Input shorter than the minimum CTC alignment length -> +inf (the
    reference surfaces the bad example; a huge finite value would
    silently poison training with garbage gradients)."""
    rng = np.random.default_rng(7)
    logits = rng.normal(size=(2, 2, 5))
    labels = np.asarray([[1, 2, 3], [1, 0, 0]], np.int32)
    sd = SameDiff()
    pl = sd.placeholder("lg", (2, 2, 5))
    sd.loss.ctcLoss(sd.constant(labels, "lb"), pl,
                    sd.constant(np.asarray([3, 1], np.int32), "ll"),
                    sd.constant(np.asarray([2, 2], np.int32), "il"),
                    blank_index=0, name="ctc")
    out = np.asarray(sd.output({"lg": logits}, "ctc")["ctc"])
    assert np.isinf(out[0]) and out[0] > 0   # T=2 < 3 labels: infeasible
    assert np.isfinite(out[1])               # 1 label in T=2: feasible


# --- round 4c: math/reduce/structural tail ----------------------------------

def _run_round4_tail_math():
    rng = np.random.default_rng(44)

    # stopGradient: identity forward; gradient pinned to ZERO explicitly
    # (the central-difference harness would see the identity, so the
    # grad assertion lives outside validate())
    xv = rng.normal(size=(2, 3))
    sd = SameDiff()
    x = sd.placeholder("x", (2, 3))
    sd.math.stopGradient(x, name="sg")
    validate(TestCase(sd, {"x": xv}, {"sg": xv}, grad_wrt=[]))
    import jax as _jax
    import jax.numpy as _jnp
    fn = sd.make_function(("sg",))
    g = _jax.grad(lambda v: sum(
        _jnp.sum(o) for o in fn(dict(sd.arrays), {"x": v}).values()))(
        _jnp.asarray(xv))
    assert float(np.abs(np.asarray(g)).max()) == 0.0

    sd = SameDiff()
    x = sd.placeholder("x", (3,))
    y = sd.placeholder("y", (2, 3))
    sd.math.broadcastTo(x, (2, 3), name="b")
    sd.math.assign(y, x, name="a")
    sd.math.axpy(y, y, alpha=2.5, name="ax")
    xv, yv = rng.normal(size=3), rng.normal(size=(2, 3))
    validate(TestCase(sd, {"x": xv, "y": yv}, {
        "b": np.broadcast_to(xv, (2, 3)),
        "a": np.broadcast_to(xv, (2, 3)),
        "ax": 2.5 * yv + yv}))

    # generator ops (no inputs)
    sd = SameDiff()
    sd.math.fill((2, 3), 7.5, name="f")
    sd.math.linspace(0.0, 1.0, 5, name="l")
    sd.math.range(2, 11, 3, name="r")
    validate(TestCase(sd, {}, {
        "f": np.full((2, 3), 7.5, np.float32),
        "l": np.linspace(0, 1, 5),
        "r": np.arange(2, 11, 3)}, grad_wrt=[]))

    sd = SameDiff()
    x = sd.placeholder("x", (2, 4))
    sd.math.repeat(x, 2, axis=1, name="rp")
    sd.math.roll(x, 3, axis=1, name="ro")
    xv = rng.normal(size=(2, 4))
    validate(TestCase(sd, {"x": xv}, {
        "rp": np.repeat(xv, 2, axis=1), "ro": np.roll(xv, 3, axis=1)}))

    perm = np.asarray([2, 0, 3, 1], np.int32)
    sd = SameDiff()
    p = sd.constant(perm, "p")
    sd.math.invertPermutation(p, name="ip")
    validate(TestCase(sd, {}, {"ip": np.argsort(perm)}, grad_wrt=[]))

    xv = rng.normal(size=(3, 6))
    sd = SameDiff()
    x = sd.placeholder("x", (3, 6))
    sd.math.nthElement(x, 2, name="n2")
    sd.math.nthElement(x, 1, reverse=True, name="n1r")
    validate(TestCase(sd, {"x": xv}, {
        "n2": np.sort(xv, -1)[:, 2], "n1r": np.sort(xv, -1)[:, -2]},
        grad_wrt=[]))

    preds = rng.normal(size=(4, 6))
    targ = np.asarray([0, 2, 5, 1], np.int32)
    want = np.array([np.sum(preds[i] > preds[i, targ[i]]) < 2
                     for i in range(4)])
    sd = SameDiff()
    pp = sd.placeholder("p", (4, 6))
    sd.math.inTopK(pp, sd.constant(targ, "t"), 2, name="k")
    validate(TestCase(sd, {"p": preds}, {"k": want}, grad_wrt=[]))

    xv = rng.normal(size=(50,)) * 2.1  # avoid exact bin boundaries
    sd = SameDiff()
    x = sd.placeholder("x", (50,))
    sd.math.histogram(x, 8, name="h")
    sd.math.histogramFixedWidth(x, -3.0, 3.0, 6, name="hf")
    hf = np.histogram(np.clip(xv, -3.0, 2.999), bins=6, range=(-3, 3))[0]
    validate(TestCase(sd, {"x": xv}, {
        "h": np.histogram(xv, bins=8)[0], "hf": hf}, grad_wrt=[]))

    # unique / uniqueWithCounts / listDiff (bounded first-occurrence)
    xv = np.asarray([5., 3., 5., 1., 3., 5., 9., 1.])
    u, fidx, inv, cnts = np.unique(xv, return_index=True,
                                   return_inverse=True, return_counts=True)
    order = np.argsort(fidx)
    vals = np.zeros(8); vals[:len(u)] = u[order]
    rank = np.argsort(order)
    counts = np.zeros(8, np.int32); counts[:len(u)] = cnts[order]
    sd = SameDiff()
    x = sd.placeholder("x", (8,))
    v1, i1, c1 = sd.math.unique(x, name="u")
    v1.rename("uv"); i1.rename("ui"); c1.rename("uc")
    v2, i2, n2, c2 = sd.math.uniqueWithCounts(x, name="uw")
    v2.rename("wv"); n2.rename("wn"); c2.rename("wc")
    validate(TestCase(sd, {"x": xv}, {
        "uv": vals, "ui": rank[inv], "uc": np.int32(len(u)),
        "wv": vals, "wn": counts, "wc": np.int32(len(u))}, grad_wrt=[]))

    yv = np.asarray([3., 9.])
    keep = ~np.isin(xv, yv)
    dv = np.zeros(8); dv[:keep.sum()] = xv[keep]
    di = np.zeros(8, np.int32); di[:keep.sum()] = np.nonzero(keep)[0]
    sd = SameDiff()
    x = sd.placeholder("x", (8,))
    o, i, c = sd.math.listDiff(x, sd.constant(yv, "y"), name="ld")
    o.rename("lv"); i.rename("li"); c.rename("lc")
    validate(TestCase(sd, {"x": xv}, {
        "lv": dv, "li": di, "lc": np.int64(keep.sum())}, grad_wrt=[]))

    # dynamicPartition (bounded, counts as last output)
    data = rng.normal(size=(6, 2))
    parts = np.asarray([0, 2, 1, 0, 2, 2], np.int32)
    sd = SameDiff()
    x = sd.placeholder("x", (6, 2))
    outs = sd.math.dynamicPartition(x, sd.constant(parts, "p"), 3,
                                    name="dp")
    for j, o in enumerate(outs[:3]):
        o.rename(f"dp{j}")
    outs[3].rename("dpc")
    exp = {}
    for j in range(3):
        rows = data[parts == j]
        pad = np.zeros((6, 2)); pad[:len(rows)] = rows
        exp[f"dp{j}"] = pad
    exp["dpc"] = np.asarray([2, 1, 3], np.int32)
    validate(TestCase(sd, {"x": data}, exp, grad_wrt=[]))

    # clipByGlobalNorm (active clip), xdivy/xlogy/divNoNan/truncatediv
    av, bv = rng.normal(size=(2, 2)) * 3, rng.normal(size=(3,)) * 3
    gn = np.sqrt((av ** 2).sum() + (bv ** 2).sum())
    sc = min(1.0, 1.5 / gn)
    sd = SameDiff()
    a = sd.placeholder("a", (2, 2))
    b = sd.placeholder("b", (3,))
    ca, cb = sd.math.clipByGlobalNorm([a, b], 1.5, name="cg")
    ca.rename("ca"); cb.rename("cb")
    validate(TestCase(sd, {"a": av, "b": bv},
                      {"ca": av * sc, "cb": bv * sc}, max_rel_error=1e-3))

    xv = rng.uniform(0.5, 2.0, (2, 3))
    yv = rng.uniform(0.5, 2.0, (2, 3))
    sd = SameDiff()
    x = sd.placeholder("x", (2, 3))
    y = sd.placeholder("y", (2, 3))
    sd.math.xdivy(x, y, name="xd")
    sd.math.xlogy(x, y, name="xl")
    sd.math.divNoNan(x, y, name="dn")
    sd.math.truncatediv(x, y, name="td")
    validate(TestCase(sd, {"x": xv, "y": yv}, {
        "xd": xv / yv, "xl": xv * np.log(yv), "dn": xv / yv,
        "td": np.trunc(xv / yv)}, grad_wrt=["x"], max_rel_error=1e-3))
    # zero-handling (forward only)
    sd = SameDiff()
    x = sd.placeholder("x", (3,))
    y = sd.placeholder("y", (3,))
    sd.math.xdivy(x, y, name="xd")
    sd.math.divNoNan(x, y, name="dn")
    validate(TestCase(sd, {"x": np.asarray([0., 2., 0.]),
                           "y": np.asarray([5., 0., 0.])},
                      {"xd": np.asarray([0., np.inf, 0.]),
                       "dn": np.asarray([0., 0., 0.])}, grad_wrt=[]))
    itd = np.asarray([-7, 7, -9], np.int32), np.asarray([2, -2, 3], np.int32)
    sd = SameDiff()
    sd.math.truncatediv(sd.constant(itd[0], "a"), sd.constant(itd[1], "b"),
                        name="t")
    validate(TestCase(sd, {}, {"t": np.asarray([-3, -3, -3], np.int32)},
                      grad_wrt=[]))

    # condition family + compareAndBitpack + equalsWithEps + mergeMaxIndex
    xv = np.asarray([0.1, -2.0, 3.0, 0.5, 3.0, -1.0])
    sd = SameDiff()
    x = sd.placeholder("x", (6,))
    sd.math.firstIndex(x, "gt", 0.4, name="fi")
    sd.math.lastIndex(x, "gt", 0.4, name="li")
    sd.math.matchCondition(x, "abs_gt", 0.9, name="mc")
    cv, cc = sd.math.choose(x, "lt", 0.0, name="ch")
    cv.rename("chv"); cc.rename("chc")
    validate(TestCase(sd, {"x": xv}, {
        "fi": np.int64(2), "li": np.int64(4), "mc": np.int64(4),
        "chv": np.asarray([-2., -1., 0, 0, 0, 0]), "chc": np.int64(2)},
        grad_wrt=[]))

    bits = np.asarray([[1., -1., 2., -3., 4., 0.5, -0.5, 2.]])
    sd = SameDiff()
    x = sd.placeholder("x", (1, 8))
    sd.math.compareAndBitpack(x, 0.0, name="cb")
    want = np.uint8(int("10101101", 2))
    validate(TestCase(sd, {"x": bits}, {"cb": np.asarray([[want]])},
                      grad_wrt=[]))

    sd = SameDiff()
    x = sd.placeholder("x", (3,))
    y = sd.placeholder("y", (3,))
    sd.math.equalsWithEps(x, y, eps=0.1, name="e")
    sd.math.mergeMaxIndex(x, y, name="mm")
    sd.math.relativeError(x, y, name="re")
    xv, yv = np.asarray([1., 2., 3.]), np.asarray([1.05, 2.5, 2.9])
    validate(TestCase(sd, {"x": xv, "y": yv}, {
        "e": np.bool_(False), "mm": np.asarray([1, 1, 0], np.int32),
        "re": np.abs(xv - yv) / np.maximum(np.abs(xv), np.abs(yv))},
        grad_wrt=[]))

    # sufficientStatistics -> normalizeMoments == mean/var
    xv = rng.normal(size=(4, 5))
    sd = SameDiff()
    x = sd.placeholder("x", (4, 5))
    cnt, s, ss = sd.math.sufficientStatistics(x, (0,), name="st")
    cnt.rename("c"); s.rename("s"); ss.rename("ss")
    mean, var = sd.math.normalizeMoments(cnt, s, ss, name="nm")
    mean.rename("m"); var.rename("v")
    validate(TestCase(sd, {"x": xv}, {
        "c": np.float64(4), "s": xv.sum(0), "ss": (xv * xv).sum(0),
        "m": xv.mean(0), "v": xv.var(0)}, max_rel_error=1e-3))

    # checkNumerics (identity in-graph), rank / sizeOp, split_v
    sd = SameDiff()
    x = sd.placeholder("x", (2, 3))
    sd.math.checkNumerics(x, "probe", name="cn")
    sd.math.rank(x, name="rk")
    sd.math.sizeOp(x, name="sz")
    a, b2 = sd.split_v(x, (1, 2), axis=1, name="sv")
    a.rename("sva"); b2.rename("svb")
    xv = rng.normal(size=(2, 3))
    validate(TestCase(sd, {"x": xv}, {
        "cn": xv, "rk": np.int32(2), "sz": np.int64(6),
        "sva": xv[:, :1], "svb": xv[:, 1:]}))

    # reduce tail: all/any/median/percentile/squaredNorm/iamax/iamin
    bv = np.asarray([[True, True], [True, False]])
    sd = SameDiff()
    x = sd.constant(bv, "b")
    sd.math.all(x, dims=(1,), name="al")
    sd.math.any(x, dims=(1,), name="an")
    validate(TestCase(sd, {}, {"al": bv.all(1), "an": bv.any(1)},
                      grad_wrt=[]))

    xv = rng.normal(size=(3, 7))
    sd = SameDiff()
    x = sd.placeholder("x", (3, 7))
    sd.math.median(x, dims=(1,), name="md")
    sd.math.percentile(x, 30.0, dims=(1,), name="pc")
    sd.math.squaredNorm(x, dims=(1,), name="sn")
    sd.math.iamax(x, dims=(1,), name="ix")
    sd.math.iamin(x, dims=(1,), name="im")
    validate(TestCase(sd, {"x": xv}, {
        "md": np.median(xv, 1), "pc": np.percentile(xv, 30.0, 1),
        "sn": (xv * xv).sum(1), "ix": np.abs(xv).argmax(1),
        "im": np.abs(xv).argmin(1)}, grad_wrt=[]))


def test_round4_tail_math_sweep():
    _run_round4_tail_math()


# --- round 4d: nn/cnn/linalg/loss/quant/scatter/image tail ------------------

def _run_round4_tail_misc():
    rng = np.random.default_rng(45)

    # nn.reluLayer / nn.mirrorPad
    xv, wv, bv = (rng.normal(size=(3, 4)), rng.normal(size=(4, 5)),
                  rng.normal(size=(5,)))
    sd = SameDiff()
    x = sd.placeholder("x", (3, 4))
    w = sd.placeholder("w", (4, 5))
    b = sd.placeholder("b", (5,))
    sd.nn.reluLayer(x, w, b, name="rl")
    validate(TestCase(sd, {"x": xv, "w": wv, "b": bv},
                      {"rl": np.maximum(xv @ wv + bv, 0)},
                      max_rel_error=1e-3))

    xv = rng.normal(size=(3, 4))
    sd = SameDiff()
    x = sd.placeholder("x", (3, 4))
    sd.nn.mirrorPad(x, ((1, 1), (2, 0)), mode="REFLECT", name="mr")
    sd.nn.mirrorPad(x, ((1, 0), (0, 2)), mode="SYMMETRIC", name="ms")
    validate(TestCase(sd, {"x": xv}, {
        "mr": np.pad(xv, ((1, 1), (2, 0)), mode="reflect"),
        "ms": np.pad(xv, ((1, 0), (0, 2)), mode="symmetric")},
        max_rel_error=1e-3))

    # cnn.avgPooling1d / pnormPool2d / maxPoolWithArgmax
    xv = rng.normal(size=(2, 8, 3))
    want = np.stack([xv[:, i * 2:i * 2 + 4].mean(1) for i in range(3)], 1)
    sd = SameDiff()
    x = sd.placeholder("x", (2, 8, 3))
    sd.cnn.avgPooling1d(x, k=4, s=2, name="ap")
    validate(TestCase(sd, {"x": xv}, {"ap": want}, max_rel_error=1e-3))

    xv = rng.normal(size=(1, 4, 4, 2))
    p = 3.0
    w2 = np.zeros((1, 2, 2, 2))
    for i in range(2):
        for j in range(2):
            blk = np.abs(xv[:, i * 2:i * 2 + 2, j * 2:j * 2 + 2]) ** p
            w2[:, i, j] = blk.sum((1, 2)) ** (1 / p)
    sd = SameDiff()
    x = sd.placeholder("x", (1, 4, 4, 2))
    sd.cnn.pnormPool2d(x, (2, 2), (2, 2), p=p, name="pp")
    validate(TestCase(sd, {"x": xv}, {"pp": w2}, max_rel_error=1e-3))

    xv = rng.normal(size=(2, 4, 6, 3))
    sd = SameDiff()
    x = sd.placeholder("x", (2, 4, 6, 3))
    v, idx = sd.cnn.maxPoolWithArgmax(x, (2, 2), (2, 2), name="ma")
    v.rename("mav"); idx.rename("mai")
    vals = np.zeros((2, 2, 3, 3)); fidx = np.zeros((2, 2, 3, 3), np.int64)
    for bi in range(2):
        for i in range(2):
            for j in range(3):
                for c in range(3):
                    win = xv[bi, i * 2:i * 2 + 2, j * 2:j * 2 + 2, c]
                    k = np.argmax(win)
                    ri, cj = divmod(k, 2)
                    vals[bi, i, j, c] = win[ri, cj]
                    fidx[bi, i, j, c] = ((i * 2 + ri) * 6 + j * 2 + cj) * 3 + c
    validate(TestCase(sd, {"x": xv}, {"mav": vals, "mai": fidx},
                      grad_wrt=[]))

    # linalg.lu (vs scipy LAPACK) + matrixDiag
    import scipy.linalg as sla
    av = rng.normal(size=(4, 4)) + 4 * np.eye(4)
    lu_ref, piv_ref = sla.lu_factor(av)
    sd = SameDiff()
    a = sd.placeholder("a", (4, 4))
    l_, pv = sd.linalg.lu(a, name="lu")
    l_.rename("lu_m"); pv.rename("lu_p")
    validate(TestCase(sd, {"a": av},
                      {"lu_m": lu_ref, "lu_p": piv_ref.astype(np.int32)},
                      grad_wrt=[], max_rel_error=1e-3))
    dv = rng.normal(size=(2, 3))
    sd = SameDiff()
    d = sd.placeholder("d", (2, 3))
    sd.linalg.matrixDiag(d, name="md")
    want = np.zeros((2, 3, 3))
    for i in range(2):
        want[i] = np.diag(dv[i])
    validate(TestCase(sd, {"d": dv}, {"md": want}))

    # loss twins + meanPairwiseSquaredError
    lv = rng.normal(size=(3, 5))
    onehot = np.eye(5)[[1, 4, 0]]
    e = np.exp(lv - lv.max(-1, keepdims=True))
    sm = e / e.sum(-1, keepdims=True)
    sd = SameDiff()
    lg = sd.placeholder("lg", (3, 5))
    per, bp = sd.loss.softmaxCrossEntropyWithLogits(
        sd.constant(onehot, "lb"), lg, name="ce")
    per.rename("ce_l"); bp.rename("ce_b")
    validate(TestCase(sd, {"lg": lv}, {
        "ce_l": -(onehot * np.log(sm)).sum(-1), "ce_b": sm - onehot},
        grad_wrt=["lg"], max_rel_error=1e-3))

    labels = rng.normal(size=(2, 4))
    preds = rng.normal(size=(2, 4))
    d = preds - labels
    per = np.zeros(2)
    for i in range(2):
        s = 0.0
        for a2 in range(4):
            for b2 in range(4):
                s += (d[i, a2] - d[i, b2]) ** 2
        per[i] = s / (4 * 3)
    sd = SameDiff()
    pl = sd.placeholder("l", (2, 4))
    pp = sd.placeholder("p", (2, 4))
    sd.loss.meanPairwiseSquaredError(pl, pp, name="mp")
    validate(TestCase(sd, {"l": labels, "p": preds},
                      {"mp": per.mean()}, max_rel_error=1e-3))

    # fake quant: hand case — lo=0, hi=63.75, 8 bits -> scale 0.25
    xv = np.asarray([-1.0, 0.1, 0.37, 10.12, 63.6, 70.0])
    want = np.asarray([0.0, 0.0, 0.25, 10.0, 63.5, 63.75])
    sd = SameDiff()
    x = sd.placeholder("x", (6,))
    sd.math.fakeQuantWithMinMaxArgs(x, 0.0, 63.75, 8, name="fa")
    lo = sd.constant(np.float64(0.0), "lo")
    hi = sd.constant(np.float64(63.75), "hi")
    sd.math.fakeQuantWithMinMaxVars(x, lo, hi, 8, name="fv")
    validate(TestCase(sd, {"x": xv}, {"fa": want, "fv": want},
                      grad_wrt=[]))
    # per-channel: different ranges per channel
    xv = np.asarray([[0.3, -0.4], [1.7, 0.9]])
    sd = SameDiff()
    x = sd.placeholder("x", (2, 2))
    lo = sd.constant(np.asarray([0.0, -0.5]), "lo")
    hi = sd.constant(np.asarray([1.275, 0.775]), "hi")
    sd.math.fakeQuantWithMinMaxVarsPerChannel(x, lo, hi, 8, name="fc")
    # both ranges span 1.275 -> scale 0.005; values on the grid pass
    # through, out-of-range values clip to the (nudged) range ends
    want = np.asarray([[0.3, -0.4], [1.275, 0.775]])
    validate(TestCase(sd, {"x": xv}, {"fc": want}, grad_wrt=[]))

    # bitwise.bitcast: f32 bits == numpy view
    xv = np.asarray([1.0, -2.5, 0.0], np.float32)
    sd = SameDiff()
    x = sd.constant(xv, "x")
    sd.bitwise.bitcast(x, "int32", name="bc")
    validate(TestCase(sd, {}, {"bc": xv.view(np.int32)}, grad_wrt=[]))

    # image.resizeArea: integer-factor block mean
    xv = rng.normal(size=(1, 4, 6, 2))
    want = xv.reshape(1, 2, 2, 3, 2, 2).mean(axis=(2, 4))
    sd = SameDiff()
    x = sd.placeholder("x", (1, 4, 6, 2))
    sd.image.resizeArea(x, 2, 3, name="ra")
    validate(TestCase(sd, {"x": xv}, {"ra": want}, max_rel_error=1e-3))

    # scatter-nd family vs numpy loops
    idx = np.asarray([[0, 1], [2, 0], [0, 1]], np.int32)
    upd = np.asarray([1.0, 2.0, 3.0])
    want = np.zeros((3, 2)); want[0, 1] += 1 + 3; want[2, 0] += 2
    refv = rng.normal(size=(3, 2))
    sd = SameDiff()
    u = sd.placeholder("u", (3,))
    r = sd.placeholder("r", (3, 2))
    sd.scatter_nd(sd.constant(idx, "i"), u, (3, 2), name="sn")
    sd.scatter_nd_add(r, sd.constant(idx, "i2"), u, name="sa")
    sd.scatter_nd_sub(r, sd.constant(idx, "i3"), u, name="ss")
    validate(TestCase(sd, {"u": upd, "r": refv}, {
        "sn": want, "sa": refv + want, "ss": refv - want}))
    # ndUpdate: last-write-wins is unspecified for dup indices — use unique
    idx2 = np.asarray([[0, 0], [1, 1]], np.int32)
    upd2 = np.asarray([7.0, 8.0])
    wantu = refv.copy(); wantu[0, 0] = 7; wantu[1, 1] = 8
    sd = SameDiff()
    r = sd.placeholder("r", (3, 2))
    u = sd.placeholder("u", (2,))
    sd.scatter_nd_update(r, sd.constant(idx2, "i"), u, name="su")
    validate(TestCase(sd, {"r": refv, "u": upd2}, {"su": wantu}))

    # rnn.ctcGreedyDecoder vs a loop oracle
    lg = rng.normal(size=(2, 5, 4))
    seq = np.asarray([5, 3], np.int32)
    lp = lg - np.log(np.exp(lg).sum(-1, keepdims=True))
    dec = np.full((2, 5), -1, np.int32)
    lens = np.zeros(2, np.int32)
    score = np.zeros(2)
    for b in range(2):
        path = lp[b].argmax(-1)
        prev = -1
        k = 0
        for t in range(seq[b]):
            score[b] -= lp[b, t].max()
            s = path[t]
            if s != 0 and s != prev:
                dec[b, k] = s; k += 1
            prev = s
        lens[b] = k
    sd = SameDiff()
    x = sd.placeholder("x", (2, 5, 4))
    o, ln, sc = sd.rnn.ctcGreedyDecoder(x, sd.constant(seq, "s"),
                                        blank_index=0, name="gd")
    o.rename("gd_o"); ln.rename("gd_l"); sc.rename("gd_s")
    validate(TestCase(sd, {"x": lg}, {
        "gd_o": dec, "gd_l": lens, "gd_s": score}, grad_wrt=[]))


def test_round4_tail_misc_sweep():
    _run_round4_tail_misc()


def test_round4_stochastic_ops_statistics():
    """random.multinomial / image.randomCrop: seed-deterministic, output
    properties pinned (exemption pointers in _EXEMPT)."""
    rng = np.random.default_rng(9)
    logits = np.log(np.asarray([[0.7, 0.2, 0.1], [0.05, 0.05, 0.9]]))
    sd = SameDiff()
    x = sd.constant(logits, "x")
    sd.random.multinomial(x, 4000, seed=5, name="m")
    out = np.asarray(sd.output({}, "m")["m"])
    assert out.shape == (2, 4000) and out.min() >= 0 and out.max() <= 2
    frac0 = (out[0] == 0).mean()
    frac2 = (out[1] == 2).mean()
    assert 0.65 < frac0 < 0.75 and 0.85 < frac2 < 0.95
    # determinism
    sd2 = SameDiff()
    x = sd2.constant(logits, "x")
    sd2.random.multinomial(x, 4000, seed=5, name="m")
    np.testing.assert_array_equal(out, np.asarray(sd2.output({}, "m")["m"]))

    img = rng.normal(size=(2, 8, 10, 3)).astype(np.float32)
    sd = SameDiff()
    x = sd.constant(img, "x")
    sd.image.randomCrop(x, 4, 5, seed=3, name="c")
    crop = np.asarray(sd.output({}, "c")["c"])
    assert crop.shape == (2, 4, 5, 3)
    # the crop is a contiguous window of the source
    found = any(
        np.allclose(img[:, i:i + 4, j:j + 5], crop)
        for i in range(5) for j in range(6))
    assert found


def test_round4_review_regressions():
    """Round-4 review findings: fakeQuant straight-through gradient,
    split_v -1/"rest" + size validation, scatter-nd out-of-bounds drop."""
    import jax as _jax
    import jax.numpy as _jnp

    # STE gradient: 1 inside the nudged range, 0 outside
    sd = SameDiff()
    x = sd.placeholder("x", (4,))
    sd.math.fakeQuantWithMinMaxArgs(x, 0.0, 63.75, 8, name="q")
    fn = sd.make_function(("q",))
    g = _jax.grad(lambda v: float(0) + _jnp.sum(
        fn(dict(sd.arrays), {"x": v})["q"]))(
        _jnp.asarray([-5.0, 1.3, 60.0, 99.0]))
    np.testing.assert_allclose(np.asarray(g), [0.0, 1.0, 1.0, 0.0])

    # split_v: -1 takes the rest; bad sizes raise
    sd = SameDiff()
    x = sd.placeholder("x", (2, 5))
    a, b = sd.split_v(x, (2, -1), axis=1, name="sv")
    a.rename("a"); b.rename("b")
    xv = np.arange(10.0).reshape(2, 5)
    out = sd.output({"x": xv}, "a", "b")
    assert np.asarray(out["a"]).shape == (2, 2)
    np.testing.assert_array_equal(np.asarray(out["b"]), xv[:, 2:])
    with pytest.raises(ValueError, match="must sum"):
        sd2 = SameDiff()
        x2 = sd2.placeholder("x", (2, 5))
        a2, b2 = sd2.split_v(x2, (2, 2), axis=1, name="sv")
        a2.rename("bad_a")
        sd2.output({"x": xv}, "bad_a")

    # scatter_nd: out-of-bounds index dropped, not clipped onto an edge
    sd = SameDiff()
    u = sd.constant(np.asarray([7.0, 1.0]), "u")
    sd.scatter_nd(sd.constant(np.asarray([[5, 0], [1, 1]], np.int32), "i"),
                  u, (3, 2), name="sn")
    out = np.asarray(sd.output({}, "sn")["sn"])
    want = np.zeros((3, 2)); want[1, 1] = 1.0
    np.testing.assert_array_equal(out, want)


# --- round 6: cheap in-tier-1 coverage gate ---------------------------------


def test_zz_coverage_registry_light():
    """Tier-1 stand-in for the slow-marked test_coverage_registry_complete:
    when this module runs as a whole (tier-1 runs one process, definition
    order, random ordering disabled), every sweep above has already
    populated the process-local coverage set, so the registry-completeness
    assertion costs nothing extra here. Skips when invoked in isolation
    (the slow test remains the order-independent form)."""
    rep = coverage_report()
    if rep["validated"] < 100:
        pytest.skip("module sweeps did not run in this process; use "
                    "pytest -m slow test_coverage_registry_complete")
    unexpected = sorted(set(rep["missing"]) - set(_EXEMPT))
    assert not unexpected, (
        f"registered ops without validation coverage: {unexpected} — add a "
        "sweep entry in test_op_validation.py or an explicit exemption "
        "with a pointer to the covering test")
    assert rep["validated"] >= 350, rep["validated"]
