"""Per-op validation via the OpValidation harness (reference
``org.nd4j.autodiff.validation.OpValidation`` — forward + gradient per op,
with coverage accounting)."""

import numpy as np
import pytest

from deeplearning4j_tpu.samediff.core import SameDiff
from deeplearning4j_tpu.samediff.validation import (
    TestCase,
    coverage_report,
    validate,
)


def _case(build, inputs, expected, **kw):
    sd = SameDiff.create()
    build(sd)
    return TestCase(sd, inputs, expected, **kw)


def test_matmul_and_bias():
    sd = SameDiff.create()
    a = sd.placeholder("a", shape=(2, 3), dtype="float64")
    b = sd.placeholder("b", shape=(3, 2), dtype="float64")
    y = sd.math.mmul(a, b, name="y")
    av = np.arange(6, dtype=np.float64).reshape(2, 3)
    bv = np.arange(6, dtype=np.float64).reshape(3, 2) * 0.5
    validate(TestCase(sd, {"a": av, "b": bv}, {"y": av @ bv}))


def test_elementwise_family():
    sd = SameDiff.create()
    x = sd.placeholder("x", shape=(4,), dtype="float64")
    y = sd.placeholder("y", shape=(4,), dtype="float64")
    s = (x * y + x - y / 2.0).rename("s")
    xv = np.asarray([0.5, -1.0, 2.0, 3.0])
    yv = np.asarray([1.0, 2.0, -0.5, 0.25])
    validate(TestCase(sd, {"x": xv, "y": yv},
                      {"s": xv * yv + xv - yv / 2.0}))


def test_activations_and_reductions():
    sd = SameDiff.create()
    x = sd.placeholder("x", shape=(3, 4), dtype="float64")
    h = sd.nn.tanh(x)
    m = sd.math.mean(h, dims=(1,), name="m")
    xv = np.linspace(-2, 2, 12).reshape(3, 4)
    validate(TestCase(sd, {"x": xv}, {"m": np.tanh(xv).mean(1)}))


def test_softmax_gradient():
    sd = SameDiff.create()
    x = sd.placeholder("x", shape=(2, 5), dtype="float64")
    p = sd.nn.softmax(x, name="p")
    xv = np.random.default_rng(0).normal(size=(2, 5))
    e = np.exp(xv - xv.max(1, keepdims=True))
    validate(TestCase(sd, {"x": xv}, {"p": e / e.sum(1, keepdims=True)}))


def test_conv2d_forward_and_grad():
    sd = SameDiff.create()
    x = sd.placeholder("x", shape=(1, 4, 4, 2), dtype="float64")
    w = sd.placeholder("w", shape=(2, 2, 2, 3), dtype="float64")
    b = sd.constant(np.zeros(3))
    y = sd.cnn.conv2d(x, w, b, strides=(1, 1), padding="VALID", name="y")
    rng = np.random.default_rng(1)
    xv = rng.normal(size=(1, 4, 4, 2))
    wv = rng.normal(size=(2, 2, 2, 3)) * 0.5
    import jax

    want = np.asarray(jax.lax.conv_general_dilated(
        xv, wv, (1, 1), "VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC")))
    validate(TestCase(sd, {"x": xv, "w": wv}, {"y": want},
                      max_rel_error=1e-3))


def test_layer_norm_grad():
    sd = SameDiff.create()
    x = sd.placeholder("x", shape=(2, 6), dtype="float64")
    g = sd.constant(np.ones(6))
    b = sd.constant(np.zeros(6))
    y = sd.nn.layerNorm(x, g, b, name="y")
    xv = np.random.default_rng(2).normal(size=(2, 6)) * 3
    mu = xv.mean(-1, keepdims=True)
    var = xv.var(-1, keepdims=True)
    validate(TestCase(sd, {"x": xv}, {"y": (xv - mu) / np.sqrt(var + 1e-5)},
                      max_rel_error=1e-3))


def test_coverage_accounting_floor():
    """Reference parity: op validation keeps a coverage ledger. Runs its
    own case so the ledger check is self-contained (independent of test
    order / xdist sharding)."""
    sd = SameDiff()
    x = sd.placeholder("x", (2, 3))
    sd.math.mul(x, x, name="y")
    xv = np.random.default_rng(3).normal(size=(2, 3))
    validate(TestCase(sd, {"x": xv}, {"y": xv * xv}))
    rep = coverage_report()
    assert rep["registered"] > 150  # the registry is substantial
    assert rep["validated"] >= 1    # the case above recorded its ops
    assert isinstance(rep["missing"], list)


# --------------------------------------------------------------------------
# broad registry sweep (reference: OpValidation coverage accounting fails CI
# for untested ops; this sweep pushes per-op forward+gradient coverage)
# --------------------------------------------------------------------------

def _seed(op: str) -> int:
    import zlib

    return zlib.crc32(op.encode())  # stable across runs (hash() is not)


# (registry op, numpy oracle, (lo, hi) input range, grad_checked)
_UNARY_SWEEP = [
    ("math.exp", np.exp, (-1, 1), True),
    ("math.expm1", np.expm1, (-1, 1), True),
    ("math.exp2", np.exp2, (-1, 1), True),
    ("math.log", np.log, (0.5, 2.0), True),
    ("math.log1p", np.log1p, (-0.4, 1.0), True),
    ("math.log2", np.log2, (0.5, 2.0), True),
    ("math.log10", np.log10, (0.5, 2.0), True),
    ("math.sqrt", np.sqrt, (0.5, 2.0), True),
    ("math.rsqrt", lambda x: 1.0 / np.sqrt(x), (0.5, 2.0), True),
    ("math.square", np.square, (-2, 2), True),
    ("math.reciprocal", np.reciprocal, (0.5, 2.0), True),
    ("math.abs", np.abs, (0.3, 2.0), True),
    ("math.neg", np.negative, (-2, 2), True),
    ("math.sin", np.sin, (-1, 1), True),
    ("math.cos", np.cos, (-1, 1), True),
    ("math.tan", np.tan, (-1, 1), True),
    ("math.asin", np.arcsin, (-0.8, 0.8), True),
    ("math.acos", np.arccos, (-0.8, 0.8), True),
    ("math.atan", np.arctan, (-2, 2), True),
    ("math.sinh", np.sinh, (-1, 1), True),
    ("math.cosh", np.cosh, (-1, 1), True),
    ("math.asinh", np.arcsinh, (-2, 2), True),
    ("math.acosh", np.arccosh, (1.5, 3.0), True),
    ("math.atanh", np.arctanh, (-0.8, 0.8), True),
    ("math.erf", None, (-1.5, 1.5), True),     # oracle via math.erf below
    ("math.erfc", None, (-1.5, 1.5), True),
    ("math.floor", np.floor, (0.1, 0.9), False),
    ("math.ceil", np.ceil, (0.1, 0.9), False),
    ("math.round", np.round, (0.1, 0.4), False),
    ("math.sign", np.sign, (0.3, 2.0), False),
    ("math.isnan", np.isnan, (-1, 1), False),
    ("math.isinf", np.isinf, (-1, 1), False),
    ("math.isfinite", np.isfinite, (-1, 1), False),
]


def _run_unary(op, oracle, rng_range, check_grad):
    import math as _m

    if oracle is None:
        base = {"math.erf": _m.erf, "math.erfc": _m.erfc}[op]
        oracle = np.vectorize(base)
    rng = np.random.default_rng(_seed(op))
    xv = rng.uniform(*rng_range, size=(2, 3))
    sd = SameDiff()
    x = sd.placeholder("x", (2, 3))
    sd._op(op, [x], name="y")
    validate(TestCase(sd, {"x": xv}, {"y": oracle(xv)},
                      grad_wrt=["x"] if check_grad else []))


@pytest.mark.parametrize("op,oracle,rng_range,check_grad", _UNARY_SWEEP,
                         ids=[c[0] for c in _UNARY_SWEEP])
def test_unary_sweep(op, oracle, rng_range, check_grad):
    _run_unary(op, oracle, rng_range, check_grad)


_BINARY_SWEEP = [
    ("math.add", np.add, True),
    ("math.sub", np.subtract, True),
    ("math.mul", np.multiply, True),
    ("math.div", np.divide, True),
    ("math.pow", np.power, True),
    ("math.maximum", np.maximum, True),
    ("math.minimum", np.minimum, True),
    ("math.atan2", np.arctan2, True),
    ("math.squared_difference", lambda a, b: (a - b) ** 2, True),
    ("math.rsub", lambda a, b: b - a, True),
    ("math.rdiv", lambda a, b: b / a, True),
    ("math.mod", np.mod, False),
    ("math.floordiv", np.floor_divide, False),
    ("math.gt", np.greater, False),
    ("math.gte", np.greater_equal, False),
    ("math.lt", np.less, False),
    ("math.lte", np.less_equal, False),
    ("math.eq", np.equal, False),
    ("math.neq", np.not_equal, False),
]


def _run_binary(op, oracle, check_grad):
    rng = np.random.default_rng(_seed(op))
    av = rng.uniform(0.5, 2.0, size=(2, 3))
    bv = rng.uniform(0.6, 1.9, size=(2, 3))
    sd = SameDiff()
    a = sd.placeholder("a", (2, 3))
    b = sd.placeholder("b", (2, 3))
    sd._op(op, [a, b], name="y")
    validate(TestCase(sd, {"a": av, "b": bv}, {"y": oracle(av, bv)},
                      grad_wrt=["a", "b"] if check_grad else []))


@pytest.mark.parametrize("op,oracle,check_grad", _BINARY_SWEEP,
                         ids=[c[0] for c in _BINARY_SWEEP])
def test_binary_sweep(op, oracle, check_grad):
    _run_binary(op, oracle, check_grad)


_REDUCE_SWEEP = [
    ("reduce.sum", lambda x, ax, kd: x.sum(axis=ax, keepdims=kd), True),
    ("reduce.mean", lambda x, ax, kd: x.mean(axis=ax, keepdims=kd), True),
    ("reduce.prod", lambda x, ax, kd: x.prod(axis=ax, keepdims=kd), True),
    ("reduce.amax", lambda x, ax, kd: x.max(axis=ax, keepdims=kd), False),
    ("reduce.amin", lambda x, ax, kd: x.min(axis=ax, keepdims=kd), False),
    ("reduce.std", lambda x, ax, kd: x.std(axis=ax, keepdims=kd), True),
    ("reduce.var", lambda x, ax, kd: x.var(axis=ax, keepdims=kd), True),
    ("reduce.norm1", lambda x, ax, kd: np.abs(x).sum(axis=ax, keepdims=kd),
     True),
    ("reduce.norm2",
     lambda x, ax, kd: np.sqrt((x * x).sum(axis=ax, keepdims=kd)), True),
    ("reduce.normmax",
     lambda x, ax, kd: np.abs(x).max(axis=ax, keepdims=kd), False),
    ("reduce.countNonZero",
     lambda x, ax, kd: (x != 0).sum(axis=ax, keepdims=kd), False),
]


def _run_reduce(op, oracle, check_grad, axis, keepdims):
    rng = np.random.default_rng(_seed(op))
    xv = rng.uniform(0.5, 2.0, size=(3, 4))
    sd = SameDiff()
    x = sd.placeholder("x", (3, 4))
    sd._op(op, [x], name="y", axis=axis, keepdims=keepdims)
    validate(TestCase(sd, {"x": xv},
                      {"y": oracle(xv, axis, keepdims)},
                      grad_wrt=["x"] if check_grad else []))


@pytest.mark.parametrize("op,oracle,check_grad", _REDUCE_SWEEP,
                         ids=[c[0] for c in _REDUCE_SWEEP])
@pytest.mark.parametrize("axis,keepdims", [((1,), False), ((0, 1), True)])
def test_reduce_sweep(op, oracle, check_grad, axis, keepdims):
    _run_reduce(op, oracle, check_grad, axis, keepdims)


def test_shape_op_sweep(rng):
    """Forward-only validation of the structural ops (reference shape
    function tests)."""
    xv = rng.normal(size=(2, 3, 4)).astype(np.float64)
    sd = SameDiff()
    x = sd.placeholder("x", (2, 3, 4))
    sd._op("reshape", [x], name="r", shape=(6, 4))
    sd._op("permute", [x], name="p", dims=(2, 0, 1))
    sd._op("expand_dims", [x], name="e", axis=1)
    sd._op("tile", [x], name="t", reps=(1, 2, 1))
    sd._op("squeeze", [sd._op("expand_dims", [x], name="e2", axis=0)[0]],
           name="sq", axis=(0,))
    sd._op("strided_slice", [x], name="ss", begin=(0, 1, 0),
           end=(2, 3, 4), strides=(1, 1, 2))
    sd._op("split", [x], name="sp", n_out=2, axis=2, num=2)
    sd._op("stack", [x, x], name="st", axis=0)
    sd._op("unstack", [x], name="us", n_out=2, axis=0, num=2)
    sd._op("cast", [x], name="c", dtype="float32")
    validate(TestCase(sd, {"x": xv}, {
        "r": xv.reshape(6, 4),
        "p": xv.transpose(2, 0, 1),
        "e": xv[:, None],
        "t": np.tile(xv, (1, 2, 1)),
        "sq": xv,
        "ss": xv[0:2, 1:3, ::2],
        "sp:0": xv[:, :, :2], "sp:1": xv[:, :, 2:],
        "st": np.stack([xv, xv]),
        "us:0": xv[0], "us:1": xv[1],
        "c": xv.astype(np.float32),
    }, grad_wrt=[]))


def test_coverage_after_sweep():
    """Self-contained (isolation/xdist-safe): runs the whole sweep
    forward-only in-process, then asserts the ledger floor."""
    for op, oracle, rng_range, _ in _UNARY_SWEEP:
        _run_unary(op, oracle, rng_range, check_grad=False)
    for op, oracle, _ in _BINARY_SWEEP:
        _run_binary(op, oracle, check_grad=False)
    for op, oracle, _ in _REDUCE_SWEEP:
        _run_reduce(op, oracle, False, (1,), False)
    rep = coverage_report()
    assert rep["validated"] >= 60, rep["validated"]


# --------------------------------------------------------------------------
# nn / cnn / structural sweep (activation oracles in numpy; conv/pool
# against explicit loops)
# --------------------------------------------------------------------------

def _np_sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


_NN_SWEEP = [
    ("nn.relu", lambda x: np.maximum(x, 0.0), False),  # kink at 0
    ("nn.relu6", lambda x: np.clip(x, 0.0, 6.0), False),
    ("nn.elu", lambda x: np.where(x > 0, x, np.exp(x) - 1.0), True),
    ("nn.sigmoid", _np_sigmoid, True),
    ("nn.tanh", np.tanh, True),
    ("nn.softplus", lambda x: np.log1p(np.exp(x)), True),
    ("nn.softsign", lambda x: x / (1.0 + np.abs(x)), True),
    ("nn.swish", lambda x: x * _np_sigmoid(x), True),
    ("nn.silu", lambda x: x * _np_sigmoid(x), True),
    ("nn.gelu", None, True),   # jax default gelu is the tanh approximation
    ("nn.mish", lambda x: x * np.tanh(np.log1p(np.exp(x))), True),
    ("nn.selu", lambda x: 1.0507009873554805 * np.where(
        x > 0, x, 1.6732632423543772 * (np.exp(x) - 1.0)), True),
]


def _run_nn_unary(op, oracle, check_grad):
    if oracle is None:  # tanh-approx gelu
        def oracle(x):
            return 0.5 * x * (1.0 + np.tanh(
                np.sqrt(2.0 / np.pi) * (x + 0.044715 * x ** 3)))
    rng = np.random.default_rng(_seed(op))
    xv = rng.uniform(0.3, 2.0, size=(2, 3)) * np.where(
        rng.random((2, 3)) < 0.5, -1.0, 1.0)  # both signs, away from 0
    sd = SameDiff()
    x = sd.placeholder("x", (2, 3))
    sd._op(op, [x], name="y")
    validate(TestCase(sd, {"x": xv}, {"y": oracle(xv)},
                      grad_wrt=["x"] if check_grad else [],
                      max_rel_error=1e-3))


@pytest.mark.parametrize("op,oracle,check_grad", _NN_SWEEP,
                         ids=[c[0] for c in _NN_SWEEP])
def test_nn_unary_sweep(op, oracle, check_grad):
    _run_nn_unary(op, oracle, check_grad)


def test_nn_composite_sweep(rng):
    xv = rng.normal(size=(4, 6))
    wv = rng.normal(size=(6, 3))
    bv = rng.normal(size=(3,))
    gv = rng.normal(size=(6,)) + 1.0
    sd = SameDiff()
    x = sd.placeholder("x", (4, 6))
    w = sd.constant(wv, name="w")
    b3 = sd.constant(bv, name="b3")
    g = sd.constant(gv, name="g")
    b6 = sd.constant(np.zeros(6), name="b6")
    sd._op("nn.linear", [x, w, b3], name="lin")
    sd._op("nn.biasAdd", [sd._op("math.mul", [x, x])[0], b6], name="ba")
    sd._op("nn.softmax", [x], name="sm", axis=-1)
    sd._op("nn.logSoftmax", [x], name="lsm", axis=-1)
    sd._op("nn.leakyRelu", [x], name="lr", alpha=0.1)
    sd._op("nn.layerNorm", [x, g, b6], name="ln", axis=-1, eps=1e-5)
    sd._op("nn.pad", [x], name="pd", paddings=((0, 0), (1, 2)),
           mode="constant", value=0.0)

    e = np.exp(xv - xv.max(-1, keepdims=True))
    sm = e / e.sum(-1, keepdims=True)
    mu = xv.mean(-1, keepdims=True)
    var = xv.var(-1, keepdims=True)
    validate(TestCase(sd, {"x": xv}, {
        "lin": xv @ wv + bv,
        "ba": xv * xv,
        "sm": sm,
        "lsm": np.log(sm),
        "lr": np.where(xv > 0, xv, 0.1 * xv),
        "ln": gv * (xv - mu) / np.sqrt(var + 1e-5),
        "pd": np.pad(xv, ((0, 0), (1, 2))),
    }, max_rel_error=1e-3))


def test_cnn_ops_sweep(rng):
    """conv2d / pooling / depthwise against explicit numpy loops."""
    x = rng.normal(size=(2, 6, 6, 3))
    k = rng.normal(size=(3, 3, 3, 4), scale=0.5)
    sd = SameDiff()
    xin = sd.placeholder("x", (2, 6, 6, 3))
    kc = sd.placeholder("k", (3, 3, 3, 4))     # placeholders stay f64 in
    zero = sd.placeholder("b0", (4,))          # the x64 validate context
    sd._op("cnn.conv2d", [xin, kc, zero], name="cv", strides=(1, 1),
           padding="VALID", dilation=(1, 1))
    sd._op("cnn.maxPooling2d", [xin], name="mp", k=(2, 2), s=(2, 2),
           padding="VALID")
    sd._op("cnn.avgPooling2d", [xin], name="ap", k=(2, 2), s=(2, 2),
           padding="VALID")

    conv = np.zeros((2, 4, 4, 4))
    for i in range(4):
        for j in range(4):
            patch = x[:, i:i + 3, j:j + 3, :]
            conv[:, i, j, :] = np.einsum("bhwc,hwco->bo", patch, k)
    mp = x.reshape(2, 3, 2, 3, 2, 3).max(axis=(2, 4))
    ap = x.reshape(2, 3, 2, 3, 2, 3).mean(axis=(2, 4))
    validate(TestCase(sd, {"x": x, "k": k, "b0": np.zeros(4)},
                      {"cv": conv, "mp": mp, "ap": ap},
                      grad_wrt=[], max_rel_error=1e-3))


def test_coverage_final_floor():
    """With the nn/cnn sweeps the harness-validated slice of the registry
    crosses 90 ops (self-contained like test_coverage_after_sweep)."""
    test_coverage_after_sweep()
    for case in _NN_SWEEP:
        _run_nn_unary(*case)
    r = np.random.default_rng(0)
    test_nn_composite_sweep(r)
    test_cnn_ops_sweep(r)
    rep = coverage_report()
    assert rep["validated"] >= 90, rep["validated"]
