"""Per-op validation via the OpValidation harness (reference
``org.nd4j.autodiff.validation.OpValidation`` — forward + gradient per op,
with coverage accounting)."""

import numpy as np
import pytest

from deeplearning4j_tpu.samediff.core import SameDiff
from deeplearning4j_tpu.samediff.validation import (
    TestCase,
    coverage_report,
    validate,
)


def _case(build, inputs, expected, **kw):
    sd = SameDiff.create()
    build(sd)
    return TestCase(sd, inputs, expected, **kw)


def test_matmul_and_bias():
    sd = SameDiff.create()
    a = sd.placeholder("a", shape=(2, 3), dtype="float64")
    b = sd.placeholder("b", shape=(3, 2), dtype="float64")
    y = sd.math.mmul(a, b, name="y")
    av = np.arange(6, dtype=np.float64).reshape(2, 3)
    bv = np.arange(6, dtype=np.float64).reshape(3, 2) * 0.5
    validate(TestCase(sd, {"a": av, "b": bv}, {"y": av @ bv}))


def test_elementwise_family():
    sd = SameDiff.create()
    x = sd.placeholder("x", shape=(4,), dtype="float64")
    y = sd.placeholder("y", shape=(4,), dtype="float64")
    s = (x * y + x - y / 2.0).rename("s")
    xv = np.asarray([0.5, -1.0, 2.0, 3.0])
    yv = np.asarray([1.0, 2.0, -0.5, 0.25])
    validate(TestCase(sd, {"x": xv, "y": yv},
                      {"s": xv * yv + xv - yv / 2.0}))


def test_activations_and_reductions():
    sd = SameDiff.create()
    x = sd.placeholder("x", shape=(3, 4), dtype="float64")
    h = sd.nn.tanh(x)
    m = sd.math.mean(h, dims=(1,), name="m")
    xv = np.linspace(-2, 2, 12).reshape(3, 4)
    validate(TestCase(sd, {"x": xv}, {"m": np.tanh(xv).mean(1)}))


def test_softmax_gradient():
    sd = SameDiff.create()
    x = sd.placeholder("x", shape=(2, 5), dtype="float64")
    p = sd.nn.softmax(x, name="p")
    xv = np.random.default_rng(0).normal(size=(2, 5))
    e = np.exp(xv - xv.max(1, keepdims=True))
    validate(TestCase(sd, {"x": xv}, {"p": e / e.sum(1, keepdims=True)}))


def test_conv2d_forward_and_grad():
    sd = SameDiff.create()
    x = sd.placeholder("x", shape=(1, 4, 4, 2), dtype="float64")
    w = sd.placeholder("w", shape=(2, 2, 2, 3), dtype="float64")
    b = sd.constant(np.zeros(3))
    y = sd.cnn.conv2d(x, w, b, strides=(1, 1), padding="VALID", name="y")
    rng = np.random.default_rng(1)
    xv = rng.normal(size=(1, 4, 4, 2))
    wv = rng.normal(size=(2, 2, 2, 3)) * 0.5
    import jax

    want = np.asarray(jax.lax.conv_general_dilated(
        xv, wv, (1, 1), "VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC")))
    validate(TestCase(sd, {"x": xv, "w": wv}, {"y": want},
                      max_rel_error=1e-3))


def test_layer_norm_grad():
    sd = SameDiff.create()
    x = sd.placeholder("x", shape=(2, 6), dtype="float64")
    g = sd.constant(np.ones(6))
    b = sd.constant(np.zeros(6))
    y = sd.nn.layerNorm(x, g, b, name="y")
    xv = np.random.default_rng(2).normal(size=(2, 6)) * 3
    mu = xv.mean(-1, keepdims=True)
    var = xv.var(-1, keepdims=True)
    validate(TestCase(sd, {"x": xv}, {"y": (xv - mu) / np.sqrt(var + 1e-5)},
                      max_rel_error=1e-3))


def test_coverage_accounting_floor():
    """Reference parity: op validation keeps a coverage ledger. Runs its
    own case so the ledger check is self-contained (independent of test
    order / xdist sharding)."""
    sd = SameDiff()
    x = sd.placeholder("x", (2, 3))
    sd.math.mul(x, x, name="y")
    xv = np.random.default_rng(3).normal(size=(2, 3))
    validate(TestCase(sd, {"x": xv}, {"y": xv * xv}))
    rep = coverage_report()
    assert rep["registered"] > 150  # the registry is substantial
    assert rep["validated"] >= 1    # the case above recorded its ops
    assert isinstance(rep["missing"], list)
