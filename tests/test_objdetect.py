"""YOLO2 output layer + detection utils (reference:
``YoloGradientCheckTests``, ``TestYolo2OutputLayer``, YoloUtils tests)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from deeplearning4j_tpu import serde
from deeplearning4j_tpu.conf.layers_objdetect import (
    DetectedObject,
    Yolo2OutputLayer,
    get_predicted_objects,
    iou,
    nms,
)

PRIORS = ((1.0, 1.5), (3.0, 3.0))


def _layer():
    return Yolo2OutputLayer(boxes=PRIORS)


def _label_grid(b=2, h=4, w=4, c=3):
    """One object per example: box in cell (1,2) [x from 2..3, y 1..2]."""
    labels = np.zeros((b, h, w, 4 + c), np.float32)
    labels[:, 1, 2, 0:4] = [2.1, 1.2, 2.9, 1.9]  # x1,y1,x2,y2 grid units
    labels[:, 1, 2, 4] = 1.0  # class 0
    return labels


def test_shapes_and_activation():
    layer = _layer()
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(2, 4, 4, 2 * (5 + 3))), jnp.float32)
    y, _ = layer.forward({}, {}, x)
    assert y.shape == (2, 4, 4, 2 * (5 + 3))
    grid = np.asarray(y).reshape(2, 4, 4, 2, 8)
    # centers are inside their cells, confidences in (0,1), probs sum to 1
    cx = grid[..., 0]
    assert (cx >= 0).all() and (cx <= 4).all()
    conf = grid[..., 4]
    assert (conf > 0).all() and (conf < 1).all()
    np.testing.assert_allclose(grid[..., 5:].sum(-1), 1.0, rtol=1e-5)


def test_loss_finite_and_differentiable():
    layer = _layer()
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(2, 4, 4, 16)) * 0.1, jnp.float32)
    labels = jnp.asarray(_label_grid())

    def loss(x):
        return layer.score({}, x, labels)

    val, grad = jax.value_and_grad(loss)(x)
    assert np.isfinite(float(val))
    assert np.isfinite(np.asarray(grad)).all()
    assert float(jnp.abs(grad).sum()) > 0


def test_loss_decreases_under_training():
    from deeplearning4j_tpu.conf import Activation, InputType, WeightInit
    from deeplearning4j_tpu.conf.graph import ComputationGraphConfiguration
    from deeplearning4j_tpu.conf.layers_cnn import ConvolutionLayer, ConvolutionMode
    from deeplearning4j_tpu.conf.multilayer import NeuralNetConfiguration
    from deeplearning4j_tpu.conf.updaters import Adam
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.nn.graph import ComputationGraph

    g = (NeuralNetConfiguration.builder()
         .seed(1).updater(Adam(1e-2)).weight_init(WeightInit.XAVIER)
         .graph_builder()
         .add_inputs("input")
         .set_input_types(InputType.convolutional(16, 16, 3)))
    g.add_layer("c1", ConvolutionLayer(
        n_out=16, kernel_size=(3, 3), stride=(2, 2),
        activation=Activation.RELU,
        convolution_mode=ConvolutionMode.SAME), "input")
    g.add_layer("c2", ConvolutionLayer(
        n_out=16, kernel_size=(3, 3), stride=(2, 2),
        activation=Activation.RELU,
        convolution_mode=ConvolutionMode.SAME), "c1")
    g.add_layer("detect", ConvolutionLayer(
        n_out=2 * (5 + 3), kernel_size=(1, 1),
        activation=Activation.IDENTITY,
        convolution_mode=ConvolutionMode.SAME), "c2")
    g.add_layer("yolo", Yolo2OutputLayer(boxes=PRIORS), "detect")
    g.set_outputs("yolo")
    net = ComputationGraph(g.build()).init()

    rng = np.random.default_rng(0)
    feats = rng.normal(size=(2, 16, 16, 3)).astype(np.float32)
    ds = DataSet(feats, _label_grid(b=2, h=4, w=4, c=3))
    s0 = net.fit_batch(ds)
    for _ in range(30):
        s1 = net.fit_batch(ds)
    assert s1 < s0


def test_get_predicted_objects_and_nms():
    layer = _layer()
    # hand-build an activated grid: [b,h,w,nb,(5+C)]
    act = np.zeros((1, 4, 4, 2, 8), np.float32)
    # strong detection at cell (1,2), anchor 0, class 1
    act[0, 1, 2, 0] = [2.5, 1.5, 1.0, 1.0, 0.9, 0.05, 0.9, 0.05]
    # overlapping weaker detection, same class -> NMS suppressed
    act[0, 1, 2, 1] = [2.6, 1.4, 1.2, 1.2, 0.6, 0.05, 0.9, 0.05]
    # distant detection, other class -> kept
    act[0, 3, 0, 0] = [0.5, 3.5, 1.0, 1.0, 0.8, 0.9, 0.05, 0.05]
    objs = get_predicted_objects(layer, act.reshape(1, 4, 4, 16),
                                 threshold=0.4)
    assert len(objs) == 3
    kept = nms(objs, iou_threshold=0.4)
    assert len(kept) == 2
    classes = sorted(o.predicted_class for o in kept)
    assert classes == [0, 1]


def test_iou_math():
    a = DetectedObject(0, 1.0, 1.0, 2.0, 2.0, 0, 1.0)
    b = DetectedObject(0, 1.0, 1.0, 2.0, 2.0, 0, 1.0)
    assert iou(a, b) == pytest.approx(1.0)
    c = DetectedObject(0, 10.0, 10.0, 2.0, 2.0, 0, 1.0)
    assert iou(a, c) == 0.0


def test_serde_roundtrip():
    layer = _layer()
    back = serde.from_json(serde.to_json(layer))
    assert back == layer
    assert back.boxes == PRIORS


def test_bad_depth_raises():
    layer = _layer()
    x = jnp.zeros((1, 4, 4, 15))  # not divisible by nb*(5+C)
    with pytest.raises(ValueError):
        layer.forward({}, {}, x)


def test_mask_excludes_padded_examples():
    layer = _layer()
    rng = np.random.default_rng(0)
    x1 = jnp.asarray(rng.normal(size=(2, 4, 4, 16)) * 0.1, jnp.float32)
    labels = jnp.asarray(_label_grid(b=2))
    # pad with a garbage third example, mask it out
    x2 = jnp.concatenate([x1, jnp.ones((1, 4, 4, 16)) * 5.0])
    labels2 = jnp.concatenate([labels, jnp.zeros((1, 4, 4, 7))])
    mask = jnp.asarray([1.0, 1.0, 0.0])
    unmasked = layer.score({}, x1, labels)
    masked = layer.score({}, x2, labels2, mask=mask)
    np.testing.assert_allclose(float(unmasked), float(masked), rtol=1e-6)
