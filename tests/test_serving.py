"""High-throughput serving: dynamic cross-request batcher, bucketed AOT
warmup, inference-graph optimization (reference oracle: the
``org.deeplearning4j.parallelism.inference`` observable tests, SURVEY.md
§3.6 — batched observables must demux each caller's exact slice, and a
bad observation fails alone).

All engine/aot assertions read COUNTER DELTAS: the AOT executable cache
and the telemetry registry are process-global and shared across the test
session.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.conf import Activation, InputType
from deeplearning4j_tpu.conf.layers import (
    ActivationLayer,
    DenseLayer,
    DropoutLayer,
    OutputLayer,
)
from deeplearning4j_tpu.conf.layers_cnn import (
    BatchNormalization,
    ConvolutionLayer,
    FusedConvBN1x1,
)
from deeplearning4j_tpu.conf.losses import LossMCXENT
from deeplearning4j_tpu.conf.multilayer import NeuralNetConfiguration
from deeplearning4j_tpu.conf.updaters import Sgd
from deeplearning4j_tpu.nn.inference_opt import optimize_for_inference
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.optimize import aot_cache
from deeplearning4j_tpu.parallel.batcher import (
    BadRequestError,
    BatchingConfig,
    DeadlineExpiredError,
    InferenceEngine,
    ServerOverloadedError,
    bucket_ladder,
    bucket_rows,
    next_pow2,
)

pytestmark = pytest.mark.serving


def _mlp_conf(n_in=4, n_out=3, hidden=8, seed=0):
    return (NeuralNetConfiguration.builder().seed(seed).updater(Sgd(0.1))
            .list()
            .layer(DenseLayer(n_out=hidden, activation=Activation.TANH))
            .layer(OutputLayer(n_out=n_out, activation=Activation.SOFTMAX,
                               loss_fn=LossMCXENT()))
            .set_input_type(InputType.feed_forward(n_in)).build())


def _mlp(seed=0, hidden=8):
    # a distinct ``hidden`` width gives a test its own AOT graph key: the
    # executable cache is process-global and keyed by conf-derived graph
    # signature, so identical architectures SHARE compiled buckets across
    # tests (by design — assertions about cold-cache compiles need an
    # architecture no other test uses)
    return MultiLayerNetwork(_mlp_conf(hidden=hidden, seed=seed)).init()


def _engine(model=None, **cfg):
    cfg.setdefault("max_batch", 8)
    return InferenceEngine(model or _mlp(), BatchingConfig(**cfg),
                           graph_opt=False)


def _inert_engine(**cfg):
    """Engine whose dispatcher never starts: requests stay queued so
    drain/launch/expiry can be driven deterministically."""
    eng = _engine(**cfg)
    eng._ensure_thread = lambda: None
    return eng


# --- bucket math -----------------------------------------------------------

def test_next_pow2():
    assert [next_pow2(n) for n in (1, 2, 3, 4, 5, 8, 9, 64, 65)] == \
        [1, 2, 4, 4, 8, 8, 16, 64, 128]


def test_bucket_rows_alignment():
    assert bucket_rows(5) == 8
    assert bucket_rows(8) == 8
    assert bucket_rows(9, align=8) == 16  # 2 rows/device -> 16 total
    assert bucket_rows(17, align=8) == 32
    assert bucket_rows(1, align=8) == 8


def test_bucket_ladder_covers_max_batch():
    assert bucket_ladder(8) == [1, 2, 4, 8]
    assert bucket_ladder(6) == [1, 2, 4, 8]  # ceil to cover a 6-row batch
    assert bucket_ladder(16, align=8) == [8, 16]


# --- coalescing + demux (deterministic, no dispatcher thread) --------------

def test_coalesced_launch_demuxes_exact_slices():
    net = _mlp()
    eng = InferenceEngine(net, BatchingConfig(max_batch=8, max_delay_ms=0.0),
                          graph_opt=False)
    eng._ensure_thread = lambda: None
    rng = np.random.default_rng(0)
    xs = [rng.normal(size=(n, 4)).astype(np.float32) for n in (1, 3, 2)]
    reqs = [eng.submit((x,)) for x in xs]
    batch = eng._take_batch()
    assert len(batch) == 3  # one shared launch for all three callers
    eng._launch(batch)
    for req, x in zip(reqs, xs):
        got = eng.result(req)
        assert got.shape == (x.shape[0], 3)
        # bit-identical to this caller's own unbatched forward at the
        # same bucket (row-independent compute; padding rows sliced off)
        np.testing.assert_array_equal(got, np.asarray(net.output(x)))
    eng.close()


def test_drain_respects_max_batch():
    eng = _inert_engine(max_batch=4, max_delay_ms=0.0)
    reqs = [eng.submit((np.zeros((2, 4), np.float32),)) for _ in range(3)]
    batch = eng._take_batch()
    assert [r.n for r in batch] == [2, 2]  # third would overflow max_batch
    assert eng.stats()["queue_depth"] == 1
    assert batch[0] is reqs[0] and batch[1] is reqs[1]
    eng.close()


def test_oversized_request_launches_alone():
    eng = _inert_engine(max_batch=4, max_delay_ms=0.0)
    big = eng.submit((np.zeros((6, 4), np.float32),))
    batch = eng._take_batch()
    assert batch == [big]
    eng._launch(batch)
    assert eng.result(big).shape == (6, 3)
    eng.close()


def test_heterogeneous_shapes_never_share_a_launch():
    """A (B, 4) caller and a (B, 2, 4)-shaped caller must not be
    concatenated; grouping is by trailing-shape signature."""
    eng = _inert_engine(max_delay_ms=0.0)
    eng._templates = None  # shape-agnostic backend: group sig only
    a = eng.submit((np.zeros((2, 4), np.float32),))
    eng.submit((np.zeros((1, 2, 4), np.float32),))
    batch = eng._take_batch()
    assert batch == [a]
    assert eng.stats()["queue_depth"] == 1
    eng.close()


# --- concurrent clients through the real dispatcher ------------------------

def test_concurrent_clients_each_get_their_own_result():
    net = _mlp()
    eng = InferenceEngine(net, BatchingConfig(max_batch=16, max_delay_ms=5),
                          graph_opt=False)
    rng = np.random.default_rng(1)
    inputs = [rng.normal(size=(1 + i % 5, 4)).astype(np.float32)
              for i in range(24)]
    results = [None] * len(inputs)

    def client(i):
        results[i] = eng.predict(inputs[i])

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(len(inputs))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for i, x in enumerate(inputs):
        np.testing.assert_array_equal(results[i], np.asarray(net.output(x)))
    eng.close()


def test_malformed_request_fails_sender_only():
    net = _mlp()
    eng = InferenceEngine(net, BatchingConfig(max_batch=8, max_delay_ms=20),
                          graph_opt=False)
    x = np.ones((2, 4), np.float32)
    good = eng.submit((x,))
    with pytest.raises(BadRequestError, match="does not match"):
        eng.submit((np.ones((2, 5), np.float32),))  # wrong feature width
    with pytest.raises(BadRequestError, match="malformed|ragged"):
        eng.submit(([[1.0, 2.0], [3.0]],))
    with pytest.raises(BadRequestError, match="takes 1 input"):
        eng.submit((x, x))
    # the shared batch was never poisoned: the good caller completes
    np.testing.assert_array_equal(eng.result(good),
                                  np.asarray(net.output(x)))
    eng.close()


def test_backend_failure_reaches_every_coalesced_caller():
    class Broken:
        def output(self, *xs):
            raise RuntimeError("device exploded")

    eng = InferenceEngine(Broken(), BatchingConfig(max_delay_ms=0.0),
                          graph_opt=False)
    req = eng.submit((np.ones((1, 4), np.float32),))
    with pytest.raises(RuntimeError, match="device exploded"):
        eng.result(req)
    eng.close()


# --- admission control / deadlines -----------------------------------------

def test_queue_full_rejects_with_503_semantics():
    eng = _inert_engine(max_queue=2)
    eng.submit((np.zeros((1, 4), np.float32),))
    eng.submit((np.zeros((1, 4), np.float32),))
    with pytest.raises(ServerOverloadedError, match="queue full"):
        eng.submit((np.zeros((1, 4), np.float32),))
    eng.close()


def test_expired_deadline_never_launches():
    eng = _inert_engine()
    req = eng.submit((np.zeros((1, 4), np.float32),), timeout_ms=0.01)
    time.sleep(0.005)
    with eng._cond:
        eng._expire_locked(time.monotonic())
    with pytest.raises(DeadlineExpiredError):
        eng.result(req)
    assert eng.stats()["queue_depth"] == 0
    eng.close()


def test_close_fails_pending_requests():
    eng = _inert_engine()
    req = eng.submit((np.zeros((1, 4), np.float32),))
    eng.close()
    with pytest.raises(RuntimeError, match="closed"):
        eng.result(req)
    with pytest.raises(RuntimeError, match="closed"):
        eng.submit((np.zeros((1, 4), np.float32),))


# --- warmup / zero-recompile invariant -------------------------------------

def test_warmup_then_ragged_sweep_zero_recompiles():
    eng = _engine(_mlp(hidden=11), max_batch=8)  # unique arch: cold cache
    report = eng.warmup()
    assert report["buckets"] == [1, 2, 4, 8]
    assert report["compiled"] >= 1  # cold cache: at least one real compile
    miss0 = aot_cache.stats()["misses"]
    rng = np.random.default_rng(2)
    for n in (1, 2, 3, 4, 5, 6, 7, 8, 3, 7):  # ragged sweep
        out = eng.predict(rng.normal(size=(n, 4)).astype(np.float32))
        assert out.shape == (n, 3)
    stats = aot_cache.stats()
    assert stats["misses"] == miss0, "ragged traffic recompiled"
    assert stats["hits"] > 0
    eng.close()


def test_warmup_is_idempotent():
    eng = _engine(max_batch=4)
    eng.warmup()
    assert eng.warmup()["compiled"] == 0  # second pass: all cached
    eng.close()


def test_warmup_requires_shapes_when_conf_missing():
    class Anon:
        def output(self, *xs):
            return xs[0]

    eng = InferenceEngine(Anon(), BatchingConfig(max_batch=2),
                          graph_opt=False)
    with pytest.raises(ValueError, match="pass\\s+warmup"):
        eng.warmup()
    eng.close()


# --- inference-graph optimization pass -------------------------------------

def _bn_net(seed=3):
    conf = (NeuralNetConfiguration.builder().seed(seed).list()
            .layer(ConvolutionLayer(n_out=4, kernel_size=(3, 3),
                                    activation=Activation.IDENTITY))
            .layer(BatchNormalization())
            .layer(ActivationLayer(activation=Activation.RELU))
            .layer(DropoutLayer(dropout=0.5))
            .layer(DenseLayer(n_out=8, activation=Activation.IDENTITY,
                              has_bias=False))
            .layer(BatchNormalization(activation=Activation.RELU))
            .layer(OutputLayer(n_out=3, activation=Activation.SOFTMAX,
                               loss_fn=LossMCXENT()))
            .set_input_type(InputType.convolutional(6, 6, 2)).build())
    net = MultiLayerNetwork(conf)
    net.init()
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(16, 6, 6, 2)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 16)]
    net.fit(x, y)  # non-trivial BN running stats
    return net, x


def test_bn_fold_matches_unoptimized_output():
    net, x = _bn_net()
    opt = optimize_for_inference(net)
    names = [type(l).__name__ for l in opt.conf.layers]
    assert "BatchNormalization" not in names
    assert "DropoutLayer" not in names
    np.testing.assert_allclose(np.asarray(opt.output(x)),
                               np.asarray(net.output(x)),
                               rtol=1e-5, atol=1e-6)


def test_optimize_never_mutates_original():
    net, x = _bn_net(seed=4)
    before = np.asarray(net.output(x))
    layers_before = tuple(net.conf.layers)
    optimize_for_inference(net)
    assert tuple(net.conf.layers) == layers_before
    np.testing.assert_array_equal(np.asarray(net.output(x)), before)


def test_bf16_policy_outputs_f32():
    net, x = _bn_net(seed=5)
    opt = optimize_for_inference(net, bf16=True)
    out = np.asarray(opt.output(x))
    assert out.dtype == np.float32
    np.testing.assert_allclose(out, np.asarray(net.output(x)), atol=0.05)


def test_fused_conv_bn_unfuses_to_plain_conv():
    conf = (NeuralNetConfiguration.builder().seed(6).list()
            .layer(FusedConvBN1x1(n_out=4, activation=Activation.RELU))
            .layer(OutputLayer(n_out=3, activation=Activation.SOFTMAX,
                               loss_fn=LossMCXENT()))
            .set_input_type(InputType.convolutional(5, 5, 2)).build())
    net = MultiLayerNetwork(conf)
    net.init()
    rng = np.random.default_rng(6)
    x = rng.normal(size=(8, 5, 5, 2)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 8)]
    net.fit(x, y)
    opt = optimize_for_inference(net)
    names = [type(l).__name__ for l in opt.conf.layers]
    assert "FusedConvBN1x1" not in names
    assert "ConvolutionLayer" in names
    np.testing.assert_allclose(np.asarray(opt.output(x)),
                               np.asarray(net.output(x)),
                               rtol=1e-5, atol=1e-6)


# --- ParallelInference bucketing -------------------------------------------

def test_parallel_inference_bucketed_sweep_single_compile():
    from deeplearning4j_tpu.parallel import ParallelInference

    net = _mlp(seed=7)
    pi = ParallelInference(net)  # 8 virtual devices -> align=8
    rng = np.random.default_rng(7)
    # reference outputs computed UP FRONT: each exact-size net.output()
    # launch has its own signature and must not be charged to the sweep
    xs = [rng.normal(size=(n, 4)).astype(np.float32)
          for n in (1, 2, 5, 7, 8, 4)]  # all quantize to the 8-row bucket
    refs = [np.asarray(net.output(x)) for x in xs]
    first = pi.output(xs[0])
    assert first.shape == (1, 3)
    miss0 = pi.cache_stats()["misses"]
    for x, ref in zip(xs, refs):
        np.testing.assert_allclose(pi.output(x), ref, atol=1e-6)
    assert pi.cache_stats()["misses"] == miss0


def test_parallel_inference_batch_limit_tail_rides_same_buckets():
    from deeplearning4j_tpu.parallel import ParallelInference

    net = _mlp(seed=8)
    pi = ParallelInference(net, batch_limit=16)
    rng = np.random.default_rng(8)
    # 38 = 16 + 16 + 6-row tail; the tail pads to the 8-row bucket — a
    # ladder shape, never a per-size shape
    x = rng.normal(size=(38, 4)).astype(np.float32)
    ref = np.asarray(net.output(x))
    pi.output(rng.normal(size=(16, 4)).astype(np.float32))
    miss0 = pi.cache_stats()["misses"]
    got = pi.output(x)
    np.testing.assert_allclose(got, ref, atol=1e-6)
    assert pi.cache_stats()["misses"] - miss0 <= 1  # the 8-row bucket
    miss1 = pi.cache_stats()["misses"]
    pi.output(rng.normal(size=(38, 4)).astype(np.float32))
    assert pi.cache_stats()["misses"] == miss1  # repeat size: all hits


# --- InferenceServer over HTTP ---------------------------------------------

def _post(url, payload, timeout=30):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_server_concurrent_predicts_share_engine():
    from deeplearning4j_tpu.parallel import InferenceServer

    net = _mlp(seed=9)
    server = InferenceServer(
        net, batching=BatchingConfig(max_batch=8, max_delay_ms=5)
    ).start(port=0, warmup=True)
    try:
        base = f"http://127.0.0.1:{server.port}"
        rng = np.random.default_rng(9)
        inputs = [rng.normal(size=(1 + i % 4, 4)).astype(np.float32)
                  for i in range(8)]
        results = [None] * len(inputs)

        def client(i):
            results[i] = _post(base + "/predict",
                               {"inputs": [inputs[i].tolist()]})

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(len(inputs))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for i, x in enumerate(inputs):
            code, body = results[i]
            assert code == 200, body
            np.testing.assert_allclose(
                np.asarray(body["outputs"][0], np.float32),
                np.asarray(net.output(x)), rtol=1e-5, atol=1e-6)
        # serving metrics are live on the server's own scrape endpoint
        text = urllib.request.urlopen(base + "/metrics",
                                      timeout=10).read().decode()
        assert "dl4j_serving_requests_total" in text
        assert "dl4j_serving_batches_total" in text
        info = json.loads(urllib.request.urlopen(base + "/model",
                                                 timeout=10).read())
        assert info["batching"]["max_batch"] == 8
        assert info["buckets"] == [1, 2, 4, 8]
    finally:
        server.stop()


def test_server_uint8_image_path_matches_direct_output():
    from deeplearning4j_tpu.parallel import InferenceServer

    conf = (NeuralNetConfiguration.builder().seed(10).list()
            .layer(ConvolutionLayer(n_out=3, kernel_size=(3, 3),
                                    activation=Activation.RELU))
            .layer(OutputLayer(n_out=2, activation=Activation.SOFTMAX,
                               loss_fn=LossMCXENT()))
            .set_input_type(InputType.convolutional(6, 6, 1)).build())
    net = MultiLayerNetwork(conf).init()
    server = InferenceServer(net, graph_opt=False).start(port=0, warmup=True)
    try:
        base = f"http://127.0.0.1:{server.port}"
        x = np.random.default_rng(10).integers(
            0, 256, size=(3, 6, 6, 1)).astype(np.uint8)
        ref = np.asarray(net.output(x))  # own exact-size launch: before
        # the snapshot, so its compile isn't charged to the served path
        miss0 = aot_cache.stats()["misses"]
        code, body = _post(base + "/predict", {"inputs": [x.tolist()]})
        assert code == 200, body
        # integer-valued image JSON rides as uint8 (the in-jit dequant
        # path), matching a direct uint8 output() call exactly — and the
        # uint8 executable was part of warmup, so no compile happened
        np.testing.assert_array_equal(
            np.asarray(body["outputs"][0], np.float32), ref)
        assert aot_cache.stats()["misses"] == miss0
    finally:
        server.stop()


def test_server_legacy_lock_path_still_serves():
    from deeplearning4j_tpu.parallel import InferenceServer

    net = _mlp(seed=11)
    server = InferenceServer(net, batching=None).start(port=0)
    try:
        assert server.engine is None
        base = f"http://127.0.0.1:{server.port}"
        x = np.ones((2, 4), np.float32)
        code, body = _post(base + "/predict", {"inputs": [x.tolist()]})
        assert code == 200
        np.testing.assert_allclose(
            np.asarray(body["outputs"][0], np.float32),
            np.asarray(net.output(x)), rtol=1e-5, atol=1e-6)
        assert server.warmup() == {"buckets": [], "compiled": 0}
    finally:
        server.stop()


def test_server_503_when_engine_overloaded():
    from deeplearning4j_tpu.parallel import InferenceServer

    net = _mlp(seed=12)
    server = InferenceServer(
        net, batching=BatchingConfig(max_queue=1), graph_opt=False
    ).start(port=0)
    try:
        # jam the dispatcher so submissions pile up against max_queue
        server.engine._ensure_thread = lambda: None
        server.engine.submit((np.ones((1, 4), np.float32),))
        base = f"http://127.0.0.1:{server.port}"
        code, body = _post(base + "/predict", {"inputs": [[[1, 2, 3, 4]]]})
        assert code == 503
        assert "queue full" in body["error"]
    finally:
        server.stop()
