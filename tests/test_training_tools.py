"""Transfer learning, early stopping, checkpointing, stats/UI (reference:
``TransferLearningMLNTest``, ``TestEarlyStopping``, CheckpointListener
tests, StatsListener tests)."""

import os

import numpy as np
import pytest

from deeplearning4j_tpu.conf import Activation, InputType, WeightInit
from deeplearning4j_tpu.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.conf.losses import LossMCXENT, LossMSE
from deeplearning4j_tpu.conf.multilayer import NeuralNetConfiguration
from deeplearning4j_tpu.conf.updaters import Adam, Sgd
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import ArrayDataSetIterator
from deeplearning4j_tpu.earlystopping import (
    DataSetLossCalculator,
    EarlyStoppingConfiguration,
    EarlyStoppingTrainer,
    InvalidScoreIterationTerminationCondition,
    LocalFileModelSaver,
    MaxEpochsTerminationCondition,
    MaxScoreIterationTerminationCondition,
    ScoreImprovementEpochTerminationCondition,
    TerminationReason,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.transferlearning import (
    FineTuneConfiguration,
    FrozenLayer,
    TransferLearning,
    TransferLearningHelper,
)
from deeplearning4j_tpu.optimize.checkpoint import CheckpointListener
from deeplearning4j_tpu.ui import (
    FileStatsStorage,
    InMemoryStatsStorage,
    StatsListener,
    UIServer,
)


def _conf(n_in=4, classes=3, updater=None):
    return (NeuralNetConfiguration.builder()
            .seed(12345)
            .updater(updater or Adam(1e-2))
            .list()
            .layer(DenseLayer(n_out=8, activation=Activation.TANH))
            .layer(DenseLayer(n_out=6, activation=Activation.RELU))
            .layer(OutputLayer(n_out=classes, activation=Activation.SOFTMAX,
                               loss_fn=LossMCXENT()))
            .set_input_type(InputType.feed_forward(n_in))
            .build())


def _data(n=48, n_in=4, classes=3, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, n_in)).astype(np.float32)
    y = np.eye(classes, dtype=np.float32)[rng.integers(0, classes, n)]
    return DataSet(x, y)


def _flat(net, idx):
    return np.concatenate([np.asarray(v).ravel()
                           for v in sorted(net.params[str(idx)].items())
                           for v in [v[1]]])


# --------------------------------------------------------------------------
# transfer learning
# --------------------------------------------------------------------------

def test_frozen_layers_do_not_move():
    base = MultiLayerNetwork(_conf())
    base.init()
    ds = _data()
    base.fit_batch(ds)

    t_net = (TransferLearning.Builder(base)
             .fine_tune_configuration(FineTuneConfiguration(updater=Sgd(0.1)))
             .set_feature_extractor(1)  # freeze layers 0..1
             .build())
    assert isinstance(t_net.conf.layers[0], FrozenLayer)
    assert isinstance(t_net.conf.layers[1], FrozenLayer)
    frozen0 = _flat(t_net, 0).copy()
    frozen1 = _flat(t_net, 1).copy()
    head_before = _flat(t_net, 2).copy()
    for _ in range(5):
        t_net.fit_batch(ds)
    np.testing.assert_array_equal(_flat(t_net, 0), frozen0)
    np.testing.assert_array_equal(_flat(t_net, 1), frozen1)
    assert not np.allclose(_flat(t_net, 2), head_before)


def test_transfer_replace_output():
    base = MultiLayerNetwork(_conf(classes=3))
    base.init()
    w0 = _flat(base, 0).copy()

    t_net = (TransferLearning.Builder(base)
             .set_feature_extractor(0)
             .remove_output_layer()
             .add_layer(OutputLayer(n_out=5, activation=Activation.SOFTMAX,
                                    loss_fn=LossMCXENT(),
                                    updater=Sgd(0.1)))
             .build())
    # retained layer params copied over
    np.testing.assert_array_equal(_flat(t_net, 0), w0)
    ds = _data(classes=5)
    s0 = t_net.fit_batch(ds)
    for _ in range(10):
        s1 = t_net.fit_batch(ds)
    assert s1 < s0
    out = t_net.output(ds.features)
    assert out.shape == (48, 5)


def test_n_out_replace_reinits_next_layer():
    base = MultiLayerNetwork(_conf())
    base.init()
    t_net = (TransferLearning.Builder(base)
             .n_out_replace(1, 12, WeightInit.XAVIER)
             .build())
    assert t_net.params["1"]["W"].shape == (8, 12)
    assert t_net.params["2"]["W"].shape == (12, 3)
    # layer 0 untouched
    np.testing.assert_array_equal(_flat(t_net, 0), _flat(base, 0))


def test_transfer_learning_helper_featurize():
    base = MultiLayerNetwork(_conf())
    base.init()
    t_net = (TransferLearning.Builder(base)
             .set_feature_extractor(0)
             .build())
    helper = TransferLearningHelper(t_net)
    ds = _data()
    feat = helper.featurize(ds)
    assert feat.features.shape == (48, 8)
    s0 = None
    for _ in range(5):
        helper.fit_featurized(feat)
        s0 = s0 or helper.unfrozen_mln().score_value
    assert helper.unfrozen_mln().score_value <= s0
    # tail training propagated back to the full net
    full_out = t_net.output(ds.features)
    tail_out = helper.output_from_featurized(feat.features)
    np.testing.assert_allclose(np.asarray(full_out), np.asarray(tail_out),
                               atol=1e-5)


# --------------------------------------------------------------------------
# early stopping
# --------------------------------------------------------------------------

def test_early_stopping_max_epochs(tmp_path):
    net = MultiLayerNetwork(_conf())
    ds = _data()
    it = ArrayDataSetIterator(ds.features, ds.labels, batch=16)
    cfg = EarlyStoppingConfiguration(
        epoch_termination_conditions=[MaxEpochsTerminationCondition(4)],
        score_calculator=DataSetLossCalculator(
            ArrayDataSetIterator(ds.features, ds.labels, batch=16)),
        model_saver=LocalFileModelSaver(str(tmp_path)))
    result = EarlyStoppingTrainer(cfg, net, it).fit()
    assert result.termination_reason is TerminationReason.EPOCH
    assert result.total_epochs == 4
    assert result.best_model_epoch >= 0
    best = result.get_best_model()
    assert best is not None
    assert os.path.exists(tmp_path / "bestModel.zip")
    # best model scores what the result claims (fresh calculator)
    calc = DataSetLossCalculator(
        ArrayDataSetIterator(ds.features, ds.labels, batch=16))
    assert abs(calc.calculate_score(best) - result.best_model_score) < 1e-4


def test_early_stopping_patience():
    net = MultiLayerNetwork(_conf(updater=Sgd(0.0)))  # lr=0: never improves
    ds = _data()
    it = ArrayDataSetIterator(ds.features, ds.labels, batch=16)
    cfg = EarlyStoppingConfiguration(
        epoch_termination_conditions=[
            ScoreImprovementEpochTerminationCondition(2),
            MaxEpochsTerminationCondition(50)],
        score_calculator=DataSetLossCalculator(
            ArrayDataSetIterator(ds.features, ds.labels, batch=16)))
    result = EarlyStoppingTrainer(cfg, net, it).fit()
    assert result.total_epochs < 50
    assert "ScoreImprovement" in result.termination_details


def test_early_stopping_iteration_condition():
    net = MultiLayerNetwork(_conf())
    ds = _data()
    it = ArrayDataSetIterator(ds.features, ds.labels, batch=16)
    cfg = EarlyStoppingConfiguration(
        epoch_termination_conditions=[MaxEpochsTerminationCondition(100)],
        iteration_termination_conditions=[
            MaxScoreIterationTerminationCondition(1e-9),
            InvalidScoreIterationTerminationCondition()])
    result = EarlyStoppingTrainer(cfg, net, it).fit()
    assert result.termination_reason is TerminationReason.ITERATION


# --------------------------------------------------------------------------
# checkpoint listener
# --------------------------------------------------------------------------

def test_checkpoint_listener_epochs_and_retention(tmp_path):
    net = MultiLayerNetwork(_conf())
    net.init()
    cl = CheckpointListener(str(tmp_path), save_every_n_epochs=1,
                            keep_last=2)
    net.set_listeners(cl)
    ds = _data()
    net.fit(ArrayDataSetIterator(ds.features, ds.labels, batch=16),
            epochs=5)
    cps = cl.list_checkpoints()
    assert len(cps) == 2  # retention kept only the last 2
    assert cps[-1].epoch == 4
    restored = cl.load_checkpoint()
    np.testing.assert_allclose(restored.params_flat(), net.params_flat(),
                               rtol=1e-6)
    # resume continues training (exact resume incl. updater state)
    restored.fit_batch(ds)


def test_checkpoint_listener_iterations(tmp_path):
    net = MultiLayerNetwork(_conf())
    net.init()
    cl = CheckpointListener(str(tmp_path), save_every_n_iterations=2)
    net.set_listeners(cl)
    ds = _data()
    for _ in range(6):
        net.fit_batch(ds)
    assert len(cl.list_checkpoints()) == 3


# --------------------------------------------------------------------------
# stats + UI
# --------------------------------------------------------------------------

def test_stats_listener_and_dashboard(tmp_path):
    net = MultiLayerNetwork(_conf())
    net.init()
    storage = InMemoryStatsStorage()
    net.set_listeners(StatsListener(storage, frequency=1))
    ds = _data()
    for _ in range(4):
        net.fit_batch(ds)
    recs = storage.records()
    assert len(recs) == 4
    assert "param_mean_mag" in recs[0]
    assert "update_param_ratio_log10" in recs[1]
    assert recs[1]["update_param_ratio_log10"]  # nonempty after an update
    html_path = UIServer.get_instance().attach(storage).render(
        str(tmp_path / "dash.html"))
    text = open(html_path).read()
    assert "Model score" in text and "<svg" in text
    UIServer.get_instance().detach(storage)


def test_file_stats_storage_roundtrip(tmp_path):
    p = str(tmp_path / "stats.jsonl")
    st = FileStatsStorage(p)
    st.put({"session": "s", "iteration": 0, "score": 1.0})
    st.put({"session": "s", "iteration": 1, "score": 0.5})
    st2 = FileStatsStorage(p)
    assert len(st2.records()) == 2
    assert st2.records()[1]["score"] == 0.5


def test_transfer_net_serializes(tmp_path):
    from deeplearning4j_tpu.util import serializer

    base = MultiLayerNetwork(_conf())
    base.init()
    t_net = (TransferLearning.Builder(base)
             .set_feature_extractor(0)
             .build())
    p = str(tmp_path / "transfer.zip")
    serializer.write_model(t_net, p)
    restored = serializer.restore_multi_layer_network(p)
    assert isinstance(restored.conf.layers[0], FrozenLayer)
    np.testing.assert_allclose(restored.params_flat(), t_net.params_flat(),
                               rtol=1e-6)


def test_early_stopping_validates_config():
    net = MultiLayerNetwork(_conf())
    ds = _data()
    it = ArrayDataSetIterator(ds.features, ds.labels, batch=16)
    with pytest.raises(ValueError):
        EarlyStoppingTrainer(EarlyStoppingConfiguration(), net, it).fit()


def test_early_stopping_conditions_reset_between_fits():
    ds = _data()
    cfg = EarlyStoppingConfiguration(
        epoch_termination_conditions=[
            ScoreImprovementEpochTerminationCondition(1),
            MaxEpochsTerminationCondition(10)],
        score_calculator=DataSetLossCalculator(
            ArrayDataSetIterator(ds.features, ds.labels, batch=16)))
    for _ in range(2):  # reusing cfg must not carry _best/_bad over
        net = MultiLayerNetwork(_conf(updater=Sgd(0.0)))
        it = ArrayDataSetIterator(ds.features, ds.labels, batch=16)
        result = EarlyStoppingTrainer(cfg, net, it).fit()
        assert result.total_epochs >= 2  # epoch 0 eval + at least 1 more


def test_early_stopping_eval_frequency_respects_patience():
    ds = _data()
    net = MultiLayerNetwork(_conf(updater=Sgd(0.0)))
    it = ArrayDataSetIterator(ds.features, ds.labels, batch=16)
    cfg = EarlyStoppingConfiguration(
        evaluate_every_n_epochs=3,
        epoch_termination_conditions=[
            ScoreImprovementEpochTerminationCondition(1),
            MaxEpochsTerminationCondition(30)],
        score_calculator=DataSetLossCalculator(
            ArrayDataSetIterator(ds.features, ds.labels, batch=16)))
    result = EarlyStoppingTrainer(cfg, net, it).fit()
    # evaluations at epochs 0,3,6: patience 1 -> stop on the 3rd eval
    # (epoch 6), NOT at epoch 1 from stale-score checks
    assert result.total_epochs == 7


def test_checkpoint_numbering_survives_retention_restart(tmp_path):
    net = MultiLayerNetwork(_conf())
    net.init()
    ds = _data()
    cl = CheckpointListener(str(tmp_path), save_every_n_iterations=1,
                            keep_last=2)
    net.set_listeners(cl)
    for _ in range(5):
        net.fit_batch(ds)
    # restart a new listener in the same directory
    cl2 = CheckpointListener(str(tmp_path), save_every_n_iterations=1,
                             keep_last=2)
    net.set_listeners(cl2)
    net.fit_batch(ds)
    nums = [c.number for c in cl2.list_checkpoints()]
    assert len(nums) == len(set(nums))  # no duplicate numbers
    assert max(nums) == 5


def test_graph_model_savers(tmp_path):
    from deeplearning4j_tpu.conf.graph import ComputationGraphConfiguration
    from deeplearning4j_tpu.nn.graph import ComputationGraph

    g = (NeuralNetConfiguration.builder()
         .seed(1).updater(Adam(1e-2))
         .graph_builder()
         .add_inputs("in")
         .set_input_types(InputType.feed_forward(4)))
    g.add_layer("d", DenseLayer(n_out=8, activation=Activation.TANH), "in")
    g.add_layer("out", OutputLayer(n_out=3, activation=Activation.SOFTMAX,
                                   loss_fn=LossMCXENT()), "d")
    g.set_outputs("out")
    net = ComputationGraph(g.build()).init()
    ds = _data()
    it = ArrayDataSetIterator(ds.features, ds.labels, batch=16)
    cfg = EarlyStoppingConfiguration(
        epoch_termination_conditions=[MaxEpochsTerminationCondition(2)],
        score_calculator=DataSetLossCalculator(
            ArrayDataSetIterator(ds.features, ds.labels, batch=16)),
        model_saver=LocalFileModelSaver(str(tmp_path)))
    result = EarlyStoppingTrainer(cfg, net, it).fit()
    best = result.get_best_model()
    assert type(best).__name__ == "ComputationGraph"


def test_orbax_async_checkpointing(tmp_path):
    from deeplearning4j_tpu.optimize.checkpoint import AsyncCheckpointListener

    net = MultiLayerNetwork(_conf())
    net.init()
    cl = AsyncCheckpointListener(str(tmp_path / "orbax"),
                                 save_every_n_iterations=2, max_to_keep=2)
    net.set_listeners(cl)
    ds = _data()
    for _ in range(6):
        net.fit_batch(ds)
    cl.wait()
    assert len(cl.all_steps()) == 2  # retention kept the last 2
    restored = cl.restore_latest()
    np.testing.assert_allclose(restored.params_flat(), net.params_flat(),
                               rtol=1e-6)
    # counters restored exactly (epoch-keyed schedules depend on this)
    assert restored.iteration == net.iteration
    assert restored.epoch == net.epoch
    # exact resume: training continues from the restored updater state
    restored.fit_batch(ds)


def test_ui_server_live_http(tmp_path):
    import json
    import urllib.request

    net = MultiLayerNetwork(_conf())
    net.init()
    storage = InMemoryStatsStorage()
    net.set_listeners(StatsListener(storage, frequency=1))
    ds = _data()
    for _ in range(3):
        net.fit_batch(ds)
    ui = UIServer.get_instance().attach(storage)
    port = ui.start(port=0)  # free port
    try:
        html = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/", timeout=10).read().decode()
        assert "Model score" in html and "<svg" in html
        assert "http-equiv='refresh'" in html
        stats = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/train/stats.json", timeout=10).read())
        assert len(stats) == 3 and "score" in stats[0]
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            urllib.request.urlopen(f"http://127.0.0.1:{port}/nope",
                                   timeout=10)
        assert exc_info.value.code == 404
    finally:
        ui.stop()
        UIServer.get_instance().detach(storage)


# --------------------------------------------------------------------------
# legacy full-batch solvers (LineGradientDescent / ConjugateGradient / LBFGS)
# --------------------------------------------------------------------------

def _solver_net_and_data(seed=7):
    rng = np.random.default_rng(seed)
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Sgd(0.1))
            .list()
            .layer(DenseLayer(n_out=8, activation=Activation.TANH))
            .layer(OutputLayer(n_out=1, activation=Activation.IDENTITY,
                               loss_fn=LossMSE()))
            .set_input_type(InputType.feed_forward(3)).build())
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork as MLN
    net = MLN(conf).init()
    x = rng.normal(size=(64, 3)).astype(np.float32)
    y = (x @ np.asarray([[1.0], [-2.0], [0.5]], np.float32)
         + 0.1 * rng.normal(size=(64, 1)).astype(np.float32))
    from deeplearning4j_tpu.datasets.dataset import DataSet
    return net, DataSet(x, y)


@pytest.mark.parametrize("solver_cls", ["LineGradientDescent",
                                        "ConjugateGradient", "LBFGS"])
def test_legacy_solver_minimizes(solver_cls):
    from deeplearning4j_tpu.optimize import legacy

    net, ds = _solver_net_and_data()
    before = net.score(ds)
    solver = getattr(legacy, solver_cls)(max_iterations=60)
    final = solver.optimize(net, ds)
    after = net.score(ds)
    assert after < before * 0.2
    assert final == pytest.approx(after, rel=0.05)


def test_lbfgs_beats_line_gd_iteration_for_iteration():
    from deeplearning4j_tpu.optimize.legacy import LBFGS, LineGradientDescent

    net1, ds = _solver_net_and_data(seed=11)
    net2, _ = _solver_net_and_data(seed=11)
    LineGradientDescent(max_iterations=15).optimize(net1, ds)
    LBFGS(max_iterations=15).optimize(net2, ds)
    assert net2.score(ds) <= net1.score(ds) * 1.05  # curvature should help


def test_legacy_solver_on_graph():
    from deeplearning4j_tpu.optimize.legacy import LBFGS

    rng = np.random.default_rng(5)
    from deeplearning4j_tpu.conf.multilayer import NeuralNetConfiguration as NNC
    b = NNC.builder().seed(5).updater(Sgd(0.1)).graph_builder()
    b.add_inputs("in")
    b.add_layer("h", DenseLayer(n_out=6, activation=Activation.TANH), "in")
    b.add_layer("out", OutputLayer(n_out=1, activation=Activation.IDENTITY,
                                   loss_fn=LossMSE()), "h")
    b.set_outputs("out")
    b.set_input_types(InputType.feed_forward(2))
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    net = ComputationGraph(b.build()).init()
    from deeplearning4j_tpu.datasets.dataset import DataSet
    x = rng.normal(size=(32, 2)).astype(np.float32)
    y = (x[:, :1] * x[:, 1:] + 0.5).astype(np.float32)
    ds = DataSet(x, y)
    before = net.score(ds)
    LBFGS(max_iterations=80).optimize(net, ds)
    assert net.score(ds) < before * 0.5


def test_remote_ui_stats_router():
    from deeplearning4j_tpu.ui import RemoteUIStatsStorageRouter

    ui = UIServer.get_instance()
    port = ui.start(port=0)
    try:
        router = RemoteUIStatsStorageRouter(f"http://127.0.0.1:{port}")
        net = MultiLayerNetwork(_conf())
        net.init()
        net.set_listeners(StatsListener(router, frequency=1))
        ds = _data()
        for _ in range(3):
            net.fit_batch(ds)
        assert router.flush()  # delivery is async
        # the server's auto-attached remote storage received the records
        assert len(ui.remote_storage().records()) == 3
        assert "score" in ui.remote_storage().records()[0]
        # and the dashboard renders them
        assert "Model score" in ui.render_html()

        # a non-dict body is rejected (it would poison every later render)
        import urllib.error
        import urllib.request
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/train/post", data=b"42",
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 400
        assert "Model score" in ui.render_html()  # still renders
    finally:
        ui.stop()
        ui.detach(ui.remote_storage())
        ui._remote_storage = None

    # a dashboard outage must not crash the training loop
    router2 = RemoteUIStatsStorageRouter(f"http://127.0.0.1:{port}",
                                         retries=1, timeout=0.5)
    net2 = MultiLayerNetwork(_conf())
    net2.init()
    net2.set_listeners(StatsListener(router2, frequency=1))
    net2.fit_batch(_data())          # server is down: no exception
    router2.flush(timeout=10.0)
    assert router2.dropped == 1


# --------------------------------------------------------------------------
# round 3: per-layer histograms (reference dashboard histogram panels)
# --------------------------------------------------------------------------

def test_stats_listener_histograms_and_panels(tmp_path):
    import time as _time

    from deeplearning4j_tpu.ui.stats import _histogram

    ds = _data(32)
    net = MultiLayerNetwork(_conf()).init()
    storage = InMemoryStatsStorage()
    net.set_listeners(StatsListener(storage, frequency=1, histograms=True,
                                    histogram_bins=16, sample_ds=ds))
    net.fit(ArrayDataSetIterator(ds.features, ds.labels, 16), epochs=2)

    recs = storage.records()
    assert recs
    last = recs[-1]
    for key in ("param_histograms", "update_histograms",
                "activation_histograms", "gradient_histograms"):
        assert key in last, key
        assert last[key], key
        for layer, h in last[key].items():
            assert sum(h["counts"]) > 0 and h["min"] <= h["max"], (key,
                                                                   layer)
            assert len(h["counts"]) == 16
    # param histogram counts cover every parameter scalar of the layer
    n0 = sum(np.asarray(v).size for v in net.params["0"].values())
    assert sum(last["param_histograms"]["0"]["counts"]) == n0
    # activation histograms keyed per layer (3 layers)
    assert set(last["activation_histograms"]) == {"0", "1", "2"}

    # dashboard renders the histogram panels
    ui = UIServer().attach(storage)
    html_text = ui.render_html()
    for title in ("Parameter histograms", "Update histograms",
                  "Activation histograms", "Gradient histograms"):
        assert title in html_text

    # degenerate input: constant tensor still histograms (min==max)
    h = _histogram(np.zeros(10), 8)
    assert sum(h["counts"]) == 10

    # measured overhead: a histogram collection must stay well under the
    # cost of a handful of training steps (here: just bounded sanity)
    t0 = _time.monotonic()
    net.fit_batch(ds)
    assert _time.monotonic() - t0 < 30.0


def test_feed_forward_returns_per_layer_activations():
    ds = _data(8)
    net = MultiLayerNetwork(_conf()).init()
    acts = net.feed_forward(ds.features)
    assert len(acts) == 3
    assert np.asarray(acts[0]).shape == (8, 8)
    assert np.asarray(acts[1]).shape == (8, 6)
    assert np.asarray(acts[2]).shape == (8, 3)
    np.testing.assert_allclose(np.asarray(acts[2]),
                               np.asarray(net.output(ds.features)),
                               atol=1e-6)


def test_graph_feed_forward_and_histograms():
    from deeplearning4j_tpu.nn.graph import ComputationGraph

    conf = (NeuralNetConfiguration.builder()
            .seed(3).updater(Adam(1e-2))
            .graph_builder()
            .add_inputs("in")
            .set_input_types(InputType.feed_forward(4))
            .add_layer("h", DenseLayer(n_out=8, activation=Activation.TANH),
                       "in")
            .add_layer("out", OutputLayer(n_out=3,
                                          activation=Activation.SOFTMAX,
                                          loss_fn=LossMCXENT()), "h")
            .set_outputs("out")
            .build())
    net = ComputationGraph(conf).init()
    ds = _data(16)
    acts = net.feed_forward(ds.features)
    assert set(acts) == {"h", "out"}
    assert np.asarray(acts["h"]).shape == (16, 8)
    np.testing.assert_allclose(np.asarray(acts["out"]),
                               np.asarray(net.output(ds.features)),
                               atol=1e-6)

    storage = InMemoryStatsStorage()
    net.set_listeners(StatsListener(storage, frequency=1, histograms=True,
                                    sample_ds=ds))
    net.fit_batch(ds)
    net.fit_batch(ds)
    last = storage.records()[-1]
    assert set(last["activation_histograms"]) == {"h", "out"}
    assert last["gradient_histograms"]
