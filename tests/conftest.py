"""Test config: force CPU jax with 8 virtual devices so multi-chip sharding
logic is exercised without TPU hardware (the driver separately dry-runs the
multi-chip path; bench.py runs on the real chip).

Mirrors the reference's backend-parametrized test strategy (SURVEY.md §4):
the CPU platform is the correctness oracle; TPU runs the same suite with
tolerance tiers.

IMPORTANT environment quirk: this machine's ``sitecustomize.py`` registers
the experimental ``axon`` TPU PJRT plugin in EVERY interpreter (and the env
pins ``JAX_PLATFORMS=axon``), importing jax at interpreter boot — before this
conftest runs. Setting env vars here is therefore too late; we must update
the already-imported jax config and deregister the axon backend factory, or
every test run contends for (and can hang on) the single real TPU tunnel.
"""

import os

# For any subprocesses tests may spawn.
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402  (already imported by sitecustomize anyway)

jax.config.update("jax_platforms", "cpu")
try:
    from jax._src import xla_bridge as _xb

    _xb._backend_factories.pop("axon", None)
except Exception:  # pragma: no cover - jax internals may move
    pass

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(12345)
