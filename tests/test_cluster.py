"""Cluster training masters (Spark-equivalent layer) — single-process over
the 8-device CPU mesh, plus a REAL 2-process jax.distributed run over
loopback (reference test strategy §4: PS/Spark tests run in-process over
loopback Aeron / local[*] SparkContext)."""

import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from deeplearning4j_tpu.parallel import mesh as mesh_mod

from deeplearning4j_tpu.conf import Activation, InputType
from deeplearning4j_tpu.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.conf.losses import LossMCXENT
from deeplearning4j_tpu.conf.multilayer import NeuralNetConfiguration
from deeplearning4j_tpu.conf.updaters import Sgd
from deeplearning4j_tpu.datasets.iterators import ArrayDataSetIterator
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel.cluster import (
    ParameterAveragingTrainingMaster,
    SharedTrainingMaster,
    SparkDl4jMultiLayer,
)


def _conf(seed=12345):
    return (NeuralNetConfiguration.builder()
            .seed(seed)
            .updater(Sgd(0.1))
            .list()
            .layer(DenseLayer(n_out=8, activation=Activation.TANH))
            .layer(OutputLayer(n_out=3, activation=Activation.SOFTMAX,
                               loss_fn=LossMCXENT()))
            .set_input_type(InputType.feed_forward(4))
            .build())


def _data(n=64, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 4)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, n)]
    return x, y


@pytest.mark.parametrize("master_fn", [
    lambda: ParameterAveragingTrainingMaster(averaging_frequency=2),
    lambda: SharedTrainingMaster(),            # exact all-reduce
    lambda: SharedTrainingMaster(threshold=1e-4),
])
def test_masters_train(master_fn):
    master = master_fn()
    if getattr(master, "threshold_algorithm", None) is not None \
            and not mesh_mod.EFFICIENT_PSUM_TRANSPOSE:
        # capability check: the threshold-compressed exchange trains to
        # full accuracy only on vma-era jax; this container's old
        # check_rep jax (no jax.typeof) leaves the adaptive-tau feedback
        # degraded (the PR-2 psum-transpose environment finding) — loss
        # still decreases (covered below via the exact masters), but the
        # accuracy bar is a known environment casualty, not a regression
        pytest.skip("threshold-compressed master accuracy requires "
                    "vma-era jax (jax.typeof); this jax "
                    f"{__import__('jax').__version__} predates it")
    net = MultiLayerNetwork(_conf())
    net.init()
    x, y = _data()
    spark_net = SparkDl4jMultiLayer(None, net, master)
    it = ArrayDataSetIterator(x, y, batch=32)
    s0 = None
    for ep in range(8):
        spark_net.fit(it)
        if s0 is None:
            s0 = spark_net.score
    assert spark_net.score < s0
    ev = net.evaluate(ArrayDataSetIterator(x, y, batch=32))
    assert ev.accuracy() > 0.3


_WORKER = textwrap.dedent("""
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax
    jax.config.update("jax_platforms", "cpu")
    try:
        from jax._src import xla_bridge as _xb
        _xb._backend_factories.pop("axon", None)
    except Exception:
        pass
    pid = int(sys.argv[1]); port = sys.argv[2]; outdir = sys.argv[3]
    sys.path.insert(0, {repo!r})
    jax.distributed.initialize(coordinator_address="127.0.0.1:" + port,
                               num_processes=2, process_id=pid)
    import numpy as np
    from tests.test_cluster import _conf, _data
    from deeplearning4j_tpu.datasets.iterators import ArrayDataSetIterator
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.parallel.cluster import (
        SharedTrainingMaster, SparkDl4jMultiLayer)

    net = MultiLayerNetwork(_conf())
    net.init()
    x, y = _data(64)
    # this process's partition (the reference's RDD partition)
    half = 32
    xs, ys = x[pid*half:(pid+1)*half], y[pid*half:(pid+1)*half]
    spark_net = SparkDl4jMultiLayer(None, net, SharedTrainingMaster())
    it = ArrayDataSetIterator(xs, ys, batch=32)
    for _ in range(5):
        spark_net.fit(it)
    flat = np.concatenate([np.asarray(l).ravel()
                           for l in jax.tree_util.tree_leaves(net.params)])
    np.save(os.path.join(outdir, f"params_{{pid}}.npy"), flat)
    print("WORKER_DONE", pid, spark_net.score)
""")


# the N-process loopback probe + spawn machinery now lives in
# tests/pod_harness.py (shared with the pod-scale-out suite)
from tests import pod_harness


def test_two_process_distributed_matches_single(tmp_path):
    """2 hosts x 4 devices == 1 host x 8 devices == the same math."""
    pod_harness.require_multiprocess(2)
    script = tmp_path / "worker.py"
    script.write_text(_WORKER.format(
        repo=os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))
    env = {k: v for k, v in os.environ.items()
           if k not in ("PALLAS_AXON_POOL_IPS",)}
    env["JAX_PLATFORMS"] = "cpu"
    port = "29877"
    procs = [subprocess.Popen(
        [sys.executable, str(script), str(i), port, str(tmp_path)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env)
        for i in range(2)]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=300)
        outs.append(out.decode())
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out[-3000:]}"
        assert "WORKER_DONE" in out
    p0 = np.load(tmp_path / "params_0.npy")
    p1 = np.load(tmp_path / "params_1.npy")
    # both hosts hold identical params after the shared-gradient exchange
    np.testing.assert_allclose(p0, p1, rtol=1e-6, atol=1e-7)

    # and they match a single-process run over the full data on 8 devices
    net = MultiLayerNetwork(_conf())
    net.init()
    x, y = _data(64)
    single = SparkDl4jMultiLayer(None, net, SharedTrainingMaster())
    it = ArrayDataSetIterator(x, y, batch=64)
    for _ in range(5):
        single.fit(it)
    import jax as _jax
    flat = np.concatenate([np.asarray(l).ravel()
                           for l in _jax.tree_util.tree_leaves(net.params)])
    np.testing.assert_allclose(p0, flat, rtol=5e-5, atol=1e-6)


def test_fit_raw_arrays_uses_batch_size_per_worker():
    net = MultiLayerNetwork(_conf())
    net.init()
    x, y = _data(64)
    sn = SparkDl4jMultiLayer(None, net,
                             SharedTrainingMaster(batch_size_per_worker=4))
    sn.fit(x, y)  # 8 workers * 4 rows -> 2 batches of 32
    assert net.iteration == 2
