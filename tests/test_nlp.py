"""NLP: tokenizers, vocab, Word2Vec/ParagraphVectors/GloVe semantics,
serializer round-trips (reference: deeplearning4j-nlp Word2VecTests etc.)."""

import numpy as np
import pytest

from deeplearning4j_tpu.nlp import (
    CommonPreprocessor,
    DefaultTokenizerFactory,
    Glove,
    NGramTokenizerFactory,
    ParagraphVectors,
    VocabCache,
    Word2Vec,
    WordVectorSerializer,
)


def _corpus(n=300, seed=0):
    """Two topic clusters: {cat,dog,pet} co-occur; {car,road,drive}
    co-occur. Clear similarity structure for a tiny embedding."""
    rng = np.random.default_rng(seed)
    animals = ["cat", "dog", "pet", "fur", "tail"]
    cars = ["car", "road", "drive", "wheel", "engine"]
    sents = []
    for _ in range(n):
        pool = animals if rng.random() < 0.5 else cars
        sents.append(" ".join(rng.choice(pool, size=6)))
    return sents


def test_tokenizers():
    t = DefaultTokenizerFactory()
    t.set_token_pre_processor(CommonPreprocessor())
    assert t.tokenize("Hello, World! 123 foo") == ["hello", "world", "foo"]
    ng = NGramTokenizerFactory(1, 2)
    toks = ng.tokenize("a b c")
    assert "a b" in toks and "b c" in toks and "a" in toks


def test_vocab_cache():
    v = VocabCache.build(iter([["a", "b", "a"], ["a", "c"]]),
                         min_word_frequency=2)
    assert len(v) == 1 and "a" in v and v.count_of("a") == 3
    v2 = VocabCache.build(iter([["a", "b", "a"], ["a", "c"]]))
    assert v2.index_of("a") == 0  # most frequent first


def test_word2vec_learns_topics():
    w2v = Word2Vec(layer_size=24, window_size=3, min_word_frequency=2,
                   negative=5, epochs=3, batch_size=256, seed=1)
    w2v.fit(_corpus())
    assert w2v.has_word("cat") and w2v.has_word("car")
    # within-topic similarity beats cross-topic
    assert w2v.similarity("cat", "dog") > w2v.similarity("cat", "road")
    assert w2v.similarity("car", "drive") > w2v.similarity("car", "fur")
    near = w2v.words_nearest("cat", top_n=4)
    assert set(near) & {"dog", "pet", "fur", "tail"}


def test_word2vec_cbow_runs():
    w2v = Word2Vec(layer_size=16, min_word_frequency=2, epochs=1,
                   batch_size=128, elements_learning_algorithm="CBOW")
    w2v.fit(_corpus(100))
    assert w2v.get_word_vector("cat").shape == (16,)


def test_word2vec_rejects_bad_algorithm():
    with pytest.raises(ValueError):
        Word2Vec(elements_learning_algorithm="HierarchicalSoftmax")


def test_serializer_text_roundtrip(tmp_path):
    w2v = Word2Vec(layer_size=8, min_word_frequency=2, epochs=1,
                   batch_size=128).fit(_corpus(80))
    p = str(tmp_path / "vecs.txt")
    WordVectorSerializer.write_word_vectors(w2v, p)
    cache, mat = WordVectorSerializer.read_word_vectors(p)
    assert len(cache) == len(w2v.vocab)
    i = cache.index_of("cat")
    np.testing.assert_allclose(mat[i], w2v.get_word_vector("cat"),
                               atol=1e-5)


def test_serializer_model_roundtrip(tmp_path):
    w2v = Word2Vec(layer_size=8, min_word_frequency=2, epochs=1,
                   batch_size=128).fit(_corpus(80))
    p = str(tmp_path / "model.zip")
    WordVectorSerializer.write_word2vec_model(w2v, p)
    back = WordVectorSerializer.read_word2vec_model(p)
    assert back.vocab.count_of("cat") == w2v.vocab.count_of("cat")
    np.testing.assert_allclose(back.get_word_vector("dog"),
                               w2v.get_word_vector("dog"))
    assert back.similarity("cat", "dog") == pytest.approx(
        w2v.similarity("cat", "dog"), abs=1e-6)


def test_paragraph_vectors():
    docs = (["the cat sat with the dog and pet the fur"] * 6
            + ["the car took the road to drive the wheel"] * 6)
    labels = [f"animal_{i}" for i in range(6)] + [f"car_{i}" for i in range(6)]
    pv = ParagraphVectors(layer_size=16, min_word_frequency=1, epochs=8,
                          batch_size=64, negative=3, seed=3)
    pv.fit(docs, labels)
    assert pv.get_paragraph_vector("animal_0").shape == (16,)
    v = pv.infer_vector("cat dog pet")
    assert v.shape == (16,) and np.isfinite(v).all()
    near = pv.nearest_labels("cat dog pet fur", top_n=3)
    assert any(l.startswith("animal") for l in near)


def test_glove_learns_topics():
    g = Glove(layer_size=16, window_size=3, min_word_frequency=2,
              epochs=60, learning_rate=0.05, seed=2)
    g.fit(_corpus(200))
    assert g.similarity("cat", "dog") > g.similarity("cat", "road")


def test_glove_empty_corpus_raises():
    with pytest.raises(ValueError):
        Glove(min_word_frequency=2).fit(["one-word"])


# --- round 2: true CBOW + hierarchical softmax -----------------------------

def _topic_check(w2v):
    """Topic words cluster: in-topic similarity beats cross-topic."""
    sim_in = w2v.similarity("cat", "dog")
    sim_cross = w2v.similarity("cat", "car")
    assert sim_in > sim_cross, (sim_in, sim_cross)


def test_word2vec_cbow_learns_topics():
    w2v = Word2Vec(layer_size=16, window_size=3, min_word_frequency=1,
                   epochs=8, seed=7, batch_size=256,
                   elements_learning_algorithm="CBOW")
    w2v.fit(_corpus(400))
    _topic_check(w2v)
    # CBOW context example assembly produced the masked window shape
    assert w2v.syn1.shape == (len(w2v.vocab), 16)


def test_word2vec_hierarchical_softmax_skipgram():
    w2v = Word2Vec(layer_size=16, window_size=3, min_word_frequency=1,
                   epochs=8, seed=7, batch_size=256, negative=0)
    assert w2v.hs  # negative=0 -> reference default HS
    w2v.fit(_corpus(400))
    _topic_check(w2v)
    # HS output table holds the V-1 Huffman inner nodes
    assert w2v.syn1.shape == (len(w2v.vocab) - 1, 16)


def test_word2vec_hierarchical_softmax_cbow():
    w2v = Word2Vec(layer_size=16, window_size=3, min_word_frequency=1,
                   epochs=8, seed=3, batch_size=256,
                   elements_learning_algorithm="CBOW",
                   use_hierarchic_softmax=True)
    w2v.fit(_corpus(400))
    _topic_check(w2v)


def test_huffman_codes_properties():
    from deeplearning4j_tpu.nlp.word2vec import build_huffman

    counts = [100, 50, 20, 10, 5, 2, 1]
    C, P, M = build_huffman(counts)
    V = len(counts)
    lengths = M.sum(1).astype(int)
    # prefix-free: no code is a prefix of another
    codes = ["".join(str(int(b)) for b in C[i, :lengths[i]])
             for i in range(V)]
    for i in range(V):
        for j in range(V):
            if i != j:
                assert not codes[j].startswith(codes[i])
    # frequent words get codes no longer than rarer ones
    assert lengths[0] == min(lengths)
    assert lengths[-1] == max(lengths)
    # points index the V-1 inner nodes
    assert P.max() <= V - 2 and P.min() >= 0


def test_word2vec_zero_negative_without_hs_rejected():
    with pytest.raises(ValueError, match="negative"):
        Word2Vec(negative=0, use_hierarchic_softmax=False)
