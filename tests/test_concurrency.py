"""Concurrency stress: async prefetch under slow/bursty producers and
concurrent ParallelInference callers (reference: the accumulator's dedicated
multithreaded stress tests — SURVEY.md §5.2 notes races are otherwise
handled by construction)."""

import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu.conf import Activation, InputType
from deeplearning4j_tpu.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.conf.losses import LossMCXENT
from deeplearning4j_tpu.conf.multilayer import NeuralNetConfiguration
from deeplearning4j_tpu.conf.updaters import Sgd
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import (
    DataSetIterator,
    ListDataSetIterator,
)
from deeplearning4j_tpu.datasets.prefetch import AsyncDataSetIterator


class SlowIterator(DataSetIterator):
    """Bursty producer with per-batch latency."""

    def __init__(self, batches, delay=0.002):
        self._batches = batches
        self._delay = delay

    def reset(self):
        pass

    def batch_size(self):
        return self._batches[0].num_examples()

    def __iter__(self):
        for ds in self._batches:
            time.sleep(self._delay)
            yield ds


def _batches(n, rng, rows=8):
    return [DataSet(rng.normal(size=(rows, 4)).astype(np.float32),
                    np.eye(2, dtype=np.float32)[
                        rng.integers(0, 2, rows)])
            for _ in range(n)]


def test_async_iterator_preserves_order_and_count(rng):
    batches = _batches(40, rng)
    it = AsyncDataSetIterator(SlowIterator(batches), queue_size=3)
    for round_ in range(3):  # reuse across epochs (producer restart)
        seen = list(it)
        assert len(seen) == 40
        for got, want in zip(seen, batches):
            np.testing.assert_array_equal(got.features, want.features)


def test_async_iterator_propagates_producer_error(rng):
    class Exploding(SlowIterator):
        def __iter__(self):
            yield self._batches[0]
            raise RuntimeError("etl failure")

    it = AsyncDataSetIterator(Exploding(_batches(2, rng)), queue_size=2)
    with pytest.raises(RuntimeError, match="etl failure"):
        list(it)


def test_async_iterator_early_break_then_reuse(rng):
    batches = _batches(20, rng)
    it = AsyncDataSetIterator(SlowIterator(batches), queue_size=4)
    for i, _ in enumerate(it):
        if i == 3:
            break  # consumer abandons mid-epoch
    seen = list(it)  # fresh epoch restarts the producer cleanly
    assert len(seen) == 20


def test_parallel_inference_concurrent_callers(rng):
    from deeplearning4j_tpu.parallel import ParallelInference

    conf = (NeuralNetConfiguration.builder()
            .seed(1).updater(Sgd(0.1)).list()
            .layer(DenseLayer(n_out=8, activation=Activation.TANH))
            .layer(OutputLayer(n_out=2, activation=Activation.SOFTMAX,
                               loss_fn=LossMCXENT()))
            .set_input_type(InputType.feed_forward(4))
            .build())
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    net = MultiLayerNetwork(conf)
    net.init()
    pi = ParallelInference(net)
    rng_local = np.random.default_rng(0)
    xs = [rng_local.normal(size=(16, 4)).astype(np.float32)
          for _ in range(8)]
    expected = [np.asarray(net.output(x)) for x in xs]

    results = [None] * 8
    errors = []

    def worker(i):
        try:
            for _ in range(5):
                results[i] = np.asarray(pi.output(xs[i]))
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    for got, want in zip(results, expected):
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_training_while_prefetching(rng):
    """fit() over an async iterator with a slow producer: all batches
    consumed, loss finite, no deadlock (bounded dispatch + bounded queue
    interacting)."""
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (NeuralNetConfiguration.builder()
            .seed(1).updater(Sgd(0.1)).list()
            .layer(DenseLayer(n_out=8, activation=Activation.TANH))
            .layer(OutputLayer(n_out=2, activation=Activation.SOFTMAX,
                               loss_fn=LossMCXENT()))
            .set_input_type(InputType.feed_forward(4))
            .build())
    net = MultiLayerNetwork(conf)
    net.init()
    batches = _batches(30, rng)
    it = AsyncDataSetIterator(SlowIterator(batches, delay=0.001),
                              queue_size=2)
    net.fit(it, epochs=2)
    assert net.iteration == 60
    assert np.isfinite(net.score_value)
