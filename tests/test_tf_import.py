"""TF frozen-graph import (reference ``TFGraphTestAllSameDiff`` conformance
suite, SURVEY.md §4 — goldens are numpy-math oracles since no TF exists in
this env; graphs are built with the vendored wire-compatible protos)."""

import math as _math

import numpy as np
import pytest

from deeplearning4j_tpu.imports.protos import tf_graph_pb2 as pb
from deeplearning4j_tpu.imports.tf import (
    TFGraphMapper,
    UnsupportedTFOpException,
)


def _const(g, name, arr):
    arr = np.asarray(arr)
    n = g.node.add()
    n.name = name
    n.op = "Const"
    dt = {np.dtype(np.float32): pb.DT_FLOAT,
          np.dtype(np.int32): pb.DT_INT32}[arr.dtype]
    n.attr["dtype"].type = dt
    t = n.attr["value"].tensor
    t.dtype = dt
    for d in arr.shape:
        t.tensor_shape.dim.add().size = d
    t.tensor_content = arr.tobytes()
    return n


def _node(g, name, op, *inputs, **attrs):
    n = g.node.add()
    n.name = name
    n.op = op
    n.input.extend(inputs)
    for k, v in attrs.items():
        if isinstance(v, bool):
            n.attr[k].b = v
        elif isinstance(v, bytes):
            n.attr[k].s = v
        elif isinstance(v, int):
            n.attr[k].i = v
        elif isinstance(v, float):
            n.attr[k].f = v
        elif isinstance(v, (list, tuple)):
            n.attr[k].list.i.extend(v)
    return n


def _placeholder(g, name, shape):
    n = g.node.add()
    n.name = name
    n.op = "Placeholder"
    n.attr["dtype"].type = pb.DT_FLOAT
    sh = n.attr["shape"].shape
    for d in shape:
        sh.dim.add().size = d if d else -1
    return n


def test_import_mlp(rng):
    w1 = rng.normal(size=(4, 8)).astype(np.float32)
    b1 = rng.normal(size=(8,)).astype(np.float32)
    w2 = rng.normal(size=(8, 3)).astype(np.float32)
    b2 = rng.normal(size=(3,)).astype(np.float32)
    g = pb.GraphDef()
    _placeholder(g, "input", (0, 4))
    _const(g, "w1", w1)
    _const(g, "b1", b1)
    _const(g, "w2", w2)
    _const(g, "b2", b2)
    _node(g, "mm1", "MatMul", "input", "w1",
          transpose_a=False, transpose_b=False)
    _node(g, "add1", "BiasAdd", "mm1", "b1")
    _node(g, "relu1", "Relu", "add1")
    _node(g, "mm2", "MatMul", "relu1", "w2")
    _node(g, "logits", "BiasAdd", "mm2", "b2")
    _node(g, "probs", "Softmax", "logits")

    sd = TFGraphMapper.import_graph(g.SerializeToString())
    x = rng.normal(size=(5, 4)).astype(np.float32)
    out = sd.output({"input": x}, "probs")["probs"]
    h = np.maximum(x @ w1 + b1, 0.0)
    logits = h @ w2 + b2
    want = np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
    np.testing.assert_allclose(np.asarray(out), want, rtol=1e-5, atol=1e-6)


def test_import_cnn(rng):
    k = rng.normal(size=(3, 3, 2, 4), scale=0.3).astype(np.float32)
    g = pb.GraphDef()
    _placeholder(g, "img", (0, 8, 8, 2))
    _const(g, "kernel", k)
    _node(g, "conv", "Conv2D", "img", "kernel",
          strides=[1, 1, 1, 1], padding=b"SAME")
    _node(g, "relu", "Relu", "conv")
    _node(g, "pool", "MaxPool", "relu",
          ksize=[1, 2, 2, 1], strides=[1, 2, 2, 1], padding=b"VALID")
    _const(g, "axes", np.asarray([1, 2], np.int32))
    _node(g, "gap", "Mean", "pool", "axes", keep_dims=False)

    sd = TFGraphMapper.import_graph(g.SerializeToString())
    x = rng.normal(size=(2, 8, 8, 2)).astype(np.float32)
    out = np.asarray(sd.output({"img": x}, "gap")["gap"])
    assert out.shape == (2, 4)
    # oracle via jax reference conv
    import jax

    ref = jax.lax.conv_general_dilated(
        x, k, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"))
    ref = np.maximum(np.asarray(ref), 0)
    ref = ref.reshape(2, 4, 2, 4, 2, 4)[:, :, :, :, :, :]
    pooled = ref.reshape(2, 4, 2, 4, 2, 4).max(axis=(2, 4))
    np.testing.assert_allclose(out, pooled.mean(axis=(1, 2)),
                               rtol=1e-4, atol=1e-5)


def test_import_reshape_concat_reduce(rng):
    g = pb.GraphDef()
    _placeholder(g, "a", (0, 4))
    _placeholder(g, "b", (0, 4))
    _const(g, "shape", np.asarray([-1, 2, 2], np.int32))
    _node(g, "r", "Reshape", "a", "shape")
    _const(g, "ax", np.asarray(1, np.int32))
    _node(g, "cat", "ConcatV2", "a", "b", "ax")
    _const(g, "rax", np.asarray([1], np.int32))
    _node(g, "m", "Mean", "cat", "rax", keep_dims=True)
    sd = TFGraphMapper.import_graph(g.SerializeToString())
    a = rng.normal(size=(3, 4)).astype(np.float32)
    b = rng.normal(size=(3, 4)).astype(np.float32)
    outs = sd.output({"a": a, "b": b}, "r", "cat", "m")
    assert np.asarray(outs["r"]).shape == (3, 2, 2)
    np.testing.assert_allclose(np.asarray(outs["cat"]),
                               np.concatenate([a, b], 1), rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(outs["m"]),
        np.concatenate([a, b], 1).mean(1, keepdims=True), rtol=1e-5)


def test_import_fused_batchnorm(rng):
    g = pb.GraphDef()
    _placeholder(g, "x", (0, 4, 4, 3))
    _const(g, "gamma", np.asarray([1.0, 2.0, 0.5], np.float32))
    _const(g, "beta", np.asarray([0.1, -0.1, 0.0], np.float32))
    _const(g, "mean", np.asarray([0.5, -0.5, 0.0], np.float32))
    _const(g, "var", np.asarray([1.0, 4.0, 0.25], np.float32))
    _node(g, "bn", "FusedBatchNormV3", "x", "gamma", "beta", "mean", "var",
          epsilon=1e-3, is_training=False)
    sd = TFGraphMapper.import_graph(g.SerializeToString())
    x = rng.normal(size=(2, 4, 4, 3)).astype(np.float32)
    out = np.asarray(sd.output({"x": x}, "bn")["bn"])
    want = ((x - [0.5, -0.5, 0.0]) / np.sqrt(np.asarray([1.0, 4.0, 0.25])
                                             + 1e-3)
            * [1.0, 2.0, 0.5] + [0.1, -0.1, 0.0])
    np.testing.assert_allclose(out, want, rtol=1e-4, atol=1e-5)


def test_identity_and_control_inputs(rng):
    g = pb.GraphDef()
    _placeholder(g, "x", (0, 3))
    _node(g, "id", "Identity", "x")
    _node(g, "sq", "Square", "id", "^x")  # control input ignored
    sd = TFGraphMapper.import_graph(g.SerializeToString())
    x = rng.normal(size=(2, 3)).astype(np.float32)
    np.testing.assert_allclose(np.asarray(sd.output({"x": x}, "sq")["sq"]),
                               x * x, rtol=1e-6)


def test_unsupported_op_raises():
    g = pb.GraphDef()
    _placeholder(g, "x", (0, 3))
    _node(g, "w", "WeirdCustomOp", "x")
    with pytest.raises(UnsupportedTFOpException) as e:
        TFGraphMapper.import_graph(g.SerializeToString())
    assert "WeirdCustomOp" in str(e.value)


def test_dynamic_reshape_rejected(rng):
    g = pb.GraphDef()
    _placeholder(g, "x", (0, 4))
    _placeholder(g, "shape", (2,))
    _node(g, "r", "Reshape", "x", "shape")
    with pytest.raises(UnsupportedTFOpException):
        TFGraphMapper.import_graph(g.SerializeToString())


def test_const_through_identity(rng):
    g = pb.GraphDef()
    _placeholder(g, "x", (0, 4))
    _const(g, "shape_c", np.asarray([-1, 2, 2], np.int32))
    _node(g, "shape_c/read", "Identity", "shape_c")
    _node(g, "r", "Reshape", "x", "shape_c/read")
    sd = TFGraphMapper.import_graph(g.SerializeToString())
    out = sd.output({"x": rng.normal(size=(3, 4)).astype(np.float32)}, "r")
    assert np.asarray(out["r"]).shape == (3, 2, 2)


def test_nchw_graph_imports(rng):
    """GPU-targeted NCHW graphs import (round 3): the mapper sandwiches
    each NCHW node between transposes, so results match the NHWC oracle
    exactly — conv + bias + pool + batch-norm, all in NCHW."""
    import jax

    x = rng.normal(size=(2, 3, 8, 8)).astype(np.float32)   # NCHW
    k = rng.normal(size=(3, 3, 3, 4)).astype(np.float32)   # HWIO always
    b = rng.normal(size=(4,)).astype(np.float32)
    gamma = rng.uniform(0.5, 1.5, 4).astype(np.float32)
    beta = rng.normal(size=(4,)).astype(np.float32)
    mean = rng.normal(size=(4,)).astype(np.float32) * 0.1
    var = rng.uniform(0.5, 1.5, 4).astype(np.float32)

    g = pb.GraphDef()
    _placeholder(g, "x", (0, 3, 8, 8))
    _const(g, "k", k)
    _const(g, "b", b)
    for nm, arr in (("gamma", gamma), ("beta", beta), ("mean", mean),
                    ("var", var)):
        _const(g, nm, arr)
    n = _node(g, "conv", "Conv2D", "x", "k",
              strides=[1, 1, 1, 1], padding=b"SAME")
    n.attr["data_format"].s = b"NCHW"
    n2 = _node(g, "bias", "BiasAdd", "conv", "b")
    n2.attr["data_format"].s = b"NCHW"
    n3 = _node(g, "bn", "FusedBatchNormV3", "bias", "gamma", "beta",
               "mean", "var", epsilon=1e-3, is_training=False)
    n3.attr["data_format"].s = b"NCHW"
    n4 = _node(g, "pool", "MaxPool", "bn",
               ksize=[1, 1, 2, 2], strides=[1, 1, 2, 2], padding=b"VALID")
    n4.attr["data_format"].s = b"NCHW"

    sd = TFGraphMapper.import_graph(g.SerializeToString())
    out = np.asarray(sd.output({"x": x}, "pool")["pool"])
    assert out.shape == (2, 4, 4, 4)  # NCHW out

    # NHWC oracle on transposed data
    xh = x.transpose(0, 2, 3, 1)
    y = np.asarray(jax.lax.conv_general_dilated(
        xh, k, (1, 1), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))) + b
    y = gamma * (y - mean) / np.sqrt(var + 1e-3) + beta
    want = y.reshape(2, 4, 2, 4, 2, 4).max(axis=(2, 4))  # 2x2 maxpool
    np.testing.assert_allclose(out, want.transpose(0, 3, 1, 2),
                               rtol=2e-4, atol=2e-4)


def test_bfloat16_const_decodes():
    import ml_dtypes

    vals = np.asarray([1.5, -2.25, 0.5, 3.0], np.float32)
    g = pb.GraphDef()
    n = g.node.add()
    n.name = "c"
    n.op = "Const"
    n.attr["dtype"].type = pb.DT_BFLOAT16
    t = n.attr["value"].tensor
    t.dtype = pb.DT_BFLOAT16
    t.tensor_shape.dim.add().size = 4
    t.tensor_content = vals.astype(ml_dtypes.bfloat16).tobytes()
    sd = TFGraphMapper.import_graph(g.SerializeToString())
    np.testing.assert_allclose(np.asarray(sd.arrays["c"]), vals)


def test_imported_graph_fine_tunes(rng):
    """Reference flow: import frozen graph -> convertToVariable -> fit
    (the BERT-fine-tune path at small scale)."""
    from deeplearning4j_tpu.conf.updaters import Adam
    from deeplearning4j_tpu.samediff.core import SDVariable
    from deeplearning4j_tpu.samediff.training import TrainingConfig

    w1 = rng.normal(size=(4, 8), scale=0.5).astype(np.float32)
    b1 = np.zeros(8, np.float32)
    w2 = rng.normal(size=(8, 2), scale=0.5).astype(np.float32)
    g = pb.GraphDef()
    _placeholder(g, "input", (0, 4))
    _const(g, "w1", w1)
    _const(g, "b1", b1)
    _const(g, "w2", w2)
    _node(g, "mm1", "MatMul", "input", "w1",
          transpose_a=False, transpose_b=False)
    _node(g, "a1", "BiasAdd", "mm1", "b1")
    _node(g, "r1", "Relu", "a1")
    _node(g, "logits", "MatMul", "r1", "w2")

    sd = TFGraphMapper.import_graph(g.SerializeToString())
    for wname in ("w1", "b1", "w2"):
        SDVariable(sd, wname).convert_to_variable()
    labels = sd.placeholder("labels", shape=(None, 2))
    logits = SDVariable(sd, "logits")
    loss = sd.loss.softmaxCrossEntropy(labels, logits)
    sd.set_training_config(TrainingConfig(
        updater=Adam(1e-2), data_set_feature_mapping=["input"],
        data_set_label_mapping=["labels"]))

    from deeplearning4j_tpu.datasets.dataset import DataSet

    from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator

    x = rng.normal(size=(32, 4)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[(x[:, 0] > 0).astype(int)]
    it = ListDataSetIterator([DataSet(x, y)])
    losses = []
    for _ in range(30):
        sd.fit(it)
        losses.append(float(np.asarray(
            sd.output({"input": x, "labels": y},
                      loss.name)[loss.name])))
    assert losses[-1] < losses[0]
    # frozen-by-choice: w1 stays put if converted back to constant
    np.testing.assert_raises(
        AssertionError, np.testing.assert_allclose,
        np.asarray(sd.arrays["w2"]), w2)


def test_progressive_unfreezing_resets_updater_state(rng):
    """convert_to_variable after a fit must re-init updater state (it used
    to KeyError on the newly trainable name)."""
    from deeplearning4j_tpu.conf.updaters import Adam
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
    from deeplearning4j_tpu.samediff.core import SDVariable
    from deeplearning4j_tpu.samediff.training import TrainingConfig

    w1 = rng.normal(size=(4, 8), scale=0.5).astype(np.float32)
    w2 = rng.normal(size=(8, 2), scale=0.5).astype(np.float32)
    g = pb.GraphDef()
    _placeholder(g, "input", (0, 4))
    _const(g, "w1", w1)
    _const(g, "w2", w2)
    _node(g, "mm1", "MatMul", "input", "w1",
          transpose_a=False, transpose_b=False)
    _node(g, "r1", "Relu", "mm1")
    _node(g, "logits", "MatMul", "r1", "w2")
    sd = TFGraphMapper.import_graph(g.SerializeToString())
    SDVariable(sd, "w2").convert_to_variable()
    labels = sd.placeholder("labels", shape=(None, 2))
    from deeplearning4j_tpu.samediff.core import SDVariable as V

    sd.loss.softmaxCrossEntropy(labels, V(sd, "logits"))
    sd.set_training_config(TrainingConfig(
        updater=Adam(1e-2), data_set_feature_mapping=["input"],
        data_set_label_mapping=["labels"]))
    x = rng.normal(size=(16, 4)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[(x[:, 0] > 0).astype(int)]
    it = ListDataSetIterator([DataSet(x, y)])
    sd.fit(it)
    SDVariable(sd, "w1").convert_to_variable()  # progressive unfreeze
    sd.fit(it)  # must not KeyError
    assert not np.allclose(np.asarray(sd.arrays["w1"]), w1)


def test_imported_graph_serializes(tmp_path, rng):
    """Imported graphs round-trip through SameDiff save/load (reference:
    TFGraphMapper output is a normal SameDiff, persistable as FlatBuffers)."""
    from deeplearning4j_tpu.samediff.serde import load as sd_load
    from deeplearning4j_tpu.samediff.serde import save as sd_save

    w = rng.normal(size=(4, 3)).astype(np.float32)
    g = pb.GraphDef()
    _placeholder(g, "input", (0, 4))
    _const(g, "w", w)
    _node(g, "mm", "MatMul", "input", "w",
          transpose_a=False, transpose_b=False)
    _node(g, "out", "Softmax", "mm")
    sd = TFGraphMapper.import_graph(g.SerializeToString())
    x = rng.normal(size=(2, 4)).astype(np.float32)
    before = np.asarray(sd.output({"input": x}, "out")["out"])
    path = str(tmp_path / "imported.sdz")
    sd_save(sd, path)
    sd2 = sd_load(path)
    after = np.asarray(sd2.output({"input": x}, "out")["out"])
    np.testing.assert_allclose(before, after, rtol=1e-6)


# --------------------------------------------------------------------------
# BERT-class op surface: gather/batchmatmul/stridedslice/split/onehot/...
# --------------------------------------------------------------------------

def _run(g, feeds, out):
    sd = TFGraphMapper.import_graph(g.SerializeToString())
    return np.asarray(sd.output(feeds, out)[out])


def test_import_gather_and_onehot(rng):
    table = rng.normal(size=(10, 6)).astype(np.float32)
    g = pb.GraphDef()
    _const(g, "table", table)
    _const(g, "ids", np.asarray([1, 7, 3], np.int32))
    _const(g, "axis", np.asarray(0, np.int32))
    _node(g, "emb", "GatherV2", "table", "ids", "axis")
    _const(g, "depth", np.asarray(5, np.int32))
    _const(g, "on", np.asarray(2.0, np.float32))
    _const(g, "off", np.asarray(-1.0, np.float32))
    _node(g, "oh", "OneHot", "ids", "depth", "on", "off")
    got = _run(g, {}, "emb")
    np.testing.assert_allclose(got, table[[1, 7, 3]], rtol=1e-5)
    oh = _run(g, {}, "oh")
    want = np.full((3, 5), -1.0, np.float32)
    for r, c in enumerate([1, 7, 3]):
        if c < 5:
            want[r, c] = 2.0
    np.testing.assert_allclose(oh, want, rtol=1e-5)


def test_import_batchmatmul_select_cast(rng):
    a = rng.normal(size=(2, 3, 4)).astype(np.float32)
    b = rng.normal(size=(2, 4, 5)).astype(np.float32)
    g = pb.GraphDef()
    _const(g, "a", a)
    _const(g, "b", b)
    _node(g, "mm", "BatchMatMulV2", "a", "b", adj_x=False, adj_y=False)
    got = _run(g, {}, "mm")
    np.testing.assert_allclose(got, a @ b, rtol=1e-4, atol=1e-5)

    g2 = pb.GraphDef()
    _const(g2, "x", np.asarray([1.0, -2.0, 3.0], np.float32))
    _const(g2, "y", np.asarray([10.0, 20.0, 30.0], np.float32))
    _const(g2, "zero", np.asarray([0.0, 0.0, 0.0], np.float32))
    _node(g2, "c", "Greater", "x", "zero")
    _node(g2, "sel", "SelectV2", "c", "x", "y")
    cast = _node(g2, "i", "Cast", "sel")
    cast.attr["DstT"].type = pb.DT_INT32
    np.testing.assert_array_equal(_run(g2, {}, "sel"), [1.0, 20.0, 3.0])
    out = _run(g2, {}, "i")
    assert out.dtype == np.int32
    np.testing.assert_array_equal(out, [1, 20, 3])


def test_import_split_unpack_multi_output(rng):
    x = rng.normal(size=(4, 6)).astype(np.float32)
    g = pb.GraphDef()
    _const(g, "x", x)
    _const(g, "axis", np.asarray(1, np.int32))
    _node(g, "sp", "Split", "axis", "x", num_split=3)
    # consume outputs 0 and 2
    _node(g, "s02", "Add", "sp", "sp:2")
    got = _run(g, {}, "s02")
    np.testing.assert_allclose(got, x[:, 0:2] + x[:, 4:6], rtol=1e-5)

    g2 = pb.GraphDef()
    _const(g2, "x", x)
    _node(g2, "u", "Unpack", "x", num=4, axis=0)
    _node(g2, "last2", "Sub", "u:3", "u:1")
    got = _run(g2, {}, "last2")
    np.testing.assert_allclose(got, x[3] - x[1], rtol=1e-5)


def test_import_stridedslice_slice_tile_range(rng):
    x = rng.normal(size=(5, 8)).astype(np.float32)
    g = pb.GraphDef()
    _const(g, "x", x)
    _const(g, "b", np.asarray([1, 2], np.int32))
    _const(g, "e", np.asarray([4, 8], np.int32))
    _const(g, "s", np.asarray([1, 2], np.int32))
    _node(g, "ss", "StridedSlice", "x", "b", "e", "s",
          begin_mask=0, end_mask=0, ellipsis_mask=0, new_axis_mask=0,
          shrink_axis_mask=0)
    np.testing.assert_allclose(_run(g, {}, "ss"), x[1:4, 2:8:2], rtol=1e-5)

    # shrink_axis on dim 0 -> x[2, :3]
    g2 = pb.GraphDef()
    _const(g2, "x", x)
    _const(g2, "b", np.asarray([2, 0], np.int32))
    _const(g2, "e", np.asarray([3, 3], np.int32))
    _const(g2, "s", np.asarray([1, 1], np.int32))
    _node(g2, "row", "StridedSlice", "x", "b", "e", "s",
          shrink_axis_mask=1)
    np.testing.assert_allclose(_run(g2, {}, "row"), x[2, :3], rtol=1e-5)

    g3 = pb.GraphDef()
    _const(g3, "x", x)
    _const(g3, "b", np.asarray([1, 0], np.int32))
    _const(g3, "sz", np.asarray([2, -1], np.int32))
    _node(g3, "sl", "Slice", "x", "b", "sz")
    np.testing.assert_allclose(_run(g3, {}, "sl"), x[1:3, :], rtol=1e-5)

    g4 = pb.GraphDef()
    _const(g4, "x", np.asarray([[1.0, 2.0]], np.float32))
    _const(g4, "reps", np.asarray([2, 3], np.int32))
    _node(g4, "t", "Tile", "x", "reps")
    np.testing.assert_allclose(_run(g4, {}, "t"),
                               np.tile([[1.0, 2.0]], (2, 3)))

    g5 = pb.GraphDef()
    _const(g5, "st", np.asarray(0, np.int32))
    _const(g5, "li", np.asarray(6, np.int32))
    _const(g5, "d", np.asarray(2, np.int32))
    _node(g5, "r", "Range", "st", "li", "d")
    _const(g5, "dims", np.asarray([2, 2], np.int32))
    _const(g5, "val", np.asarray(7.0, np.float32))
    _node(g5, "f", "Fill", "dims", "val")
    np.testing.assert_array_equal(_run(g5, {}, "r"), [0, 2, 4])
    np.testing.assert_allclose(_run(g5, {}, "f"), np.full((2, 2), 7.0))


def test_import_attention_block_end_to_end(rng):
    """Mini self-attention built the way BERT frozen graphs express it:
    batched matmuls, scale, softmax, strided slicing."""
    B, T, D = 2, 4, 8
    x = rng.normal(size=(B, T, D)).astype(np.float32)
    wq = rng.normal(size=(D, D), scale=0.3).astype(np.float32)
    wk = rng.normal(size=(D, D), scale=0.3).astype(np.float32)
    g = pb.GraphDef()
    _placeholder(g, "x", (0, T, D))
    _const(g, "wq", wq)
    _const(g, "wk", wk)
    _const(g, "scale", np.asarray(1.0 / np.sqrt(D), np.float32))
    _node(g, "q", "BatchMatMulV2", "x", "wq")
    _node(g, "k", "BatchMatMulV2", "x", "wk")
    _node(g, "scores", "BatchMatMulV2", "q", "k", adj_y=True)
    _node(g, "scaled", "Mul", "scores", "scale")
    _node(g, "probs", "Softmax", "scaled")
    _node(g, "ctx", "BatchMatMulV2", "probs", "x")
    got = _run(g, {"x": x}, "ctx")
    q, k = x @ wq, x @ wk
    s = (q @ k.transpose(0, 2, 1)) / np.sqrt(D)
    p = np.exp(s - s.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    np.testing.assert_allclose(got, p @ x, rtol=1e-4, atol=1e-5)


def test_import_edge_semantics(rng):
    # SplitV with an inferred -1 size
    x = rng.normal(size=(4, 6)).astype(np.float32)
    g = pb.GraphDef()
    _const(g, "x", x)
    _const(g, "sizes", np.asarray([-1, 2], np.int32))
    _const(g, "axis", np.asarray(1, np.int32))
    _node(g, "sp", "SplitV", "x", "sizes", "axis", num_split=2)
    _const(g, "zero", np.zeros((4, 4), np.float32))
    _node(g, "first", "Add", "sp", "zero")
    got = _run(g, {}, "first")
    np.testing.assert_allclose(got, x[:, :4], rtol=1e-5)

    # float Range
    g2 = pb.GraphDef()
    _const(g2, "st", np.asarray(0.0, np.float32))
    _const(g2, "li", np.asarray(1.0, np.float32))
    _const(g2, "d", np.asarray(0.25, np.float32))
    _node(g2, "r", "Range", "st", "li", "d")
    np.testing.assert_allclose(_run(g2, {}, "r"), [0.0, 0.25, 0.5, 0.75])

    # Select (v1) with rank-1 cond row-selects
    g3 = pb.GraphDef()
    _const(g3, "c", np.asarray([1.0, 0.0], np.float32))
    _const(g3, "zero", np.asarray([0.0, 0.0], np.float32))
    _const(g3, "a", np.asarray([[1.0, 2.0, 3.0], [4.0, 5.0, 6.0]],
                               np.float32))
    _const(g3, "b", np.asarray([[9.0, 9.0, 9.0], [8.0, 8.0, 8.0]],
                               np.float32))
    _node(g3, "cb", "Greater", "c", "zero")
    _node(g3, "sel", "Select", "cb", "a", "b")
    np.testing.assert_allclose(_run(g3, {}, "sel"),
                               [[1.0, 2.0, 3.0], [8.0, 8.0, 8.0]])

    # OneHot axis=0
    g4 = pb.GraphDef()
    _const(g4, "ids", np.asarray([1, 0, 2], np.int32))
    _const(g4, "depth", np.asarray(3, np.int32))
    _const(g4, "on", np.asarray(1.0, np.float32))
    _const(g4, "off", np.asarray(0.0, np.float32))
    oh = _node(g4, "oh", "OneHot", "ids", "depth", "on", "off")
    oh.attr["axis"].i = 0
    got = _run(g4, {}, "oh")
    assert got.shape == (3, 3)
    np.testing.assert_allclose(got, np.eye(3)[[1, 0, 2]].T)

    # LeakyRelu with explicit alpha=0.0 behaves as Relu
    g5 = pb.GraphDef()
    _const(g5, "x", np.asarray([-2.0, 3.0], np.float32))
    lr = _node(g5, "y", "LeakyRelu", "x")
    lr.attr["alpha"].f = 0.0
    np.testing.assert_allclose(_run(g5, {}, "y"), [0.0, 3.0])


# --------------------------------------------------------------------------
# BASELINE config #5 (stretch): BERT-style encoder import + fine-tune
# --------------------------------------------------------------------------

def _int_placeholder(g, name, shape):
    n = g.node.add()
    n.name = name
    n.op = "Placeholder"
    n.attr["dtype"].type = pb.DT_INT32
    sh = n.attr["shape"].shape
    for d in shape:
        sh.dim.add().size = d if d else -1
    return n


def _layernorm(g, prefix, x, gamma, beta, axm1):
    _node(g, f"{prefix}_mu", "Mean", x, axm1, keep_dims=True)
    _node(g, f"{prefix}_sqd", "SquaredDifference", x, f"{prefix}_mu")
    _node(g, f"{prefix}_var", "Mean", f"{prefix}_sqd", axm1, keep_dims=True)
    _node(g, f"{prefix}_vare", "Add", f"{prefix}_var", "ln_eps")
    _node(g, f"{prefix}_rstd", "Rsqrt", f"{prefix}_vare")
    _node(g, f"{prefix}_cen", "Sub", x, f"{prefix}_mu")
    _node(g, f"{prefix}_nrm", "Mul", f"{prefix}_cen", f"{prefix}_rstd")
    _node(g, f"{prefix}_scl", "Mul", f"{prefix}_nrm", gamma)
    _node(g, f"{prefix}_out", "Add", f"{prefix}_scl", beta)
    return f"{prefix}_out"


def _np_layernorm(x, gamma, beta, eps=1e-6):
    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    return (x - mu) / np.sqrt(var + eps) * gamma + beta


def _build_mini_bert(rng, V=50, T=8, D=16, H=2, C=3):
    """-> (GraphDef, weights dict) for a 1-layer BERT-style encoder with
    embeddings, MHA, GELU FFN, layernorms, CLS pooler + classifier —
    expressed the way TF frozen graphs decompose it."""
    hd = D // H
    w = {
        "emb": rng.normal(size=(V, D), scale=0.5).astype(np.float32),
        "pos": rng.normal(size=(T, D), scale=0.1).astype(np.float32),
        "wq": rng.normal(size=(D, D), scale=0.2).astype(np.float32),
        "wk": rng.normal(size=(D, D), scale=0.2).astype(np.float32),
        "wv": rng.normal(size=(D, D), scale=0.2).astype(np.float32),
        "wo": rng.normal(size=(D, D), scale=0.2).astype(np.float32),
        "g1": np.ones(D, np.float32), "b1": np.zeros(D, np.float32),
        "w_ff1": rng.normal(size=(D, 4 * D), scale=0.2).astype(np.float32),
        "w_ff2": rng.normal(size=(4 * D, D), scale=0.2).astype(np.float32),
        "g2": np.ones(D, np.float32), "b2": np.zeros(D, np.float32),
        "w_cls": rng.normal(size=(D, C), scale=0.2).astype(np.float32),
        "b_cls": np.zeros(C, np.float32),
    }
    g = pb.GraphDef()
    _int_placeholder(g, "ids", (0, T))
    for k, v in w.items():
        _const(g, k, v)
    _const(g, "axis0", np.asarray(0, np.int32))
    _const(g, "axm1", np.asarray([-1], np.int32))
    _const(g, "ln_eps", np.asarray(1e-6, np.float32))
    _const(g, "half", np.asarray(0.5, np.float32))
    _const(g, "one", np.asarray(1.0, np.float32))
    _const(g, "sqrt2", np.asarray(np.sqrt(2.0), np.float32))
    _const(g, "scale", np.asarray(1.0 / np.sqrt(hd), np.float32))
    _const(g, "shape_heads", np.asarray([-1, T, H, hd], np.int32))
    _const(g, "shape_flat", np.asarray([-1, T, D], np.int32))
    _const(g, "perm_heads", np.asarray([0, 2, 1, 3], np.int32))

    _node(g, "x0", "GatherV2", "emb", "ids", "axis0")
    _node(g, "x", "Add", "x0", "pos")
    # --- attention
    for nm in ("q", "k", "v"):
        _node(g, f"{nm}p", "BatchMatMulV2", "x", f"w{nm}")
        _node(g, f"{nm}h0", "Reshape", f"{nm}p", "shape_heads")
        _node(g, f"{nm}h", "Transpose", f"{nm}h0", "perm_heads")
    _node(g, "scores", "BatchMatMulV2", "qh", "kh", adj_y=True)
    _node(g, "scaled", "Mul", "scores", "scale")
    _node(g, "probs", "Softmax", "scaled")
    _node(g, "ctx0", "BatchMatMulV2", "probs", "vh")
    _node(g, "ctx1", "Transpose", "ctx0", "perm_heads")
    _node(g, "ctx2", "Reshape", "ctx1", "shape_flat")
    _node(g, "attn", "BatchMatMulV2", "ctx2", "wo")
    _node(g, "res1", "Add", "x", "attn")
    ln1 = _layernorm(g, "ln1", "res1", "g1", "b1", "axm1")
    # --- FFN with decomposed GELU
    _node(g, "ff1", "BatchMatMulV2", ln1, "w_ff1")
    _node(g, "gdiv", "RealDiv", "ff1", "sqrt2")
    _node(g, "gerf", "Erf", "gdiv")
    _node(g, "g1p", "Add", "gerf", "one")
    _node(g, "gmul", "Mul", "ff1", "g1p")
    _node(g, "gelu", "Mul", "gmul", "half")
    _node(g, "ff2", "BatchMatMulV2", "gelu", "w_ff2")
    _node(g, "res2", "Add", ln1, "ff2")
    ln2 = _layernorm(g, "ln2", "res2", "g2", "b2", "axm1")
    # --- CLS pooler + classifier
    _const(g, "ss_b", np.asarray([0, 0, 0], np.int32))
    _const(g, "ss_e", np.asarray([0, 1, 0], np.int32))
    _const(g, "ss_s", np.asarray([1, 1, 1], np.int32))
    _node(g, "cls", "StridedSlice", ln2, "ss_b", "ss_e", "ss_s",
          begin_mask=0b101, end_mask=0b101, shrink_axis_mask=0b010)
    _node(g, "logits0", "MatMul", "cls", "w_cls",
          transpose_a=False, transpose_b=False)
    _node(g, "logits", "BiasAdd", "logits0", "b_cls")
    return g, w


def _np_mini_bert(ids, w, T=8, D=16, H=2):
    hd = D // H
    x = w["emb"][ids] + w["pos"]
    B = x.shape[0]

    def heads(m):
        return m.reshape(B, T, H, hd).transpose(0, 2, 1, 3)

    q, k, v = (heads(x @ w[f"w{n}"]) for n in "qkv")
    s = (q @ k.transpose(0, 1, 3, 2)) / np.sqrt(hd)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    ctx = (p @ v).transpose(0, 2, 1, 3).reshape(B, T, D)
    x1 = _np_layernorm(x + ctx @ w["wo"], w["g1"], w["b1"])
    h = x1 @ w["w_ff1"]
    gelu = 0.5 * h * (1.0 + np.vectorize(_math.erf)(h / np.sqrt(2.0)))
    x2 = _np_layernorm(x1 + gelu @ w["w_ff2"], w["g2"], w["b2"])
    return x2[:, 0, :] @ w["w_cls"] + w["b_cls"]


def test_import_mini_bert_matches_oracle(rng):
    g, w = _build_mini_bert(rng)
    sd = TFGraphMapper.import_graph(g.SerializeToString())
    ids = rng.integers(0, 50, (4, 8)).astype(np.int32)
    got = np.asarray(sd.output({"ids": ids}, "logits")["logits"])
    want = _np_mini_bert(ids, w)
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-4)


def test_imported_bert_fine_tunes(rng):
    """The reference's BERT fine-tune flow (BASELINE config #5): import a
    frozen graph, convert_to_variable the head, train with sd.fit."""
    from deeplearning4j_tpu.conf.updaters import Adam
    from deeplearning4j_tpu.samediff import TrainingConfig
    from deeplearning4j_tpu.samediff.core import SDVariable

    g, w = _build_mini_bert(rng)
    sd = TFGraphMapper.import_graph(g.SerializeToString())
    for name in ("w_cls", "b_cls", "wq", "wk", "wv", "wo"):
        SDVariable(sd, name).convert_to_variable()
    labels = sd.placeholder("labels", shape=(None, 3))
    logits = SDVariable(sd, "logits")
    sd.loss.softmaxCrossEntropy(labels, logits, name="loss")
    sd.set_training_config(TrainingConfig.builder()
                           .updater(Adam(learning_rate=0.01))
                           .data_set_feature_mapping("ids")
                           .data_set_label_mapping("labels").build())
    ids = rng.integers(0, 50, (32, 8)).astype(np.int32)
    cls = (ids.sum(1) % 3)
    y = np.eye(3, dtype=np.float32)[cls]
    first = None
    for _ in range(30):
        hist = sd.fit(features=ids, labels=y)
        if first is None:
            first = hist.loss_curve[-1]
    assert hist.loss_curve[-1] < first


def test_multi_output_addressable_and_import_time_errors(rng):
    x = rng.normal(size=(4, 6)).astype(np.float32)
    g = pb.GraphDef()
    _const(g, "x", x)
    _node(g, "u", "Unpack", "x", num=4, axis=0)
    sd = TFGraphMapper.import_graph(g.SerializeToString())
    np.testing.assert_allclose(np.asarray(sd.output({}, "u")["u"]), x[0],
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(sd.output({}, "u:2")["u:2"]),
                               x[2], rtol=1e-5)

    # ellipsis_mask rejected AT IMPORT with the node named
    g2 = pb.GraphDef()
    _const(g2, "x", x)
    _const(g2, "b", np.asarray([0], np.int32))
    _const(g2, "e", np.asarray([1], np.int32))
    _const(g2, "s", np.asarray([1], np.int32))
    _node(g2, "ss", "StridedSlice", "x", "b", "e", "s", ellipsis_mask=1)
    with pytest.raises(UnsupportedTFOpException, match="ss"):
        TFGraphMapper.import_graph(g2.SerializeToString())

    # int OneHot keeps its dtype
    g3 = pb.GraphDef()
    _const(g3, "ids", np.asarray([0, 2], np.int32))
    _const(g3, "depth", np.asarray(3, np.int32))
    _const(g3, "on", np.asarray(1, np.int32))
    _const(g3, "off", np.asarray(0, np.int32))
    _node(g3, "oh", "OneHot", "ids", "depth", "on", "off")
    out = np.asarray(TFGraphMapper.import_graph(
        g3.SerializeToString()).output({}, "oh")["oh"])
    assert out.dtype == np.int32
    np.testing.assert_array_equal(out, np.eye(3, dtype=np.int32)[[0, 2]])


# --------------------------------------------------------------------------
# round 2: TF2 functional control flow (While/If via FunctionDefLibrary)
# + training-mode FusedBatchNorm
# --------------------------------------------------------------------------

def _func(g, name, in_args, out_ret, nodes):
    """Add a FunctionDef: in_args = [names], out_ret = {out_name: ref},
    nodes = list of (name, op, inputs, attrs)."""
    f = g.library.function.add()
    f.signature.name = name
    for a in in_args:
        arg = f.signature.input_arg.add()
        arg.name = a
        arg.type = pb.DT_FLOAT
    for o in out_ret:
        arg = f.signature.output_arg.add()
        arg.name = o
        arg.type = pb.DT_FLOAT
    for nname, nop, nins, nattrs in nodes:
        n = f.node_def.add()
        n.name = nname
        n.op = nop
        n.input.extend(nins)
        for k, v in nattrs.items():
            if isinstance(v, bool):
                n.attr[k].b = v
            elif isinstance(v, int):
                n.attr[k].i = v
            elif isinstance(v, float):
                n.attr[k].f = v
    for o, ref in out_ret.items():
        f.ret[o] = ref
    return f


def test_import_while_loop(rng):
    """x_{t+1} = x_t * a + 1 iterated until i >= 5, as a TF2 StatelessWhile
    with cond/body FunctionDefs."""
    g = pb.GraphDef()
    _placeholder(g, "x", (3,))
    _const(g, "i0", np.asarray(0.0, np.float32))
    # cond(i, x): i < 5
    f = _func(g, "loop_cond", ["i", "x"], {"out": "less:z:0"},
              [("five", "Const", [], {}),
               ("less", "Less", ["i", "five"], {})])
    t = f.node_def[0].attr["value"].tensor
    t.dtype = pb.DT_FLOAT
    t.float_val.append(5.0)
    # body(i, x): (i+1, x*1.5 + 1)
    f2 = _func(g, "loop_body", ["i", "x"],
               {"i_out": "inc:z:0", "x_out": "plus1:z:0"},
               [("one", "Const", [], {}),
                ("scale", "Const", [], {}),
                ("inc", "AddV2", ["i", "one"], {}),
                ("mul", "Mul", ["x", "scale"], {}),
                ("plus1", "AddV2", ["mul", "one"], {})])
    f2.node_def[0].attr["value"].tensor.dtype = pb.DT_FLOAT
    f2.node_def[0].attr["value"].tensor.float_val.append(1.0)
    f2.node_def[1].attr["value"].tensor.dtype = pb.DT_FLOAT
    f2.node_def[1].attr["value"].tensor.float_val.append(1.5)

    w = _node(g, "loop", "StatelessWhile", "i0", "x")
    w.attr["cond"].func.name = "loop_cond"
    w.attr["body"].func.name = "loop_body"

    sd = TFGraphMapper.import_graph(g.SerializeToString())
    xv = rng.normal(size=(3,)).astype(np.float32)
    out = sd.output({"x": xv}, "loop:1")["loop:1"]
    want = xv.copy()
    for _ in range(5):
        want = want * 1.5 + 1.0
    np.testing.assert_allclose(np.asarray(out), want, rtol=1e-5)
    # the imported control flow serializes like native control flow
    import tempfile, os
    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, "while.sdnb")
        sd.save(p)
        sd2 = type(sd).load(p)
        out2 = sd2.output({"x": xv}, "loop:1")["loop:1"]
        np.testing.assert_allclose(np.asarray(out2), want, rtol=1e-5)


def test_import_if(rng):
    g = pb.GraphDef()
    _placeholder(g, "x", (4,))
    _const(g, "thr", np.asarray(0.0, np.float32))
    _const(g, "sum_axes", np.asarray([0], np.int32))
    _node(g, "total", "Sum", "x", "sum_axes", keep_dims=False)
    _node(g, "pred", "Greater", "total", "thr")
    _func(g, "then_f", ["x"], {"out": "dbl:z:0"},
          [("dbl", "AddV2", ["x", "x"], {})])
    _func(g, "else_f", ["x"], {"out": "neg:y:0"},
          [("neg", "Neg", ["x"], {})])
    n = _node(g, "branch", "StatelessIf", "pred", "x")
    n.attr["then_branch"].func.name = "then_f"
    n.attr["else_branch"].func.name = "else_f"

    sd = TFGraphMapper.import_graph(g.SerializeToString())
    for xv in (np.asarray([1, 2, 3, 4], np.float32),
               np.asarray([-1, -2, -3, -4], np.float32)):
        out = sd.output({"x": xv}, "branch")["branch"]
        want = xv * 2 if xv.sum() > 0 else -xv
        np.testing.assert_allclose(np.asarray(out), want, rtol=1e-5)


def test_import_training_batchnorm_and_finetune(rng):
    """FusedBatchNormV3 with is_training=True: batch statistics computed
    in-graph; the imported graph fine-tunes (gradients flow through the
    stats)."""
    gamma = np.abs(rng.normal(size=(2,))).astype(np.float32) + 0.5
    beta = rng.normal(size=(2,)).astype(np.float32)
    g = pb.GraphDef()
    _placeholder(g, "x", (0, 4, 4, 2))
    _const(g, "gamma", gamma)
    _const(g, "beta", beta)
    _const(g, "zero_m", np.zeros(2, np.float32))
    _const(g, "zero_v", np.ones(2, np.float32))
    bn = _node(g, "bn", "FusedBatchNormV3", "x", "gamma", "beta",
               "zero_m", "zero_v", epsilon=1e-3, is_training=True,
               data_format=b"NHWC")

    sd = TFGraphMapper.import_graph(g.SerializeToString())
    xv = rng.normal(size=(3, 4, 4, 2)).astype(np.float32) * 2 + 1
    outs = sd.output({"x": xv}, "bn", "bn:1", "bn:2")
    mu = xv.mean(axis=(0, 1, 2))
    var = xv.var(axis=(0, 1, 2))
    want = gamma * (xv - mu) / np.sqrt(var + 1e-3) + beta
    np.testing.assert_allclose(np.asarray(outs["bn"]), want,
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(outs["bn:1"]), mu, rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(outs["bn:2"]), var, rtol=1e-4,
                               atol=1e-5)
    # gradients flow through the batch statistics (fine-tune path)
    import jax
    import jax.numpy as jnp

    fn = sd.make_function(("bn",))

    def loss(x):
        return jnp.sum(fn(dict(sd.arrays), {"x": x})["bn"] ** 2)

    gx = jax.grad(loss)(jnp.asarray(xv))
    assert np.all(np.isfinite(np.asarray(gx)))
    assert float(jnp.sum(jnp.abs(gx))) > 0


def _stripped_bn_graph():
    """FusedBatchNorm whose is_training attr was stripped (proto3
    default-value elision) — legal wire bytes, ambiguous semantics."""
    g = pb.GraphDef()
    _placeholder(g, "x", (0, 2, 2, 1))
    _const(g, "gamma", np.ones(1, np.float32))
    _const(g, "beta", np.zeros(1, np.float32))
    _const(g, "m", np.asarray([0.5], np.float32))
    _const(g, "v", np.asarray([2.0], np.float32))
    _node(g, "bn", "FusedBatchNorm", "x", "gamma", "beta", "m", "v",
          epsilon=1e-3, data_format=b"NHWC")
    return g


def test_import_batchnorm_missing_is_training_fails_closed():
    """is_training absent -> refuse to guess (round-3 verdict: the
    round-3 importer warned and silently picked the OPPOSITE of TF's op
    default on legal input)."""
    g = _stripped_bn_graph()
    with pytest.raises(UnsupportedTFOpException,
                       match="bn_missing_is_training"):
        TFGraphMapper.import_graph(g.SerializeToString())


def test_import_batchnorm_missing_is_training_override_inference():
    """bn_missing_is_training=False -> inference form; bn:1/bn:2 pass
    the supplied running stats through (TF output layout)."""
    g = _stripped_bn_graph()
    sd = TFGraphMapper.import_graph(g.SerializeToString(),
                                    bn_missing_is_training=False)
    xv = np.ones((1, 2, 2, 1), np.float32)
    outs = sd.output({"x": xv}, "bn", "bn:1")
    np.testing.assert_allclose(np.asarray(outs["bn"]),
                               (xv - 0.5) / np.sqrt(2.0 + 1e-3), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(outs["bn:1"]), [0.5])


def test_import_batchnorm_missing_is_training_override_training():
    """bn_missing_is_training=True -> TF's op default: batch stats
    computed in-graph, running-stat inputs ignored."""
    g = _stripped_bn_graph()
    sd = TFGraphMapper.import_graph(g.SerializeToString(),
                                    bn_missing_is_training=True)
    rng = np.random.default_rng(5)
    xv = rng.normal(size=(2, 2, 2, 1)).astype(np.float32)
    outs = sd.output({"x": xv}, "bn", "bn:1", "bn:2")
    bm = xv.mean(axis=(0, 1, 2))
    bv = xv.var(axis=(0, 1, 2))
    np.testing.assert_allclose(np.asarray(outs["bn"]),
                               (xv - bm) / np.sqrt(bv + 1e-3), rtol=1e-4)
    np.testing.assert_allclose(np.asarray(outs["bn:1"]), bm, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(outs["bn:2"]), bv, rtol=1e-5)


def test_import_where_bounded(rng):
    """1-input Where under the bounded-shape convention: indices
    [size(x), rank] zero-padded past the true nonzero count, count at
    output :1; numpy np.argwhere is the oracle for the live rows."""
    g = pb.GraphDef()
    _placeholder(g, "x", (3, 4))
    _node(g, "w", "Where", "x")
    with pytest.warns(UserWarning, match="bounded-shape"):
        sd = TFGraphMapper.import_graph(g.SerializeToString())
    xv = rng.normal(size=(3, 4)).astype(np.float32)
    xv[xv < 0.3] = 0.0
    outs = sd.output({"x": xv}, "w", "w:1")
    idx = np.asarray(outs["w"])
    count = int(np.asarray(outs["w:1"]))
    want = np.argwhere(xv)
    assert idx.shape == (12, 2)
    assert count == len(want)
    np.testing.assert_array_equal(idx[:count], want)
    np.testing.assert_array_equal(idx[count:], 0)


def test_import_sparse_softmax_ce_with_logits(rng):
    """Twin-output SparseSoftmaxCrossEntropyWithLogits vs numpy: loss
    [B] per-example, backprop [B, C] = softmax - onehot."""
    g = pb.GraphDef()
    _placeholder(g, "logits", (0, 5))
    _const(g, "labels", np.asarray([1, 4, 0], np.int32))
    _node(g, "ce", "SparseSoftmaxCrossEntropyWithLogits",
          "logits", "labels")
    sd = TFGraphMapper.import_graph(g.SerializeToString())
    lv = rng.normal(size=(3, 5)).astype(np.float32)
    labels = np.asarray([1, 4, 0])
    outs = sd.output({"logits": lv}, "ce", "ce:1")
    e = np.exp(lv - lv.max(axis=-1, keepdims=True))
    sm = e / e.sum(axis=-1, keepdims=True)
    want_loss = -np.log(sm[np.arange(3), labels])
    onehot = np.eye(5, dtype=np.float32)[labels]
    np.testing.assert_allclose(np.asarray(outs["ce"]), want_loss,
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(outs["ce:1"]), sm - onehot,
                               rtol=1e-5, atol=1e-6)


def test_import_missing_function_raises():
    g = pb.GraphDef()
    _placeholder(g, "x", (2,))
    _const(g, "i0", np.asarray(0.0, np.float32))
    n = _node(g, "loop", "StatelessWhile", "i0", "x")
    n.attr["cond"].func.name = "nope"
    n.attr["body"].func.name = "nada"
    with pytest.raises(UnsupportedTFOpException, match="function library"):
        TFGraphMapper.import_graph(g.SerializeToString())


def test_import_if_multi_output(rng):
    """If branches returning TWO tensors (round 2: multi-output sd.cond)."""
    g = pb.GraphDef()
    _placeholder(g, "x", (4,))
    _const(g, "thr", np.asarray(0.0, np.float32))
    _const(g, "sum_axes", np.asarray([0], np.int32))
    _node(g, "total", "Sum", "x", "sum_axes", keep_dims=False)
    _node(g, "pred", "Greater", "total", "thr")
    _func(g, "then2", ["x"], {"a": "dbl:z:0", "b": "neg:y:0"},
          [("dbl", "AddV2", ["x", "x"], {}),
           ("neg", "Neg", ["x"], {})])
    _func(g, "else2", ["x"], {"a": "neg:y:0", "b": "dbl:z:0"},
          [("dbl", "AddV2", ["x", "x"], {}),
           ("neg", "Neg", ["x"], {})])
    n = _node(g, "branch", "StatelessIf", "pred", "x")
    n.attr["then_branch"].func.name = "then2"
    n.attr["else_branch"].func.name = "else2"

    sd = TFGraphMapper.import_graph(g.SerializeToString())
    for xv in (np.asarray([1, 2, 3, 4], np.float32),
               np.asarray([-1, -2, -3, -4], np.float32)):
        out = sd.output({"x": xv}, "branch", "branch:1")
        wa, wb = ((xv * 2, -xv) if xv.sum() > 0 else (-xv, xv * 2))
        np.testing.assert_allclose(np.asarray(out["branch"]), wa, rtol=1e-5)
        np.testing.assert_allclose(np.asarray(out["branch:1"]), wb,
                                   rtol=1e-5)


def test_import_v1_while_frames(rng):
    """TF1 frame control flow (round 3): Enter/Merge/Switch/LoopCond/
    NextIteration/Exit reconstruct into ONE structured sd.while_loop.
    Loop: i=0, acc=1; while i < 5: i += 1; acc *= 2 -> i=5, acc=32; a
    loop-INVARIANT Enter (the limit) rides through, and downstream nodes
    consume the Exit outputs."""
    g = pb.GraphDef()
    _const(g, "i0", np.asarray(0.0, np.float32))
    _const(g, "acc0", np.asarray(1.0, np.float32))
    _const(g, "limit", np.asarray(5.0, np.float32))
    _node(g, "enter_i", "Enter", "i0", frame_name=b"loop")
    _node(g, "enter_acc", "Enter", "acc0", frame_name=b"loop")
    n = _node(g, "enter_limit", "Enter", "limit", frame_name=b"loop")
    n.attr["is_constant"].b = True
    _node(g, "merge_i", "Merge", "enter_i", "next_i")
    _node(g, "merge_acc", "Merge", "enter_acc", "next_acc")
    _node(g, "less", "Less", "merge_i", "enter_limit")
    _node(g, "cond", "LoopCond", "less")
    _node(g, "switch_i", "Switch", "merge_i", "cond")
    _node(g, "switch_acc", "Switch", "merge_acc", "cond")
    _const(g, "one", np.asarray(1.0, np.float32))
    _const(g, "two", np.asarray(2.0, np.float32))
    _node(g, "add_i", "Add", "switch_i:1", "one")
    _node(g, "mul_acc", "Mul", "switch_acc:1", "two")
    _node(g, "next_i", "NextIteration", "add_i")
    _node(g, "next_acc", "NextIteration", "mul_acc")
    _node(g, "exit_i", "Exit", "switch_i")
    _node(g, "exit_acc", "Exit", "switch_acc")
    _node(g, "final", "Mul", "exit_acc", "exit_i")

    sd = TFGraphMapper.import_graph(g.SerializeToString())
    out = sd.output({}, "exit_i", "exit_acc", "final")
    assert float(np.asarray(out["exit_i"])) == 5.0
    assert float(np.asarray(out["exit_acc"])) == 32.0
    assert float(np.asarray(out["final"])) == 160.0


def test_import_v1_while_serializes(tmp_path, rng):
    """The reconstructed while_loop round-trips through serde like
    natively-built control flow."""
    g = pb.GraphDef()
    _const(g, "x0", np.asarray(2.0, np.float32))
    _const(g, "lim", np.asarray(100.0, np.float32))
    _node(g, "enter_x", "Enter", "x0", frame_name=b"f")
    n = _node(g, "enter_l", "Enter", "lim", frame_name=b"f")
    n.attr["is_constant"].b = True
    _node(g, "merge_x", "Merge", "enter_x", "next_x")
    _node(g, "less", "Less", "merge_x", "enter_l")
    _node(g, "cond", "LoopCond", "less")
    _node(g, "switch_x", "Switch", "merge_x", "cond")
    _node(g, "sq", "Mul", "switch_x:1", "switch_x:1")
    _node(g, "next_x", "NextIteration", "sq")
    _node(g, "exit_x", "Exit", "switch_x")
    sd = TFGraphMapper.import_graph(g.SerializeToString())
    assert float(np.asarray(sd.output({}, "exit_x")["exit_x"])) == 256.0

    from deeplearning4j_tpu.samediff.core import SameDiff

    p = str(tmp_path / "v1while.sd")
    sd.save(p)
    sd2 = SameDiff.load(p)
    assert float(np.asarray(sd2.output({}, "exit_x")["exit_x"])) == 256.0


def test_import_v1_cond_rejected(rng):
    """v1 Switch/Merge OUTSIDE a while frame (tf.cond v1) stays
    unsupported with a clear error (TF2 functional If imports)."""
    g = pb.GraphDef()
    _const(g, "p", np.asarray(1, np.int32))
    _const(g, "x", np.asarray(1.0, np.float32))
    _node(g, "sw", "Switch", "x", "p")
    _node(g, "m", "Merge", "sw", "sw:1")
    with pytest.raises(UnsupportedTFOpException, match="tf.cond v1"):
        TFGraphMapper.import_graph(g.SerializeToString())


def test_import_nested_v1_frames_rejected(rng):
    g = pb.GraphDef()
    _const(g, "x0", np.asarray(0.0, np.float32))
    _node(g, "enter_a", "Enter", "x0", frame_name=b"outer")
    _node(g, "enter_b", "Enter", "enter_a", frame_name=b"inner")
    _node(g, "merge_a", "Merge", "enter_a", "enter_a")
    _node(g, "cond", "LoopCond", "merge_a")
    with pytest.raises(UnsupportedTFOpException, match="nested"):
        TFGraphMapper.import_graph(g.SerializeToString())


def test_import_round3_op_batch(rng):
    """Round-3 TF surface widening: AddN, ClipByValue, Einsum, GatherNd,
    TopKV2, ReverseV2, Cumprod, PadV2, MirrorPad, MatrixBandPart,
    SpaceToDepth round-trip, resize, 3-D conv/pool, new unary/binary
    entries — numpy oracles."""
    import scipy.special as sps

    g = pb.GraphDef()
    _placeholder(g, "x", (0, 4))
    _placeholder(g, "y", (0, 4))
    _node(g, "addn", "AddN", "x", "y", "x")
    _const(g, "lo", np.asarray(-0.5, np.float32))
    _const(g, "hi", np.asarray(0.5, np.float32))
    _node(g, "clip", "ClipByValue", "x", "lo", "hi")
    n = _node(g, "es", "Einsum", "x", "y")
    n.attr["equation"].s = b"bi,bi->b"
    _node(g, "sinh", "Sinh", "x")
    _node(g, "erfc", "Erfc", "x")
    _node(g, "atan2", "Atan2", "x", "y")
    _node(g, "mod", "FloorMod", "x", "y")
    _node(g, "tmod", "Mod", "x", "y")
    _const(g, "aax", np.asarray(1, np.int32))
    _node(g, "amin", "ArgMin", "x", "aax")
    _const(g, "rax", np.asarray([1], np.int32))
    _node(g, "rev", "ReverseV2", "x", "rax")
    _const(g, "cax", np.asarray(1, np.int32))
    _node(g, "cprod", "Cumprod", "x", "cax")
    _const(g, "k2", np.asarray(2, np.int32))
    _node(g, "topk", "TopKV2", "x", "k2")
    _const(g, "pads", np.asarray([[0, 0], [1, 2]], np.int32))
    _const(g, "pval", np.asarray(9.0, np.float32))
    _node(g, "padv2", "PadV2", "x", "pads", "pval")
    m = _node(g, "mpad", "MirrorPad", "x", "pads")
    m.attr["mode"].s = b"REFLECT"
    _placeholder(g, "sq", (0, 3, 3))
    _const(g, "bl", np.asarray(1, np.int32))
    _const(g, "bu", np.asarray(1, np.int32))
    _node(g, "band", "MatrixBandPart", "sq", "bl", "bu")
    _placeholder(g, "img", (0, 4, 4, 4))
    n = _node(g, "s2d", "SpaceToDepth", "img")
    n.attr["block_size"].i = 2
    n = _node(g, "d2s", "DepthToSpace", "s2d")
    n.attr["block_size"].i = 2
    _const(g, "sz", np.asarray([8, 8], np.int32))
    r = _node(g, "rsz", "ResizeNearestNeighbor", "img", "sz")
    r.attr["half_pixel_centers"].b = True
    _placeholder(g, "vol", (0, 4, 4, 4, 2))
    _const(g, "k3", rng.normal(size=(2, 2, 2, 2, 3)).astype(np.float32))
    _node(g, "c3", "Conv3D", "vol", "k3",
          strides=[1, 1, 1, 1, 1], padding=b"VALID")
    _node(g, "mp3", "MaxPool3D", "vol",
          ksize=[1, 2, 2, 2, 1], strides=[1, 2, 2, 2, 1], padding=b"VALID")
    _placeholder(g, "gsrc", (0, 4))
    _const(g, "gidx", np.asarray([[0, 1], [2, 3]], np.int32))
    _node(g, "gnd", "GatherNd", "gsrc", "gidx")

    sd = TFGraphMapper.import_graph(g.SerializeToString())
    xv = rng.normal(size=(3, 4)).astype(np.float32)
    yv = rng.uniform(0.5, 2.0, size=(3, 4)).astype(np.float32)
    sqv = rng.normal(size=(2, 3, 3)).astype(np.float32)
    imgv = rng.normal(size=(1, 4, 4, 4)).astype(np.float32)
    volv = rng.normal(size=(1, 4, 4, 4, 2)).astype(np.float32)
    gsv = rng.normal(size=(3, 4)).astype(np.float32)

    outs = sd.output(
        {"x": xv, "y": yv, "sq": sqv, "img": imgv, "vol": volv,
         "gsrc": gsv},
        "addn", "clip", "es", "sinh", "erfc", "atan2", "mod", "rev",
        "cprod", "topk", "topk:1", "padv2", "mpad", "band", "d2s", "rsz",
        "c3", "mp3", "gnd", "tmod", "amin")

    np.testing.assert_allclose(np.asarray(outs["addn"]), 2 * xv + yv,
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(outs["clip"]),
                               np.clip(xv, -0.5, 0.5), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(outs["es"]), (xv * yv).sum(1),
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(outs["sinh"]), np.sinh(xv),
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(outs["erfc"]), sps.erfc(xv),
                               rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(outs["atan2"]),
                               np.arctan2(xv, yv), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(outs["mod"]), np.mod(xv, yv),
                               rtol=1e-4, atol=1e-5)
    # TF's raw Mod is TRUNCATING (sign follows the dividend) = fmod
    np.testing.assert_allclose(np.asarray(outs["tmod"]),
                               np.fmod(xv, yv), rtol=1e-4, atol=1e-5)
    np.testing.assert_array_equal(np.asarray(outs["amin"]),
                                  np.argmin(xv, axis=1))
    np.testing.assert_allclose(np.asarray(outs["rev"]), xv[:, ::-1],
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(outs["cprod"]),
                               np.cumprod(xv, axis=1), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(outs["topk"]),
                               np.sort(xv, 1)[:, ::-1][:, :2], rtol=1e-6)
    np.testing.assert_allclose(np.asarray(outs["padv2"]),
                               np.pad(xv, ((0, 0), (1, 2)),
                                      constant_values=9.0), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(outs["mpad"]),
                               np.pad(xv, ((0, 0), (1, 2)),
                                      mode="reflect"), rtol=1e-6)
    band = sqv.copy()
    band[:, 0, 2] = 0.0
    band[:, 2, 0] = 0.0
    np.testing.assert_allclose(np.asarray(outs["band"]), band, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(outs["d2s"]), imgv, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(outs["rsz"]),
                               imgv.repeat(2, 1).repeat(2, 2), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(outs["gnd"]),
                               gsv[[0, 2], [1, 3]], rtol=1e-6)
    # conv3d/pool3d exact math is pinned by test_op_validation; here the
    # import path's attr plumbing is what's under test
    np.testing.assert_allclose(
        np.asarray(outs["mp3"]),
        volv.reshape(1, 2, 2, 2, 2, 2, 2, 2).max(axis=(2, 4, 6)),
        rtol=1e-6)
    assert np.asarray(outs["c3"]).shape == (1, 3, 3, 3, 3)
