"""Feature-mask RESIZING through time-resizing layers (reference
``feedForwardMaskArray``: Conv1D/Subsampling1D/Upsampling1D/Cropping1D/
ZeroPadding1D transform the [batch, time] mask through their own time
geometry instead of terminating it — round-2 verdict item #6)."""

import numpy as np
import pytest

from deeplearning4j_tpu.conf import Activation, InputType, WeightInit
from deeplearning4j_tpu.conf.layers_cnn import (
    Convolution1DLayer,
    ConvolutionMode,
    PoolingType,
)
from deeplearning4j_tpu.conf.layers_extra import (
    Cropping1D,
    Subsampling1DLayer,
    Upsampling1D,
    ZeroPadding1DLayer,
)
from deeplearning4j_tpu.conf.layers_rnn import LSTM, RnnOutputLayer
from deeplearning4j_tpu.conf.losses import LossMCXENT
from deeplearning4j_tpu.conf.multilayer import NeuralNetConfiguration
from deeplearning4j_tpu.conf.updaters import Adam
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


# --------------------------------------------------------------------------
# resize_mask unit semantics (manual downsampled-mask parity)
# --------------------------------------------------------------------------
def test_resize_mask_oracles():
    m = np.asarray([[1, 1, 1, 1, 0, 0, 0, 0],
                    [1, 1, 1, 0, 0, 0, 0, 0]], np.float32)

    conv = Convolution1DLayer(n_out=4, kernel=2, stride1d=2,
                              convolution_mode=ConvolutionMode.TRUNCATE)
    # windows [0,1] [2,3] [4,5] [6,7]; valid iff ANY input step valid
    np.testing.assert_array_equal(
        np.asarray(conv.resize_mask(m)),
        [[1, 1, 0, 0], [1, 1, 0, 0]])

    pool = Subsampling1DLayer(pooling_type=PoolingType.MAX, kernel_size=2,
                              stride=2)
    np.testing.assert_array_equal(
        np.asarray(pool.resize_mask(m)),
        [[1, 1, 0, 0], [1, 1, 0, 0]])

    up = Upsampling1D(size=2)
    np.testing.assert_array_equal(
        np.asarray(up.resize_mask(m[:, :3])),
        [[1, 1, 1, 1, 1, 1], [1, 1, 1, 1, 1, 1]])

    crop = Cropping1D(cropping=(1, 2))
    np.testing.assert_array_equal(
        np.asarray(crop.resize_mask(m)),
        [[1, 1, 1, 0, 0], [1, 1, 0, 0, 0]])

    pad = ZeroPadding1DLayer(padding=(1, 1))
    got = np.asarray(pad.resize_mask(m[:, :3]))
    np.testing.assert_array_equal(got, [[0, 1, 1, 1, 0], [0, 1, 1, 1, 0]])


def test_resize_mask_straddling_window_counts_valid():
    """A pooling window straddling the valid/invalid boundary stays VALID
    (max semantics): valid length 3 with k=2/s=2 -> [1, 1]."""
    pool = Subsampling1DLayer(kernel_size=2, stride=2)
    m = np.asarray([[1, 1, 1, 0]], np.float32)
    np.testing.assert_array_equal(np.asarray(pool.resize_mask(m)), [[1, 1]])


# --------------------------------------------------------------------------
# end-to-end: masked strided-conv sequence models keep masking downstream
# --------------------------------------------------------------------------
def _mln_conf():
    return (NeuralNetConfiguration.builder()
            .seed(7)
            .updater(Adam(learning_rate=0.01))
            .weight_init(WeightInit.XAVIER)
            .list()
            .layer(Convolution1DLayer(
                n_out=5, kernel=2, stride1d=2, activation=Activation.TANH,
                convolution_mode=ConvolutionMode.TRUNCATE))
            .layer(LSTM(n_out=6))
            .layer(RnnOutputLayer(n_out=2, activation=Activation.SOFTMAX,
                                  loss_fn=LossMCXENT()))
            .set_input_type(InputType.recurrent(3, timesteps=8))
            .build())


def _cg_conf():
    return (NeuralNetConfiguration.builder()
            .seed(7)
            .updater(Adam(learning_rate=0.01))
            .weight_init(WeightInit.XAVIER)
            .graph_builder()
            .add_inputs("in")
            .set_input_types(InputType.recurrent(3, timesteps=8))
            .add_layer("conv", Convolution1DLayer(
                n_out=5, kernel=2, stride1d=2, activation=Activation.TANH,
                convolution_mode=ConvolutionMode.TRUNCATE), "in")
            .add_layer("lstm", LSTM(n_out=6), "conv")
            .add_layer("out", RnnOutputLayer(n_out=2,
                                             activation=Activation.SOFTMAX,
                                             loss_fn=LossMCXENT()), "lstm")
            .set_outputs("out")
            .build())


def _masked_batch():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(2, 8, 3)).astype(np.float32)
    mask = np.ones((2, 8), np.float32)
    mask[0, 4:] = 0.0          # sample 0: valid length 4 -> conv mask [1,1,0,0]
    return x, mask


@pytest.mark.parametrize("kind", ["mln", "cg"])
def test_strided_conv_mask_reaches_downstream_rnn(kind):
    """Perturbing input steps that are masked out (and whose conv windows
    are FULLY masked) must not change ANY output step: the LSTM after the
    strided conv must receive the downsampled mask (round 2 terminated it,
    so the perturbation leaked through the conv into live LSTM state)."""
    x, mask = _masked_batch()
    x2 = x.copy()
    x2[0, 4:] += 3.21          # fully-masked windows [4,5], [6,7]

    if kind == "mln":
        net = MultiLayerNetwork(_mln_conf()).init()
        y1 = np.asarray(net.output(x, fmask=mask))
        y2 = np.asarray(net.output(x2, fmask=mask))
    else:
        net = ComputationGraph(_cg_conf()).init()
        y1 = np.asarray(net.output(x, fmasks=[mask]))
        y2 = np.asarray(net.output(x2, fmasks=[mask]))
    np.testing.assert_allclose(y1, y2, atol=1e-6)
    # the unmasked sample must still see real (non-frozen) dynamics:
    # perturbing ITS tail changes its outputs
    x3 = x.copy()
    x3[1, 4:] += 3.21
    y3 = (np.asarray(net.output(x3, fmask=mask)) if kind == "mln"
          else np.asarray(net.output(x3, fmasks=[mask])))
    assert np.abs(y3[1] - y1[1]).max() > 1e-4


def test_mln_masked_strided_conv_trains():
    """fit() with per-timestep labels through the resized mask chain:
    labels mask downsampling is the caller's job (labels are already at
    the conv-output rate), feature masks resize internally."""
    from deeplearning4j_tpu.datasets.dataset import DataSet

    x, mask = _masked_batch()
    rng = np.random.default_rng(1)
    y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, (2, 4))]
    lmask = np.ones((2, 4), np.float32)
    lmask[0, 2:] = 0.0
    net = MultiLayerNetwork(_mln_conf()).init()
    loss = net.fit_batch(DataSet(x, y, features_mask=mask,
                                 labels_mask=lmask))
    assert np.isfinite(loss)
    flat = net.params_flat()
    assert np.all(np.isfinite(flat))

def test_variable_length_conf_resizes_mask():
    """Unknown conf timesteps (InputType.recurrent(3), the variable-length
    case masks exist for) must still resize the mask: the decision is made
    from TRACED shapes, not static conf types (round-3 review finding)."""
    conf = (NeuralNetConfiguration.builder()
            .seed(7).updater(Adam(learning_rate=0.01))
            .weight_init(WeightInit.XAVIER)
            .list()
            .layer(Convolution1DLayer(
                n_out=5, kernel=2, stride1d=2, activation=Activation.TANH,
                convolution_mode=ConvolutionMode.TRUNCATE))
            .layer(LSTM(n_out=6))
            .layer(RnnOutputLayer(n_out=2, activation=Activation.SOFTMAX,
                                  loss_fn=LossMCXENT()))
            .set_input_type(InputType.recurrent(3))   # timesteps unknown
            .build())
    net = MultiLayerNetwork(conf).init()
    x, mask = _masked_batch()
    y1 = np.asarray(net.output(x, fmask=mask))        # must not crash
    # identical semantics to the static-timesteps config
    static = MultiLayerNetwork(_mln_conf()).init()
    y2 = np.asarray(static.output(x, fmask=mask))
    np.testing.assert_allclose(y1, y2, atol=1e-6)


def test_attention_vertex_streaming_refused():
    """AttentionVertex has no wrapped .layer but attends over the whole
    sequence — rnn_time_step must refuse it (round-3 review finding)."""
    from deeplearning4j_tpu.conf.graph import AttentionVertex

    conf = (NeuralNetConfiguration.builder()
            .seed(3).updater(Adam(learning_rate=0.01))
            .graph_builder()
            .add_inputs("in")
            .set_input_types(InputType.recurrent(4, 6))
            .add_layer("rnn", LSTM(n_out=8), "in")
            .add_vertex("att", AttentionVertex(n_out=8, n_heads=2),
                        "rnn", "rnn", "rnn")
            .add_layer("out", RnnOutputLayer(n_out=2,
                                             activation=Activation.SOFTMAX,
                                             loss_fn=LossMCXENT()), "att")
            .set_outputs("out")
            .build())
    net = ComputationGraph(conf).init()
    x = np.random.default_rng(0).normal(size=(2, 6, 4)).astype(np.float32)
    with pytest.raises(RuntimeError, match="rnn_time_step is unsupported"):
        net.rnn_time_step(x)


def test_attention_vertex_streaming_with_window():
    """Round-3 refusal closed where the window allows: a CAUSAL
    AttentionVertex with streaming_window >= T streams through
    rnn_time_step chunk by chunk and matches the full-sequence forward
    exactly; the whole-sequence (default) vertex still refuses."""
    from deeplearning4j_tpu.conf.graph import AttentionVertex

    T = 6

    def build(window):
        conf = (NeuralNetConfiguration.builder()
                .seed(3).updater(Adam(learning_rate=0.01))
                .graph_builder()
                .add_inputs("in")
                .set_input_types(InputType.recurrent(4, T))
                .add_layer("rnn", LSTM(n_out=8), "in")
                .add_vertex("att", AttentionVertex(
                    n_out=8, n_heads=2, causal=True,
                    streaming_window=window), "rnn", "rnn", "rnn")
                .add_layer("out", RnnOutputLayer(
                    n_out=2, activation=Activation.SOFTMAX,
                    loss_fn=LossMCXENT()), "att")
                .set_outputs("out")
                .build())
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        return ComputationGraph(conf).init()

    net = build(window=T)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(3, T, 4)).astype(np.float32)
    full = np.asarray(net.output(x))

    streamed = []
    for t0 in range(0, T, 2):           # three 2-step chunks
        streamed.append(np.asarray(net.rnn_time_step(x[:, t0:t0 + 2])))
    got = np.concatenate(streamed, axis=1)
    np.testing.assert_allclose(got, full, rtol=1e-4, atol=1e-5)

    # non-causal / windowless stays refused
    import pytest as _pytest

    from deeplearning4j_tpu.conf.graph import AttentionVertex as AV

    with _pytest.raises(ValueError, match="requires causal"):
        AV(n_out=8, n_heads=2, streaming_window=4)


def test_attention_vertex_window_tbptt_trains():
    """The windowed causal vertex also trains under truncated BPTT (the
    KV cache threads across segments, transformer-XL style): finite and
    decreasing loss."""
    from deeplearning4j_tpu.conf.graph import AttentionVertex
    from deeplearning4j_tpu.conf.multilayer import BackpropType
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.nn.graph import ComputationGraph

    T = 8
    conf = (NeuralNetConfiguration.builder()
            .seed(5).updater(Adam(learning_rate=0.01))
            .graph_builder()
            .add_inputs("in")
            .set_input_types(InputType.recurrent(4, T))
            .add_layer("rnn", LSTM(n_out=8), "in")
            .add_vertex("att", AttentionVertex(
                n_out=8, n_heads=2, causal=True, streaming_window=4),
                "rnn", "rnn", "rnn")
            .add_layer("out", RnnOutputLayer(
                n_out=2, activation=Activation.SOFTMAX,
                loss_fn=LossMCXENT()), "att")
            .set_outputs("out")
            .backprop_type(BackpropType.TRUNCATED_BPTT, fwd=4, back=4)
            .build())
    net = ComputationGraph(conf).init()
    rng = np.random.default_rng(1)
    x = rng.normal(size=(4, T, 4)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, (4, T))]
    first = net.fit_batch(DataSet(x, y))
    for _ in range(15):
        loss = net.fit_batch(DataSet(x, y))
    assert np.isfinite(loss) and loss < first
