"""Layer shape inference + forward correctness (reference oracle:
layer tests in deeplearning4j-nn src/test, SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.conf import inputs as it
from deeplearning4j_tpu.conf.activations import Activation
from deeplearning4j_tpu.conf.layers import (
    ActivationLayer,
    DenseLayer,
    DropoutLayer,
    EmbeddingLayer,
    EmbeddingSequenceLayer,
    OutputLayer,
)
from deeplearning4j_tpu.conf.layers_cnn import (
    BatchNormalization,
    ConvolutionLayer,
    ConvolutionMode,
    Cropping2D,
    Deconvolution2D,
    GlobalPoolingLayer,
    LocalResponseNormalization,
    PoolingType,
    SeparableConvolution2D,
    SpaceToDepthLayer,
    SubsamplingLayer,
    Upsampling2D,
    ZeroPaddingLayer,
)

KEY = jax.random.PRNGKey(0)


def run_layer(layer, input_type, x, train=False):
    params = layer.init(KEY, input_type)
    state = layer.init_state(input_type)
    y, _ = layer.forward(params, state, jnp.asarray(x), train=train,
                         rng=jax.random.PRNGKey(1))
    return np.asarray(y)


def test_dense_shapes_and_values():
    layer = DenseLayer(n_out=3, activation=Activation.IDENTITY)
    t = it.InputType.feed_forward(4)
    params = layer.init(KEY, t)
    assert params["W"].shape == (4, 3) and params["b"].shape == (3,)
    x = np.ones((2, 4), np.float32)
    y, _ = layer.forward(params, {}, jnp.asarray(x))
    want = x @ np.asarray(params["W"]) + np.asarray(params["b"])
    np.testing.assert_allclose(np.asarray(y), want, rtol=1e-5)
    assert layer.output_type(t) == it.InputType.feed_forward(3)


def test_conv_same_truncate_strict_output_sizes():
    t = it.InputType.convolutional(28, 28, 1)
    same = ConvolutionLayer(n_out=8, kernel_size=(3, 3), stride=(2, 2),
                            convolution_mode=ConvolutionMode.SAME)
    assert same.output_type(t) == it.InputType.convolutional(14, 14, 8)
    trunc = ConvolutionLayer(n_out=8, kernel_size=(5, 5), stride=(2, 2),
                             convolution_mode=ConvolutionMode.TRUNCATE)
    assert trunc.output_type(t) == it.InputType.convolutional(12, 12, 8)
    strict = ConvolutionLayer(n_out=8, kernel_size=(5, 5), stride=(2, 2),
                              convolution_mode=ConvolutionMode.STRICT)
    with pytest.raises(ValueError):
        strict.output_type(t)  # (28-5) % 2 != 0


def test_conv_forward_matches_manual():
    t = it.InputType.convolutional(5, 5, 2)
    layer = ConvolutionLayer(n_out=3, kernel_size=(3, 3), stride=(1, 1),
                             activation=Activation.IDENTITY)
    params = layer.init(KEY, t)
    x = np.random.default_rng(0).normal(size=(1, 5, 5, 2)).astype(np.float32)
    y, _ = layer.forward(params, {}, jnp.asarray(x))
    assert y.shape == (1, 3, 3, 3)
    # manual: output position (0,0), channel 0
    W = np.asarray(params["W"])
    b = np.asarray(params["b"])
    want00 = (x[0, :3, :3, :] * W[:, :, :, 0]).sum() + b[0]
    np.testing.assert_allclose(np.asarray(y)[0, 0, 0, 0], want00, rtol=1e-4)


def test_pooling_max_avg():
    t = it.InputType.convolutional(4, 4, 1)
    x = np.arange(16, dtype=np.float32).reshape(1, 4, 4, 1)
    mx = run_layer(SubsamplingLayer(pooling_type=PoolingType.MAX), t, x)
    np.testing.assert_allclose(mx[0, :, :, 0], [[5, 7], [13, 15]])
    av = run_layer(SubsamplingLayer(pooling_type=PoolingType.AVG), t, x)
    np.testing.assert_allclose(av[0, :, :, 0], [[2.5, 4.5], [10.5, 12.5]])


def test_batchnorm_train_and_eval():
    t = it.InputType.feed_forward(3)
    bn = BatchNormalization(decay=0.5)
    params = bn.init(KEY, t)
    state = bn.init_state(t)
    x = np.random.default_rng(0).normal(3.0, 2.0, size=(64, 3)).astype(np.float32)
    y, new_state = bn.forward(params, state, jnp.asarray(x), train=True)
    # normalized output: ~zero mean, ~unit var
    np.testing.assert_allclose(np.asarray(y).mean(0), 0.0, atol=1e-4)
    np.testing.assert_allclose(np.asarray(y).std(0), 1.0, atol=1e-2)
    # running stats moved toward batch stats
    assert np.all(np.asarray(new_state["mean"]) != 0.0)
    # eval mode uses running stats, state unchanged
    y2, s2 = bn.forward(params, new_state, jnp.asarray(x), train=False)
    assert s2 is new_state


def test_global_pooling_cnn_and_rnn_mask():
    t = it.InputType.convolutional(4, 4, 3)
    x = np.random.default_rng(0).normal(size=(2, 4, 4, 3)).astype(np.float32)
    y = run_layer(GlobalPoolingLayer(pooling_type=PoolingType.AVG), t, x)
    np.testing.assert_allclose(y, x.mean((1, 2)), rtol=1e-5)
    # masked RNN pooling
    gp = GlobalPoolingLayer(pooling_type=PoolingType.AVG)
    seq = np.ones((1, 4, 2), np.float32)
    seq[0, 2:] = 100.0  # should be excluded by mask
    mask = jnp.asarray([[1.0, 1.0, 0.0, 0.0]])
    y2, _ = gp.forward({}, {}, jnp.asarray(seq), mask=mask)
    np.testing.assert_allclose(np.asarray(y2), [[1.0, 1.0]], rtol=1e-5)


def test_dropout_train_vs_eval():
    layer = DropoutLayer(dropout=0.5)
    x = np.ones((1000,), np.float32)
    y_eval = run_layer(layer, it.InputType.feed_forward(1000), x, train=False)
    np.testing.assert_allclose(y_eval, x)
    y_train = run_layer(layer, it.InputType.feed_forward(1000), x, train=True)
    kept = (y_train != 0).mean()
    assert 0.4 < kept < 0.6
    # inverted dropout: kept values scaled by 1/p
    np.testing.assert_allclose(y_train[y_train != 0], 2.0, rtol=1e-5)


def test_embedding():
    layer = EmbeddingLayer(n_in=10, n_out=4)
    params = layer.init(KEY, it.InputType.feed_forward(1))
    idx = np.array([[1], [7]], np.int32)
    y, _ = layer.forward(params, {}, jnp.asarray(idx))
    np.testing.assert_allclose(np.asarray(y)[0], np.asarray(params["W"])[1])
    seq = EmbeddingSequenceLayer(n_in=10, n_out=4)
    sp = seq.init(KEY, it.InputType.recurrent(1, 5))
    ys, _ = seq.forward(sp, {}, jnp.asarray(np.zeros((2, 5), np.int32)))
    assert ys.shape == (2, 5, 4)


def test_spatial_reshaping_layers():
    t = it.InputType.convolutional(4, 4, 2)
    x = np.random.default_rng(0).normal(size=(1, 4, 4, 2)).astype(np.float32)
    up = run_layer(Upsampling2D(size=(2, 2)), t, x)
    assert up.shape == (1, 8, 8, 2)
    np.testing.assert_allclose(up[0, :2, :2, 0], x[0, 0, 0, 0])
    zp = run_layer(ZeroPaddingLayer(padding=(1, 2, 3, 4)), t, x)
    assert zp.shape == (1, 7, 11, 2)
    cr = run_layer(Cropping2D(cropping=(1, 1, 1, 1)), t, x)
    assert cr.shape == (1, 2, 2, 2)
    np.testing.assert_allclose(cr[0], x[0, 1:3, 1:3])
    sd = run_layer(SpaceToDepthLayer(block_size=2), t, x)
    assert sd.shape == (1, 2, 2, 8)


def test_separable_and_deconv_shapes():
    t = it.InputType.convolutional(8, 8, 3)
    x = np.random.default_rng(0).normal(size=(2, 8, 8, 3)).astype(np.float32)
    sep = SeparableConvolution2D(n_out=6, kernel_size=(3, 3),
                                 convolution_mode=ConvolutionMode.SAME)
    y = run_layer(sep, t, x)
    assert y.shape == (2, 8, 8, 6)
    dec = Deconvolution2D(n_out=4, kernel_size=(2, 2), stride=(2, 2),
                          convolution_mode=ConvolutionMode.SAME)
    y2 = run_layer(dec, t, x)
    assert y2.shape == (2, 16, 16, 4)
    assert dec.output_type(t) == it.InputType.convolutional(16, 16, 4)


def test_lrn_shape_preserved():
    t = it.InputType.convolutional(4, 4, 8)
    x = np.random.default_rng(0).normal(size=(1, 4, 4, 8)).astype(np.float32)
    y = run_layer(LocalResponseNormalization(), t, x)
    assert y.shape == x.shape
    assert np.all(np.abs(y) <= np.abs(x) + 1e-6)  # normalization shrinks


def test_activation_layer():
    y = run_layer(ActivationLayer(activation=Activation.RELU),
                  it.InputType.feed_forward(3),
                  np.array([[-1.0, 0.0, 2.0]], np.float32))
    np.testing.assert_allclose(y, [[0.0, 0.0, 2.0]])


def test_deconv_truncate_shape_matches_declared():
    t = it.InputType.convolutional(8, 8, 3)
    dec = Deconvolution2D(n_out=6, kernel_size=(3, 3), stride=(1, 1),
                          padding=(0, 0),
                          convolution_mode=ConvolutionMode.TRUNCATE)
    declared = dec.output_type(t)
    x = np.zeros((1, 8, 8, 3), np.float32)
    y = run_layer(dec, t, x)
    assert y.shape == (1, declared.height, declared.width, 6) == (1, 10, 10, 6)
    dec2 = Deconvolution2D(n_out=2, kernel_size=(4, 4), stride=(2, 2),
                           padding=(1, 1),
                           convolution_mode=ConvolutionMode.TRUNCATE)
    d2 = dec2.output_type(t)
    y2 = run_layer(dec2, t, x)
    assert y2.shape == (1, d2.height, d2.width, 2) == (1, 16, 16, 2)


def test_batchnorm_use_batch_mean_in_eval():
    t = it.InputType.feed_forward(2)
    bn = BatchNormalization(use_batch_mean_in_eval=True)
    params = bn.init(KEY, t)
    state = bn.init_state(t)  # running stats untouched (mean 0, var 1)
    x = np.random.default_rng(0).normal(5.0, 3.0, (32, 2)).astype(np.float32)
    y, _ = bn.forward(params, state, jnp.asarray(x), train=False)
    np.testing.assert_allclose(np.asarray(y).mean(0), 0.0, atol=1e-4)
